"""Oracle check: ops.align_codon_jax vs align_np / scoring_np."""

import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
jax.config.update("jax_compilation_cache_dir", "/tmp/rifraf_cache_codon")

sys.path.insert(0, "/root/repo")

import jax.numpy as jnp
import numpy as np

from rifraf_tpu.engine.proposals import Deletion, Insertion, Substitution
from rifraf_tpu.engine.scoring_np import score_proposal
from rifraf_tpu.models.errormodel import ErrorModel, Scores
from rifraf_tpu.models.sequences import make_read_scores
from rifraf_tpu.ops import align_codon_jax as acj
from rifraf_tpu.ops import align_np

REF_SCORES = Scores.from_error_model(ErrorModel(10.0, 1e-1, 1e-1, 1.0, 1.0))

L = int(os.environ.get("L", "60"))
rng = np.random.default_rng(5)

fails = 0
for trial in range(4):
    tlen = int(rng.integers(max(10, L - 9), L + 10))
    template = rng.integers(0, 4, size=tlen).astype(np.int8)
    ref_len = int(rng.integers(max(9, L - 6), L + 7) // 3 * 3)
    ref_seq = rng.integers(0, 4, size=ref_len).astype(np.int8)
    bw = int(rng.integers(5, 12))
    rs = make_read_scores(ref_seq, np.full(ref_len, np.log10(0.1)), bw,
                          REF_SCORES)
    assert rs.do_codon_moves

    # host oracle
    A_h, mv_h = align_np.forward_moves_vec(template, rs)
    B_h = align_np.backward_vec(template, rs)

    rt = acj.make_ref_tables(rs)
    K = acj.band_height_codon(ref_len, tlen, bw)
    Tmax = tlen + 8
    T1p = tlen + 9
    tpl = np.zeros(Tmax, np.int8)
    tpl[:tlen] = template
    fwd = acj.forward_codon(jnp.asarray(tpl), tlen, rt, K, T1p,
                            want_moves=True)
    bwd = acj.backward_codon(jnp.asarray(tpl), tlen, rt, K, T1p)

    # compare every in-band cell
    ok = True
    bands = np.asarray(fwd.bands)
    starts = np.asarray(fwd.starts)
    mvs = np.asarray(fwd.moves)
    bbands = np.asarray(bwd.bands)
    bstarts = np.asarray(bwd.starts)
    for j in range(tlen + 1):
        lo, hi = A_h.row_range(j)
        for i in range(lo, hi + 1):
            got = bands[j, i - starts[j]]
            want = A_h[i, j]
            if not (np.isclose(got, want, rtol=1e-9, atol=1e-9)
                    or (not np.isfinite(want) and got < -1e30)):
                print(f"trial {trial} fwd mismatch ({i},{j}): {got} vs {want}")
                ok = False
            # moves: fp ties between predecessors may break differently
            # across engines (the reference fixes no canonical tie-break
            # beyond its own evaluation order), so check CONSISTENCY:
            # the chosen predecessor must achieve this cell's value
            gm = mvs[j, i - starts[j]]
            if np.isfinite(want) and not (i == 0 and j == 0):
                if gm == align_np.TRACE_MATCH:
                    sb_, tb_ = ref_seq[i - 1], template[j - 1]
                    e = (rs.match_scores[i - 1] if sb_ == tb_
                         else rs.mismatch_scores[i - 1])
                    pred = A_h[i - 1, j - 1] + e
                elif gm == align_np.TRACE_INSERT:
                    pred = A_h[i - 1, j] + rs.ins_scores[i - 1]
                elif gm == align_np.TRACE_DELETE:
                    pred = A_h[i, j - 1] + rs.del_scores[i]
                elif gm == align_np.TRACE_CODON_INSERT:
                    pred = A_h[i - 3, j] + rs.codon_ins_scores[i - 3]
                elif gm == align_np.TRACE_CODON_DELETE:
                    pred = A_h[i, j - 3] + rs.codon_del_scores[i]
                else:
                    pred = np.nan
                if not np.isclose(pred, want, rtol=1e-6, atol=1e-6):
                    print(f"trial {trial} move inconsistent ({i},{j}): "
                          f"move {gm} pred {pred} vs {want}")
                    ok = False
            bg = bbands[j, i - bstarts[j]]
            bw_ = B_h[i, j]
            if not (np.isclose(bg, bw_, rtol=1e-9, atol=1e-9)
                    or (not np.isfinite(bw_) and bg < -1e30)):
                print(f"trial {trial} bwd mismatch ({i},{j}): {bg} vs {bw_}")
                ok = False
            if not ok:
                break
        if not ok:
            break
    sc = float(np.asarray(fwd.score))
    want_sc = float(A_h[ref_len, tlen])
    if not np.isclose(sc, want_sc, rtol=1e-9):
        print(f"trial {trial} score {sc} vs {want_sc}")
        ok = False

    # proposals
    props = []
    for pos in range(tlen):
        props.append(Deletion(pos))
        props.append(Substitution(pos, int(rng.integers(0, 4))))
    for pos in range(tlen + 1):
        props.append(Insertion(pos, int(rng.integers(0, 4))))
    kinds = np.array([
        {Substitution: 0, Deletion: 1, Insertion: 2}[type(p)] for p in props
    ], np.int32)
    poss = np.array([p.pos for p in props], np.int32)
    bases = np.array([getattr(p, "base", 0) for p in props], np.int32)
    t_cols = np.zeros(T1p, np.int8)
    t_cols[1 : tlen + 1] = template
    got = np.asarray(acj._score_proposals_codon(
        jnp.asarray(kinds), jnp.asarray(poss), jnp.asarray(bases),
        jnp.asarray(t_cols), jnp.int32(tlen),
        fwd.bands, fwd.starts, bwd.bands, bwd.starts,
        tuple(rt[:9]), K, T1p, ref_len + 1, rt.do_cins, rt.do_cdel,
    ))
    want = np.array([
        score_proposal(p, A_h, B_h, template, rs) for p in props
    ])
    bad = ~(np.isclose(got, want, rtol=1e-9, atol=1e-9)
            | (~np.isfinite(want) & (got < -1e30)))
    if bad.any():
        k = np.argmax(bad)
        print(f"trial {trial} proposal mismatch {props[k]}: {got[k]} vs {want[k]} ({int(bad.sum())} bad)")
        ok = False
    print(f"trial {trial} (tlen={tlen} ref={ref_len} bw={bw}):",
          "OK" if ok else "FAIL", flush=True)
    fails += not ok

sys.exit(1 if fails else 0)
