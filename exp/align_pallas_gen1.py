"""Pallas TPU kernel for the batched banded forward DP.

The XLA path (align_jax) runs a lax.scan over template columns; each step is
a small [N, K] vector op, so the loop is overhead-bound. This kernel runs
the whole column sweep on-core:

- **Reads on lanes**: a block of 128 reads occupies the 128-lane axis; the
  band (K data rows) sits on sublanes. One column update is a single
  [K, 128] VPU tile operation.
- **Pre-shifted tables**: each read's per-base score tables are written
  into a [Lbuf, 128] buffer at row offset `K + off_k (+1)`, so the window
  needed for column j starts at row `j + K` for EVERY read — one contiguous
  dynamic slice per table per column, no gathers (the diagonal-aligned band
  layout of bandedarrays.jl:101-114 makes the window contiguous).
- **Sequential grid**: grid = (read_blocks, T+1); the DP carry lives in a
  VMEM scratch ref that persists across the sequentially-iterated column
  axis; each step writes one [K, 128] band column block to the output.
- The within-column insert chain uses the same max-plus closed form as the
  XLA kernel (F = G + cummax(cand - G)), computed along sublanes.

Used for score-only forward/backward fills (realignment + rescoring); the
moves-recording variant stays on the XLA path.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from rifraf_tpu.models.sequences import ReadBatch
from rifraf_tpu.ops.align_jax import BandGeometry, batch_geometry

NEG_INF = float(np.finfo(np.float32).min) / 2  # avoid inf arithmetic on VPU

LANES = 128


def _cumop(x, op, K):
    """Inclusive scan along sublanes (axis 0) via log-step doubling."""
    s = 1
    while s < K:
        shifted = pltpu.roll(x, s, axis=0)
        # rows < s have no source; mask them to identity by using iota
        idx = jax.lax.broadcasted_iota(jnp.int32, x.shape, 0)
        x = jnp.where(idx >= s, op(x, shifted), x)
        s *= 2
    return x


# Columns computed per grid step.
#
# Measured on TPU v5e (2026-07, BASELINE.md): this kernel is overhead-bound
# at ~700 ms per fill for 1 kb x 256 reads x K=56 — the T+1 sequentially
# iterated grid steps each do only ~2 log-K sublane-roll scans of a
# [K, 128] tile, vs ~5 ms for the XLA lax.scan path whose per-column op
# covers all reads at once. The kernel is therefore an explicit opt-in
# (params.backend="pallas"), kept oracle-verified for TPU runtimes where
# an on-core column sweep wins; the XLA path is the production default.
COLS_PER_STEP = 1


def _forward_kernel(
    tlen_ref,  # SMEM [1, 1] true template length
    t_ref,  # VMEM [Tpad, 1] int32 template codes
    match_ref,  # VMEM [Lbuf, 128]
    mismatch_ref,
    ins_ref,
    dels_ref,
    seq_ref,  # VMEM [Lbuf, 128] int32 codes (padded with -9)
    off_ref,  # VMEM [1, 128] int32 per-read offset
    slen_ref,  # VMEM [1, 128] int32
    nd_ref,  # VMEM [1, 128] int32
    dend_ref,  # VMEM [1, 128] int32 data row of the final cell
    out_ref,  # VMEM [COLS_PER_STEP * K, 128] band columns for this step
    score_ref,  # VMEM [1, 128] final scores (last grid step)
    carry,  # scratch VMEM [K, 128]
    acc_score,  # scratch VMEM [1, 128]
    *,
    K: int,
):
    jbase = pl.program_id(1) * COLS_PER_STEP
    tlen = tlen_ref[0, 0]

    off = off_ref[0, :]
    slen = slen_ref[0, :]
    nd = nd_ref[0, :]
    d = jax.lax.broadcasted_iota(jnp.int32, (K, LANES), 0)
    neg = jnp.full((K, LANES), NEG_INF, jnp.float32)

    @pl.when(jbase == 0)
    def _():
        acc_score[:] = jnp.full((1, LANES), NEG_INF, jnp.float32)

    for c in range(COLS_PER_STEP):
        j = jbase + c
        i = d + (j - off)[None, :]
        valid = (i >= 0) & (i <= slen[None, :]) & (d < nd[None, :]) & (j <= tlen)

        win = pl.ds(j + K, K)
        mw = match_ref[win, :]
        mmw = mismatch_ref[win, :]
        insw = ins_ref[win, :]
        delw = dels_ref[win, :]
        seqw = seq_ref[win, :]

        tb = t_ref[j, 0]  # template stored shifted: row j holds t[j-1]
        msc = jnp.where(seqw == tb, mw, mmw)

        prev = carry[:]
        mcand = jnp.where((i >= 1) & (j >= 1), prev + msc, neg)
        prev_up = pltpu.roll(prev, K - 1, axis=0)  # prev_up[d] = prev[d+1]
        prev_up = jnp.where(d == K - 1, neg, prev_up)
        dcand = jnp.where(j >= 1, prev_up + delw, neg)
        cand = jnp.maximum(mcand, dcand)
        # column 0: only the (0, 0) cell seeds the recurrence
        cand = jnp.where((j == 0) & (i == 0), 0.0, cand)
        cand = jnp.where(valid, cand, neg)

        g = jnp.where((i >= 1) & valid, insw, 0.0)
        G = _cumop(g, lambda a, b: a + b, K)
        F = G + _cumop(cand - G, jnp.maximum, K)
        F = jnp.where(valid, F, neg)

        carry[:] = F
        out_ref[c * K : (c + 1) * K, :] = F

        # record the final score when this column is the last true column
        @pl.when(j == tlen)
        def _():
            dend = dend_ref[0, :]
            sel = jnp.where(d == dend[None, :], F, NEG_INF)
            acc_score[:] = jnp.max(sel, axis=0, keepdims=True)

    @pl.when(pl.program_id(1) == pl.num_programs(1) - 1)
    def _():
        score_ref[:] = acc_score[:]


def _prep_tables(batch: ReadBatch, geom: BandGeometry, K: int, NB: int,
                 Lbuf: int):
    """Host-side table shifting: read k's entry for DP row index r lands at
    buffer row `base_k + r` with base_k chosen so the column-j window is
    rows [j + K, j + 2K) for every read. Fully vectorized scatter (a Python
    loop over 2048 reads would dominate the fill time)."""
    N = batch.n_reads
    n_pad = NB * LANES
    off = np.asarray(geom.offset).astype(np.int64)
    lengths = np.asarray(batch.lengths).astype(np.int64)
    L = batch.max_len

    match = np.zeros((Lbuf, n_pad), np.float32)
    mismatch = np.zeros((Lbuf, n_pad), np.float32)
    ins = np.zeros((Lbuf, n_pad), np.float32)
    dels = np.zeros((Lbuf, n_pad), np.float32)
    seq = np.full((Lbuf, n_pad), -9, np.int32)

    pos = np.arange(L)[None, :]  # [1, L]
    live = pos < lengths[:, None]  # [N, L]
    # match/mismatch/ins/seq indexed by i-1 -> base = K + off + 1
    rows = (K + off[:, None] + 1 + pos)[live]
    cols = np.broadcast_to(np.arange(N)[:, None], (N, L))[live]
    match[rows, cols] = np.asarray(batch.match)[live]
    mismatch[rows, cols] = np.asarray(batch.mismatch)[live]
    ins[rows, cols] = np.asarray(batch.ins)[live]
    seq[rows, cols] = np.asarray(batch.seq)[live]
    # dels indexed by i -> base = K + off
    pos1 = np.arange(L + 1)[None, :]
    live1 = pos1 <= lengths[:, None]
    rows1 = (K + off[:, None] + pos1)[live1]
    cols1 = np.broadcast_to(np.arange(N)[:, None], (N, L + 1))[live1]
    dels[rows1, cols1] = np.asarray(batch.dels)[live1]

    meta = np.zeros((4, 1, n_pad), np.int32)
    meta[0, 0, :N] = off
    meta[1, 0, :N] = np.asarray(geom.slen)
    meta[2, 0, :N] = np.asarray(geom.nd)
    meta[3, 0, :N] = np.maximum(np.asarray(geom.slen) - np.asarray(geom.tlen), 0) + np.asarray(
        geom.bandwidth
    )
    return match, mismatch, ins, dels, seq, meta


@functools.partial(
    jax.jit, static_argnames=("K", "T1", "NB", "Lbuf", "interpret")
)
def _forward_call(
    tlen_s,
    t,
    match,
    mismatch,
    ins,
    dels,
    seq,
    meta,
    K: int,
    T1: int,
    NB: int,
    Lbuf: int,
    interpret: bool = False,
):
    n_steps = (T1 + COLS_PER_STEP - 1) // COLS_PER_STEP
    grid = (NB, n_steps)

    def tab_spec():
        return pl.BlockSpec(
            (Lbuf, LANES), lambda nb, j: (0, nb), memory_space=pltpu.VMEM
        )

    # meta rows are separate inputs sliced from one [4, 1, n_pad] array
    metas = [meta[r] for r in range(4)]

    out_band, scores = pl.pallas_call(
        functools.partial(_forward_kernel, K=K),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1), lambda nb, j: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((t.shape[0], 1), lambda nb, j: (0, 0), memory_space=pltpu.VMEM),
            tab_spec(),
            tab_spec(),
            tab_spec(),
            tab_spec(),
            tab_spec(),
            pl.BlockSpec((1, LANES), lambda nb, j: (0, nb), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, LANES), lambda nb, j: (0, nb), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, LANES), lambda nb, j: (0, nb), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, LANES), lambda nb, j: (0, nb), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec(
                (COLS_PER_STEP * K, LANES),
                lambda nb, j: (j, nb),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec((1, LANES), lambda nb, j: (0, nb), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(
                (n_steps * COLS_PER_STEP * K, NB * LANES), jnp.float32
            ),
            jax.ShapeDtypeStruct((1, NB * LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((K, LANES), jnp.float32),
            pltpu.VMEM((1, LANES), jnp.float32),
        ],
        interpret=interpret,
    )(
        tlen_s,
        t,
        match,
        mismatch,
        ins,
        dels,
        seq,
        metas[0],
        metas[1],
        metas[2],
        metas[3],
    )
    return out_band, scores


def forward_batch_pallas(
    template: np.ndarray,
    batch: ReadBatch,
    tlen: Optional[int] = None,
    K: Optional[int] = None,
    interpret: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray, BandGeometry]:
    """Pallas banded forward fill. Returns (bands [N, K, T+1], scores [N],
    geometry), matching align_jax.forward_batch's band layout."""
    from rifraf_tpu.ops.align_jax import band_height

    if tlen is None:
        tlen = len(template)
    if K is None:
        K = band_height(batch, tlen)
        K = max(((K + 7) // 8) * 8, 8)  # f32 block sublane divisibility
    elif K <= 0 or K % 8:
        raise ValueError(f"K must be a positive multiple of 8, got {K}")
    geom = batch_geometry(batch, tlen)
    NB = (batch.n_reads + LANES - 1) // LANES
    T1 = len(template) + 1
    n_steps = (T1 + COLS_PER_STEP - 1) // COLS_PER_STEP
    T1p = n_steps * COLS_PER_STEP
    Lbuf = ((max(batch.max_len, T1p) + 2 * K + 8 + 7) // 8) * 8
    match, mismatch, ins, dels, seq, meta = _prep_tables(batch, geom, K, NB, Lbuf)
    t = np.full((T1p, 1), -1, np.int32)
    # t_ref row j holds t[j-1] (row 0 unused)
    t[1:T1, 0] = np.asarray(template, np.int32)[: T1 - 1]
    tlen_s = np.array([[tlen]], np.int32)
    band_flat, scores = _forward_call(
        tlen_s, t, match, mismatch, ins, dels, seq, meta,
        K=K, T1=T1, NB=NB, Lbuf=Lbuf, interpret=interpret,
    )
    # [T1p*K, NB*128] -> [N, K, T1]
    band = band_flat[: T1 * K].reshape(T1, K, NB * LANES)[:, :, : batch.n_reads]
    band = jnp.transpose(band, (2, 1, 0))
    return band, scores[0, : batch.n_reads], geom


def _reverse_batch_host(batch: ReadBatch) -> ReadBatch:
    """Reverse each read's true-length prefix (host-side twin of
    align_jax._reverse_read; padding tails stay in place)."""
    lengths = np.asarray(batch.lengths).astype(np.int64)
    N, L = batch.seq.shape

    k = np.arange(L)[None, :]
    idx = np.where(k < lengths[:, None], lengths[:, None] - 1 - k, k)

    def rev(a):
        return np.take_along_axis(np.asarray(a), idx, axis=1)

    k1 = np.arange(L + 1)[None, :]
    idx1 = np.where(k1 <= lengths[:, None], lengths[:, None] - k1, k1)
    dels_r = np.take_along_axis(np.asarray(batch.dels), idx1, axis=1)
    return batch._replace(
        seq=rev(batch.seq),
        match=rev(batch.match),
        mismatch=rev(batch.mismatch),
        ins=rev(batch.ins),
        dels=dels_r,
    )


@functools.partial(jax.jit, static_argnames=("K",))
def _flip_bands(band, geom: BandGeometry, K: int):
    """Flip reversed-sequence forward bands into backward bands
    (align.jl:196-202 / align_jax._backward_one's flip + re-mask)."""

    def flip_one(b, slen, tlen, bandwidth, offset, nd):
        T1 = b.shape[1]
        f = b[::-1, ::-1]
        f = jnp.roll(f, nd - K, axis=0)
        f = jnp.roll(f, tlen + 1 - T1, axis=1)
        j = jnp.arange(T1, dtype=jnp.int32)
        dd = jnp.arange(K, dtype=jnp.int32)
        i = dd[:, None] + j[None, :] - offset
        valid = (i >= 0) & (i <= slen) & (dd[:, None] < nd) & (j[None, :] <= tlen)
        return jnp.where(valid, f, NEG_INF)

    return jax.vmap(flip_one)(
        band, geom.slen, geom.tlen, geom.bandwidth, geom.offset, geom.nd
    )


def backward_batch_pallas(
    template: np.ndarray,
    batch: ReadBatch,
    tlen: Optional[int] = None,
    K: Optional[int] = None,
    interpret: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray, BandGeometry]:
    """Pallas banded backward fill: forward kernel on host-reversed
    sequences, then a jitted flip back into the original band frame.
    Matches align_jax.backward_batch's band layout (with the kernel's
    finite NEG_INF sentinel for out-of-band cells). A caller-supplied K
    must be a positive multiple of 8 (the kernel's sublane tile): silently
    rounding here would desynchronize the band height from an
    align_jax.backward_batch call made with the same K."""
    from rifraf_tpu.ops.align_jax import band_height

    if tlen is None:
        tlen = len(template)
    if K is None:
        K = band_height(batch, tlen)
        K = max(((K + 7) // 8) * 8, 8)
    elif K <= 0 or K % 8:
        raise ValueError(f"K must be a positive multiple of 8, got {K}")
    rbatch = _reverse_batch_host(batch)
    rt = np.asarray(template).copy()
    rt[:tlen] = rt[:tlen][::-1]
    band, scores, _ = forward_batch_pallas(
        rt, rbatch, tlen=tlen, K=K, interpret=interpret
    )
    geom = batch_geometry(batch, tlen)
    return _flip_bands(band, geom, K), scores, geom
