"""Roofline accounting for the Pallas fill/dense engines on the real TPU.

Measures (dependent-chain, warm) the fused iteration step and its stats
variants, counts the HBM bytes each program must move and the VPU work
per cell (shared models: rifraf_tpu.utils.roofline), and prints achieved
fractions of the chip's rooflines.

Usage: python exp/roofline.py [TLEN] [N_READS] [BW]
"""

import sys
import time

import numpy as np

sys.path.insert(0, "/root/repo")

import jax
import jax.numpy as jnp

from rifraf_tpu.models.errormodel import ErrorModel, Scores
from rifraf_tpu.models.sequences import batch_reads, make_read_scores
from rifraf_tpu.ops import align_jax, dense_pallas, fill_pallas
from rifraf_tpu.utils import roofline
from rifraf_tpu.utils.shapes import plan_cols

TLEN = int(sys.argv[1]) if len(sys.argv) > 1 else 1000
N_READS = int(sys.argv[2]) if len(sys.argv) > 2 else 256
BW = int(sys.argv[3]) if len(sys.argv) > 3 else 16

HBM_GBPS = roofline.HBM_GBPS
VPU_TOPS = roofline.VPU_TOPS

scores = Scores.from_error_model(ErrorModel(1.0, 2.0, 2.0, 0.0, 0.0))
rng = np.random.default_rng(3)
template = rng.integers(0, 4, size=TLEN).astype(np.int8)
reads = []
for n in range(N_READS):
    slen = int(rng.integers(TLEN - 8, TLEN + 9))
    s = rng.integers(0, 4, size=slen).astype(np.int8)
    log_p = rng.uniform(-3.0, -1.0, size=slen)
    reads.append(make_read_scores(s, log_p, BW, scores))
batch = batch_reads(reads, dtype=np.float32)

tlen = TLEN
geom = align_jax.batch_geometry(batch, tlen)
K = fill_pallas.uniform_band_height(np.asarray(geom.offset), np.asarray(geom.nd))
Tmax = ((tlen + 63) // 64) * 64
T1p = ((Tmax + 1 + 63) // 64) * 64
tpl = np.zeros(Tmax, np.int8)
tpl[:tlen] = template
Npad = ((batch.n_reads + 127) // 128) * 128
lengths = np.asarray(batch.lengths)

bufs = fill_pallas.build_fill_buffers(
    jnp.asarray(batch.seq), jnp.asarray(batch.match),
    jnp.asarray(batch.mismatch), jnp.asarray(batch.ins),
    jnp.asarray(batch.dels), jnp.asarray(batch.lengths), Npad,
)
jax.block_until_ready(bufs)
plan = plan_cols(T1p, K, kernel="dense")
C = plan.cols
C_fill = plan_cols(T1p, K, kernel="fill", want_moves=True).cols
print(f"K={K} T1p={T1p} C={C} (vmem {plan.vmem_bytes >> 10} KiB) "
      f"Npad={Npad} backend={jax.default_backend()}")

t_dev = jnp.asarray(tpl)
w = jnp.ones(N_READS, jnp.float32)


def run_fused(t, _dep):
    return dense_pallas.fused_step_pallas(
        t_dev, jnp.int32(tlen), bufs, geom, w, K, T1p, C,
    )


def run_fused_stats(t, _dep):
    return dense_pallas.fused_step_pallas(
        t_dev, jnp.int32(tlen), bufs, geom, w, K, T1p, C,
        want_stats=True,
    )


def run_fill_stats(t, _dep):
    return dense_pallas.fill_stats_pallas(
        t_dev, jnp.int32(tlen), bufs, geom, K, T1p, C_fill,
    )


def dep_chain(make, n=5):
    out = make(t_dev, 0)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    dep = 0
    for i in range(n):
        out = make(t_dev, dep)
        first = out[0] if isinstance(out, tuple) else out
        jax.block_until_ready(first)
        dep = first
    return (time.perf_counter() - t0) / n


cells = 2 * K * T1p * Npad  # fwd + rev streams
GB = 1e9

# ---- HBM bytes / VPU ops per program (shared analytic models) ----
m_fused = roofline.fused_model(T1p, K, Npad, C)
m_stats = roofline.fused_model(T1p, K, Npad, C, want_stats=True)
m_fill = roofline.fill_model(T1p, K, Npad, C_fill, n_streams=1,
                             want_moves=True, moves_lanes=Npad)
m_fstat = roofline.stats_model(T1p, K, Npad, C_fill)

t_fused = dep_chain(run_fused)
t_stats = dep_chain(run_fused_stats)
t_fill_stats = dep_chain(run_fill_stats)

for label, t, bts, ops in (
    ("fused fill+align+dense", t_fused, m_fused["bytes"], m_fused["ops"]),
    ("  + stats (on-core rev sweep)", t_stats, m_stats["bytes"],
     m_stats["ops"]),
    ("adapt fill+stats (fwd only)", t_fill_stats,
     m_fill["bytes"] + m_fstat["bytes"], m_fill["ops"] + m_fstat["ops"]),
):
    line = (f"{label}: {t*1e3:8.2f} ms | {bts/GB:6.2f} GB -> "
            f"{bts/GB/t:6.1f} GB/s ({100*bts/GB/t/HBM_GBPS:5.1f}% of HBM roof)")
    if ops:
        line += (f" | {ops/1e9:6.1f} Gop -> {ops/1e12/t:5.2f} Top/s "
                 f"({100*ops/1e12/t/VPU_TOPS:5.1f}% of VPU roof)")
    print(line)

print(f"cells (fwd+rev): {cells/1e6:.1f} M; cells/s (fused): "
      f"{cells/t_fused/1e9:.2f} G")

# ---- device-only time: N dependent iterations inside ONE jit ----
# (the dependent-chain numbers above include the ~100 ms tunnel round
# trip per block_until_ready; this isolates what the chip itself does)
N_SCAN = 10


@jax.jit
def scan_fused(t0):
    def body(tmpl, _):
        out = dense_pallas.fused_tables_pallas(
            tmpl, jnp.int32(tlen), bufs, geom, w, K, T1p, C,
        )
        # data dependency: xor the (always-zero) sign of the total in
        dep = (out["total"] < -1e30).astype(jnp.int8)
        return tmpl ^ dep, out["total"]

    return jax.lax.scan(body, t0, None, length=N_SCAN)[1]


@jax.jit
def scan_stats(t0):
    def body(tmpl, _):
        out = dense_pallas.fused_tables_pallas(
            tmpl, jnp.int32(tlen), bufs, geom, w, K, T1p, C,
            want_stats=True,
        )
        dep = (out["total"] < -1e30).astype(jnp.int8)
        return tmpl ^ dep, out["n_errors"].sum()

    return jax.lax.scan(body, t0, None, length=N_SCAN)[1]


for label, fn, bts in (
    ("fused", scan_fused, m_fused["bytes"]),
    ("fused+stats", scan_stats, m_stats["bytes"]),
):
    jax.block_until_ready(fn(t_dev))
    t0 = time.perf_counter()
    jax.block_until_ready(fn(t_dev))
    dt = (time.perf_counter() - t0) / N_SCAN
    print(f"device-only {label}: {dt*1e3:7.2f} ms/iter | "
          f"{bts/GB/dt:6.1f} GB/s ({100*bts/GB/dt/HBM_GBPS:5.1f}% HBM) | "
          f"cells/s {cells/dt/1e9:.2f} G")
