"""Component-level honest profile of the fused step on the live backend.

Times each device component with an in-jit fori_loop chain (data-dependent
carry -> pure device time per call, no dispatch overhead) and a single
dispatch wall (device + dispatch + tunnel). Usage:

    python exp/profile_fused.py [--tlen 1000] [--reads 256] [--bw 16]
"""

import argparse
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update(
    "jax_compilation_cache_dir",
    os.environ.get("RIFRAF_TPU_CACHE", os.path.expanduser("~/.cache/rifraf_tpu_xla")),
)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

sys.path.insert(0, "/root/repo")

from rifraf_tpu.models.errormodel import ErrorModel, Scores
from rifraf_tpu.models.sequences import batch_reads, make_read_scores
from rifraf_tpu.ops import align_jax
from rifraf_tpu.ops.fused import fused_step_full
from rifraf_tpu.ops.proposal_dense import _dense_batch, dense_tables_blocked, masked_weighted_sum


def build(tlen, n_reads, bw, seed=0):
    scores = Scores.from_error_model(ErrorModel(1.0, 2.0, 2.0, 0.0, 0.0))
    rng = np.random.default_rng(seed)
    template = rng.integers(0, 4, size=tlen).astype(np.int8)
    reads = []
    for _ in range(n_reads):
        slen = int(rng.integers(int(tlen * 0.95), int(tlen * 1.05)))
        s = rng.integers(0, 4, size=slen).astype(np.int8)
        log_p = rng.uniform(-3.0, -1.0, size=slen)
        reads.append(make_read_scores(s, log_p, bw, scores))
    batch = batch_reads(reads, dtype=np.float32)
    K = ((align_jax.band_height(batch, tlen) + 7) // 8) * 8
    geom = align_jax.batch_geometry(batch, tlen)
    Tpad = ((tlen + 1 + 63) // 64) * 64
    t_dev = jnp.asarray(np.pad(template, (0, Tpad - tlen)), jnp.int8)
    w = jnp.ones(n_reads, jnp.float32)
    dev = {
        "t": t_dev,
        "seq": jnp.asarray(batch.seq),
        "match": jnp.asarray(batch.match),
        "mismatch": jnp.asarray(batch.mismatch),
        "ins": jnp.asarray(batch.ins),
        "dels": jnp.asarray(batch.dels),
        "geom": geom,
        "w": w,
        "K": K,
    }
    return dev


def chain_time(fn, reps, *args):
    """Pure device time per call: fori_loop with a data-dependent scalar."""
    g = lambda eps: fn(eps, *args)  # args are STATIC: close over them

    @jax.jit
    def looped(eps):
        def body(_, carry):
            eps = carry
            out = g(eps)
            # fold a scalar of the output back into eps (dependency)
            leaf = jax.tree_util.tree_leaves(out)[0]
            return eps + 0.0 * jnp.sum(leaf.astype(jnp.float32) * 0.0)

        return jax.lax.fori_loop(0, reps, body, eps)

    r = looped(jnp.float32(0))
    jax.block_until_ready(r)
    t0 = time.perf_counter()
    r = looped(jnp.float32(0))
    jax.block_until_ready(r)
    return (time.perf_counter() - t0) / reps


def single_time(fn, *args, n=3):
    f = jax.jit(lambda eps: fn(eps, *args))
    jax.block_until_ready(f(jnp.float32(0)))
    best = np.inf
    for i in range(n):
        t0 = time.perf_counter()
        jax.block_until_ready(f(jnp.float32(i + 1) * 0))
        best = min(best, time.perf_counter() - t0)
    return best


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--tlen", type=int, default=1000)
    ap.add_argument("--reads", type=int, default=256)
    ap.add_argument("--bw", type=int, default=16)
    ap.add_argument("--reps", type=int, default=10)
    ap.add_argument("--only", type=str, default="")
    ap.add_argument("--chain", action="store_true")
    args = ap.parse_args()

    print(f"backend={jax.default_backend()}", file=sys.stderr)
    d = build(args.tlen, args.reads, args.bw)
    K = d["K"]
    print(f"K={K} Tpad={d['t'].shape[0]}", file=sys.stderr)

    fwd_bwd = jax.vmap(
        align_jax._fwd_bwd_one, in_axes=(None, 0, 0, 0, 0, 0, 0, None, None)
    )

    def fill_only(eps, want_moves):
        A, moves, scores, B = fwd_bwd(
            d["t"], d["seq"], d["match"] + eps, d["mismatch"], d["ins"],
            d["dels"], d["geom"], K, want_moves,
        )
        return A, B, scores

    def fill_and_keep(eps, want_moves):
        return fwd_bwd(
            d["t"], d["seq"], d["match"] + eps, d["mismatch"], d["ins"],
            d["dels"], d["geom"], K, want_moves,
        )

    # precompute A, B, moves once for downstream components
    A, moves, scores, B = jax.jit(
        lambda: fill_and_keep(jnp.float32(0), True)
    )()
    jax.block_until_ready((A, moves, B))

    def dense_only(eps):
        subs, insr, dele = _dense_batch(
            A + eps, B, d["seq"], d["match"], d["mismatch"], d["ins"],
            d["dels"], d["geom"],
        )
        return (masked_weighted_sum(d["w"], subs),
                masked_weighted_sum(d["w"], insr),
                masked_weighted_sum(d["w"], dele))

    def dense_blocked_only(eps):
        return dense_tables_blocked(
            A + eps, B, d["seq"], d["match"], d["mismatch"], d["ins"],
            d["dels"], d["geom"], d["w"],
        )

    def stats_only(eps):
        statf = jax.vmap(
            align_jax._traceback_stats_one, in_axes=(0, 0, None, 0, None)
        )
        nerr, edits = statf(moves, d["seq"], d["t"], d["geom"], K)
        return nerr.astype(jnp.float32) + eps, edits

    def fused(eps, want_moves, want_stats):
        return fused_step_full(
            d["t"], d["seq"], d["match"] + eps, d["mismatch"], d["ins"],
            d["dels"], d["geom"], d["w"], K, want_moves, want_stats, 0,
        )[3]

    all_comps = {
        "fill": ("fill(no moves)", fill_only, (False,)),
        "fillm": ("fill(+moves)", fill_only, (True,)),
        "dense": ("dense_sweep", dense_only, ()),
        "denseb": ("dense_blocked", dense_blocked_only, ()),
        "stats": ("tb_stats", stats_only, ()),
        "fused": ("fused(nostat)", fused, (False, False)),
        "fuseds": ("fused(stats)", fused, (False, True)),
        "fusedm": ("fused(moves+stats)", fused, (True, True)),
    }
    sel = args.only.split(",") if args.only else list(all_comps)
    reps = args.reps
    rows = []
    for name, fn, a in [all_comps[s] for s in sel]:
        try:
            t0 = time.perf_counter()
            dt_single = single_time(fn, *a)
            compile_s = time.perf_counter() - t0
            if args.chain:
                dt_chain = chain_time(fn, reps, *a)
                print(f"{name:22s} device={dt_chain*1e3:9.2f} ms  single={dt_single*1e3:9.2f} ms",
                      flush=True)
            else:
                print(f"{name:22s} single={dt_single*1e3:9.2f} ms  (compile+warm {compile_s:.1f}s)",
                      flush=True)
        except Exception as e:
            print(f"{name:22s} FAILED: {type(e).__name__}: {e}", flush=True)


if __name__ == "__main__":
    main()
