"""Oracle check for ops.dense_pallas.fused_step_pallas vs the XLA dense
sweep (ops.proposal_dense.score_all_edits) and XLA fills.

CPU interpret mode by default; --tpu for the real kernels; --time for
warm timings at scale.
"""

import os
import sys
import time

interpret = "--tpu" not in sys.argv
if interpret:
    os.environ["JAX_PLATFORMS"] = "cpu"

import jax

if interpret:
    jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, "/root/repo")

import jax.numpy as jnp
import numpy as np

from rifraf_tpu.models.errormodel import ErrorModel, Scores
from rifraf_tpu.models.sequences import batch_reads, make_read_scores
from rifraf_tpu.ops import align_jax, dense_pallas, fill_pallas
from rifraf_tpu.ops.proposal_dense import score_all_edits

TLEN = int(os.environ.get("TLEN", "40"))
N_READS = int(os.environ.get("NREADS", "5"))
BW = int(os.environ.get("BW", "6"))

scores_m = Scores.from_error_model(ErrorModel(1.0, 2.0, 2.0, 0.0, 0.0))
rng = np.random.default_rng(11)
template = rng.integers(0, 4, size=TLEN).astype(np.int8)
reads = []
for n in range(N_READS):
    slen = int(rng.integers(max(4, TLEN - 8), TLEN + 9))
    s = rng.integers(0, 4, size=slen).astype(np.int8)
    log_p = rng.uniform(-3.0, -1.0, size=slen)
    reads.append(make_read_scores(s, log_p, BW, scores_m))
batch = batch_reads(reads, dtype=np.float32)

tlen = TLEN
geom = align_jax.batch_geometry(batch, tlen)
K = fill_pallas.uniform_band_height(np.asarray(geom.offset), np.asarray(geom.nd))
Tmax = ((tlen + 63) // 64) * 64
T1p = Tmax + 64
from rifraf_tpu.utils.shapes import plan_cols

C = plan_cols(T1p, K, kernel="dense").cols
tpl_pad = np.zeros(Tmax, np.int8)
tpl_pad[:tlen] = template
Npad = ((batch.n_reads + 127) // 128) * 128
lengths = np.asarray(batch.lengths)

bufs = fill_pallas.build_fill_buffers(
    jnp.asarray(batch.seq), jnp.asarray(batch.match),
    jnp.asarray(batch.mismatch), jnp.asarray(batch.ins),
    jnp.asarray(batch.dels), jnp.asarray(batch.lengths), Npad,
)
weights = np.ones(batch.n_reads, np.float32)
weights[min(1, batch.n_reads - 1)] = 0.0  # exercise zero-weight masking

t0 = time.perf_counter()
packed, _ = dense_pallas.fused_step_pallas(
    jnp.asarray(tpl_pad), jnp.int32(tlen), bufs, geom,
    jnp.asarray(weights), K, T1p, C, interpret=interpret,
)
packed = np.asarray(packed)
print(f"fused_step_pallas: {time.perf_counter() - t0:.1f}s compile+run "
      f"K={K} T1p={T1p} C={C}", flush=True)

lay = dense_pallas.pack_layout_pallas(Npad, T1p)
total = packed[0]
sc = packed[slice(*lay["scores"])][: batch.n_reads]
sub_t = packed[slice(*lay["sub"])].reshape(T1p, 4)
ins_t = packed[slice(*lay["ins"])].reshape(T1p, 4)
del_t = packed[slice(*lay["del"])]

# --- oracles (XLA per-read frame) ---
Kx = align_jax.band_height(batch, tlen)
A, _, scores_x, _ = align_jax.forward_batch(tpl_pad, batch, tlen=tlen, K=Kx)
B, _, _ = align_jax.backward_batch(tpl_pad, batch, tlen=tlen, K=Kx)
sub_x, ins_x, del_x = score_all_edits(A, B, batch, geom, jnp.asarray(weights))
sub_x, ins_x, del_x = (np.asarray(v) for v in (sub_x, ins_x, del_x))
scores_x = np.asarray(scores_x)

ok = True
if not np.allclose(sc, scores_x, rtol=1e-5, atol=1e-5):
    print("SCORES mismatch", sc[:5], scores_x[:5])
    ok = False
want_total = float(np.sum(np.where(weights > 0, scores_x, 0.0) * weights))
if not np.isclose(total, want_total, rtol=1e-5):
    print("TOTAL mismatch", total, want_total)
    ok = False

# sub/del valid at pos < tlen; ins at pos <= tlen
for name, got, want, hi in (
    ("sub", sub_t, sub_x, tlen),
    ("ins", ins_t, ins_x, tlen + 1),
    ("del", del_t, del_x, tlen),
):
    g, w_ = got[:hi], want[:hi]
    finite = np.isfinite(w_)
    if not np.allclose(g[finite], w_[finite], rtol=2e-5, atol=2e-5):
        bad = np.argwhere(~np.isclose(g, w_, rtol=2e-5, atol=2e-5) & finite)
        print(f"{name} mismatch at {bad[:6].tolist()} "
              f"got={g[tuple(bad[0])]} want={w_[tuple(bad[0])]}")
        ok = False
    # -inf oracle entries must be hugely negative on the pallas side too
    if finite.size and np.any(g[~finite] > -1e30):
        print(f"{name}: masked entries not negative")
        ok = False
print("tables match:", ok, flush=True)

if "--time" in sys.argv:
    tpl_dev = jnp.asarray(tpl_pad)
    w_dev = jnp.asarray(weights)
    jax.block_until_ready(bufs)
    best = np.inf
    for i in range(6):
        t0 = time.perf_counter()
        r, _ = dense_pallas.fused_step_pallas(
            tpl_dev, jnp.int32(tlen), bufs, geom, w_dev, K, T1p, C,
            interpret=interpret,
        )
        jax.block_until_ready(r)
        dt = time.perf_counter() - t0
        if i:
            best = min(best, dt)
        print(f"  warm fused_pallas: {dt*1e3:.1f} ms", flush=True)
    print(f"fused_pallas best: {best*1e3:.1f} ms", flush=True)

sys.exit(0 if ok else 1)
