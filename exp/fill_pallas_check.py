"""Shake out ops.fill_pallas against the XLA oracle.

CPU interpret mode by default; pass --tpu to run the real kernel.
"""

import os
import sys
import time

interpret = "--tpu" not in sys.argv

if interpret:
    os.environ["JAX_PLATFORMS"] = "cpu"

import jax

if interpret:
    jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, "/root/repo")

import jax.numpy as jnp
import numpy as np

from rifraf_tpu.models.errormodel import ErrorModel, Scores
from rifraf_tpu.models.sequences import batch_reads, make_read_scores
from rifraf_tpu.ops import align_jax, fill_pallas

TLEN = int(os.environ.get("TLEN", "40"))
N_READS = int(os.environ.get("NREADS", "5"))
BW = int(os.environ.get("BW", "6"))

scores = Scores.from_error_model(ErrorModel(1.0, 2.0, 2.0, 0.0, 0.0))
rng = np.random.default_rng(3)
template = rng.integers(0, 4, size=TLEN).astype(np.int8)
reads = []
for n in range(N_READS):
    slen = int(rng.integers(max(4, TLEN - 6), TLEN + 7))
    s = rng.integers(0, 4, size=slen).astype(np.int8)
    log_p = rng.uniform(-3.0, -1.0, size=slen)
    reads.append(make_read_scores(s, log_p, BW, scores))
batch = batch_reads(reads, dtype=np.float32)

tlen = TLEN
geom = align_jax.batch_geometry(batch, tlen)
off_h = np.asarray(geom.offset)
nd_h = np.asarray(geom.nd)
K = fill_pallas.uniform_band_height(off_h, nd_h)
Tmax = ((tlen + 63) // 64) * 64
T1p = Tmax + 64

tpl_pad = np.zeros(Tmax, np.int8)
tpl_pad[:tlen] = template

Npad = ((batch.n_reads + 127) // 128) * 128
bufs = fill_pallas.build_fill_buffers(
    jnp.asarray(batch.seq), jnp.asarray(batch.match),
    jnp.asarray(batch.mismatch), jnp.asarray(batch.ins),
    jnp.asarray(batch.dels), jnp.asarray(batch.lengths), Npad,
)

t0 = time.perf_counter()
A, Brev, sc, OFF, _mv = fill_pallas.fill_uniform(
    jnp.asarray(tpl_pad), jnp.int32(tlen), bufs, geom, K, T1p,
    interpret=interpret,
)
jax.block_until_ready(A)
print(f"fill_uniform: {time.perf_counter() - t0:.1f}s (compile+run) "
      f"K={K} T1p={T1p} Npad={Npad}", flush=True)

# oracle: XLA per-read-frame fill
Kx = align_jax.band_height(batch, tlen)
bands_x, _, scores_x, _ = align_jax.forward_batch(tpl_pad, batch, tlen=tlen, K=Kx)
bands_x = np.asarray(bands_x)
scores_x = np.asarray(scores_x)

A = np.asarray(A)[: batch.n_reads]
sc = np.asarray(sc)[: batch.n_reads]
OFF = int(OFF)

ok = True
for k in range(batch.n_reads):
    delta = OFF - int(off_h[k])
    ndk = int(nd_h[k])
    # uniform-frame rows [delta, delta+nd) == per-read rows [0, nd)
    got = A[k, delta : delta + ndk, : tlen + 1]
    want = bands_x[k, :ndk, : tlen + 1]
    finite = np.isfinite(want)
    if not np.allclose(got[finite], want[finite], rtol=1e-5, atol=1e-5):
        bad = np.argwhere(
            ~np.isclose(got, want, rtol=1e-5, atol=1e-5) & finite
        )
        print(f"read {k}: band mismatch at {bad[:5]} "
              f"got={got[tuple(bad[0])]} want={want[tuple(bad[0])]}")
        ok = False
    # out-of-band cells must be <= sentinel-ish (never look like scores)
    if np.any(got[~finite] > -1e30):
        print(f"read {k}: out-of-band cell not masked")
        ok = False

print("forward bands match:", ok)
print("forward scores match:",
      np.allclose(sc, scores_x, rtol=1e-5, atol=1e-5), flush=True)

# backward oracle
Bx, scores_b, _ = align_jax.backward_batch(tpl_pad, batch, tlen=tlen, K=Kx)
Bx = np.asarray(Bx)
B = fill_pallas.flip_reversed_uniform(
    Brev, jnp.int32(tlen), bufs.lengths, OFF, K
)
B = np.asarray(B)[: batch.n_reads]

okb = True
for k in range(batch.n_reads):
    delta = OFF - int(off_h[k])
    ndk = int(nd_h[k])
    got = B[k, delta : delta + ndk, : tlen + 1]
    want = Bx[k, :ndk, : tlen + 1]
    finite = np.isfinite(want)
    if not np.allclose(got[finite], want[finite], rtol=1e-5, atol=1e-5):
        bad = np.argwhere(~np.isclose(got, want, rtol=1e-5, atol=1e-5) & finite)
        print(f"read {k}: BACKWARD mismatch at {bad[:5]} "
              f"got={got[tuple(bad[0])]} want={want[tuple(bad[0])]}")
        okb = False

print("backward bands match:", okb, flush=True)

if "--time" in sys.argv:
    tpl_dev = jnp.asarray(tpl_pad)
    jax.block_until_ready(bufs)
    best = np.inf
    for i in range(6):
        t0 = time.perf_counter()
        A2, Brev2, sc2, OFF2, _mv2 = fill_pallas.fill_uniform(
            tpl_dev, jnp.int32(tlen), bufs, geom, K, T1p, interpret=interpret
        )
        B2 = fill_pallas.flip_reversed_uniform(
            Brev2, jnp.int32(tlen), bufs.lengths, OFF2, K
        )
        jax.block_until_ready((A2, B2, sc2))
        dt = time.perf_counter() - t0
        if i:
            best = min(best, dt)
        print(f"  warm fill+flip: {dt*1e3:.1f} ms", flush=True)
    print(f"pallas fill+flip best: {best*1e3:.1f} ms", flush=True)

    # XLA merged fill for comparison (same process, same data)
    fwd_bwd = jax.jit(jax.vmap(
        align_jax._fwd_bwd_one, in_axes=(None, 0, 0, 0, 0, 0, 0, None, None)
    ), static_argnames=("K", "want_moves"))
    args = (jnp.asarray(np.pad(tpl_pad, (0, 0))), jnp.asarray(batch.seq),
            jnp.asarray(batch.match), jnp.asarray(batch.mismatch),
            jnp.asarray(batch.ins), jnp.asarray(batch.dels))
    jax.block_until_ready(args)
    bestx = np.inf
    for i in range(4):
        t0 = time.perf_counter()
        out = fwd_bwd(*args, geom, Kx, False)
        jax.block_until_ready(out)
        dt = time.perf_counter() - t0
        if i:
            bestx = min(bestx, dt)
    print(f"xla merged fill best: {bestx*1e3:.1f} ms "
          f"(speedup {bestx/best:.1f}x)", flush=True)

sys.exit(0 if (ok and okb) else 1)
