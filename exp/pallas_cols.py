"""Measure the existing Pallas forward fill at different COLS_PER_STEP.

Run in a FRESH process per setting (round-2 observed Pallas degrading
subsequent XLA launches in the same process):

    python exp/pallas_cols.py <cols_per_step> [--tlen 1000] [--reads 256]
"""

import sys
import time

import numpy as np

sys.path.insert(0, "/root/repo")

import rifraf_tpu.ops.align_pallas as ap

ap.COLS_PER_STEP = int(sys.argv[1]) if len(sys.argv) > 1 else 1

import jax
import jax.numpy as jnp

from rifraf_tpu.models.errormodel import ErrorModel, Scores
from rifraf_tpu.models.sequences import batch_reads, make_read_scores
from rifraf_tpu.ops import align_jax

TLEN = 1000
N_READS = 256

scores = Scores.from_error_model(ErrorModel(1.0, 2.0, 2.0, 0.0, 0.0))
rng = np.random.default_rng(0)
template = rng.integers(0, 4, size=TLEN).astype(np.int8)
reads = []
for _ in range(N_READS):
    slen = int(rng.integers(980, 1020))
    s = rng.integers(0, 4, size=slen).astype(np.int8)
    log_p = rng.uniform(-3.0, -1.0, size=slen)
    reads.append(make_read_scores(s, log_p, 16, scores))
batch = batch_reads(reads, dtype=np.float32)

print(f"backend={jax.default_backend()} cols_per_step={ap.COLS_PER_STEP}",
      flush=True)

t0 = time.perf_counter()
band, score, geom = ap.forward_batch_pallas(template, batch)
jax.block_until_ready((band, score))
print(f"compile+run: {time.perf_counter() - t0:.1f}s", flush=True)

# warm timing: repeat calls (prep re-runs on host each call; time the
# device call separately by pre-prepping once)
K = band.shape[1]
NB = (batch.n_reads + 127) // 128
T1 = TLEN + 1
n_steps = (T1 + ap.COLS_PER_STEP - 1) // ap.COLS_PER_STEP
T1p = n_steps * ap.COLS_PER_STEP
Lbuf = ((max(batch.max_len, T1p) + 2 * K + 8 + 7) // 8) * 8
geomx = ap.batch_geometry(batch, TLEN)
match, mismatch, ins, dels, seq, meta = ap._prep_tables(batch, geomx, K, NB, Lbuf)
t = np.full((T1p, 1), -1, np.int32)
t[1:T1, 0] = template.astype(np.int32)
tlen_s = np.array([[TLEN]], np.int32)

args = [jnp.asarray(a) for a in (tlen_s, t, match, mismatch, ins, dels, seq, meta)]
jax.block_until_ready(args)

best = np.inf
for i in range(5):
    t0 = time.perf_counter()
    out = ap._forward_call(*args, K=K, T1=T1, NB=NB, Lbuf=Lbuf)
    jax.block_until_ready(out)
    best = min(best, time.perf_counter() - t0)
print(f"device-resident fill: {best*1e3:.1f} ms (K={K}, NB={NB}, steps={n_steps})",
      flush=True)

# correctness vs XLA path
bands_x, _, scores_x, _ = align_jax.forward_batch(template, batch, tlen=TLEN, K=K)
ok = np.allclose(np.asarray(score), np.asarray(scores_x), rtol=1e-4, atol=1e-4)
print(f"scores match XLA: {ok}", flush=True)
