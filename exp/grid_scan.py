"""Scan the reference's 2^4 x 2 integration grid (test_model.jl:325-375)
for exact template recovery; report failures per combo/seed."""

import itertools
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
jax.config.update("jax_compilation_cache_dir", "/tmp/rifraf_jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

sys.path.insert(0, "/root/repo")

import numpy as np

from rifraf_tpu.engine.driver import rifraf
from rifraf_tpu.engine.params import RifrafParams
from rifraf_tpu.models.errormodel import ErrorModel, Scores
from rifraf_tpu.sim.sample import sample_sequences
from rifraf_tpu.utils.constants import decode_seq

REF_SAMPLE_ERRORS = ErrorModel(8.0, 0.0, 0.0, 1.0, 1.0)
REF_SCORES = Scores.from_error_model(ErrorModel(8.0, 0.1, 0.1, 1.0, 1.0))
SEQ_ERRORS = ErrorModel(1.0, 2.0, 2.0, 0.0, 0.0)
SEQ_SCORES = Scores.from_error_model(SEQ_ERRORS)
SAMPLE_PARAMS = dict(
    ref_error_rate=0.1,
    ref_errors=REF_SAMPLE_ERRORS,
    error_rate=0.005,
    alpha=1.0,
    phred_scale=1.5,
    actual_std=3.0,
    reported_std=0.3,
    seq_errors=SEQ_ERRORS,
)

base_seed = int(sys.argv[1]) if len(sys.argv) > 1 else 1234

combos = list(itertools.product(
    [True, False], [True, False], [True, False], [True, False], [3, 6]
))
fails = []
for i, (use_ref, dap, seed_indels, ico, batch_size) in enumerate(combos):
    rng = np.random.default_rng(base_seed + i)
    ref, template, t_p, seqs, actual, phreds, cb, db = sample_sequences(
        nseqs=5, length=30, rng=rng, **SAMPLE_PARAMS
    )
    params = RifrafParams(
        scores=SEQ_SCORES,
        ref_scores=REF_SCORES,
        do_alignment_proposals=dap,
        seed_indels=seed_indels,
        indel_correction_only=ico,
        batch_size=batch_size,
        seed=base_seed + i,
    )
    result = rifraf(
        seqs, phreds=phreds, reference=ref if use_ref else None, params=params
    )
    ok = decode_seq(result.consensus) == decode_seq(template)
    tag = f"ref={int(use_ref)} dap={int(dap)} si={int(seed_indels)} ico={int(ico)} bs={batch_size}"
    print(f"{i:2d} {tag}  {'ok' if ok else 'FAIL'}", flush=True)
    if not ok:
        fails.append((i, tag))

print(f"\n{len(combos) - len(fails)}/{len(combos)} recovered (base_seed={base_seed})")
for i, tag in fails:
    print("FAIL:", i, tag)
