"""Stage-by-stage timing of the Pallas fill path on TPU.

Times (warm, device-resident args, block per call):
  1. _fill_call alone (kernel + out reshape nothing else)
  2. buffer build (place + block tables)
  3. fill_uniform end-to-end
  4. + flip_reversed_uniform
"""

import sys
import time

import numpy as np

sys.path.insert(0, "/root/repo")

import jax
import jax.numpy as jnp

from rifraf_tpu.models.errormodel import ErrorModel, Scores
from rifraf_tpu.models.sequences import batch_reads, make_read_scores
from rifraf_tpu.ops import align_jax, fill_pallas

TLEN = int(sys.argv[1]) if len(sys.argv) > 1 else 1000
N_READS = int(sys.argv[2]) if len(sys.argv) > 2 else 256
BW = int(sys.argv[3]) if len(sys.argv) > 3 else 16

scores = Scores.from_error_model(ErrorModel(1.0, 2.0, 2.0, 0.0, 0.0))
rng = np.random.default_rng(3)
template = rng.integers(0, 4, size=TLEN).astype(np.int8)
reads = []
for n in range(N_READS):
    slen = int(rng.integers(TLEN - 8, TLEN + 9))
    s = rng.integers(0, 4, size=slen).astype(np.int8)
    log_p = rng.uniform(-3.0, -1.0, size=slen)
    reads.append(make_read_scores(s, log_p, BW, scores))
batch = batch_reads(reads, dtype=np.float32)

tlen = TLEN
geom = align_jax.batch_geometry(batch, tlen)
K = fill_pallas.uniform_band_height(np.asarray(geom.offset), np.asarray(geom.nd))
Tmax = ((tlen + 63) // 64) * 64
T1p = Tmax + 64
tpl_pad = np.zeros(Tmax, np.int8)
tpl_pad[:tlen] = template
Npad = ((batch.n_reads + 127) // 128) * 128

bufs = fill_pallas.build_fill_buffers(
    jnp.asarray(batch.seq), jnp.asarray(batch.match),
    jnp.asarray(batch.mismatch), jnp.asarray(batch.ins),
    jnp.asarray(batch.dels), jnp.asarray(batch.lengths), Npad,
)
jax.block_until_ready(bufs)
from rifraf_tpu.utils.shapes import plan_cols

C = plan_cols(T1p, K, kernel="fill").cols
print(f"K={K} T1p={T1p} C={C} Npad={Npad} backend={jax.default_backend()}",
      flush=True)


def timeit(label, f, n=5):
    jax.block_until_ready(f())
    best = np.inf
    for _ in range(n):
        t0 = time.perf_counter()
        jax.block_until_ready(f())
        best = min(best, time.perf_counter() - t0)
    print(f"{label:28s} {best*1e3:9.2f} ms", flush=True)
    return best


tpl_dev = jnp.asarray(tpl_pad)
tl = jnp.int32(tlen)

# stage 2: buffer build only (jit the prep portion)
@jax.jit
def prep_only(template, tlen):
    # replicate fill_uniform's prep: places + blocking for both streams
    OFF = jnp.max(geom.offset).astype(jnp.int32)
    n_steps = T1p // C
    CB = C + K
    L = bufs.seq_T.shape[0]
    Lbuf = T1p + K + 8
    Lbig = Lbuf + L

    def place(tab_T, row0, fill):
        buf = jnp.full((Lbig, Npad), fill, tab_T.dtype)
        buf = jax.lax.dynamic_update_slice(
            buf, tab_T, (row0.astype(jnp.int32), jnp.int32(0)))
        return buf[:Lbuf]

    def stream(sqT, mtT, mmT, giT, dlT):
        return [
            fill_pallas._block_tables(place(x, OFF + 1, 0.0), n_steps, C, CB)
            for x in (mtT, mmT, giT)
        ] + [
            fill_pallas._block_tables(place(dlT, OFF, 0.0), n_steps, C, CB),
            fill_pallas._block_tables(place(sqT, OFF + 1, -9), n_steps, C, CB),
        ]

    a = stream(bufs.seq_T, bufs.match_T, bufs.mismatch_T, bufs.ins_T, bufs.dels_T)
    b = stream(bufs.rseq_T, bufs.rmatch_T, bufs.rmismatch_T, bufs.rins_T, bufs.rdels_T)
    return a + b


timeit("prep(build+block tables)", lambda: prep_only(tpl_dev, tl))

# full fill_uniform without flip
def fill_only():
    A, Brev, sc, OFF, _ = fill_pallas.fill_uniform(
        tpl_dev, tl, bufs, geom, K, T1p)
    return A, Brev, sc

timeit("fill_uniform (A,Brev,sc)", fill_only)

def fill_flip():
    A, Brev, sc, OFF, _ = fill_pallas.fill_uniform(
        tpl_dev, tl, bufs, geom, K, T1p)
    B = fill_pallas.flip_reversed_uniform(Brev, tl, bufs.lengths, OFF, K)
    return A, B, sc

timeit("fill_uniform + flip", fill_flip)

# scores only (skip the big band outputs' materialization cost? they are
# pallas outputs regardless; this just skips the reshape/transpose)
@jax.jit
def scores_only(template, tlen):
    A, Brev, sc, OFF, _ = fill_pallas.fill_uniform(
        template, tlen, bufs, geom, K, T1p)
    return sc

timeit("fill (scores fetch only)", lambda: scores_only(tpl_dev, tl))
