"""rifraf_tpu: TPU-native RIFRAF consensus framework.

A from-scratch JAX/XLA re-design of the RIFRAF reference-informed
frame-restoring consensus algorithm (reference: sdwfrost/Rifraf.jl). The
public surface mirrors the reference's export list (src/Rifraf.jl:12-45);
the engine underneath is batched, bucketed, and device-sharded.
"""

from .engine.driver import (
    EstimatedProbs,
    RifrafResult,
    RifrafState,
    calibrate_phreds,
    correct_shifts,
    estimate_point_probs,
    rifraf,
)
from .engine.params import RifrafParams, Stage
from .engine.proposals import (
    AmbiguousProposalsError,
    Deletion,
    Insertion,
    Proposal,
    ScoredProposal,
    Substitution,
    apply_proposals,
    choose_candidates,
)
from .io.fastx import (
    read_fasta,
    read_fasta_records,
    read_fastq,
    read_samples,
    write_fasta,
    write_fastq,
    write_samples,
)
from .models.errormodel import ErrorModel, Scores
from .models.sequences import (
    ReadBatch,
    ReadScores,
    batch_reads,
    make_read_scores,
    read_scores_from_phreds,
)
from .ops.align_np import align, align_moves
from .ops.banded_array import BandedArray
from .sim.sample import (
    sample_from_template,
    sample_mixture,
    sample_sequences,
)
from .utils.constants import (
    BASES,
    CODON_LENGTH,
    decode_seq,
    encode_seq,
    reverse_complement,
)
from .utils.mathops import logsumexp10, summax
from .utils.phred import (
    cap_phreds,
    normalize,
    p_to_phred,
    phred_to_log_p,
    phred_to_p,
)

__version__ = "0.1.0"

__all__ = [
    "rifraf",
    "RifrafParams",
    "RifrafResult",
    "RifrafState",
    "Stage",
    "EstimatedProbs",
    "estimate_point_probs",
    "calibrate_phreds",
    "correct_shifts",
    "ErrorModel",
    "Scores",
    "normalize",
    "ReadScores",
    "ReadBatch",
    "make_read_scores",
    "read_scores_from_phreds",
    "batch_reads",
    "BandedArray",
    "align",
    "align_moves",
    "Proposal",
    "Substitution",
    "Insertion",
    "Deletion",
    "ScoredProposal",
    "AmbiguousProposalsError",
    "apply_proposals",
    "choose_candidates",
    "sample_sequences",
    "sample_mixture",
    "sample_from_template",
    "read_fasta",
    "read_fasta_records",
    "write_fasta",
    "read_fastq",
    "write_fastq",
    "write_samples",
    "read_samples",
    "encode_seq",
    "reverse_complement",
    "decode_seq",
    "BASES",
    "CODON_LENGTH",
    "logsumexp10",
    "summax",
    "p_to_phred",
    "phred_to_log_p",
    "phred_to_p",
    "cap_phreds",
]
