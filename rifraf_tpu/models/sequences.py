"""Per-read score precompute and batched device layout.

`ReadScores` mirrors the reference's RifrafSequence
(/root/reference/src/rifrafsequences.jl:19-81): a read plus per-position score
vectors, precomputed so the DP inner loop does no math. The TPU-native twist
is `ReadBatch`: N reads padded to a common length and stacked into dense
arrays, ready to be vmapped over on device.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import NamedTuple, Optional, Sequence

import numpy as np

from ..utils.constants import encode_seq
from ..utils.phred import phred_to_log_p
from .errormodel import Scores

NEG_INF = -np.inf


@dataclass
class ReadScores:
    """A read plus precomputed per-base alignment score vectors.

    Score vector semantics (rifrafsequences.jl:40-72, all log10):
      - match_scores[i]    = log10(1 - p_i)
      - mismatch_scores[i] = log10(p_i) + scores.mismatch
      - ins_scores[i]      = log10(p_i) + scores.insertion
      - del_scores (len n+1): del_scores[i] = max(log_p[i-1], log_p[i]) +
        scores.deletion, symmetric at the ends
      - codon_ins_scores (len n-2): max of 3 neighbors + scores.codon_insertion
      - codon_del_scores (len n+1): like del_scores with codon penalty
    """

    seq: np.ndarray  # int8 codes [n]
    error_log_p: np.ndarray  # float64 [n]
    est_n_errors: float
    match_scores: np.ndarray
    mismatch_scores: np.ndarray
    ins_scores: np.ndarray
    del_scores: np.ndarray  # [n + 1]
    codon_ins_scores: Optional[np.ndarray]  # [n - 2] or None
    codon_del_scores: Optional[np.ndarray]  # [n + 1] or None
    bandwidth: int
    scores: Scores
    bandwidth_fixed: bool = False

    def __len__(self) -> int:
        return len(self.seq)

    @property
    def do_codon_ins(self) -> bool:
        return self.codon_ins_scores is not None

    @property
    def do_codon_del(self) -> bool:
        return self.codon_del_scores is not None

    @property
    def do_codon_moves(self) -> bool:
        return self.do_codon_ins or self.do_codon_del

    def with_scores(self, scores: Scores) -> "ReadScores":
        """Recompute score vectors with new penalties, keeping bandwidth state
        (rifrafsequences.jl:90-94)."""
        result = make_read_scores(self.seq, self.error_log_p, self.bandwidth, scores)
        result.bandwidth_fixed = self.bandwidth_fixed
        return result

    def reversed(self) -> "ReadScores":
        """Score vectors for the reversed read, used by the backward pass.

        Matches align.jl's `doreverse` index arithmetic (align.jl:64-68,
        88-99): every per-base score vector is simply reversed.
        """
        out = replace(
            self,
            seq=self.seq[::-1].copy(),
            error_log_p=self.error_log_p[::-1].copy(),
            match_scores=self.match_scores[::-1].copy(),
            mismatch_scores=self.mismatch_scores[::-1].copy(),
            ins_scores=self.ins_scores[::-1].copy(),
            del_scores=self.del_scores[::-1].copy(),
            codon_ins_scores=(
                None if self.codon_ins_scores is None else self.codon_ins_scores[::-1].copy()
            ),
            codon_del_scores=(
                None if self.codon_del_scores is None else self.codon_del_scores[::-1].copy()
            ),
        )
        return out


def make_read_scores(
    seq,
    error_log_p,
    bandwidth: int,
    scores: Scores,
) -> ReadScores:
    """Build a ReadScores (rifrafsequences.jl:19-81).

    `seq` may be a DNA string or an int8 code array.
    """
    if isinstance(seq, str):
        seq = encode_seq(seq)
    seq = np.asarray(seq, dtype=np.int8)
    error_log_p = np.asarray(error_log_p, dtype=np.float64)

    if bandwidth < 1:
        raise ValueError("bandwidth must be positive")
    if len(seq) != len(error_log_p):
        raise ValueError("length mismatch")
    n = len(seq)
    if n == 0:
        return empty_read_scores(scores)
    if np.min(error_log_p) == -np.inf:
        raise ValueError("a log error probability is negative infinity")
    if np.max(error_log_p) > 0.0:
        raise ValueError(f"a log error probability is > 0: {np.max(error_log_p)}")

    error_p = np.power(10.0, error_log_p)
    match_scores = np.log10(1.0 - error_p)
    mismatch_scores = error_log_p + scores.mismatch
    ins_scores = error_log_p + scores.insertion

    # del_scores[i] = max of neighboring log error probs + penalty; symmetric
    # at the ends (rifrafsequences.jl:49-53)
    del_scores = np.empty(n + 1, dtype=np.float64)
    del_scores[0] = error_log_p[0] + scores.deletion
    del_scores[-1] = error_log_p[-1] + scores.deletion
    if n > 1:
        del_scores[1:n] = np.maximum(error_log_p[:-1], error_log_p[1:]) + scores.deletion

    codon_ins_scores = None
    if scores.codon_insertion > -np.inf:
        if n >= 3:
            # codon_ins_scores[i] = max(log_p[i], log_p[i+1], log_p[i+2]) + penalty
            # (rifrafsequences.jl:58-63, shifted to 0-based)
            codon_ins_scores = (
                np.maximum.reduce([error_log_p[:-2], error_log_p[1:-1], error_log_p[2:]])
                + scores.codon_insertion
            )
        else:
            codon_ins_scores = np.zeros(0, dtype=np.float64)

    codon_del_scores = None
    if scores.codon_deletion > -np.inf:
        codon_del_scores = np.empty(n + 1, dtype=np.float64)
        codon_del_scores[0] = error_log_p[0] + scores.codon_deletion
        codon_del_scores[-1] = error_log_p[-1] + scores.codon_deletion
        if n > 1:
            codon_del_scores[1:n] = (
                np.maximum(error_log_p[:-1], error_log_p[1:]) + scores.codon_deletion
            )

    est_n_errors = float(np.sum(error_p))

    return ReadScores(
        seq=seq,
        error_log_p=error_log_p,
        est_n_errors=est_n_errors,
        match_scores=match_scores,
        mismatch_scores=mismatch_scores,
        ins_scores=ins_scores,
        del_scores=del_scores,
        codon_ins_scores=codon_ins_scores,
        codon_del_scores=codon_del_scores,
        bandwidth=bandwidth,
        scores=scores,
    )


def read_scores_from_phreds(seq, phreds, bandwidth: int, scores: Scores) -> ReadScores:
    """Build from PHRED values instead of log error rates
    (rifrafsequences.jl:84-87)."""
    return make_read_scores(seq, phred_to_log_p(phreds), bandwidth, scores)


def empty_read_scores(scores: Scores) -> ReadScores:
    """Empty sequence (rifrafsequences.jl:97-100)."""
    z = np.zeros(0, dtype=np.float64)
    return ReadScores(
        seq=np.zeros(0, dtype=np.int8),
        error_log_p=z,
        est_n_errors=0.0,
        match_scores=z,
        mismatch_scores=z,
        ins_scores=z,
        del_scores=z,
        codon_ins_scores=None,
        codon_del_scores=None,
        bandwidth=0,
        scores=scores,
    )


class ReadBatch(NamedTuple):
    """N reads padded to length L and stacked for the device.

    Padding positions carry harmless finite scores; every kernel masks by
    `lengths`. `cins`/`cdel` are all -inf when codon moves are disabled, which
    uniformly disables those moves in the kernels — and when NO read in the
    batch carries codon scores (the read path: codon moves live only in the
    reference alignment), they collapse to a compact ``[N, 1]`` -inf
    sentinel instead of two dead full-width f32 planes. Consumers must key
    on ``do_codon_moves`` (or the plane width), not assume ``[N, L]``.
    """

    seq: np.ndarray  # int8 [N, L], padded with GAP_INT
    lengths: np.ndarray  # int32 [N]
    match: np.ndarray  # [N, L]
    mismatch: np.ndarray  # [N, L]
    ins: np.ndarray  # [N, L]
    dels: np.ndarray  # [N, L + 1]
    # [N, L] (index i <-> codon_ins_scores[i], valid i <= n-3), or the
    # [N, 1] -inf sentinel when no read has codon scores
    cins: np.ndarray
    cdel: np.ndarray  # [N, L + 1], or the [N, 1] -inf sentinel
    bandwidth: np.ndarray  # int32 [N]

    @property
    def do_codon_moves(self) -> bool:
        """True when the batch carries real (full-width) codon-score
        planes; False for the compact disabled sentinel."""
        return self.cins.shape[1] > 1

    @property
    def n_reads(self) -> int:
        return self.seq.shape[0]

    @property
    def max_len(self) -> int:
        return self.seq.shape[1]


def batch_reads(reads: Sequence[ReadScores], max_len: Optional[int] = None, dtype=np.float32) -> ReadBatch:
    """Pad and stack ReadScores into a ReadBatch."""
    n = len(reads)
    if n == 0:
        raise ValueError("cannot batch zero reads")
    length = max(len(r) for r in reads)
    if max_len is not None:
        if max_len < length:
            raise ValueError("max_len smaller than longest read")
        length = max_len

    seq = np.full((n, length), -1, dtype=np.int8)
    lengths = np.zeros(n, dtype=np.int32)
    match = np.zeros((n, length), dtype=dtype)
    mismatch = np.zeros((n, length), dtype=dtype)
    ins = np.zeros((n, length), dtype=dtype)
    dels = np.zeros((n, length + 1), dtype=dtype)
    # the codon planes are read-path dead weight for standard reads
    # (codon moves exist only in the reference alignment): when no read
    # carries codon scores, keep a [n, 1] -inf sentinel instead of
    # materializing two full [n, L(+1)] f32 planes of -inf
    any_codon = any(r.do_codon_moves for r in reads)
    if any_codon:
        cins = np.full((n, length), NEG_INF, dtype=dtype)
        cdel = np.full((n, length + 1), NEG_INF, dtype=dtype)
    else:
        cins = np.full((n, 1), NEG_INF, dtype=dtype)
        cdel = np.full((n, 1), NEG_INF, dtype=dtype)
    bandwidth = np.zeros(n, dtype=np.int32)

    for k, r in enumerate(reads):
        m = len(r)
        lengths[k] = m
        seq[k, :m] = r.seq
        match[k, :m] = r.match_scores
        mismatch[k, :m] = r.mismatch_scores
        ins[k, :m] = r.ins_scores
        dels[k, : m + 1] = r.del_scores
        if r.codon_ins_scores is not None and len(r.codon_ins_scores) > 0:
            cins[k, : m - 2] = r.codon_ins_scores
        if r.codon_del_scores is not None:
            cdel[k, : m + 1] = r.codon_del_scores
        bandwidth[k] = r.bandwidth

    return ReadBatch(
        seq=seq,
        lengths=lengths,
        match=match,
        mismatch=mismatch,
        ins=ins,
        dels=dels,
        cins=cins,
        cdel=cdel,
        bandwidth=bandwidth,
    )
