"""Error model and alignment scores.

Mirrors /root/reference/src/errormodel.jl: an ErrorModel holds relative rates
of each error kind; Scores are the log10-normalized rates plus optional extra
penalties (codon indels get 3x the single-indel extra penalty,
errormodel.jl:75-80).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np


@dataclass(frozen=True)
class ErrorModel:
    """Relative rates of each kind of sequencing error (errormodel.jl:19-30)."""

    mismatch: float
    insertion: float
    deletion: float
    codon_insertion: float = 0.0
    codon_deletion: float = 0.0

    def normalize(self) -> "ErrorModel":
        """Turn error rates into probabilities (errormodel.jl:33-41)."""
        args = np.array(
            [
                self.mismatch,
                self.insertion,
                self.deletion,
                self.codon_insertion,
                self.codon_deletion,
            ],
            dtype=np.float64,
        )
        m, i, d, ci, cd = args / args.sum()
        return ErrorModel(m, i, d, ci, cd)


@dataclass(frozen=True)
class Scores:
    """Log10 alignment penalties (errormodel.jl:43-49). All fields <= 0."""

    mismatch: float
    insertion: float
    deletion: float
    codon_insertion: float = -np.inf
    codon_deletion: float = -np.inf

    @classmethod
    def from_error_model(
        cls,
        errors: ErrorModel,
        mismatch: float = 0.0,
        insertion: float = 0.0,
        deletion: float = 0.0,
    ) -> "Scores":
        """Derive scores from an error model plus extra penalties
        (errormodel.jl:66-81)."""
        args = np.array(
            [
                errors.mismatch,
                errors.insertion,
                errors.deletion,
                errors.codon_insertion,
                errors.codon_deletion,
            ],
            dtype=np.float64,
        )
        with np.errstate(divide="ignore"):
            m, i, d, ci, cd = np.log10(args / args.sum())
        return cls(
            mismatch=float(m + mismatch),
            insertion=float(i + insertion),
            deletion=float(d + deletion),
            codon_insertion=float(ci + 3 * insertion),
            codon_deletion=float(cd + 3 * deletion),
        )
