"""Device-resident hill-climb: a whole INIT/REFINE stage in ONE dispatch.

The host driver (engine.driver) pays a dispatch plus a packed
device->host fetch EVERY iteration — a fixed ~75-100 ms round trip each
on the tunneled TPU (BASELINE.md), dwarfing the per-iteration device
work once the Pallas kernels run it in ~20 ms. This module runs the
reference's hill-climbing loop for one stage (model.jl:1150-1227,
restricted to the no-reference INIT/REFINE stages) as a single
``lax.while_loop``: per iteration it computes the dense all-edit score
tables on device, selects improving candidates (choose_candidates'
greedy min-dist filter, proposals.jl:104-115), applies them to a padded
template buffer (apply_proposals, proposals.jl:80-102), re-scores, and
applies the multi-candidate rollback (model.jl:898-935) — fetching
NOTHING until the stage converges; the final state comes back in one
packed array.

Bit-identity with the host driver: candidate scores come from the same
dense tables, ties break in the same generation order (all_proposals'
emission order == the flat layout's index order; both
``sorted(..., reverse=True)`` and ``top_k`` are stable), the min-dist
filter walks candidates in the same order, and the rollback uses the
same np.isclose formula — asserted by tests/test_device_loop.py.

The reference-default candidate algorithms run in-loop as GATES over
the dense tables: ``gate="edits"`` masks candidate slots with the
in-kernel edits_seen indicators (alignment_proposals' traceback
restriction, model.jl:483-497), and ``gate="seeds"`` masks FRAME indels
with the consensus-vs-reference seed anchors (model.jl:538-562,
computed on device by ops.align_codon_jax.path_indel_columns). The
gated score vector is NEG outside the restricted set, so ordering,
choose_candidates, and rollback are untouched — bit-identity with the
host loop holds gate-for-gate.

Eligibility (enforced by the driver): a stable batch — full-batch, or
batch_fixed's deterministic INIT/FRAME batch (driver.resample draws no
randomness there, so host and device loops see identical reads),
min_dist >= 2 (the vectorized apply relies on chosen proposals touching
distinct anchors), bandwidths settled, no mesh sharding. Falls back to
the host loop mid-stage (without losing work) when the
improving-candidate count exceeds the top-k cap or the template drifts
too far from its entry length for the compiled band margins.
"""

from __future__ import annotations

import os
from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

CAP = 192  # top-k candidate cap; overflow falls back to the host loop
MAX_DRIFT = 48  # max template-length drift inside one compiled loop
NEG = jnp.float32(np.finfo(np.float32).min / 2)
# trace-time flag: per-round speculation diagnostics (prediction size,
# next-round actual, match/rollback bits) via jax.debug.print. Purely
# a debugging aid — adds no ops when unset
_SPEC_DEBUG = os.environ.get("RIFRAF_TPU_SPEC_DEBUG", "") == "1"


class StageResult(NamedTuple):
    consensus: np.ndarray
    score: float
    n_iters: int
    history: list  # per-iteration consensus snapshots (iteration tops)
    completed: bool  # stage ended itself (no candidates / score stall)
    # the host loop's old_score to resume with: the score of the LAST
    # COUNTED iteration's top (a bailed iteration was aborted, so the
    # host must see the same old_score that iteration saw, not the
    # current score — else its stall check compares the score to itself)
    old_score: float = -np.inf
    # speculative evaluation accounting (speculate_k > 0 runners only):
    # launches that packed speculative segments, and how many verified —
    # each hit consumed TWO counted iterations in one launch, so the
    # stage took n_iters - spec_hits scoring rounds instead of n_iters
    spec_attempts: int = 0
    spec_hits: int = 0


def _candidate_scores(sub_t, ins_t, del_t, tmpl, tlen, total, do_indels,
                      Tmax: int, do_subs: bool = True,
                      gate: str = "none", gates=None):
    """Flat candidate score vector in all_proposals' emission order:
    [Ins(0, b) x4] then per position j: [Sub(j, b) x4, Del(j),
    Ins(j+1, b) x4]. Ineligible slots (own-base substitutions, positions
    beyond tlen, subs/indels when disabled, non-improving) hold NEG.
    ``do_subs=False`` is FRAME's indel_correction_only gating
    (model.jl:423-426).

    ``gate="edits"`` restricts slots to the edits observed in the read
    tracebacks (alignment_proposals, model.jl:483-497): ``gates`` is the
    [>= Tmax+1, 9] edits_seen indicator (cols 0-3 sub bases, 4-7 ins
    bases, 8 del). ``gate="seeds"`` restricts FRAME indels to the
    reference-alignment seed neighborhoods (model.jl:538-562): ``gates``
    is ``(ins_gate, del_gate)``, anchor-indexed [>= Tmax+1] booleans
    (Insertion(0) stays unconditional, matching all_proposals)."""
    j = jnp.arange(Tmax)
    live = j < tlen
    if do_subs:
        sub_ok = live[:, None] & (
            jnp.arange(4)[None, :] != tmpl[:Tmax, None]
        )
        if gate == "edits":
            sub_ok = sub_ok & (gates[:Tmax, 0:4] != 0)
        sub = jnp.where(sub_ok, sub_t[:Tmax], NEG)
    else:
        sub = jnp.full((Tmax, 4), NEG)
    if do_indels:
        del_ok = live
        ins0_ok = jnp.ones((4,), bool)
        ins_ok = (j[:, None] + 1) <= tlen
        if gate == "edits":
            del_ok = del_ok & (gates[:Tmax, 8] != 0)
            ins0_ok = gates[0, 4:8] != 0
            ins_ok = ins_ok & (gates[1 : Tmax + 1, 4:8] != 0)
        elif gate == "seeds":
            ins_gate, del_gate = gates
            del_ok = del_ok & del_gate[1 : Tmax + 1]
            ins_ok = ins_ok & ins_gate[1 : Tmax + 1][:, None]
        dele = jnp.where(del_ok, del_t[:Tmax], NEG)
        ins0 = jnp.where(ins0_ok, ins_t[0], NEG)
        ins_next = jnp.where(ins_ok, ins_t[1 : Tmax + 1], NEG)
    else:
        dele = jnp.full((Tmax,), NEG)
        ins0 = jnp.full((4,), NEG)
        ins_next = jnp.full((Tmax, 4), NEG)
    blocks = jnp.concatenate([sub, dele[:, None], ins_next], axis=1)
    flat = jnp.concatenate([ins0, blocks.reshape(-1)])
    return jnp.where(flat > total, flat, NEG)


def _decode(idx):
    """Flat index -> (kind, pos, base, anchor); kind 0 sub, 1 del, 2 ins.
    anchor matches proposals.anchor: Insertion -> pos, others -> pos+1."""
    is0 = idx < 4
    r = jnp.maximum(idx - 4, 0)
    j = r // 9
    k = r % 9
    kind = jnp.where(is0, 2, jnp.where(k < 4, 0, jnp.where(k == 4, 1, 2)))
    pos = jnp.where(is0, 0, jnp.where(k <= 4, j, j + 1))
    base = jnp.where(is0, idx, jnp.where(k < 4, k, jnp.where(k == 4, 0, k - 5)))
    anchor = jnp.where(kind == 2, pos, pos + 1)
    return kind, pos, base, anchor


def _choose_parts(cand_flat, min_dist: int):
    """top-k + greedy min-dist filter (choose_candidates,
    proposals.jl:104-115), exposing the intermediate arrays so the
    speculative composer can continue the greedy walk past the kept
    set. Returns (vals, ok, kind, pos, base, anchor, keep,
    n_improving)."""
    vals, idxs = jax.lax.top_k(cand_flat, CAP)
    ok = vals > NEG
    n_improving = jnp.sum((cand_flat > NEG).astype(jnp.int32))
    kind, pos, base, anchor = _decode(idxs)

    def body(c, kept_anchor):
        a = anchor[c]
        clash = jnp.any(
            (jnp.abs(a - kept_anchor) < min_dist) & (kept_anchor >= 0)
        )
        keep_c = ok[c] & jnp.logical_not(clash)
        return kept_anchor.at[c].set(jnp.where(keep_c, a, -(10**9)))

    kept_anchor = jax.lax.fori_loop(
        0, CAP, body, jnp.full((CAP,), -(10**9), jnp.int32)
    )
    keep = kept_anchor >= 0
    return vals, ok, kind, pos, base, anchor, keep, n_improving


def _choose(cand_flat, min_dist: int):
    """top-k + greedy min-dist filter. Returns (kind, pos, base, keep,
    n_improving, best_score)."""
    (vals, ok, kind, pos, base, anchor, keep,
     n_improving) = _choose_parts(cand_flat, min_dist)
    return kind, pos, base, keep, n_improving, vals[0]


# layer-1 blocking radius for the speculative composite. Empirically,
# a blocked candidate within a few bases of an applied edit is almost
# always an alternative fix of the SAME underlying error — it stops
# improving once the neighbour lands, so admitting it poisons the
# predicted set. Candidates farther out are usually independent errors
# that the next serial round really does pick. Must stay >= 2, the
# floor that keeps the coordinate remap in _remap_pos exact (no
# layer-1 edit touches a layer-2 position or shares its insertion
# anchor).
SPEC_NEAR_RADIUS = 6


def _choose_next_set(ok, anchor, keep, min_dist: int,
                     near_radius: int = SPEC_NEAR_RADIUS):
    """The speculative composite: continue _choose's greedy min-dist
    walk over the SAME top-CAP candidate list, excluding the layer-1
    picks. The next serial round enforces min_dist only among ITS OWN
    picks — the candidates it is most likely to accept are exactly the
    ones round k blocked — so layer-1 anchors block at ``near_radius``
    only (near ones are likely alternative fixes of an already-fixed
    error), while layer-2 picks block each other at the full min_dist
    like any real round. Whether the next round actually accepts this
    set is verified against the winner's own dense tables.
    ``near_radius`` must stay >= 2 (the _remap_pos exactness floor);
    the single-best segment passes 2 to keep genuine near-neighbour
    survivors reachable."""
    assert near_radius >= 2
    blocked = jnp.where(keep, anchor, -(10**9))

    def body(c, kept2):
        a = anchor[c]
        clash = jnp.any(
            (jnp.abs(a - blocked) < near_radius) & (blocked >= 0)
        ) | jnp.any((jnp.abs(a - kept2) < min_dist) & (kept2 >= 0))
        keep_c = ok[c] & jnp.logical_not(keep[c]) & jnp.logical_not(clash)
        return kept2.at[c].set(jnp.where(keep_c, a, -(10**9)))

    kept2 = jax.lax.fori_loop(
        0, CAP, body, jnp.full((CAP,), -(10**9), jnp.int32)
    )
    return kept2 >= 0


def _indel_shifts(tlen, kind, pos, keep, Tmax: int):
    """_apply's insertion/deletion cumulants for a kept edit set WITHOUT
    applying it: (inc_ins [Tmax+1], exc_del [Tmax+1]) — the coordinate
    shift every surviving position experiences after the set lands."""
    is_del = keep & (kind == 1)
    is_ins = keep & (kind == 2)
    del_mark = jnp.zeros((Tmax,), bool).at[pos].max(is_del, mode="drop")
    ins_mark = jnp.zeros((Tmax + 1,), bool).at[
        jnp.where(is_ins, pos, Tmax + 1)
    ].max(is_ins, mode="drop")
    del_mark = del_mark & (jnp.arange(Tmax) < tlen)
    ins_mark = ins_mark & (jnp.arange(Tmax + 1) <= tlen)
    inc_ins = jnp.cumsum(ins_mark.astype(jnp.int32))
    exc_del = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32),
         jnp.cumsum(del_mark.astype(jnp.int32))]
    )
    return inc_ins, exc_del


def _remap_pos(pos, inc_ins, exc_del):
    """Map an edit position from pre-apply to post-apply coordinates:
    a surviving base at j lands at j + inc_ins[j] - exc_del[j]. The
    radius-2 anchor separation between the layer-1 and layer-2 sets
    (_choose_next_set) guarantees no layer-1 edit touches a layer-2
    position (so the base survives), and no layer-1 insertion shares a
    layer-2 insertion anchor (so the same formula covers insertions:
    inc == exc there). Layer-2 edits may shift CLOSER to each other
    (indels between them); _spec_sep_ok rejects a composite whose
    remapped anchors fall under the _apply separation floor."""
    return pos + inc_ins[pos] - exc_del[pos]


def _spec_sep_ok(kind, pos_r, keep2, Tmax: int):
    """True when the remapped layer-2 anchors still satisfy _apply's
    independence floor (pairwise >= 2). Layer-1 indels can contract
    layer-2 gaps by one per indel in the gap — at min_dist >= 4 the
    floor can never be crossed, but the check is cheap and keeps tiny
    min_dist configurations safe (an invalid composite is clamped to a
    duplicate segment and can never match)."""
    a_r = jnp.where(kind == 2, pos_r, pos_r + 1)
    # unique far-apart fillers for the dropped lanes so they can never
    # trip the adjacent-difference test
    fill = 4 * Tmax + 2 * jnp.arange(CAP, dtype=a_r.dtype)
    srt = jnp.sort(jnp.where(keep2, a_r, fill))
    return jnp.all(srt[1:] - srt[:-1] >= 2)


def _apply(tmpl, tlen, kind, pos, base, keep, Tmax: int):
    """Vectorized apply_proposals (proposals.jl:80-102) for a
    min-dist-separated set: at most one edit per anchor, so no
    deletion+insertion interactions; every kept edit lands at an
    independent position."""
    is_sub = keep & (kind == 0)
    is_del = keep & (kind == 1)
    is_ins = keep & (kind == 2)
    sub_mark = jnp.zeros((Tmax,), bool).at[pos].max(is_sub, mode="drop")
    sub_base = jnp.zeros((Tmax,), jnp.int8).at[pos].max(
        jnp.where(is_sub, base, 0).astype(jnp.int8), mode="drop"
    )
    del_mark = jnp.zeros((Tmax,), bool).at[pos].max(is_del, mode="drop")
    ins_mark = jnp.zeros((Tmax + 1,), bool).at[
        jnp.where(is_ins, pos, Tmax + 1)
    ].max(is_ins, mode="drop")
    ins_base = jnp.zeros((Tmax + 1,), jnp.int8).at[
        jnp.where(is_ins, pos, Tmax + 1)
    ].max(jnp.where(is_ins, base, 0).astype(jnp.int8), mode="drop")
    j = jnp.arange(Tmax)
    livej = j < tlen
    sub_mark = sub_mark & livej
    del_mark = del_mark & livej
    ins_mark = ins_mark & (jnp.arange(Tmax + 1) <= tlen)

    inc_ins = jnp.cumsum(ins_mark.astype(jnp.int32))  # #ins at q <= p
    exc_ins = jnp.concatenate([jnp.zeros((1,), jnp.int32), inc_ins[:-1]])
    cum_del = jnp.cumsum(del_mark.astype(jnp.int32))
    exc_del = jnp.concatenate([jnp.zeros((1,), jnp.int32), cum_del])

    out = jnp.zeros((Tmax,), jnp.int8)
    newbase = jnp.where(sub_mark, sub_base, tmpl[:Tmax])
    # base at j lands after every insertion at q <= j and loses a slot
    # per deletion at q < j; insertion at p goes before original index p
    w_base = j + inc_ins[:Tmax] - exc_del[:Tmax]
    put_base = livej & jnp.logical_not(del_mark)
    out = out.at[jnp.where(put_base, w_base, Tmax)].set(newbase, mode="drop")
    p1 = jnp.arange(Tmax + 1)
    w_ins = p1 + exc_ins - exc_del[: Tmax + 1]
    out = out.at[jnp.where(ins_mark, w_ins, Tmax)].set(
        ins_base, mode="drop"
    )
    new_tlen = tlen + inc_ins[-1] - cum_del[-1]
    return out, new_tlen


def _isclose(a, b):
    """np.isclose with default tolerances (the rollback comparison,
    driver.handle_candidates / model.jl:917-919)."""
    return jnp.abs(a - b) <= 1e-8 + 1e-5 * jnp.abs(b)


def unpack_stage_packed(packed, H: int, Tmax: int, speculate: bool = False):
    """Host-side view of ONE packed stage-program row (the single-fetch
    array built at the end of ``run`` below): returns ``(tlen, total,
    n_rec, completed, resume_old, hlen [H] int64, hist [H, Tmax] int8,
    tmpl [Tmax] int8)``. The one consumer-side copy of the layout,
    shared by ``runner`` and parallel.sweep_sharded's per-bucket
    unpack. ``speculate=True`` rows (speculate_k > 0 runners) carry a
    two-scalar ``[spec_attempts, spec_hits]`` tail appended AFTER the
    default layout — the front offsets are byte-identical either way —
    and the tuple gains those two ints."""
    p = np.asarray(packed)
    o = 5
    hlen = p[o : o + H].astype(np.int64)
    o += H
    hist = p[o : o + H * Tmax].reshape(H, Tmax).astype(np.int8)
    o += H * Tmax
    tmpl = p[o : o + Tmax].astype(np.int8)
    out = (int(p[0]), float(p[1]), int(p[2]), bool(p[3]), float(p[4]),
           hlen, hist, tmpl)
    if speculate:
        o += Tmax
        out = out + (int(p[o]), int(p[o + 1]))
    return out


def make_stage_runner(
    step_fn: Callable,  # (tmpl, tlen, step_state) -> (total, sub, ins, del)
    do_indels: bool,
    min_dist: int,
    H: int,  # history capacity = params.max_iters + 1 (static)
    Tmax: int,
    stop_on_same: bool,
    do_subs: bool = True,
    gate: str = "none",
    plan=None,
    seg_step_fn: Callable = None,
    aot_key=None,
    speculate_k: int = 0,
    spec_step_fn: Callable = None,
):
    """Build the jitted whole-stage runner. ``step_fn`` takes the
    device-resident batch state as an ARGUMENT pytree (not a closure) so
    one compiled runner serves every batch of the same shape — callers
    cache via engine.realign's lru-cached factories. ``stop_on_same``
    mirrors check_score's full-batch stall exit (driver.check_score
    requires batch_size == len(sequences) for it).

    With ``gate != "none"`` the step_fn returns a FIFTH element — the
    gate pytree for the template it just scored (edits_seen array for
    "edits", (ins_gate, del_gate) for "seeds") — which rides the carry
    alongside the tables so candidate masking always matches the
    template the tables describe.

    ``plan`` is opaque diagnostic metadata (the utils.shapes.BlockPlan
    the step was built with, for Pallas steps) attached to the returned
    runner as ``runner.plan`` so sweep/bench reporting can show which
    VMEM blocking each cached stage program uses.

    ``seg_step_fn`` (optional) scores SEVERAL candidate templates of
    the same batch in ONE segment-packed dispatch:
    ``(tmpls [2, Tmax], tlens [2], step_state) -> tables`` with a
    leading segment axis on every leaf. When provided, the rollback
    re-score packs {multi-applied, single-best} as two segments of one
    launch (the reads duplicated per segment), instead of a
    conditional second dispatch — on lane-starved solo runs (the
    reference-default 5/20-read batches) the extra segment rides
    otherwise-padded lanes for free, and one dispatch replaces two.
    Values are unchanged: the per-segment reductions reproduce
    ``step_fn``'s sums exactly (ops.fused.fused_step_segmented), and
    the same rollback comparison selects the same winner — the
    conditional path merely skipped computing the loser.

    ``speculate_k`` (0, 1, or 2) enables SPECULATIVE edit-set
    evaluation: every scoring round packs, alongside the round's
    {multi-applied, single-best} pair, up to ``speculate_k`` candidate
    templates for the NEXT round — the greedy min-dist walk continued
    past this round's picks (the composite edit set round k+1 is
    expected to accept), applied on the predicted winner — as extra
    segments of the SAME launch via ``spec_step_fn`` ``(tmpls [S,Tmax],
    tlens [S], step_state) -> tables`` with a leading segment axis,
    S = 2 + speculate_k. After the launch, round k+1's greedy rule is
    replayed against the winner's OWN dense tables (they came back in
    segment 0/1); when the replay lands exactly on a speculative
    template, its tables are already in hand and the loop advances TWO
    counted iterations for one launch — an entire round, realign
    included, is skipped. On a miss the carry is bit-identical to the
    serial round's exit, and the next body iteration recomputes round
    k+1 from the same tables — zero result change, only the speculative
    lanes were wasted. ``speculate_k=0`` (default) leaves the legacy
    body untouched — bit-identical program, byte-identical packed
    layout. When speculating, ``spec_step_fn`` supersedes
    ``seg_step_fn`` (the rollback pair rides the same launch)."""
    if speculate_k not in (0, 1, 2):
        raise ValueError(f"speculate_k must be 0, 1, or 2: {speculate_k}")
    if speculate_k and spec_step_fn is None:
        raise ValueError("speculate_k > 0 requires spec_step_fn")
    speculating = speculate_k > 0

    def cond(carry):
        return jnp.logical_not(carry["done"]) & (
            carry["it"] < carry["iters_left"]
        )

    def body(carry):
        tmpl, tlen = carry["tmpl"], carry["tlen"]
        total, sub_t, ins_t, del_t = carry["tables"][:4]
        gates = carry["tables"][4] if gate != "none" else None
        it = carry["it"]
        # record this iteration's starting consensus (the driver appends
        # to consensus_stages at every iteration top)
        hist = jax.lax.dynamic_update_slice(
            carry["hist"], tmpl[None], (it, jnp.zeros_like(it))
        )
        hlen = carry["hlen"].at[it].set(tlen)

        # check_score, full-batch case: unchanged score at the top of a
        # non-first stage iteration ends the stage (driver.check_score's
        # cur_iters > 1; prev_iters counts host iterations already spent
        # in this stage before the device loop took over)
        if stop_on_same:
            stop_same = ((it + carry["prev_iters"]) > 0) & (
                total == carry["old_score"]
            )
        else:
            stop_same = jnp.asarray(False)

        cand = _candidate_scores(
            sub_t, ins_t, del_t, tmpl, tlen, total, do_indels, Tmax,
            do_subs, gate, gates,
        )
        kind, pos, base, keep, n_improving, best = _choose(cand, min_dist)
        no_cand = n_improving == 0
        overflow = n_improving > CAP

        tmpl_multi, tlen_multi = _apply(tmpl, tlen, kind, pos, base, keep, Tmax)
        n_keep = jnp.sum(keep.astype(jnp.int32))
        # stay inside the padded buffer / compiled band-height margin
        drift = (tlen_multi + 1 >= Tmax) | (
            jnp.abs(tlen_multi - carry["tlen0"]) > MAX_DRIFT
        )
        bail = (overflow | drift) & jnp.logical_not(stop_same | no_cand)
        done = stop_same | no_cand | bail
        do_work = jnp.logical_not(done)

        def work(_):
            # handle_candidates: apply all chosen, re-score; if multiple
            # and the combination is no better than the best single,
            # roll back to the single best (which the next fill scores)
            if seg_step_fn is not None:
                # segment-packed pair: score multi + single-best in ONE
                # dispatch (two segments over duplicated reads), then
                # select — same values, half the dispatches
                keep1 = keep & (jnp.cumsum(keep.astype(jnp.int32)) == 1)
                tmpl1, tlen1 = _apply(
                    tmpl, tlen, kind, pos, base, keep1, Tmax
                )
                outs = seg_step_fn(
                    jnp.stack([tmpl_multi, tmpl1]),
                    jnp.stack([tlen_multi, tlen1]),
                    carry["step_state"],
                )
                total2 = outs[0][0]
                rollback = (n_keep > 1) & (
                    (total2 < best) | _isclose(total2, best)
                )
                pick = jax.tree_util.tree_map(
                    lambda x: jnp.where(rollback, x[1], x[0]), outs
                )
                return (
                    jnp.where(rollback, tmpl1, tmpl_multi),
                    jnp.where(rollback, tlen1, tlen_multi),
                    pick,
                )
            out2 = step_fn(tmpl_multi, tlen_multi, carry["step_state"])
            total2 = out2[0]
            rollback = (n_keep > 1) & (
                (total2 < best) | _isclose(total2, best)
            )

            def single(_):
                keep1 = keep & (jnp.cumsum(keep.astype(jnp.int32)) == 1)
                tmpl1, tlen1 = _apply(tmpl, tlen, kind, pos, base, keep1, Tmax)
                return (tmpl1, tlen1) + (
                    step_fn(tmpl1, tlen1, carry["step_state"]),
                )

            def multi(_):
                return tmpl_multi, tlen_multi, out2

            return jax.lax.cond(rollback, single, multi, None)

        def no_work(_):
            return tmpl, tlen, carry["tables"]

        tmpl_n, tlen_n, tables_n = jax.lax.cond(do_work, work, no_work, None)
        return {
            "tmpl": tmpl_n,
            "tlen": tlen_n,
            "tables": tables_n,
            "old_score": total,
            "done": done,
            "bail": carry["bail"] | bail,
            "it": it + jnp.where(done, 0, 1),
            # a bailed iteration was ABORTED before applying anything:
            # the host must redo it, so it is not counted or recorded
            "n_rec": jnp.where(bail, it, it + 1),
            "old_score_prev": carry["old_score"],
            "hist": hist,
            "hlen": hlen,
            "tlen0": carry["tlen0"],
            "iters_left": carry["iters_left"],
            "prev_iters": carry["prev_iters"],
            "step_state": carry["step_state"],
        }

    def body_spec(carry):
        # the speculative round: identical pre-launch logic to ``body``
        # (same candidate scoring, same greedy choose, same bail/stall
        # exits), then ONE S-segment launch scoring {multi, single-best,
        # speculative composite(s)} together, then the serial rollback
        # rule for round k and a replay of round k+1's greedy rule
        # against the winner's freshly-fetched tables
        tmpl, tlen = carry["tmpl"], carry["tlen"]
        total, sub_t, ins_t, del_t = carry["tables"][:4]
        gates = carry["tables"][4] if gate != "none" else None
        it = carry["it"]
        hist = jax.lax.dynamic_update_slice(
            carry["hist"], tmpl[None], (it, jnp.zeros_like(it))
        )
        hlen = carry["hlen"].at[it].set(tlen)

        if stop_on_same:
            stop_same = ((it + carry["prev_iters"]) > 0) & (
                total == carry["old_score"]
            )
        else:
            stop_same = jnp.asarray(False)

        cand = _candidate_scores(
            sub_t, ins_t, del_t, tmpl, tlen, total, do_indels, Tmax,
            do_subs, gate, gates,
        )
        (vals, ok, kind, pos, base, anchor, keep,
         n_improving) = _choose_parts(cand, min_dist)
        best = vals[0]
        no_cand = n_improving == 0
        overflow = n_improving > CAP

        tmpl_multi, tlen_multi = _apply(tmpl, tlen, kind, pos, base, keep,
                                        Tmax)
        n_keep = jnp.sum(keep.astype(jnp.int32))
        drift = (tlen_multi + 1 >= Tmax) | (
            jnp.abs(tlen_multi - carry["tlen0"]) > MAX_DRIFT
        )
        bail = (overflow | drift) & jnp.logical_not(stop_same | no_cand)
        done = stop_same | no_cand | bail
        do_work = jnp.logical_not(done)

        def guard_spec(sp, sl, fallback_t, fallback_l, extra_ok):
            # a speculative template must respect the compiled margins
            # (band height / padded buffer) like any real round; out of
            # range (or structurally invalid per extra_ok), substitute
            # a harmless duplicate and never match
            sp_ok = extra_ok & (sl + 1 < Tmax) & (
                jnp.abs(sl - carry["tlen0"]) <= MAX_DRIFT
            )
            return (sp_ok, jnp.where(sp_ok, sp, fallback_t),
                    jnp.where(sp_ok, sl, fallback_l))

        def work(_):
            keep1 = keep & (jnp.cumsum(keep.astype(jnp.int32)) == 1)
            tmpl1, tlen1 = _apply(tmpl, tlen, kind, pos, base, keep1, Tmax)

            # compose the speculative edit set(s): the greedy walk
            # continued past the layer-1 picks, positions remapped
            # through layer-1's indels, applied on the predicted winner
            keep2 = _choose_next_set(ok, anchor, keep, min_dist)
            inc_ins, exc_del = _indel_shifts(tlen, kind, pos, keep, Tmax)
            pos_r = _remap_pos(pos, inc_ins, exc_del)
            sep_ok = _spec_sep_ok(kind, pos_r, keep2, Tmax)
            spec0, sl0 = _apply(tmpl_multi, tlen_multi, kind, pos_r, base,
                                keep2, Tmax)
            spec0_ok, spec0, sl0 = guard_spec(spec0, sl0, tmpl_multi,
                                              tlen_multi, sep_ok)
            tmpls = [tmpl_multi, tmpl1, spec0]
            tlens = [tlen_multi, tlen1, sl0]
            if speculate_k >= 2:
                # the single-best segment drops the composite's poison
                # filter to the radius-2 floor: a genuine straggler 2-5
                # bases from a layer-1 edit is exactly the shape of the
                # common one-edit endgame round, and a single edit can't
                # be poisoned by extra picks
                keep2n = _choose_next_set(ok, anchor, keep, min_dist,
                                          near_radius=2)
                keep2_1 = keep2n & (
                    jnp.cumsum(keep2n.astype(jnp.int32)) == 1
                )
                spec1, sl1 = _apply(tmpl_multi, tlen_multi, kind, pos_r,
                                    base, keep2_1, Tmax)
                # a single edit has no pairwise separation to violate
                spec1_ok, spec1, sl1 = guard_spec(spec1, sl1, tmpl_multi,
                                                  tlen_multi,
                                                  jnp.asarray(True))
                tmpls.append(spec1)
                tlens.append(sl1)

            outs = spec_step_fn(
                jnp.stack(tmpls), jnp.stack(tlens), carry["step_state"]
            )
            # round-k resolve: the serial rollback rule, segment 0 vs 1
            total_m = outs[0][0]
            rollback = (n_keep > 1) & (
                (total_m < best) | _isclose(total_m, best)
            )
            w_tmpl = jnp.where(rollback, tmpl1, tmpl_multi)
            w_tlen = jnp.where(rollback, tlen1, tlen_multi)
            tables_w = jax.tree_util.tree_map(
                lambda x: jnp.where(rollback, x[1], x[0]), outs
            )
            total_w = tables_w[0]

            # replay round k+1's greedy rule against the winner's OWN
            # dense tables; the stall guard (it+1+prev_iters) > 0 always
            # holds at iteration it+1
            gates_w = tables_w[4] if gate != "none" else None
            if stop_on_same:
                stop_same2 = total_w == total
            else:
                stop_same2 = jnp.asarray(False)
            cand2 = _candidate_scores(
                tables_w[1], tables_w[2], tables_w[3], w_tmpl, w_tlen,
                total_w, do_indels, Tmax, do_subs, gate, gates_w,
            )
            (vals2, ok2, kind2, pos2, base2, anchor2, keep2a,
             n_improving2) = _choose_parts(cand2, min_dist)
            best2 = vals2[0]
            no_cand2 = n_improving2 == 0
            overflow2 = n_improving2 > CAP
            tmpl_m2, tlen_m2 = _apply(w_tmpl, w_tlen, kind2, pos2, base2,
                                      keep2a, Tmax)
            n_keep2 = jnp.sum(keep2a.astype(jnp.int32))
            drift2 = (tlen_m2 + 1 >= Tmax) | (
                jnp.abs(tlen_m2 - carry["tlen0"]) > MAX_DRIFT
            )
            done2 = stop_same2 | no_cand2 | overflow2 | drift2
            can2 = (it + 1) < carry["iters_left"]

            # a hit = the replayed choice IS a speculative template
            # (bit-equal buffer), so its score/tables are already here
            match0 = spec0_ok & (sl0 == tlen_m2) & jnp.all(spec0 == tmpl_m2)
            total_m2 = outs[0][2]
            rollback2 = (n_keep2 > 1) & (
                (total_m2 < best2) | _isclose(total_m2, best2)
            )
            if speculate_k >= 2:
                keep2b = keep2a & (
                    jnp.cumsum(keep2a.astype(jnp.int32)) == 1
                )
                tmpl1_2, tlen1_2 = _apply(w_tmpl, w_tlen, kind2, pos2,
                                          base2, keep2b, Tmax)
                match1 = spec1_ok & (sl1 == tlen1_2) & jnp.all(
                    spec1 == tmpl1_2
                )
                # when round k+1 applies exactly ONE edit, the full-set
                # and single-best templates coincide and the serial
                # rollback cannot fire (it needs n_keep2 > 1), so
                # matching EITHER speculative segment suffices — the
                # single-best segment often survives rounds where extra
                # predicted edits spoiled the composite. rollback2 is
                # only meaningful under match0 (its score input is
                # segment 2's total), and single1 never consults it.
                single1 = n_keep2 == 1
                hit = (can2 & jnp.logical_not(done2)
                       & jnp.logical_not(rollback)
                       & ((match0
                           & (jnp.logical_not(rollback2) | match1))
                          | (single1 & match1)))
                use1 = (match0 & rollback2) | (
                    single1 & match1 & jnp.logical_not(match0)
                )
                tables_hit = jax.tree_util.tree_map(
                    lambda x: jnp.where(use1, x[3], x[2]), outs
                )
                tmpl_hit = jnp.where(use1, tmpl1_2, tmpl_m2)
                tlen_hit = jnp.where(use1, tlen1_2, tlen_m2)
            else:
                hit = (can2 & jnp.logical_not(done2)
                       & jnp.logical_not(rollback)
                       & jnp.logical_not(rollback2) & match0)
                tables_hit = jax.tree_util.tree_map(lambda x: x[2], outs)
                tmpl_hit = tmpl_m2
                tlen_hit = tlen_m2

            tmpl_n = jnp.where(hit, tmpl_hit, w_tmpl)
            tlen_n = jnp.where(hit, tlen_hit, w_tlen)
            tables_n = jax.tree_util.tree_map(
                lambda a, b: jnp.where(hit, a, b), tables_hit, tables_w
            )
            if _SPEC_DEBUG:
                jax.debug.print(
                    "spec it={it} keep1={nk} pred={np} next={nn} "
                    "rb={rb} rb2={rb2} done2={d2} same2={ss} can2={c2} "
                    "ok0={ok} dlen={dl} ndiff={nd} hit={h}",
                    it=it, nk=n_keep, np=jnp.sum(keep2.astype(jnp.int32)),
                    nn=n_keep2, rb=rollback, rb2=rollback2, d2=done2,
                    ss=stop_same2, c2=can2, ok=spec0_ok,
                    dl=sl0 - tlen_m2,
                    nd=jnp.sum((spec0 != tmpl_m2).astype(jnp.int32)),
                    h=hit,
                )
                big = jnp.int32(10**6)

                def _first8(m, a):
                    return jnp.sort(jnp.where(m, a, big))[:8]

                jax.debug.print(
                    "  l1={l1} predP={pa} predK={pk} nextP={na} "
                    "nextK={nk2}",
                    l1=_first8(keep, anchor),
                    pa=_first8(keep2, pos_r),
                    pk=_first8(keep2, kind * 10000 + pos_r),
                    na=_first8(keep2a, pos2),
                    nk2=_first8(keep2a, kind2 * 10000 + pos2),
                )
                if speculate_k >= 2:
                    jax.debug.print(
                        "  specN={sn} spec1K={s1}",
                        sn=_first8(keep2n, kind * 10000 + pos_r),
                        s1=_first8(keep2_1, kind * 10000 + pos_r),
                    )
            return tmpl_n, tlen_n, tables_n, hit, w_tmpl, w_tlen, total_w

        def no_work(_):
            return (tmpl, tlen, carry["tables"], jnp.asarray(False),
                    tmpl, tlen, total)

        (tmpl_n, tlen_n, tables_n, hit, w_tmpl, w_tlen,
         w_total) = jax.lax.cond(do_work, work, no_work, None)
        # a hit consumed round k+1 too: record ITS iteration top (the
        # round-k winner) exactly as the serial loop would have
        hist2 = jax.lax.dynamic_update_slice(
            hist, w_tmpl[None], (it + 1, jnp.zeros_like(it))
        )
        hist = jnp.where(hit, hist2, hist)
        hlen = jnp.where(hit, hlen.at[it + 1].set(w_tlen), hlen)
        adv = jnp.where(done, 0, jnp.where(hit, 2, 1))
        return {
            "tmpl": tmpl_n,
            "tlen": tlen_n,
            "tables": tables_n,
            # on a hit the carry mirrors the serial state AFTER round
            # k+1: old_score = the winner's total (round k+1's top),
            # old_score_prev = round k's top total
            "old_score": jnp.where(hit, w_total, total),
            "done": done,
            "bail": carry["bail"] | bail,
            "it": it + adv,
            "n_rec": jnp.where(bail, it, it + jnp.maximum(adv, 1)),
            "old_score_prev": jnp.where(hit, total, carry["old_score"]),
            "hist": hist,
            "hlen": hlen,
            "tlen0": carry["tlen0"],
            "iters_left": carry["iters_left"],
            "prev_iters": carry["prev_iters"],
            "step_state": carry["step_state"],
            "spec_try": carry["spec_try"] + do_work.astype(jnp.int32),
            "spec_hit": carry["spec_hit"] + hit.astype(jnp.int32),
        }

    @jax.jit
    def run(tmpl0, tlen0, prev_score, iters_left, prev_iters, step_state):
        tables0 = step_fn(tmpl0, tlen0, step_state)
        carry = {
            "tmpl": tmpl0,
            "tlen": tlen0,
            "tables": tables0,
            # match the step dtype (f64 under x64) or the while_loop
            # carry would change dtype across iterations
            "old_score": prev_score.astype(tables0[0].dtype),
            "done": jnp.asarray(False),
            "bail": jnp.asarray(False),
            "it": jnp.int32(0),
            "n_rec": jnp.int32(0),
            "hist": jnp.zeros((H, Tmax), jnp.int8),
            "hlen": jnp.zeros((H,), jnp.int32),
            "tlen0": tlen0,
            "iters_left": iters_left,
            "prev_iters": prev_iters,
            "step_state": step_state,
            "old_score_prev": prev_score.astype(tables0[0].dtype),
        }
        if speculating:
            carry["spec_try"] = jnp.int32(0)
            carry["spec_hit"] = jnp.int32(0)
        out = jax.lax.while_loop(
            cond, body_spec if speculating else body, carry
        )
        # ONE packed fetch: scalars, per-iteration lengths, history,
        # template — in the step dtype so the final score survives intact
        pdt = out["tables"][0].dtype
        parts = [
            jnp.stack([
                out["tlen"].astype(pdt),
                out["tables"][0],
                out["n_rec"].astype(pdt),
                # completed = the stage ENDED ITSELF (no candidates /
                # score stall): a bail or an iters_left exhaustion exits
                # with done's natural-termination causes absent, and the
                # host loop must keep iterating, not finish_stage
                (out["done"] & jnp.logical_not(out["bail"])).astype(pdt),
                # resume old_score: a bailed iteration m was aborted, so
                # the host redoing it must see what IT saw (the score of
                # iteration m-1), not S_m — else check_score's stall
                # test compares the score against itself
                jnp.where(out["bail"], out["old_score_prev"],
                          out["tables"][0]).astype(pdt),
            ]),
            out["hlen"].astype(pdt),
            out["hist"].astype(pdt).reshape(-1),
            out["tmpl"].astype(pdt),
        ]
        if speculating:
            # speculation tail AFTER the default layout: front offsets
            # stay byte-identical for every existing consumer
            parts.append(jnp.stack([
                out["spec_try"].astype(pdt),
                out["spec_hit"].astype(pdt),
            ]))
        return jnp.concatenate(parts)

    def runner(consensus: np.ndarray, prev_score: float,
               iters_left: int, prev_iters: int = 0,
               step_state=()) -> StageResult:
        tmpl0 = np.zeros(Tmax, np.int8)
        tmpl0[: len(consensus)] = consensus
        # prev_score rides as a weak-typed python float: under x64 it
        # traces as f64, so the stall comparison sees the exact host
        # score (an early f32 cast broke f64 bit-identity runs)
        packed = np.asarray(
            run(jnp.asarray(tmpl0), jnp.int32(len(consensus)),
                float(prev_score), jnp.int32(iters_left),
                jnp.int32(prev_iters), step_state)
        )
        spec_attempts = spec_hits = 0
        if speculating:
            (tlen, total, n_rec, completed, resume_old, hlen, hist,
             tmpl, spec_attempts, spec_hits) = unpack_stage_packed(
                packed, H, Tmax, speculate=True)
        else:
            (tlen, total, n_rec, completed, resume_old, hlen, hist,
             tmpl) = unpack_stage_packed(packed, H, Tmax)
        history = [hist[i, : hlen[i]].copy() for i in range(n_rec)]
        return StageResult(
            consensus=tmpl[:tlen],
            score=total,
            n_iters=n_rec,
            history=history,
            completed=completed,
            old_score=resume_old,
            spec_attempts=spec_attempts,
            spec_hits=spec_hits,
        )

    # the raw compiled whole-stage program: callers that batch a CLUSTER
    # axis (parallel.sweep_sharded) vmap this directly and unpack the
    # packed rows themselves. ``aot_key`` (kind, *statics) routes it
    # through the serve.aot persisted-executable cache — a pass-through
    # until a cache is activated, then a cold process loads the
    # serialized module instead of re-tracing this whole stage loop.
    if aot_key is not None:
        from ..serve.aot import aot_program

        run = aot_program(aot_key[0], tuple(aot_key[1:]), run)
    runner.run = run
    runner.plan = plan
    return runner


def make_segment_stage_runner(
    step_fn: Callable,  # (tmpls [S,Tmax], tlens [S], state) -> per-seg tables
    do_indels: bool,
    min_dist: int,
    H: int,
    Tmax: int,
    stop_on_same: bool,
    n_seg: int,
    do_subs: bool = True,
    gate: str = "none",
    plan=None,
):
    """Whole-stage runner for a SEGMENT-PACKED lane block: ``n_seg``
    independent problems share one read block (utils.shapes
    .pack_segments), each hill-climbing its own template, with ONE
    segment-aware fused dispatch per iteration scoring every segment's
    current candidate jointly (ops.fused.fused_step_segmented).

    This is the hand-written equivalent of ``jax.vmap`` over
    per-problem ``make_stage_runner`` loops — which is exactly what it
    must stay bit-identical to (the per-problem baseline runs each
    cluster in its own block). The while loop mirrors vmap's batching
    rule for ``lax.while_loop``: the condition is ``any`` over the
    per-segment predicates, the body computes every segment every
    iteration, and finished segments' carries are frozen by a
    per-segment select. ``lax.cond`` under vmap computes both branches
    and selects — so the rollback re-score here scores BOTH the
    multi-applied and single-best templates for every segment each
    iteration (two segment-packed dispatches), matching the vmapped
    program's values branch for branch. All per-segment scalar logic
    (candidate scoring/selection/apply, history, stall checks) is the
    SAME code as the per-problem runner, vmapped over the segment
    axis.

    ``step_fn`` takes per-segment templates ``[S, Tmax]`` / lengths
    ``[S]`` plus the (shared) packed batch state, and returns the
    tables tuple with a leading segment axis on every leaf:
    ``(total [S], sub [S,T1,4], ins [S,T1,4], del [S,T1][, gates])``.

    ``run(tmpl0 [S,Tmax], tlen0 [S], live [S], prev_score [S],
    iters_left, prev_iters, step_state)`` returns one packed row per
    segment (``unpack_stage_packed`` layout). Dead slots
    (``live=False`` — padding when a block holds fewer than ``n_seg``
    problems) start ``done`` and never iterate."""

    if gate == "none":
        def cand_fn(sub_t, ins_t, del_t, tmpl, tlen, total):
            return _candidate_scores(
                sub_t, ins_t, del_t, tmpl, tlen, total, do_indels,
                Tmax, do_subs, gate, None,
            )
        cand_vmap = jax.vmap(cand_fn)
    else:
        def cand_fn(sub_t, ins_t, del_t, tmpl, tlen, total, gates):
            return _candidate_scores(
                sub_t, ins_t, del_t, tmpl, tlen, total, do_indels,
                Tmax, do_subs, gate, gates,
            )
        cand_vmap = jax.vmap(cand_fn)
    choose_vmap = jax.vmap(lambda c: _choose(c, min_dist))
    apply_vmap = jax.vmap(
        lambda tm, tl, k, p, b, kp: _apply(tm, tl, k, p, b, kp, Tmax)
    )

    def active_pred(carry):
        return jnp.logical_not(carry["done"]) & (
            carry["it"] < carry["iters_left"]
        )

    def cond(carry):
        return jnp.any(active_pred(carry))

    def body(carry):
        pred = active_pred(carry)  # [S]
        tmpl, tlen = carry["tmpl"], carry["tlen"]
        total, sub_t, ins_t, del_t = carry["tables"][:4]
        it = carry["it"]
        hist = jax.vmap(
            lambda h, t, i: jax.lax.dynamic_update_slice(
                h, t[None], (i, jnp.zeros_like(i))
            )
        )(carry["hist"], tmpl, it)
        hlen = jax.vmap(lambda hl, i, tl: hl.at[i].set(tl))(
            carry["hlen"], it, tlen
        )

        if stop_on_same:
            stop_same = ((it + carry["prev_iters"]) > 0) & (
                total == carry["old_score"]
            )
        else:
            stop_same = jnp.zeros((n_seg,), bool)

        if gate == "none":
            cand = cand_vmap(sub_t, ins_t, del_t, tmpl, tlen, total)
        else:
            cand = cand_vmap(
                sub_t, ins_t, del_t, tmpl, tlen, total,
                carry["tables"][4],
            )
        kind, pos, base, keep, n_improving, best = choose_vmap(cand)
        no_cand = n_improving == 0
        overflow = n_improving > CAP

        tmpl_multi, tlen_multi = apply_vmap(
            tmpl, tlen, kind, pos, base, keep
        )
        n_keep = jnp.sum(keep.astype(jnp.int32), axis=1)
        drift = (tlen_multi + 1 >= Tmax) | (
            jnp.abs(tlen_multi - carry["tlen0"]) > MAX_DRIFT
        )
        bail = (overflow | drift) & jnp.logical_not(stop_same | no_cand)
        done = stop_same | no_cand | bail

        # work: the vmapped cond computes both branches for every
        # segment — two segment-packed dispatches, select per segment
        keep1 = keep & (jnp.cumsum(keep.astype(jnp.int32), axis=1) == 1)
        tmpl1, tlen1 = apply_vmap(tmpl, tlen, kind, pos, base, keep1)
        out2 = step_fn(tmpl_multi, tlen_multi, carry["step_state"])
        out1 = step_fn(tmpl1, tlen1, carry["step_state"])
        rollback = (n_keep > 1) & (
            (out2[0] < best) | _isclose(out2[0], best)
        )

        def sel(mask, a, b):
            m = mask.reshape((n_seg,) + (1,) * (a.ndim - 1))
            return jnp.where(m, a, b)

        tmpl_w = sel(rollback, tmpl1, tmpl_multi)
        tlen_w = jnp.where(rollback, tlen1, tlen_multi)
        tables_w = jax.tree_util.tree_map(
            lambda a, b: sel(rollback, a, b), out1, out2
        )
        tmpl_n = sel(done, tmpl, tmpl_w)
        tlen_n = jnp.where(done, tlen, tlen_w)
        tables_n = jax.tree_util.tree_map(
            lambda old, new: sel(done, old, new),
            carry["tables"], tables_w,
        )

        new = {
            "tmpl": tmpl_n,
            "tlen": tlen_n,
            "tables": tables_n,
            "old_score": total,
            "done": done,
            "bail": carry["bail"] | bail,
            "it": it + jnp.where(done, 0, 1),
            "n_rec": jnp.where(bail, it, it + 1),
            "old_score_prev": carry["old_score"],
            "hist": hist,
            "hlen": hlen,
            "tlen0": carry["tlen0"],
            "iters_left": carry["iters_left"],
            "prev_iters": carry["prev_iters"],
            "step_state": carry["step_state"],
        }
        # freeze finished segments (vmap's while_loop masking rule)
        frozen = {}
        for k in new:
            if k in ("iters_left", "prev_iters", "step_state"):
                frozen[k] = new[k]
            else:
                frozen[k] = jax.tree_util.tree_map(
                    lambda n, o: jnp.where(
                        pred.reshape((n_seg,) + (1,) * (n.ndim - 1)),
                        n, o,
                    ),
                    new[k], carry[k],
                )
        return frozen

    @jax.jit
    def run(tmpl0, tlen0, live, prev_score, iters_left, prev_iters,
            step_state):
        tables0 = step_fn(tmpl0, tlen0, step_state)
        carry = {
            "tmpl": tmpl0,
            "tlen": tlen0,
            "tables": tables0,
            "old_score": prev_score.astype(tables0[0].dtype),
            "done": jnp.logical_not(live),
            "bail": jnp.zeros((n_seg,), bool),
            "it": jnp.zeros((n_seg,), jnp.int32),
            "n_rec": jnp.zeros((n_seg,), jnp.int32),
            "hist": jnp.zeros((n_seg, H, Tmax), jnp.int8),
            "hlen": jnp.zeros((n_seg, H), jnp.int32),
            "tlen0": tlen0,
            "iters_left": iters_left,
            "prev_iters": prev_iters,
            "step_state": step_state,
            "old_score_prev": prev_score.astype(tables0[0].dtype),
        }
        out = jax.lax.while_loop(cond, body, carry)
        pdt = out["tables"][0].dtype
        head = jnp.stack([
            out["tlen"].astype(pdt),
            out["tables"][0],
            out["n_rec"].astype(pdt),
            (out["done"] & jnp.logical_not(out["bail"])).astype(pdt),
            jnp.where(out["bail"], out["old_score_prev"],
                      out["tables"][0]).astype(pdt),
        ], axis=1)
        return jnp.concatenate([
            head,
            out["hlen"].astype(pdt),
            out["hist"].astype(pdt).reshape(n_seg, -1),
            out["tmpl"].astype(pdt),
        ], axis=1)

    run.plan = plan
    return run
