"""Result-integrity primitives: numerical sentinels, shadow-verify
sampling, and the divergence tolerance shared by sweep and serve.

Three silent-wrong-answer classes threaten a consensus fleet (PAPERS.md:
gpuPairHMM treats log-space Pair-HMM fidelity as a first-class
accelerator concern; Endeavor targets the genome-scale fleets where
silent corruption dominates):

1. **Numerical escapes** — NaN/+Inf/underflow inside the band tables or
   scores. The ``want_guard=`` reduction in ``ops.fused`` flags these
   per read ON DEVICE (one extra lane-wise reduction in the same
   launch); :func:`check_guard` decodes the fetched flags into a typed
   :class:`NumericalIntegrityError` naming the stage and read lane.
2. **Wrong-but-plausible results** — a bit-flipped fetch or a flaky
   chip returns finite numbers that are simply not the answer. Shadow
   verification re-scores a deterministic sample of completed results
   (:func:`selected_for_verify`) on the independent oracle path
   (``RIFRAF_TPU_FUSED_IMPL=split``, the 3-launch XLA-scan route) and
   compares within :func:`score_tolerance` — the same log10-space bound
   ``tests/test_precision.py`` gates kernels with. Disagreement raises
   :class:`ResultDivergenceError`.
3. **Suspect devices** — repeated trips from one chip. ``serve``'s
   DeviceScoreboard consumes these exceptions' ``device`` attribution.

All knobs default OFF: the f32 default path with integrity disabled is
bit-identical to the unguarded code (the guard section is absent from
``pack_layout``, not zero-filled).
"""

from __future__ import annotations

import contextlib
import hashlib
import os
import threading

import numpy as np

from ..ops.fused import (  # re-exported: the canonical bit definitions
    GUARD_NAN,
    GUARD_POSINF,
    GUARD_UNDERFLOW,
)

__all__ = [
    "GUARD_NAN",
    "GUARD_POSINF",
    "GUARD_UNDERFLOW",
    "IntegrityError",
    "NumericalIntegrityError",
    "ResultDivergenceError",
    "decode_guard",
    "check_guard",
    "check_finite",
    "selected_for_verify",
    "score_tolerance",
    "scores_diverge",
    "alternate_impl",
    "oracle_impl",
    "oracle_rescore",
    "verify_result",
]


class IntegrityError(RuntimeError):
    """Base for result-integrity failures. ``code`` is a stable
    machine-readable identifier (the convention of engine.validate and
    serve.errors); ``device`` (when known) attributes the failure to a
    chip for the quarantine scoreboard."""

    code = "integrity"

    def __init__(self, message: str, *, device=None, **context):
        super().__init__(message)
        self.device = device
        self.context = dict(context)


class NumericalIntegrityError(IntegrityError):
    """A guard reduction tripped: NaN/+Inf/sentinel-underflow in the
    band tables, scores, or dense total of one launch. ``stage`` names
    the launch ("adapt", "stage", "score", ...); ``lane`` is the first
    offending read lane (-1 = not lane-attributable, e.g. the dense
    total); ``flags`` is the decoded bit list."""

    code = "numerical_integrity"

    def __init__(self, stage: str, lane: int, flags, *, device=None,
                 **context):
        names = decode_guard(flags) if isinstance(flags, int) else flags
        where = f"read lane {lane}" if lane >= 0 else "dense total"
        super().__init__(
            f"numerical sentinel tripped at stage {stage!r} ({where}): "
            f"{'|'.join(names) or 'none'}",
            device=device, stage=stage, lane=lane, flags=list(names),
            **context,
        )
        self.stage = stage
        self.lane = lane
        self.flags = list(names)


class ResultDivergenceError(IntegrityError):
    """Shadow verification disagreed with the primary result beyond the
    precision-harness tolerance: the primary answer is not trustworthy.
    ``got``/``want`` are the primary/oracle scores; ``what`` names the
    request or cluster."""

    code = "result_divergence"

    def __init__(self, what: str, got, want, tol, *, device=None,
                 detail="", **context):
        msg = (
            f"shadow verification diverged for {what}: primary score "
            f"{got!r} vs oracle {want!r} (tol {tol:g})"
        )
        if detail:
            msg += f" — {detail}"
        super().__init__(
            msg, device=device, what=what, got=got, want=want, tol=tol,
            **context,
        )
        self.what = what
        self.got = got
        self.want = want
        self.tol = tol


_GUARD_NAMES = (
    (GUARD_NAN, "nan"),
    (GUARD_POSINF, "posinf"),
    (GUARD_UNDERFLOW, "underflow"),
)


def decode_guard(flags: int):
    """Bitmask -> tuple of human-readable flag names."""
    return tuple(name for bit, name in _GUARD_NAMES if int(flags) & bit)


def check_guard(guard, stage: str, *, device=None, lane_map=None):
    """Validate a fetched ``guard`` section (``pack_layout``'s trailing
    ``n_reads + 1`` words: per-read flags then the dense-total flag).
    Raises :class:`NumericalIntegrityError` on the first trip, naming
    the stage and offending lane. ``lane_map`` (optional sequence)
    translates a packed lane index back to a caller-side id (e.g. the
    request a segment lane belongs to) recorded in ``context``."""
    g = np.asarray(guard)
    # a corrupted flag word is itself a trip: treat non-finite as NaN-bit
    bad = ~np.isfinite(g)
    gi = np.where(bad, GUARD_NAN, np.nan_to_num(g)).astype(np.int64)
    hits = np.flatnonzero(gi)
    if hits.size == 0:
        return
    i = int(hits[0])
    lane = i if i < g.size - 1 else -1
    ctx = {}
    if lane >= 0 and lane_map is not None and lane < len(lane_map):
        ctx["owner"] = lane_map[lane]
    raise NumericalIntegrityError(
        stage, lane, int(gi[i]), device=device, n_tripped=int(hits.size),
        **ctx,
    )


def check_finite(values, stage: str, *, device=None, what="values"):
    """Host-side sentinel for values that already crossed the fence
    (fetched totals/scores): any NaN or +Inf raises
    :class:`NumericalIntegrityError`. -Inf is legal (the empty/padded
    score sentinel)."""
    v = np.asarray(values, np.float64).reshape(-1)
    bad = np.isnan(v) | np.isposinf(v)
    hits = np.flatnonzero(bad)
    if hits.size == 0:
        return
    i = int(hits[0])
    flags = GUARD_NAN if np.isnan(v[i]) else GUARD_POSINF
    raise NumericalIntegrityError(
        stage, i if v.size > 1 else -1, int(flags), device=device,
        what=what, n_tripped=int(hits.size),
    )


def selected_for_verify(digest: str, verify_fraction: float) -> bool:
    """Deterministic digest-keyed sampling: the SAME results are
    shadow-verified on every run/replica for a given fraction —
    reproducible from the journal alone, no RNG state. ``digest`` is
    any stable per-result key (serve request key, sweep content
    digest)."""
    if verify_fraction <= 0.0:
        return False
    if verify_fraction >= 1.0:
        return True
    h = hashlib.sha256(digest.encode("utf-8")).digest()
    # first 8 bytes -> uniform in [0, 1)
    u = int.from_bytes(h[:8], "big") / 2.0 ** 64
    return u < verify_fraction


def score_tolerance(score, band_dtype: str = "f32") -> float:
    """Absolute log10-space tolerance for primary-vs-oracle score
    comparison — the ``tests/test_precision.py`` bound. f32 paths gate
    at ``1e-6`` absolute (assert_close's default ``atol_log10=-6``);
    bf16 band stores carry ~|x|/256 absolute error per table value
    (8 mantissa bits), so the bound scales with the score magnitude
    exactly like the precision harness's bf16 legs."""
    if band_dtype == "bf16":
        mag = float(np.abs(score)) if np.isfinite(score) else 1.0
        return max(1e-3, mag / 256.0 * 4.0)
    return 1e-6


def alternate_impl() -> str:
    """The fused-step routing INDEPENDENT of the currently selected one:
    the 3-launch split/XLA-scan oracle normally, the megakernel when the
    session is already pinned to split. Either pair is bit-identical on
    healthy hardware (tests/test_fused_pallas.py), so any disagreement
    is the hardware/result, not the kernel."""
    from ..ops.fused_pallas import fused_impl

    return "mega" if fused_impl() == "split" else "split"


# select_impl reads RIFRAF_TPU_FUSED_IMPL from the environment on every
# call (not frozen into the trace cache), so pinning the env var around
# a rifraf() call routes that call — and only that call — through the
# oracle path. The lock serializes concurrent shadow verifications
# (fleet worker threads) against each other's env mutation.
_ORACLE_LOCK = threading.RLock()


@contextlib.contextmanager
def oracle_impl(impl=None):
    """Pin the fused-step routing to the independent oracle path for the
    duration (thread-exclusive)."""
    impl = impl or alternate_impl()
    with _ORACLE_LOCK:
        old = os.environ.get("RIFRAF_TPU_FUSED_IMPL")
        os.environ["RIFRAF_TPU_FUSED_IMPL"] = impl
        try:
            yield impl
        finally:
            if old is None:
                os.environ.pop("RIFRAF_TPU_FUSED_IMPL", None)
            else:
                os.environ["RIFRAF_TPU_FUSED_IMPL"] = old


def oracle_rescore(cluster, *, max_iters: int = 100, min_dist: int = 15,
                   bandwidth_pvalue: float = 0.1,
                   do_alignment_proposals: bool = False,
                   band_dtype: str = "f32", band_growth: str = "double",
                   scores=None, bandwidth=None, device=None, impl=None,
                   input_enc: str = "f32"):
    """Recompute one cluster's consensus on the independent oracle path:
    the per-cluster device loop in the batched path's exact algorithmic
    configuration (the sweep-vs-driver equality contract,
    tests/test_sweep_sharded.py), routed through :func:`oracle_impl` and
    optionally pinned to a DIFFERENT device. Returns the RifrafResult."""
    import jax

    from .driver import rifraf
    from .params import RifrafParams

    # scores/bandwidth: rifraf() re-derives ReadScores from the raw
    # seq/error_log_p, so the oracle must use the SAME values the
    # cluster was encoded with (the fallback-path contract) or the
    # recomputation diverges for the wrong reason. None = the
    # RifrafParams defaults, matching sweep callers.
    extra = {}
    if scores is not None:
        extra["scores"] = scores
    if bandwidth is not None:
        extra["bandwidth"] = bandwidth
    params = RifrafParams(
        batch_size=0, batch_fixed=False,
        do_alignment_proposals=do_alignment_proposals,
        max_iters=max_iters, min_dist=min_dist,
        bandwidth_pvalue=bandwidth_pvalue, device_loop="on",
        band_dtype=band_dtype, band_growth=band_growth,
        input_enc=input_enc,
        **extra,
    )
    with oracle_impl(impl):
        ctx = (jax.default_device(device) if device is not None
               else contextlib.nullcontext())
        with ctx:
            return rifraf(
                [r.seq for r in cluster],
                error_log_ps=[r.error_log_p for r in cluster],
                params=params,
            )


def verify_result(cluster, got_consensus, got_score, *, what: str,
                  band_dtype: str = "f32", device=None, impl=None,
                  suspect_device=None, **oracle_params):
    """Shadow-verify one completed result: oracle-rescore the cluster
    and raise :class:`ResultDivergenceError` (attributed to
    ``suspect_device``, the device that PRODUCED the primary result) if
    the consensus differs or the score disagrees beyond the precision
    bound. Returns the oracle RifrafResult — the trustworthy answer the
    caller can substitute for the diverged one."""
    res = oracle_rescore(cluster, band_dtype=band_dtype, device=device,
                         impl=impl, **oracle_params)
    want_score = float(res.state.score)
    diverged, tol = scores_diverge(got_score, want_score, band_dtype)
    same_cons = np.array_equal(
        np.asarray(got_consensus), np.asarray(res.consensus)
    )
    if diverged or not same_cons:
        raise ResultDivergenceError(
            what, float(got_score), want_score, tol,
            device=suspect_device,
            detail="consensus mismatch" if not same_cons else "",
        )
    return res


def scores_diverge(got, want, band_dtype: str = "f32"):
    """True + tolerance if two log10 total scores disagree beyond the
    precision-harness bound (finiteness mismatch always diverges)."""
    tol = score_tolerance(want, band_dtype)
    g, w = float(got), float(want)
    gf, wf = np.isfinite(g), np.isfinite(w)
    if gf != wf:
        return True, tol
    if not wf:  # both ±inf: diverge unless identical sign
        return (g != w), tol
    return (abs(g - w) > tol), tol
