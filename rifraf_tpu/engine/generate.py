"""Proposal generation: which consensus edits to consider.

Mirrors /root/reference/src/model.jl:401-562. All positions here are the
0-based coordinates of engine.proposals; seed neighborhoods are computed in
the reference's shared anchor coordinate so the clamping matches exactly.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Set

import numpy as np

from ..models.sequences import ReadScores
from ..ops import align_np
from ..utils.constants import CODON_LENGTH
from .proposals import (
    Deletion,
    Insertion,
    Proposal,
    Substitution,
    anchor,
)


def all_proposals(
    stage,
    consensus: np.ndarray,
    indel_correction_only: bool,
    indel_seeds: Sequence[Proposal] = (),
    seed_neighborhood: int = CODON_LENGTH,
) -> List[Proposal]:
    """Every allowed edit at every position, optionally restricted to the
    neighborhoods of seed indels (model.jl:401-456)."""
    from .params import Stage

    length = len(consensus)
    # seed neighborhoods, in anchor coordinates (model.jl:412-422)
    ins_anchors: Set[int] = set()
    del_anchors: Set[int] = set()
    for p in indel_seeds:
        a = anchor(p)
        if isinstance(p, Insertion):
            for idx in range(max(a - seed_neighborhood, 0), min(a + seed_neighborhood, length) + 1):
                ins_anchors.add(idx)
        else:
            for idx in range(max(a - seed_neighborhood, 1), min(a + seed_neighborhood, length) + 1):
                del_anchors.add(idx)

    do_subs = stage != Stage.FRAME or not indel_correction_only
    do_indels = stage in (Stage.INIT, Stage.FRAME, Stage.SCORE)
    no_seeds = len(indel_seeds) == 0
    results: List[Proposal] = []
    if do_indels:
        for base in range(4):
            results.append(Insertion(0, base))
    for j in range(length):
        if do_subs:
            for base in range(4):
                if consensus[j] != base:
                    results.append(Substitution(j, base))
        if do_indels:
            # anchors: deletion of consensus[j] has anchor j+1; insertion
            # after consensus[j] has anchor j+1
            if no_seeds or (j + 1) in del_anchors:
                results.append(Deletion(j))
            if no_seeds or (j + 1) in ins_anchors:
                for base in range(4):
                    results.append(Insertion(j + 1, base))
    return results


def moves_to_proposals(
    moves: Sequence[int], consensus: np.ndarray, seq: np.ndarray
) -> List[Proposal]:
    """Edits implied by one read-vs-consensus traceback (model.jl:458-480)."""
    proposals: List[Proposal] = []
    i = j = 0
    for move in moves:
        di, dj = align_np.OFFSETS[move]
        i += di
        j += dj
        if move == align_np.TRACE_MATCH:
            if seq[i - 1] != consensus[j - 1]:
                proposals.append(Substitution(j - 1, int(seq[i - 1])))
        elif move == align_np.TRACE_INSERT:
            proposals.append(Insertion(j, int(seq[i - 1])))
        elif move == align_np.TRACE_DELETE:
            proposals.append(Deletion(j - 1))
    return proposals


def alignment_proposals(
    tracebacks: Sequence[Sequence[int]],
    consensus: np.ndarray,
    seqs: Sequence[np.ndarray],
    do_indels: bool,
) -> List[Proposal]:
    """Proposals that appear in at least one read alignment
    (model.jl:483-497)."""
    result: Set[Proposal] = set()
    for moves, seq in zip(tracebacks, seqs):
        for proposal in moves_to_proposals(moves, consensus, seq):
            if do_indels or isinstance(proposal, Substitution):
                result.add(proposal)
    return list(result)


def proposals_from_edits(
    edits: np.ndarray, tlen: int, do_indels: bool
) -> List[Proposal]:
    """alignment_proposals (model.jl:483-497) from the device-computed
    union edit-indicator table (ops.align_jax._traceback_stats_one):
    rows = template positions, columns 0-3 substitution bases, 4-7
    insertion bases, 8 deletion. Yields the same SET as the host traceback
    walk — the reference materializes it via a Set, so the set order was
    never part of the contract — without ever fetching the move bands.

    Emission ORDER deliberately matches all_proposals (and the device
    loop's flat candidate layout, engine.device_loop._candidate_scores):
    choose_candidates breaks score ties by emission order, so host and
    device runs stay bit-identical under the edits gate."""
    results: List[Proposal] = []
    if do_indels:
        for b in np.nonzero(edits[0, 4:8])[0]:
            results.append(Insertion(0, int(b)))
    for j in range(tlen):
        for b in np.nonzero(edits[j, 0:4])[0]:
            results.append(Substitution(j, int(b)))
        if do_indels:
            if edits[j, 8]:
                results.append(Deletion(j))
            for b in np.nonzero(edits[j + 1, 4:8])[0]:
                results.append(Insertion(j + 1, int(b)))
    return results


def _align_moves_routed(consensus: np.ndarray, reference: ReadScores,
                        skew_matches: bool = False):
    """align_moves via the numpy engine for short pairs, the jitted codon
    engine (ops.align_codon_jax, exact-equal by its oracle tests) for
    long ones — the host column loop costs ~seconds per call at multi-kb
    references."""
    from ..ops.align_codon_jax import DEVICE_THRESHOLD, align_moves_device

    if min(len(consensus), len(reference)) >= DEVICE_THRESHOLD:
        return align_moves_device(consensus, reference,
                                  skew_matches=skew_matches)
    return align_np.align_moves(consensus, reference,
                                skew_matches=skew_matches)


def has_single_indels(consensus: np.ndarray, reference: ReadScores) -> bool:
    """model.jl:532-536."""
    moves = _align_moves_routed(consensus, reference)
    return align_np.TRACE_INSERT in moves or align_np.TRACE_DELETE in moves


def single_indel_proposals(
    consensus: np.ndarray, reference: ReadScores
) -> List[Proposal]:
    """Single (non-codon) indels from the consensus-vs-reference alignment,
    used as frame-correction seeds (model.jl:538-562)."""
    moves = _align_moves_routed(consensus, reference, skew_matches=True)
    results: List[Proposal] = []
    cons_idx = 0
    ref_idx = 0
    for move in moves:
        if move == align_np.TRACE_MATCH:
            cons_idx += 1
            ref_idx += 1
        elif move == align_np.TRACE_INSERT:
            ref_idx += 1
            results.append(Insertion(cons_idx, int(reference.seq[ref_idx - 1])))
        elif move == align_np.TRACE_DELETE:
            cons_idx += 1
            results.append(Deletion(cons_idx - 1))
        elif move == align_np.TRACE_CODON_INSERT:
            ref_idx += 3
        elif move == align_np.TRACE_CODON_DELETE:
            cons_idx += 3
    return results
