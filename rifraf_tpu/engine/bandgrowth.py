"""Per-read bandwidth growth policies — the ONE copy of the decision
both adaptation loops run (engine.realign._maybe_grow_bandwidth on flat
[N] arrays, parallel.sweep_sharded.ChunkExecutor on [G, N] cluster
matrices; pure elementwise numpy, so both shapes ride the same code).

Two policies:

- ``"double"`` (default): the reference port — every flagged read's
  bandwidth doubles, capped at ``entry_bw << MAX_BANDWIDTH_DOUBLINGS``
  (and the read/template lengths). Bit-identical to the historical
  per-read loop.
- ``"adaptive"`` (WFA-style, PAPERS.md "High-throughput Pairwise
  Alignment with the Wavefront Algorithm"): growth is driven by WHERE
  the score frontier hits the band wall. ``edge_hits`` counts the
  optimal path's cells pinned to a band-limit row (ops.align_jax
  ``want_edge`` / the stats kernels' acc row 2); a read whose path
  never touches the wall is NOT band-limited — more band cannot change
  its alignment — so a flagged read with zero hits fixes immediately
  instead of doubling. A wall-riding read grows by the measured
  deficit: about half the pinned run (each extra diagonal of band
  absorbs two pinned cells of slack), rounded UP to the 8-row K grid
  the band frames bucket on, and never more than the blunt policy's
  x2. Well-behaved reads keep small bandwidths, so heterogeneous-K
  re-bucketing (plan_sweep) can ride K for bandwidth 9-16 instead of
  the worst read's band.

A read is FLAGGED for growth exactly as the reference decides it
(model.jl:716): its traceback error count exceeds the Poisson
threshold, is still improving (dropped since the previous round), and
its bandwidth has room under the cap. Everything else fixes.
"""

from __future__ import annotations

import numpy as np

# growth cap: entry bandwidth << 5, the reference's limit (realign.py
# and sweep_sharded.py import their module-level copies from here)
MAX_BANDWIDTH_DOUBLINGS = 5

# adaptive mode enters the loop at min(entry, ADAPTIVE_ENTRY_BW): the
# whole point is that most reads never needed the caller's default band
# (the driver's 10% of read length), and the policy grows the few that
# did — entry rides the smallest useful K bucket instead
ADAPTIVE_ENTRY_BW = 16

BAND_GROWTH_POLICIES = ("double", "adaptive")


def check_band_growth(band_growth: str) -> str:
    if band_growth not in BAND_GROWTH_POLICIES:
        raise ValueError(
            f"band_growth must be one of {BAND_GROWTH_POLICIES}, "
            f"got {band_growth!r}"
        )
    return band_growth


def adaptive_entry(bandwidths):
    """Entry bandwidths for the adaptive policy: the caller's request
    capped at ADAPTIVE_ENTRY_BW (element-wise; never raises a smaller
    request)."""
    return np.minimum(np.asarray(bandwidths), ADAPTIVE_ENTRY_BW).astype(
        np.asarray(bandwidths).dtype
    )


def _bucket8(x):
    """Round up to the 8-row sublane grid the band heights bucket on."""
    return ((x + 7) // 8) * 8


def grow_bandwidths(
    bandwidths,  # int array, current per-read bandwidths (any shape)
    fixed,  # bool array, reads already settled
    old_errors,  # int array, previous round's error counts
    n_errors,  # int array, this round's traceback error counts
    thresholds,  # Poisson flag thresholds (same shape or broadcastable)
    entry_bw,  # int array, the ORIGINAL entry bandwidths (pre-lowering)
    tlen,  # template lengths (broadcastable)
    slen,  # read lengths (broadcastable)
    band_growth: str = "double",
    edge_hits=None,  # int array, band-edge hit counts (adaptive only)
):
    """One adaptation round's growth decision, vectorized.

    Returns ``(new_bandwidths, new_fixed, new_old_errors)`` — fresh
    arrays, inputs untouched. The growth cap is always
    ``min(entry_bw << MAX_BANDWIDTH_DOUBLINGS, tlen, slen)`` with the
    ORIGINAL entry bandwidths, so adaptive's lowered entry never lowers
    the ceiling below the blunt policy's."""
    bandwidths = np.asarray(bandwidths)
    fixed = np.asarray(fixed, bool)
    old_errors = np.asarray(old_errors)
    n_errors = np.asarray(n_errors)

    max_bw = np.minimum(
        np.minimum(
            np.asarray(entry_bw).astype(np.int64) << MAX_BANDWIDTH_DOUBLINGS,
            tlen,
        ),
        slen,
    )
    flagged = (
        (~fixed)
        & (n_errors > thresholds)
        & (n_errors < old_errors)
        & (bandwidths < max_bw)
    )

    if band_growth == "double":
        grow = flagged
        growth = bandwidths  # x2
    elif band_growth == "adaptive":
        if edge_hits is None:
            raise ValueError("adaptive growth requires edge_hits")
        edge_hits = np.asarray(edge_hits)
        # flagged reads whose path never rode the wall are error-bound,
        # not band-bound: growing them re-runs the same alignment
        grow = flagged & (edge_hits > 0)
        deficit = _bucket8(np.maximum((edge_hits + 1) // 2, 1))
        growth = np.minimum(bandwidths, deficit)  # never beyond x2
    else:
        check_band_growth(band_growth)

    new_bw = np.where(
        grow, np.minimum(bandwidths + growth, max_bw), bandwidths
    ).astype(bandwidths.dtype)
    new_fixed = fixed | ~grow
    new_old = np.where(grow, n_errors, old_errors).astype(old_errors.dtype)
    return new_bw, new_fixed, new_old
