"""Typed input validation at every API boundary.

Malformed input used to surface as an opaque shape error deep inside
jit (or as a plain ``ValueError`` with no machine-readable identity).
This module is the single validation pass the public entry points run
BEFORE any device dispatch: ``rifraf()``, ``sweep_clusters_sharded``,
serving admission (``ConsensusServer.submit`` / ``encode_cluster``),
and both CLI parsers all funnel raw clusters through
``validate_cluster``.

Every failure raises an ``InvalidInputError`` subclass. The hierarchy
derives from ``ValueError`` (existing callers that catch ValueError
keep working) and mirrors the serving errors' contract: a stable
machine-readable ``code`` plus a ``context`` dict naming the offending
record (read index, read name, source file/line when known) — the same
``(code, context)`` pair the streaming front door (``io.stream``)
writes to quarantine sidecars.

Codes:

- ``empty_cluster``    — a cluster with no reads;
- ``zero_length_read`` — a read with no bases;
- ``length_mismatch``  — seq and quality lengths differ;
- ``phred_range``      — a phred outside [0, MAX_PHRED] or non-finite;
- ``bad_alphabet``     — a base outside ACGT (N and other ambiguity
  codes included: the engine's int8 encoding has no code for them);
- ``malformed_record`` — a record that does not parse at all
  (truncated FASTQ block, bad header, invalid JSON, missing fields);
- ``truncated``        — an input cut off mid-record (EOF inside a
  FASTQ block or a gzip stream that ends early).
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

# FASTQ offset-33 printable range '!'..'~' (Q0..Q93) — phreds outside
# it cannot round-trip through quality strings and signal corrupt
# input. The bounds are shared with utils.phred so the conversion and
# validation layers can never disagree about what a legal score is.
from ..utils.phred import MAX_PHRED, MIN_PHRED

_VALID_BASES = frozenset(b"ACGTacgt")


class InvalidInputError(ValueError):
    """Malformed input caught before device dispatch. Carries a stable
    machine-readable ``code`` and a ``context`` dict naming the record
    (the quarantine-sidecar / serving-response contract)."""

    code = "invalid_input"

    def __init__(self, message: str, **context):
        super().__init__(message)
        self.context = {k: v for k, v in context.items() if v is not None}


class EmptyClusterInputError(InvalidInputError):
    code = "empty_cluster"


class EmptyReadError(InvalidInputError):
    code = "zero_length_read"


class LengthMismatchError(InvalidInputError):
    code = "length_mismatch"


class PhredRangeError(InvalidInputError):
    code = "phred_range"


class AlphabetError(InvalidInputError):
    code = "bad_alphabet"


class MalformedRecordError(InvalidInputError):
    code = "malformed_record"


class TruncatedInputError(InvalidInputError):
    code = "truncated"


def _where(name: Optional[str], index: Optional[int],
           source: Optional[str]) -> str:
    parts = []
    if name:
        parts.append(f"read {name!r}")
    elif index is not None:
        parts.append(f"read {index}")
    if source:
        parts.append(f"in {source}")
    return (" (" + " ".join(parts) + ")") if parts else ""


def validate_seq(seq, *, name: Optional[str] = None,
                 index: Optional[int] = None,
                 source: Optional[str] = None) -> None:
    """One sequence — a DNA string or an int8 code array. Zero-length
    reads and non-ACGT bytes raise typed errors with record context."""
    ctx = dict(name=name, index=index, source=source)
    if isinstance(seq, (str, bytes)):
        if len(seq) == 0:
            raise EmptyReadError(
                f"zero-length read{_where(name, index, source)}", **ctx)
        raw = seq.encode("ascii", "replace") if isinstance(seq, str) \
            else seq
        bad = [c for c in raw if c not in _VALID_BASES]
        if bad:
            ch = chr(bad[0])
            raise AlphabetError(
                f"invalid DNA character {ch!r}"
                f"{_where(name, index, source)} (ACGT only; ambiguity "
                "codes like 'N' have no engine encoding)",
                base=ch, **ctx)
        return
    arr = np.asarray(seq)
    if arr.size == 0:
        raise EmptyReadError(
            f"zero-length read{_where(name, index, source)}", **ctx)
    if arr.min() < 0 or arr.max() > 3:
        raise AlphabetError(
            f"invalid base code {int(arr.min() if arr.min() < 0 else arr.max())}"
            f"{_where(name, index, source)} (int8 codes must be in "
            "[0, 3])", **ctx)


def validate_phreds(phred, seq_len: Optional[int] = None, *,
                    name: Optional[str] = None,
                    index: Optional[int] = None,
                    source: Optional[str] = None) -> None:
    """One read's phred vector: numeric, finite, within
    [0, MAX_PHRED], and matching the read length when given."""
    ctx = dict(name=name, index=index, source=source)
    try:
        arr = np.asarray(phred, dtype=float)
    except (TypeError, ValueError) as e:
        raise PhredRangeError(
            f"non-numeric phred values{_where(name, index, source)}: {e}",
            **ctx) from None
    if seq_len is not None and arr.size != seq_len:
        raise LengthMismatchError(
            f"quality length {arr.size} != sequence length {seq_len}"
            f"{_where(name, index, source)}",
            qual_len=int(arr.size), seq_len=int(seq_len), **ctx)
    if arr.size == 0:
        return
    if not np.isfinite(arr).all():
        raise PhredRangeError(
            f"non-finite phred value{_where(name, index, source)}", **ctx)
    lo, hi = float(arr.min()), float(arr.max())
    if lo < MIN_PHRED:  # MIN_PHRED = 0: Q0 ('!') is legal FASTQ
        raise PhredRangeError(
            f"phred score cannot be negative (got {lo:g})"
            f"{_where(name, index, source)}", value=lo, **ctx)
    if hi > MAX_PHRED:
        raise PhredRangeError(
            f"phred score {hi:g} exceeds {MAX_PHRED}"
            f"{_where(name, index, source)}", value=hi, **ctx)


def validate_cluster(seqs: Sequence,
                     phreds: Optional[Sequence] = None,
                     error_log_ps: Optional[Sequence] = None,
                     *, source: Optional[str] = None,
                     names: Optional[Sequence[str]] = None) -> None:
    """One cluster of reads + qualities — the unit of ``rifraf()``, one
    serving request, and one sweep cluster. Raises an
    ``InvalidInputError`` subclass on the first offending record."""
    if seqs is None or len(seqs) == 0:
        raise EmptyClusterInputError(
            "cluster carries no reads" + (f" (in {source})" if source
                                          else ""), source=source)
    quals = phreds if phreds is not None else error_log_ps
    if quals is not None and len(quals) != len(seqs):
        raise LengthMismatchError(
            f"{len(seqs)} sequences but {len(quals)} quality vectors"
            + (f" (in {source})" if source else ""),
            n_seqs=len(seqs), n_quals=len(quals), source=source)
    for i, seq in enumerate(seqs):
        name = names[i] if names is not None and i < len(names) else None
        validate_seq(seq, name=name, index=i, source=source)
        if phreds is not None:
            validate_phreds(phreds[i], len(seqs[i]), name=name, index=i,
                            source=source)
        elif error_log_ps is not None:
            lp = np.asarray(error_log_ps[i], dtype=float)
            if lp.size != len(seq):
                raise LengthMismatchError(
                    f"error_log_p length {lp.size} != sequence length "
                    f"{len(seq)}{_where(name, i, source)}",
                    qual_len=int(lp.size), seq_len=len(seq),
                    name=name, index=i, source=source)


def validate_encoded_cluster(cluster, *,
                             source: Optional[str] = None) -> None:
    """A cluster of ready-made ``ReadScores`` at the serving admission
    boundary: non-empty, and no zero-length members (a zero-length read
    would reach the band geometry as a degenerate shape)."""
    if not cluster:
        raise EmptyClusterInputError(
            "cluster carries no reads", source=source)
    for i, r in enumerate(cluster):
        if len(r) == 0:
            raise EmptyReadError(
                f"zero-length read{_where(None, i, source)}",
                index=i, source=source)
