"""Host (numpy) proposal scoring: the O(bandwidth) rescoring trick.

Mirrors /root/reference/src/model.jl:227-399. Given cached forward (A) and
backward (B) banded matrices for a read-vs-consensus alignment, scores a
single-base edit of the consensus without redoing the full alignment:

- Deletion: join column `pos` of A with column `pos+1` of B via the max-plus
  inner product (seq_score_deletion, model.jl:227-236).
- Substitution/Insertion: recompute one new column after the last valid A
  column, then join with the appropriate B column (score_nocodon,
  model.jl:242-285).
- With codon moves enabled (the consensus-vs-reference path), recompute
  CODON_LENGTH+1 columns and take the best join over 3 B columns
  (model.jl:302-383).

This is the exactness oracle for the batched device scorer
(rifraf_tpu.ops.proposal_jax) and the production path for the single
reference sequence.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..models.sequences import ReadScores
from ..ops.align_np import update
from ..ops.banded_array import BandedArray, equal_ranges
from ..utils.constants import CODON_LENGTH, GAP_INT
from .proposals import Deletion, Insertion, Proposal, Substitution


def summax_ranges(acol, a_range, bcol, b_range) -> float:
    """Max-plus inner product of two sub-columns over their common rows
    (model.jl:229-236, util.jl:40-48)."""
    (amin, amax), (bmin, bmax) = equal_ranges(a_range, b_range)
    asub = acol[amin:amax]
    bsub = bcol[bmin:bmax]
    if len(asub) == 0:
        return -np.inf
    return float(np.max(asub + bsub))


def seq_score_deletion(A: BandedArray, B: BandedArray, acol: int, bcol: int) -> float:
    return summax_ranges(
        A.sparsecol(acol), A.row_range(acol), B.sparsecol(bcol), B.row_range(bcol)
    )


# first B column to join, relative to acol (model.jl:238-240)
BOFFSETS = {Substitution: 2, Insertion: 1, Deletion: 2}


def _new_column(
    A: BandedArray,
    pseq: ReadScores,
    newcols: np.ndarray,
    acol: int,
    col_idx: int,
    logical_col: int,
    t_base: int,
) -> None:
    """Fill newcols[:, col_idx] = logical column `logical_col` of the edited
    alignment, reading columns <= acol from A and later ones from newcols
    (model.jl:264-273, 345-355)."""
    ncols = A.ncols
    amin, amax = A.row_range(min(logical_col, ncols - 1))
    for i in range(amin, amax + 1):
        seq_base = pseq.seq[i - 1] if i > 0 else GAP_INT
        score, _ = update(
            A, i, logical_col, seq_base, t_base, pseq, newcols=newcols, acol=acol
        )
        newcols[i, col_idx] = score


def score_nocodon(
    proposal: Proposal,
    A: BandedArray,
    B: BandedArray,
    pseq: ReadScores,
    newcols: Optional[np.ndarray] = None,
) -> float:
    """model.jl:242-285 (0-based columns; see engine.proposals for the
    coordinate mapping)."""
    if A.nrows != len(pseq) + 1:
        raise ValueError("wrong size array")
    if isinstance(proposal, Deletion):
        return seq_score_deletion(A, B, proposal.pos, proposal.pos + 1)
    if newcols is None:
        newcols = np.full((A.nrows, CODON_LENGTH + 1), -np.inf)
    nrows, ncols = A.shape
    acol = proposal.pos
    new_acol = acol + 1
    _new_column(A, pseq, newcols, acol, 0, new_acol, proposal.base)

    imin, imax = A.row_range(min(new_acol, ncols - 1))
    acol_vals = newcols[imin : imax + 1, 0]
    bj = proposal.pos + 1 if isinstance(proposal, Substitution) else proposal.pos
    score = summax_ranges(acol_vals, (imin, imax), B.sparsecol(bj), B.row_range(bj))
    if score == -np.inf:
        raise RuntimeError("failed to compute a valid score")
    return score


def score_proposal(
    proposal: Proposal,
    A: BandedArray,
    B: BandedArray,
    consensus: np.ndarray,
    pseq: ReadScores,
    newcols: Optional[np.ndarray] = None,
) -> float:
    """Score a proposal against one read using cached A/B (model.jl:302-383).

    Exactness invariant (tested): equals the full realignment score of the
    edited consensus (test_model.jl:39-153).
    """
    if not pseq.do_codon_moves:
        return score_nocodon(proposal, A, B, pseq, newcols)

    nrows, ncols = A.shape
    # last valid column of A: 0-based col index == number of consensus
    # prefix bases unaffected by the edit
    acol = proposal.pos  # same for all three types (see scoring notes)
    # first/last B columns to join (model.jl:310-314), 0-based
    first_bcol = acol + BOFFSETS[type(proposal)]
    last_bcol = first_bcol + CODON_LENGTH - 1

    if isinstance(proposal, Deletion) and acol == ncols - 2:
        # suffix deletion needs no recomputation (model.jl:316-319)
        return float(A[nrows - 1, ncols - 2])

    just_a = last_bcol >= ncols - 1
    n_after = CODON_LENGTH if not just_a else len(consensus) - proposal.pos - (
        0 if isinstance(proposal, Insertion) else 1
    )
    n_new_bases = 0 if isinstance(proposal, Deletion) else 1
    if n_new_bases == 0 and n_after == 0:
        raise RuntimeError("no new columns need to be recomputed")
    n_new = n_new_bases + n_after

    # consensus bases for the recomputed columns (model.jl:287-300)
    prefix = (
        [proposal.base]
        if isinstance(proposal, (Substitution, Insertion))
        else []
    )
    next_pos = proposal.pos + (0 if isinstance(proposal, Insertion) else 1)
    suffix = list(consensus[next_pos : next_pos + n_after])
    sub_consensus = prefix + suffix

    if newcols is None or newcols.shape[1] < n_new:
        newcols = np.full((nrows, max(n_new, CODON_LENGTH + 1)), -np.inf)
    for j in range(n_new):
        _new_column(A, pseq, newcols, acol, j, acol + j + 1, sub_consensus[j])

    if just_a:
        return float(newcols[nrows - 1, n_new - 1])

    best = -np.inf
    for j in range(CODON_LENGTH):
        new_j = n_new - CODON_LENGTH + j
        imin, imax = A.row_range(min(acol + new_j + 1, ncols - 1))
        acol_vals = newcols[imin : imax + 1, new_j]
        bj = first_bcol + j
        score = summax_ranges(
            acol_vals, (imin, imax), B.sparsecol(bj), B.row_range(bj)
        )
        best = max(best, score)
    if best == -np.inf:
        raise RuntimeError("failed to compute a valid score")
    return best
