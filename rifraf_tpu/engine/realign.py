"""Batched realignment engine: device-side A/B/move bands for a read batch.

This replaces the reference's per-read host loops (model.jl:643-714) with
ONE fused device dispatch per iteration (ops.fused.fused_step_full:
forward fill + backward fill + dense all-edits score tables + weighted
totals), plus host logic for adaptive bandwidth (model.jl:643-672).
Proposal scoring reads out of the cached dense tables — no further device
launches — and the per-read scores / total stay on device until a float
is actually needed. All shapes are bucketed so the hill-climbing loop —
whose consensus length, bandwidths, and batch size all change — re-uses
cached XLA executables instead of recompiling:

- template length padded up to `len_bucket` multiples (dynamic true length);
- band-buffer height K padded to the next multiple of 8;
- read count and read length fixed per batch selection.

Bandwidth doubling mutates per-read dynamic scalars only; K grows (and
recompiles, once per bucket) only when a band no longer fits.
"""

from __future__ import annotations

import functools
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..models.sequences import ReadBatch, ReadScores, batch_reads
from ..ops import align_jax, align_np
from ..ops.banded_array import BandedArray
from ..ops.proposal_jax import score_proposals_batch
from ..utils.debug import myassert
from ..utils.mathops import poisson_cquantile
from ..utils.shapes import bucket as _bucket
from ..utils.timers import Timers
from .bandgrowth import (
    MAX_BANDWIDTH_DOUBLINGS,  # noqa: F401  (re-exported; model.jl:650 cap)
    adaptive_entry,
    check_band_growth,
    grow_bandwidths,
)
from .params import resolve_dtype, validate_backend
from .proposals import Proposal
from .scoring_np import score_proposal as score_proposal_np


def _pallas_interpret() -> bool:
    """Test hook: RIFRAF_TPU_PALLAS_INTERPRET=1 makes the Pallas policy
    accept non-TPU backends and runs every kernel in interpret mode, so
    the whole Pallas realign path (incl. adaptation and stats) can be
    driven end-to-end by the CPU suite."""
    import os

    return bool(os.environ.get("RIFRAF_TPU_PALLAS_INTERPRET"))

_BYTES_PER_CELL = 22  # A+B f32, moves int8, ~2 transient copies


def _band_itemsize(band_dtype: str) -> int:
    """HBM bytes per band cell for the store dtype (params.band_dtype)."""
    return 2 if band_dtype == "bf16" else 4


def _bw_hist(bandwidths) -> tuple:
    """Compact per-read bandwidth histogram ((bw, count), ...) for the
    roofline registry and BENCH lines — the adaptive policy's win shows
    up here as mass staying on small bandwidths."""
    vals, counts = np.unique(np.asarray(bandwidths), return_counts=True)
    return tuple((int(v), int(c)) for v, c in zip(vals, counts))


def _dense_cols(T1p: int, K: int, Npad: int = 0,
                want_stats: bool = False, impl: str = "split",
                n_live: int = 0, band_dtype: str = "f32",
                bw_hist=None, input_enc: str = "f32") -> int:
    """Column block for the fused/dense Pallas dispatches via the shared
    VMEM planner (utils.shapes.plan_cols), recording the block plan and
    modelled HBM traffic so bench/diagnostics can report roofline
    utilization per dispatch. ``impl`` is the routing decision from
    ops.fused_pallas.select_impl: the megakernel plans under
    kernel="fused" and its single-launch byte model (band bytes counted
    once). ``n_live`` (real reads in the batch, vs the Npad lane
    padding) adds the dispatch's lane occupancy to the record — a
    5-read reference-default batch fills 5/128 of the lane axis, and
    every modelled byte is spent on the padded shape. Interpret mode
    (CPU tests) pins C=8 to keep the traced kernel body bounded."""
    from ..utils import roofline
    from ..utils.shapes import plan_cols

    plan = plan_cols(T1p, K, kernel="fused" if impl == "mega" else "dense",
                     want_moves=impl == "mega" and want_stats)
    C = 8 if _pallas_interpret() else plan.cols
    if Npad:
        isz = _band_itemsize(band_dtype)
        if impl == "mega":
            model = roofline.fused_mega_model(T1p, K, Npad, C,
                                              want_stats=want_stats,
                                              band_itemsize=isz,
                                              input_enc=input_enc)
        else:
            model = roofline.fused_model(T1p, K, Npad, C,
                                         want_stats=want_stats,
                                         band_itemsize=isz,
                                         input_enc=input_enc)
        roofline.record(
            "fused_step", T1p=T1p, K=K, Npad=Npad, C=C, impl=impl,
            vmem_bytes=plan.vmem_bytes, model_bytes=model["bytes"],
            model_ops=model["ops"], want_stats=want_stats,
            lane_occupancy=(n_live / Npad) if n_live else None,
            band_dtype=band_dtype, bw_hist=bw_hist, input_enc=input_enc,
        )
    return C


def _fill_cols(T1p: int, K: int, Npad: int = 0, band_dtype: str = "f32",
               bw_hist=None, input_enc: str = "f32") -> int:
    """Column block for the forward-only fill+stats dispatch (adapt
    rounds): the fill plan must also hold the int32 move block in VMEM
    (want_moves=True)."""
    from ..utils import roofline
    from ..utils.shapes import plan_cols

    plan = plan_cols(T1p, K, kernel="fill", want_moves=True)
    C = 8 if _pallas_interpret() else plan.cols
    if Npad:
        f = roofline.fill_model(T1p, K, Npad, C, n_streams=1,
                                want_moves=True, moves_lanes=Npad,
                                band_itemsize=_band_itemsize(band_dtype),
                                input_enc=input_enc)
        s = roofline.stats_model(T1p, K, Npad, C, input_enc=input_enc)
        roofline.record(
            "fill_stats", T1p=T1p, K=K, Npad=Npad, C=C,
            vmem_bytes=plan.vmem_bytes,
            model_bytes=f["bytes"] + s["bytes"],
            model_ops=f["ops"] + s["ops"],
            band_dtype=band_dtype, bw_hist=bw_hist, input_enc=input_enc,
        )
    return C


def _default_hbm_budget() -> float:
    """HBM working-set budget for one fused step: band buffers (A, B,
    moves) plus XLA's transient copies scale with reads x K x T1; beyond
    this the read axis runs in sequential chunks (ops.fused read_chunk).

    Derived as 3/4 of the device's memory when the runtime reports it
    (so smaller chips chunk earlier), else 12e9 — verified on a 16 GB
    v5e at 10 kb x 512 x band 64, the largest BASELINE config: 2 chunks,
    no OOM, 28 s end to end vs 37 s at 8e9. Override with env
    RIFRAF_TPU_HBM_BUDGET (bytes)."""
    import os

    env = os.environ.get("RIFRAF_TPU_HBM_BUDGET")
    if env:
        budget = float(env)
        if budget < 1:
            raise ValueError(
                f"RIFRAF_TPU_HBM_BUDGET must be >= 1 byte, got {env!r}"
            )
        return budget
    try:
        import jax

        # prefer the thread-local default device (cluster-sweep workers
        # pin themselves with jax.default_device) over device 0; the
        # config value may also be a platform STRING ("tpu"), which has
        # no memory_stats — fall back to device 0 then
        dev = getattr(jax.config, "jax_default_device", None)
        if dev is None or not hasattr(dev, "memory_stats"):
            dev = jax.local_devices()[0]
        stats = dev.memory_stats()
        if stats and stats.get("bytes_limit"):
            return 0.75 * float(stats["bytes_limit"])
    except Exception:
        pass
    return 12e9


def _pick_read_chunk(n: int, K: int, T1: int, budget: float) -> int:
    """Chunk size whose fused working set fits the budget (ceil division
    over the fewest chunks — ops.fused pads the read axis to a multiple);
    0 = no chunking needed."""
    per_read = K * T1 * _BYTES_PER_CELL
    if n * per_read <= budget:
        return 0
    n_chunks = -(-(n * per_read) // int(budget))
    return max(1, -(-n // n_chunks))


class BatchAligner:
    """Cached batched alignments of the current read batch vs the consensus.

    Owns the padded ReadBatch, the device A/B/move bands, per-read
    bandwidth state, and host tracebacks. The driver mutates the batch
    membership and the consensus; this class keeps the device state in sync
    (the As/Bs/Amoves caches of RifrafState, model.jl:176-182).
    """

    def __init__(self, reads: Sequence[ReadScores], dtype=None,
                 len_bucket: int = 64, mesh=None, backend: str = "auto",
                 band_dtype: str = "f32", band_growth: str = "double",
                 input_enc: str = "f32"):
        """`mesh`: an optional jax.sharding.Mesh with a "reads" axis. When
        given, the read axis of every batch array is sharded across the
        mesh, per-read DP fills run on their home devices, and the
        proposal-score reduction over reads happens on device — XLA
        inserts the psum over ICI. One consensus then spans all chips
        (the BASELINE north star; replaces scripts/rifraf.jl:190-191's
        process parallelism with collectives).

        `band_dtype`/`band_growth`: the byte-wall levers (params.
        RifrafParams): HBM store dtype of the DP band tables and the
        bandwidth-adaptation policy (engine.bandgrowth).

        `input_enc`: streamed-input wire format of the Pallas kernels
        ("f32" exact default, "packed" = 2-bit bases + int8-quantized
        score planes, ops.encoding). Pallas-only: the XLA fallback and
        panel paths keep exact f32 inputs either way."""
        from ..ops.encoding import check_input_enc

        self.dtype = resolve_dtype(dtype)
        self.len_bucket = int(len_bucket)
        self.mesh = mesh
        self.backend = backend
        if band_dtype not in ("f32", "bf16"):
            raise ValueError(
                f"band_dtype must be 'f32' or 'bf16', got {band_dtype!r}"
            )
        check_band_growth(band_growth)
        check_input_enc(input_enc)
        if mesh is not None:
            # the shard_map wrappers and their psum epilogues compile
            # against the f32 band layout with uniform doubling; all
            # three levers are single-device (and sweep-fleet) features,
            # so a mesh silently rides the exact defaults
            band_dtype, band_growth, input_enc = "f32", "double", "f32"
        self.band_dtype = band_dtype
        self.band_growth = band_growth
        self.input_enc = input_enc
        # resolved per aligner, not as a process global: cluster-sweep
        # threads pinned to different (possibly heterogeneous) devices
        # must each chunk against their OWN device's HBM
        self.hbm_budget = _default_hbm_budget()
        validate_backend(backend, self.dtype, mesh)
        self.n_forward_fills = 0  # diagnostic: counts device forward launches
        self.timers = Timers()
        self.set_batch(list(reads))
        self.A_bands = None
        self.B_bands = None
        self.moves = None
        self.geom = None
        self.tracebacks: Optional[List[List[int]]] = None
        self.scores = None  # [N] per-read totals, device-resident

    # --- batch management -------------------------------------------------
    def set_batch(self, reads: List[ReadScores]) -> None:
        self.reads = reads
        max_len = _bucket(max(len(r) for r in reads), self.len_bucket)
        batch = batch_reads(reads, max_len=max_len, dtype=self.dtype)
        # mutable per-read bandwidth state (RifrafSequence.bandwidth /
        # bandwidth_fixed, rifrafsequences.jl:15-17)
        bandwidths = np.array([r.bandwidth for r in reads], dtype=np.int32)
        fixed = np.array([r.bandwidth_fixed for r in reads], dtype=bool)
        self.weights = None
        self._weights_dev = None
        self._bw_dev = None  # sharded bandwidth cache (mesh path)
        self._bw_dev_host = None  # host mirror of _bw_dev for staleness checks
        self._lengths_host = np.asarray(batch.lengths)
        if self.mesh is not None:
            from ..parallel.sharding import pad_batch_to, shard_batch, shard_read_axis

            n_dev = self.mesh.devices.size
            n = _bucket(len(reads), n_dev)
            batch, self.weights = pad_batch_to(batch, n)
            pad = n - len(reads)
            if pad:
                # padding duplicates the last read; freeze its bandwidth so
                # adaptation never touches it
                bandwidths = np.concatenate(
                    [bandwidths, np.repeat(bandwidths[-1:], pad)]
                )
                fixed = np.concatenate([fixed, np.ones(pad, dtype=bool)])
            self._lengths_host = np.asarray(batch.lengths)
            batch = shard_batch(batch, self.mesh)
            self._weights_dev = shard_read_axis(
                self.weights.astype(self.dtype), self.mesh
            )
        else:
            # device-resident once per batch selection: per-call
            # host->device transfers dominate the unfused step otherwise
            # (BASELINE.md round-2 measurements)
            import jax.numpy as jnp

            batch = ReadBatch(*[jnp.asarray(a) for a in batch])
        self.batch = batch
        self.bandwidths = bandwidths
        self.fixed = fixed
        self.est_n_errors = np.array([r.est_n_errors for r in reads])
        self.A_bands = None
        self.B_bands = None
        self._tables_host = None
        self._total = None
        self.edits_seen = None
        self._realign_key = None  # memo key of the last completed realign
        # Pallas-path state (built lazily; template-independent per
        # batch). Fill buffers key on the input encoding: one process
        # can interleave packed-encoded aligners with f32 ones (and the
        # panel path always needs the exact f32 buffers)
        self._fill_bufs = {}
        self._stage_runners = {}

    def _padded_template(self, consensus: np.ndarray) -> np.ndarray:
        T = _bucket(len(consensus) + 1, self.len_bucket)
        out = np.zeros(T, dtype=np.int8)
        out[: len(consensus)] = consensus
        return out

    def _K(self, tlen: int) -> int:
        # align_jax.band_height over HOST arrays: the batch's own
        # lengths live on device, and np.asarray on them costs a full
        # device->host round trip per call (profiled 0.33 s EACH at
        # 2048 reads on the tunneled TPU)
        bw = self.bandwidths.astype(np.int64)
        nd = 2 * bw + np.abs(self._lengths_host.astype(np.int64) - tlen) + 1
        return _bucket(int(nd.max()), 8)

    def _current_batch(self) -> ReadBatch:
        bw = self.bandwidths
        if self.mesh is not None:
            if self._bw_dev is None:
                from ..parallel.sharding import shard_read_axis

                self._bw_dev = shard_read_axis(bw, self.mesh)
                self._bw_dev_host = bw.copy()
            # a stale sharded copy here would refill the bands with OLD
            # bandwidths after growth doubled them (util.jl:7-15-style
            # DEBUG invariant, checked at the consumption point)
            myassert(np.array_equal(self._bw_dev_host, self.bandwidths),
                     "sharded bandwidth cache is stale")
            bw = self._bw_dev
        return self.batch._replace(bandwidth=bw)

    # --- Pallas fast path -------------------------------------------------
    def _pallas_K(self, tlen: int, margin: int = 0) -> int:
        """Uniform-frame band height for the current bandwidths (+margin
        template-length drift headroom), rounded to the store dtype's
        sublane tile: 8 for f32, 16 for bf16 (the TPU bf16 min tile is
        (16, 128) — an 8-row bf16 block would relayout on every store)."""
        bw = self.bandwidths.astype(np.int64)
        lengths = self._lengths_host.astype(np.int64)
        off = np.maximum(tlen - lengths, 0) + bw
        nd = 2 * bw + np.abs(lengths - tlen) + 1
        K = int((off.max() - off + nd).max()) + margin
        mult = 16 if self.band_dtype == "bf16" else 8
        return ((K + mult - 1) // mult) * mult

    def _pallas_mode(self, tlen: int):
        """Which Pallas path serves this problem: "single" (one fused
        launch), "panels" (the long-template carry-chained panel path,
        ops.dense_pallas.fused_tables_pallas_panels), or None (XLA).
        Raises when backend='pallas' was forced but nothing fits."""
        if self.backend == "xla":
            return None
        if self.dtype != np.float32:
            return None
        import jax

        if jax.default_backend() != "tpu" and not _pallas_interpret():
            return None
        forced = self.backend == "pallas"
        K_uni = self._pallas_K(tlen)
        K_xla = self._K(tlen)
        reason = None
        if K_uni > K_xla + 64:
            reason = (
                f"uniform-frame band height {K_uni} blows up vs {K_xla} "
                "(pathological read-length spread)"
            )
        else:
            # per-device working set: under a mesh each shard holds only
            # its local lanes (shard_map path)
            if self.mesh is not None:
                _, Npad, _ = self._mesh_npads()
            else:
                Npad = _bucket(self.batch.n_reads, 128)
            T1p = _bucket(_bucket(tlen + 1, self.len_bucket) + 1, 64)
            # single launch holds both streams' bands + the halo-blocked
            # backward copy + dense temporaries (~4 bands); keep 1/3 of
            # the budget as transient headroom — a barely-fitting single
            # launch OOMs on XLA's scratch copies. Band bytes scale with
            # the store dtype: bf16 halves them, widening the single-
            # launch range (panel mode below stays f32-internal, so its
            # bytes stay at 4)
            band_isz = _band_itemsize(self.band_dtype)
            if 4 * T1p * K_uni * Npad * band_isz <= 0.66 * self.hbm_budget:
                return "single"
            # long templates: panel mode keeps ONE full band (donated
            # in-place panel writes, no concat copy) + the int8 move
            # band + O(panel) temporaries
            band_bytes = T1p * K_uni * Npad * 4
            if self.mesh is None and 2.0 * band_bytes <= self.hbm_budget:
                return "panels"
            reason = "band working set exceeds the HBM budget"
        if forced:
            raise RuntimeError(f"backend='pallas' unavailable: {reason}")
        return None

    def pallas_eligible(self, tlen: int) -> bool:
        """Policy: the Pallas fill+dense engines serve every realign
        flavor on a real TPU — score-and-tables, traceback statistics
        (bandwidth adaptation, alignment-derived proposals; the kernel
        records moves and the stats scan consumes them in the uniform
        frame), SCORE-stage move fetches, sharded meshes (shard_map),
        and long templates (panel mode). The XLA scan engine keeps f64
        exactness runs, pathological read-length spreads, and working
        sets past even the panel path's budget."""
        return self._pallas_mode(tlen) is not None

    def _mesh_npads(self):
        """(Nlocal, Npad_local, Npad_total) of the per-shard lane layout."""
        n_dev = self.mesh.devices.size
        Nlocal = self.batch.n_reads // n_dev
        Npad_local = _bucket(Nlocal, 128)
        return Nlocal, Npad_local, n_dev * Npad_local

    def _mesh_read_slots(self, n: int) -> np.ndarray:
        """Packed-array slot of each of the first n reads under the
        per-shard lane padding (see mesh_fused_step_pallas)."""
        Nlocal, Npad_local, _ = self._mesh_npads()
        r = np.arange(n)
        return (r // Nlocal) * Npad_local + (r % Nlocal)

    def _ensure_fill_bufs(self, input_enc=None):
        """Lazily-built per-encoding fill buffers. ``input_enc`` defaults
        to the aligner's knob; the panel path pins "f32" explicitly (it
        never decodes in-kernel)."""
        enc = self.input_enc if input_enc is None else input_enc
        if enc not in self._fill_bufs:
            import jax

            import jax.numpy as jnp

            if self.mesh is not None:
                # mesh forces input_enc="f32" in __init__, so this cache
                # only ever holds the f32 sharded buffers
                from ..parallel.sharding import mesh_fill_buffers

                _, Npad_local, _ = self._mesh_npads()
                self._fill_bufs[enc] = jax.block_until_ready(
                    mesh_fill_buffers(self.mesh, self.batch, Npad_local)
                )
            else:
                from ..ops.fill_pallas import build_fill_buffers

                Npad = _bucket(self.batch.n_reads, 128)
                self._fill_bufs[enc] = jax.block_until_ready(
                    build_fill_buffers(
                        self.batch.seq, self.batch.match,
                        self.batch.mismatch, self.batch.ins,
                        self.batch.dels,
                        jnp.asarray(self._lengths_host), Npad,
                        input_enc=enc,
                    )
                )
        return self._fill_bufs[enc]

    def _uniform_geom_host(self, tlen: int):
        """Host-side uniform-frame geometry (fill_pallas.uniform_geometry
        semantics) for the SCORE-stage traceback walk."""
        from ..ops.align_jax import BandGeometry

        lengths = self._lengths_host.astype(np.int64)
        bw = self.bandwidths.astype(np.int64)
        OFF = int((np.maximum(tlen - lengths, 0) + bw).max())
        slen = lengths.astype(np.int32)
        tl = np.full_like(slen, tlen)
        return BandGeometry(
            slen=slen,
            tlen=tl,
            bandwidth=(OFF - np.maximum(tl - slen, 0)).astype(np.int32),
            offset=np.full_like(slen, OFF),
            nd=np.full_like(slen, self._pallas_K(tlen)),
        )

    def _realign_pallas(self, t: np.ndarray, tlen: int,
                        want_moves: bool = False,
                        want_stats: bool = False) -> None:
        """The realign on the Pallas engines: one dispatch, one packed
        fetch (same contract as the XLA branch); want_stats adds the
        in-kernel move recording + device traceback statistics, and
        want_moves additionally ships the move band for the SCORE-stage
        host traceback walk."""
        import jax.numpy as jnp

        from ..ops import align_jax
        from ..ops.fused_pallas import fused_step_auto, select_impl

        T = len(t)
        T1 = T + 1
        T1p = _bucket(T1, 64)
        K = self._pallas_K(tlen)
        # one routing decision for both layouts: under a mesh each shard
        # runs the same single-launch megakernel on its local lanes
        # (shard_map + psum epilogue), so eligibility is identical to
        # the single-device call — want_moves still pins split
        impl = select_impl(
            T1p, K, want_stats=want_stats, want_moves=want_moves)[0]
        C = _dense_cols(T1p, K, _bucket(self.batch.n_reads, 128),
                        want_stats=want_stats, impl=impl,
                        n_live=self.batch.n_reads,
                        band_dtype=self.band_dtype,
                        bw_hist=_bw_hist(self.bandwidths),
                        input_enc=self.input_enc)
        bufs = self._ensure_fill_bufs()
        batch = self._current_batch()
        self.n_forward_fills += 1
        if self.mesh is not None:
            from ..parallel.sharding import mesh_fused_step_pallas

            with self.timers.time("fused_dispatch"):
                packed, moves_dev = mesh_fused_step_pallas(
                    self.mesh, jnp.asarray(t, jnp.int8), jnp.int32(tlen),
                    bufs, batch.lengths, batch.bandwidth,
                    self._weights_dev.astype(jnp.float32),
                    K, T1p, C,
                    want_stats=want_stats, want_moves=want_moves,
                    interpret=_pallas_interpret(), impl=impl,
                )
            _, Npad_local, Npad = self._mesh_npads()
            slots = self._mesh_read_slots(self.batch.n_reads)
            from ..utils import roofline

            n_dev = self.mesh.devices.size
            mm = roofline.mesh_fused_model(
                T1p, K, Npad_local, C, n_dev,
                want_stats=want_stats, impl=impl)
            roofline.record(
                "mesh_fused_step", T1p=T1p, K=K, Npad_local=Npad_local,
                C=C, impl=impl, n_devices=n_dev, want_stats=want_stats,
                model_bytes_per_device=mm["bytes_per_device"],
                ici_bytes_per_device=mm["ici_bytes_per_device"],
                model_speedup=mm["model_speedup"],
                scaling_efficiency=mm["scaling_efficiency"],
            )
        else:
            geom = align_jax.batch_geometry(batch, tlen)
            weights = jnp.ones(self.batch.n_reads, dtype=jnp.float32)
            with self.timers.time("fused_dispatch"):
                packed, moves_dev = fused_step_auto(
                    jnp.asarray(t, jnp.int8), jnp.int32(tlen), bufs, geom,
                    weights, K, T1p, C,
                    want_stats=want_stats, want_moves=want_moves,
                    interpret=_pallas_interpret(), impl=impl,
                    band_dtype=self.band_dtype,
                    input_enc=self.input_enc,
                )
            Npad = bufs.seq_T.shape[1]
            slots = np.arange(self.batch.n_reads)
        self._finish_pallas_fetch(
            packed, moves_dev, Npad, slots, T1p, T1, want_stats,
            want_moves, tlen,
        )

    def _realign_pallas_panels(self, t: np.ndarray, tlen: int,
                               want_moves: bool = False,
                               want_stats: bool = False) -> None:
        """Long-template realign on the panel-blocked Pallas path
        (ops.dense_pallas.fused_tables_pallas_panels): same contract and
        packed-single-fetch discipline as _realign_pallas."""
        import jax.numpy as jnp

        from ..ops import align_jax
        from ..ops.dense_pallas import fused_tables_pallas_panels

        T = len(t)
        T1 = T + 1
        T1p = _bucket(T1, 64)
        K = self._pallas_K(tlen)
        Npad = _bucket(self.batch.n_reads, 128)
        # panels stay f32-internal (band_dtype not threaded): default isz
        C = _dense_cols(T1p, K, Npad, want_stats=want_stats,
                        bw_hist=_bw_hist(self.bandwidths))
        # panel size: per-panel temporaries (~2.2 band-panels) stay a
        # small fraction of the budget; multiple of C
        per_col = 13 * K * Npad * 4
        P = max(C, min(4096, int(self.hbm_budget // per_col)) // C * C)
        # panels never decode in-kernel: always the exact f32 buffers
        bufs = self._ensure_fill_bufs("f32")
        batch = self._current_batch()
        geom = align_jax.batch_geometry(batch, tlen)
        weights = jnp.ones(self.batch.n_reads, dtype=jnp.float32)
        self.n_forward_fills += 1
        with self.timers.time("fused_dispatch"):
            out = fused_tables_pallas_panels(
                jnp.asarray(t, jnp.int8), jnp.int32(tlen), bufs, geom,
                weights, K, T1p, C, panel_cols=P,
                want_stats=want_stats, want_moves=want_moves,
                interpret=_pallas_interpret(),
            )
            from ..ops.dense_pallas import pack_parts

            packed = jnp.concatenate(pack_parts(out, want_stats))
        self._finish_pallas_fetch(
            packed, out.get("moves"), Npad,
            np.arange(self.batch.n_reads), T1p, T1, want_stats,
            want_moves, tlen,
        )

    def _finish_pallas_fetch(self, packed, moves_dev, Npad, slots,
                             T1p: int, T1: int, want_stats: bool,
                             want_moves: bool, tlen: int) -> None:
        """Shared tail of every Pallas realign flavor: ONE packed fetch,
        unpack via pack_layout_pallas (the single consumer-side copy of
        the section order), stats validation, and the optional move
        fetch + host traceback walk."""
        from ..ops.dense_pallas import pack_layout_pallas

        with self.timers.time("packed_fetch"):
            ph = np.asarray(packed)
        lay = pack_layout_pallas(Npad, T1p, want_stats, T1)
        self._total = float(ph[0])
        self.scores = ph[slice(*lay["scores"])][slots]
        self._tables_host = (
            ph[slice(*lay["sub"])].reshape(T1p, 4)[:T1],
            ph[slice(*lay["ins"])].reshape(T1p, 4)[:T1],
            ph[slice(*lay["del"])][:T1],
        )
        if want_stats:
            n_errors = ph[slice(*lay["n_errors"])][slots].astype(np.int64)
            if (n_errors[: len(self.reads)] < 0).any():
                raise RuntimeError(
                    "device traceback hit TRACE_NONE (malformed band)"
                )
            self.edits_seen = ph[slice(*lay["edits"])].reshape(T1, 9) > 0
        else:
            self.edits_seen = None
        if want_moves:
            with self.timers.time("moves_fetch"):
                moves_host = np.asarray(moves_dev)[slots][:, :, :T1]
            with self.timers.time("traceback_walk"):
                self.tracebacks = align_jax.traceback_batch(
                    moves_host, self._uniform_geom_host(tlen)
                )
        else:
            self.tracebacks = None
        self.A_bands = None
        self.B_bands = None
        self.moves = None
        self.geom = None

    def _adapt_pallas_ok(self, tlen: int) -> bool:
        """Adaptation rounds run the single-launch forward-only
        fill+stats program whenever its (much smaller) working set fits
        — even in panel mode, whose dense/backward streams are what
        break the budget."""
        mode = self._pallas_mode(tlen)
        if mode == "single":
            return True
        if mode != "panels":
            return False
        K = self._pallas_K(tlen)
        T1p = _bucket(_bucket(tlen + 1, self.len_bucket) + 1, 64)
        Npad = _bucket(self.batch.n_reads, 128)
        # fwd band f32 + moves int32 out + int8 copy + blocked tables
        return 10 * T1p * K * Npad <= self.hbm_budget

    # --- device-resident stage loop ---------------------------------------
    def stage_runner(self, tlen0: int, do_indels: bool, min_dist: int,
                     history_cap: int, stop_on_same: bool,
                     use_edits: bool = False, speculate_k: int = 0):
        """Jitted whole-stage hill-climb runner (engine.device_loop) over
        this batch, or None when no step engine fits. The compiled
        while-loop is cached at module level by static shape config
        (_pallas_stage_runner/_xla_stage_runner) — a fresh aligner with
        the same shapes reuses it; this method binds the batch's device
        state and returns a (consensus, prev_score, iters_left,
        prev_iters) -> StageResult callable.

        ``use_edits`` adds the in-kernel traceback-statistics pass to
        every step and masks candidates with the union edit indicators —
        the device-resident do_alignment_proposals path (model.jl:
        483-497). One divergence from the host path: the in-loop step
        cannot raise on a malformed band (n_errors < 0) the way
        realign(want_stats=True) does.

        ``speculate_k`` > 0 requests speculative next-round composites
        packed into every scoring launch (device_loop's speculative
        body). Speculative blocks run the XLA segmented step — the
        megakernel fills one template per launch (ops.fused_pallas
        .mega_segment_eligible) — so a Pallas-eligible stage is routed
        to the XLA runner while speculating; when the XLA shapes force
        read chunking (chunked partial sums associate differently) or
        exceed the dense-block threshold, speculation is dropped
        instead (the serial path, ``runner.speculate_k == 0``). The
        effective value is exposed as ``runner.speculate_k``."""
        import jax.numpy as jnp

        from .device_loop import MAX_DRIFT

        if not bool(self.fixed.all()) or self.mesh is not None:
            return None
        Tmax = _bucket(tlen0 + 1, self.len_bucket)
        mode = self._pallas_mode(tlen0)
        if mode == "panels":
            # the panel path is a host-driven launch sequence; compiling
            # it unrolled inside the whole-stage while_loop would blow
            # the program up -- the host loop drives panel realigns
            return None
        use_pallas = mode == "single"
        spec_k = int(speculate_k)
        if spec_k:
            from ..ops.fused import DENSE_BLOCK_THRESHOLD as _DBT

            K_x = _bucket(self._K(tlen0) + MAX_DRIFT, 8)
            # the speculative launch carries (2 + k) segments of
            # duplicated reads — its working set, not the serial one,
            # must fit unchunked
            chunk_x = _pick_read_chunk(
                (2 + spec_k) * self.batch.n_reads, K_x, Tmax + 1,
                self.hbm_budget,
            )
            if chunk_x or Tmax + 1 > _DBT:
                spec_k = 0
            else:
                use_pallas = False
        # K in the key: a re-entry after a drift bail re-centers the
        # drift budget on the NEW entry length, so a cached runner whose
        # compiled band height only covered the OLD entry length must
        # not be reused (its band would silently truncate)
        K = (self._pallas_K(tlen0, margin=MAX_DRIFT) if use_pallas
             else _bucket(self._K(tlen0) + MAX_DRIFT, 8))
        T1 = Tmax + 1
        T1p = _bucket(T1, 64)
        # fused-step routing is part of the runner's identity: a runner
        # compiled for the megakernel must not be served after the env
        # flips to split (and vice versa)
        impl = "split"
        if use_pallas:
            from ..ops.fused_pallas import select_impl

            impl = select_impl(T1p, K, want_stats=use_edits)[0]
        n_reads = self.batch.n_reads
        # segment-pair packing of the rollback re-score (the ref-default
        # self-packing): only on the XLA step, only when the batch is
        # lane-starved enough that the duplicated reads ride padded
        # lanes, never through the read-chunked step (chunked partial
        # sums associate differently), and within the unblocked dense
        # sweep. In the runner key: the env gate can flip mid-process
        from ..ops.fused import DENSE_BLOCK_THRESHOLD
        from ..parallel.sweep_sharded import segment_pack_enabled

        chunk0 = _pick_read_chunk(n_reads, K, T1, self.hbm_budget)
        seg_pair = (
            not use_pallas
            and not spec_k  # the speculative launch packs the pair too
            and segment_pack_enabled()
            and (not chunk0 or chunk0 >= n_reads)
            and 2 * n_reads <= 128
            and T1 <= DENSE_BLOCK_THRESHOLD
        )
        key = (Tmax, K, use_pallas, do_indels, min_dist, history_cap,
               stop_on_same, use_edits, impl, seg_pair, self.band_dtype,
               self.input_enc, spec_k)
        if key in self._stage_runners:
            return self._stage_runners[key]
        bw_dev = jnp.asarray(self.bandwidths)
        lengths_dev = jnp.asarray(self._lengths_host)

        if use_pallas:
            C = _dense_cols(T1p, K, _bucket(n_reads, 128),
                            want_stats=use_edits, impl=impl,
                            n_live=n_reads, band_dtype=self.band_dtype,
                            bw_hist=_bw_hist(self.bandwidths),
                            input_enc=self.input_enc)
            weights = jnp.ones(n_reads, dtype=jnp.float32)
            base = _pallas_stage_runner(
                K, T1p, C, do_indels, min_dist,
                history_cap, Tmax, stop_on_same, use_edits, impl,
                self.band_dtype, self.input_enc,
            )
            state = (self._ensure_fill_bufs(), lengths_dev, bw_dev, weights)
        else:
            batch = self._current_batch()
            chunk = _pick_read_chunk(n_reads, K, T1, self.hbm_budget)
            weights = jnp.ones(n_reads, dtype=self.dtype)
            base = _xla_stage_runner(
                K, T1, Tmax, chunk, n_reads, do_indels, min_dist,
                history_cap, stop_on_same, use_edits, seg_pair,
                self.band_dtype, spec_k,
            )
            # one roofline record per compiled shape (like the Pallas
            # branch): lane occupancy against the 128-lane vector axis,
            # with segment-pair packing the re-score rides 2x the lanes
            # and a speculative launch (2 + k)x
            n_live = ((2 + spec_k) * n_reads if spec_k
                      else 2 * n_reads if seg_pair else n_reads)
            _dense_cols(_bucket(T1, 64), K, Npad=_bucket(n_live, 128),
                        want_stats=use_edits, impl="xla", n_live=n_live,
                        band_dtype=self.band_dtype,
                        bw_hist=_bw_hist(self.bandwidths))
            state = (
                (batch.seq, batch.match, batch.mismatch, batch.ins,
                 batch.dels),
                lengths_dev, bw_dev, weights,
            )

        def runner(consensus, prev_score, iters_left, prev_iters=0):
            return base(consensus, prev_score, iters_left, prev_iters,
                        step_state=state)

        runner.speculate_k = spec_k
        self._stage_runners[key] = runner
        return runner

    def stage_runner_frame(self, tlen0: int, ref: ReadScores,
                           indel_correction_only: bool, min_dist: int,
                           history_cap: int, stop_on_same: bool,
                           seed_gate: bool = False):
        """Jitted whole-FRAME-stage runner: the read step plus the codon
        reference engine's dense all-edit tables, so penalty-escalation
        rounds of FRAME (model.jl:1150-1227 with reference scoring) run
        as one dispatch each. Same caching/bail contract as
        stage_runner; None when no engine fits (mesh, unsettled
        bandwidths, or the reference's bandwidth not yet adapted).

        ``seed_gate`` adds the seed_indels restriction (model.jl:538-562)
        to every step: a SKEWED consensus-vs-reference alignment
        (single_indel_proposals' skew_matches=True), the single-indel
        emission columns of its optimal path (ops.align_codon_jax.
        path_indel_columns — the device form of the host traceback walk),
        and a +-CODON_LENGTH dilation yield anchor gates over the dense
        FRAME indel candidates."""
        import jax.numpy as jnp

        from ..ops.align_codon_jax import (
            band_height_codon,
            get_engine,
        )
        from .device_loop import MAX_DRIFT

        if (not bool(self.fixed.all()) or self.mesh is not None
                or not ref.bandwidth_fixed):
            return None
        Tmax = _bucket(tlen0 + 1, self.len_bucket)
        mode = self._pallas_mode(tlen0)
        if mode == "panels":
            return None
        use_pallas = mode == "single"
        K = (self._pallas_K(tlen0, margin=MAX_DRIFT) if use_pallas
             else _bucket(self._K(tlen0) + MAX_DRIFT, 8))
        eng = get_engine(ref)
        rt = eng._tables(ref.bandwidth, False)
        Kc = _bucket(
            band_height_codon(len(ref), tlen0, ref.bandwidth)
            + MAX_DRIFT + 1, 16,
        )
        T1pc = Tmax + 64
        nrows = eng.Lpad + 1
        do_subs = not indel_correction_only
        # the hit must hold the SAME RefTables object: penalty
        # escalation rebuilds rt, and an id()-style key could collide
        # after GC and serve a runner closed over stale penalty tables
        # (the same hazard align_codon_jax._ENGINE_CACHE guards). The
        # skewed tables derive from the same engine, so the rt identity
        # check covers them too.
        T1 = Tmax + 1
        T1p = _bucket(T1, 64)
        impl = "split"
        if use_pallas:
            from ..ops.fused_pallas import select_impl

            impl = select_impl(T1p, K)[0]
        key = ("frame", Tmax, K, use_pallas, do_subs, min_dist,
               history_cap, stop_on_same, Kc, T1pc, nrows, ref.bandwidth,
               seed_gate, impl, self.band_dtype, self.input_enc)
        hit = self._stage_runners.get(key)
        if hit is not None and hit[0] is rt:
            return hit[1]

        n_reads = self.batch.n_reads
        bw_dev = jnp.asarray(self.bandwidths)
        lengths_dev = jnp.asarray(self._lengths_host)
        rt9 = tuple(rt[:9])
        if seed_gate:
            rt9s = tuple(eng._tables(ref.bandwidth, True)[:9])

        if use_pallas:
            C = _dense_cols(T1p, K, _bucket(n_reads, 128), impl=impl,
                            n_live=n_reads, band_dtype=self.band_dtype,
                            bw_hist=_bw_hist(self.bandwidths),
                            input_enc=self.input_enc)
            weights = jnp.ones(n_reads, dtype=jnp.float32)
            base = _pallas_frame_runner(
                K, T1p, C, True, do_subs, min_dist, history_cap, Tmax,
                stop_on_same, Kc, T1pc, nrows, rt.do_cins, rt.do_cdel,
                seed_gate, impl, self.band_dtype, self.input_enc,
            )
            read_state = (self._ensure_fill_bufs(), lengths_dev, bw_dev,
                          weights)
        else:
            batch = self._current_batch()
            chunk = _pick_read_chunk(n_reads, K, T1, self.hbm_budget)
            weights = jnp.ones(n_reads, dtype=self.dtype)
            base = _xla_frame_runner(
                K, T1, Tmax, chunk, n_reads, True, do_subs, min_dist,
                history_cap, stop_on_same, Kc, T1pc, nrows,
                rt.do_cins, rt.do_cdel, seed_gate, self.band_dtype,
            )
            read_state = (
                (batch.seq, batch.match, batch.mismatch, batch.ins,
                 batch.dels),
                lengths_dev, bw_dev, weights,
            )
        state = ((read_state, rt9, rt9s) if seed_gate
                 else (read_state, rt9))

        def runner(consensus, prev_score, iters_left, prev_iters=0):
            return base(consensus, prev_score, iters_left, prev_iters,
                        step_state=state)

        self._stage_runners[key] = (rt, runner)
        return runner

    # --- alignment --------------------------------------------------------
    def realign(
        self,
        consensus: np.ndarray,
        pvalue: float,
        realign_As: bool = True,
        realign_Bs: bool = True,
        want_moves: bool = False,
        want_stats: bool = False,
    ) -> None:
        """One fused device dispatch + ONE packed device->host fetch:
        forward (+moves), backward, dense all-edit score tables, weighted
        totals, and (want_stats) device-side traceback statistics — with
        adaptive bandwidth on the first alignment of each read
        (smart_forward_moves!, model.jl:643-672).

        `want_stats` computes per-read alignment error counts and the
        union edit-indicator table on device (alignment-derived proposals
        + bandwidth adaptation). `want_moves` additionally ships the move
        band to the host and walks real tracebacks (SCORE stage only —
        the fetch is expensive, see ops.fused docstring).

        `realign_As`/`realign_Bs` are accepted for driver API parity with
        the reference's dirty flags (model.jl:689, 703) but the fused
        program always refills both bands: on device a redundant refill is
        ~100x cheaper than a second dispatch (BASELINE.md).
        """
        import jax.numpy as jnp

        from ..ops.fused import fused_step_full, pack_layout

        t = self._padded_template(consensus)
        tlen = len(consensus)
        # memoization: the driver re-realigns at the top of every
        # iteration, but after an accepted candidate the consensus, batch,
        # and bandwidths are exactly what the post-accept realign already
        # filled. Skipping the redundant dispatch+fetch matters doubly on
        # hardware where every device->host fetch pays a fixed ~100 ms
        # round trip (BASELINE.md "tunneled TPU" measurements) — this is
        # the realign_As/realign_Bs dirty-flag fast path of model.jl:
        # 689-703, keyed on content instead of flags.
        # bandwidths are part of the key: a hit must never serve bands
        # filled under different bandwidths. The cached key holds the
        # POST-adaptation bandwidths of the fill that produced the bands,
        # so a hit requires the current bandwidths to match those.
        key = (t.tobytes(), tlen, want_moves, want_stats,
               self.bandwidths.tobytes())
        if key == self._realign_key and bool(self.fixed.all()):
            return
        self._tlen = tlen
        T1 = len(t) + 1
        weights = self._weights_dev
        if weights is None:
            weights = jnp.ones(self.batch.n_reads, dtype=self.dtype)
        t_dev = jnp.asarray(t, jnp.int8)
        if not bool(self.fixed.all()):
            # adaptation rounds: fills + traceback statistics ONLY — the
            # dense all-edits sweep is the most expensive component of
            # the step and its tables would be discarded every round the
            # bandwidths grow (round-4 profile: adaptation dominated the
            # whole run at 2048 reads)
            self._adapt_bandwidths(t_dev, tlen, T1, weights, pvalue)
        # final pass at settled bandwidths
        mode = self._pallas_mode(tlen)
        if mode == "panels":
            self._realign_pallas_panels(t, tlen, want_moves, want_stats)
        elif mode == "single":
            self._realign_pallas(t, tlen, want_moves, want_stats)
        else:
            batch = self._current_batch()
            K = self._K(tlen)
            geom = align_jax.batch_geometry(batch, tlen)
            self.n_forward_fills += 1
            # sequential read chunks bound HBM for big problems; never
            # under a mesh (the read axis is already sharded across chips)
            chunk = (
                0 if self.mesh is not None
                else _pick_read_chunk(self.batch.n_reads, K, T1,
                                      self.hbm_budget)
            )
            with self.timers.time("fused_dispatch"):
                A, B, moves, packed = fused_step_full(
                    t_dev,
                    batch.seq,
                    batch.match,
                    batch.mismatch,
                    batch.ins,
                    batch.dels,
                    geom,
                    weights,
                    K,
                    want_moves,
                    want_stats,
                    chunk,
                    band_dtype=self.band_dtype,
                )
            self.A_bands, self.B_bands = A, B
            self.moves, self.geom = moves, geom
            with self.timers.time("packed_fetch"):
                ph = np.asarray(packed)
            lay = pack_layout(self.batch.n_reads, T1, want_stats)
            self._total = float(ph[0])
            self.scores = ph[slice(*lay["scores"])]
            self._tables_host = (
                ph[slice(*lay["sub"])].reshape(T1, 4),
                ph[slice(*lay["ins"])].reshape(T1, 4),
                ph[slice(*lay["del"])],
            )
            if want_stats:
                n_errors = ph[slice(*lay["n_errors"])].astype(np.int64)
                if (n_errors[: len(self.reads)] < 0).any():
                    raise RuntimeError(
                        "device traceback hit TRACE_NONE (malformed band)"
                    )
                self.edits_seen = ph[slice(*lay["edits"])].reshape(T1, 9) > 0
            else:
                self.edits_seen = None
            if want_moves:
                with self.timers.time("moves_fetch"):
                    moves_host = np.asarray(moves)
                with self.timers.time("traceback_walk"):
                    self.tracebacks = align_jax.traceback_batch(
                        moves_host, geom
                    )
            else:
                self.tracebacks = None
        # store with the FINAL bandwidths (adaptation may have doubled
        # them above); the entry-time `key` would never hit again
        self._realign_key = (t.tobytes(), tlen, want_moves, want_stats,
                             self.bandwidths.tobytes())

    def _adapt_bandwidths(self, t_dev, tlen: int, T1: int, weights,
                          pvalue: float) -> None:
        """Adaptive-bandwidth rounds (smart_forward_moves!,
        model.jl:643-672): fill + device traceback statistics, fetch the
        error counts, double band-limited reads, repeat until stable."""
        from ..ops.fused import fused_step_full, pack_layout

        self._old_errors = np.full(len(self.reads), np.iinfo(np.int64).max)
        # cap is computed ONCE from the bandwidths at entry
        # (model.jl:650: seq.bandwidth * 2^5); recomputing from the
        # already-doubled value each round would let a read grow past
        # the final refill, leaving A and B with mismatched band heights
        entry_bw = self.bandwidths.copy()
        want_edge = self.band_growth == "adaptive"
        if want_edge:
            # adaptive mode enters at min(bandwidth, 16): most reads
            # never needed the caller's default band, and the policy
            # grows the few that ride the wall. The cap above still
            # derives from the ORIGINAL entry bandwidths.
            lowered = np.where(self.fixed, self.bandwidths,
                               adaptive_entry(self.bandwidths))
            if not np.array_equal(lowered, self.bandwidths):
                self.bandwidths = lowered.astype(self.bandwidths.dtype)
                self._bw_dev = None
        for _round in range(MAX_BANDWIDTH_DOUBLINGS + 1):
            edge_hits = None
            if self._adapt_pallas_ok(tlen):
                n_errors, edge_hits = self._adapt_round_pallas(
                    t_dev, tlen, want_edge)
            else:
                batch = self._current_batch()
                K = self._K(tlen)
                geom = align_jax.batch_geometry(batch, tlen)
                self.n_forward_fills += 1
                chunk = (
                    0 if self.mesh is not None
                    else _pick_read_chunk(self.batch.n_reads, K, T1,
                                          self.hbm_budget)
                )
                with self.timers.time("adapt_dispatch"):
                    _, _, _, packed = fused_step_full(
                        t_dev, batch.seq, batch.match, batch.mismatch,
                        batch.ins, batch.dels, geom, weights, K,
                        False, True, chunk, False, want_edge,
                        self.band_dtype,
                    )
                with self.timers.time("adapt_fetch"):
                    ph = np.asarray(packed)
                lay = pack_layout(self.batch.n_reads, T1, True, False,
                                  want_edge)
                n_errors = ph[slice(*lay["n_errors"])].astype(np.int64)
                if want_edge:
                    edge_hits = ph[slice(*lay["edge_hits"])].astype(
                        np.int64)
            if (n_errors[: len(self.reads)] < 0).any():
                raise RuntimeError(
                    "device traceback hit TRACE_NONE (malformed band)"
                )
            grew = self._maybe_grow_bandwidth(n_errors, tlen, pvalue,
                                              entry_bw, edge_hits)
            if not grew:
                self.fixed[:] = True
                break

    def _adapt_round_pallas(self, t_dev, tlen: int,
                            want_edge: bool = False):
        """One adaptation round on the Pallas engine: forward-only fill
        with in-kernel move recording + device traceback statistics —
        no backward stream, no dense sweep (ops.dense_pallas.
        fill_stats_pallas). Returns (n_errors, edge_hits-or-None):
        per-read alignment error counts plus, under the adaptive
        growth policy, the count of on-path cells pinned to a band-limit
        row (the stats kernels' ``want_edge`` section)."""
        import jax.numpy as jnp

        from ..ops.dense_pallas import fill_stats_pallas

        T1p = _bucket(int(t_dev.shape[0]) + 1, 64)
        K = self._pallas_K(tlen)
        C = _fill_cols(T1p, K, _bucket(self.batch.n_reads, 128),
                       band_dtype=self.band_dtype,
                       bw_hist=_bw_hist(self.bandwidths),
                       input_enc=self.input_enc)
        bufs = self._ensure_fill_bufs()
        batch = self._current_batch()
        self.n_forward_fills += 1
        if self.mesh is not None:
            from ..parallel.sharding import mesh_fill_stats_pallas

            with self.timers.time("adapt_dispatch"):
                packed = mesh_fill_stats_pallas(
                    self.mesh, t_dev, jnp.int32(tlen), bufs,
                    batch.lengths, batch.bandwidth, K, T1p, C,
                    interpret=_pallas_interpret(),
                )
            _, _, Npad = self._mesh_npads()
            slots = self._mesh_read_slots(len(self.reads))
        else:
            geom = align_jax.batch_geometry(batch, tlen)
            with self.timers.time("adapt_dispatch"):
                packed = fill_stats_pallas(
                    t_dev, jnp.int32(tlen), bufs, geom, K, T1p, C,
                    interpret=_pallas_interpret(), want_edge=want_edge,
                    band_dtype=self.band_dtype, input_enc=self.input_enc,
                )
            Npad = bufs.seq_T.shape[1]
            slots = np.arange(self.batch.n_reads)
        with self.timers.time("adapt_fetch"):
            ph = np.asarray(packed)
        n_errors = ph[Npad : 2 * Npad][slots].astype(np.int64)
        if want_edge:
            return n_errors, ph[2 * Npad :][slots].astype(np.int64)
        return n_errors, None

    def _maybe_grow_bandwidth(self, n_errors, tlen: int, pvalue: float,
                              entry_bw: np.ndarray,
                              edge_hits=None) -> bool:
        """Grow bandwidths of reads whose alignments look band-limited
        (model.jl:655-671), by the policy in engine.bandgrowth: blunt
        x2 doubling (default, the reference port) or per-read adaptive
        growth from the traceback's band-edge hit counts. Returns True
        if any bandwidth grew."""
        n = len(self.reads)
        thresholds = np.array([
            poisson_cquantile(self.est_n_errors[k], pvalue)
            for k in range(n)
        ])
        new_bw, new_fixed, new_old = grow_bandwidths(
            self.bandwidths[:n], self.fixed[:n], self._old_errors[:n],
            np.asarray(n_errors)[:n], thresholds, entry_bw[:n], tlen,
            self._lengths_host[:n].astype(np.int64),
            band_growth=self.band_growth,
            edge_hits=(None if edge_hits is None
                       else np.asarray(edge_hits)[:n]),
        )
        grew = bool((new_bw != self.bandwidths[:n]).any())
        self.bandwidths[:n] = new_bw
        self.fixed[:n] = new_fixed
        self._old_errors[:n] = new_old
        if grew:
            self._bw_dev = None  # invalidate the sharded device copy
        return grew

    def total_score(self, weights: Optional[np.ndarray] = None) -> float:
        """Sum of per-read alignment scores (rescore!, model.jl:630-635).
        The default total was already reduced on device by the fused step
        and arrived in the packed fetch (with sharding-padding reads
        masked); only custom weights force a host-side reduction."""
        if weights is None and self._total is not None:
            return self._total
        if weights is None:
            weights = self.weights  # masks sharding-padding reads, if any
        scores = np.asarray(self.scores)
        if weights is None:
            return float(np.sum(scores))
        # mask BEFORE multiplying: 0 * -inf would be nan (and warn)
        w = np.asarray(weights)
        return float(np.sum(np.where(w > 0, scores, 0.0) * w))

    # --- proposal scoring -------------------------------------------------
    # cap on reads x proposals per launch: keeps the [N, K, P] scoring
    # intermediates within a fraction of HBM and the XLA program small
    MAX_SCORE_ELEMS = 2048 * 2048

    def score_proposals(self, proposals: Sequence[Proposal]) -> np.ndarray:
        """Total score of each proposal across the batch (the reference's
        per-proposal-per-read host loop, model.jl:385-399).

        The fused realign already computed batch-total score tables for
        EVERY single-base edit (ops.proposal_dense, reduced over the —
        possibly sharded — read axis on device) and shipped them in the
        packed fetch, so scoring any proposal set is a host-side table
        readout: zero additional device work. The sparse per-proposal
        kernel (ops.proposal_jax) remains the fallback when no tables are
        cached."""
        if len(proposals) == 0:
            return np.empty(0, dtype=self.dtype)
        if self._tables_host is not None:
            with self.timers.time("tables_readout"):
                return self._read_tables(self._tables_host, proposals)
        n = self.batch.n_reads
        chunk = max(128, self.MAX_SCORE_ELEMS // max(n, 1))
        batch = self._current_batch()
        outs = []
        for s in range(0, len(proposals), chunk):
            sub = proposals[s : s + chunk]
            kw = {} if len(proposals) <= chunk else {"pad_bucket": chunk}
            per_read = score_proposals_batch(
                self.A_bands, self.B_bands, batch, self.geom, sub, **kw
            )
            if self._weights_dev is not None:
                from ..parallel.sharding import weighted_read_sum

                outs.append(np.asarray(weighted_read_sum(self._weights_dev, per_read)))
            else:
                outs.append(np.asarray(per_read).sum(axis=0))
        return np.concatenate(outs) if len(outs) > 1 else outs[0]

    def dense_score_tables(self, tlen: int):
        """The cached dense all-edit score tables for the CURRENT
        consensus, truncated to the true length: (sub [tlen, 4], ins
        [tlen + 1, 4], del [tlen]), or None when the last realign did
        not ship tables (sparse fallback engines). driver.estimate_probs
        reads the whole tables in one shot instead of materializing and
        scoring ~5*tlen single-edit Proposal objects."""
        if self._tables_host is None:
            return None
        sub_t, ins_t, del_t = self._tables_host
        if sub_t.shape[0] < tlen + 1:
            return None
        return sub_t[:tlen], ins_t[: tlen + 1], del_t[:tlen]

    @staticmethod
    def _read_tables(tables, proposals: Sequence[Proposal]) -> np.ndarray:
        from .proposals import Insertion, Substitution

        sub_t, ins_t, del_t = tables
        out = np.empty(len(proposals), dtype=sub_t.dtype)
        for k, p in enumerate(proposals):
            if isinstance(p, Substitution):
                out[k] = sub_t[p.pos, p.base]
            elif isinstance(p, Insertion):
                out[k] = ins_t[p.pos, p.base]
            else:
                out[k] = del_t[p.pos]
        return out

    def export_bandwidths(self) -> None:
        """Write adapted bandwidths back into the ReadScores objects so
        state survives batch reselection (the reference mutates
        RifrafSequence in place)."""
        for k, r in enumerate(self.reads):
            r.bandwidth = int(self.bandwidths[k])
            r.bandwidth_fixed = bool(self.fixed[k])


def _frame_ref_tables(Tmax: int, Kc: int, T1pc: int, nrows: int,
                      do_cins: bool, do_cdel: bool):
    """Dense all-edit score tables of consensus-vs-REFERENCE with codon
    moves, as a jit-friendly function (tmpl, tlen, rt_arrays) ->
    (ref_score, sub [Tmax, 4], ins [Tmax + 1, 4], del [Tmax]). One
    codon-engine fill pair + one vmapped O(band) rescoring over every
    single-base edit (model.jl:302-383 densified, as ops.proposal_dense
    does for reads). Positions >= tlen hold garbage; the device loop
    masks them."""
    import jax.numpy as jnp

    from ..ops.align_codon_jax import (
        KIND_DEL,
        KIND_INS,
        KIND_SUB,
        RefTables,
        _score_proposals_codon,
        backward_codon,
        forward_codon,
    )

    n_sub, n_del, n_ins = Tmax * 4, Tmax, (Tmax + 1) * 4
    kinds = np.concatenate([
        np.full(n_sub, KIND_SUB), np.full(n_del, KIND_DEL),
        np.full(n_ins, KIND_INS),
    ]).astype(np.int32)
    poss = np.concatenate([
        np.repeat(np.arange(Tmax), 4), np.arange(Tmax),
        np.repeat(np.arange(Tmax + 1), 4),
    ]).astype(np.int32)
    bases = np.concatenate([
        np.tile(np.arange(4), Tmax), np.zeros(Tmax),
        np.tile(np.arange(4), Tmax + 1),
    ]).astype(np.int32)
    kinds_d, poss_d, bases_d = (
        jnp.asarray(kinds), jnp.asarray(poss), jnp.asarray(bases)
    )

    def ref_tables(tmpl, tlen, rt9):
        # rt9: the 9 RefTables arrays (the bool flags ride as statics so
        # the step_state pytree stays all-array)
        rt = RefTables(*rt9, do_cins=do_cins, do_cdel=do_cdel)
        fwd = forward_codon(tmpl[:Tmax], tlen, rt, Kc, T1pc)
        bwd = backward_codon(tmpl[:Tmax], tlen, rt, Kc, T1pc)
        t_cols = jnp.pad(
            jnp.concatenate([tmpl[:1], tmpl[:Tmax]]).astype(jnp.int8),
            (0, T1pc - Tmax - 1),
        )
        flat = _score_proposals_codon(
            kinds_d, poss_d, bases_d, t_cols, tlen,
            fwd.bands, fwd.starts, bwd.bands, bwd.starts,
            tuple(rt[:9]), Kc, T1pc, nrows, do_cins, do_cdel,
        )
        sub_t = flat[:n_sub].reshape(Tmax, 4)
        del_t = flat[n_sub : n_sub + n_del]
        ins_t = flat[n_sub + n_del :].reshape(Tmax + 1, 4)
        return fwd.score, sub_t, ins_t, del_t

    return ref_tables


def _add_ref_tables(read_out, ref_out, Tmax: int):
    """Sum the read-batch tables and the reference tables (the
    per-candidate score is reads + reference, model.jl:385-399). The
    read tables may be longer (T1p rows on the Pallas step); the
    reference tables are zero-padded up — rows past Tmax are garbage in
    both and masked by the device loop."""
    import jax.numpy as jnp

    total_r, sub_r, ins_r, del_r = read_out
    ref_score, sub_f, ins_f, del_f = ref_out

    def padto(a, n):
        return jnp.pad(a, ((0, n - a.shape[0]),) + ((0, 0),) * (a.ndim - 1))

    return (
        total_r + ref_score,
        sub_r + padto(sub_f, sub_r.shape[0]),
        ins_r + padto(ins_f, ins_r.shape[0]),
        del_r + padto(del_f, del_r.shape[0]),
    )


def _frame_seed_gates(tmpl, tlen, rt9s, Kc: int, T1pc: int, nrows: int,
                      do_cins: bool, do_cdel: bool, Tmax: int):
    """Device seed_indels gate (model.jl:538-562 + all_proposals'
    neighborhoods): skewed consensus-vs-reference fill with moves, the
    optimal path's single-indel emission columns, dilated by
    +-CODON_LENGTH in anchor space. Returns (ins_gate, del_gate), both
    [Tmax + 1] anchor-indexed booleans; with no seeds at all the gates
    open fully (all_proposals' no_seeds). The host clamps deletion
    neighborhoods to anchor >= 1 and both to anchor <= length — free
    here, since the device loop only ever queries anchors 1..tlen."""
    import jax.numpy as jnp

    from ..ops.align_codon_jax import (
        RefTables,
        forward_codon,
        path_indel_columns,
    )
    from ..utils.constants import CODON_LENGTH

    rts = RefTables(*rt9s, do_cins=do_cins, do_cdel=do_cdel)
    # the skew is baked into rt9s (make_ref_tables(skew=True)) — same
    # single application as the host's align_moves(skew_matches=True)
    fwd = forward_codon(tmpl[:Tmax], tlen, rts, Kc, T1pc, want_moves=True)
    ins_col, del_col = path_indel_columns(
        fwd.moves, fwd.starts, rts.slen, tlen, Kc, nrows + Kc, do_cins
    )

    def dilate(col):
        out = col
        for s in range(1, CODON_LENGTH + 1):
            z = jnp.zeros((s,), bool)
            out = out | jnp.concatenate([col[s:], z]) \
                      | jnp.concatenate([z, col[:-s]])
        return out

    any_seed = jnp.any(ins_col) | jnp.any(del_col)
    ins_gate = jnp.where(any_seed, dilate(ins_col), True)[: Tmax + 1]
    del_gate = jnp.where(any_seed, dilate(del_col), True)[: Tmax + 1]
    return ins_gate, del_gate


@functools.lru_cache(maxsize=32)
def _pallas_frame_runner(K, T1p, C, do_indels, do_subs, min_dist,
                         history_cap, Tmax, stop_on_same, Kc, T1pc, nrows,
                         do_cins, do_cdel, seed_gate=False, impl="split",
                         band_dtype="f32", input_enc="f32"):
    """Compiled device FRAME stage loop: Pallas read step + codon-engine
    reference tables. step_state = ((FillBuffers, lengths, bandwidths,
    weights), rt_arrays[, skewed rt_arrays]). ``impl`` is the fused-step
    routing resolved by the caller (ops.fused_pallas.select_impl) — it
    sits in the lru_cache key, so flipping RIFRAF_TPU_FUSED_IMPL builds
    a fresh runner instead of serving a stale trace."""
    from ..ops.align_jax import BandGeometry
    from ..ops.fused_pallas import fused_tables_auto
    from .device_loop import make_stage_runner

    ref_tables = _frame_ref_tables(Tmax, Kc, T1pc, nrows, do_cins, do_cdel)

    def step_fn(tmpl, tlen, s):
        if seed_gate:
            (bufs, lengths, bw, weights), rt, rts = s
        else:
            (bufs, lengths, bw, weights), rt = s
        geom = BandGeometry.make(lengths, tlen, bw)
        out = fused_tables_auto(
            tmpl, tlen, bufs, geom, weights, K, T1p, C,
            interpret=_pallas_interpret(), impl=impl,
            band_dtype=band_dtype, input_enc=input_enc,
        )
        base = _add_ref_tables(
            (out["total"], out["sub"], out["ins"], out["del"]),
            ref_tables(tmpl, tlen, rt), Tmax,
        )
        if seed_gate:
            return base + (_frame_seed_gates(
                tmpl, tlen, rts, Kc, T1pc, nrows, do_cins, do_cdel, Tmax
            ),)
        return base

    from ..utils.shapes import plan_cols

    return make_stage_runner(
        step_fn, do_indels, min_dist, history_cap, Tmax, stop_on_same,
        do_subs=do_subs, gate="seeds" if seed_gate else "none",
        plan=plan_cols(T1p, K,
                       kernel="fused" if impl == "mega" else "dense"),
    )


@functools.lru_cache(maxsize=32)
def _xla_frame_runner(K, T1, Tmax, chunk, n_reads, do_indels, do_subs,
                      min_dist, history_cap, stop_on_same, Kc, T1pc, nrows,
                      do_cins, do_cdel, seed_gate=False, band_dtype="f32"):
    """Compiled device FRAME stage loop over the fused XLA scan step
    (CPU equality tests / f64 runs). step_state = (((seq, match,
    mismatch, ins, dels), lengths, bandwidths, weights), rt_arrays[,
    skewed rt_arrays])."""
    from ..ops.align_jax import BandGeometry
    from ..ops.fused import fused_step_full, unpack_tables
    from .device_loop import make_stage_runner

    ref_tables = _frame_ref_tables(Tmax, Kc, T1pc, nrows, do_cins, do_cdel)

    def step_fn(tmpl, tlen, s):
        if seed_gate:
            ((seq, match, mismatch, ins, dels), lengths, bw, weights), \
                rt, rts = s
        else:
            ((seq, match, mismatch, ins, dels), lengths, bw, weights), \
                rt = s
        geom = BandGeometry.make(lengths, tlen, bw)
        _, _, _, packed = fused_step_full(
            tmpl[:Tmax], seq, match, mismatch, ins, dels, geom, weights,
            K, False, False, chunk, band_dtype=band_dtype,
        )
        base = _add_ref_tables(
            unpack_tables(packed, n_reads, T1),
            ref_tables(tmpl, tlen, rt), Tmax,
        )
        if seed_gate:
            return base + (_frame_seed_gates(
                tmpl, tlen, rts, Kc, T1pc, nrows, do_cins, do_cdel, Tmax
            ),)
        return base

    return make_stage_runner(
        step_fn, do_indels, min_dist, history_cap, Tmax, stop_on_same,
        do_subs=do_subs, gate="seeds" if seed_gate else "none",
        aot_key=("realign_frame",
                 K, T1, Tmax, chunk, n_reads, do_indels, do_subs,
                 min_dist, history_cap, stop_on_same, Kc, T1pc, nrows,
                 do_cins, do_cdel, seed_gate, band_dtype),
    )


@functools.lru_cache(maxsize=64)
def _pallas_stage_runner(K, T1p, C, do_indels, min_dist,
                         history_cap, Tmax, stop_on_same, use_edits=False,
                         impl="split", band_dtype="f32", input_enc="f32"):
    """Compiled device stage loop over the Pallas fused step, shared
    across aligners of identical shape config. step_state =
    (FillBuffers, lengths, bandwidths, weights). ``impl`` routes each
    step to the single-launch megakernel or the split 3-launch path
    (resolved by the caller, cached in the key — see
    _pallas_frame_runner)."""
    from ..ops.align_jax import BandGeometry
    from ..ops.fused_pallas import fused_tables_auto
    from .device_loop import make_stage_runner

    def step_fn(tmpl, tlen, s):
        bufs, lengths, bw, weights = s
        geom = BandGeometry.make(lengths, tlen, bw)
        out = fused_tables_auto(
            tmpl, tlen, bufs, geom, weights, K, T1p, C,
            want_stats=use_edits, interpret=_pallas_interpret(),
            impl=impl, band_dtype=band_dtype, input_enc=input_enc,
        )
        base = (out["total"], out["sub"], out["ins"], out["del"])
        if use_edits:
            return base + (out["edits"],)
        return base

    from ..utils.shapes import plan_cols

    return make_stage_runner(
        step_fn, do_indels, min_dist, history_cap, Tmax, stop_on_same,
        gate="edits" if use_edits else "none",
        plan=plan_cols(T1p, K,
                       kernel="fused" if impl == "mega" else "dense"),
    )


@functools.lru_cache(maxsize=64)
def _xla_stage_runner(K, T1, Tmax, chunk, n_reads, do_indels, min_dist,
                      history_cap, stop_on_same, use_edits=False,
                      seg_pair=False, band_dtype="f32", speculate_k=0):
    """Compiled device stage loop over the fused XLA scan step (any
    backend / f64 exactness runs). step_state = ((seq, match, mismatch,
    ins, dels), lengths, bandwidths, weights).

    ``seg_pair`` packs the rollback re-score as a two-segment launch
    (ops.fused.fused_step_segmented over the reads duplicated per
    segment): on lane-starved small batches — the reference-default
    driver's 5-candidate/20-read stage sub-batches — the second
    template rides otherwise-padded lanes, replacing the conditional
    second dispatch. Bit-identical to the conditional path: segment 0's
    reductions walk the same lanes in the same order with exact zeros
    in segment 1's lanes (the unchunked fused step and the segmented
    step share _dense_batch/masked_weighted_sum).

    ``speculate_k`` > 0 builds every scoring round as a
    (2 + speculate_k)-segment launch over the reads duplicated per
    segment — {multi, single-best, speculative composite(s)} — for
    device_loop's speculative body; same bit-exactness argument as
    ``seg_pair``, per segment."""
    import jax.numpy as jnp

    from ..ops.align_jax import BandGeometry
    from ..ops.fused import fused_step_full, fused_step_segmented, unpack_tables
    from .device_loop import make_stage_runner

    def step_fn(tmpl, tlen, s):
        (seq, match, mismatch, ins, dels), lengths, bw, weights = s
        geom = BandGeometry.make(lengths, tlen, bw)
        _, _, _, packed = fused_step_full(
            tmpl[:Tmax], seq, match, mismatch, ins, dels, geom, weights,
            K, False, use_edits, chunk, band_dtype=band_dtype,
        )
        return unpack_tables(packed, n_reads, T1, use_edits)

    def _multi_seg_step(n_seg):
        # one segment-packed launch scoring n_seg templates over the
        # reads duplicated per segment
        def step(tmpls, tlens, s):
            (seq, match, mismatch, ins, dels), lengths, bw, weights = s

            def tile(a):
                return jnp.concatenate([a] * n_seg, axis=0)

            seg = jnp.concatenate([
                jnp.full((n_reads,), i, jnp.int32) for i in range(n_seg)
            ])
            out = fused_step_segmented(
                tmpls[:, :Tmax], tlens, seg, tile(seq), tile(match),
                tile(mismatch), tile(ins), tile(dels), tile(lengths),
                tile(bw), tile(weights), K, n_seg,
                want_stats=use_edits, want_tables=True,
                band_dtype=band_dtype,
            )
            tables = (out["total"], out["sub"], out["ins"], out["del"])
            if use_edits:
                # the plain step's edits come back through the packed
                # float buffer (unpack_tables); match that dtype so the
                # rollback cond's two branches carry identical types
                tables += (out["edits"].astype(out["sub"].dtype),)
            return tables

        return step

    seg_step = _multi_seg_step(2) if seg_pair else None
    spec_step = _multi_seg_step(2 + speculate_k) if speculate_k else None

    return make_stage_runner(
        step_fn, do_indels, min_dist, history_cap, Tmax, stop_on_same,
        gate="edits" if use_edits else "none", seg_step_fn=seg_step,
        speculate_k=speculate_k, spec_step_fn=spec_step,
        aot_key=("realign_stage",
                 K, T1, Tmax, chunk, n_reads, do_indels, min_dist,
                 history_cap, stop_on_same, use_edits, seg_pair,
                 band_dtype, speculate_k),
    )


class RefAligner:
    """Consensus-vs-reference alignment state (A_ref/B_ref/Amoves_ref,
    model.jl:180-182). Single sequence with codon moves. Short pairs run
    the numpy oracle engine; long ones the jitted codon engine
    (ops.align_codon_jax — the host column loop measured ~11 s per
    realign at a 9 kb reference), which is exact-equal by its oracle
    tests."""

    def __init__(self):
        self.A: Optional[BandedArray] = None
        self.B: Optional[BandedArray] = None
        self.Amoves: Optional[BandedArray] = None
        self._dev = None  # CodonDeviceAligner for long refs
        self._dev_consensus = None

    @staticmethod
    def _use_device(consensus: np.ndarray, ref: ReadScores) -> bool:
        from ..ops.align_codon_jax import DEVICE_THRESHOLD

        return min(len(consensus), len(ref)) >= DEVICE_THRESHOLD

    @staticmethod
    def _adapt_loop(fill_fn, count_fn, consensus, ref: ReadScores,
                    pvalue: float) -> None:
        """The shared adaptive-bandwidth protocol (smart_forward_moves!,
        model.jl:643-672), parameterized over the fill engine so the
        host and device paths cannot drift."""
        max_bw = min(ref.bandwidth << MAX_BANDWIDTH_DOUBLINGS,
                     len(consensus), len(ref))
        if ref.bandwidth_fixed:
            max_bw = ref.bandwidth
        n_errors = old_n_errors = np.iinfo(np.int64).max
        while True:
            fill_fn()
            if ref.bandwidth_fixed or ref.bandwidth >= max_bw:
                break
            old_n_errors = n_errors
            n_errors = count_fn()
            threshold = poisson_cquantile(ref.est_n_errors, pvalue)
            if n_errors > threshold and n_errors < old_n_errors:
                ref.bandwidth = min(ref.bandwidth * 2, max_bw)
            else:
                break
        ref.bandwidth_fixed = True

    def realign(self, consensus: np.ndarray, ref: ReadScores, pvalue: float,
                realign_As: bool = True, realign_Bs: bool = True) -> None:
        """smart_forward_moves! + backward! for the reference."""
        if self._use_device(consensus, ref):
            self._realign_device(consensus, ref, pvalue, realign_Bs)
            return
        self._dev = None
        if realign_As:

            def fill():
                self.A, self.Amoves = align_np.forward_moves_vec(
                    consensus, ref
                )

            self._adapt_loop(
                fill,
                lambda: align_np.count_errors_in_moves(
                    self.Amoves, consensus, ref.seq
                ),
                consensus, ref, pvalue,
            )
        if realign_Bs:
            self.B = align_np.backward_vec(consensus, ref)

    def _realign_device(self, consensus: np.ndarray, ref: ReadScores,
                        pvalue: float, realign_Bs: bool = True) -> None:
        """The same adaptive-bandwidth protocol on the jitted engine
        (fills cache per consensus/bandwidth, so redundant calls are
        free)."""
        from ..ops.align_codon_jax import get_engine

        self._dev = get_engine(ref)
        self._adapt_loop(
            lambda: self._dev.fill(consensus, ref.bandwidth,
                                   want_moves=True,
                                   want_backward=realign_Bs),
            lambda: self._dev.n_errors(consensus),
            consensus, ref, pvalue,
        )
        self.A = self.B = self.Amoves = None

    def score(self) -> float:
        if self._dev is not None:
            return self._dev.score()
        return float(self.A[self.A.nrows - 1, self.A.ncols - 1])

    def score_proposals(self, proposals: Sequence[Proposal],
                        consensus: np.ndarray, ref: ReadScores) -> np.ndarray:
        if self._dev is not None:
            return self._dev.score_proposals(proposals)
        newcols = np.full((self.A.nrows, 4), -np.inf)
        out = np.empty(len(proposals))
        for k, p in enumerate(proposals):
            out[k] = score_proposal_np(p, self.A, self.B, consensus, ref, newcols)
        return out
