"""The RIFRAF consensus driver: greedy hill-climbing over proposal stages.

Mirrors /root/reference/src/model.jl:564-1316 — the INIT -> FRAME -> REFINE
-> SCORE state machine, stochastic read batching, reference penalty
escalation, convergence logic, and quality estimation — re-architected so
that all O(reads x length x bandwidth) work happens in the batched device
kernels (engine.realign), while the branchy, data-dependent control flow
stays on the host exactly where the reference keeps it.
"""

from __future__ import annotations

import sys
from dataclasses import dataclass, field
from typing import List, Optional, Sequence

import numpy as np

from ..models.errormodel import Scores
from ..models.sequences import ReadScores, make_read_scores
from ..ops import align_np
from ..utils.constants import CODON_LENGTH, decode_seq
from ..utils.mathops import logsumexp10
from ..utils.phred import phred_to_log_p, phred_to_p
from ..utils.timers import Timers
from .generate import (
    all_proposals,
    has_single_indels,
    proposals_from_edits,
    single_indel_proposals,
)
from .params import RifrafParams, Stage, check_params, next_stage
from .proposals import (
    Deletion,
    Insertion,
    Proposal,
    ScoredProposal,
    Substitution,
    apply_proposals,
    choose_candidates,
)
from .realign import BatchAligner, RefAligner


@dataclass
class EstimatedProbs:
    """Per-base consensus error probabilities (model.jl:12-24)."""

    sub: np.ndarray  # [len, 4]
    dele: np.ndarray  # [len]
    ins: np.ndarray  # [len + 1, 4]


@dataclass
class RifrafState:
    """Mutable run state (model.jl:166-193)."""

    consensus: np.ndarray
    reference: Optional[ReadScores]
    ref_scores: Scores
    sequences: List[ReadScores]
    batch_fixed_size: int
    batch_size: int
    base_batch_size: int
    score: float = -np.inf
    ref_error_rate: float = -np.inf
    n_ref_indel_mults: int = 0
    batch_seqs: List[ReadScores] = field(default_factory=list)
    # whether the reference's score vectors have been built from a real
    # error-rate estimate (initial_state only makes a placeholder)
    ref_built: bool = False
    realign_As: bool = True
    realign_Bs: bool = True
    penalties_increased: bool = False
    stage: Stage = Stage.INIT
    stage_iterations: np.ndarray = field(
        default_factory=lambda: np.zeros(len(Stage), dtype=int)
    )
    batch_randomness: float = 0.9
    converged: bool = False
    # device/host alignment engines (the As/Bs/Amoves caches)
    aligner: Optional[BatchAligner] = None
    ref_aligner: Optional[RefAligner] = None
    # observability: (stage, reason) pairs already logged for device-loop
    # declines, and stage name -> chosen execution path
    device_declines: set = field(default_factory=set)
    stage_paths: dict = field(default_factory=dict)
    # per-stage round accounting for speculative evaluation: stage name
    # -> {"iterations", "rounds", "attempts", "hits"}; rounds counts
    # scoring rounds actually paid (a speculation hit consumes two
    # iterations in one round), attempts/hits the speculative launches
    spec_stats: dict = field(default_factory=dict)


@dataclass
class RifrafResult:
    """model.jl:195-225. `timers` is a TPU addition: per-stage wall-clock
    sections of the run (resample / realign / candidate scoring / device
    dispatch vs fetch), printed at verbose>=2."""

    consensus: np.ndarray
    params: RifrafParams
    state: RifrafState
    consensus_stages: List[List[np.ndarray]]
    error_probs: Optional[EstimatedProbs] = None
    aln_error_probs: Optional[np.ndarray] = None
    timers: Optional[Timers] = None
    # execution metadata: {"stage_paths": {stage name -> "device_loop" /
    # "host (...reason...)" / "host"}, "declines": [{"stage", "reason"},
    # ...]} — which engine ran each stage, and every device-loop decline
    # the run hit (the per-stage reasons logged at verbose>=1, collected
    # so callers — e.g. the serving stats — can count fallbacks without
    # parsing logs)
    metadata: Optional[dict] = None


def _log(params: RifrafParams, level: int, msg: str) -> None:
    if params.verbose >= level:
        if params.log_prefix:
            msg = "\n".join(
                params.log_prefix + line for line in msg.split("\n")
            )
        # a single write call (print would issue a second one for the
        # newline) keeps concurrent sweep jobs from splicing into each
        # other's lines
        sys.stderr.write(msg + "\n")


_cache_enabled = False


def _enable_compilation_cache() -> None:
    """Persistent XLA compilation cache: the engine's bucketed shapes form a
    small, stable executable set, so repeated runs skip compilation."""
    global _cache_enabled
    if _cache_enabled:
        return
    _cache_enabled = True
    import os

    import jax

    try:
        # never override an already-configured cache dir (tests/conftest.py
        # points each pytest process at its own private cache): redirecting
        # it to the shared default made a test process and any concurrently
        # running driver process write the SAME cache files, and the jax
        # cache serializer segfaults under concurrent writers on this image
        if jax.config.jax_compilation_cache_dir is not None:
            return
        cache_dir = os.environ.get("RIFRAF_TPU_CACHE")
        if cache_dir is None:
            from ..utils.cachedir import machine_cache_dir

            cache_dir = machine_cache_dir(
                os.path.expanduser("~/.cache/rifraf_tpu_xla")
            )
        elif not cache_dir or cache_dir == "off":
            return
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:
        pass


def recover_stale_cache(err) -> bool:
    """Stale-persistent-cache recovery (the MULTICHIP_r01/r02 failure:
    a libtpu upgrade left AOT entries the new client refuses with
    ``FAILED_PRECONDITION: libtpu version mismatch``). If ``err``
    carries that signature, drop the configured cache dir's entries and
    disable the cache for the rest of the process so the caller can
    retry the failed dispatch against a fresh compile. Returns whether
    recovery ran — False means the error is something else and must
    propagate."""
    global _cache_enabled
    from ..utils.cachedir import clear_cache_dir, is_stale_cache_error

    if not is_stale_cache_error(err):
        return False
    try:
        import jax

        cache_dir = jax.config.jax_compilation_cache_dir
        n = clear_cache_dir(cache_dir)
        jax.config.update("jax_enable_compilation_cache", False)
        # the AOT-executable cache (serve.aot) carries the same
        # staleness mode — serialized modules the upgraded runtime
        # refuses — so recovery drops it in the same stroke
        from ..serve.aot import clear_aot_cache

        n_aot = clear_aot_cache()
        sys.stderr.write(
            f"rifraf-tpu: stale persistent compilation cache detected "
            f"({err!r}); dropped {n} entries from {cache_dir!r} plus "
            f"{n_aot} AOT executables and disabled the cache for this "
            "process\n"
        )
    except Exception:
        return False
    # keep _enable_compilation_cache from re-pointing jax at the dir
    _cache_enabled = True
    return True


def initial_state(
    consensus: Optional[np.ndarray],
    sequences: List[ReadScores],
    reference: Optional[np.ndarray],
    params: RifrafParams,
) -> RifrafState:
    """model.jl:564-615."""
    batch_size = params.batch_size if params.batch_size > 1 else len(sequences)
    batch_size = min(batch_size, len(sequences))
    batch_fixed_size = min(params.batch_fixed_size, len(sequences))

    if consensus is None or len(consensus) == 0:
        idx = int(
            np.argmax([logsumexp10(s.match_scores) for s in sequences])
        )
        consensus = sequences[idx].seq.copy()

    refseq = None
    if reference is not None and len(reference) > 0:
        # placeholder error rate until FRAME estimates the real one
        ref_error_log_p = np.full(len(reference), 0.0)
        refseq = ReadScores(
            seq=np.asarray(reference, dtype=np.int8),
            error_log_p=ref_error_log_p,
            est_n_errors=float(len(reference)),
            match_scores=np.zeros(len(reference)),
            mismatch_scores=np.zeros(len(reference)),
            ins_scores=np.zeros(len(reference)),
            del_scores=np.zeros(len(reference) + 1),
            codon_ins_scores=None,
            codon_del_scores=None,
            bandwidth=params.bandwidth,
            scores=params.ref_scores,
        )

    return RifrafState(
        consensus=np.asarray(consensus, dtype=np.int8),
        reference=refseq,
        ref_scores=params.ref_scores,
        sequences=sequences,
        batch_fixed_size=batch_fixed_size,
        batch_size=batch_size,
        base_batch_size=batch_size,
        batch_randomness=params.batch_randomness,
    )


def use_ref(state: RifrafState, use_ref_for_qvs: bool) -> bool:
    """model.jl:617-628."""
    if state.reference is None or len(state.reference) == 0:
        return False
    if state.stage == Stage.FRAME:
        return True
    if state.stage == Stage.SCORE and use_ref_for_qvs:
        return True
    return False


def _build_reference_scores(state: RifrafState, params: RifrafParams) -> None:
    """Estimate the reference error rate from the consensus edit distance
    and build the real per-base score vectors (the INIT->FRAME edge,
    model.jl:946-962). Also invoked lazily if a stage needs the reference
    before FRAME ever ran (e.g. do_frame=False with use_ref_for_qvs=True):
    the placeholder built by initial_state must never be scored against."""
    edit_dist = align_np.edit_distance(state.consensus, state.reference.seq)
    ref_error_rate = edit_dist / max(len(state.reference), len(state.consensus))
    ref_error_rate *= params.ref_error_mult
    # needs to be < 0.5, otherwise matches aren't rewarded at all
    state.ref_error_rate = min(max(ref_error_rate, 1e-10), 0.5)
    ref_error_log_p = np.full(len(state.reference), np.log10(state.ref_error_rate))
    state.reference = make_read_scores(
        state.reference.seq, ref_error_log_p, params.bandwidth, state.ref_scores
    )
    state.ref_built = True


def reweight(wv: np.ndarray, n: int, randomness: float) -> np.ndarray:
    """Interpolate between top-n / error-proportional / uniform weights
    (model.jl:1017-1036)."""
    if randomness < 0.0 or randomness > 1.0:
        raise ValueError("randomness must be between 0.0 and 1.0")
    wv = wv / wv.sum()
    if randomness > 0.5:
        weight = (randomness - 0.5) * 2.0
        endpoint = np.full(len(wv), 1.0 / len(wv))
    elif randomness < 0.5:
        weight = 1.0 - randomness * 2.0
        endpoint = np.zeros(len(wv))
        indices = np.argsort(wv)[::-1][:n]
        endpoint[indices] = 1.0 / n
    else:
        return wv
    return weight * endpoint + (1.0 - weight) * wv


def resample(state: RifrafState, params: RifrafParams, rng: np.random.Generator) -> None:
    """Select the working batch of reads (resample!, model.jl:1038-1066)."""
    err_weights = np.array([s.est_n_errors for s in state.sequences])
    if state.stage in (Stage.INIT, Stage.FRAME) and params.batch_fixed:
        indices = np.argsort(err_weights, kind="stable")[: state.batch_fixed_size]
        state.batch_seqs = [state.sequences[i] for i in indices]
        _log(params, 2, "    kept fixed batch")
        return
    wv = reweight(
        1.0 - err_weights / err_weights.sum(), state.batch_size, state.batch_randomness
    )
    if state.batch_size < len(state.sequences):
        wv = np.maximum(wv, 0.0)
        wv = wv / wv.sum()
        indices = rng.choice(
            len(state.sequences), size=state.batch_size, replace=False, p=wv
        )
        state.batch_seqs = [state.sequences[i] for i in indices]
        state.realign_As = True
        _log(params, 2, f"    sampled {state.batch_size} new sequences")
    else:
        state.batch_seqs = list(state.sequences)
        _log(params, 2, "    sampled all sequences")


def _same_batch(aligner: Optional[BatchAligner], batch_seqs: List[ReadScores]) -> bool:
    """Membership (and order) comparison of the aligner's cached batch vs
    the freshly resampled one. `resample` rebuilds the list object every
    iteration even when the selection is unchanged, so identity of the list
    would always miss — defeating the realign_As=False fast path after a
    single-candidate accept (model.jl:928-930)."""
    return (
        aligner is not None
        and len(aligner.reads) == len(batch_seqs)
        and all(a is b for a, b in zip(aligner.reads, batch_seqs))
    )


def realign_rescore(state: RifrafState, params: RifrafParams) -> None:
    """realign! + rescore! (model.jl:630-719), batched on device."""
    if not _same_batch(state.aligner, state.batch_seqs):
        if state.aligner is not None:
            state.aligner.export_bandwidths()
        if state.aligner is None:
            state.aligner = BatchAligner(
                state.batch_seqs, dtype=params.dtype,
                len_bucket=params.len_bucket, mesh=params.mesh,
                backend=params.backend, band_dtype=params.band_dtype,
                band_growth=params.band_growth,
                input_enc=params.input_enc,
            )
        else:
            state.aligner.set_batch(state.batch_seqs)
        state.realign_As = True
        state.realign_Bs = True
    _log(params, 2, f"    realigning As={state.realign_As} Bs={state.realign_Bs}")
    # alignment-derived proposals and bandwidth adaptation run on
    # device-side traceback statistics (want_stats); real host tracebacks
    # (want_moves: the expensive move-band fetch) are only needed for the
    # SCORE stage's alignment pileup
    want_stats = (
        state.stage in (Stage.INIT, Stage.REFINE)
        and params.do_alignment_proposals
    )
    want_moves = state.stage == Stage.SCORE
    state.aligner.realign(
        state.consensus,
        params.bandwidth_pvalue,
        realign_As=state.realign_As,
        realign_Bs=state.realign_Bs,
        want_moves=want_moves,
        want_stats=want_stats,
    )
    uref = use_ref(state, params.use_ref_for_qvs)
    if uref:
        if not state.ref_built:
            _build_reference_scores(state, params)
        if state.ref_aligner is None:
            state.ref_aligner = RefAligner()
        state.ref_aligner.realign(
            state.consensus,
            state.reference,
            params.bandwidth_pvalue,
            realign_As=True,
            realign_Bs=True,
        )
    state.score = state.aligner.total_score()
    if uref:
        state.score += state.ref_aligner.score()


def check_score(state: RifrafState, params: RifrafParams, old_score: float,
                rng: np.random.Generator) -> bool:
    """Grow the batch if the score regressed too much (model.jl:1074-1114).
    Returns False if the stage should end."""
    _log(params, 2, f"    score: {state.score}")
    cur_iters = state.stage_iterations[int(state.stage) - 1]
    if (
        not state.penalties_increased
        and state.batch_size == len(state.sequences)
        and cur_iters > 1
    ):
        if state.score < old_score:
            _log(params, 2, "    WARNING: not using batches, but score decreased.")
        elif state.score == old_score:
            _log(params, 2, "    score did not change. ending stage.")
            return False
    if (
        old_score != -np.inf
        and (state.score - old_score) / old_score > params.batch_threshold
        and not state.penalties_increased
        and state.batch_size < len(state.sequences)
        and cur_iters > 1
    ):
        state.batch_size = min(
            state.batch_size + state.base_batch_size, len(state.sequences)
        )
        _log(params, 2, f"    NOTE: increased batch size to {state.batch_size}.")
        resample(state, params, rng)
        state.realign_As = True
        state.realign_Bs = True
        realign_rescore(state, params)
        _log(params, 2, f"    new score: {state.score}")
    return True


def get_candidates(
    state: RifrafState,
    params: RifrafParams,
    indel_seeds: Sequence[Proposal] = (),
) -> List[ScoredProposal]:
    """Generate and score all proposals; keep the improving ones
    (model.jl:499-526). Reads are scored in one device launch; the
    reference term is host-scored (codon moves)."""
    uref = state.stage == Stage.FRAME

    if state.stage in (Stage.INIT, Stage.REFINE) and params.do_alignment_proposals:
        do_indels = state.stage == Stage.INIT
        proposals = proposals_from_edits(
            state.aligner.edits_seen, len(state.consensus), do_indels
        )
    else:
        proposals = all_proposals(
            state.stage, state.consensus, params.indel_correction_only, indel_seeds
        )
    if not proposals:
        return []
    scores = state.aligner.score_proposals(proposals)
    if uref:
        scores = scores + state.ref_aligner.score_proposals(
            proposals, state.consensus, state.reference
        )
    return [
        ScoredProposal(p, float(s))
        for p, s in zip(proposals, scores)
        if s > state.score
    ]


def handle_candidates(
    candidates: List[ScoredProposal], state: RifrafState, params: RifrafParams
) -> None:
    """Apply the best compatible candidates, with single-best rollback
    (model.jl:898-935)."""
    old_consensus = state.consensus
    chosen = choose_candidates(candidates, params.min_dist)
    _log(params, 2,
         f"    found {len(candidates)} candidates; filtered to {len(chosen)}")
    state.consensus = apply_proposals(
        old_consensus, [c.proposal for c in chosen]
    )
    state.realign_As = True
    state.realign_Bs = False
    realign_rescore(state, params)
    if len(chosen) > 1 and (
        state.score < chosen[0].score or np.isclose(state.score, chosen[0].score)
    ):
        _log(params, 2, "    rejecting multiple candidates in favor of best")
        chosen = chosen[:1]
        state.consensus = apply_proposals(
            old_consensus, [c.proposal for c in chosen]
        )
    else:
        state.realign_As = False
    state.realign_Bs = True


def finish_stage(state: RifrafState, params: RifrafParams) -> None:
    """Stage transitions / penalty escalation / convergence
    (model.jl:937-995)."""
    _log(params, 2, f"    no candidates found in {state.stage.name}.")
    if state.stage == Stage.INIT:
        if state.reference is None or not params.do_frame:
            state.converged = True
        else:
            state.stage = Stage.FRAME
            _build_reference_scores(state, params)
            if not has_single_indels(state.consensus, state.reference):
                state.converged = True
    elif state.stage == Stage.FRAME:
        if not has_single_indels(state.consensus, state.reference):
            state.stage = Stage.REFINE
        elif state.n_ref_indel_mults == params.max_ref_indel_mults:
            _log(params, 2,
                 "    NOTE: alignment had single indels but reached penalty limit.")
            state.stage = Stage.REFINE
        else:
            state.penalties_increased = True
            state.n_ref_indel_mults += 1
            mult = params.ref_indel_mult ** state.n_ref_indel_mults
            state.ref_scores = Scores(
                mismatch=state.ref_scores.mismatch,
                insertion=state.ref_scores.insertion * mult,
                deletion=state.ref_scores.deletion * mult,
                codon_insertion=state.ref_scores.codon_insertion,
                codon_deletion=state.ref_scores.codon_deletion,
            )
            state.reference = state.reference.with_scores(state.ref_scores)
            _log(params, 2,
                 "    NOTE: alignment to reference had single indels. "
                 "increasing penalty.")
    elif state.stage == Stage.REFINE:
        state.converged = True
    else:
        raise RuntimeError(f"invalid stage: {state.stage}")


def _try_device_stage(
    state: RifrafState,
    params: RifrafParams,
    old_score: float,
    iters_left: int,
    consensus_stages,
    rng: np.random.Generator,
) -> Optional["object"]:
    """Run the remainder of the current stage as ONE device dispatch
    (engine.device_loop) when eligible; returns the StageResult or None
    for the host path. Bit-identical to the host loop by construction —
    the candidate tables, candidate gates (do_alignment_proposals edits,
    seed_indels anchors), tie order, min-dist filter, and rollback rule
    all match (tests/test_device_loop.py).

    Config-level declines are logged ONCE per (stage, reason) at
    verbose>=1, naming the disqualifying parameter, and recorded in
    state.stage_paths (surfaced in RifrafResult.metadata)."""
    if params.device_loop == "off":
        return None
    if params.device_loop == "auto":
        import jax

        if jax.default_backend() != "tpu":
            return None

    def decline(reason: str):
        key = (state.stage, reason)
        if key not in state.device_declines:
            state.device_declines.add(key)
            _log(params, 1,
                 f"device loop declined for {state.stage.name}: {reason}")
        # overwrite a plain "host" stamp from an earlier iteration: the
        # reason is the useful part (a later device success overwrites
        # this in turn)
        state.stage_paths[state.stage.name] = f"host ({reason})"
        return None

    if state.stage == Stage.FRAME:
        if state.reference is None or not state.ref_built:
            # transient: the reference scores are built on the
            # INIT->FRAME edge; not a configuration refusal
            return None
        if params.seed_indels:
            # the host computes indel seeds via _align_moves_routed: the
            # numpy engine below DEVICE_THRESHOLD, the codon device
            # engine above. The two break score TIES differently (the
            # repo only guarantees path-score equality), so the in-loop
            # seed gate — which always uses the device engine — is only
            # bit-identical to the host when every in-loop template
            # length stays in the device-routed regime. Drift inside the
            # loop is bounded by MAX_DRIFT before it bails.
            from ..ops.align_codon_jax import DEVICE_THRESHOLD
            from .device_loop import MAX_DRIFT

            if (len(state.consensus) - MAX_DRIFT < DEVICE_THRESHOLD
                    or len(state.reference) < DEVICE_THRESHOLD):
                return decline(
                    "seed_indels with consensus/reference below the "
                    "device alignment threshold (the host's numpy "
                    "aligner breaks score ties differently)"
                )
    elif state.stage not in (Stage.INIT, Stage.REFINE):
        return None
    if params.min_dist < 2:
        return decline(
            "min_dist < 2 (the vectorized apply needs separated anchors)"
        )
    if params.verbose >= 2:
        return decline("verbose >= 2 (per-iteration logging stays on host)")
    if params.mesh is not None:
        return decline("mesh is not None (the device loop is single-device)")
    # batching: a full batch always qualifies; a PARTIAL batch only under
    # batch_fixed INIT/FRAME — that selection is a deterministic stable
    # argsort (resample draws no rng), and within a fixed batch
    # check_score's growth branch needs a relative score DROP, which the
    # improving-only hill climb cannot produce mid-stage
    full_batch = state.batch_size >= len(state.sequences)
    fixed_partial = (
        params.batch_fixed and state.stage in (Stage.INIT, Stage.FRAME)
    )
    if not (full_batch or fixed_partial):
        return decline(
            "batch_size < n_reads without batch_fixed "
            "(stochastic per-iteration resampling)"
        )
    if state.aligner is None:
        # first iteration of the run builds the aligner on the host
        return None
    if not bool(state.aligner.fixed.all()):
        return decline("read bandwidths still adapting")
    # the selection resample would make this iteration (deterministic for
    # the eligible configs; draws no rng)
    resample(state, params, rng)
    if not _same_batch(state.aligner, state.batch_seqs):
        return decline("working batch differs from the aligner's batch")
    # stop_on_same mirrors check_score's stall exit EXACTLY: that branch
    # requires batch_size == len(sequences), so a fixed partial batch
    # must run with the stall check off
    if state.stage == Stage.FRAME:
        runner = state.aligner.stage_runner_frame(
            len(state.consensus),
            state.reference,
            indel_correction_only=params.indel_correction_only,
            min_dist=params.min_dist,
            history_cap=params.max_iters + 1,
            # after a penalty escalation the host's check_score skips
            # its stall test once (penalties_increased); the loop's
            # stop-on-same must not fire in its place
            stop_on_same=full_batch and not state.penalties_increased,
            seed_gate=params.seed_indels,
        )
    else:
        runner = state.aligner.stage_runner(
            len(state.consensus),
            do_indels=state.stage == Stage.INIT,
            min_dist=params.min_dist,
            history_cap=params.max_iters + 1,
            stop_on_same=full_batch,
            use_edits=params.do_alignment_proposals,
            speculate_k=params.speculate_k,
        )
    if runner is None:
        return decline(
            "no whole-stage step engine fits (panel-mode template or "
            "reference bandwidth unsettled)"
        )
    if params.speculate_k and not getattr(runner, "speculate_k", 0):
        # speculation requested but not engaged for this stage: surface
        # the reason once, decline-style, without leaving the device loop
        reason = (
            "speculation unsupported for FRAME (reference-scored rounds)"
            if state.stage == Stage.FRAME else
            "speculation declined (XLA shapes need read chunking or "
            "exceed the dense-block threshold)"
        )
        key = (state.stage, reason)
        if key not in state.device_declines:
            state.device_declines.add(key)
            _log(params, 1,
                 f"speculation declined for {state.stage.name}: {reason}")
    stage_idx = int(state.stage) - 1
    res = runner(
        state.consensus,
        old_score,
        iters_left=iters_left,
        prev_iters=int(state.stage_iterations[stage_idx]),
    )
    state.stage_paths[state.stage.name] = "device_loop"
    st = state.spec_stats.setdefault(
        state.stage.name,
        {"iterations": 0, "rounds": 0, "attempts": 0, "hits": 0},
    )
    st["iterations"] += res.n_iters
    # each verified hit served two counted iterations from one round
    st["rounds"] += res.n_iters - res.spec_hits
    st["attempts"] += res.spec_attempts
    st["hits"] += res.spec_hits
    spec_note = (
        f", speculation {res.spec_hits}/{res.spec_attempts} hits"
        if res.spec_attempts else ""
    )
    _log(params, 1,
         f"device stage {state.stage.name}: {res.n_iters} iterations, "
         f"score {res.score}{spec_note}")
    state.consensus = np.asarray(res.consensus, dtype=np.int8)
    state.score = res.score
    state.stage_iterations[stage_idx] += res.n_iters
    consensus_stages[stage_idx].extend(res.history)
    state.realign_As = True
    state.realign_Bs = True
    # the aligner's cached tables/bands describe mid-loop templates
    state.aligner._realign_key = None
    if res.completed:
        finish_stage(state, params)
    return res


def _speculation_metadata(state: RifrafState, params: RifrafParams) -> dict:
    """The RifrafResult.metadata["speculation"] block: per-stage
    iteration/round counts plus speculative-launch attempts and the
    verified hit-rate (each hit = one whole round, realign included,
    skipped). Present for every run — with speculate_k=0 it still
    reports the per-stage round counts, so serial and speculative runs
    compare field for field."""
    attempts = sum(st["attempts"] for st in state.spec_stats.values())
    hits = sum(st["hits"] for st in state.spec_stats.values())
    return {
        "enabled": params.speculate_k > 0,
        "k": params.speculate_k,
        "stages": {
            name: dict(st) for name, st in sorted(state.spec_stats.items())
        },
        "attempts": attempts,
        "hits": hits,
        "hit_rate": (hits / attempts) if attempts else 0.0,
    }


def normalize_log_differences(sub_scores, del_scores, ins_scores, state_score):
    """model.jl:720-735."""
    pos_scores = np.hstack([sub_scores, del_scores[:, None]])
    pos_exp = np.power(10.0, pos_scores)
    pos_probs = pos_exp / pos_exp.sum(axis=1, keepdims=True)
    ins_exp = np.power(10.0, ins_scores)
    ins_probs = ins_exp / (10.0 ** state_score + ins_exp.sum(axis=1, keepdims=True))
    return EstimatedProbs(
        sub=pos_probs[:, :4], dele=pos_probs[:, 4], ins=ins_probs
    )


def estimate_probs(state: RifrafState, params: RifrafParams) -> EstimatedProbs:
    """Per-base quality estimation: score every edit everywhere
    (model.jl:737-791)."""
    tlen = len(state.consensus)
    # all three tables start at the no-change score: a slot no proposal
    # covers must behave as "no edit" (= state.score), not 0.0 — a
    # positive 0.0 slot would trip the positivity check below if proposal
    # gating ever stops covering every insertion position
    sub_scores = np.zeros((tlen, 4)) + state.score
    del_scores = np.zeros(tlen) + state.score
    ins_scores = np.zeros((tlen + 1, 4)) + state.score

    uref = (
        state.reference is not None
        and len(state.reference) > 0
        and params.use_ref_for_qvs
    )
    tables = None if uref else state.aligner.dense_score_tables(tlen)
    if tables is not None:
        # the realign already shipped batch-total scores for EVERY
        # single-base edit: read the whole tables at once. SCORE-stage
        # proposals are exactly all non-identity subs + all indels
        # (generate.all_proposals), so only the identity-substitution
        # slots keep the no-change score
        sub_t, ins_t, del_t = tables
        sub_scores[:] = sub_t
        sub_scores[np.arange(tlen), state.consensus] = state.score
        del_scores[:] = del_t
        ins_scores[:] = ins_t
    else:
        proposals = all_proposals(Stage.SCORE, state.consensus, False)
        scores = state.aligner.score_proposals(proposals)
        if uref:
            scores = scores + state.ref_aligner.score_proposals(
                proposals, state.consensus, state.reference
            )
        for p, score in zip(proposals, scores):
            if isinstance(p, Substitution):
                sub_scores[p.pos, p.base] = score
            elif isinstance(p, Deletion):
                del_scores[p.pos] = score
            else:
                ins_scores[p.pos, p.base] = score
    max_score = max(sub_scores.max(), del_scores.max(), ins_scores.max())
    sub_scores -= max_score
    del_scores -= max_score
    ins_scores -= max_score
    if sub_scores.max() > 0.0 or del_scores.max() > 0.0 or ins_scores.max() > 0.0:
        raise RuntimeError("scores cannot be positive")
    return normalize_log_differences(
        sub_scores, del_scores, ins_scores, state.score - max_score
    )


def estimate_point_probs(probs: EstimatedProbs) -> np.ndarray:
    """Scalar per-base error summary (model.jl:793-802)."""
    pos_probs = np.hstack([probs.sub, probs.dele[:, None]])
    no_point_error_prob = pos_probs.max(axis=1)
    no_ins_error_prob = 1.0 - 0.5 * probs.ins.sum(axis=1)
    result = 1.0 - (
        no_point_error_prob * no_ins_error_prob[:-1] * no_ins_error_prob[1:]
    )
    return result


def base_distribution(base: int, ilp: float) -> np.ndarray:
    """model.jl:804-809."""
    lp = np.log10(1.0 - 10.0 ** ilp)
    result = np.full(4, lp - np.log10(3.0))
    result[base] = ilp
    return result


def alignment_error_probs(
    tlen: int, seqs: Sequence[ReadScores], tracebacks: Sequence[Sequence[int]]
) -> np.ndarray:
    """Pileup-based per-base error probabilities (model.jl:811-840).

    Vectorized over each read's whole move path (the reference walks
    move-by-move in a scalar loop; at 2048 reads x 1 kb that is ~4M
    Python iterations — here each read is three numpy scatters)."""
    probs = np.zeros((tlen, 4))
    # per-move (di, dj) lookup tables (OFFSETS as arrays)
    max_code = max(align_np.OFFSETS) + 1
    DI = np.zeros(max_code, np.int64)
    DJ = np.zeros(max_code, np.int64)
    for code, (di, dj) in align_np.OFFSETS.items():
        DI[code], DJ[code] = di, dj
    log3 = np.log10(3.0)
    for s, moves in zip(seqs, tracebacks):
        m = np.asarray(moves, dtype=np.int64)
        if m.size == 0:
            continue
        i = np.cumsum(DI[m])
        j = np.cumsum(DJ[m])
        sel = m == align_np.TRACE_MATCH
        ii = i[sel] - 1
        jj = j[sel] - 1
        base = s.seq[ii].astype(np.int64)
        ilp = s.match_scores[ii]
        other = np.log10(1.0 - np.power(10.0, ilp)) - log3
        # base_distribution: `other` in every column, `ilp` at the base
        np.add.at(probs, jj, other[:, None])
        np.add.at(probs, (jj, base), ilp - other)
    probs = np.power(10.0, probs)
    probs = 1.0 - (probs / probs.sum(axis=1, keepdims=True)).max(axis=1)
    return probs


def rifraf(
    dnaseqs: Sequence[np.ndarray],
    error_log_ps: Optional[Sequence[np.ndarray]] = None,
    phreds: Optional[Sequence[np.ndarray]] = None,
    consensus: Optional[np.ndarray] = None,
    reference: Optional[np.ndarray] = None,
    params: Optional[RifrafParams] = None,
) -> RifrafResult:
    """Find a consensus sequence for a set of reads (model.jl:1116-1287).

    `dnaseqs` are int8 code arrays (or DNA strings); provide either
    `error_log_ps` (log10 error probabilities) or `phreds`.
    """
    from ..utils.constants import encode_seq
    from .validate import validate_cluster

    _enable_compilation_cache()
    if params is None:
        params = RifrafParams()
    if error_log_ps is None and phreds is None:
        raise ValueError("provide error_log_ps or phreds")
    # typed validation pass BEFORE any encoding or device dispatch:
    # empty clusters, zero-length reads, seq/qual length mismatches,
    # out-of-range phreds, and non-ACGT bytes raise InvalidInputError
    # subclasses (ValueError-compatible) with record context
    validate_cluster(dnaseqs, phreds, error_log_ps, source="rifraf")
    dnaseqs = [encode_seq(s) if isinstance(s, str) else np.asarray(s, np.int8)
               for s in dnaseqs]
    if isinstance(reference, str):
        reference = encode_seq(reference)
    if isinstance(consensus, str):
        consensus = encode_seq(consensus)
    if error_log_ps is None:
        error_log_ps = [phred_to_log_p(p) for p in phreds]

    ref_len = 0 if reference is None else len(reference)
    check_params(params.scores, ref_len, params)

    sequences = [
        make_read_scores(s, p, params.bandwidth, params.scores)
        for s, p in zip(dnaseqs, error_log_ps)
    ]
    state = initial_state(consensus, sequences, reference, params)
    rng = np.random.default_rng(params.seed)

    enabled = set()
    if params.do_init:
        enabled.add(Stage.INIT)
    if params.do_frame:
        enabled.add(Stage.FRAME)
    if params.do_refine:
        enabled.add(Stage.REFINE)
    if params.do_score:
        enabled.add(Stage.SCORE)

    consensus_stages: List[List[np.ndarray]] = [[] for _ in range(len(Stage) - 1)]
    state.realign_As = True
    state.realign_Bs = True
    old_score = -np.inf
    timers = Timers()

    iterations_used = 0
    device_blocked = set()  # stages whose device loop bailed at entry
    while iterations_used < params.max_iters:
        while state.stage < Stage.SCORE and state.stage not in enabled:
            state.stage = next_stage(state.stage)
        if state.stage == Stage.SCORE:
            break
        res = None
        if state.stage not in device_blocked:
            with timers.time("device_stage"):
                res = _try_device_stage(
                    state, params, old_score,
                    params.max_iters - iterations_used, consensus_stages,
                    rng,
                )
            if res is not None and res.n_iters == 0 and not res.completed:
                # bailed before finishing one iteration (candidate
                # overflow / template drift): let the host loop own the
                # rest of this stage
                device_blocked.add(state.stage)
                state.stage_paths[state.stage.name] = (
                    "device_loop (bailed to host)"
                )
                res = None
        if res is not None:
            iterations_used += res.n_iters
            # resume value: equals res.score for a completed stage; for a
            # mid-stage bail it is what the aborted iteration saw, so the
            # host's stall check doesn't compare the score with itself
            old_score = res.old_score
            if state.converged:
                break
            continue
        iterations_used += 1
        iteration = iterations_used
        state.stage_iterations[int(state.stage) - 1] += 1
        state.stage_paths.setdefault(state.stage.name, "host")
        # host iterations are one scoring round each, never speculative
        host_st = state.spec_stats.setdefault(
            state.stage.name,
            {"iterations": 0, "rounds": 0, "attempts": 0, "hits": 0},
        )
        host_st["iterations"] += 1
        host_st["rounds"] += 1
        consensus_stages[int(state.stage) - 1].append(state.consensus.copy())
        _log(params, 1, f"iteration {iteration} : {state.stage.name} : {state.score}")
        # per-iteration consensus dump (model.jl:1164-1168)
        if params.verbose >= 3:
            _log(params, 3, f"  consensus: {decode_seq(state.consensus)}")
        else:
            _log(params, 2, f"  consensus length: {len(state.consensus)}")

        _log(params, 2, "  step: resample")
        with timers.time("resample"):
            resample(state, params, rng)
        with timers.time("realign_rescore"):
            realign_rescore(state, params)

        if check_score(state, params, old_score, rng):
            old_score = state.score
            state.penalties_increased = False
            if state.stage == Stage.FRAME and params.seed_indels:
                indel_seeds = single_indel_proposals(state.consensus, state.reference)
            else:
                indel_seeds = []
            with timers.time("get_candidates"):
                candidates = get_candidates(state, params, indel_seeds=indel_seeds)
            state.realign_As = True
            if candidates:
                _log(params, 2, "  step: handle candidates")
                with timers.time("handle_candidates"):
                    handle_candidates(candidates, state, params)
            else:
                finish_stage(state, params)
        else:
            finish_stage(state, params)
        if state.converged:
            break

        if (
            not params.batch_fixed
            or (
                state.stage == Stage.REFINE
                and state.stage_iterations[int(Stage.REFINE) - 1] > 1
            )
        ) and state.batch_size < len(state.sequences):
            state.batch_randomness *= params.batch_mult
            _log(params, 2,
                 f"  batch randomness decreased to {state.batch_randomness}")

    state.stage = Stage.SCORE
    result = RifrafResult(
        consensus=state.consensus,
        params=params,
        state=state,
        consensus_stages=consensus_stages,
        timers=timers,
        metadata={
            "stage_paths": dict(state.stage_paths),
            "declines": [
                {"stage": stage.name, "reason": reason}
                for stage, reason in sorted(
                    state.device_declines,
                    key=lambda kv: (int(kv[0]), kv[1]),
                )
            ],
            "speculation": _speculation_metadata(state, params),
        },
    )
    if params.do_score:
        _log(params, 2, "computing consensus quality scores")
        state.realign_As = True
        state.realign_Bs = True
        with timers.time("realign_rescore"):
            realign_rescore(state, params)
        with timers.time("estimate_probs"):
            result.error_probs = estimate_probs(state, params)
            result.aln_error_probs = alignment_error_probs(
                len(state.consensus), state.batch_seqs, state.aligner.tracebacks
            )
    # fold in the aligner's device-side section timers (fused dispatch,
    # packed fetch, traceback walk, table readouts)
    if state.aligner is not None:
        timers.merge(state.aligner.timers)
    if params.verbose >= 2:
        _log(params, 2, "timers:\n" + timers.summary())
    _log(params, 1, f"done. converged: {state.converged}")
    return result


def calibrate_phreds(
    seq: np.ndarray, phred: np.ndarray, consensus: np.ndarray
) -> np.ndarray:
    """Rescale error probs so expected #errors matches the edit distance
    (model.jl:1290-1300)."""
    n_errors = align_np.edit_distance(consensus, seq)
    errors = phred_to_p(phred)
    return errors * float(n_errors) / errors.sum()


def correct_shifts(
    consensus: np.ndarray,
    reference: np.ndarray,
    log_p: float = -1.0,
    bandwidth: int = -1,
    scores: Optional[Scores] = None,
) -> np.ndarray:
    """One-shot frameshift correction against a reference
    (model.jl:1302-1316)."""
    from ..models.errormodel import ErrorModel
    from ..utils.constants import encode_seq

    if isinstance(consensus, str):
        consensus = encode_seq(consensus)
    if isinstance(reference, str):
        reference = encode_seq(reference)
    if scores is None:
        scores = Scores.from_error_model(ErrorModel(10.0, 1e-5, 1e-5, 1.0, 1.0))
    log_ps = np.full(len(reference), log_p)
    if bandwidth < 0:
        bandwidth = int(np.ceil(min(len(consensus), len(reference)) * 0.1))
    refseq = make_read_scores(reference, log_ps, max(bandwidth, 1), scores)
    proposals = single_indel_proposals(consensus, refseq)
    return apply_proposals(consensus, proposals)
