"""Consensus edit proposals.

Mirrors /root/reference/src/proposals.jl with 0-based coordinates:

- ``Substitution(pos, base)`` replaces ``seq[pos]``.
- ``Insertion(pos, base)`` inserts ``base`` before index ``pos``
  (``pos == len(seq)`` appends). The reference's 1-based
  ``Insertion(pos)`` "insert after pos" maps to the same ``pos`` here.
- ``Deletion(pos)`` removes ``seq[pos]``.

``anchor()`` recovers the reference's shared 1-based coordinate used for
sorting, ambiguity, and minimum-distance filtering (proposals.jl:41-56,
91, 104-115), so those behaviors match exactly.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, NamedTuple, Sequence, Union

import numpy as np

from ..utils import debug


@dataclass(frozen=True)
class Substitution:
    pos: int
    base: int


@dataclass(frozen=True)
class Insertion:
    pos: int  # insert before this index; pos == len appends
    base: int


@dataclass(frozen=True)
class Deletion:
    pos: int


Proposal = Union[Substitution, Insertion, Deletion]


class ScoredProposal(NamedTuple):
    proposal: Proposal
    score: float


class AmbiguousProposalsError(Exception):
    pass


def anchor(p: Proposal) -> int:
    """The reference's shared 1-based position coordinate (proposals.jl)."""
    return p.pos if isinstance(p, Insertion) else p.pos + 1


def update_pos(p: Proposal, pos: int) -> Proposal:
    """proposals.jl:17-27."""
    if isinstance(p, Substitution):
        return Substitution(pos, p.base)
    if isinstance(p, Insertion):
        return Insertion(pos, p.base)
    return Deletion(pos)


def are_ambiguous(proposals: Sequence[Proposal]) -> bool:
    """At most one insertion per position and one substitution-or-deletion
    per position (proposals.jl:41-56)."""
    ins = [anchor(p) for p in proposals if isinstance(p, Insertion)]
    other = [anchor(p) for p in proposals if not isinstance(p, Insertion)]
    return len(set(ins)) != len(ins) or len(set(other)) != len(other)


def apply_proposals(seq: np.ndarray, proposals: Sequence[Proposal]) -> np.ndarray:
    """Apply a non-ambiguous proposal set in one pass (proposals.jl:80-102).

    Deletions sort before insertions at the same anchor; an insertion
    directly after a deletion knows not to re-emit the deleted base
    (proposals.jl:63-69, 87-98).
    """
    if are_ambiguous(proposals):
        raise AmbiguousProposalsError()
    seq = np.asarray(seq, dtype=np.int8)
    ordered = sorted(
        proposals, key=lambda p: (anchor(p), 0 if isinstance(p, Deletion) else 1)
    )
    parts: List[np.ndarray] = []
    n0 = 0
    last_del_anchor = 0
    for p in ordered:
        a = anchor(p)
        parts.append(seq[n0 : max(a - 1, 0)])
        if isinstance(p, Substitution):
            parts.append(np.array([p.base], dtype=np.int8))
        elif isinstance(p, Insertion):
            if a > 0 and last_del_anchor != a:
                parts.append(np.array([seq[a - 1], p.base], dtype=np.int8))
            else:
                parts.append(np.array([p.base], dtype=np.int8))
        else:
            last_del_anchor = a
        n0 = a
    parts.append(seq[n0:])
    out = np.concatenate(parts) if parts else seq.copy()
    # module-attribute lookup so the runtime toggle works; guard at the
    # call site because the condition itself costs a pass over proposals
    if debug.DEBUG:
        debug.myassert(
            len(out)
            == len(seq)
            + sum(isinstance(p, Insertion) for p in proposals)
            - sum(isinstance(p, Deletion) for p in proposals),
            "applied-proposal length mismatch",
        )
    return out


def choose_candidates(
    candidates: Sequence[ScoredProposal], min_dist: int
) -> List[ScoredProposal]:
    """Greedily keep top-scoring proposals at least min_dist apart
    (proposals.jl:104-115)."""
    final: List[ScoredProposal] = []
    posns: List[int] = []
    for c in sorted(candidates, key=lambda c: c.score, reverse=True):
        a = anchor(c.proposal)
        if any(abs(a - p) < min_dist for p in posns):
            continue
        posns.append(a)
        final.append(c)
    return final
