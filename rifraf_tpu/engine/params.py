"""Run parameters and stage machine.

Mirrors /root/reference/src/model.jl:1-5 (Stage), 97-164 (RifrafParams),
842-896 (check_params). TPU additions: dtype/bucketing knobs for the device
kernels and a backend selector absent from the reference.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from ..models.errormodel import ErrorModel, Scores
from ..utils.constants import CODON_LENGTH


class Stage(enum.IntEnum):
    INIT = 1  # no reference; all proposals
    FRAME = 2  # reference; indel proposals
    REFINE = 3  # no reference; substitutions
    SCORE = 4


def next_stage(s: Stage) -> Stage:
    return Stage(int(s) + 1)


DEFAULT_SCORES = Scores.from_error_model(ErrorModel(1.0, 2.0, 2.0, 0.0, 0.0))
DEFAULT_REF_SCORES = Scores.from_error_model(ErrorModel(10.0, 1e-1, 1e-1, 1.0, 1.0))


@dataclass
class RifrafParams:
    """All tunables, defaults matching model.jl:97-164."""

    scores: Scores = DEFAULT_SCORES
    ref_scores: Scores = DEFAULT_REF_SCORES
    # multiplier for single indel penalties in the reference alignment
    ref_indel_mult: float = 3.0
    max_ref_indel_mults: int = 5
    # multiplier for estimated reference error rate
    ref_error_mult: float = 1.0
    do_init: bool = True
    do_frame: bool = True
    do_refine: bool = True
    do_score: bool = False
    # only propose changes that occur in pairwise alignments
    do_alignment_proposals: bool = True
    # seed indel locations from the alignment to reference
    seed_indels: bool = True
    # only propose indels during frame correction stage
    indel_correction_only: bool = True
    # use reference alignment when estimating quality scores
    use_ref_for_qvs: bool = False
    bandwidth: int = 3 * CODON_LENGTH
    # p-value for increasing bandwidth
    bandwidth_pvalue: float = 0.1
    # distance between accepted candidate proposals
    min_dist: int = 5 * CODON_LENGTH
    # use top sequences for initial stage and frame correction
    batch_fixed: bool = True
    batch_fixed_size: int = 5
    # if <= 1, no batching is used
    batch_size: int = 20
    # 0: top n picked; 0.5: error-weighted; 1: uniform
    batch_randomness: float = 0.9
    batch_mult: float = 0.7
    # score threshold for increasing batch size
    batch_threshold: float = 0.1
    max_iters: int = 100
    verbose: int = 0
    # prefix for every verbose log line (TPU addition): the cluster sweep
    # runs jobs concurrently, so each job tags its lines with its input
    # file to keep interleaved stderr attributable
    log_prefix: str = ""

    # --- TPU-native additions (no reference equivalent) ---
    # float dtype for device kernels. None resolves per backend at run
    # time (resolve_dtype): float64 when jax x64 is enabled (the CPU /
    # exactness configuration, matching the reference bit-for-bit),
    # float32 otherwise (the TPU-native choice — TPUs have no f64, and an
    # explicit "float64" there would silently truncate)
    dtype: Optional[str] = None
    # random seed for batch resampling (the reference uses global RNG state)
    seed: Optional[int] = 42
    # pad template lengths up to multiples of this so consensus edits do not
    # trigger XLA recompilation
    len_bucket: int = 64
    # optional jax.sharding.Mesh with a "reads" axis: shard the read
    # dimension across devices so one consensus spans all chips, with
    # XLA-inserted psum over ICI for the score reductions (replaces the
    # reference's process-level pmap, scripts/rifraf.jl:190-191)
    mesh: Optional[object] = None
    # alignment-fill engine. "auto": realigns (score/tables, traceback
    # statistics, SCORE-stage moves) run the on-core Pallas fill+dense
    # kernels (ops.fill_pallas/dense_pallas; shard_map over the mesh's
    # read axis when one is given) when eligible (TPU, f32, sane
    # read-length spread, fits HBM — BatchAligner.pallas_eligible),
    # everything else the fused XLA scan step; "xla" forces the scan
    # path everywhere. The retired first-generation kernel lives on only
    # as exp/align_pallas_gen1.py.
    backend: str = "auto"
    # whole-stage device-resident hill-climb (engine.device_loop): run
    # each eligible INIT/REFINE/FRAME stage as ONE lax.while_loop
    # dispatch — one fetch per stage instead of per iteration.
    # do_alignment_proposals (INIT/REFINE) is handled by an in-kernel
    # edits gate over the dense candidate tables, and seed_indels
    # (FRAME) by a device-computed consensus-vs-reference anchor gate
    # (engages when the consensus/reference are long enough that the
    # host would route the seed alignment through the same device
    # engine). "auto": on when the stage qualifies (full batch or
    # batch_fixed's deterministic INIT/FRAME batch, min_dist >= 2,
    # settled bandwidths, verbose < 2, no mesh) AND the backend is a
    # real TPU (where the per-iteration fetch costs ~100 ms); "on":
    # also on CPU (the loop is backend-agnostic; used by equality
    # tests); "off": never. Config-level declines are logged once per
    # stage at verbose >= 1 and surfaced in RifrafResult.metadata
    # ["stage_paths"].
    device_loop: str = "auto"
    # HBM store dtype of the banded DP tables (forward/backward bands
    # and the megakernel's launch-private band scratch). "f32" (default)
    # is bit-identical to the oracle; "bf16" halves band bytes — every
    # max-plus accumulation, rescoring sum, and convergence total still
    # runs in f32 (store-narrow / accumulate-wide), so results are
    # accuracy-bounded, not bit-bounded (docs/api.md "Precision modes").
    band_dtype: str = "f32"
    # bandwidth-adaptation policy (engine.bandgrowth): "double" ports
    # the reference's blunt x2 growth; "adaptive" grows only reads
    # whose traceback path rides the band wall, by the measured deficit
    # on the 8-row K grid, entering at min(bandwidth, 16)
    band_growth: str = "double"
    # streamed-input wire format of the Pallas kernels (ops.encoding):
    # "f32" (default) ships the per-base score planes and read codes
    # exactly as built — bit-identical; "packed" packs bases 2-bit and
    # quantizes the four score planes to int8 against per-read
    # scale/offset pairs, decoded to f32 in-register at VMEM load
    # (error <= scale/2 per value; accuracy-gated like band_dtype,
    # docs/api.md "Input encoding"). Pallas-only: the XLA fallback,
    # panel, and mesh paths keep exact f32 inputs either way.
    input_enc: str = "f32"
    # speculative edit-set evaluation in the device stage loop
    # (engine.device_loop): 0 (default) is the legacy serial hill-climb,
    # bit-identical program and packed layout; 1 or 2 packs that many
    # speculative next-round composites as extra segments of every
    # scoring launch (ops.fused.fused_step_segmented) and skips a whole
    # round — realign included — whenever the replayed greedy rule lands
    # on one (verified against the winner's own dense tables, so the
    # final consensus is ALWAYS identical to the serial path). Device
    # loop / XLA-step only; Pallas-eligible stages route to the XLA
    # segmented step when speculating (ops.fused_pallas
    # .mega_segment_eligible declines multi-template blocks).
    speculate_k: int = 0


def resolve_dtype(dtype) -> np.dtype:
    """Resolve the device dtype: an explicit request wins; None picks
    float64 under jax x64 (exactness/CPU) and float32 otherwise (TPU)."""
    if dtype is not None:
        return np.dtype(dtype)
    import jax

    return np.dtype(np.float64 if jax.config.jax_enable_x64 else np.float32)


def validate_backend(backend: str, dtype, mesh) -> None:
    """Shared backend validation, enforced both at the driver boundary
    (check_params) and on direct BatchAligner construction so an explicit
    backend request can never silently fall back."""
    if backend == "pallas":
        # an explicit request asserts the on-core path is available;
        # "auto" falls back silently instead
        import os

        import jax

        if resolve_dtype(dtype) != np.float32:
            raise ValueError(
                "backend='pallas' requires float32 (the on-core kernels "
                "are f32; run with x64 disabled or dtype='float32')"
            )
        if jax.default_backend() != "tpu" and not os.environ.get(
            "RIFRAF_TPU_PALLAS_INTERPRET"
        ):
            raise ValueError(
                "backend='pallas' requires a TPU backend; on "
                f"{jax.default_backend()!r} use 'auto' or 'xla'"
            )
        return
    if backend not in ("auto", "xla"):
        raise ValueError(f"unknown backend: {backend!r}")


def check_params(scores: Scores, reference_len: int, params: RifrafParams) -> None:
    """model.jl:842-896."""
    for v in (scores.mismatch, scores.insertion, scores.deletion):
        if v >= 0.0 or v == -np.inf:
            raise ValueError("scores must be between -Inf and 0.0")
    if scores.codon_insertion > -np.inf or scores.codon_deletion > -np.inf:
        raise ValueError("error model cannot allow codon indels")
    if reference_len > 0:
        if params.ref_error_mult <= 0.0:
            raise ValueError("ref_error_mult must be > 0.0")
        if params.ref_indel_mult <= 0.0:
            raise ValueError("ref_indel_mult must be > 0.0")
        rs = params.ref_scores
        vals = (rs.mismatch, rs.insertion, rs.deletion, rs.codon_insertion,
                rs.codon_deletion)
        if any(v >= 0.0 for v in vals):
            raise ValueError("ref scores cannot be >= 0")
        if any(v == -np.inf for v in vals):
            raise ValueError("ref scores cannot be -Inf")
        if params.max_ref_indel_mults < 0:
            raise ValueError("ref_indel_increases must be >= 0")
    if not any([params.do_init, params.do_frame, params.do_refine, params.do_score]):
        raise ValueError("no stages enabled")
    if params.max_iters < 1:
        raise ValueError(f"invalid max iters: {params.max_iters}")
    if params.batch_fixed and params.batch_fixed_size <= 1:
        raise ValueError("batch_fixed_size must be > 1")
    if not (0.0 <= params.batch_randomness <= 1.0):
        raise ValueError("batch_randomness must be between 0.0 and 1.0")
    if not (0.0 <= params.batch_mult <= 1.0):
        raise ValueError("batch_mult must be between 0.0 and 1.0")
    if not (0.0 <= params.batch_threshold <= 1.0):
        raise ValueError("batch_threshold must be between 0.0 and 1.0")
    if params.device_loop not in ("auto", "on", "off"):
        raise ValueError(f"unknown device_loop: {params.device_loop!r}")
    if params.band_dtype not in ("f32", "bf16"):
        raise ValueError(
            f"band_dtype must be 'f32' or 'bf16', got {params.band_dtype!r}"
        )
    if params.speculate_k not in (0, 1, 2):
        raise ValueError(
            f"speculate_k must be 0, 1, or 2, got {params.speculate_k!r}"
        )
    from ..ops.encoding import check_input_enc
    from .bandgrowth import check_band_growth

    check_band_growth(params.band_growth)
    check_input_enc(params.input_enc)
    validate_backend(params.backend, params.dtype, params.mesh)
