"""FASTA/FASTQ I/O.

Pure-Python parser/writer mirroring /root/reference/src/fastxio.jl (which
wraps BioSequences' FASTA/FASTQ readers): sequences come back as int8 code
arrays, phreds as int8 arrays (Sanger offset 33), names default to
``seq_<i>``, and negative phreds are rejected (fastxio.jl:64-74).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..utils.constants import decode_seq, encode_seq

PHRED_OFFSET = 33


def read_fasta_records(filename: str) -> List[Tuple[str, str]]:
    """(name, sequence-string) pairs from a FASTA file (fastxio.jl:10-17)."""
    records: List[Tuple[str, str]] = []
    name: Optional[str] = None
    chunks: List[str] = []
    with open(filename) as fh:
        for line in fh:
            line = line.rstrip("\n")
            if not line:
                continue
            if line.startswith(">"):
                if name is not None:
                    records.append((name, "".join(chunks)))
                name = line[1:].split()[0] if len(line) > 1 else ""
                chunks = []
            else:
                if name is None:
                    raise ValueError(f"{filename}: sequence before header")
                chunks.append(line.strip())
    if name is not None:
        records.append((name, "".join(chunks)))
    return records


def read_fasta(filename: str) -> List[np.ndarray]:
    """fastxio.jl:20-31."""
    return [encode_seq(s) for _, s in read_fasta_records(filename)]


def write_fasta(
    filename: str, seqs: Sequence, names: Optional[Sequence[str]] = None
) -> None:
    """fastxio.jl:34-53."""
    if names is None or len(names) == 0:
        names = [f"seq_{i + 1}" for i in range(len(seqs))]
    with open(filename, "w") as fh:
        for name, seq in zip(names, seqs):
            if not isinstance(seq, str):
                seq = decode_seq(seq)
            fh.write(f">{name}\n{seq}\n")


def read_fastq(
    filename: str,
) -> Tuple[List[np.ndarray], List[np.ndarray], List[str]]:
    """Sequences, phreds, and names from a FASTQ file (fastxio.jl:56-98).

    Rejects negative phred values like the reference (fastxio.jl:66-69).
    """
    seqs: List[np.ndarray] = []
    phreds: List[np.ndarray] = []
    names: List[str] = []
    with open(filename) as fh:
        while True:
            header = fh.readline()
            if not header:
                break
            header = header.rstrip("\n")
            if not header:
                continue
            if not header.startswith("@"):
                raise ValueError(f"{filename}: bad FASTQ header {header!r}")
            seq = fh.readline().rstrip("\n")
            plus = fh.readline()
            qual = fh.readline().rstrip("\n")
            if not plus.startswith("+"):
                raise ValueError(f"{filename}: malformed FASTQ record")
            if len(qual) != len(seq):
                raise ValueError(f"{filename}: quality length mismatch")
            name = header[1:].split()[0] if len(header) > 1 else ""
            q = np.frombuffer(qual.encode("ascii"), dtype=np.uint8).astype(
                np.int16
            ) - PHRED_OFFSET
            if (q < 0).any():
                raise ValueError(
                    f"{name} in {filename} contains negative phred values"
                )
            seqs.append(encode_seq(seq))
            phreds.append(q.astype(np.int8))
            names.append(name)
    return seqs, phreds, names


def write_fastq(
    filename: str,
    seqs: Sequence,
    phreds: Sequence[np.ndarray],
    names: Optional[Sequence[str]] = None,
) -> None:
    """fastxio.jl:101-124."""
    if names is None or len(names) != len(seqs):
        names = [f"seq_{i + 1}" for i in range(len(seqs))]
    with open(filename, "w") as fh:
        for seq, q, name in zip(seqs, phreds, names):
            if not isinstance(seq, str):
                seq = decode_seq(seq)
            qual = "".join(chr(int(v) + PHRED_OFFSET) for v in q)
            fh.write(f"@{name}\n{seq}\n+\n{qual}\n")


def write_samples(prefix: str, reference, template, template_error, seqs, phreds) -> None:
    """Persist a simulated dataset (sample.jl:301-307)."""
    from ..utils.phred import p_to_phred

    template_phred = p_to_phred(np.asarray(template_error))
    write_fasta(f"{prefix}-reference.fasta", [reference])
    write_fastq(f"{prefix}-template.fastq", [template], [template_phred])
    write_fastq(f"{prefix}-sequences.fastq", seqs, phreds)


def read_samples(prefix: str):
    """Round-trip a simulated dataset (sample.jl:310-316)."""
    from ..utils.phred import phred_to_p

    reference = read_fasta(f"{prefix}-reference.fasta")[0]
    template_seqs, template_phreds, _ = read_fastq(f"{prefix}-template.fastq")
    template = template_seqs[0]
    template_error = phred_to_p(template_phreds[0])
    seqs, phreds, _ = read_fastq(f"{prefix}-sequences.fastq")
    return reference, template, template_error, seqs, phreds
