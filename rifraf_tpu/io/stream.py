"""Streaming FASTQ/JSONL ingestion front door.

``read_fastq`` is a batch loader with batch semantics: the first
malformed record raises and the whole file is lost. A serving spool or
a genome-scale run cannot afford that — one corrupt read in a
million-record file must cost one quarantined record, not the job.

This module is the tolerant counterpart. ``stream_fastq`` /
``stream_jsonl`` are generators that yield every well-formed record
and route every malformed one to a :class:`QuarantineWriter` sidecar
(``<name>.quarantine.jsonl``) with a typed reason — the same stable
codes as ``engine.validate`` (``malformed_record``, ``truncated``,
``length_mismatch``, ``phred_range``, ``bad_alphabet``,
``zero_length_read``) — so an operator can grep the sidecar, fix the
producer, and re-submit just the quarantined records. The parsers
never raise on input content; a process death can only come from the
environment (or an injected ``crash`` fault).

Truncation is a first-class state, not an error: a file being written
concurrently (serve ``--watch``) legitimately ends mid-record, so
``tolerate_tail=True`` swallows the partial tail silently for re-read
on the next poll, while the default quarantines it with reason
``truncated``. A gzip stream that ends before its end-of-stream marker
is the same case.

Chaos hook: each accepted record passes the ``ingest`` fault site
(``serve.faults``), so the chaos suite can inject parse failures and
truncation here like at any other pipeline stage.
"""

from __future__ import annotations

import gzip
import json
import os
import threading
import zlib
from typing import Iterable, Iterator, List, Optional, Tuple

import numpy as np

from ..engine.validate import (
    InvalidInputError,
    LengthMismatchError,
    PhredRangeError,
    validate_phreds,
    validate_seq,
)
from ..utils.constants import encode_seq
from .fastx import PHRED_OFFSET
from .journal import _fsync_dir

_RECORD_SNIPPET = 200  # bytes of the offending record kept in quarantine

# extensions the quarantine/journal path helpers strip so sidecars sit
# next to the input as <stem>.quarantine.jsonl / <stem>.journal.jsonl
_STRIP_EXTS = (".gz", ".fastq", ".fq", ".jsonl", ".json", ".fasta", ".fa")


def _stem(path: str) -> str:
    base = str(path)
    for ext in _STRIP_EXTS:
        if base.endswith(ext):
            base = base[: -len(ext)]
    return base


def quarantine_path_for(input_path: str) -> str:
    return _stem(input_path) + ".quarantine.jsonl"


def journal_path_for(input_path: str) -> str:
    return _stem(input_path) + ".journal.jsonl"


class QuarantineWriter:
    """Append-only JSONL sidecar of rejected records.

    Lazily opened (a clean file produces no sidecar), fsync'd per entry
    (the quarantine is the only copy of the bad record's identity), and
    counting by reason for BENCH/stats reporting."""

    def __init__(self, path: Optional[str]):
        self.path = path
        self._fh = None
        self._lock = threading.Lock()
        self.counts: dict = {}

    @property
    def n(self) -> int:
        return sum(self.counts.values())

    def write(self, *, reason: str, message: str = "",
              source: Optional[str] = None, index: Optional[int] = None,
              record: Optional[str] = None, **extra) -> None:
        entry = {"reason": reason, "message": message}
        if source is not None:
            entry["source"] = source
        if index is not None:
            entry["index"] = index
        if record is not None:
            entry["record"] = record[:_RECORD_SNIPPET]
        entry.update({k: v for k, v in extra.items() if v is not None})
        with self._lock:
            self.counts[reason] = self.counts.get(reason, 0) + 1
            if self.path is None:
                return
            if self._fh is None:
                self._fh = open(self.path, "ab")
                # the sidecar's directory entry must survive the same
                # crash its fsync'd records are protecting against
                _fsync_dir(self.path)
            self._fh.write((json.dumps(entry) + "\n").encode())
            self._fh.flush()
            os.fsync(self._fh.fileno())

    def close(self) -> None:
        with self._lock:
            if self._fh is not None and not self._fh.closed:
                self._fh.close()

    def __enter__(self) -> "QuarantineWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _fire_ingest(faults, quarantine: Optional[QuarantineWriter],
                 source: str, index: int, record: Optional[str]) -> bool:
    """Run the ingest fault site for one record. Returns True when an
    injected (recoverable) fault should quarantine the record; an
    injected crash (BaseException) propagates like a real process
    death."""
    if faults is None:
        return False
    try:
        faults.fire("ingest")
    except Exception as e:  # InjectedFaultError — crash variants pass through
        if quarantine is not None:
            quarantine.write(reason="injected_fault", message=str(e),
                             source=source, index=index, record=record)
        return True
    return False


class _Lines:
    """readline with a line counter, so quarantine entries can say where."""

    def __init__(self, fh):
        self._fh = fh
        self.lineno = 0

    def readline(self) -> str:
        ln = self._fh.readline()
        if ln:
            self.lineno += 1
        return ln


def _open_text(path: str):
    if str(path).endswith(".gz"):
        return gzip.open(path, "rt")
    return open(path)


def stream_fastq(path_or_fh, quarantine: Optional[QuarantineWriter] = None,
                 *, faults=None, tolerate_tail: bool = False,
                 source: Optional[str] = None,
                 ) -> Iterator[Tuple[str, np.ndarray, np.ndarray]]:
    """Yield ``(name, seq_codes, phreds)`` for every well-formed FASTQ
    record; malformed records go to ``quarantine`` with a typed reason.

    Never raises on input content — truncated blocks, CRLF endings,
    non-ACGT bases, bad quality strings, and mid-stream gzip EOF all
    become quarantine entries (or, for a truncated tail with
    ``tolerate_tail=True``, a silent stop so a concurrently-written
    file can be re-read on the next poll)."""
    own = isinstance(path_or_fh, (str, os.PathLike))
    src = source or (str(path_or_fh) if own else "<stream>")
    fh = _open_text(path_or_fh) if own else path_or_fh
    try:
        yield from _stream_fastq_fh(_Lines(fh), quarantine, faults,
                                    tolerate_tail, src)
    except (EOFError, OSError, zlib.error) as e:
        # gzip stream cut off before its end-of-stream marker (or the
        # underlying file vanished mid-read): the records already
        # yielded are good; the rest of the file is not an error state
        if not tolerate_tail and quarantine is not None:
            quarantine.write(reason="truncated",
                             message=f"stream ended mid-record: {e}",
                             source=src)
    finally:
        if own:
            fh.close()


def _stream_fastq_fh(lines: _Lines, quarantine, faults, tolerate_tail,
                     source):
    index = -1
    while True:
        header = lines.readline()
        if not header:
            return
        h = header.rstrip("\r\n")
        if not h:
            continue
        index += 1
        if not h.startswith("@"):
            if quarantine is not None:
                quarantine.write(reason="malformed_record",
                                 message=f"bad FASTQ header {h[:60]!r}",
                                 source=source, index=index, record=h,
                                 line=lines.lineno)
            continue
        block = [lines.readline() for _ in range(3)]
        if not block[-1]:
            # EOF inside the 4-line block: a truncated tail
            if not tolerate_tail and quarantine is not None:
                quarantine.write(reason="truncated",
                                 message="file ends mid-record",
                                 source=source, index=index, record=h,
                                 line=lines.lineno)
            return
        seq, plus, qual = (ln.rstrip("\r\n") for ln in block)
        # a header of '@' (or '@' + whitespace) has no name field
        parts = h[1:].split()
        name = parts[0] if parts else f"seq_{index + 1}"
        if not plus.startswith("+"):
            if quarantine is not None:
                quarantine.write(reason="malformed_record",
                                 message="missing '+' separator line",
                                 source=source, index=index, record=h,
                                 name=name, line=lines.lineno)
            continue
        try:
            validate_seq(seq, name=name, index=index, source=source)
            if len(qual) != len(seq):
                # empty quality strings land here too
                raise LengthMismatchError(
                    f"quality length {len(qual)} != sequence length "
                    f"{len(seq)} (read {name!r} in {source})",
                    qual_len=len(qual), seq_len=len(seq), name=name,
                    index=index, source=source)
            try:
                # strict: a non-ASCII quality byte is corrupt input and
                # must quarantine, not silently become phred 30 ('?')
                qbytes = qual.encode("ascii")
            except UnicodeEncodeError:
                raise PhredRangeError(
                    "non-ASCII quality character "
                    f"(read {name!r} in {source})",
                    name=name, index=index, source=source)
            q = np.frombuffer(qbytes,
                              dtype=np.uint8).astype(np.int16) - PHRED_OFFSET
            validate_phreds(q, len(seq), name=name, index=index,
                            source=source)
        except InvalidInputError as e:
            if quarantine is not None:
                quarantine.write(reason=e.code, message=str(e),
                                 source=source, index=index, record=h,
                                 name=name, line=lines.lineno)
            continue
        except Exception as e:
            # the module contract: NO content-derived error escapes the
            # parser (in serve --watch an escape kills the process)
            if quarantine is not None:
                quarantine.write(reason="malformed_record",
                                 message=f"{type(e).__name__}: {e}",
                                 source=source, index=index, record=h,
                                 name=name, line=lines.lineno)
            continue
        if _fire_ingest(faults, quarantine, source, index, h):
            continue
        yield name, encode_seq(seq), q.astype(np.int8)


def stream_jsonl(lines: Iterable[str],
                 quarantine: Optional[QuarantineWriter] = None,
                 *, faults=None, source: str = "<stream>",
                 ) -> Iterator[dict]:
    """Yield one parsed object per well-formed JSONL line; bad JSON and
    non-object lines are quarantined with reason ``malformed_record``
    instead of killing the stream."""
    for index, raw in enumerate(lines):
        ln = raw.strip()
        if not ln:
            continue
        try:
            obj = json.loads(ln)
        except ValueError as e:
            if quarantine is not None:
                quarantine.write(reason="malformed_record",
                                 message=f"invalid JSON: {e}",
                                 source=source, index=index, record=ln)
            continue
        if not isinstance(obj, dict):
            if quarantine is not None:
                quarantine.write(reason="malformed_record",
                                 message="JSONL line is not an object",
                                 source=source, index=index, record=ln)
            continue
        if _fire_ingest(faults, quarantine, source, index, ln):
            continue
        yield obj


def cluster_key(name: str) -> str:
    """Reads named ``<cluster>/<read>`` (PacBio/ONT convention) group by
    the prefix; undecorated names each form their own cluster."""
    return name.rsplit("/", 1)[0] if "/" in name else name


def group_clusters(records: Iterable[Tuple[str, np.ndarray, np.ndarray]],
                   ) -> Iterator[Tuple[str, List[np.ndarray],
                                       List[np.ndarray], List[str]]]:
    """Group a *sorted-by-cluster* record stream into consecutive
    clusters, yielding ``(cluster_name, seqs, phreds, names)`` as soon
    as each cluster's last read passes — streaming, no full-file
    buffering."""
    key: Optional[str] = None
    seqs: List[np.ndarray] = []
    phreds: List[np.ndarray] = []
    names: List[str] = []
    for name, seq, q in records:
        k = cluster_key(name)
        if key is not None and k != key:
            yield key, seqs, phreds, names
            seqs, phreds, names = [], [], []
        key = k
        seqs.append(seq)
        phreds.append(q)
        names.append(name)
    if key is not None:
        yield key, seqs, phreds, names
