from .fastx import (
    read_fasta,
    read_fasta_records,
    read_fastq,
    read_samples,
    write_fasta,
    write_fastq,
    write_samples,
)
from .journal import (
    Journal,
    JournalError,
    fingerprint,
    open_resumable,
    read_journal,
)
from .stream import (
    QuarantineWriter,
    cluster_key,
    group_clusters,
    journal_path_for,
    quarantine_path_for,
    stream_fastq,
    stream_jsonl,
)
