from .fastx import (
    read_fasta,
    read_fasta_records,
    read_fastq,
    read_samples,
    write_fasta,
    write_fastq,
    write_samples,
)
