"""Write-ahead results journal: crash-safe progress for long pipelines.

A genome-scale sweep or a spool-serving run is hours of device work; a
preemption (``kill -9``, OOM, node loss) must not forfeit the chunks
already computed. The journal is deliberately primitive — an
append-only JSONL file, one record per line, ``fsync``'d on every
append — because primitive is what survives: after ANY process death
the file is a prefix of the intended history, possibly with one torn
trailing line, and ``read_journal`` tolerates exactly that.

Users: ``parallel.sweep_clusters_sharded(journal_path=..., resume=...)``
journals one record per completed chunk (the per-cluster results, so a
resumed sweep re-emits them bit-identically without recomputing), and
the serve CLI journals completed request ids per spool file. Both pair
the records with a ``header`` record carrying a config fingerprint, so
a resume against different inputs/parameters is refused instead of
silently mixing results.

Journal grammar (one JSON object per line)::

    {"kind": "header", "fingerprint": "...", ...}   # first line
    {"kind": <record kind>, ..., "crc": <crc32>}    # appended per unit

Two corruption classes are distinguished on read: a TORN record (the
append a crash interrupted — incomplete JSON) is expected and dropped,
while an IN-PLACE corrupted record (complete JSON whose trailing
``crc`` field no longer matches its body — a flipped bit, a partial
overwrite) refuses the resume with a typed :class:`JournalError`
naming the record index: resuming past silently-altered history would
launder the corruption into results. Records without a ``crc`` field
(pre-CRC journals) still read — legacy journals stay resumable.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import zlib
from typing import List, Optional, Tuple


class JournalError(ValueError):
    """A journal that cannot be resumed against (fingerprint mismatch,
    header missing, unreadable, or an in-place corrupted record)."""

    code = "journal_mismatch"


def _fsync_dir(path: str) -> None:
    """fsync the directory containing ``path`` so the file's CREATION
    (its directory entry), not just its appended bytes, survives a
    crash immediately after open/rotate. Best-effort: platforms/
    filesystems without directory fsync are skipped silently."""
    d = os.path.dirname(os.path.abspath(path)) or "."
    try:
        fd = os.open(d, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _record_crc(record: dict) -> int:
    """CRC32 of a record's canonical serialization (the record WITHOUT
    its ``crc`` field, serialized exactly as append writes it)."""
    return zlib.crc32(json.dumps(record).encode())


def fingerprint(*parts) -> str:
    """Stable hex digest of a config/inputs description. Parts are
    stringified with repr — pass primitives, tuples, and lists only."""
    h = hashlib.sha256()
    for p in parts:
        h.update(repr(p).encode())
        h.update(b"\x00")
    return h.hexdigest()[:32]


def read_journal(path: str) -> Tuple[List[dict], bool]:
    """Load every complete record; a torn trailing line (the append the
    crash interrupted) is dropped, not an error. A COMPLETE record
    whose ``crc`` field does not match its body is in-place corruption
    — that raises :class:`JournalError` naming the record index
    (CRC-less legacy records are accepted as-is). Returns
    ``(records, torn)`` with the ``crc`` field stripped."""
    if not os.path.exists(path):
        return [], False
    records: List[dict] = []
    torn = False
    with open(path, "rb") as fh:
        data = fh.read()
    lines = data.split(b"\n")
    # a file that does not end with a newline has a torn tail; with one,
    # the final split element is empty
    tail = lines.pop() if lines else b""
    if tail.strip():
        torn = True
    for i, ln in enumerate(lines):
        ln = ln.strip()
        if not ln:
            continue
        try:
            rec = json.loads(ln)
        except ValueError:
            # a torn line mid-file means the bytes after it belong to a
            # different write epoch — stop trusting anything past it
            torn = True
            break
        if isinstance(rec, dict) and "crc" in rec:
            crc = rec.pop("crc")
            if _record_crc(rec) != crc:
                raise JournalError(
                    f"{path}: record {i} failed its CRC32 check — the "
                    "journal was corrupted in place (not a torn tail); "
                    "refusing to trust it (delete the journal to start "
                    "fresh)")
        records.append(rec)
    return records, torn


class Journal:
    """Append-only, fsync-per-append JSONL writer. Thread-safe: the
    sweep fleet appends from several worker threads."""

    def __init__(self, path: str, header: Optional[dict] = None,
                 resume: bool = False):
        """``resume=True`` appends to an existing file (after the caller
        validated its header); otherwise the file is truncated and
        ``header`` (with ``kind="header"``) is written first."""
        self.path = path
        self._lock = threading.Lock()
        mode = "ab" if (resume and os.path.exists(path)) else "wb"
        self._fh = open(path, mode)
        if mode == "wb":
            # the file's directory entry must be durable too: fsync'ing
            # appended bytes is useless if the file itself vanishes with
            # the crash
            _fsync_dir(path)
        if mode == "ab" and self._fh.tell() > 0:
            # the crash may have torn the final append; re-anchor at the
            # last complete line so the next record starts clean
            with open(path, "rb") as rf:
                data = rf.read()
            keep = data.rfind(b"\n") + 1
            if keep < len(data):
                self._fh.truncate(keep)
                self._fh.seek(keep)
        elif header is not None:
            self.append(dict(header, kind="header"))

    def append(self, record: dict) -> None:
        # trailing crc field over the record's own serialization: read
        # back, popping "crc" and re-serializing reproduces the exact
        # bytes (json round-trips its own output), so verify-on-read
        # catches in-place corruption, not just torn tails
        body = json.dumps(record)
        crc = zlib.crc32(body.encode())
        if body == "{}":
            line = f'{{"crc": {crc}}}\n'.encode()
        else:
            line = (body[:-1] + f', "crc": {crc}}}\n').encode()
        with self._lock:
            self._fh.write(line)
            self._fh.flush()
            os.fsync(self._fh.fileno())

    def close(self) -> None:
        with self._lock:
            if not self._fh.closed:
                self._fh.close()

    def __enter__(self) -> "Journal":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def open_resumable(path: str, header: dict, resume: bool
                   ) -> Tuple[Journal, List[dict]]:
    """The standard open protocol: validate + load prior records when
    resuming, start fresh otherwise.

    Returns ``(journal, prior_records)`` where ``prior_records`` is
    empty unless ``resume`` found a journal whose header fingerprint
    matches ``header["fingerprint"]``. A resume against a MISMATCHED
    fingerprint raises ``JournalError`` — recomputing is recoverable,
    silently mixing two configs' results is not."""
    prior: List[dict] = []
    if resume and os.path.exists(path):
        records, _torn = read_journal(path)
        if records:
            head = records[0]
            if (head.get("kind") != "header"
                    or "fingerprint" not in head):
                raise JournalError(
                    f"{path}: journal has no header record; refusing "
                    "to resume (delete it to start fresh)")
            if head["fingerprint"] != header.get("fingerprint"):
                raise JournalError(
                    f"{path}: journal fingerprint "
                    f"{head['fingerprint']!r} does not match this "
                    f"run's {header.get('fingerprint')!r} — inputs or "
                    "parameters changed; refusing to resume (delete "
                    "the journal to start fresh)")
            prior = records[1:]
            return Journal(path, resume=True), prior
    return Journal(path, header=header, resume=False), prior
