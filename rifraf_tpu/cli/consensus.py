"""Batch consensus CLI.

Mirrors /root/reference/scripts/rifraf.jl: a glob of FASTQ files, one
consensus each, FASTA out, with per-file reference lookup via a TSV map.
Where the reference fans files out over Julia worker processes with `pmap`
(scripts/rifraf.jl:190-191), this CLI runs the sweep through
rifraf_tpu.parallel.cluster.sweep_clusters: one worker thread per visible
device (override with --jobs), each pinning its clusters to a home device;
async XLA dispatch overlaps one cluster's host logic with another's device
fills, and compiled executables are shared across all workers.
"""

from __future__ import annotations

import argparse
import glob as globlib
import os
import sys
from typing import List, Optional

import numpy as np

from ..engine.driver import rifraf
from ..engine.params import RifrafParams
from ..io.fastx import read_fasta_records, read_fastq, write_fasta
from ..models.errormodel import ErrorModel, Scores
from ..utils.constants import encode_seq
from ..utils.phred import cap_phreds


def parse_error_model(spec: str) -> Scores:
    """Comma-separated ratio string -> Scores (scripts/rifraf.jl:98-102)."""
    parts = [float(x) for x in spec.split(",")]
    return Scores.from_error_model(ErrorModel(*parts))


def common_prefix(strings: List[str]) -> str:
    """scripts/rifraf.jl:122-133."""
    if not strings:
        return ""
    minlen = min(len(s) for s in strings)
    x = 0
    for i in range(minlen):
        if all(s[i] == strings[0][i] for s in strings):
            x = i + 1
        else:
            break
    return strings[0][:x]


def common_suffix(strings: List[str]) -> str:
    return common_prefix([s[::-1] for s in strings])[::-1]


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="rifraf-tpu",
        description="Compute consensus sequences from noisy phred-scored reads.",
    )
    p.add_argument("--phred-cap", type=int, default=0, help="maximum PHRED score")
    p.add_argument("--prefix", type=str, default="",
                   help="prepended to each filename to make name")
    p.add_argument("--keep-unique-name", action="store_true",
                   help="keep only unique middle part of filename")
    p.add_argument("--reference", type=str, default="",
                   help="reference fasta file; uses first sequence unless "
                        "--reference-map is given")
    p.add_argument("--reference-map", type=str, default="",
                   help="file mapping input filename to reference id")
    p.add_argument("--ref-errors", type=str, default="10,0.1,0.1,1,1",
                   help="comma-separated reference error ratios - "
                        "mm, ins, del, codon ins, codon del")
    p.add_argument("--max-iters", type=int, default=100)
    p.add_argument("--jobs", "-j", type=int, default=0,
                   help="concurrent consensus jobs; 0 = one per visible "
                        "device (the pmap fan-out of scripts/rifraf.jl)")
    p.add_argument("--sharded-sweep", action="store_true",
                   help="run ALL files' hill-climbs as one device program "
                        "(parallel.sweep_clusters_sharded), vmapped over "
                        "the cluster axis and sharded across every visible "
                        "device; no-reference runs only")
    p.add_argument("--cluster-chunk", type=int, default=0,
                   help="with --sharded-sweep: process the cluster axis in "
                        "sequential chunks of this size (bounds HBM); "
                        "0 = all at once")
    p.add_argument("--sweep-bucket", type=int, default=8,
                   help="with --sharded-sweep: shape-bucket the clusters "
                        "(read-count grid of this size) so heterogeneous "
                        "inputs compile per bucket instead of padding to "
                        "the global maxima; 0 = legacy uniform scheduler")
    p.add_argument("--journal", type=str, default="",
                   help="with --sharded-sweep: write-ahead results "
                        "journal (append-only JSONL, fsync'd per chunk) "
                        "so a killed run can be resumed with --resume")
    p.add_argument("--resume", action="store_true",
                   help="with --journal: skip chunks the journal records "
                        "as completed (outputs stay bit-identical to an "
                        "uninterrupted run; the journal's config "
                        "fingerprint must match)")
    p.add_argument("--tolerant", action="store_true",
                   help="stream FASTQ through the quarantine front door "
                        "(io.stream): malformed records land in "
                        "<stem>.quarantine.jsonl with a typed reason "
                        "instead of aborting the run")
    p.add_argument("--verbose", "-v", type=int, default=0)
    p.add_argument("seq_errors", metavar="seq-errors",
                   help="comma-separated sequence error ratios - "
                        "mismatch, insertion, deletion")
    p.add_argument("input", help="a single file or a glob; filenames must be unique")
    p.add_argument("output", help="output fasta file")
    return p


def read_fastq_tolerant(path: str, verbose: int = 0):
    """FASTQ via the quarantine front door: malformed records go to
    ``<stem>.quarantine.jsonl`` with a typed reason; only well-formed
    reads come back. Same (seqs, phreds, names) contract as
    ``read_fastq``."""
    from ..io.stream import (QuarantineWriter, quarantine_path_for,
                             stream_fastq)

    seqs, phreds, names = [], [], []
    with QuarantineWriter(quarantine_path_for(path)) as q:
        for name, s, p in stream_fastq(path, q):
            seqs.append(s)
            phreds.append(p)
            names.append(name)
        if verbose >= 1 and q.n:
            print(f"quarantined {q.n} record(s) from '{path}' "
                  f"({q.counts})", file=sys.stderr)
    return seqs, phreds, names


def dofile(path: str, reffile: str, refid: str, args,
           tag_logs: bool = False) -> "RifrafResult":
    """One consensus job (scripts/rifraf.jl:71-120). ``tag_logs`` prefixes
    every verbose line with the input filename (concurrent sweeps)."""
    prefix = f"[{os.path.basename(path)}] " if tag_logs else ""
    if args.verbose >= 1:
        # single atomic write, same tagging as the driver's _log: this line
        # interleaves with other workers' output in a concurrent sweep
        sys.stderr.write(f"{prefix}reading sequences from '{path}'\n")
    reference = None
    if reffile:
        ref_records = read_fasta_records(reffile)
        if refid:
            matches = [r for r in ref_records if r[0] == refid]
            if len(matches) == 0:
                raise ValueError(f"reference '{refid}' not found in '{reffile}'")
            if len(matches) > 1:
                raise ValueError(
                    f"multiple references with id '{refid}' found in '{reffile}'"
                )
            reference = encode_seq(matches[0][1])
        elif ref_records:
            reference = encode_seq(ref_records[0][1])

    scores = parse_error_model(args.seq_errors)
    ref_scores = parse_error_model(args.ref_errors)
    if getattr(args, "tolerant", False):
        sequences, phreds, _ = read_fastq_tolerant(path, args.verbose)
    else:
        sequences, phreds, _ = read_fastq(path)
    if args.phred_cap > 0:
        phreds = [cap_phreds(p, args.phred_cap) for p in phreds]
    params = RifrafParams(
        scores=scores,
        ref_scores=ref_scores,
        max_iters=args.max_iters,
        verbose=args.verbose,
        # concurrent sweep jobs tag their log lines with the input file
        log_prefix=prefix if args.verbose else "",
    )
    return rifraf(sequences, phreds=phreds, reference=reference, params=params)


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    infiles = sorted(globlib.glob(args.input))
    if not infiles:
        if args.verbose >= 1:
            print("warning: no input files found.", file=sys.stderr)
        return 0
    basenames = [os.path.basename(f) for f in infiles]
    if len(set(basenames)) != len(basenames):
        raise ValueError("Files do not have unique names")

    if args.reference:
        if not os.path.isfile(args.reference):
            raise ValueError("reference file not found")
        if args.reference_map and not os.path.isfile(args.reference_map):
            raise ValueError("reference map file not found")
    elif args.reference_map:
        raise ValueError("--reference-map is invalid without --reference")

    refids = [""] * len(infiles)
    if args.reference_map:
        name_to_ref = {}
        with open(args.reference_map) as fh:
            for line in fh:
                if line.strip():
                    name, refid = line.split()
                    name_to_ref[name] = refid
        infiles = sorted(
            f for f in infiles if os.path.basename(f) in name_to_ref
        )
        basenames = [os.path.basename(f) for f in infiles]
        refids = [name_to_ref[n] for n in basenames]

    if args.resume and not args.journal:
        raise ValueError("--resume needs --journal PATH")
    if args.journal and not args.sharded_sweep:
        raise ValueError("--journal is a --sharded-sweep feature (the "
                         "thread sweep has no chunk checkpoints)")
    if args.sharded_sweep:
        if args.reference:
            raise ValueError(
                "--sharded-sweep supports no-reference runs only (FRAME "
                "needs per-cluster reference state; use the default "
                "thread sweep)"
            )
        outcomes = _run_sharded_sweep(infiles, basenames, args)
    else:
        from ..parallel.cluster import resolve_jobs_flag, sweep_clusters

        n_workers = resolve_jobs_flag(args.jobs, len(infiles))
        if args.verbose >= 1 and n_workers > 1:
            print(f"sweeping {len(infiles)} files on {n_workers} workers",
                  file=sys.stderr)
        results = sweep_clusters(
            lambda job: dofile(job[0], args.reference, job[1], args,
                               tag_logs=n_workers > 1),
            list(zip(infiles, refids)),
            max_workers=n_workers,
        )
        outcomes = [
            (name, r.state.converged, r.consensus)
            for name, r in zip(basenames, results)
        ]

    plen = slen = 0
    if args.keep_unique_name:
        plen = len(common_prefix(basenames))
        snames = [n[plen:] for n in basenames]
        slen = len(common_suffix(snames))

    n_converged = 0
    out_seqs, out_names = [], []
    for name, converged, consensus in outcomes:
        if converged:
            n_converged += 1
            if args.keep_unique_name:
                name = name[plen : len(name) - slen]
            out_names.append(args.prefix + name)
            out_seqs.append(consensus)
    write_fasta(args.output, out_seqs, names=out_names)
    if args.verbose >= 1:
        print(f"done. {n_converged} / {len(outcomes)} converged.",
              file=sys.stderr)
    return 0


def _run_sharded_sweep(infiles: List[str], basenames: List[str], args):
    """Read every file's cluster and run all hill-climbs as ONE device
    program (BASELINE.json config 5, user-reachable via --sharded-sweep).
    Returns (name, converged, consensus) outcomes in input order."""
    from ..models.sequences import make_read_scores
    from ..parallel.sharding import make_mesh
    from ..parallel.sweep_sharded import sweep_clusters_sharded
    from ..utils.phred import phred_to_log_p

    import jax

    scores = parse_error_model(args.seq_errors)
    params = RifrafParams(scores=scores, max_iters=args.max_iters)
    clusters = []
    for path in infiles:
        if args.tolerant:
            sequences, phreds, _ = read_fastq_tolerant(path, args.verbose)
        else:
            sequences, phreds, _ = read_fastq(path)
        if args.phred_cap > 0:
            phreds = [cap_phreds(p, args.phred_cap) for p in phreds]
        clusters.append([
            make_read_scores(s, phred_to_log_p(p), params.bandwidth, scores)
            for s, p in zip(sequences, phreds)
        ])
    n_dev = len(jax.devices())
    mesh = make_mesh() if n_dev > 1 else None
    if args.verbose >= 1:
        print(
            f"sharded sweep: {len(clusters)} clusters over {n_dev} "
            "device(s), one program",
            file=sys.stderr,
        )
    results, stats = sweep_clusters_sharded(
        clusters, mesh=mesh, max_iters=args.max_iters,
        min_dist=params.min_dist,
        bandwidth_pvalue=params.bandwidth_pvalue,
        cluster_chunk=args.cluster_chunk,
        scheduler="bucketed" if args.sweep_bucket else "uniform",
        read_bucket=args.sweep_bucket or 8,
        do_alignment_proposals=params.do_alignment_proposals,
        return_stats=True,
        journal_path=args.journal,
        resume=args.resume,
    )
    if args.verbose >= 1:
        print(
            f"sharded sweep: {stats.n_buckets} bucket(s), "
            f"{stats.n_chunks} chunk(s), padding waste "
            f"{stats.waste:.1%} (uniform layout would pad "
            f"{stats.uniform_padded_cells / max(stats.padded_cells, 1):.2f}x"
            f" this), {stats.seconds:.2f}s",
            file=sys.stderr,
        )
        for b in stats.buckets:
            print(
                f"  bucket N={b.key[0]} L={b.key[1]} T={b.key[2]} "
                f"K0={b.key[3]}: {b.n_clusters} cluster(s) in "
                f"{b.n_chunks} chunk(s) of {b.gp}, occupancy "
                f"{b.occupancy:.2f}, waste {b.waste:.1%}, "
                f"{b.seconds:.2f}s",
                file=sys.stderr,
            )
    return [
        (name, r.converged, r.consensus)
        for name, r in zip(basenames, results)
    ]


if __name__ == "__main__":
    sys.exit(main())
