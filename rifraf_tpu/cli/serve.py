"""rifraf-serve: the online consensus service CLI.

Reads JSONL requests — one cluster per line — from stdin (default) or a
watched directory, serves them through ``serve.ConsensusServer``
(continuous micro-batching, deadlines, backpressure), and writes JSONL
responses in completion order.

Request line schema::

    {"id": "r1",                  # optional; generated when absent
     "seqs": ["ACGT...", ...],    # required, one string per read
     "phreds": [[20, 20, ...]],   # per-read phred ints ...
     "quals": ["IIII...", ...],   # ... or FASTQ quality strings
     "deadline_ms": 500}          # optional per-request deadline

Response line schema (``serve.Response.to_json_dict``)::

    {"id": "r1", "ok": true, "consensus": "ACGT...", "score": -12.3,
     "n_iters": 4, "converged": true, "latency_ms": 18.2,
     "path": "batched"}
    {"id": "r2", "ok": false, "error": "deadline_exceeded",
     "message": "...", "latency_ms": 501.0}

In ``--watch DIR`` mode, every ``*.jsonl`` file that appears in DIR is
served and answered to ``<name>.out.jsonl`` alongside it; files must be
complete when they appear (write elsewhere and rename in). ``--stats``
prints the server's metrics snapshot (queue depth, batch occupancy,
padding waste, latency percentiles, timers) as JSON to stderr on exit.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
from collections import deque
from concurrent.futures import TimeoutError as FutureTimeoutError
from typing import List, Optional

import numpy as np

from ..serve import (
    ConsensusServer,
    QueueFullError,
    ServeConfig,
    ServeError,
    encode_cluster,
)
from ..utils.phred import cap_phreds
from .consensus import parse_error_model


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="rifraf-serve",
        description="Online consensus service: JSONL requests in, "
                    "JSONL responses out.",
    )
    p.add_argument("--input", default="-",
                   help="JSONL request file, '-' for stdin (default)")
    p.add_argument("--output", default="-",
                   help="JSONL response file, '-' for stdout (default)")
    p.add_argument("--watch", default="",
                   help="serve *.jsonl files appearing in this directory "
                        "instead of --input; responses go to "
                        "<name>.out.jsonl next to each input")
    p.add_argument("--watch-once", action="store_true",
                   help="with --watch: serve the files present now, then "
                        "exit (instead of polling forever)")
    p.add_argument("--watch-poll-ms", type=float, default=200.0,
                   help="with --watch: directory poll interval")
    p.add_argument("--seq-errors", default="",
                   help="comma-separated sequence error ratios "
                        "(mismatch, insertion, deletion); default scores "
                        "when omitted")
    p.add_argument("--phred-cap", type=int, default=0,
                   help="maximum PHRED score (0 = no cap)")
    p.add_argument("--max-iters", type=int, default=100)
    p.add_argument("--alignment-proposals", action="store_true",
                   help="use the full single-indel proposal pass instead "
                        "of the seeded edits gate")
    p.add_argument("--max-batch", type=int, default=16,
                   help="micro-batch occupancy flush threshold")
    p.add_argument("--max-wait-ms", type=float, default=20.0,
                   help="micro-batch latency flush threshold")
    p.add_argument("--max-queue", type=int, default=256,
                   help="bounded admission queue size (backpressure)")
    p.add_argument("--workers", type=int, default=1,
                   help="device-parallel fleet size: this many worker "
                        "threads share the flush queue, each pinned to "
                        "one device round-robin over jax.devices() "
                        "(compiled programs and the persistent cache "
                        "are shared, so the bucket grid warms once)")
    p.add_argument("--deadline-ms", type=float, default=0.0,
                   help="default per-request deadline applied to requests "
                        "without their own (0 = none)")
    p.add_argument("--warmup-file", default="",
                   help="JSONL file of example requests whose shape "
                        "buckets are pre-traced before serving")
    p.add_argument("--faults", default="",
                   help="fault-injection spec (serve/faults.py grammar, "
                        "e.g. 'dispatch:error:n=2'); overrides the "
                        "RIFRAF_TPU_FAULTS env var")
    p.add_argument("--stats", action="store_true",
                   help="print the metrics snapshot (including the "
                        "supervision health block) as JSON to stderr "
                        "on exit")
    p.add_argument("--verbose", "-v", type=int, default=0)
    return p


def config_from_args(args) -> ServeConfig:
    kw = dict(
        max_batch=args.max_batch,
        max_wait_ms=args.max_wait_ms,
        max_queue=args.max_queue,
        max_iters=args.max_iters,
        do_alignment_proposals=args.alignment_proposals,
        n_workers=max(1, args.workers),
    )
    if args.seq_errors:
        kw["scores"] = parse_error_model(args.seq_errors)
    if args.faults:
        kw["faults"] = args.faults
    return ServeConfig(**kw)


def parse_request(obj: dict, args, config: ServeConfig):
    """One decoded request object -> (cluster, deadline_ms). Raises
    ValueError on malformed input."""
    seqs = obj.get("seqs")
    if not seqs:
        raise ValueError("request needs a non-empty 'seqs' list")
    if "phreds" in obj:
        phreds = [np.asarray(p, float) for p in obj["phreds"]]
    elif "quals" in obj:
        phreds = [
            np.asarray([ord(c) - 33 for c in q], float)
            for q in obj["quals"]
        ]
    else:
        raise ValueError("request needs 'phreds' or 'quals'")
    if len(phreds) != len(seqs):
        raise ValueError("'seqs' and quality lists differ in length")
    if args.phred_cap > 0:
        phreds = [cap_phreds(p, args.phred_cap) for p in phreds]
    cluster = encode_cluster(seqs, phreds=phreds, config=config)
    deadline_ms = obj.get("deadline_ms")
    if deadline_ms is None and args.deadline_ms > 0:
        deadline_ms = args.deadline_ms
    return cluster, deadline_ms


class _Emitter:
    """Serialized completion-order JSONL writer (future callbacks fire
    on server threads)."""

    def __init__(self, fh):
        self.fh = fh
        self.lock = threading.Lock()

    def emit(self, obj: dict) -> None:
        with self.lock:
            self.fh.write(json.dumps(obj) + "\n")
            self.fh.flush()

    def emit_response(self, fut) -> None:
        self.emit(fut.result().to_json_dict())


def serve_stream(lines, server: ConsensusServer, emitter: _Emitter,
                 args, config: ServeConfig) -> int:
    """Submit every JSONL line, riding backpressure; responses stream
    out via future callbacks. Returns the number of requests admitted."""
    inflight: deque = deque()
    n = 0
    for i, line in enumerate(lines):
        line = line.strip()
        if not line:
            continue
        rid = None
        try:
            obj = json.loads(line)
            rid = obj.get("id")  # kept even when the rest is malformed
            cluster, deadline_ms = parse_request(obj, args, config)
        except (ValueError, KeyError, TypeError) as e:
            emitter.emit({"id": rid or f"line{i}", "ok": False,
                          "error": "bad_request", "message": str(e)})
            continue
        t0 = time.perf_counter()
        wait_s = server.config.result_timeout_s
        while True:
            try:
                fut = server.submit(cluster, request_id=rid,
                                    deadline_ms=deadline_ms)
                break
            except QueueFullError as e:
                # backpressure: wait out the oldest in-flight request —
                # but bounded, so a dead pipeline (which never frees
                # the queue) surfaces as a typed response, not a hang
                if time.perf_counter() - t0 > wait_s:
                    fut = None
                    emitter.emit({"id": rid or f"line{i}", "ok": False,
                                  "error": e.code, "message": str(e)})
                    break
                if inflight:
                    try:
                        inflight.popleft().result(timeout=1.0)
                    except FutureTimeoutError:
                        pass
                else:
                    time.sleep(1e-3)
            except ServeError as e:
                fut = None
                emitter.emit({"id": rid or f"line{i}", "ok": False,
                              "error": e.code, "message": str(e)})
                break
        if fut is not None:
            inflight.append(fut)
            fut.add_done_callback(emitter.emit_response)
            n += 1
    while inflight:
        try:
            inflight.popleft().result(
                timeout=server.config.result_timeout_s)
        except FutureTimeoutError:
            # dead pipeline: stop waiting — close() resolves every
            # abandoned future (ServerClosedError), and the done
            # callbacks emit those responses, so no request goes
            # unanswered
            break
    return n


def _warmup(server: ConsensusServer, path: str, args,
            config: ServeConfig, verbose: int) -> None:
    examples = []
    with open(path) as fh:
        for line in fh:
            if line.strip():
                cluster, _ = parse_request(json.loads(line), args, config)
                examples.append(cluster)
    t0 = time.perf_counter()
    n = server.warmup(examples, batch_sizes=(1, config.max_batch))
    if verbose >= 1:
        print(
            f"warmup: {n} executable(s) traced in "
            f"{time.perf_counter() - t0:.1f}s",
            file=sys.stderr,
        )


def _run_watch(server: ConsensusServer, args,
               config: ServeConfig) -> None:
    done = set()
    while True:
        fresh = sorted(
            f for f in os.listdir(args.watch)
            if f.endswith(".jsonl") and not f.endswith(".out.jsonl")
            and f not in done
        )
        for name in fresh:
            path = os.path.join(args.watch, name)
            out_path = path[: -len(".jsonl")] + ".out.jsonl"
            if args.verbose >= 1:
                print(f"serving '{path}' -> '{out_path}'",
                      file=sys.stderr)
            with open(path) as infh, open(out_path, "w") as outfh:
                serve_stream(infh, server, _Emitter(outfh), args, config)
            done.add(name)
        if args.watch_once:
            return
        time.sleep(args.watch_poll_ms / 1e3)


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    config = config_from_args(args)
    server = ConsensusServer(config)
    try:
        if args.warmup_file:
            _warmup(server, args.warmup_file, args, config, args.verbose)
        if args.watch:
            _run_watch(server, args, config)
        else:
            infh = sys.stdin if args.input == "-" else open(args.input)
            outfh = (sys.stdout if args.output == "-"
                     else open(args.output, "w"))
            try:
                n = serve_stream(infh, server, _Emitter(outfh), args,
                                 config)
                if args.verbose >= 1:
                    print(f"served {n} request(s)", file=sys.stderr)
            finally:
                if infh is not sys.stdin:
                    infh.close()
                if outfh is not sys.stdout:
                    outfh.close()
    except KeyboardInterrupt:
        pass
    finally:
        server.close()
        if args.stats:
            print(json.dumps(server.snapshot(), indent=2),
                  file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
