"""rifraf-serve: the online consensus service CLI.

Reads JSONL requests — one cluster per line — from stdin (default) or a
watched directory, serves them through ``serve.ConsensusServer``
(continuous micro-batching, deadlines, backpressure), and writes JSONL
responses in completion order.

Request line schema::

    {"id": "r1",                  # optional; generated when absent
     "seqs": ["ACGT...", ...],    # required, one string per read
     "phreds": [[20, 20, ...]],   # per-read phred ints ...
     "quals": ["IIII...", ...],   # ... or FASTQ quality strings
     "deadline_ms": 500}          # optional per-request deadline

Response line schema (``serve.Response.to_json_dict``)::

    {"id": "r1", "ok": true, "consensus": "ACGT...", "score": -12.3,
     "n_iters": 4, "converged": true, "latency_ms": 18.2,
     "path": "batched"}
    {"id": "r2", "ok": false, "error": "deadline_exceeded",
     "message": "...", "latency_ms": 501.0}

In ``--watch DIR`` mode, every ``*.jsonl`` (requests) or
``*.fastq``/``*.fq`` (raw reads, clustered by the ``<cluster>/<read>``
name convention via the ``io.stream`` front door) file that appears in
DIR is served and answered to ``<stem>.out.jsonl`` alongside it.
Files may be written in place: dotfiles and ``*.tmp`` are ignored, a
file is only read once its size is stable across two polls, and a
trailing partial JSONL line is tolerated — its complete lines are
served and the tail re-read on the next poll (a tail that never
completes is quarantined as ``truncated``). Malformed records land in
``<stem>.quarantine.jsonl`` with a typed reason instead of killing the
process.

Durability: watch mode write-ahead journals every completed request id
to ``<stem>.journal.jsonl`` (fsync'd per response, ``io.journal``
format); ``--resume`` replays the journals after a crash — ``kill -9``
included — so completed requests are skipped and their files' outputs
appended, not recomputed. Journals are fingerprinted against the serve
config (error model, phred cap, deadline) and the spool file's content
head, so a file rewritten under the same name or served under a
different configuration is re-served from scratch rather than matched
to its stale journal. ``--resume`` with ``--input FILE`` journals
to the same sidecar next to FILE.

``--stats`` prints the server's metrics snapshot (queue depth, batch
occupancy, padding waste, latency percentiles, timers, quarantine
counts) as JSON to stderr on exit.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys
import threading
import time
from collections import deque
from concurrent.futures import TimeoutError as FutureTimeoutError
from typing import List, Optional

import numpy as np

from ..serve import (
    ConsensusServer,
    QueueFullError,
    ServeConfig,
    ServeError,
    encode_cluster,
)
from ..utils.phred import cap_phreds
from .consensus import parse_error_model


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="rifraf-serve",
        description="Online consensus service: JSONL requests in, "
                    "JSONL responses out.",
    )
    p.add_argument("--input", default="-",
                   help="JSONL request file, '-' for stdin (default)")
    p.add_argument("--output", default="-",
                   help="JSONL response file, '-' for stdout (default)")
    p.add_argument("--watch", default="",
                   help="serve *.jsonl (requests) and *.fastq/*.fq (raw "
                        "reads) files appearing in this directory instead "
                        "of --input; responses go to <stem>.out.jsonl, "
                        "malformed records to <stem>.quarantine.jsonl, "
                        "completed request ids to <stem>.journal.jsonl")
    p.add_argument("--watch-once", action="store_true",
                   help="with --watch: serve the files present now, then "
                        "exit (instead of polling forever)")
    p.add_argument("--watch-poll-ms", type=float, default=200.0,
                   help="with --watch: directory poll interval")
    p.add_argument("--resume", action="store_true",
                   help="replay <stem>.journal.jsonl sidecars: skip "
                        "request ids already completed by a previous "
                        "(possibly killed) run and append to their "
                        "outputs instead of recomputing")
    p.add_argument("--seq-errors", default="",
                   help="comma-separated sequence error ratios "
                        "(mismatch, insertion, deletion); default scores "
                        "when omitted")
    p.add_argument("--phred-cap", type=int, default=0,
                   help="maximum PHRED score (0 = no cap)")
    p.add_argument("--max-iters", type=int, default=100)
    p.add_argument("--band-dtype", default="f32",
                   choices=("f32", "bf16"),
                   help="band-table storage precision: f32 is "
                        "bit-identical to the reference; bf16 halves "
                        "band HBM traffic (accumulation stays f32, see "
                        "docs/api.md Precision modes)")
    p.add_argument("--band-growth", default="double",
                   choices=("double", "adaptive"),
                   help="bandwidth adaptation policy: double the "
                        "flagged reads' bands (reference), or grow "
                        "each read by its measured band-edge deficit "
                        "(adaptive; smaller settled bands)")
    p.add_argument("--input-enc", default="f32",
                   choices=("f32", "packed"),
                   help="streamed-input encoding: f32 ships score "
                        "planes exactly; packed packs bases 2-bit and "
                        "quantizes score planes to int8 for the Pallas "
                        "kernels (accuracy-gated; the serve device "
                        "programs themselves stay exact — the value "
                        "keys program caches and the resume "
                        "fingerprint, see docs/api.md Input encoding)")
    p.add_argument("--speculate-k", type=int, default=0,
                   choices=(0, 1, 2),
                   help="speculative edit-set evaluation: score this "
                        "many next-round composites alongside every "
                        "refine round in one segmented launch and skip "
                        "a round on a verified hit (results stay "
                        "bit-identical; 0 = serial hill-climb, see "
                        "docs/api.md Speculative refinement)")
    p.add_argument("--alignment-proposals", action="store_true",
                   help="use the full single-indel proposal pass instead "
                        "of the seeded edits gate")
    p.add_argument("--max-batch", type=int, default=16,
                   help="micro-batch occupancy flush threshold")
    p.add_argument("--max-wait-ms", type=float, default=20.0,
                   help="micro-batch latency flush threshold")
    p.add_argument("--max-queue", type=int, default=256,
                   help="bounded admission queue size (backpressure)")
    p.add_argument("--workers", type=int, default=1,
                   help="device-parallel fleet size: this many worker "
                        "threads share the flush queue, each pinned to "
                        "one device round-robin over jax.devices() "
                        "(compiled programs and the persistent cache "
                        "are shared, so the bucket grid warms once)")
    p.add_argument("--min-workers", type=int, default=0,
                   help="elastic fleet floor: with --max-workers set, "
                        "the supervisor never drains below this many "
                        "workers (0 = 1)")
    p.add_argument("--max-workers", type=int, default=0,
                   help="elastic fleet ceiling: 0 (default) disables "
                        "autoscaling; otherwise the supervisor adds "
                        "workers on queue pressure (depth or "
                        "time-in-queue) and gracefully drains idle ones "
                        "back down (parked/quarantined slots never "
                        "count toward the target)")
    p.add_argument("--shed", action="store_true",
                   help="deadline-aware load shedding: reject a request "
                        "at admission (typed 'shedded' error with a "
                        "retry-after hint) when the estimated queue "
                        "service time already exceeds its deadline")
    p.add_argument("--aot-cache", default="",
                   help="persisted AOT executable cache: 'default' for "
                        "the fingerprinted per-machine directory "
                        "(~/.cache/rifraf_tpu_aot), a path, or empty "
                        "(default) to fall back to the "
                        "RIFRAF_TPU_AOT_CACHE env var; a warmed "
                        "process exports each compiled program so cold "
                        "restarts load executables from disk instead "
                        "of re-tracing")
    p.add_argument("--deadline-ms", type=float, default=0.0,
                   help="default per-request deadline applied to requests "
                        "without their own (0 = none)")
    p.add_argument("--warmup-file", default="",
                   help="JSONL file of example requests whose shape "
                        "buckets are pre-traced before serving")
    p.add_argument("--faults", default="",
                   help="fault-injection spec (serve/faults.py grammar, "
                        "e.g. 'dispatch:error:n=2'); overrides the "
                        "RIFRAF_TPU_FAULTS env var")
    p.add_argument("--guard", action="store_true",
                   help="on-device numerical sentinels: flag NaN/Inf/"
                        "sentinel-underflow in band tables and scores "
                        "per launch (result-integrity layer)")
    p.add_argument("--verify-fraction", type=float, default=0.0,
                   help="shadow-verify this fraction of completed "
                        "results (deterministic content-digest sample) "
                        "on the independent oracle path; a divergence "
                        "is counted, quarantines the device, and the "
                        "oracle result replaces the bad answer")
    p.add_argument("--quarantine-threshold", type=int, default=2,
                   help="integrity trips (guard + divergence) per "
                        "device before it is evicted from the "
                        "round-robin pending a clean golden probe "
                        "(0 disables eviction)")
    p.add_argument("--stats", action="store_true",
                   help="print the metrics snapshot (including the "
                        "supervision health block) as JSON to stderr "
                        "on exit")
    p.add_argument("--verbose", "-v", type=int, default=0)
    return p


def config_from_args(args) -> ServeConfig:
    kw = dict(
        max_batch=args.max_batch,
        max_wait_ms=args.max_wait_ms,
        max_queue=args.max_queue,
        max_iters=args.max_iters,
        do_alignment_proposals=args.alignment_proposals,
        n_workers=max(1, args.workers),
        min_workers=max(0, args.min_workers),
        max_workers=max(0, args.max_workers),
        shed=args.shed,
        band_dtype=args.band_dtype,
        band_growth=args.band_growth,
        input_enc=args.input_enc,
        speculate_k=args.speculate_k,
        guard=args.guard,
        verify_fraction=args.verify_fraction,
        quarantine_threshold=args.quarantine_threshold,
    )
    if args.aot_cache:
        kw["aot_cache"] = args.aot_cache
    if args.seq_errors:
        kw["scores"] = parse_error_model(args.seq_errors)
    if args.faults:
        kw["faults"] = args.faults
    return ServeConfig(**kw)


def parse_request(obj: dict, args, config: ServeConfig):
    """One decoded request object -> (cluster, deadline_ms). Raises
    ValueError on malformed input."""
    seqs = obj.get("seqs")
    if not seqs:
        raise ValueError("request needs a non-empty 'seqs' list")
    if "phreds" in obj:
        phreds = [np.asarray(p, float) for p in obj["phreds"]]
    elif "quals" in obj:
        phreds = [
            np.asarray([ord(c) - 33 for c in q], float)
            for q in obj["quals"]
        ]
    else:
        raise ValueError("request needs 'phreds' or 'quals'")
    if len(phreds) != len(seqs):
        raise ValueError("'seqs' and quality lists differ in length")
    if args.phred_cap > 0:
        phreds = [cap_phreds(p, args.phred_cap) for p in phreds]
    cluster = encode_cluster(seqs, phreds=phreds, config=config)
    deadline_ms = obj.get("deadline_ms")
    if deadline_ms is None and args.deadline_ms > 0:
        deadline_ms = args.deadline_ms
    return cluster, deadline_ms


class _Emitter:
    """Serialized completion-order JSONL writer (future callbacks fire
    on server threads). With a journal attached, every OK response's id
    is journaled AFTER its output line is durably written — so a resume
    never skips a request whose output the crash swallowed."""

    def __init__(self, fh, journal=None, on_ok=None, on_emit=None):
        self.fh = fh
        self.journal = journal
        self.on_ok = on_ok  # called with the id of each OK response
        self.on_emit = on_emit  # called with EVERY emitted response's id
        self.lock = threading.Lock()
        # future.result() returns once the result is SET, but the done
        # callback that emits it runs afterwards on a server thread —
        # so sinks may only be closed after drain() confirms every
        # registered emission actually happened
        self._cv = threading.Condition()
        self._pending = 0

    def expect(self) -> None:
        """Register one future whose response this emitter will emit."""
        with self._cv:
            self._pending += 1

    def drain(self, timeout_s: float) -> bool:
        """Block until every expected response has been emitted (or the
        timeout passes). Must be called before closing fh/journal."""
        with self._cv:
            return self._cv.wait_for(lambda: self._pending == 0,
                                     timeout=timeout_s)

    def emit(self, obj: dict) -> None:
        with self.lock:
            self.fh.write(json.dumps(obj) + "\n")
            self.fh.flush()
            if self.journal is not None:
                try:
                    os.fsync(self.fh.fileno())
                except (OSError, ValueError, AttributeError):
                    pass  # stdout/pipes: flush is the best we can do
        if obj.get("ok"):
            if self.journal is not None:
                # only completions are journaled: failed requests are
                # retried by a --resume run, not skipped
                self.journal.append({"kind": "req", "id": obj.get("id")})
            if self.on_ok is not None:
                self.on_ok(obj.get("id"))
        if self.on_emit is not None and obj.get("id") is not None:
            self.on_emit(obj["id"])

    def emit_response(self, fut) -> None:
        try:
            self.emit(fut.result().to_json_dict())
        finally:
            with self._cv:
                self._pending -= 1
                self._cv.notify_all()


def serve_requests(requests, server: ConsensusServer, emitter: _Emitter,
                   ) -> int:
    """Submit parsed ``(rid, cluster, deadline_ms)`` requests, riding
    backpressure; responses stream out via future callbacks. Returns
    the number of requests admitted."""
    inflight: deque = deque()
    n = 0
    for rid, cluster, deadline_ms in requests:
        t0 = time.perf_counter()
        wait_s = server.config.result_timeout_s
        while True:
            try:
                fut = server.submit(cluster, request_id=rid,
                                    deadline_ms=deadline_ms)
                break
            except QueueFullError as e:
                # backpressure: wait out the oldest in-flight request —
                # but bounded, so a dead pipeline (which never frees
                # the queue) surfaces as a typed response, not a hang
                if time.perf_counter() - t0 > wait_s:
                    fut = None
                    emitter.emit({"id": rid, "ok": False,
                                  "error": e.code, "message": str(e)})
                    break
                if inflight:
                    try:
                        inflight.popleft().result(timeout=1.0)
                    except FutureTimeoutError:
                        pass
                else:
                    time.sleep(1e-3)
            except ServeError as e:
                fut = None
                emitter.emit({"id": rid, "ok": False,
                              "error": e.code, "message": str(e)})
                break
        if fut is not None:
            inflight.append(fut)
            emitter.expect()
            fut.add_done_callback(emitter.emit_response)
            n += 1
    while inflight:
        try:
            inflight.popleft().result(
                timeout=server.config.result_timeout_s)
        except FutureTimeoutError:
            # dead pipeline: stop waiting — close() resolves every
            # abandoned future (ServerClosedError), and the done
            # callbacks emit those responses, so no request goes
            # unanswered
            break
    # callbacks fire on server threads after result() returns: wait for
    # the emissions themselves before the caller closes any sink
    emitter.drain(server.config.result_timeout_s)
    return n


def serve_stream(lines, server: ConsensusServer, emitter: _Emitter,
                 args, config: ServeConfig, done_ids=frozenset()) -> int:
    """Parse + submit every JSONL request line. Ids are stable
    (``obj["id"]`` or the line index), so ``done_ids`` from a journal
    skips previously completed requests idempotently."""

    def gen():
        for i, line in enumerate(lines):
            line = line.strip()
            if not line:
                continue
            rid = None
            try:
                obj = json.loads(line)
                rid = obj.get("id")  # kept even when the rest is bad
                if rid is None:
                    rid = f"line{i}"
                if rid in done_ids:
                    continue
                cluster, deadline_ms = parse_request(obj, args, config)
            except (ValueError, KeyError, TypeError) as e:
                emitter.emit({"id": rid or f"line{i}", "ok": False,
                              "error": "bad_request", "message": str(e)})
                continue
            yield rid, cluster, deadline_ms

    return serve_requests(gen(), server, emitter)


def serve_fastq(path: str, server: ConsensusServer, emitter: _Emitter,
                args, config: ServeConfig, done_ids=frozenset()) -> int:
    """The streaming FASTQ front door: tolerant-parse ``path``
    (malformed records to the ``<stem>.quarantine.jsonl`` sidecar),
    group consecutive reads into clusters by the ``<cluster>/<read>``
    name convention, and submit each cluster as one request (id = the
    cluster name)."""
    from ..engine.validate import InvalidInputError
    from ..io.stream import (QuarantineWriter, group_clusters,
                             quarantine_path_for, stream_fastq)

    quarantine = QuarantineWriter(quarantine_path_for(path))

    def gen():
        records = stream_fastq(path, quarantine,
                               faults=server.faults or None)
        for cname, seqs, phreds, _names in group_clusters(records):
            if cname in done_ids:
                continue
            try:
                ph = [np.asarray(p, float) for p in phreds]
                if args.phred_cap > 0:
                    ph = [cap_phreds(p, args.phred_cap) for p in ph]
                cluster = encode_cluster(seqs, phreds=ph, config=config)
            except (InvalidInputError, ValueError) as e:
                emitter.emit({"id": cname, "ok": False,
                              "error": getattr(e, "code", "bad_request"),
                              "message": str(e)})
                continue
            deadline_ms = args.deadline_ms if args.deadline_ms > 0 \
                else None
            yield cname, cluster, deadline_ms

    try:
        n = serve_requests(gen(), server, emitter)
    finally:
        quarantine.close()
        if quarantine.n:
            server.stats.count("quarantined", quarantine.n)
    if args.verbose >= 1 and quarantine.n:
        print(f"quarantined {quarantine.n} record(s) from '{path}' "
              f"({quarantine.counts})", file=sys.stderr)
    return n


def _warmup(server: ConsensusServer, path: str, args,
            config: ServeConfig, verbose: int) -> None:
    examples = []
    with open(path) as fh:
        for line in fh:
            if line.strip():
                cluster, _ = parse_request(json.loads(line), args, config)
                examples.append(cluster)
    t0 = time.perf_counter()
    n = server.warmup(examples, batch_sizes=(1, config.max_batch))
    if verbose >= 1:
        print(
            f"warmup: {n} executable(s) traced in "
            f"{time.perf_counter() - t0:.1f}s",
            file=sys.stderr,
        )


# spool file types the watcher serves (everything else — sidecars,
# dotfiles, in-progress *.tmp writes — is ignored)
_WATCH_EXTS = (".jsonl", ".fastq", ".fq", ".fastq.gz", ".fq.gz")
_SIDECAR_EXTS = (".out.jsonl", ".quarantine.jsonl", ".journal.jsonl")
# polls a size-stable JSONL file may end without a newline before its
# partial tail is declared truncated (quarantined) instead of re-read
_TAIL_GIVEUP_POLLS = 5


def watch_candidates(names) -> List[str]:
    """Filter a directory listing to servable spool files: dotfiles,
    ``*.tmp`` in-progress writes, and our own sidecar outputs are
    ignored."""
    out = []
    for f in names:
        if f.startswith("."):
            continue
        if ".tmp" in f:
            continue
        if f.endswith(_SIDECAR_EXTS):
            continue
        if f.endswith(_WATCH_EXTS):
            out.append(f)
    return sorted(out)


def _spool_fingerprint(path: str, args, config: ServeConfig) -> str:
    """Journal fingerprint for one spool file: the serve config that
    shapes responses (error model, phred cap, deadline, iteration
    budget) plus a content signal — a digest of the file's first
    64 KiB. The head digest is stable under append-growth of a large
    JSONL spool, but a file deleted and rewritten under the same name
    no longer matches its stale journal, so its (possibly different)
    requests are re-served instead of silently skipped."""
    from ..io.journal import fingerprint
    from ..utils.fprint import fold_nondefault

    head = b""
    try:
        with open(path, "rb") as fh:
            head = fh.read(65536)
    except OSError:
        pass
    # the encoding and integrity knobs fold in only when non-default so
    # spool journals written before each knob existed stay resumable;
    # guard/verify_fraction are CLI-settable and change which checks a
    # resumed run performs, so they are part of the config identity
    return fingerprint(
        os.path.basename(path), config.scores, args.phred_cap,
        args.deadline_ms, args.max_iters, args.alignment_proposals,
        hashlib.sha256(head).hexdigest(),
        config.band_dtype, config.band_growth,
        *fold_nondefault("input_enc", config.input_enc, "f32"),
        *fold_nondefault("guard", bool(config.guard), False),
        *fold_nondefault("verify_fraction", config.verify_fraction,
                         0.0),
        *fold_nondefault("speculate_k", config.speculate_k, 0),
    )


def _load_file_journal(path: str, resume: bool, fp: str = ""):
    """Prior completion state of one spool file: (done_ids, finished).
    A journal whose header fingerprint does not match ``fp`` is STALE —
    the file was rewritten or the serve config changed — so its ids are
    dropped and the file re-served from scratch (recomputing is
    recoverable; skipping new requests on old journal entries is not)."""
    from ..io.journal import read_journal
    from ..io.stream import journal_path_for

    if not resume:
        return set(), False
    records, _torn = read_journal(journal_path_for(path))
    if not records:
        return set(), False
    head = records[0]
    if head.get("kind") != "header" or \
            (fp and head.get("fingerprint") != fp):
        print(f"rifraf-serve: stale journal for '{path}' (file content "
              "or serve config changed); re-serving from scratch",
              file=sys.stderr)
        return set(), False
    done_ids = {r.get("id") for r in records if r.get("kind") == "req"}
    finished = any(r.get("kind") == "done" for r in records)
    return done_ids, finished


class _WatchedFile:
    """Per-file serving state across polls: size stability, ids served
    so far (journal ∪ this process), and the partial-tail counter."""

    def __init__(self, path: str, resume: bool, args, config):
        self.path = path
        self.args = args
        self.config = config
        self.last_size = -1
        self.stable = 0  # consecutive polls at last_size
        self.noeol_polls = 0  # stable polls ending without a newline
        self.fp = _spool_fingerprint(path, args, config)
        self.done_ids, self.finished = _load_file_journal(
            path, resume, self.fp)
        # ids ANSWERED in out.jsonl this process (journaled successes
        # plus emitted failures): failures stay un-journaled so a
        # --resume after a crash retries them, but re-polling the same
        # file must not append duplicate ok:false lines
        self.emitted = set(self.done_ids)
        self.journal = None
        self.out_fh = None

    def poll_size(self) -> bool:
        """Re-stat; returns whether the size is stable since last poll
        (the appear-then-keep-writing race guard)."""
        try:
            size = os.stat(self.path).st_size
        except OSError:
            return False  # vanished mid-poll
        stable = size == self.last_size
        self.stable = self.stable + 1 if stable else 0
        self.last_size = size
        return stable

    def open_sinks(self, resume: bool):
        """Lazily open the output + journal sidecars (append when
        resuming with prior completions, else truncate)."""
        from ..io.journal import open_resumable
        from ..io.stream import journal_path_for

        if self.out_fh is not None:
            return
        resuming = resume and bool(self.done_ids)
        if not resuming:
            # fresh header: re-fingerprint now that the file is
            # size-stable — its head may still have been growing when
            # this watcher first sighted it
            self.fp = _spool_fingerprint(self.path, self.args,
                                         self.config)
        stem = journal_path_for(self.path)[: -len(".journal.jsonl")]
        self.journal, _prior = open_resumable(
            journal_path_for(self.path), {"fingerprint": self.fp},
            resume=resuming)
        mode = "a" if resuming else "w"
        self.out_fh = open(stem + ".out.jsonl", mode)

    def mark_done(self):
        self.finished = True
        if self.journal is not None:
            self.journal.append({"kind": "done",
                                 "n": len(self.done_ids)})
        self.close_sinks()

    def close_sinks(self):
        if self.out_fh is not None:
            self.out_fh.close()
            self.out_fh = None
        if self.journal is not None:
            self.journal.close()
            self.journal = None


def _serve_watched_jsonl(wf: _WatchedFile, server, args, config,
                         final: bool) -> bool:
    """Serve the complete lines of a watched JSONL file. Returns True
    when the file is fully served (trailing newline seen, or its
    partial tail was given up on and quarantined)."""
    with open(wf.path) as fh:
        text = fh.read()
    complete = text.endswith("\n") or text == ""
    lines = text.splitlines()
    tail = None
    if not complete:
        tail = lines.pop()  # partial trailing line: re-read next poll
    # track ids as they are ANSWERED so a re-poll of a growing (or
    # newline-less) file only submits NEW lines — the emitted set
    # covers failures too, so a partial-tail file re-polled up to
    # _TAIL_GIVEUP_POLLS times does not append duplicate ok:false
    # lines (those ids stay un-journaled: a --resume run retries them)
    served_before = wf.done_ids | wf.emitted
    emitter = _Emitter(wf.out_fh, journal=wf.journal,
                       on_ok=wf.done_ids.add, on_emit=wf.emitted.add)
    serve_stream(lines, server, emitter, args, config,
                 done_ids=served_before)
    if complete:
        return True
    if final:
        # the producer went quiet mid-line: quarantine the tail with a
        # typed reason rather than waiting forever
        from ..io.stream import QuarantineWriter, quarantine_path_for

        with QuarantineWriter(quarantine_path_for(wf.path)) as q:
            q.write(reason="truncated",
                    message="file ends mid-line and stopped growing",
                    source=wf.path, record=tail)
        if args.verbose >= 1:
            print(f"quarantined truncated tail of '{wf.path}'",
                  file=sys.stderr)
        return True
    return False


def _run_watch(server: ConsensusServer, args,
               config: ServeConfig) -> None:
    files: dict = {}
    while True:
        for name in watch_candidates(os.listdir(args.watch)):
            path = os.path.join(args.watch, name)
            wf = files.get(name)
            if wf is None:
                wf = files[name] = _WatchedFile(path, args.resume,
                                                args, config)
            if wf.finished:
                continue
            stable = wf.poll_size()
            if not stable and not args.watch_once:
                continue  # still growing (or brand new): next poll
            is_fastq = not name.endswith(".jsonl")
            if args.verbose >= 1 and wf.out_fh is None:
                print(f"serving '{path}'", file=sys.stderr)
            try:
                wf.open_sinks(args.resume)
                if is_fastq:
                    # FASTQ spools are served whole once size-stable; a
                    # truly truncated record quarantines, never crashes
                    serve_fastq(path, server,
                                _Emitter(wf.out_fh, journal=wf.journal,
                                         on_ok=wf.done_ids.add),
                                args, config, done_ids=wf.done_ids)
                    wf.mark_done()
                else:
                    if not _serve_watched_jsonl(
                            wf, server, args, config,
                            final=(args.watch_once
                                   or wf.noeol_polls
                                   >= _TAIL_GIVEUP_POLLS)):
                        wf.noeol_polls += 1
                    else:
                        wf.mark_done()
            except Exception as e:
                # availability first: one poisonous spool file (an I/O
                # error, an unwritable sidecar, a parser bug) must not
                # take down the whole serving process
                print(f"rifraf-serve: error serving '{path}': "
                      f"{type(e).__name__}: {e}; file skipped",
                      file=sys.stderr)
                wf.finished = True
                wf.close_sinks()
        if args.watch_once:
            for wf in files.values():
                if not wf.finished:
                    wf.mark_done()
            return
        time.sleep(args.watch_poll_ms / 1e3)


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    config = config_from_args(args)
    server = ConsensusServer(config)
    try:
        if args.warmup_file:
            _warmup(server, args.warmup_file, args, config, args.verbose)
        if args.watch:
            _run_watch(server, args, config)
        else:
            journal = None
            done_ids: frozenset = frozenset()
            out_mode = "w"
            if args.resume:
                if args.input == "-":
                    raise SystemExit(
                        "--resume needs --input FILE or --watch "
                        "(stdin has no journal sidecar)")
                from ..io.journal import open_resumable
                from ..io.stream import journal_path_for

                fp = _spool_fingerprint(args.input, args, config)
                done_ids, _finished = _load_file_journal(
                    args.input, resume=True, fp=fp)
                journal, _prior = open_resumable(
                    journal_path_for(args.input),
                    {"fingerprint": fp},
                    resume=bool(done_ids))
                if done_ids:
                    out_mode = "a"
            is_fastq = args.input != "-" and not args.input.endswith(
                (".jsonl", ".json"))
            infh = (None if is_fastq else
                    sys.stdin if args.input == "-" else open(args.input))
            outfh = (sys.stdout if args.output == "-"
                     else open(args.output, out_mode))
            emitter = _Emitter(outfh, journal=journal)
            try:
                if is_fastq:
                    n = serve_fastq(args.input, server, emitter, args,
                                    config, done_ids=done_ids)
                else:
                    n = serve_stream(infh, server, emitter, args,
                                     config, done_ids=done_ids)
                if args.verbose >= 1:
                    print(f"served {n} request(s)", file=sys.stderr)
            finally:
                if infh is not None and infh is not sys.stdin:
                    infh.close()
                if outfh is not sys.stdout:
                    outfh.close()
                if journal is not None:
                    journal.close()
    except KeyboardInterrupt:
        pass
    finally:
        server.close()
        if args.stats:
            print(json.dumps(server.snapshot(), indent=2),
                  file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
