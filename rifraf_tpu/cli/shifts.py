"""Frameshift-correction CLI.

Mirrors /root/reference/scripts/correct_shifts.jl: FASTA in (sequence/
reference pairs, or all sequences sharing the first record as reference),
`correct_shifts` each, FASTA out.
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional

from ..engine.driver import correct_shifts
from ..io.fastx import read_fasta_records, write_fasta
from ..utils.constants import encode_seq
from .consensus import parse_error_model


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="rifraf-tpu-correct-shifts",
        description="Correct frame-shifting indels against a reference.",
    )
    p.add_argument("--multi-reference", action="store_true",
                   help="each sequence is followed by its reference")
    p.add_argument("--log-p", type=float, default=-1.0,
                   help="log error probability")
    p.add_argument("--bandwidth", type=int, default=-1,
                   help="alignment bandwidth; if < 0, choose dynamically")
    p.add_argument("--errors", type=str, default="10,0.00001,0.00001,1,1",
                   help="comma-separated reference error ratios - "
                        "mm, ins, del, codon ins, codon del")
    p.add_argument("--verbose", "-v", type=int, default=0)
    p.add_argument("input",
                   help="input fasta file, sequence/reference alternating pairs")
    p.add_argument("output", help="output fasta file of corrected sequences")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    scores = parse_error_model(args.errors)
    records = read_fasta_records(args.input)
    if args.multi_reference:
        sequences = records[0::2]
        references = records[1::2]
    else:
        sequences = records[1:]
        references = [records[0]] * len(records[1:])
    out_seqs, out_names = [], []
    for (name, seq), (_, ref) in zip(sequences, references):
        result = correct_shifts(
            encode_seq(seq),
            encode_seq(ref),
            log_p=args.log_p,
            bandwidth=args.bandwidth,
            scores=scores,
        )
        out_names.append(name)
        out_seqs.append(result)
    write_fasta(args.output, out_seqs, names=out_names)
    return 0


if __name__ == "__main__":
    sys.exit(main())
