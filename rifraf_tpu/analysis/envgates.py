"""Pass 5: env-gate registry.

Every ``RIFRAF_TPU_*`` name the code mentions must be declared in
``registry.ENV_GATES`` with a docs anchor, and the anchor file must
actually mention the name. The scan matches whole string literals
(``os.environ.get("RIFRAF_TPU_X")``, ``ENV_VAR = "RIFRAF_TPU_X"``,
monkeypatch.setenv targets), so a gate cannot be introduced through a
module-level name constant without registering it; names embedded in
docstrings or longer strings are not flagged.
"""

from __future__ import annotations

import ast
import re
from typing import List

from . import registry as default_registry
from .common import Finding, Project

ENV_NAME_RE = re.compile(r"RIFRAF_TPU_[A-Z0-9_]+\Z")


def check(project: Project, reg=None) -> List[Finding]:
    reg = reg or default_registry
    pass_id = "env-gates"
    out: List[Finding] = []
    seen = set()
    for scan in reg.ENV_SCAN:
        for sf in project.iter_py(scan, skip=tuple(reg.ENV_SKIP)):
            for node in ast.walk(sf.tree):
                if not (isinstance(node, ast.Constant)
                        and isinstance(node.value, str)
                        and ENV_NAME_RE.fullmatch(node.value)):
                    continue
                name = node.value
                seen.add(name)
                if name not in reg.ENV_GATES:
                    out.append(Finding(
                        sf.rel, node.lineno, pass_id,
                        f"env gate '{name}' is not registered in "
                        "registry.ENV_GATES; declare it with a docs "
                        "anchor",
                    ))
    for name, anchor in reg.ENV_GATES.items():
        doc = project.root / anchor
        if not doc.is_file():
            out.append(Finding(
                anchor, 1, pass_id,
                f"docs anchor for '{name}' does not exist",
            ))
        elif name not in doc.read_text():
            out.append(Finding(
                anchor, 1, pass_id,
                f"docs anchor '{anchor}' never mentions '{name}'",
            ))
        elif name not in seen:
            out.append(Finding(
                anchor, 1, pass_id,
                f"registered env gate '{name}' is no longer read "
                "anywhere; drop it from registry.ENV_GATES",
            ))
    return out
