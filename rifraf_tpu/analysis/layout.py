"""Pass 4: packed-array layout contracts.

Two halves:

(a) ``pack_layout`` ordering — the slice map in ``ops/fused.py`` is the
single source of truth for the fused step's packed host transfer. Its
``take(name, size)`` calls must appear in exactly the registry's
canonical order, each under exactly the registry's gating flags, and
the guard section must come LAST: every consumer (and every journal
written by an integrity-off run) depends on pre-guard offsets being
byte-identical whether or not the guard section exists.

(b) qmeta discipline — the packed input encoding ships an ``[8, 1,
128]`` dequant-row block as an EXTRA kernel input. The contract keeping
f32-encoding callers byte-identical: every ``args.append(qmeta)`` sits
inside an ``if input_enc == "packed"`` gate with its paired
``in_specs.append(...)`` in the same gated block, and inside the
kernels the qmeta ref is popped FIRST from ``*refs`` (before any other
conditional or output ref), so the positional layout of every other
ref is independent of the encoding.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Tuple

from . import registry as default_registry
from .common import Finding, Project, ancestors, call_name, enclosing_function


# ---- (a) pack_layout ordering ----

def _collect_takes(fn: ast.FunctionDef):
    """(name, gating-flag tuple, lineno) per take() call, in source
    order. Gating flags are the Name tests of enclosing ifs inside the
    layout function."""
    out = []
    for node in ast.walk(fn):
        if not (isinstance(node, ast.Call) and call_name(node) == "take"):
            continue
        if not (node.args and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            continue
        flags = []
        for anc in ancestors(node):
            if anc is fn:
                break
            if isinstance(anc, ast.If) and isinstance(anc.test, ast.Name):
                flags.append(anc.test.id)
        out.append((node.args[0].value, tuple(reversed(flags)),
                    node.lineno))
    out.sort(key=lambda t: t[2])
    return out


def _check_pack_layout(project: Project, reg) -> List[Finding]:
    pass_id = "layout"
    out: List[Finding] = []
    sf = project.file(reg.PACK_LAYOUT_FILE)
    if sf is None:
        return [Finding(reg.PACK_LAYOUT_FILE, 1, pass_id,
                        "pack_layout file missing")]
    fn = sf.find_function(reg.PACK_LAYOUT_FUNC)
    if fn is None:
        return [Finding(sf.rel, 1, pass_id,
                        f"'{reg.PACK_LAYOUT_FUNC}' not found")]
    takes = _collect_takes(fn)
    canon = list(reg.PACK_LAYOUT)
    for i, (name, flags, line) in enumerate(takes):
        if i >= len(canon):
            out.append(Finding(
                sf.rel, line, pass_id,
                f"unexpected extra pack_layout section '{name}'; "
                "register it in registry.PACK_LAYOUT (new sections "
                "must go BEFORE the guard tail only if every consumer "
                "is updated)",
            ))
            continue
        want_name, want_flags = canon[i]
        if name != want_name:
            out.append(Finding(
                sf.rel, line, pass_id,
                f"pack_layout section #{i} is '{name}', registry "
                f"expects '{want_name}' — reordering breaks every "
                "packed-offset consumer",
            ))
        elif tuple(flags) != tuple(want_flags):
            out.append(Finding(
                sf.rel, line, pass_id,
                f"pack_layout section '{name}' gated by "
                f"{list(flags)}, registry expects {list(want_flags)}",
            ))
    if len(takes) < len(canon):
        missing = [n for n, _ in canon[len(takes):]]
        out.append(Finding(
            sf.rel, fn.lineno, pass_id,
            f"pack_layout is missing registered section(s) {missing}",
        ))
    if takes and takes[-1][0] != reg.PACK_TAIL and \
            any(n == reg.PACK_TAIL for n, _, _ in takes):
        out.append(Finding(
            sf.rel, takes[-1][2], pass_id,
            f"'{reg.PACK_TAIL}' must be the LAST pack_layout section "
            "so integrity-off layouts stay byte-identical",
        ))
    return out


# ---- (b) qmeta append/pop discipline ----

def _gated_packed(node: ast.AST, reg) -> Optional[ast.If]:
    """The enclosing `if input_enc == "packed"` statement, if any."""
    for anc in ancestors(node):
        if isinstance(anc, ast.If):
            t = anc.test
            if (isinstance(t, ast.Compare)
                    and isinstance(t.left, ast.Name)
                    and t.left.id == reg.QMETA_GATE_NAME
                    and len(t.comparators) == 1
                    and isinstance(t.comparators[0], ast.Constant)
                    and t.comparators[0].value == reg.QMETA_GATE_VALUE):
                return anc
    return None


def _is_refs_pop0(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "pop"
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id == "refs"
            and len(node.args) == 1
            and isinstance(node.args[0], ast.Constant)
            and node.args[0].value == 0)


def _check_qmeta(project: Project, reg) -> List[Finding]:
    pass_id = "layout"
    out: List[Finding] = []
    for rel in reg.QMETA_FILES:
        sf = project.file(rel)
        if sf is None:
            out.append(Finding(rel, 1, pass_id, "qmeta file missing"))
            continue
        # appends: args.append(qmeta) gated + spec-paired
        for node in ast.walk(sf.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "append"
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == "args"
                    and len(node.args) == 1
                    and isinstance(node.args[0], ast.Name)
                    and node.args[0].id == "qmeta"):
                continue
            gate = _gated_packed(node, reg)
            if gate is None:
                out.append(Finding(
                    sf.rel, node.lineno, pass_id,
                    "args.append(qmeta) outside an "
                    f"`if {reg.QMETA_GATE_NAME} == "
                    f"\"{reg.QMETA_GATE_VALUE}\"` gate — the f32 "
                    "encoding would ship a phantom kernel input",
                ))
                continue
            spec_ok = False
            for sub in ast.walk(gate):
                if (isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Attribute)
                        and sub.func.attr == "append"
                        and isinstance(sub.func.value, ast.Name)
                        and sub.func.value.id == "in_specs"
                        and sub.lineno < node.lineno):
                    spec_ok = True
            if not spec_ok:
                out.append(Finding(
                    sf.rel, node.lineno, pass_id,
                    "args.append(qmeta) without a paired "
                    "in_specs.append(...) earlier in the same gated "
                    "block — args and in_specs would desync",
                ))
        # kernels: the packed-gated refs.pop(0) must be the FIRST pop
        for fn in sf.functions():
            pops = []
            for node in ast.walk(fn):
                if _is_refs_pop0(node):
                    pops.append(node)
            pops.sort(key=lambda n: (n.lineno, n.col_offset))
            for i, pop in enumerate(pops):
                p = getattr(pop, "_rifraf_parent", None)
                is_qmeta_pop = (
                    isinstance(p, ast.IfExp)
                    and isinstance(p.test, ast.Compare)
                    and isinstance(p.test.left, ast.Name)
                    and p.test.left.id == reg.QMETA_GATE_NAME
                    and len(p.test.comparators) == 1
                    and isinstance(p.test.comparators[0], ast.Constant)
                    and (p.test.comparators[0].value
                         == reg.QMETA_GATE_VALUE)
                    and p.body is pop
                )
                if is_qmeta_pop and i != 0:
                    out.append(Finding(
                        sf.rel, pop.lineno, pass_id,
                        "qmeta refs.pop(0) must be the FIRST pop in "
                        "the kernel — the packed block is appended "
                        "directly after the unconditional inputs, so "
                        "popping it later misaligns every ref",
                    ))
    return out


def check(project: Project, reg=None) -> List[Finding]:
    reg = reg or default_registry
    return _check_pack_layout(project, reg) + _check_qmeta(project, reg)
