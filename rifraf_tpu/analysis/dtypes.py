"""Pass 3: dtype discipline — store narrow, accumulate wide.

The bf16 band store and int8 score planes win their HBM-bandwidth
savings ONLY because every arithmetic accumulation (the max-plus
recurrence, reductions, dot products) runs in float32: a narrow value
must be re-widened at load before it feeds max/add. Until now this
contract lived in bit-identity tests that can say "something drifted"
but not WHERE; this pass enforces it structurally.

Per function (the contract is local — narrow values are created at
store boundaries and re-widened at load boundaries inside the same
function), the pass tracks:

- narrowing casts: ``x.astype(jnp.bfloat16)``, ``.astype("int8")``,
  ``lax.convert_element_type(x, jnp.bfloat16)``, and casts to a dtype
  variable bound from a registry NARROW_RESOLVER
  (``band_store_dtype(...)`` — dynamically f32 OR bf16, so it must be
  treated as potentially narrow);
- names bound to narrow values (cleared on any other reassignment);
- widening: ``.astype(jnp.float32)`` / other WIDE_DTYPES casts clear
  the taint.

A narrow expression or tainted name appearing as an operand of an
accumulate call (``jnp.max``/``maximum``/``sum``/``dot``/
``logsumexp10``/``summax``/...) or of a ``+`` binop is a finding.
Storing narrow values (assignments, ``ref[...] = x``, concatenate,
where/select) is fine — that is the point of the narrow store.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from . import registry as default_registry
from .common import Finding, Project, call_name, dotted_name


def _dtype_token(node: ast.AST) -> str:
    """'bfloat16' from jnp.bfloat16 / np.int8 / 'int8' literals."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    name = dotted_name(node)
    return name.rsplit(".", 1)[-1] if name else ""


class _FnChecker(ast.NodeVisitor):
    def __init__(self, sf, reg, findings: List[Finding]):
        self.sf = sf
        self.reg = reg
        self.findings = findings
        self.narrow_names: Set[str] = set()
        # names bound to a dtype object that may be narrow (e.g.
        # band_dt = band_store_dtype(band_dtype))
        self.narrow_dtype_vars: Set[str] = set()

    # ---- classification ----

    def _is_narrow_dtype_expr(self, node: ast.AST) -> bool:
        tok = _dtype_token(node)
        if tok in self.reg.NARROW_DTYPES:
            return True
        if isinstance(node, ast.Name) and node.id in self.narrow_dtype_vars:
            return True
        if isinstance(node, ast.Call) and \
                call_name(node) in self.reg.NARROW_RESOLVERS:
            return True
        return False

    def _is_wide_dtype_expr(self, node: ast.AST) -> bool:
        return _dtype_token(node) in self.reg.WIDE_DTYPES

    def _is_narrow_value(self, node: ast.AST) -> bool:
        """Whether an expression yields a narrow-dtype value."""
        if isinstance(node, ast.Name):
            return node.id in self.narrow_names
        if isinstance(node, ast.Call):
            name = call_name(node)
            if name == "astype" and node.args:
                if self._is_narrow_dtype_expr(node.args[0]):
                    return True
                if self._is_wide_dtype_expr(node.args[0]):
                    return False
                # dynamic dtype (e.g. .astype(out_ref.dtype)): unknown,
                # treat as clean — the storing side owns the contract
                return False
            if name == "convert_element_type" and len(node.args) >= 2:
                return self._is_narrow_dtype_expr(node.args[1])
            # a narrow value piped through shape-only ops stays narrow
            if name in ("reshape", "transpose", "squeeze", "ravel") and \
                    isinstance(node.func, ast.Attribute) and \
                    self._is_narrow_value(node.func.value):
                return True
            return False
        if isinstance(node, (ast.Subscript,)):
            return self._is_narrow_value(node.value)
        return False

    # ---- taint bookkeeping ----

    def visit_Assign(self, node: ast.Assign) -> None:
        self.generic_visit(node)
        is_narrow = self._is_narrow_value(node.value)
        is_narrow_dtype = isinstance(node.value, ast.Call) and \
            call_name(node.value) in self.reg.NARROW_RESOLVERS
        for tgt in node.targets:
            if isinstance(tgt, ast.Name):
                self.narrow_names.discard(tgt.id)
                self.narrow_dtype_vars.discard(tgt.id)
                if is_narrow:
                    self.narrow_names.add(tgt.id)
                if is_narrow_dtype:
                    self.narrow_dtype_vars.add(tgt.id)

    # ---- accumulation checks ----

    def _flag(self, node: ast.AST, what: str) -> None:
        self.findings.append(Finding(
            self.sf.rel, getattr(node, "lineno", 1), "dtype-discipline",
            f"narrow-dtype value flows into {what} without a re-widen; "
            "store narrow, accumulate wide (.astype(jnp.float32) "
            "before max/add)",
        ))

    def visit_Call(self, node: ast.Call) -> None:
        name = call_name(node)
        if name in self.reg.ACCUMULATE_CALLS:
            operands = list(node.args)
            if isinstance(node.func, ast.Attribute):
                # x.max() / x.sum(): the receiver is the operand
                operands.append(node.func.value)
            for arg in operands:
                if self._is_narrow_value(arg):
                    self._flag(node, f"accumulate call '{name}'")
        self.generic_visit(node)

    def visit_BinOp(self, node: ast.BinOp) -> None:
        if isinstance(node.op, (ast.Add, ast.Sub, ast.Mult, ast.MatMult)):
            for side in (node.left, node.right):
                if self._is_narrow_value(side):
                    self._flag(node, "arithmetic binop")
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        if isinstance(node.op, (ast.Add, ast.Sub, ast.Mult)):
            if self._is_narrow_value(node.value):
                self._flag(node, "augmented accumulation")
        self.generic_visit(node)

    # nested defs are visited standalone by check() — do not descend,
    # or their statements would be checked twice with leaked taint
    def visit_FunctionDef(self, node) -> None:
        pass

    visit_AsyncFunctionDef = visit_FunctionDef


def check(project: Project, reg=None) -> List[Finding]:
    reg = reg or default_registry
    findings: List[Finding] = []
    for scan in reg.DTYPE_SCAN:
        for sf in project.iter_py(scan):
            for node in ast.walk(sf.tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    checker = _FnChecker(sf, reg, findings)
                    for stmt in node.body:
                        checker.visit(stmt)
    return findings
