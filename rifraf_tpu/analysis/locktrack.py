"""Pass 6 (runtime half): the LockTracker harness.

Where the static pass (races.py) proves every mutation SITE sits under
a ``with self.<lock>``, this harness proves the discipline holds at
RUNTIME under real thread interleavings — including mutations the
static pass cannot see (dict/list item writes through a local alias, a
helper called with the lock supposedly held).

Usage (tests/test_analysis_races.py):

    tracker = LockTracker()
    track_instance(stats, tracker)        # spec from the registry
    ... hammer from N threads ...
    assert tracker.violations == []

``track_instance``:

- replaces each declared lock attribute with a tracked wrapper
  (``TrackedLock`` for Lock/RLock, ``TrackedCondition`` for Condition)
  that records the owning thread between acquire and release;
- wraps each shared mutable container attribute (dict/list/set/deque
  not in the allowlist) in a guard proxy whose mutating methods assert
  one of the instance's tracked locks is held by the CURRENT thread —
  ``__setattr__`` interception alone cannot see item mutation;
- swaps the instance's ``__class__`` to a subclass whose
  ``__setattr__`` asserts lock ownership on every non-allowlisted
  attribute rebind, and records (thread, attr) for allowlisted handoff
  attributes so a test can assert the single-writer/ownership pattern
  (e.g. ``Worker.inflight`` written by the worker thread only while it
  is alive).

The detector is DETERMINISTIC in a way timing-based race tests are
not: any unguarded mutation is recorded on every schedule, not only on
the schedules where two threads actually collide.
"""

from __future__ import annotations

import threading
from collections import deque
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from . import registry as default_registry


@dataclass
class Violation:
    cls: str
    attr: str
    op: str
    thread: str

    def __str__(self) -> str:
        return (f"{self.cls}.{self.attr}: unguarded {self.op} from "
                f"thread '{self.thread}'")


class LockTracker:
    """Violation sink + write journal shared by every tracked object."""

    def __init__(self):
        self._mu = threading.Lock()
        self.violations: List[Violation] = []
        # (cls, attr) -> ordered list of writer thread names, for
        # ownership/handoff assertions on allowlisted attributes
        self.writes: Dict[Tuple[str, str], List[str]] = {}

    def record_violation(self, cls: str, attr: str, op: str) -> None:
        v = Violation(cls, attr, op, threading.current_thread().name)
        with self._mu:
            self.violations.append(v)

    def record_write(self, cls: str, attr: str) -> None:
        name = threading.current_thread().name
        with self._mu:
            self.writes.setdefault((cls, attr), []).append(name)


class TrackedLock:
    """threading.Lock wrapper recording the owning thread."""

    def __init__(self):
        self._lock = threading.Lock()
        self.owner: Optional[int] = None

    def acquire(self, *a, **k) -> bool:
        got = self._lock.acquire(*a, **k)
        if got:
            self.owner = threading.get_ident()
        return got

    def release(self) -> None:
        self.owner = None
        self._lock.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def held_by_me(self) -> bool:
        return self.owner == threading.get_ident()


class TrackedCondition:
    """threading.Condition wrapper; ownership is cleared for the
    duration of a wait (the condition releases its lock there)."""

    def __init__(self):
        self._cv = threading.Condition()
        self.owner: Optional[int] = None

    def acquire(self, *a, **k) -> bool:
        got = self._cv.acquire(*a, **k)
        if got:
            self.owner = threading.get_ident()
        return got

    def release(self) -> None:
        self.owner = None
        self._cv.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def wait(self, timeout=None):
        self.owner = None
        try:
            return self._cv.wait(timeout)
        finally:
            self.owner = threading.get_ident()

    def wait_for(self, predicate, timeout=None):
        self.owner = None
        try:
            return self._cv.wait_for(predicate, timeout)
        finally:
            self.owner = threading.get_ident()

    def notify(self, n=1):
        self._cv.notify(n)

    def notify_all(self):
        self._cv.notify_all()

    def held_by_me(self) -> bool:
        return self.owner == threading.get_ident()


def _held_any(locks) -> bool:
    return any(lk.held_by_me() for lk in locks)


def _make_guard(value, locks, tracker: LockTracker, cls: str, attr: str):
    """A guard-proxy subclass instance mirroring ``value``; mutating
    methods record a violation when no tracked lock is held."""

    def checked(op_name, fn):
        def op(self, *a, **k):
            if not _held_any(locks):
                tracker.record_violation(cls, attr, f"{op_name}()")
            return fn(self, *a, **k)
        op.__name__ = op_name
        return op

    if isinstance(value, dict):
        ops = ("__setitem__", "__delitem__", "pop", "popitem", "clear",
               "update", "setdefault")
        base, init = dict, (value,)
    elif isinstance(value, deque):
        ops = ("append", "appendleft", "extend", "extendleft", "pop",
               "popleft", "remove", "clear", "__setitem__",
               "__delitem__")
        base, init = deque, (value, value.maxlen)
    elif isinstance(value, list):
        ops = ("append", "extend", "insert", "pop", "remove", "clear",
               "sort", "reverse", "__setitem__", "__delitem__",
               "__iadd__")
        base, init = list, (value,)
    elif isinstance(value, set):
        ops = ("add", "discard", "remove", "pop", "clear", "update",
               "difference_update", "intersection_update",
               "symmetric_difference_update")
        base, init = set, (value,)
    else:
        return value
    ns = {name: checked(name, getattr(base, name)) for name in ops}
    proxy_cls = type(f"Guarded{base.__name__.capitalize()}", (base,), ns)
    return proxy_cls(*init)


def _spec_for(obj, reg) -> Optional[dict]:
    for (_rel, cls_name), spec in reg.SHARED_STATE.items():
        if type(obj).__name__ == cls_name or any(
            c.__name__ == cls_name for c in type(obj).__mro__
        ):
            return spec
    return None


def track_instance(obj, tracker: LockTracker, spec: Optional[dict] = None,
                   reg=None):
    """Instrument one live instance against its registry spec (or an
    explicit ``spec`` with the same shape). Returns ``obj``."""
    reg = reg or default_registry
    if spec is None:
        spec = _spec_for(obj, reg)
    if spec is None:
        raise KeyError(
            f"{type(obj).__name__} has no registry.SHARED_STATE entry"
        )
    cls_name = type(obj).__name__
    unguarded_ok = set(spec.get("unguarded_ok", {}))
    lock_names = tuple(spec["locks"])

    # 1. swap the declared locks for tracked ones
    tracked_locks = []
    for name in lock_names:
        current = object.__getattribute__(obj, name)
        wrapper = (TrackedCondition()
                   if isinstance(current, threading.Condition)
                   else TrackedLock())
        object.__setattr__(obj, name, wrapper)
        tracked_locks.append(wrapper)

    # 2. wrap shared mutable containers in guard proxies
    for name, value in list(vars(obj).items()):
        if name in lock_names or name in unguarded_ok:
            continue
        if isinstance(value, (dict, list, set, deque)):
            object.__setattr__(
                obj, name,
                _make_guard(value, tracked_locks, tracker, cls_name,
                            name),
            )

    # 3. subclass swap: assert ownership on attribute rebinds
    base = type(obj)

    def __setattr__(self, name, value):
        if name in unguarded_ok:
            tracker.record_write(cls_name, name)
        elif tracked_locks and not _held_any(tracked_locks):
            tracker.record_violation(cls_name, name, "attribute rebind")
        elif not tracked_locks:
            # lock-free class: every non-allowlisted rebind is a
            # violation — the registry says nothing else is shared
            tracker.record_violation(cls_name, name, "attribute rebind")
        object.__setattr__(self, name, value)

    tracked_cls = type(f"Tracked{cls_name}", (base,),
                       {"__setattr__": __setattr__})
    object.__setattr__(obj, "__class__", tracked_cls)
    return obj
