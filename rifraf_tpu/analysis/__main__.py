"""CLI entry: ``python -m rifraf_tpu.analysis``.

Exit status 0 = clean (suppressed findings do not fail the build; a
suppression without a reason does), 1 = findings. ``--json`` emits a
machine-readable report (the shape bench.py embeds as its ``lint``
block)."""

from __future__ import annotations

import argparse
import json
import os
import sys

from . import PASS_IDS, run_all


def _default_root() -> str:
    # rifraf_tpu/analysis/__main__.py -> repo checkout root
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m rifraf_tpu.analysis",
        description="rifraf-lint: invariant-enforcing static analysis",
    )
    ap.add_argument("--root", default=_default_root(),
                    help="repo checkout to analyze (default: the "
                         "checkout this package lives in)")
    ap.add_argument("--passes", default="",
                    help="comma-separated pass ids (default: all of "
                         f"{', '.join(PASS_IDS)})")
    ap.add_argument("--json", action="store_true",
                    help="emit a JSON report instead of text")
    ap.add_argument("--list", action="store_true",
                    help="list pass ids and exit")
    args = ap.parse_args(argv)

    if args.list:
        for p in PASS_IDS:
            print(p)
        return 0

    passes = [p.strip() for p in args.passes.split(",") if p.strip()]
    report = run_all(args.root, passes or None)
    findings = report["findings"]
    if args.json:
        print(json.dumps({
            "findings": [f.to_dict() for f in findings],
            "suppressed": report["suppressed"],
            "per_pass": report["per_pass"],
            "wall_s": round(report["wall_s"], 3),
        }, indent=2))
    else:
        for f in findings:
            print(f)
        n_sup = report["suppressed"]
        print(f"rifraf-lint: {len(findings)} finding(s), "
              f"{n_sup} suppressed, "
              f"{len(report['per_pass'])} pass(es) in "
              f"{report['wall_s']:.2f}s")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
