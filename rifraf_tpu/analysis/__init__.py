"""rifraf-lint: invariant-enforcing static analysis for rifraf-tpu.

Six passes, each driven by the declarations in ``registry.py``:

==================  =================================================
pass id             contract enforced
==================  =================================================
``cache-keys``      every lru_cache'd program factory's key covers the
                    program-identity knobs (or carries an exemption)
``fingerprints``    journal/spool fingerprint builders fold in every
                    fingerprint knob (or carry an exemption)
``dtype-discipline``  narrow casts (bf16/int8) in ops/ never feed
                    max/add/reductions without a re-widen
``layout``          pack_layout section order (guard last) and qmeta
                    append-last/pop-first discipline
``env-gates``       every RIFRAF_TPU_* mention is registered with a
                    docs anchor
``races``           serve shared state mutates only under its declared
                    locks (static half; locktrack.py is the runtime
                    half)
==================  =================================================

Suppression: ``# rifraf-lint: disable=<pass> -- <reason>`` on (or
directly above) the offending line. The reason is mandatory — a bare
suppression is itself a finding (pass id ``suppression``).

CLI: ``python -m rifraf_tpu.analysis`` (exit 1 on findings). The
package is stdlib-only and never imports JAX, so it runs anywhere.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence

from . import dtypes, envgates, keys, layout, races
from .common import Finding, Project

PASSES = (
    ("cache-keys", keys.check_cache_keys),
    ("fingerprints", keys.check_fingerprints),
    ("dtype-discipline", dtypes.check),
    ("layout", layout.check),
    ("env-gates", envgates.check),
    ("races", races.check),
)

PASS_IDS = tuple(p for p, _ in PASSES)


def run_all(root, passes: Optional[Sequence[str]] = None,
            reg=None) -> dict:
    """Run the requested passes (default: all) against the checkout at
    ``root``. Returns ``{"findings": [Finding], "suppressed": int,
    "per_pass": {id: {"findings": n, "suppressed": n}},
    "wall_s": float}`` — suppressed findings are counted, not listed,
    and suppressions missing a reason surface as ``suppression``
    findings."""
    t0 = time.perf_counter()
    project = Project(root)
    wanted = tuple(passes) if passes else PASS_IDS
    unknown = set(wanted) - set(PASS_IDS)
    if unknown:
        raise ValueError(f"unknown pass id(s): {sorted(unknown)}")
    findings: List[Finding] = []
    suppressed_total = 0
    per_pass: Dict[str, dict] = {}
    for pass_id, fn in PASSES:
        if pass_id not in wanted:
            continue
        raw = fn(project, reg)
        kept, suppressed = [], 0
        for f in raw:
            sf = project.file(f.path)
            if sf is not None and sf.suppress.active(f.line, f.pass_id):
                suppressed += 1
            else:
                kept.append(f)
        findings.extend(kept)
        suppressed_total += suppressed
        per_pass[pass_id] = {
            "findings": len(kept),
            "suppressed": suppressed,
        }
    # reason-less suppressions across every file any pass parsed
    for sf in project.loaded():
        for line, pass_ids in sf.suppress.missing_reason:
            findings.append(Finding(
                sf.rel, line, "suppression",
                "suppression of "
                f"{', '.join(sorted(pass_ids))} has no reason; write "
                "`# rifraf-lint: disable=<pass> -- <why>`",
            ))
    findings.sort(key=lambda f: (f.path, f.line, f.pass_id))
    return {
        "findings": findings,
        "suppressed": suppressed_total,
        "per_pass": per_pass,
        "wall_s": time.perf_counter() - t0,
    }


__all__ = ["Finding", "Project", "PASSES", "PASS_IDS", "run_all"]
