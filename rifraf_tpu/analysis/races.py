"""Pass 6 (static half): serve lock discipline.

The serving stack runs caller, batcher, worker-N, and supervisor
threads against shared state. The discipline, declared per class in
``registry.SHARED_STATE``: every mutation of a shared attribute happens
inside ``with self.<lock>`` for one of the class's declared locks —
except attributes explicitly allowlisted as lock-free single-writer /
GIL-atomic handoffs (each with a written reason) and private helpers
declared ``caller_locked`` (all call sites hold the lock).

Mutations the pass sees: attribute rebinds (``self.x = ...``, including
tuple-unpack targets and augmented assigns), item writes
(``self.x[k] = ...``, ``del self.x[k]``), and mutating container-method
calls (``self.x.append(...)`` etc., registry.MUTATOR_METHODS).
``__init__`` is exempt (no other thread can hold the instance yet).

The runtime half (``locktrack.py``) enforces the same table with real
threads; this half catches the violations a stress test may never
schedule.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Tuple

from . import registry as default_registry
from .common import (
    Finding,
    Project,
    enclosing_function,
    in_with_lock,
)


def _self_attr_of_target(tgt: ast.AST) -> Optional[Tuple[str, str]]:
    """('attr', kind) when ``tgt`` writes through self: rebinds
    (self.x), item writes (self.x[k]), nested tuple targets."""
    if isinstance(tgt, ast.Attribute) and \
            isinstance(tgt.value, ast.Name) and tgt.value.id == "self":
        return tgt.attr, "rebind"
    if isinstance(tgt, ast.Subscript) and \
            isinstance(tgt.value, ast.Attribute) and \
            isinstance(tgt.value.value, ast.Name) and \
            tgt.value.value.id == "self":
        return tgt.value.attr, "item write"
    return None


def _method_of(node: ast.AST, cls: ast.ClassDef) -> Optional[str]:
    fn = enclosing_function(node)
    while fn is not None and fn not in cls.body:
        fn = enclosing_function(fn)
    return fn.name if fn is not None else None


def _check_class(sf, cls: ast.ClassDef, spec, reg,
                 out: List[Finding]) -> None:
    pass_id = "races"
    mutators = getattr(reg, "MUTATOR_METHODS",
                       default_registry.MUTATOR_METHODS)
    locks = tuple(spec["locks"])
    unguarded_ok = spec.get("unguarded_ok", {})
    caller_locked = spec.get("caller_locked", {})
    for attr, reason in list(unguarded_ok.items()) + \
            list(caller_locked.items()):
        if not (reason or "").strip():
            out.append(Finding(
                sf.rel, cls.lineno, pass_id,
                f"allowlist entry '{attr}' on {cls.name} has no reason",
            ))

    def flag(node: ast.AST, attr: str, kind: str) -> None:
        method = _method_of(node, cls)
        if method in ("__init__",) or method is None:
            return
        if method in caller_locked:
            return
        if attr in unguarded_ok:
            return
        if locks and in_with_lock(node, locks):
            return
        have = (f"hold one of {list(locks)}" if locks
                else "declare it in the registry allowlist")
        out.append(Finding(
            sf.rel, node.lineno, pass_id,
            f"unguarded {kind} of shared attribute "
            f"'{cls.name}.{attr}' in {method}(); {have} or allowlist "
            "it with a reason",
        ))

    for node in ast.walk(cls):
        if isinstance(node, ast.Assign):
            targets = []
            for t in node.targets:
                targets.extend(t.elts if isinstance(t, ast.Tuple) else [t])
            for t in targets:
                hit = _self_attr_of_target(t)
                if hit:
                    flag(node, *hit)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            if isinstance(node, ast.AnnAssign) and node.value is None:
                continue
            hit = _self_attr_of_target(node.target)
            if hit:
                flag(node, hit[0], "rebind")
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                hit = _self_attr_of_target(t)
                if hit:
                    flag(node, hit[0], "delete")
        elif isinstance(node, ast.Call):
            f = node.func
            if (isinstance(f, ast.Attribute)
                    and f.attr in mutators
                    and isinstance(f.value, ast.Attribute)
                    and isinstance(f.value.value, ast.Name)
                    and f.value.value.id == "self"):
                flag(node, f.value.attr, f"mutating call .{f.attr}()")


def check(project: Project, reg=None) -> List[Finding]:
    reg = reg or default_registry
    out: List[Finding] = []
    for (rel, cls_name), spec in reg.SHARED_STATE.items():
        sf = project.file(rel)
        if sf is None:
            out.append(Finding(rel, 1, "races",
                               f"shared-state file '{rel}' missing"))
            continue
        cls = sf.find_class(cls_name)
        if cls is None:
            out.append(Finding(
                sf.rel, 1, "races",
                f"registered shared-state class '{cls_name}' not "
                f"found in '{rel}'",
            ))
            continue
        _check_class(sf, cls, spec, reg, out)
    return out
