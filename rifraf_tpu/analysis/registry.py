"""The invariant registry: rifraf-tpu's cross-cutting contracts AS DATA.

Every pass in ``rifraf_tpu.analysis`` is driven by the declarations in
this module, so adding a routing knob, a fingerprint field, an env
gate, or a thread-shared class means editing ONE table here — and the
registry self-checks force the edit to be explicit: each program
factory and fingerprint builder must account for EVERY declared knob,
either by carrying it or by an exemption with a written reason.
``docs/analysis.md`` documents each table and how to extend it.

Nothing here imports the rest of the package (see common.py's
stdlib-only rule): the registry describes the code, it never runs it.
"""

from __future__ import annotations

# --------------------------------------------------------------------
# Pass 1: cache-key completeness
# --------------------------------------------------------------------
# The knobs that ROUTE a compiled program: two calls differing in any
# of these must hit different executables, so every lru_cache'd program
# factory must carry each knob in its parameter list (= its cache key)
# or be exempt with a reason.
PROGRAM_IDENTITY_KNOBS = (
    "band_dtype",   # bf16/f32 band-store precision (PR 10)
    "input_enc",    # f32 vs packed 2-bit/int8 input encoding (PR 13)
    "impl",         # fused Pallas implementation: "mega" | "split"
    "want_edge",    # edge-hit statistics output (adaptive band growth)
    "want_guard",   # integrity guard-word output (PR 11)
    "speculate_k",  # speculative edit-set segments per launch (PR 15)
)

# Parameter names that satisfy a knob (a factory may spell the edge
# knob `use_edits`: the stage runners' edit-table variant implies the
# edge-statistics path).
KNOB_ALIASES = {
    "band_dtype": ("band_dtype",),
    "input_enc": ("input_enc",),
    "impl": ("impl",),
    "want_edge": ("want_edge", "use_edits"),
    "want_guard": ("want_guard",),
    "speculate_k": ("speculate_k",),
}

# Files scanned for lru_cache'd factories. EVERY lru_cache'd function
# found here must have a registry entry below — an unregistered one is
# a finding, so a new factory cannot land without declaring its keys.
FACTORY_SCAN = (
    "rifraf_tpu/engine/realign.py",
    "rifraf_tpu/parallel/sweep_sharded.py",
    "rifraf_tpu/serve",
)

# (file, function) -> {"required": knobs..., "exempt": {knob: reason}}.
# required + exempt must cover PROGRAM_IDENTITY_KNOBS exactly.
_XLA_EXEMPT = {
    "impl": "XLA scan path has a single implementation; `impl` routes "
            "only the Pallas kernels",
    "input_enc": "the XLA path consumes exact f32 inputs; BatchAligner "
                 "routes packed encodings to the Pallas runners only",
}
_NO_SPEC_FRAME = {
    "speculate_k": "frame realignment runs a fixed codon sweep, not "
                   "the refine hill-climb; there is no next round to "
                   "speculate",
}
PROGRAM_FACTORIES = {
    ("rifraf_tpu/engine/realign.py", "_pallas_frame_runner"): {
        "required": ("band_dtype", "input_enc", "impl"),
        "exempt": dict(
            _NO_SPEC_FRAME,
            want_edge="frame realignment computes no traceback "
                      "statistics; edge hits are sweep-stage outputs",
            want_guard="guard words are sweep/serve integrity "
                       "outputs; the frame loop never packs them",
        ),
    },
    ("rifraf_tpu/engine/realign.py", "_xla_frame_runner"): {
        "required": ("band_dtype",),
        "exempt": dict(
            _XLA_EXEMPT,
            **_NO_SPEC_FRAME,
            want_edge="frame realignment computes no traceback "
                      "statistics; edge hits are sweep-stage outputs",
            want_guard="guard words are sweep/serve integrity outputs; "
                       "the frame loop never packs them",
        ),
    },
    ("rifraf_tpu/engine/realign.py", "_pallas_stage_runner"): {
        "required": ("band_dtype", "input_enc", "impl", "want_edge"),
        "exempt": {
            "want_guard": "the realign driver verifies guards in its "
                          "own adapt rounds, never in the stage loop",
            "speculate_k": "speculative rounds need the XLA segmented "
                           "step (the megakernel fills one template "
                           "per launch); stage_runner routes a "
                           "speculating stage to _xla_stage_runner",
        },
    },
    ("rifraf_tpu/engine/realign.py", "_xla_stage_runner"): {
        "required": ("band_dtype", "want_edge", "speculate_k"),
        "exempt": dict(
            _XLA_EXEMPT,
            want_guard="the realign driver verifies guards in its own "
                       "adapt rounds, never in the stage loop",
        ),
    },
    ("rifraf_tpu/parallel/sweep_sharded.py", "_adapt_program"): {
        "required": ("band_dtype", "input_enc", "want_edge",
                     "want_guard"),
        "exempt": {
            "impl": "the fused impl is process-global "
                    "(RIFRAF_TPU_FUSED_IMPL read at trace time); the "
                    "inner realign factories carry it where both impls "
                    "can coexist",
            "speculate_k": "adapt rounds are single scoring launches "
                           "over a fixed template, not the refine "
                           "hill-climb; nothing to speculate",
        },
    },
    ("rifraf_tpu/parallel/sweep_sharded.py", "_stage_program"): {
        "required": ("band_dtype", "input_enc", "want_edge",
                     "speculate_k"),
        "exempt": {
            "impl": "the fused impl is process-global "
                    "(RIFRAF_TPU_FUSED_IMPL read at trace time); the "
                    "inner realign factories carry it where both impls "
                    "can coexist",
            "want_guard": "guard flags are produced by the adapt-round "
                          "programs only; the INIT stage never packs "
                          "them",
        },
    },
    ("rifraf_tpu/parallel/sweep_sharded.py", "_seg_adapt_program"): {
        "required": ("band_dtype", "input_enc", "want_edge",
                     "want_guard"),
        "exempt": {
            "impl": "the fused impl is process-global "
                    "(RIFRAF_TPU_FUSED_IMPL read at trace time); the "
                    "inner realign factories carry it where both impls "
                    "can coexist",
            "speculate_k": "adapt rounds are single scoring launches "
                           "over a fixed template, not the refine "
                           "hill-climb; nothing to speculate",
        },
    },
    ("rifraf_tpu/parallel/sweep_sharded.py", "_seg_stage_program"): {
        "required": ("band_dtype", "input_enc", "want_edge"),
        "exempt": {
            "impl": "the fused impl is process-global "
                    "(RIFRAF_TPU_FUSED_IMPL read at trace time); the "
                    "inner realign factories carry it where both impls "
                    "can coexist",
            "want_guard": "guard flags are produced by the adapt-round "
                          "programs only; the INIT stage never packs "
                          "them",
            "speculate_k": "the segment-packed stage program already "
                           "spends the segment axis on cluster "
                           "packing; ChunkExecutor speculates only "
                           "through the unsegmented _stage_program",
        },
    },
}

# --------------------------------------------------------------------
# Pass 2: fingerprint coverage
# --------------------------------------------------------------------
# Fields a resumable-journal fingerprint must fold in: anything that
# changes results (or changes which checks ran) between the run that
# wrote the journal and the run resuming it.
FINGERPRINT_KNOBS = (
    "band_dtype",
    "band_growth",
    "input_enc",
    "guard",
    "verify_fraction",
    "max_iters",
    "min_dist",
    "bandwidth_pvalue",
    "proposals",
    "scores",
    "content",
    # speculation is result-identical, but its journal records
    # different round-level provenance (attempt/hit stats), so a
    # resume must not silently mix the two modes (PR 15)
    "speculate_k",
)

# Identifiers (parameter names, attribute names, or string-literal part
# labels) that count as folding a knob into the digest.
FINGERPRINT_ALIASES = {
    "band_dtype": ("band_dtype",),
    "band_growth": ("band_growth",),
    "input_enc": ("input_enc",),
    "guard": ("guard",),
    "verify_fraction": ("verify_fraction",),
    "max_iters": ("max_iters",),
    "min_dist": ("min_dist",),
    "bandwidth_pvalue": ("bandwidth_pvalue",),
    "proposals": ("do_alignment_proposals", "alignment_proposals"),
    "scores": ("scores",),
    # a content signal: the sweep digests every cluster's reads, the
    # spool digests the file head
    "content": ("_content_digest", "sha256", "head"),
    "speculate_k": ("speculate_k",),
}

FINGERPRINT_BUILDERS = {
    ("rifraf_tpu/parallel/sweep_sharded.py", "_journal_fingerprint"): {
        "required": ("band_dtype", "band_growth", "input_enc", "guard",
                     "verify_fraction", "max_iters", "min_dist",
                     "bandwidth_pvalue", "proposals", "content",
                     "speculate_k"),
        "exempt": {
            "scores": "per-read score parameters are hashed inside "
                      "_content_digest's per-read tuples",
        },
    },
    ("rifraf_tpu/cli/serve.py", "_spool_fingerprint"): {
        "required": ("band_dtype", "band_growth", "input_enc", "guard",
                     "verify_fraction", "max_iters", "proposals",
                     "scores", "content", "speculate_k"),
        "exempt": {
            "min_dist": "the serve CLI exposes no flag; every spool "
                        "run uses the pinned ServeConfig default",
            "bandwidth_pvalue": "the serve CLI exposes no flag; every "
                                "spool run uses the pinned ServeConfig "
                                "default",
        },
    },
}

# --------------------------------------------------------------------
# Pass 3: dtype discipline (store narrow, accumulate wide)
# --------------------------------------------------------------------
DTYPE_SCAN = ("rifraf_tpu/ops",)

# dtypes that may only be STORED, never accumulated in
NARROW_DTYPES = ("bfloat16", "int8", "float16", "uint8")
# dtypes whose cast re-widens a narrow value
WIDE_DTYPES = ("float32", "int32", "float64", "int64")
# functions whose RESULT is a narrow dtype object (so `.astype(x)`
# where x came from one of these is a narrowing cast)
NARROW_RESOLVERS = ("band_store_dtype",)
# call targets that accumulate (max-plus recurrence, reductions) —
# feeding a narrow value into one of these without an intervening
# re-widen is the violation
ACCUMULATE_CALLS = (
    "max", "maximum", "min", "minimum", "sum", "cumsum", "dot",
    "matmul", "logaddexp", "logsumexp10", "summax", "add", "prod",
    "mean",
)

# --------------------------------------------------------------------
# Pass 4: packed-array layout contracts
# --------------------------------------------------------------------
# Canonical pack_layout section order: (name, gating flags). The guard
# section must stay LAST so integrity-off layouts (and every pre-guard
# offset of integrity-on layouts) stay byte-identical.
PACK_LAYOUT_FILE = "rifraf_tpu/ops/fused.py"
PACK_LAYOUT_FUNC = "pack_layout"
PACK_LAYOUT = (
    ("total", ()),
    ("scores", ()),
    ("n_errors", ("want_stats",)),
    ("edits", ("want_stats",)),
    ("edge_hits", ("want_stats", "want_edge")),
    ("sub", ("want_tables",)),
    ("ins", ("want_tables",)),
    ("del", ("want_tables",)),
    ("guard", ("want_guard",)),
)
PACK_TAIL = "guard"

# qmeta discipline (packed input encoding, PR 13): the [8, 1, 128]
# dequant-row block is appended to the kernel inputs ONLY under an
# `input_enc == "packed"` gate, with its BlockSpec appended in the same
# gated block — and inside the kernels it must be popped FIRST from
# *refs, before any other conditional or output ref.
QMETA_FILES = (
    "rifraf_tpu/ops/fill_pallas.py",
    "rifraf_tpu/ops/fused_pallas.py",
    "rifraf_tpu/ops/dense_pallas.py",
)
QMETA_GATE_NAME = "input_enc"
QMETA_GATE_VALUE = "packed"

# --------------------------------------------------------------------
# Pass 5: env-gate registry
# --------------------------------------------------------------------
# Every RIFRAF_TPU_* name the code mentions, with the doc file that
# explains it. The pass scans ENV_SCAN for unregistered names and
# verifies each anchor file exists and mentions the name.
ENV_GATES = {
    "RIFRAF_TPU_FUSED_IMPL": "docs/api.md",
    "RIFRAF_TPU_STATS_IMPL": "docs/api.md",
    "RIFRAF_TPU_AOT_CACHE": "docs/api.md",
    "RIFRAF_TPU_SEGMENT_PACK": "docs/api.md",
    "RIFRAF_TPU_HBM_GBPS": "docs/api.md",
    "RIFRAF_TPU_VPU_TOPS": "docs/api.md",
    "RIFRAF_TPU_ICI_GBPS": "docs/api.md",
    "RIFRAF_TPU_FAULTS": "docs/serving.md",
    "RIFRAF_TPU_PALLAS_INTERPRET": "docs/analysis.md",
    "RIFRAF_TPU_CACHE": "docs/analysis.md",
    "RIFRAF_TPU_HBM_BUDGET": "docs/analysis.md",
    "RIFRAF_TPU_DEBUG": "docs/analysis.md",
    "RIFRAF_TPU_BAND_DTYPE": "docs/analysis.md",
    "RIFRAF_TPU_SPEC_DEBUG": "docs/api.md",
}
# the analysis package itself is excluded: its registry and fixtures
# NAME the gates without reading them
ENV_SCAN = ("rifraf_tpu", "bench.py", "tests")
ENV_SKIP = ("rifraf_tpu/analysis",)

# --------------------------------------------------------------------
# Pass 6: serve lock discipline (static half; locktrack.py is the
# runtime half and reads the same table)
# --------------------------------------------------------------------
# (file, class) -> {"locks": guarding attrs, "unguarded_ok":
# {attr: reason} for deliberately lock-free single-writer/GIL-atomic
# handoffs, "caller_locked": {method: reason} for private helpers
# whose callers all hold the lock}.
SHARED_STATE = {
    ("rifraf_tpu/utils/timers.py", "Timers"): {
        "locks": ("_lock",),
        "unguarded_ok": {},
        "caller_locked": {},
    },
    ("rifraf_tpu/serve/stats.py", "ServerStats"): {
        "locks": ("_lock",),
        "unguarded_ok": {},
        "caller_locked": {},
    },
    ("rifraf_tpu/serve/quarantine.py", "DeviceScoreboard"): {
        "locks": ("_lock",),
        "unguarded_ok": {},
        "caller_locked": {
            "_get": "lazy-init helper; every caller holds _lock",
        },
    },
    ("rifraf_tpu/serve/batcher.py", "MicroBatcher"): {
        "locks": ("_lock",),
        "unguarded_ok": {},
        "caller_locked": {
            "_lane_demand": "pure read helper; both callers (add, the "
                            "flush policy) hold _lock",
        },
    },
    ("rifraf_tpu/cli/serve.py", "_Emitter"): {
        "locks": ("lock", "_cv"),
        "unguarded_ok": {
            "journal": "io.journal.Journal serializes internally "
                       "(its own _lock around every append)",
        },
        "caller_locked": {},
    },
    ("rifraf_tpu/serve/worker.py", "Worker"): {
        "locks": (),
        "unguarded_ok": {
            "last_beat": "monotonic heartbeat float; single writer "
                         "(the worker thread), the supervisor only "
                         "compares staleness",
            "busy": "bool flag, single writer; a stale supervisor "
                    "read delays a scale decision by one tick at most",
            "inflight": "rebind-only handoff (the list object is "
                        "replaced atomically under the GIL); "
                        "take_inflight() swaps it out only after the "
                        "worker thread is dead",
            "draining": "written once by the supervisor; the worker "
                        "loop polls it",
            "drained": "written once by the worker on clean exit; "
                       "read post-mortem by the supervisor",
            "_last_probe": "probe rate-limit timestamp; only the "
                           "supervisor-driven probe path writes it",
        },
        "caller_locked": {},
    },
    ("rifraf_tpu/serve/server.py", "ConsensusServer"): {
        "locks": ("_outstanding_lock",),
        "unguarded_ok": {
            "_closed": "set once by close(); racy readers fail over "
                       "to the closed path on their next submit",
            "_unhealthy": "set once by the supervisor's terminal "
                          "transition; readers degrade gracefully",
            "_worker_restarts": "supervisor-thread-only counter",
            "_batcher_restarts": "supervisor-thread-only counter",
            "_last_crash": "supervisor-thread-only backoff timestamp",
            "_last_scale": "supervisor-thread-only elastic timestamp",
            "_last_active": "supervisor-thread-only idle timestamp",
            "_last_stall_beat": "supervisor-thread-only stall map",
            "_batcher_thread": "rebound by start() and the "
                               "supervisor's restart path only",
            "_supervisor_thread": "rebound by start() only",
            "_worker_threads": "slot rebinds happen on the "
                               "supervisor thread (start() runs "
                               "before any other thread exists)",
            "_workers": "slot rebinds happen on the supervisor "
                        "thread (start() runs before any other "
                        "thread exists)",
            "_draining": "supervisor-thread-only elastic set",
            "_retired": "supervisor-thread-only elastic set",
            "_parked": "supervisor-thread-only probe set",
            "_batcher": "MicroBatcher serializes internally (its own "
                        "SHARED_STATE entry enforces _lock)",
        },
        "caller_locked": {},
    },
}

# mutating container-method names the static race pass treats as
# writes when called on a self attribute
MUTATOR_METHODS = (
    "append", "appendleft", "extend", "insert", "pop", "popleft",
    "popitem", "remove", "discard", "add", "clear", "update",
    "setdefault",
)
