"""Passes 1 and 2: cache-key completeness and fingerprint coverage.

Pass ``cache-keys``: every ``lru_cache``'d function in the factory scan
set is a compiled-program factory whose parameter list IS its routing
key. Each must be registered, and each registered factory must carry
every program-identity knob in its parameters — or carry a written
exemption. The registry itself is validated: a factory whose
required+exempt sets do not cover the full knob list is a finding, so
declaring a NEW knob in the registry forces an explicit decision at
every factory.

Pass ``fingerprints``: the resumable-journal fingerprint builders must
mention every fingerprint knob (as a parameter, attribute, or
string-literal part label) somewhere in their body. This is a
reachability check, not a dataflow proof — the regression tests pin the
actual digests — but it catches the real historical failure mode: a
knob added to the sweep config and never threaded into the digest.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from . import registry as default_registry
from .common import Finding, Project, call_name


def _is_lru_cached(fn: ast.FunctionDef) -> bool:
    for dec in fn.decorator_list:
        target = dec.func if isinstance(dec, ast.Call) else dec
        name = target.attr if isinstance(target, ast.Attribute) else (
            target.id if isinstance(target, ast.Name) else ""
        )
        if name == "lru_cache":
            return True
    return False


def _param_names(fn: ast.FunctionDef) -> set:
    args = fn.args
    names = {a.arg for a in args.args + args.kwonlyargs + args.posonlyargs}
    if args.vararg:
        names.add(args.vararg.arg)
    if args.kwarg:
        names.add(args.kwarg.arg)
    return names


def check_cache_keys(project: Project, reg=None) -> List[Finding]:
    reg = reg or default_registry
    pass_id = "cache-keys"
    out: List[Finding] = []
    seen = set()
    for scan in reg.FACTORY_SCAN:
        for sf in project.iter_py(scan):
            for fn in sf.functions():
                if not _is_lru_cached(fn):
                    continue
                key = (sf.rel, fn.name)
                seen.add(key)
                entry = reg.PROGRAM_FACTORIES.get(key)
                if entry is None:
                    out.append(Finding(
                        sf.rel, fn.lineno, pass_id,
                        f"lru_cache'd factory '{fn.name}' is not in "
                        "registry.PROGRAM_FACTORIES; declare its "
                        "program-identity knobs (or exemptions) there",
                    ))
                    continue
                covered = set(entry["required"]) | set(entry["exempt"])
                missing_decl = set(reg.PROGRAM_IDENTITY_KNOBS) - covered
                if missing_decl:
                    out.append(Finding(
                        sf.rel, fn.lineno, pass_id,
                        f"registry entry for '{fn.name}' does not "
                        f"account for knob(s) "
                        f"{sorted(missing_decl)}; add each to "
                        "'required' or 'exempt' (with a reason)",
                    ))
                for knob, reason in entry["exempt"].items():
                    if not (reason or "").strip():
                        out.append(Finding(
                            sf.rel, fn.lineno, pass_id,
                            f"exemption of knob '{knob}' on "
                            f"'{fn.name}' has no reason",
                        ))
                params = _param_names(fn)
                for knob in entry["required"]:
                    aliases = reg.KNOB_ALIASES.get(knob, (knob,))
                    if not params.intersection(aliases):
                        out.append(Finding(
                            sf.rel, fn.lineno, pass_id,
                            f"factory '{fn.name}' cache key is missing "
                            f"program-identity knob '{knob}' (accepted "
                            f"parameter names: {', '.join(aliases)})",
                        ))
    # stale registry rows: a registered factory that no longer exists
    # (renamed/moved) would otherwise silently stop being checked
    for (rel, name) in reg.PROGRAM_FACTORIES:
        if (rel, name) in seen:
            continue
        sf = project.file(rel)
        out.append(Finding(
            rel, 1, pass_id,
            f"registered factory '{name}' not found"
            + ("" if sf is not None else f" (file '{rel}' missing)"),
        ))
    return out


def _body_tokens(fn: ast.FunctionDef) -> set:
    """Every identifier-ish token in a function: parameter names, Name
    loads, attribute names, call targets, and string literals (the
    part labels fold_nondefault emits)."""
    tokens = set(_param_names(fn))
    for node in ast.walk(fn):
        if isinstance(node, ast.Name):
            tokens.add(node.id)
        elif isinstance(node, ast.Attribute):
            tokens.add(node.attr)
        elif isinstance(node, ast.Constant) and isinstance(node.value, str):
            tokens.add(node.value)
        elif isinstance(node, ast.Call):
            tokens.add(call_name(node))
    return tokens


def check_fingerprints(project: Project, reg=None) -> List[Finding]:
    reg = reg or default_registry
    pass_id = "fingerprints"
    out: List[Finding] = []
    for (rel, name), entry in reg.FINGERPRINT_BUILDERS.items():
        sf = project.file(rel)
        if sf is None:
            out.append(Finding(rel, 1, pass_id,
                               f"fingerprint builder file '{rel}' missing"))
            continue
        fn = sf.find_function(name)
        if fn is None:
            out.append(Finding(
                sf.rel, 1, pass_id,
                f"fingerprint builder '{name}' not found in '{rel}'",
            ))
            continue
        covered = set(entry["required"]) | set(entry["exempt"])
        missing_decl = set(reg.FINGERPRINT_KNOBS) - covered
        if missing_decl:
            out.append(Finding(
                sf.rel, fn.lineno, pass_id,
                f"registry entry for '{name}' does not account for "
                f"fingerprint knob(s) {sorted(missing_decl)}",
            ))
        for knob, reason in entry["exempt"].items():
            if not (reason or "").strip():
                out.append(Finding(
                    sf.rel, fn.lineno, pass_id,
                    f"exemption of fingerprint knob '{knob}' on "
                    f"'{name}' has no reason",
                ))
        tokens = _body_tokens(fn)
        for knob in entry["required"]:
            aliases = reg.FINGERPRINT_ALIASES.get(knob, (knob,))
            if not tokens.intersection(aliases):
                out.append(Finding(
                    sf.rel, fn.lineno, pass_id,
                    f"fingerprint builder '{name}' never folds in "
                    f"knob '{knob}' (looked for: "
                    f"{', '.join(aliases)})",
                ))
    return out
