"""Shared infrastructure for the rifraf-lint passes.

Everything here is pure stdlib (``ast``/``re``/``pathlib``) — the
analysis package must import and run on any machine, including CI
runners and dev boxes with no JAX installed, so no module in
``rifraf_tpu.analysis`` may import the rest of the package.

The pieces:

- ``Finding`` — one violation: repo-relative path, 1-based line, the
  pass id, and a human message. ``str()`` renders the
  ``path:line: [pass] message`` form the CLI prints.
- ``Suppressions`` — per-file map of ``# rifraf-lint: disable=<pass>``
  comments. A suppression must carry a reason after ``--``; one that
  does not is ITSELF a finding (pass id ``suppression``), so silencing
  the linter always leaves a paper trail.
- ``SourceFile`` / ``Project`` — parsed-file cache shared by all
  passes, with parent links on every AST node (``node._rifraf_parent``)
  so passes can walk upward to enclosing ``if``/``with``/function
  scopes.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass
from pathlib import Path
from typing import Dict, Iterator, List, Optional, Set, Tuple

SUPPRESS_RE = re.compile(
    r"#\s*rifraf-lint:\s*disable=([a-z0-9_,-]+)(?:\s*--\s*(?P<reason>.*\S))?\s*$"
)


@dataclass
class Finding:
    path: str
    line: int
    pass_id: str
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.pass_id}] {self.message}"

    def to_dict(self) -> dict:
        return {
            "path": self.path,
            "line": self.line,
            "pass": self.pass_id,
            "message": self.message,
        }


class Suppressions:
    """Per-file suppression map.

    A trailing comment suppresses its own line; a standalone comment
    line suppresses the NEXT line (so a suppression can sit above a
    long statement). ``disable=a,b`` suppresses several passes at once.
    """

    def __init__(self, source: str):
        self.by_line: Dict[int, Set[str]] = {}
        # (line, passes) of suppressions written without a reason
        self.missing_reason: List[Tuple[int, Set[str]]] = []
        for i, raw in enumerate(source.splitlines(), start=1):
            m = SUPPRESS_RE.search(raw)
            if m is None:
                continue
            passes = {p.strip() for p in m.group(1).split(",") if p.strip()}
            target = i if raw[: m.start()].strip() else i + 1
            self.by_line.setdefault(target, set()).update(passes)
            if not m.group("reason"):
                self.missing_reason.append((i, passes))

    def active(self, line: int, pass_id: str) -> bool:
        return pass_id in self.by_line.get(line, ())


def attach_parents(tree: ast.AST) -> None:
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            child._rifraf_parent = node  # type: ignore[attr-defined]


def parent(node: ast.AST) -> Optional[ast.AST]:
    return getattr(node, "_rifraf_parent", None)


def ancestors(node: ast.AST) -> Iterator[ast.AST]:
    cur = parent(node)
    while cur is not None:
        yield cur
        cur = parent(cur)


class SourceFile:
    def __init__(self, path: Path, root: Path):
        self.path = path
        self.rel = path.relative_to(root).as_posix()
        self.source = path.read_text()
        self.tree = ast.parse(self.source, filename=str(path))
        attach_parents(self.tree)
        self.suppress = Suppressions(self.source)

    def functions(self) -> Iterator[ast.FunctionDef]:
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield node

    def find_function(self, name: str) -> Optional[ast.FunctionDef]:
        for fn in self.functions():
            if fn.name == name:
                return fn
        return None

    def find_class(self, name: str) -> Optional[ast.ClassDef]:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.ClassDef) and node.name == name:
                return node
        return None


class Project:
    """Parsed-file cache rooted at the repo checkout."""

    def __init__(self, root):
        self.root = Path(root).resolve()
        self._cache: Dict[str, Optional[SourceFile]] = {}

    def file(self, rel: str) -> Optional[SourceFile]:
        if rel not in self._cache:
            path = self.root / rel
            if path.is_file():
                self._cache[rel] = SourceFile(path, self.root)
            else:
                self._cache[rel] = None
        return self._cache[rel]

    def iter_py(self, rel: str, skip: Tuple[str, ...] = ()) -> List[SourceFile]:
        """Every parsed .py under ``rel`` (a file or directory),
        skipping any repo-relative prefix in ``skip``."""
        path = self.root / rel
        out: List[SourceFile] = []
        if path.is_file():
            sf = self.file(rel)
            return [sf] if sf is not None else []
        if not path.is_dir():
            return []
        for p in sorted(path.rglob("*.py")):
            r = p.relative_to(self.root).as_posix()
            if any(r == s or r.startswith(s + "/") for s in skip):
                continue
            sf = self.file(r)
            if sf is not None:
                out.append(sf)
        return out

    def loaded(self) -> List[SourceFile]:
        return [sf for sf in self._cache.values() if sf is not None]


def dotted_name(node: ast.AST) -> str:
    """'jnp.bfloat16' for Attribute/Name chains, '' otherwise."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted_name(node.value)
        return f"{base}.{node.attr}" if base else node.attr
    return ""


def call_name(call: ast.Call) -> str:
    """Trailing identifier of a call target: jnp.max -> 'max'."""
    f = call.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return ""


def in_with_lock(node: ast.AST, locks: Tuple[str, ...]) -> bool:
    """Whether ``node`` sits inside a ``with self.<lock>:`` block for
    any lock name in ``locks``."""
    for anc in ancestors(node):
        if isinstance(anc, ast.With):
            for item in anc.items:
                expr = item.context_expr
                # `with self._lock:` or `with self._cv:` ...
                if (
                    isinstance(expr, ast.Attribute)
                    and isinstance(expr.value, ast.Name)
                    and expr.value.id == "self"
                    and expr.attr in locks
                ):
                    return True
                # ... or `with self._lock.acquire_timeout(...)`-style
                # calls on the lock object
                if (
                    isinstance(expr, ast.Call)
                    and isinstance(expr.func, ast.Attribute)
                    and isinstance(expr.func.value, ast.Attribute)
                    and isinstance(expr.func.value.value, ast.Name)
                    and expr.func.value.value.id == "self"
                    and expr.func.value.attr in locks
                ):
                    return True
    return False


def enclosing_function(node: ast.AST) -> Optional[ast.FunctionDef]:
    for anc in ancestors(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return anc
    return None
