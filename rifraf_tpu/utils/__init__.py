from .constants import (
    CODON_LENGTH,
    BASES,
    BASE_TO_INT,
    INT_TO_BASE,
    GAP_INT,
    encode_seq,
    decode_seq,
)
from .phred import (
    MIN_PHRED,
    MAX_PHRED,
    p_to_phred,
    phred_to_log_p,
    phred_to_p,
    cap_phreds,
    normalize,
)
from .fprint import fold_nondefault
from .mathops import logsumexp10, summax
from .shapes import bucket, pow2_bucket

__all__ = [
    "CODON_LENGTH",
    "BASES",
    "BASE_TO_INT",
    "INT_TO_BASE",
    "GAP_INT",
    "encode_seq",
    "decode_seq",
    "MIN_PHRED",
    "MAX_PHRED",
    "p_to_phred",
    "phred_to_log_p",
    "phred_to_p",
    "cap_phreds",
    "normalize",
    "fold_nondefault",
    "logsumexp10",
    "summax",
    "bucket",
    "pow2_bucket",
]
