"""Shared mesh plumbing: the shard_map compat shim and mesh-axis rounding.

Every layer that touches a device mesh needs the same two fragments:

- ``shard_map`` moved from ``jax.experimental.shard_map`` into the top
  namespace across JAX releases, renaming its varying-axes check from
  ``check_rep`` to ``check_vma`` on the way. The shim here accepts the
  NEW spelling and translates down, so call sites are written once
  against the current API.
- Batch axes that a mesh shards must round up to the mesh size so every
  shard carries the same local extent. The serving worker additionally
  rounds to powers of two first (logarithmic executable count); both
  rules compose in :func:`mesh_round`.

Hoisted out of ``parallel/sharding.py`` / ``serve/worker.py`` where the
two fragments had been copied; import from here everywhere mesh code
lives so the compat window and the rounding rule cannot drift.
"""

from __future__ import annotations

from .shapes import bucket as _bucket
from .shapes import pow2_bucket


def shard_map_compat(*args, **kwargs):
    """``jax.shard_map`` across the API migration: older releases keep
    it in ``jax.experimental.shard_map`` and call the varying-axes check
    ``check_rep`` instead of ``check_vma``. Write call sites against the
    new spelling; this shim translates for the old one."""
    try:
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
    return shard_map(*args, **kwargs)


def mesh_axis_size(mesh) -> int:
    """Number of devices a mesh shards over (1 for ``None`` — unsharded
    code paths pass their optional mesh straight through)."""
    return int(mesh.devices.size) if mesh is not None else 1


def mesh_round(n: int, mesh, pow2: bool = False) -> int:
    """Round a batch-axis extent so a mesh shards it evenly.

    ``pow2`` first rounds to the next power of two (the serving
    worker's rule: the set of distinct compiled batch shapes stays
    logarithmic), then to a multiple of the mesh axis — the order
    matters, a power of two is not necessarily a mesh multiple."""
    if pow2:
        n = pow2_bucket(n)
    return _bucket(n, mesh_axis_size(mesh))
