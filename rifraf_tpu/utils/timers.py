"""Lightweight named wall-clock timers for driver observability.

The reference has no profiling at all (SURVEY §5); the TPU driver needs
it because its cost structure is invisible from Python — a slow run can
be retracing, dispatch overhead, device compute, or host tracebacks, and
only per-section timing tells them apart.

Thread-safe: the serving stack shares one ``Timers`` (via
``serve.stats.ServerStats``) across worker, batcher, and supervisor
threads, so the read-modify-write in ``add`` and the iterations in
``merge``/``summary``/``to_dict`` run under an internal lock — an
unsynchronized ``data[name] = (n + 1, s + seconds)`` loses increments
when two sections finish concurrently, and iterating while another
thread inserts raises RuntimeError.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Dict, Tuple


class Timers:
    """name -> (calls, total_seconds); zero-dependency, host wall clock."""

    def __init__(self):
        self._lock = threading.Lock()
        self.data: Dict[str, Tuple[int, float]] = {}

    def add(self, name: str, seconds: float) -> None:
        with self._lock:
            n, s = self.data.get(name, (0, 0.0))
            self.data[name] = (n + 1, s + seconds)

    @contextmanager
    def time(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add(name, time.perf_counter() - t0)

    def merge(self, other: "Timers") -> None:
        with other._lock:
            items = list(other.data.items())
        with self._lock:
            for name, (n, s) in items:
                cn, cs = self.data.get(name, (0, 0.0))
                self.data[name] = (cn + n, cs + s)

    def summary(self) -> str:
        with self._lock:
            items = list(self.data.items())
        lines = []
        for name, (n, s) in sorted(items, key=lambda kv: -kv[1][1]):
            lines.append(f"  {name:28s} {n:6d} calls  {s*1e3:10.1f} ms")
        return "\n".join(lines)

    def to_dict(self) -> Dict[str, Dict[str, float]]:
        """JSON-serializable export: name -> {"calls", "seconds"}, sorted
        by descending total time like summary(). The serving stats
        surface (serve.stats.ServerStats) and bench.py emit this instead
        of reaching into .data."""
        with self._lock:
            items = list(self.data.items())
        return {
            name: {"calls": n, "seconds": round(s, 6)}
            for name, (n, s) in sorted(items, key=lambda kv: -kv[1][1])
        }
