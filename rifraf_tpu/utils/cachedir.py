"""Machine-fingerprinted JAX compilation-cache directories.

XLA:CPU AOT cache entries are machine-specific: loading entries compiled
on a different host (cache dirs survive image snapshots) emits
cpu_aot_loader machine-mismatch errors and has produced mid-process
segfaults on this image. Suffixing the dir with a CPU-feature
fingerprint keeps every machine in its own cache. Shared by the driver
(engine.driver._enable_compilation_cache) and the test suite
(tests/conftest.py) so the two schemes cannot drift.

The fingerprint cannot catch every staleness mode: a runtime upgrade
under an unchanged CPU (libtpu version bumps on TPU VMs are the
recorded case) leaves entries whose AOT payload the new client refuses
with ``FAILED_PRECONDITION: libtpu version mismatch``. The helpers
below classify that error and drop the poisoned entries so callers can
retry with a clean (or disabled) cache instead of failing the run.
"""

from __future__ import annotations

import hashlib
import os

# substrings that identify a persistent-cache entry the CURRENT runtime
# cannot load (vs a genuine compile error): the recorded failures are
# "FAILED_PRECONDITION: libtpu version mismatch: terminal has ... client
# AOT libtpu has ..." from device_put / executable deserialization, and
# the CPU analogue from cpu_aot_loader
_STALE_MARKERS = (
    "libtpu version mismatch",
    "cpu_aot_loader",
)


def machine_cache_dir(base: str) -> str:
    """``base`` suffixed with a fingerprint of the host CPU's feature
    flags."""
    try:
        with open("/proc/cpuinfo") as fh:
            flags = next(
                (ln for ln in fh if ln.startswith("flags")), "unknown"
            )
    except OSError:
        flags = "unknown"
    fp = hashlib.md5(flags.encode()).hexdigest()[:10]
    return f"{base}_{fp}"


def is_stale_cache_error(err) -> bool:
    """Whether an exception (or captured output text) carries the
    stale-AOT-cache signature: the named markers, or a
    ``FAILED_PRECONDITION`` that mentions an AOT payload. Anything else
    — including FAILED_PRECONDITION from a real shape/runtime problem —
    is NOT classified stale; dropping the cache must never mask a
    genuine failure."""
    msg = str(err)
    if any(m in msg for m in _STALE_MARKERS):
        return True
    return "FAILED_PRECONDITION" in msg and "AOT" in msg


def default_aot_cache_dir() -> str:
    """The default AOT-executable cache directory (serve.aot): a
    machine-fingerprinted sibling of the XLA compilation cache.
    ``RIFRAF_TPU_AOT_CACHE`` overrides it with an explicit path (or
    disables with ``off``/empty — the caller checks that before asking
    for a default)."""
    return machine_cache_dir(
        os.path.expanduser("~/.cache/rifraf_tpu_aot")
    )


def atomic_write_bytes(path: str, data: bytes) -> None:
    """Write ``data`` to ``path`` atomically (tmp file + rename in the
    same directory), creating parent directories. A reader — another
    process deserializing AOT entries mid-write — sees either the old
    file or the complete new one, never a torn payload."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp-{os.getpid()}"
    try:
        with open(tmp, "wb") as fh:
            fh.write(data)
        os.replace(tmp, path)
    finally:
        try:
            if os.path.exists(tmp):
                os.unlink(tmp)
        except OSError:
            pass


def clear_cache_dir(path) -> int:
    """Drop every persistent-cache entry under ``path`` (files only; the
    directory and any subdirectories stay, so a configured cache dir
    remains valid). Returns the number of entries removed; missing or
    unreadable paths are a 0-entry no-op — recovery must never raise."""
    if not path:
        return 0
    removed = 0
    try:
        for root, _dirs, files in os.walk(path):
            for name in files:
                try:
                    os.unlink(os.path.join(root, name))
                    removed += 1
                except OSError:
                    pass
    except OSError:
        pass
    return removed
