"""Machine-fingerprinted JAX compilation-cache directories.

XLA:CPU AOT cache entries are machine-specific: loading entries compiled
on a different host (cache dirs survive image snapshots) emits
cpu_aot_loader machine-mismatch errors and has produced mid-process
segfaults on this image. Suffixing the dir with a CPU-feature
fingerprint keeps every machine in its own cache. Shared by the driver
(engine.driver._enable_compilation_cache) and the test suite
(tests/conftest.py) so the two schemes cannot drift.
"""

from __future__ import annotations

import hashlib


def machine_cache_dir(base: str) -> str:
    """``base`` suffixed with a fingerprint of the host CPU's feature
    flags."""
    try:
        with open("/proc/cpuinfo") as fh:
            flags = next(
                (ln for ln in fh if ln.startswith("flags")), "unknown"
            )
    except OSError:
        flags = "unknown"
    fp = hashlib.md5(flags.encode()).hexdigest()[:10]
    return f"{base}_{fp}"
