"""Base-level constants and sequence encoding.

TPU-native re-design of the reference's primitive layer
(/root/reference/src/util.jl:1-5, src/types.jl): DNA sequences are int8 code
arrays (A=0, C=1, G=2, T=3) so they can live on device; strings only exist at
the I/O boundary.
"""

from __future__ import annotations

import numpy as np

CODON_LENGTH = 3

BASES = "ACGT"
BASE_TO_INT = {"A": 0, "C": 1, "G": 2, "T": 3}
INT_TO_BASE = np.array(list(BASES))

# Code used for padding / gaps in int8 sequence arrays.
GAP_INT = -1


def encode_seq(seq: str) -> np.ndarray:
    """Encode a DNA string as an int8 code array (A=0, C=1, G=2, T=3)."""
    if len(seq) == 0:
        return np.zeros(0, dtype=np.int8)
    arr = np.frombuffer(seq.upper().encode("ascii"), dtype=np.uint8)
    out = np.full(arr.shape, GAP_INT, dtype=np.int8)
    for base, code in BASE_TO_INT.items():
        out[arr == ord(base)] = code
    if (out == GAP_INT).any():
        bad = seq[int(np.argmax(out == GAP_INT))]
        raise ValueError(f"invalid DNA character: {bad!r}")
    return out


def decode_seq(codes: np.ndarray) -> str:
    """Decode an int8 code array back to a DNA string (ignores padding)."""
    codes = np.asarray(codes)
    codes = codes[codes >= 0]
    if codes.size == 0:
        return ""
    return "".join(INT_TO_BASE[codes])


def reverse_complement(codes: np.ndarray) -> np.ndarray:
    """Reverse complement of an int8 code array. With A=0, C=1, G=2, T=3
    the complement is simply 3 - code; padding/gap codes are preserved."""
    codes = np.asarray(codes, dtype=np.int8)
    out = np.where(codes >= 0, 3 - codes, codes).astype(np.int8)
    return out[::-1].copy()
