"""Fingerprint part helpers shared by every resumable-journal surface.

The sweep journal (parallel.sweep_sharded) and the serve spool journal
(cli.serve) both need the same backward-compatibility move when a new
knob joins their fingerprint: fold it in ONLY when it differs from the
default, so every journal minted before the knob existed keeps its
digest and stays resumable. Before this module each site re-implemented
the conditional inline; centralizing it gives the fingerprint-coverage
lint pass (``rifraf_tpu.analysis``, pass ``fingerprints``) one named
idiom to look for and keeps the two digests drifting in lockstep.
"""

from __future__ import annotations

from typing import Any, List


def fold_nondefault(name: str, value: Any, default: Any) -> List[Any]:
    """Fingerprint part-pair for one knob: ``[]`` at the default value
    (pre-knob journals keep their digest), else ``[name, value]``.
    Splat the result into the ``fingerprint(...)`` part list:

        fingerprint(*base_parts,
                    *fold_nondefault("input_enc", input_enc, "f32"))

    The comparison is ``==``, so pass values already normalized to the
    journaled representation (e.g. ``bool(guard)``, not a truthy
    object — ``repr`` of the part is what lands in the digest)."""
    return [] if value == default else [name, value]
