"""Bytes-moved / FLOP models and a per-call roofline registry.

The Pallas engines are bandwidth-bound: every kernel streams its band,
tables, and (for the stats sweep) the in-kernel move codes through HBM
once, so seconds alone say nothing about how close a run sits to the
hardware. This module is the single definition of the per-kernel
byte/op models (hoisted from exp/roofline.py so bench.py, the exp
scripts, and the realign engine all report the SAME accounting), plus a
tiny bounded registry that non-jit wrappers use to record the block
plan and modelled traffic of each dispatch.

Peaks default to TPU v5e public numbers
(cloud.google.com/tpu/docs/v5e): 819 GB/s HBM; the VPU f32 roof is
~ 8 sublanes * 128 lanes * 4 ALUs * ~0.94 GHz ~ 3.8 Top/s (the MXU is
unused: the DP has no matmuls). Override the HBM roof for other chips
with RIFRAF_TPU_HBM_GBPS.

All models count PADDED shapes (T1p columns, Npad lanes, K band rows)
— that is what the chip actually moves; the lane-packing occupancy from
utils.shapes.pack_lanes says how much of it was useful.
"""

from __future__ import annotations

import math
import os
import threading
from typing import Dict, List, Optional

HBM_GBPS = float(os.environ.get("RIFRAF_TPU_HBM_GBPS", "819.0"))
VPU_TOPS = float(os.environ.get("RIFRAF_TPU_VPU_TOPS", "3.8"))
# v5e interchip interconnect: 1600 Gbps per chip (cloud.google.com/tpu/
# docs/v5e) = 200 GB/s; override for other topologies with
# RIFRAF_TPU_ICI_GBPS.
ICI_GBPS = float(os.environ.get("RIFRAF_TPU_ICI_GBPS", "200.0"))

_F32 = 4


def _tab_bytes_per_step(CB: int, Npad: int, input_enc: str = "f32") -> int:
    """Per-stream per-grid-step HBM bytes of the five blocked input
    tables (mt/mm/gi/dl score planes + read codes). "f32" streams all
    five as 4-byte floats; "packed" (ops.encoding) streams the four
    score planes as int8 and the codes as 2-bit-packed int32 words
    (16 codes per lane word, ceil(CB/16) rows)."""
    if input_enc == "packed":
        words = -(-CB // 16)
        return (4 * CB * 1 + words * 4) * Npad
    return 5 * CB * Npad * _F32


def _sq_bytes_per_step(CB: int, Npad: int, input_enc: str = "f32") -> int:
    """Per-grid-step HBM bytes of the blocked read-code table alone
    (the stats kernel's only input plane)."""
    if input_enc == "packed":
        return (-(-CB // 16)) * 4 * Npad
    return CB * Npad * _F32


def _qmeta_bytes(Npad: int, input_enc: str = "f32") -> int:
    """Per-launch bytes of the packed path's [8, Npad] f32 per-read
    scale/offset plane (zero for f32 — no metadata is shipped)."""
    return 8 * Npad * _F32 if input_enc == "packed" else 0


def fill_model(
    T1p: int,
    K: int,
    Npad: int,
    C: int,
    n_streams: int = 2,
    want_moves: bool = False,
    moves_lanes: Optional[int] = None,
    band_itemsize: int = _F32,
    input_enc: str = "f32",
) -> Dict[str, float]:
    """HBM bytes + VPU ops for one fill dispatch: 5 blocked tables per
    stream read once per grid step (halo'd: C+K rows per C columns),
    the band written once, and — with ``want_moves`` — the int32 move
    band written once across ``moves_lanes`` lanes (the fused layout
    launches fwd+rev lanes but only fills the forward half).

    ``band_itemsize`` is the HBM store width of the band tables
    (params.band_dtype: 4 for f32, 2 for bf16) — the emission tables
    and move codes stay 4-byte regardless. ``input_enc`` sets the wire
    width of the five input tables (params.input_enc: "packed" streams
    int8 score planes + 2-bit codes + one [8, Npad] qmeta plane)."""
    n_steps = T1p // C
    CB = C + K
    tab = (n_streams * n_steps * _tab_bytes_per_step(CB, Npad, input_enc)
           + _qmeta_bytes(Npad, input_enc))
    band = n_streams * K * T1p * Npad * band_itemsize
    moves = 0
    if want_moves:
        moves = K * T1p * (moves_lanes if moves_lanes else Npad) * _F32
    cells = n_streams * K * T1p * Npad
    # per cell: ~2 table selects, 2 adds + max (cand), two log-K scans
    # (add + max) ~ 2*log2(K) ops, one select ~= 8 + 2*log2(K)
    ops = cells * (8 + 2 * math.log2(K))
    return {"bytes": float(tab + band + moves), "ops": float(ops),
            "tab_bytes": float(tab), "band_bytes": float(band),
            "moves_bytes": float(moves)}


def dense_model(T1p: int, K: int, Npad: int, C: int,
                band_itemsize: int = _F32,
                input_enc: str = "f32") -> Dict[str, float]:
    """HBM bytes + VPU ops for the dense candidate-tables kernel plus
    the backward-alignment halo program that feeds it: the halo program
    reads the raw reversed band once and writes the halo-blocked copy;
    the kernel reads the forward half of the band, the halo-blocked
    backward band, and the 5 forward tables again, and writes the
    [T1p, 16, Npad] per-column join maxima. Band traffic scales with
    ``band_itemsize`` (params.band_dtype), the table re-read with
    ``input_enc`` (params.input_enc); output tiles stay 4-byte."""
    n_steps = T1p // C
    CB = C + K
    bh = n_steps * (C + 1) * K * Npad * band_itemsize
    halo_src = K * T1p * Npad * band_itemsize  # raw Brev read (halo prog)
    rd = (K * T1p * Npad * band_itemsize + bh
          + n_steps * _tab_bytes_per_step(CB, Npad, input_enc)
          + _qmeta_bytes(Npad, input_enc))
    out = T1p * 16 * Npad * _F32
    # per column per base: 2 scans + joins over K rows, 9 outputs
    ops = T1p * Npad * K * (8 * (4 + 2 * math.log2(K)) + 10)
    return {"bytes": float(rd + out + bh + halo_src), "ops": float(ops),
            "halo_bytes": float(bh), "halo_src_bytes": float(halo_src)}


def stats_model(
    T1p: int, K: int, Npad: int, C: int, moves_itemsize: int = 4,
    input_enc: str = "f32",
) -> Dict[str, float]:
    """HBM bytes + VPU ops for the reverse-sweep stats kernel: reads
    the move band once (int32 from the fused layout, int8 from the
    panel store), the blocked read-base table once (2-bit word rows
    under ``input_enc="packed"`` — the stats sweep needs no qmeta), and
    writes the [T1p, 16, Npad] per-column edit tiles plus an 8-row
    accumulator."""
    n_steps = T1p // C
    CB = C + K
    moves = K * T1p * Npad * moves_itemsize
    sq = n_steps * _sq_bytes_per_step(CB, Npad, input_enc)
    tiles = T1p * 16 * Npad * _F32
    acc = 8 * Npad * _F32
    # per cell: decode + on-path closure (two log-K scans) + indicator
    # joins ~= 10 + 4*log2(K)
    ops = K * T1p * Npad * (10 + 4 * math.log2(K))
    return {"bytes": float(moves + sq + tiles + acc), "ops": float(ops),
            "moves_bytes": float(moves), "tiles_bytes": float(tiles)}


def fused_model(
    T1p: int,
    K: int,
    Npad: int,
    C: int,
    want_stats: bool = False,
    stats_itemsize: int = 4,
    band_itemsize: int = _F32,
    input_enc: str = "f32",
) -> Dict[str, float]:
    """One fused consensus step: two-stream fill + backward halo +
    dense tables, plus — with ``want_stats`` — the move-band write and
    the reverse stats sweep."""
    f = fill_model(T1p, K, Npad, C, n_streams=2, want_moves=want_stats,
                   moves_lanes=2 * Npad, band_itemsize=band_itemsize,
                   input_enc=input_enc)
    d = dense_model(T1p, K, Npad, C, band_itemsize=band_itemsize,
                    input_enc=input_enc)
    total = f["bytes"] + d["bytes"]
    ops = f["ops"] + d["ops"]
    parts = {"fill": f, "dense": d}
    if want_stats:
        s = stats_model(T1p, K, Npad, C, moves_itemsize=stats_itemsize,
                        input_enc=input_enc)
        total += s["bytes"]
        ops += s["ops"]
        parts["stats"] = s
    return {"bytes": float(total), "ops": float(ops), "parts": parts}


def fused_mega_model(
    T1p: int,
    K: int,
    Npad: int,
    C: int,
    want_stats: bool = False,
    spread: int = 0,
    band_itemsize: int = _F32,
    input_enc: str = "f32",
) -> Dict[str, float]:
    """One SINGLE-LAUNCH fused step (ops.fused_pallas megakernel): the
    band bytes are counted ONCE per direction — each stream's band is
    written to the chained scratch in phase 1 and read back in phase 2 —
    instead of the split path's write + halo-copy (write AND read) +
    re-read. The move codes likewise stay in scratch: one int32 write,
    one read, no int8 round trip. ``spread`` widens the phase-2 backward
    window for lane-packed launches (per-problem template lengths make
    the window (C + 2 + spread) columns instead of (C + 2))."""
    n_steps = T1p // C
    CB = C + K
    # phase 1: both streams' tables read once (wire width set by
    # input_enc, plus one qmeta plane when packed); both bands written
    # once; the move band written once (int32) when the stats chain is on
    tab = (2 * n_steps * _tab_bytes_per_step(CB, Npad, input_enc)
           + _qmeta_bytes(Npad, input_enc))
    band_w = 2 * K * T1p * Npad * band_itemsize
    moves = K * T1p * Npad * _F32 if want_stats else 0.0
    # phase 2: A read back once; B read back through the rolled window
    # ((C + 2 + spread) columns per C output columns); forward tables
    # re-read; dense tiles out; moves read back + stats tiles out
    a_r = K * T1p * Npad * band_itemsize
    b_r = n_steps * (C + 2 + spread) * K * Npad * band_itemsize
    tab2 = n_steps * _tab_bytes_per_step(CB, Npad, input_enc)
    tiles = T1p * 16 * Npad * _F32
    total = tab + band_w + moves + a_r + b_r + tab2 + tiles
    if want_stats:
        total += moves  # read back
        total += T1p * 16 * Npad * _F32 + 8 * Npad * _F32  # stats tiles
    cells = 2 * K * T1p * Npad
    ops = cells * (8 + 2 * math.log2(K))  # fills
    ops += T1p * Npad * K * (8 * (4 + 2 * math.log2(K)) + 10)  # dense
    if want_stats:
        ops += K * T1p * Npad * (10 + 4 * math.log2(K))
    return {"bytes": float(total), "ops": float(ops),
            "tab_bytes": float(tab + tab2),
            "band_bytes": float(band_w + a_r + b_r),
            "moves_bytes": float(2 * moves if want_stats else 0.0)}


def ici_collective_bytes(
    T1p: int, n_devices: int, want_stats: bool = False,
) -> float:
    """Per-device ICI bytes of one read-axis-sharded fused step's
    cross-chip epilogue (parallel.sharding.mesh_fused_step_pallas): a
    ring all-reduce moves ``2 * (n - 1) / n`` of the reduced payload
    through each device's links. The payload is the psum'd dense edit
    tables — sub ``[T1p, 4]`` + ins ``[T1p, 4]`` + del ``[T1p]`` — plus
    the total/convergence scalars, and with ``want_stats`` the pmax'd
    edits-indicator union ``[T1p, 9]``. Per-read vectors (scores,
    n_errors) stay shard-local and cost nothing."""
    if n_devices <= 1:
        return 0.0
    payload = (9 * T1p + 2) * _F32
    if want_stats:
        payload += 9 * T1p * _F32
    return payload * 2.0 * (n_devices - 1) / n_devices


def mesh_fused_model(
    T1p: int,
    K: int,
    Npad_local: int,
    C: int,
    n_devices: int,
    want_stats: bool = False,
    impl: str = "mega",
    band_itemsize: int = _F32,
) -> Dict[str, float]:
    """One fused step sharded over ``n_devices`` chips: per-device HBM
    bytes at the LOCAL lane count plus the ICI collective term, against
    the single-device model at the full lane count — so read-axis
    scaling efficiency is a modeled number. The returned
    ``scaling_efficiency`` is the modeled speedup over one device
    divided by ``n_devices`` (1.0 = perfectly linear; the ICI term and
    any lane re-padding are what pull it below)."""
    per_model = fused_mega_model if impl == "mega" else fused_model
    per = per_model(T1p, K, Npad_local, C, want_stats=want_stats,
                    band_itemsize=band_itemsize)
    ici = ici_collective_bytes(T1p, n_devices, want_stats=want_stats)
    t_dev = per["bytes"] / (HBM_GBPS * 1e9) + ici / (ICI_GBPS * 1e9)
    one = per_model(T1p, K, Npad_local * n_devices, C,
                    want_stats=want_stats, band_itemsize=band_itemsize)
    t_one = one["bytes"] / (HBM_GBPS * 1e9)
    speedup = t_one / t_dev if t_dev > 0 else float(n_devices)
    return {
        "bytes_per_device": float(per["bytes"]),
        "ici_bytes_per_device": float(ici),
        "ops_per_device": float(per["ops"]),
        "single_device_bytes": float(one["bytes"]),
        "t_model_s": float(t_dev),
        "t_single_s": float(t_one),
        "model_speedup": float(speedup),
        "scaling_efficiency": float(speedup / max(n_devices, 1)),
    }


def utilization(nbytes: float, seconds: float) -> Dict[str, float]:
    """Achieved bandwidth and fraction of the HBM roof."""
    if seconds <= 0:
        return {"gbps": 0.0, "pct_hbm": 0.0}
    gbps = nbytes / 1e9 / seconds
    return {"gbps": gbps, "pct_hbm": 100.0 * gbps / HBM_GBPS}


# ---- per-call registry -----------------------------------------------------
# Non-jit wrappers (engine.realign, the panel driver, bench) record the
# block plan + modelled traffic of each dispatch here; jit bodies trace
# once, so recording must happen OUTSIDE them. Bounded so long sweeps
# don't grow host memory; snapshot() drains a copy for reporting.

_MAX_RECORDS = 256
_records: List[Dict] = []
_lock = threading.Lock()


def record(kernel: str, **fields) -> None:
    """Append one dispatch record ({"kernel": ..., **fields}); keeps
    only the most recent _MAX_RECORDS."""
    entry = {"kernel": kernel}
    entry.update(fields)
    with _lock:
        _records.append(entry)
        if len(_records) > _MAX_RECORDS:
            del _records[: len(_records) - _MAX_RECORDS]


def snapshot() -> List[Dict]:
    """Copy of the current records, oldest first."""
    with _lock:
        return [dict(r) for r in _records]


def clear() -> None:
    with _lock:
        _records.clear()
