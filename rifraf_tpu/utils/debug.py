"""DEBUG-gated assertions.

Mirrors /root/reference/src/util.jl:7-15 (`DEBUG` + `@myassert`): invariant
checks that can be disabled. Python has no macros, so ``myassert`` only
skips the *raise* when the flag is off — its condition argument is still
evaluated. For invariants whose condition is itself expensive, guard the
whole call at the call site: ``if debug.DEBUG: myassert(...)``. Disable
with ``rifraf_tpu.utils.debug.DEBUG = False`` or env ``RIFRAF_TPU_DEBUG=0``
(read once at import).
"""

from __future__ import annotations

import os

DEBUG = os.environ.get("RIFRAF_TPU_DEBUG", "1").lower() not in (
    "0", "false", "no", "off"
)


def myassert(condition: bool, msg: str) -> None:
    """Raise unless ``condition``, only when DEBUG is on (util.jl:10-15)."""
    if DEBUG and not condition:
        raise AssertionError(msg)
