"""Shared shape-grid helpers and the VMEM block planner.

Every device program in this repo is compiled for PADDED shapes drawn
from a fixed grid — template columns to `len_bucket` multiples, band
heights to sublane multiples, read lanes to 128 — so the hill-climbing
loop's changing problem sizes re-use cached XLA executables instead of
recompiling (engine.realign module docstring). These helpers are the
single definition of that rounding; engine.realign,
ops.align_codon_jax, and parallel.sweep_sharded all import them
(three private copies existed before).

`plan_cols` is the single VMEM budgeter for every Pallas kernel's
columns-per-grid-step choice (it replaces the private
`fill_pallas._pick_cols` and `dense_pallas.pick_dense_cols` copies):
each kernel declares its double-buffered per-grid-step working set in
[rows, 128]-lane f32 tiles, and the planner picks the largest
power-of-two divisor of T1p that fits the budget. The returned
BlockPlan carries the sizing model alongside the choice so callers
(engine.realign, bench, exp/roofline) can record WHY a block shape was
chosen, not just what it was.
"""

from __future__ import annotations

from typing import List, NamedTuple, Sequence

LANES = 128
_STAT_ROWS = 16  # dense/stats per-column output rows (dense_pallas.ROWS)

# per-kernel column caps: fill streams whole output blocks so it can
# afford wide steps; dense is capped at T1p // 2 so the backward halo
# slice (C + 1 columns) always fits inside the band; stats matches fill
# (it re-reads the fill's blocked tables at the fill's block shape).
_COL_CAPS = {
    "fill": lambda T1p: min(T1p, 512),
    "dense": lambda T1p: min(T1p // 2, 256),
    "stats": lambda T1p: min(T1p, 512),
    # the single-launch megakernel chains fill -> dense -> stats through
    # on-chip carry, so its per-step set is the max of both phases
    "fused": lambda T1p: min(T1p // 2, 256),
}


class BlockPlan(NamedTuple):
    """One kernel's chosen VMEM blocking, plus the model behind it."""

    kernel: str  # "fill" | "dense" | "stats"
    T1p: int  # padded template columns
    K: int  # uniform band height
    cols: int  # columns per grid step (the choice)
    n_steps: int  # T1p // cols
    vmem_bytes: int  # modelled double-buffered working set at `cols`
    vmem_budget: int  # the budget it was fit under

    @property
    def fits(self) -> bool:
        """Whether even the chosen block width fits the budget. plan_cols
        always returns cols >= 1; when the 1-column working set already
        overflows, callers must decline the kernel (the megakernel falls
        back to the split 3-launch path on this signal)."""
        return self.vmem_bytes <= self.vmem_budget


def _block_rows(kernel: str, c: int, K: int, want_moves: bool) -> int:
    """Double-buffered per-grid-step working set of one kernel at block
    width ``c``, in [rows, 128] f32 tiles (multiply by 2*128*4 for
    bytes). These formulas ARE the sizing model — they deliberately
    reproduce the historical per-module pickers bit-for-bit so hoisting
    the planner changes no compiled shape."""
    if kernel == "fill":
        # output band block [C*K, 128] (twice with a move-band output)
        # + 5 halo'd table blocks [C+K, 128]
        out_blocks = 2 if want_moves else 1
        return out_blocks * c * K + 5 * (c + K)
    if kernel == "dense":
        # A block C*K + B halo (C+1)*K + 5 tables (C+K) + out C*ROWS
        return c * K + (c + 1) * K + 5 * (c + K) + c * _STAT_ROWS
    if kernel == "stats":
        # moves block C*K (int8 input still budgeted as f32: the kernel
        # widens on load) + seq table block (C+K) + out tiles C*16
        return c * K + (c + K) + c * _STAT_ROWS
    if kernel == "fused":
        # ops.fused_pallas megakernel: phase 1 holds both streams' table
        # blocks + two fill tiles (+ the move tile with stats); phase 2
        # holds the A tile, the (C+2)-column B window, the forward
        # tables, the dense out tile (+ the move tile and stats tiles).
        # ``want_moves`` here means the stats chain is fused in.
        p1 = 10 * (c + K) + 2 * c * K + (c * K if want_moves else 0)
        p2 = (c * K + (c + 2) * K + 5 * (c + K) + c * _STAT_ROWS
              + ((c * K + c * _STAT_ROWS) if want_moves else 0))
        return max(p1, p2)
    raise ValueError(f"unknown kernel: {kernel!r}")


def plan_cols(
    T1p: int,
    K: int,
    kernel: str = "fill",
    want_moves: bool = False,
    vmem_budget: int = 9 << 20,
) -> BlockPlan:
    """Pick columns-per-grid-step for one Pallas kernel: the largest
    power-of-two divisor of ``T1p`` (>= 1, under the kernel's cap)
    whose double-buffered working set fits ``vmem_budget`` bytes. T1p
    is a multiple of 64 for bucketed templates, so powers of two up to
    64 always divide it. Monotone in the budget: a larger budget never
    yields fewer columns (tests/test_shapes_planner.py)."""
    cap = _COL_CAPS[kernel](T1p)
    best = 1
    c = 1
    while c <= cap:
        if T1p % c == 0:
            need = 2 * LANES * 4 * _block_rows(kernel, c, K, want_moves)
            if need <= vmem_budget:
                best = c
        c *= 2
    return BlockPlan(
        kernel=kernel,
        T1p=T1p,
        K=K,
        cols=best,
        n_steps=T1p // best,
        vmem_bytes=2 * LANES * 4 * _block_rows(kernel, best, K, want_moves),
        vmem_budget=vmem_budget,
    )


class LanePacking(NamedTuple):
    """Length-sorted assignment of reads to 128-lane tiles.

    The uniform band frame sizes every lane's DP band by the GLOBAL
    (K, T1p), so a tile mixing a 200 bp read with 3 kb neighbours
    moves the 3 kb tile's bytes for everyone. Packing reads into tiles
    by descending length makes each tile's max length (and hence the
    bytes a length-aware layout must move for it) tight. This is the
    ACCOUNTING for that packing — callers sort/report with it (the
    sweep planner's occupancy stats, the roofline layer); the driver's
    read order itself is unchanged, keeping results bit-identical."""

    order: List[int]  # read indices, length-descending (stable)
    inverse: List[int]  # inverse permutation: orig idx -> packed slot
    n_tiles: int  # ceil(n_reads / lanes)
    tile_max: List[int]  # max length per packed tile
    occupancy: float  # useful cells / packed per-tile-max cells
    uniform_occupancy: float  # useful cells / global-max cells


def pack_lanes(lengths: Sequence[int], lanes: int = LANES) -> LanePacking:
    """Pack reads into ``lanes``-wide tiles by descending length and
    report the padded-cell occupancy of the packed layout vs the
    uniform (pad-everything-to-global-max) layout."""
    lens = [int(x) for x in lengths]
    n = len(lens)
    if n == 0:
        return LanePacking([], [], 0, [], 1.0, 1.0)
    order = sorted(range(n), key=lambda i: (-lens[i], i))
    inverse = [0] * n
    for slot, i in enumerate(order):
        inverse[i] = slot
    n_tiles = (n + lanes - 1) // lanes
    tile_max = [
        max(lens[i] for i in order[t * lanes : (t + 1) * lanes])
        for t in range(n_tiles)
    ]
    useful = sum(lens)
    packed_cells = sum(m * lanes for m in tile_max)
    uniform_cells = n_tiles * lanes * max(lens)
    return LanePacking(
        order=order,
        inverse=inverse,
        n_tiles=n_tiles,
        tile_max=tile_max,
        occupancy=useful / packed_cells if packed_cells else 1.0,
        uniform_occupancy=useful / uniform_cells if uniform_cells else 1.0,
    )


class SegmentPacking(NamedTuple):
    """Assignment of whole PROBLEMS (clusters) to shared lane blocks.

    ``pack_lanes`` packs one problem's reads into tiles; this packs
    many small problems into ONE ``[Npad]`` read block at READ
    granularity — each problem occupies exactly its own read lanes
    (optionally rounded to ``align``), identified by a per-lane
    problem-id segment mask, instead of riding a whole
    ``bucket(n_reads, read_bucket)`` block of its own. Every lane-axis
    reduction downstream must then be segment-aware
    (ops.fused.fused_step_segmented): per-segment masked sums walk the
    lane axis in the same order with exact zeros elsewhere, so packed
    results stay bit-identical to per-problem runs.

    ``blocks[b]`` lists (problem index, lane offset, n_lanes) for block
    ``b``; ``seg_ids[b]`` is the per-lane problem-SLOT id of block b
    (slot s = s-th member of the block, NOT the global problem index;
    pad lanes hold slot 0 and must carry weight 0)."""

    blocks: List[List[tuple]]  # per block: (problem, offset, n_lanes)
    seg_ids: List[List[int]]  # per block: [npad] per-lane slot ids
    npad: int  # shared lane-block height (all blocks one shape)
    n_seg: int  # max problems per block (the static segment axis)
    occupancy: float  # useful lanes / (n_blocks * npad)


def pack_segments(
    counts: Sequence[int],
    lanes: int = LANES,
    align: int = 1,
) -> SegmentPacking:
    """First-fit-decreasing packing of problem read counts into shared
    ``lanes``-high blocks. ``align`` rounds each problem's lane
    footprint (use the per-problem read grid on backends whose lane
    reductions are tree-shaped rather than sequential — a segment whose
    lanes start at a multiple of its own padded width reduces under the
    same tree shape as its per-problem block; the default 1 is exact
    for order-preserving reductions, which is what the XLA fused step
    compiles to on current backends). Problems wider than ``lanes``
    are rejected — the caller routes those through whole-block
    execution (the packer declines)."""
    counts = [int(c) for c in counts]
    if any(c <= 0 for c in counts):
        raise ValueError("pack_segments needs positive read counts")
    widths = [bucket(c, align) if align > 1 else c for c in counts]
    if any(w > lanes for w in widths):
        raise ValueError("problem wider than one lane block")
    order = sorted(range(len(counts)), key=lambda i: (-widths[i], i))
    blocks: List[List[tuple]] = []
    used: List[int] = []
    for i in order:
        w = widths[i]
        for b, u in enumerate(used):
            if u + w <= lanes:
                blocks[b].append((i, u, counts[i]))
                used[b] = u + w
                break
        else:
            blocks.append([(i, 0, counts[i])])
            used.append(w)
    if not blocks:
        return SegmentPacking([], [], 0, 0, 1.0)
    # keep input order within each block (the sweep's documented
    # intra-bucket order invariant) and recompute contiguous offsets
    npad = lanes if len(blocks) > 1 else bucket(max(used), align)
    seg_ids = []
    for b, members in enumerate(blocks):
        members.sort(key=lambda t: t[0])
        off = 0
        ids = []
        for s, (i, _, n) in enumerate(members):
            members[s] = (i, off, n)
            ids.extend([s] * widths[i])
            off += widths[i]
        ids.extend([0] * (npad - len(ids)))
        seg_ids.append(ids)
    useful = sum(counts)
    return SegmentPacking(
        blocks=blocks,
        seg_ids=seg_ids,
        npad=npad,
        n_seg=max(len(m) for m in blocks),
        occupancy=useful / (len(blocks) * npad) if blocks else 1.0,
    )


def bucket(n: int, b: int) -> int:
    """Round ``n`` up to the next multiple of ``b``."""
    return ((n + b - 1) // b) * b


def pow2_bucket(n: int) -> int:
    """Round ``n`` up to the next power of two (>= 1). Used for axes
    whose exact size varies freely (e.g. the cluster axis of a sweep
    bucket) to cap the number of distinct compiled shapes at log2."""
    return 1 << max(n - 1, 0).bit_length()
