"""Shared shape-grid helpers.

Every device program in this repo is compiled for PADDED shapes drawn
from a fixed grid — template columns to `len_bucket` multiples, band
heights to sublane multiples, read lanes to 128 — so the hill-climbing
loop's changing problem sizes re-use cached XLA executables instead of
recompiling (engine.realign module docstring). These helpers are the
single definition of that rounding; engine.realign,
ops.align_codon_jax, and parallel.sweep_sharded all import them
(three private copies existed before).
"""

from __future__ import annotations


def bucket(n: int, b: int) -> int:
    """Round ``n`` up to the next multiple of ``b``."""
    return ((n + b - 1) // b) * b


def pow2_bucket(n: int) -> int:
    """Round ``n`` up to the next power of two (>= 1). Used for axes
    whose exact size varies freely (e.g. the cluster axis of a sweep
    bucket) to cap the number of distinct compiled shapes at log2."""
    return 1 << max(n - 1, 0).bit_length()
