"""Log-space math helpers (reference: /root/reference/src/util.jl:24-48)."""

from __future__ import annotations

import numpy as np


def logsumexp10(x) -> float:
    """LogSumExp in base 10 (util.jl:28-38)."""
    x = np.asarray(x, dtype=np.float64)
    if x.size == 0:
        return -np.inf
    u = np.max(x)
    if np.isinf(u):
        return float("nan") if np.isnan(x).any() else float(u)
    return float(np.log10(np.sum(np.power(10.0, x - u))) + u)


def poisson_cquantile(mean: float, pvalue: float) -> float:
    """Complementary quantile of Poisson(mean): smallest k with
    P(X > k) <= pvalue. Matches Distributions.cquantile used for adaptive
    bandwidth (reference model.jl:661). Exact summation for small means,
    Wilson-Hilferty normal approximation for large ones."""
    from statistics import NormalDist

    if mean <= 0:
        return 0.0
    target = 1.0 - pvalue
    if mean < 50.0:
        import math

        pmf = math.exp(-mean)
        cdf = pmf
        k = 0
        while cdf < target and k < 10_000:
            k += 1
            pmf *= mean / k
            cdf += pmf
        return float(k)
    z = NormalDist().inv_cdf(target)
    # Wilson-Hilferty transformation for the Poisson quantile
    k = mean * (1.0 - 1.0 / (9.0 * mean) + z / (3.0 * mean ** 0.5)) ** 3
    return float(np.ceil(k))


def summax(a, b) -> float:
    """Max-plus inner product: max_i(a[i] + b[i]) (util.jl:40-48).

    Used to join a forward column with a backward column; the name is kept
    for parity with the reference.
    """
    a = np.asarray(a)
    b = np.asarray(b)
    n = min(len(a), len(b))
    return float(np.max(a[:n] + b[:n]))
