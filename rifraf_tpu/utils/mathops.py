"""Log-space math helpers (reference: /root/reference/src/util.jl:24-48)."""

from __future__ import annotations

import numpy as np


def logsumexp10(x) -> float:
    """LogSumExp in base 10 (util.jl:28-38)."""
    x = np.asarray(x, dtype=np.float64)
    if x.size == 0:
        return -np.inf
    u = np.max(x)
    if np.isinf(u):
        return float("nan") if np.isnan(x).any() else float(u)
    return float(np.log10(np.sum(np.power(10.0, x - u))) + u)


def summax(a, b) -> float:
    """Max-plus inner product: max_i(a[i] + b[i]) (util.jl:40-48).

    Used to join a forward column with a backward column; the name is kept
    for parity with the reference.
    """
    a = np.asarray(a)
    b = np.asarray(b)
    n = min(len(a), len(b))
    return float(np.max(a[:n] + b[:n]))
