"""Phred <-> probability conversions.

Mirrors the semantics of /root/reference/src/phred.jl: phred scores are
integers, probabilities are in linear space, and log probabilities are
base-10 (the whole framework works in log10 space).
"""

from __future__ import annotations

import numpy as np

MIN_PHRED = 1
MAX_PHRED = ord("~") - 33  # 93


def p_to_phred(p) -> np.ndarray:
    """Convert error probability to PHRED score (phred.jl:5-11)."""
    p = np.asarray(p, dtype=np.float64)
    scores = np.minimum(np.round(-10.0 * np.log10(p)), MAX_PHRED)
    return scores.astype(np.int8)


def phred_to_log_p(x) -> np.ndarray:
    """Convert PHRED score to log10 error probability (phred.jl:14-18)."""
    return np.asarray(x, dtype=np.float64) / (-10.0)


def phred_to_p(q) -> np.ndarray:
    """Convert PHRED score to error probability (phred.jl:21-27)."""
    return np.power(10.0, phred_to_log_p(q))


def cap_phreds(phreds, max_phred: int) -> np.ndarray:
    """Cap phred values at a maximum (phred.jl:36-41)."""
    if max_phred < 1:
        raise ValueError("max phred value must be positive")
    return np.minimum(np.asarray(phreds), max_phred).astype(np.int8)


def normalize(parts) -> np.ndarray:
    """Normalize rates to probabilities (phred.jl:30-34)."""
    parts = np.asarray(parts, dtype=np.float64)
    return parts / parts.sum()
