"""Phred <-> probability conversions.

Mirrors the semantics of /root/reference/src/phred.jl: phred scores are
integers, probabilities are in linear space, and log probabilities are
base-10 (the whole framework works in log10 space).
"""

from __future__ import annotations

import numpy as np

# Valid phred range: FASTQ offset-33 quality strings span '!'..'~',
# i.e. Q0..Q93, and Q0 ("error probability 1") is a legal, encodable
# score — so 0 is the accepted LOWER bound everywhere (engine.validate
# enforces the same [0, 93] window; the two layers intentionally share
# these constants). Note cap_phreds separately requires its CAP to be
# >= 1: capping every score at 0 would declare all bases certainly
# wrong, which is a caller bug, not a data property.
MIN_PHRED = 0
MAX_PHRED = ord("~") - 33  # 93


def p_to_phred(p) -> np.ndarray:
    """Convert error probability to PHRED score (phred.jl:5-11)."""
    p = np.asarray(p, dtype=np.float64)
    scores = np.minimum(np.round(-10.0 * np.log10(p)), MAX_PHRED)
    return scores.astype(np.int8)


def phred_to_log_p(x) -> np.ndarray:
    """Convert PHRED score to log10 error probability (phred.jl:14-18)."""
    return np.asarray(x, dtype=np.float64) / (-10.0)


def phred_to_p(q) -> np.ndarray:
    """Convert PHRED score to error probability (phred.jl:21-27)."""
    return np.power(10.0, phred_to_log_p(q))


def cap_phreds(phreds, max_phred: int) -> np.ndarray:
    """Cap phred values at a maximum (phred.jl:36-41). The cap itself
    must be >= 1 (a 0 cap would zero every quality); individual scores
    of 0 are valid input — see MIN_PHRED."""
    if max_phred < 1:
        raise ValueError("max phred value must be positive")
    return np.minimum(np.asarray(phreds), max_phred).astype(np.int8)


def normalize(parts) -> np.ndarray:
    """Normalize rates to probabilities (phred.jl:30-34)."""
    parts = np.asarray(parts, dtype=np.float64)
    return parts / parts.sum()
