"""Cluster-level parallel sweep: concurrent independent consensus jobs.

The reference fans independent input files out over Julia worker processes
with ``pmap`` (scripts/rifraf.jl:190-191, Distributed RPC). The TPU-native
equivalent is NOT process parallelism — XLA dispatch is already
asynchronous, so one Python process can keep several devices (or one
device's stream) busy by driving each cluster's hill-climbing loop from its
own host thread:

- each worker thread pins its jobs to a home device via the thread-local
  ``jax.default_device`` context, so with D visible devices D clusters run
  genuinely concurrently (DP over the cluster axis);
- on a single device the threads still overlap one cluster's host work
  (proposal generation, candidate filtering, convergence checks) with
  another cluster's device fills — the dispatch queue is the pipeline;
- compiled executables are shared process-wide, so shape-bucketed clusters
  compile once and every thread reuses the cache (a worker-process design
  would recompile per process).

Determinism: ``rifraf()`` derives all randomness from ``params.seed`` per
call, so results are bit-identical to a sequential sweep regardless of
worker count or completion order (asserted in tests/test_cluster.py).
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from queue import Queue
from typing import Callable, List, Optional, Sequence, TypeVar

T = TypeVar("T")
R = TypeVar("R")


def default_worker_count(n_jobs: int) -> int:
    """Workers for a sweep: one per visible device (the DP width), but
    never more than there are jobs, and at least 1. A couple of extra
    threads beyond the device count would only contend on the host."""
    import jax

    try:
        n_dev = len(jax.devices())
    except RuntimeError:
        n_dev = 1
    return max(1, min(n_jobs, n_dev))


def sweep_clusters(
    fn: Callable[[T], R],
    jobs: Sequence[T],
    max_workers: Optional[int] = None,
    devices: Optional[Sequence] = None,
) -> List[R]:
    """Run ``fn`` over independent cluster jobs concurrently; results in
    job order (the ``pmap(dofile, infiles)`` of scripts/rifraf.jl:190-191).

    ``max_workers``: thread count; default = min(n_jobs, n_devices).
    ``devices``: device list to pin workers to round-robin; default
    ``jax.devices()``. Pass ``max_workers=1`` for a plain sequential loop
    (no threads, no device pinning) — useful for debugging.
    """
    jobs = list(jobs)
    if not jobs:
        return []
    if max_workers is None:
        if devices is not None:
            # one worker per *given* device, not per visible device —
            # more threads would just contend on the same chips
            max_workers = max(1, min(len(jobs), len(devices)))
        else:
            max_workers = default_worker_count(len(jobs))
    if max_workers <= 1 or len(jobs) == 1:
        return [fn(j) for j in jobs]

    import jax

    if devices is None:
        devices = jax.devices()
    # dynamic checkout rather than static round-robin: with uneven job
    # sizes a static assignment can stack queued jobs on a busy device
    # while others sit idle
    free: Queue = Queue()
    for i in range(max_workers):
        free.put(devices[i % len(devices)])

    def run(job: T) -> R:
        dev = free.get()
        try:
            # jax config context managers are thread-local: pinning here
            # affects only this worker's dispatches
            with jax.default_device(dev):
                return fn(job)
        finally:
            free.put(dev)

    with ThreadPoolExecutor(max_workers=max_workers) as pool:
        futures = [pool.submit(run, j) for j in jobs]
        try:
            return [f.result() for f in futures]
        except BaseException:
            # first failure: stop handing out queued jobs — a long sweep
            # must not keep burning device time after a fatal error
            # (already-running jobs finish; cancel() only stops pending)
            for f in futures:
                f.cancel()
            raise


class PipelineJobError(Exception):
    """One pipeline item failed. Carries the originating job index (and
    the failing stage name); the causing exception is ``__cause__``.
    With ``pipeline_map(..., on_error="return")`` these appear in the
    result list instead of aborting the remaining jobs — the serve
    worker depends on that isolation to fail one micro-batch's requests
    without stalling the batches behind it."""

    def __init__(self, job_index: int, stage: str, cause: BaseException):
        super().__init__(
            f"pipeline job {job_index} failed in {stage}: {cause!r}"
        )
        self.job_index = job_index
        self.stage = stage
        self.__cause__ = cause


def pipeline_map(
    pack_fn: Callable[[T], object],
    run_fn: Callable[[object], object],
    collect_fn: Callable[[object], R],
    items: Sequence[T],
    on_error: str = "raise",
    stage_hook: Optional[Callable[[str, int], None]] = None,
) -> List[R]:
    """Two-deep host/device software pipeline over ``items``.

    For each item: ``pack_fn`` (host-side work — NumPy packing, padding)
    runs on a single background thread, ``run_fn`` (device dispatch —
    must NOT block on results, JAX dispatch is asynchronous) and
    ``collect_fn`` (the blocking fetch, e.g. ``np.asarray``) run on the
    calling thread. The schedule overlaps item k+1's packing with item
    k's device execution, and defers item k's collect until AFTER item
    k+1 has been dispatched — so the device queue is never drained by a
    host-side fetch while more work is available:

        pack[0] dispatch[0] | pack[1] dispatch[1] collect[0] | ...

    One background thread (not a pool): packing is NumPy-bound and the
    pipeline only ever needs the next item early. Results come back in
    item order.

    ``on_error="raise"`` (default) propagates the first exception from
    any stage to the caller unchanged. ``on_error="return"`` isolates
    failures per job: a failing item's result slot holds a
    PipelineJobError naming the job index and stage (its remaining
    stages are skipped), and every other item still runs to completion.

    ``stage_hook(stage, job_index)``, when given, is called immediately
    before each stage executes — the serve worker's supervision
    heartbeat and fault-injection hook point. Exceptions it raises are
    treated exactly like the stage itself failing (``on_error``
    applies); BaseExceptions (injected crashes) propagate and kill the
    hosting thread, which is the scenario the supervisor recovers from.
    """
    if on_error not in ("raise", "return"):
        raise ValueError(f"unknown on_error: {on_error!r}")
    items = list(items)
    if not items:
        return []

    def pack(i: int, item: T):
        try:
            if stage_hook is not None:
                stage_hook("pack", i)
            return pack_fn(item)
        except Exception as e:  # noqa: BLE001 — isolation is the point
            if on_error == "raise":
                raise
            return PipelineJobError(i, "pack", e)

    def step(i: int, stage: str, fn, arg):
        if isinstance(arg, PipelineJobError):
            return arg  # an earlier stage already failed this job
        try:
            if stage_hook is not None:
                stage_hook(stage, i)
            return fn(arg)
        except Exception as e:  # noqa: BLE001
            if on_error == "raise":
                raise
            return PipelineJobError(i, stage, e)

    out: List[R] = []
    with ThreadPoolExecutor(max_workers=1) as pool:
        nxt = pool.submit(pack, 0, items[0])
        pending = None  # (index, device handle) for the previous item
        for i in range(len(items)):
            packed = nxt.result()
            if i + 1 < len(items):
                nxt = pool.submit(pack, i + 1, items[i + 1])
            handle = step(i, "run", run_fn, packed)
            if pending is not None:
                out.append(step(pending[0], "collect", collect_fn,
                                pending[1]))
            pending = (i, handle)
        out.append(step(pending[0], "collect", collect_fn, pending[1]))
    return out


def resolve_jobs_flag(jobs_flag: int, n_files: int) -> int:
    """CLI --jobs semantics: 0 = auto (one worker per device), else the
    explicit count capped by the number of files."""
    if jobs_flag <= 0:
        return default_worker_count(n_files)
    return max(1, min(jobs_flag, n_files))
