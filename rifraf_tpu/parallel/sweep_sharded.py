"""Cluster-sharded consensus sweep: many clusters, one device program.

The reference fans independent consensus jobs over Julia worker
processes (scripts/rifraf.jl:190-191, `pmap`). parallel.cluster replaces
that with device-pinned host threads — one PYTHON driver per cluster.
This module is the third rung (BASELINE.json config 5, "1024-cluster
sweep ... across a pod"): the WHOLE hill-climb of G clusters runs as one
jitted program, vmapped over the cluster axis and sharded across a
`jax.sharding.Mesh` — XLA partitions the program along clusters (no
collectives needed; the axis is embarrassingly parallel), so a pod
slice processes thousands of clusters with one dispatch per
adaptation round plus one per stage sweep.

Scheduling (the ``scheduler="bucketed"`` default): clusters are grouped
into SHAPE BUCKETS keyed ``(Npad, Lpad, Tmax, K0)`` on a fixed grid —
read count to ``read_bucket`` multiples, read length and template
columns to ``len_bucket`` multiples, band height to ``band_bucket``
multiples — so each bucket signature compiles ONCE (module-level
lru-cached program factories, the pattern of engine.realign's
``_xla_stage_runner``) and the executable is reused across chunks and
across calls. Real read sets are heterogeneous (amplicon sweeps mix
200 bp and 3 kb clusters); padding everything to the global maxima
burns device cells on padding — the per-bucket padded/useful cell
accounting comes back in ``SweepStats``. Chunks are additionally sized
to FILL the 128-lane vector axis (``lane_target``): a bucket of small
clusters packs ceil(128/Npad) of them per launch instead of letting the
hardware pad a quarter-full lane tile, buckets too small to ever fill a
tile are coalesced into coarser-grid neighbours first
(``_coalesce_underfilled`` — padding is masked, so only the reported
``waste`` moves), and the executed lane fill is reported per bucket
(``BucketStats.lane_slot_occupancy``) and in aggregate
(``SweepStats.lane_occupancy`` / ``lane_occupancy_reads``).
``scheduler="uniform"`` keeps
the legacy everything-to-global-maxima layout (one bucket, band grid 8,
raw read-count padding), with chunk shapes pinned to the GLOBAL grid so
chunked calls no longer recompile per chunk.

Chunks are double-buffered through ``parallel.cluster.pipeline_map``:
host packing of chunk k+1 (NumPy batch building, Poisson thresholds)
overlaps device execution of chunk k via JAX async dispatch, and chunk
k's blocking fetch happens only after chunk k+1 has been dispatched.
On non-CPU backends the stage program donates its read-batch buffers
(``donate_argnums``) so each bucket's HBM is recycled as soon as its
stage finishes.

Scope: the device-loop configuration (engine.device_loop) — no
reference, full batch per cluster; candidates from the all-edits tables,
optionally masked by the in-kernel alignment-edits gate
(``do_alignment_proposals=True``). Reference-guided and FRAME-stage runs
still go through the host driver. Per-cluster results are BIT-IDENTICAL
to running `rifraf()` per cluster in the matching configuration
(tests/test_sweep_sharded.py): the same fused XLA step, the same
candidate selection, the same adaptive-bandwidth protocol, just with a
leading cluster axis everywhere (lax.while_loop under vmap keeps
finished clusters frozen while stragglers iterate). Bucketing cannot
perturb results: band-height padding is masked by the band geometry,
and weight-0 pad reads/clusters drop out of every reduction.
"""

from __future__ import annotations

import functools
import hashlib
import os
import threading
import time
from typing import List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from ..engine.bandgrowth import (
    ADAPTIVE_ENTRY_BW,
    MAX_BANDWIDTH_DOUBLINGS,  # noqa: F401  (re-exported; model.jl:650)
    adaptive_entry,
    check_band_growth,
    grow_bandwidths,
)
from ..models.sequences import ReadScores, batch_reads
from ..utils.fprint import fold_nondefault
from ..utils.mathops import logsumexp10, poisson_cquantile
from ..utils.shapes import LANES, pack_segments
from ..utils.shapes import bucket as _bucket
from .cluster import pipeline_map

# bucketed-scheduler grid defaults: read-count and band-height rounding
READ_BUCKET = 8
BAND_BUCKET = 16
# lane-packing floor: a chunk's read-lane footprint (gp clusters x Npad
# reads) is padded by the hardware to 128-lane multiples, so chunks are
# sized to fill at least one full lane tile when the bucket has the
# members (plan_sweep lane_target)
LANE_TARGET = LANES
# segment packing declines templates long enough for the blocked dense
# sweep, whose internal reductions are full-lane-width — must equal
# ops.fused.DENSE_BLOCK_THRESHOLD (pinned by tests/test_lane_packing.py;
# duplicated here so plan_sweep stays importable without JAX)
SEG_TMAX_MAX = 2048


def segment_pack_enabled() -> bool:
    """Env opt-out for segment packing (``RIFRAF_TPU_SEGMENT_PACK=0``).
    Resolved OUTSIDE jit — it selects which host-side plan and which
    lru-cached program factory run, never a traced branch."""
    return os.environ.get("RIFRAF_TPU_SEGMENT_PACK", "1") != "0"


def _lane_slots(gp: int, n: int, lanes: int = LANES) -> int:
    """Hardware lane slots one chunk's launch occupies: the [gp, Npad]
    read axes flatten onto the 128-lane vector axis, padded up to a lane
    multiple."""
    return -(-gp * n // lanes) * lanes


class SweepResult(NamedTuple):
    consensus: np.ndarray
    score: float
    n_iters: int
    converged: bool


class BucketStats(NamedTuple):
    """Per-bucket report: one entry per compiled shape signature."""

    key: Tuple[int, int, int, int]  # (Npad, Lpad, Tmax, K0)
    n_clusters: int
    n_chunks: int
    gp: int  # pinned cluster-axis size of every chunk
    occupancy: float  # real clusters / (n_chunks * gp)
    useful_cells: int  # sum of real read lengths
    padded_cells: int  # n_chunks * gp * Npad * Lpad
    waste: float  # 1 - useful/padded
    seconds: float  # main-thread dispatch+fetch time (approximate
    #   under pipelining: packing overlaps other buckets' device work)
    # read-lane tile fill if this bucket's reads were packed into
    # 128-lane tiles longest-first (utils.shapes.pack_lanes) vs padding
    # every lane to the bucket's Lpad — how much of the padded footprint
    # a lane-packed Pallas engine would actually use
    lane_occupancy: float = 1.0
    uniform_lane_occupancy: float = 1.0
    # EXECUTED lane packing (the plan_sweep lane_target floor): hardware
    # 128-lane slots the bucket's launches occupied, and the fraction of
    # them that carried a real read (real reads / lane_slots)
    lane_slots: int = 0
    lane_slot_occupancy: float = 1.0


class SweepStats(NamedTuple):
    n_clusters: int
    n_buckets: int
    n_chunks: int
    useful_cells: int
    padded_cells: int
    waste: float
    # cells the legacy uniform layout would have allocated for the same
    # inputs — padded_cells/uniform_padded_cells is the bucketing win
    uniform_padded_cells: int
    seconds: float  # wall time of the whole sweep
    buckets: List[BucketStats]
    # aggregate executed lane fill, two levels: ``lane_occupancy`` is
    # the fraction of occupied 128-lane slots carrying a real CLUSTER's
    # Npad block (what chunk sizing + bucket coalescing control — the
    # rest is tile-rounding/pad-cluster loss); ``lane_occupancy_reads``
    # further discounts within-cluster read padding (n_reads < Npad),
    # which is bounded by the read-count bucket grid, not by packing
    lane_occupancy: float = 1.0
    lane_occupancy_reads: float = 1.0
    # precision / growth-policy provenance of the run, plus the settled
    # per-read bandwidth histogram ((bandwidth, count), ...) over live
    # lanes — the adaptive policy's win shows up here as mass staying at
    # small bandwidths instead of doubling to the worst read's
    band_dtype: str = "f32"
    band_growth: str = "double"
    bw_hist: Tuple = ()
    # requested streamed-input encoding (params.input_enc). The sweep's
    # device programs run the XLA fused step, whose inputs are always
    # exact f32 — here the encoding is PROGRAM IDENTITY only: it keys
    # the lru-cached program factories and the resume fingerprint so a
    # journal written under one encoding is never replayed into a run
    # configured for the other
    input_enc: str = "f32"
    # speculative-refinement accounting (params.speculate_k). The 1+k
    # extra segment copies of each speculating chunk's read lanes exist
    # only to score speculative composites: they are OVERHEAD, not
    # demand, so they are excluded from lane_occupancy/lane_slots above
    # (which stay comparable to non-speculative baselines) and reported
    # separately here, alongside the stage loops' attempt/hit counters
    speculate_k: int = 0
    spec_overhead_lanes: int = 0
    spec_attempts: int = 0
    spec_hits: int = 0


class BucketPlan(NamedTuple):
    """One shape bucket: which input clusters it holds and how they are
    chunked along the (pinned) cluster axis."""

    key: Tuple[int, int, int, int]  # (Npad, Lpad, Tmax, K0)
    band: int  # band-height grid for this bucket's K choices
    gp: int  # cluster-axis size every chunk is padded to
    chunks: List[List[int]]  # input indices per chunk, input order


class PackPlan(NamedTuple):
    """One segment-packed lane block: which clusters share it and
    where their read lanes sit."""

    members: List[Tuple[int, int, int]]  # (cluster idx, lane offset, n)
    seg_ids: List[int]  # [Npad] per-lane segment-slot id


class SegmentBucketPlan(NamedTuple):
    """A shape bucket executed with READ-GRANULARITY segment packing:
    several small clusters share each ``[Npad]`` lane block, located by
    a per-lane segment mask, instead of one whole
    ``bucket(n_reads, read_bucket)`` block each. Produced by
    ``plan_sweep`` for clusters too small to fill a lane tile alone;
    executed by ``ChunkExecutor`` through the segment-aware fused step
    (ops.fused.fused_step_segmented) and the hand-batched segment
    stage runner (engine.device_loop.make_segment_stage_runner)."""

    key: Tuple[int, int, int, int]  # (Npad, Lpad, Tmax, K0)
    band: int
    sp: int  # static segment axis: max clusters per pack
    gp: int  # packs per chunk (pinned; cluster_chunk bounds PACKS here)
    chunks: List[List[PackPlan]]


class _ClusterInfo(NamedTuple):
    n_reads: int
    max_len: int
    seed_idx: int  # read index of the initial consensus
    tlen0: int  # its length
    entry_k: int  # band height demand at entry bandwidths
    useful: int  # sum of read lengths


def _settled_bw_hist(chunks: Sequence[np.ndarray]) -> Tuple:
    """((bandwidth, count), ...) over the settled live-lane bandwidths
    every executed chunk reported via bw_sink. Journal-replayed chunks
    never re-run, so a resumed sweep's histogram covers only the chunks
    executed THIS call."""
    if not chunks:
        return ()
    vals, counts = np.unique(np.concatenate(chunks), return_counts=True)
    return tuple((int(v), int(c)) for v, c in zip(vals, counts))


def _cluster_infos(
    clusters: Sequence[Sequence[ReadScores]],
    band_growth: str = "double",
) -> List[_ClusterInfo]:
    """Host-side per-cluster facts the planner and packer share. The
    seed is the read with the best logsumexp10(match_scores)
    (model.jl:575-579) — computed once here, reused by packing.

    ``band_growth="adaptive"`` computes ``entry_k`` from the LOWERED
    entry bandwidths (min(bandwidth, 16), engine.bandgrowth) the
    executor actually enters adaptation with, so well-behaved clusters
    bucket onto small-K shapes instead of the caller's default band."""

    def ebw(b: int) -> int:
        return min(b, ADAPTIVE_ENTRY_BW) if band_growth == "adaptive" else b

    infos = []
    for c in clusters:
        k = int(np.argmax([logsumexp10(r.match_scores) for r in c]))
        tlen0 = len(c[k])
        infos.append(_ClusterInfo(
            n_reads=len(c),
            max_len=max(len(r) for r in c),
            seed_idx=k,
            tlen0=tlen0,
            entry_k=max(
                2 * ebw(r.bandwidth) + abs(len(r) - tlen0) + 1 for r in c
            ),
            useful=sum(len(r) for r in c),
        ))
    return infos


def cluster_info(cluster: Sequence[ReadScores],
                 band_growth: str = "double") -> _ClusterInfo:
    """Per-cluster shape/seed facts for ONE cluster (the serving
    admission path computes these once per request)."""
    return _cluster_infos([cluster], band_growth)[0]


def _content_digest(clusters: Sequence[Sequence[ReadScores]]) -> str:
    """Digest of the cluster CONTENT for the resume fingerprint. Shape
    facts (_ClusterInfo) alone cannot distinguish edited read/phred
    content of the same lengths, or a different error model — resuming
    across either would silently mix two configurations' results. The
    score vectors are all derived from (seq, error_log_p, scores), so
    hashing those plus the bandwidth state covers everything the sweep
    computes from."""
    h = hashlib.sha256()
    for c in clusters:
        for r in c:
            h.update(np.ascontiguousarray(r.seq).tobytes())
            h.update(np.ascontiguousarray(r.error_log_p).tobytes())
            h.update(repr((r.scores, r.bandwidth,
                           r.bandwidth_fixed)).encode())
            h.update(b"\x00")
        h.update(b"\x01")
    return h.hexdigest()[:32]


def _journal_fingerprint(G, infos, clusters, max_iters, min_dist,
                         bandwidth_pvalue, len_bucket, cluster_chunk,
                         scheduler, read_bucket, band_bucket,
                         do_alignment_proposals, lane_target,
                         segment_pack, segment_align, band_dtype,
                         band_growth, guard, verify_fraction,
                         input_enc, speculate_k=0) -> str:
    """The sweep journal's resume fingerprint: every knob that changes
    results (or which integrity checks ran) between the run that wrote
    the journal and the run resuming it, plus the cluster content
    digest. The integrity knobs (guard, verify_fraction), the input
    encoding, and speculate_k fold in only when non-default
    (utils.fold_nondefault) so journals minted before each knob existed
    stay resumable — a guard or verify setting never changes results,
    but resuming a guarded run unguarded would skip its checks
    silently; speculation is result-identical too, but its journal
    records different round-level provenance (attempt/hit stats), so a
    resume must not silently mix the two modes."""
    from ..io.journal import fingerprint

    return fingerprint(
        G, [tuple(i) for i in infos], _content_digest(clusters),
        max_iters, min_dist,
        bandwidth_pvalue, len_bucket, cluster_chunk, scheduler,
        read_bucket, band_bucket, do_alignment_proposals,
        lane_target, segment_pack, segment_align,
        band_dtype, band_growth,
        *fold_nondefault("guard", bool(guard), False),
        *fold_nondefault("verify_fraction", verify_fraction, 0.0),
        *fold_nondefault("input_enc", input_enc, "f32"),
        *fold_nondefault("speculate_k", speculate_k, 0),
    )


def bucket_key(
    info: _ClusterInfo,
    read_bucket: int = READ_BUCKET,
    band_bucket: int = BAND_BUCKET,
    len_bucket: int = 64,
) -> Tuple[int, int, int, int]:
    """The bucketed scheduler's shape key ``(Npad, Lpad, Tmax, K0)`` for
    one cluster. Single definition shared by plan_sweep and the serving
    micro-batcher, so an online request and an offline sweep cluster
    with the same rounded shape land on the SAME compiled executable."""
    return (
        _bucket(info.n_reads, read_bucket),
        _bucket(info.max_len, len_bucket),
        _bucket(info.tlen0 + 2, len_bucket),
        _bucket(info.entry_k, band_bucket),
    )


def _coalesce_underfilled(
    groups: dict,
    infos: List["_ClusterInfo"],
    read_bucket: int,
    band_bucket: int,
    len_bucket: int,
    lane_target: int,
) -> dict:
    """Merge buckets too small to fill one lane tile into coarser-grid
    neighbours. A bucket whose whole membership occupies fewer than
    ``lane_target`` read lanes (``Npad * members``) cannot fill a single
    128-lane tile no matter how it is chunked, so its launch pays a
    mostly-empty tile AND its signature pays a compile. Regrouping those
    members with the SHAPE axes (Lpad, Tmax, K0) rounded on a 2x/4x/8x
    coarser grid coalesces near-miss shapes into shared, fuller
    launches. The read-count axis keeps its fine grid: coarsening Npad
    would pad every cluster's read lanes, which is exactly the waste
    lane packing exists to avoid. Correctness is the module invariant —
    a key is only a padding spec, any key that covers a member's demands
    yields bit-identical results (band-height padding is masked by the
    band geometry, weight-0 pad reads/clusters drop out of reductions) —
    so coalescing trades padded cells (reported as ``waste``) for lane
    fill and fewer compiled signatures."""
    for scale in (2, 4, 8):
        small = [
            k for k, members in groups.items()
            if k[0] * len(members) < lane_target
        ]
        if len(small) <= 1:
            break
        for k in small:
            members = groups.pop(k)
            for i in members:
                ck = bucket_key(
                    infos[i], read_bucket, band_bucket * scale,
                    len_bucket * scale,
                )
                groups.setdefault(ck, []).append(i)
    # absorb the ragtag tail: whatever is still under one tile after the
    # coarsest regroup merges per read-count class into ONE bucket at
    # the elementwise-max key (the uniform layout, but scoped to the
    # handful of stragglers instead of the whole sweep)
    small = [
        k for k, members in groups.items()
        if k[0] * len(members) < lane_target
    ]
    by_npad = {}
    for k in small:
        by_npad.setdefault(k[0], []).append(k)
    for npad, keys in by_npad.items():
        if len(keys) <= 1:
            continue
        members = []
        for k in keys:
            members.extend(groups.pop(k))
        mk = tuple(max(k[d] for k in keys) for d in range(4))
        groups.setdefault(mk, []).extend(members)
    # merging interleaves members — restore input order per bucket (the
    # planner's documented intra-bucket order invariant)
    for members in groups.values():
        members.sort()
    return groups


def plan_sweep(
    clusters: Sequence[Sequence[ReadScores]],
    scheduler: str = "bucketed",
    read_bucket: int = READ_BUCKET,
    band_bucket: int = BAND_BUCKET,
    len_bucket: int = 64,
    cluster_chunk: int = 0,
    n_axis: int = 1,
    infos: Optional[List[_ClusterInfo]] = None,
    lane_target: int = LANE_TARGET,
    segment_pack: Optional[bool] = None,
    segment_align: int = 1,
    band_growth: str = "double",
) -> List[BucketPlan]:
    """Group clusters into shape buckets and chunk each bucket's cluster
    axis. Pure host arithmetic — no JAX — so planner invariants are
    cheaply testable.

    ``bucketed``: per-cluster key = (reads to ``read_bucket``, max read
    length to ``len_bucket``, seed length + 2 to ``len_bucket``, entry
    band demand to ``band_bucket``). ``uniform``: ONE bucket at the
    global maxima (raw read count, band grid 8) — the legacy layout.
    Either way every chunk of a bucket is padded to the same ``gp``
    (``cluster_chunk`` rounded up to the cluster grid), so chunked calls
    reuse one executable instead of recompiling per chunk.

    ``lane_target`` makes lane packing an EXECUTION strategy, not just
    an accounting stat: a bucketed chunk's launch flattens [gp, Npad]
    read axes onto the 128-lane vector axis, so a small-cluster bucket
    (say Npad=8) chunked at gp=4 fills a quarter of one lane tile and
    the hardware pads the rest. The floor raises each bucket's chunk
    target until gp*Npad >= lane_target (bounded by the bucket's member
    count), packing multiple small clusters into full lane tiles — it
    takes precedence over a smaller ``cluster_chunk`` because the
    per-launch footprint of such a bucket is tiny anyway. Buckets whose
    WHOLE membership cannot fill one tile are first coalesced into
    coarser-grid neighbours (see _coalesce_underfilled). 0 disables
    both.

    ``segment_pack`` (default: the ``RIFRAF_TPU_SEGMENT_PACK`` env
    gate, on unless set to ``0``) packs at READ granularity instead of
    flooring to whole blocks: clusters too small to fill a lane tile
    alone (``bucket(n_reads, read_bucket) < lane_target``) are grouped
    by their SHAPE axes (Lpad, Tmax, K0) and first-fit packed into
    shared ``[Npad]`` blocks (utils.shapes.pack_segments), each lane
    tagged with its cluster's segment id — a 5-read and an 11-read
    cluster share 16 lanes instead of riding 8+16. The packer declines
    (whole-block path) for clusters that fill a tile alone and for
    templates long enough for the blocked dense sweep
    (``SEG_TMAX_MAX``), whose internal reductions are not
    segment-aware. ``segment_align`` > 1 rounds each cluster's lane
    footprint (see pack_segments — for backends with tree-shaped lane
    reductions).
    """
    if scheduler not in ("bucketed", "uniform"):
        raise ValueError(f"unknown sweep scheduler: {scheduler!r}")
    if infos is None:
        infos = _cluster_infos(clusters, band_growth)
    if not infos:
        return []

    if scheduler == "uniform":
        band = 8
        grid = max(n_axis, 1)
        key = (
            max(i.n_reads for i in infos),
            _bucket(max(i.max_len for i in infos), len_bucket),
            _bucket(max(i.tlen0 for i in infos) + 2, len_bucket),
            _bucket(max(i.entry_k for i in infos), band),
        )
        groups = {key: list(range(len(infos)))}
    else:
        band = band_bucket
        # the cluster axis only rounds to the mesh axis (so every chunk
        # shards evenly) — no larger minimum: padding a one-cluster
        # bucket to a fixed grid can cost more cells than the uniform
        # layout it is supposed to beat
        grid = max(n_axis, 1)
        if segment_pack is None:
            segment_pack = lane_target > 0 and segment_pack_enabled()
        seg_groups = {}
        groups = {}
        for i, info in enumerate(infos):
            key = bucket_key(info, read_bucket, band, len_bucket)
            if (
                segment_pack
                and lane_target > 0
                and key[0] < lane_target
                and key[2] + 1 <= SEG_TMAX_MAX
            ):
                seg_groups.setdefault(key[1:], []).append(i)
            else:
                groups.setdefault(key, []).append(i)
        # mesh decline: a segment-packed group executes on its PACK
        # axis, and the mesh shards that axis — packing a group into
        # fewer packs than the mesh could otherwise fill serializes
        # devices the whole-block path would use (one cluster per
        # slot). Route such groups back to whole-block bucketing (a
        # structural decline, independent of the env gate).
        if max(n_axis, 1) > 1:
            for shape_key in list(seg_groups):
                members = seg_groups[shape_key]
                pk = pack_segments(
                    [infos[i].n_reads for i in members],
                    lanes=lane_target, align=segment_align,
                )
                if (len(pk.blocks) < n_axis
                        and len(members) > len(pk.blocks)):
                    for i in members:
                        groups.setdefault(
                            bucket_key(infos[i], read_bucket, band,
                                       len_bucket), []
                        ).append(i)
                    del seg_groups[shape_key]
        if lane_target > 0:
            groups = _coalesce_underfilled(
                groups, infos, read_bucket, band, len_bucket, lane_target
            )

    plans = []
    if scheduler == "bucketed":
        for shape_key, members in seg_groups.items():
            pk = pack_segments(
                [infos[i].n_reads for i in members],
                lanes=lane_target,
                align=segment_align,
            )
            npad = _bucket(pk.npad, read_bucket)
            packs = []
            for b, blk in enumerate(pk.blocks):
                packs.append(PackPlan(
                    members=[
                        (members[li], off, n) for li, off, n in blk
                    ],
                    seg_ids=(
                        pk.seg_ids[b] + [0] * (npad - len(pk.seg_ids[b]))
                    ),
                ))
            target = (
                min(len(packs), cluster_chunk) if cluster_chunk
                else len(packs)
            )
            gp = _bucket(max(target, 1), max(n_axis, 1))
            plans.append(SegmentBucketPlan(
                key=(npad,) + shape_key,
                band=band_bucket,
                sp=pk.n_seg,
                gp=gp,
                chunks=[
                    packs[s : s + gp] for s in range(0, len(packs), gp)
                ],
            ))
    for key, members in groups.items():
        target = min(len(members), cluster_chunk) if cluster_chunk else (
            len(members)
        )
        if scheduler == "bucketed" and lane_target > 0:
            want = -(-lane_target // key[0])  # clusters per full lane tile
            target = max(target, min(len(members), want))
        gp = _bucket(max(target, 1), grid)
        chunks = [members[s : s + gp] for s in range(0, len(members), gp)]
        plans.append(BucketPlan(key=key, band=band, gp=gp, chunks=chunks))
    return plans


def plan_cells(plans: Sequence[BucketPlan]) -> int:
    """Total padded device cells (read-lane cells, the [G, N, L] batch
    footprint) a plan allocates."""
    return sum(
        len(p.chunks) * p.gp * p.key[0] * p.key[1] for p in plans
    )


@functools.lru_cache(maxsize=None)
def _adapt_program(Tmax: int, K: int, want_edge: bool = False,
                   band_dtype: str = "f32", want_guard: bool = False,
                   input_enc: str = "f32"):
    """One adaptive-bandwidth round for a whole chunk: vmapped fill +
    traceback statistics, n_errors [G, N] out (plus edge_hits [G, N]
    when ``want_edge``, for the adaptive growth policy; plus the
    per-read guard flags [G, N + 1] when ``want_guard`` — the numerical
    sentinel over the freshly filled bands and scores). Module-level
    cache so repeated sweep calls reuse the jitted wrapper (a fresh
    jax.jit per call would recompile every round of every call).
    ``input_enc`` is cache-key/AOT-identity only: the XLA fused step
    always consumes exact f32 inputs (ops.encoding is Pallas-only)."""
    import jax

    from ..ops import align_jax
    from ..ops.fused import fused_step_full, pack_layout

    def one(seq_g, match_g, mismatch_g, ins_g, dels_g, lengths_g, bw_g,
            w_g, tmpl_g, tlen_g):
        geom = align_jax.BandGeometry.make(lengths_g, tlen_g, bw_g)
        _, _, _, packed = fused_step_full(
            tmpl_g[:Tmax], seq_g, match_g, mismatch_g, ins_g, dels_g,
            geom, w_g, K, False, True, 0, False, want_edge, band_dtype,
            want_guard,
        )
        lay = pack_layout(seq_g.shape[0], Tmax + 1, True, False,
                          want_edge, want_guard)
        out = [packed[slice(*lay["n_errors"])]]
        if want_edge:
            out.append(packed[slice(*lay["edge_hits"])])
        if want_guard:
            out.append(packed[slice(*lay["guard"])])
        return tuple(out)

    from ..serve.aot import aot_program

    return aot_program(
        "sweep_adapt",
        (Tmax, K, want_edge, band_dtype, want_guard, input_enc),
        jax.jit(jax.vmap(one)),
    )


@functools.lru_cache(maxsize=None)
def _stage_program(Tmax: int, K: int, H: int, min_dist: int,
                   use_edits: bool, donate: bool,
                   band_dtype: str = "f32", input_enc: str = "f32",
                   speculate_k: int = 0):
    """The whole INIT stage for a chunk, vmapped over the cluster axis.
    One cached program per (Tmax, K, H, min_dist, gate) signature; XLA's
    jit cache then keys on the batch avals, so every chunk of a bucket
    (and every later call with the same bucket) reuses one executable.
    ``donate`` hands the read-batch buffers to XLA (non-CPU backends) so
    a finished bucket's HBM is recycled for the next one.
    ``speculate_k`` > 0 compiles the speculative stage loop: every work
    round scores {multi, single, k composite(s)} as 2+k segments of one
    fused_step_segmented launch (results stay bit-identical; the packed
    row grows the 2-scalar [attempts, hits] tail)."""
    import jax
    import jax.numpy as jnp

    from ..engine.device_loop import make_stage_runner
    from ..ops import align_jax
    from ..ops.fused import (fused_step_full, fused_step_segmented,
                             unpack_tables)

    def step_fn(tmpl, tlen, s):
        (seq_g, match_g, mismatch_g, ins_g, dels_g), lengths_g, bw_g, \
            w_g = s
        geom = align_jax.BandGeometry.make(lengths_g, tlen, bw_g)
        _, _, _, packed = fused_step_full(
            tmpl[:Tmax], seq_g, match_g, mismatch_g, ins_g, dels_g, geom,
            w_g, K, False, use_edits, 0, band_dtype=band_dtype,
        )
        return unpack_tables(packed, seq_g.shape[0], Tmax + 1, use_edits)

    spec_step = None
    if speculate_k:
        S = 2 + speculate_k

        def spec_step(tmpls, tlens, s):
            # one segment-packed launch scoring all S templates over the
            # cluster's reads duplicated per segment (same construction
            # as realign's speculative step; per-segment reductions are
            # bit-identical to per-template fused_step_full runs)
            (seq_g, match_g, mismatch_g, ins_g, dels_g), lengths_g, \
                bw_g, w_g = s
            n_reads = seq_g.shape[0]

            def tile(a):
                return jnp.concatenate([a] * S, axis=0)

            seg = jnp.concatenate([
                jnp.full((n_reads,), i, jnp.int32) for i in range(S)
            ])
            out = fused_step_segmented(
                tmpls[:, :Tmax], tlens, seg, tile(seq_g), tile(match_g),
                tile(mismatch_g), tile(ins_g), tile(dels_g),
                tile(lengths_g), tile(bw_g), tile(w_g), K, S,
                want_stats=use_edits, want_tables=True,
                band_dtype=band_dtype,
            )
            tables = (out["total"], out["sub"], out["ins"], out["del"])
            if use_edits:
                tables += (out["edits"].astype(out["sub"].dtype),)
            return tables

    runner = make_stage_runner(
        step_fn, do_indels=True, min_dist=min_dist, H=H, Tmax=Tmax,
        stop_on_same=True, gate="edits" if use_edits else "none",
        speculate_k=speculate_k, spec_step_fn=spec_step,
    )

    def call(t0, tl, step_state):
        return jax.vmap(
            lambda a, b, s: runner.run(
                a, b, -jnp.inf, jnp.int32(H - 1), jnp.int32(0), s
            ),
            in_axes=(0, 0, ((0, 0, 0, 0, 0), 0, 0, 0)),
        )(t0, tl, step_state)

    from ..serve.aot import aot_program

    return aot_program(
        "sweep_stage",
        (Tmax, K, H, min_dist, use_edits, donate, band_dtype, input_enc,
         speculate_k),
        jax.jit(call, donate_argnums=(2,) if donate else ()),
    )


@functools.lru_cache(maxsize=None)
def _seg_adapt_program(Tmax: int, K: int, S: int,
                       want_edge: bool = False, band_dtype: str = "f32",
                       want_guard: bool = False, input_enc: str = "f32"):
    """Segment-packed adaptive-bandwidth round: per-lane traceback
    error counts for a chunk of packs, each lane filled against ITS
    segment's template. Per-lane values are identical to the
    whole-block adapt program's (the fills are independent per read).
    ``want_guard`` appends the per-LANE guard flags [G, N]."""
    import jax

    from ..ops.fused import fused_step_segmented

    def one(seq_g, match_g, mismatch_g, ins_g, dels_g, lengths_g, bw_g,
            w_g, seg_g, tmpl_g, tlen_g):
        out = fused_step_segmented(
            tmpl_g, tlen_g, seg_g, seq_g, match_g, mismatch_g, ins_g,
            dels_g, lengths_g, bw_g, w_g, K, S,
            want_stats=True, want_tables=False, want_edge=want_edge,
            band_dtype=band_dtype, want_guard=want_guard,
        )
        res = [out["n_errors"]]
        if want_edge:
            res.append(out["edge_hits"])
        if want_guard:
            res.append(out["guard"])
        return tuple(res)

    from ..serve.aot import aot_program

    return aot_program(
        "sweep_seg_adapt",
        (Tmax, K, S, want_edge, band_dtype, want_guard, input_enc),
        jax.jit(jax.vmap(one)),
    )


@functools.lru_cache(maxsize=None)
def _seg_stage_program(Tmax: int, K: int, H: int, min_dist: int,
                       use_edits: bool, donate: bool, S: int,
                       band_dtype: str = "f32", input_enc: str = "f32"):
    """The whole INIT stage for a chunk of SEGMENT-PACKED blocks: S
    clusters share each block's lane axis, hill-climbing jointly via
    the segment stage runner, vmapped over the pack axis. Same cache
    discipline as _stage_program: one program per (shape, S)
    signature."""
    import jax
    import jax.numpy as jnp

    from ..engine.device_loop import make_segment_stage_runner
    from ..ops.fused import fused_step_segmented

    def step_fn(tmpls, tlens, s):
        (seq_g, match_g, mismatch_g, ins_g, dels_g), lengths_g, bw_g, \
            w_g, seg_g = s
        out = fused_step_segmented(
            tmpls, tlens, seg_g, seq_g, match_g, mismatch_g, ins_g,
            dels_g, lengths_g, bw_g, w_g, K, S,
            want_stats=use_edits, want_tables=True,
            band_dtype=band_dtype,
        )
        tabs = (out["total"], out["sub"], out["ins"], out["del"])
        if use_edits:
            tabs = tabs + (out["edits"],)
        return tabs

    run = make_segment_stage_runner(
        step_fn, do_indels=True, min_dist=min_dist, H=H, Tmax=Tmax,
        stop_on_same=True, n_seg=S,
        gate="edits" if use_edits else "none",
    )

    def call(t0, tl, live, step_state):
        prev = jnp.full((S,), -jnp.inf)
        return jax.vmap(
            lambda a, b, lv, s: run(
                a, b, lv, prev, jnp.int32(H - 1), jnp.int32(0), s
            ),
            in_axes=(0, 0, 0, ((0, 0, 0, 0, 0), 0, 0, 0, 0)),
        )(t0, tl, live, step_state)

    from ..serve.aot import aot_program

    return aot_program(
        "sweep_seg_stage",
        (Tmax, K, H, min_dist, use_edits, donate, S, band_dtype,
         input_enc),
        jax.jit(call, donate_argnums=(3,) if donate else ()),
    )


class ChunkExecutor:
    """Pack/run/collect engine for one bucket chunk — the device side of
    sweep_clusters_sharded, factored out so the online consensus service
    (rifraf_tpu.serve) drives the SAME module-level lru-cached program
    factories (_adapt_program/_stage_program) and padding rules. A
    serving micro-batch and an offline sweep chunk with one bucket
    signature share one compiled executable.

    The three methods are shaped for parallel.cluster.pipeline_map:
    ``pack`` is pure NumPy (safe on the pipeline's background thread),
    ``run`` dispatches asynchronously and returns an un-fetched handle,
    ``collect`` is the blocking fetch.
    """

    def __init__(self, mesh=None, max_iters: int = 100, min_dist: int = 15,
                 bandwidth_pvalue: float = 0.1,
                 do_alignment_proposals: bool = False, device=None,
                 band_dtype: str = "f32", band_growth: str = "double",
                 bw_sink=None, want_guard: bool = False,
                 input_enc: str = "f32", speculate_k: int = 0):
        import jax

        from ..engine.params import resolve_dtype
        from ..ops.encoding import check_input_enc

        if mesh is not None and device is not None:
            raise ValueError("pass mesh OR device, not both")
        if band_dtype not in ("f32", "bf16"):
            raise ValueError(f"unknown band_dtype: {band_dtype!r}")
        if speculate_k not in (0, 1, 2):
            raise ValueError(
                f"speculate_k must be 0, 1, or 2, got {speculate_k!r}"
            )
        check_band_growth(band_growth)
        check_input_enc(input_enc)
        self.mesh = mesh
        self.device = device
        self.max_iters = max_iters
        self.H = max_iters + 1
        self.min_dist = min_dist
        self.bandwidth_pvalue = bandwidth_pvalue
        self.use_edits = do_alignment_proposals
        self.dtype = resolve_dtype(None)
        self.donate = jax.default_backend() != "cpu"
        # the cluster-axis mesh shards plain vmapped programs, which
        # compile fine at either band dtype / growth policy (unlike
        # realign's read-axis shard_map wrappers) — no mesh escape hatch
        self.band_dtype = band_dtype
        self.band_growth = band_growth
        # requested input encoding. The sweep's device programs are XLA
        # (always exact f32 inputs), so this is program identity only —
        # it keys the compiled-program caches and the resume
        # fingerprint; results are bit-identical across encodings here
        self.input_enc = input_enc
        # optional callable fed the SETTLED bandwidths of each chunk's
        # live lanes — sweep-level accounting without widening the
        # run()/collect() handle protocol the serving path relies on
        self.bw_sink = bw_sink
        # numerical sentinels: the adapt rounds fetch the on-device
        # guard reduction with their n_errors (NaN/+Inf/underflow per
        # read lane -> NumericalIntegrityError), and collect() checks
        # the fetched stage totals host-side. Off by default: the
        # unguarded programs are byte-identical to pre-guard code.
        self.want_guard = want_guard
        # speculative edit-set evaluation (params.speculate_k): per-chunk
        # buckets whose Tmax exceeds the segmented step's dense-block
        # threshold fall back to the serial program (results identical
        # either way). Attempt/hit counters accumulate here across
        # collect() calls; each fleet executor is driven by one worker
        # thread, so plain ints suffice.
        self.speculate_k = speculate_k
        self.spec_attempts = 0
        self.spec_hits = 0

    def _check_guard(self, guard, stage: str, owners):
        """Validate fetched per-chunk-row guard flags (raises
        NumericalIntegrityError on the first trip, attributed to this
        executor's device). ``owners[g]`` is either one owner id for
        every lane of row ``g`` (whole-block: the row IS one cluster)
        or a per-lane sequence (segment-packed rows)."""
        from ..engine.integrity import check_guard

        for g in range(min(guard.shape[0], len(owners))):
            ow = owners[g]
            lane_map = (list(ow) if isinstance(ow, (list, tuple, np.ndarray))
                        else [ow] * guard.shape[1])
            check_guard(guard[g], stage, device=self.device,
                        lane_map=lane_map)

    def _check_totals(self, pairs, stage: str):
        """Host-side sentinel over fetched stage results: NaN/+Inf in a
        final total is a numerical escape the adapt-round guard cannot
        see (it fires inside the refine loop's launch)."""
        from ..engine.integrity import check_finite

        for ci, r in pairs:
            check_finite([r.score], stage, device=self.device,
                         what=f"cluster {ci} total")

    def _shard(self, a, *spec):
        """Device placement of one input array: sharded over the mesh
        axis, pinned to ``device`` (fleet mode — jit follows committed
        argument placement, so every executor of a fleet shares ONE
        trace/lowering via the module-level lru-cached program factories
        and the persistent compilation cache, but runs its own per-device
        executable), or the default device."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec

        if self.mesh is None:
            if self.device is not None:
                return jax.device_put(a, self.device)
            return jnp.asarray(a)
        return jax.device_put(
            a,
            NamedSharding(
                self.mesh, PartitionSpec(self.mesh.axis_names[0], *spec)
            ),
        )

    def pack(self, plan: BucketPlan, idxs: Sequence[int], clusters,
             infos) -> dict:
        """Host side of one chunk: batch, pad, and threshold. ``idxs``
        index into ``clusters``/``infos``; runs on the pipeline's
        background thread while the previous chunk executes on device."""
        N, L, Tmax, _ = plan.key
        Gp = plan.gp
        dtype = self.dtype
        seqs = np.zeros((Gp, N, L), np.int8)
        match = np.zeros((Gp, N, L), dtype)
        mismatch = np.zeros((Gp, N, L), dtype)
        ins = np.zeros((Gp, N, L), dtype)
        dels = np.zeros((Gp, N, L + 1), dtype)
        lengths = np.zeros((Gp, N), np.int32)
        weights = np.zeros((Gp, N), dtype)
        bandwidths = np.zeros((Gp, N), np.int32)
        est_err = np.zeros((Gp, N), np.float64)
        tlens0 = np.zeros(Gp, np.int32)
        tmpl0 = np.zeros((Gp, Tmax), np.int8)

        # pad every cluster to [N] reads (repeating the first read at
        # weight 0 keeps shapes without changing geometry bounds) and
        # every read to [L]; cluster slots beyond the chunk repeat the
        # chunk's first cluster at weight 0 everywhere
        for g in range(Gp):
            ci = idxs[g] if g < len(idxs) else idxs[0]
            c, info = clusters[ci], infos[ci]
            live = len(c) if g < len(idxs) else 0
            b = batch_reads(list(c) + [c[0]] * (N - len(c)), max_len=L,
                            dtype=dtype)
            seqs[g], match[g], mismatch[g] = b.seq, b.match, b.mismatch
            ins[g], dels[g], lengths[g] = b.ins, b.dels, b.lengths
            weights[g, :live] = 1.0
            bandwidths[g] = [r.bandwidth for r in c] + [
                c[0].bandwidth
            ] * (N - len(c))
            est_err[g] = [r.est_n_errors for r in c] + [
                c[0].est_n_errors
            ] * (N - len(c))
            tlens0[g] = info.tlen0
            seed = c[info.seed_idx]
            tmpl0[g, : len(seed)] = seed.seq
        thresholds = np.array([
            [poisson_cquantile(est_err[g, k], self.bandwidth_pvalue)
             for k in range(N)] for g in range(Gp)
        ])
        return {
            "plan": plan, "idxs": list(idxs), "seqs": seqs,
            "match": match, "mismatch": mismatch, "ins": ins,
            "dels": dels, "lengths": lengths, "weights": weights,
            "bandwidths": bandwidths, "est_err": est_err,
            "thresholds": thresholds, "tlens0": tlens0, "tmpl0": tmpl0,
        }

    def run(self, p: dict):
        """Device side of one chunk: adaptive-bandwidth rounds (each a
        blocking fetch of n_errors), then ONE async stage dispatch —
        returns the un-fetched packed handle so the next chunk can pack
        and dispatch before anyone blocks on it."""
        import jax.numpy as jnp

        from ..engine.device_loop import MAX_DRIFT

        plan, idxs = p["plan"], p["idxs"]
        _, _, Tmax, _ = plan.key
        shard = self._shard
        lengths, weights = p["lengths"], p["weights"]
        bandwidths, tlens0 = p["bandwidths"], p["tlens0"]

        # the big read batch transfers ONCE; only the bandwidths column
        # re-uploads per adaptation round
        sq_d = shard(p["seqs"], None, None)
        mt_d = shard(p["match"], None, None)
        mm_d = shard(p["mismatch"], None, None)
        gi_d = shard(p["ins"], None, None)
        dl_d = shard(p["dels"], None, None)
        ln_d = shard(lengths, None)
        w_d = shard(weights, None)
        t0_d = shard(p["tmpl0"], None)
        tl_d = jnp.asarray(tlens0)

        # ---- adaptive bandwidth (smart_forward_moves!,
        # model.jl:643-672), all the chunk's clusters per round in ONE
        # vmapped dispatch ----
        entry_bw = bandwidths.copy()
        fixed = np.zeros_like(weights, bool)
        fixed[weights == 0] = True
        adaptive = self.band_growth == "adaptive"
        if adaptive:
            bandwidths = np.where(
                fixed, bandwidths, adaptive_entry(bandwidths)
            )
        old_errors = np.full(lengths.shape, np.iinfo(np.int64).max)
        for _ in range(MAX_BANDWIDTH_DOUBLINGS + 1):
            K = _bucket(
                int((2 * bandwidths + np.abs(lengths - tlens0[:, None])
                     + 1).max()),
                plan.band,
            )
            out = _adapt_program(Tmax, K, adaptive, self.band_dtype,
                                 self.want_guard, self.input_enc)(
                sq_d, mt_d, mm_d, gi_d, dl_d, ln_d,
                shard(bandwidths, None), w_d, t0_d, tl_d,
            )
            n_err = np.asarray(out[0]).astype(np.int64)
            edge = (np.asarray(out[1]).astype(np.int64) if adaptive
                    else None)
            if self.want_guard:
                self._check_guard(np.asarray(out[-1]), "adapt", idxs)
            bandwidths, fixed, old_errors = grow_bandwidths(
                bandwidths, fixed, old_errors, n_err, p["thresholds"],
                entry_bw, tlens0[:, None], lengths,
                band_growth=self.band_growth, edge_hits=edge,
            )
            if fixed.all():
                break
        if self.bw_sink is not None:
            self.bw_sink(bandwidths[weights > 0])

        # ---- the whole INIT stage, vmapped over clusters: dispatch
        # only; the fetch is deferred to collect() ----
        K = _bucket(
            int((2 * bandwidths + np.abs(lengths - tlens0[:, None])
                 + 1).max()) + MAX_DRIFT,
            plan.band,
        )
        step_state = (
            (sq_d, mt_d, mm_d, gi_d, dl_d), ln_d,
            shard(bandwidths, None), w_d,
        )
        spec_k = self.speculate_k
        if spec_k:
            from ..ops.fused import DENSE_BLOCK_THRESHOLD

            if Tmax + 1 > DENSE_BLOCK_THRESHOLD:
                spec_k = 0
        packed = _stage_program(
            Tmax, K, self.H, self.min_dist, self.use_edits, self.donate,
            self.band_dtype, self.input_enc, spec_k,
        )(t0_d, tl_d, step_state)
        return packed, plan, idxs, spec_k

    def collect(self, handle) -> List[SweepResult]:
        """Blocking fetch + unpack: one SweepResult per index of the
        chunk, in ``idxs`` order (padding slots dropped)."""
        from ..engine.device_loop import unpack_stage_packed

        packed_dev, plan, idxs, spec_k = handle
        packed = np.asarray(packed_dev)
        Tmax = plan.key[2]
        results = []
        for g in range(len(idxs)):
            out = unpack_stage_packed(packed[g], self.H, Tmax,
                                      speculate=bool(spec_k))
            tlen, total, n_rec, completed, _, _, _, tmpl = out[:8]
            if spec_k:
                self.spec_attempts += out[8]
                self.spec_hits += out[9]
            results.append(SweepResult(
                consensus=tmpl[:tlen], score=total, n_iters=n_rec,
                converged=completed,
            ))
        if self.want_guard:
            self._check_totals(zip(idxs, results), "stage")
        return results

    def pack_seg(self, plan: SegmentBucketPlan, packs: Sequence[PackPlan],
                 clusters, infos) -> dict:
        """Host side of one SEGMENT-PACKED chunk: each pack's lane
        block holds several clusters' reads at their planned offsets,
        gap/pad lanes repeat the pack's first read at weight 0 (a
        duplicate of a real read, so the edits union and every masked
        reduction are untouched — the same padding convention as
        whole-block packing). Per-SEGMENT seed templates/lengths ride
        alongside, plus the per-lane segment-id mask."""
        N, L, Tmax, _ = plan.key
        Gp, S = plan.gp, plan.sp
        dtype = self.dtype
        seqs = np.zeros((Gp, N, L), np.int8)
        match = np.zeros((Gp, N, L), dtype)
        mismatch = np.zeros((Gp, N, L), dtype)
        ins = np.zeros((Gp, N, L), dtype)
        dels = np.zeros((Gp, N, L + 1), dtype)
        lengths = np.zeros((Gp, N), np.int32)
        weights = np.zeros((Gp, N), dtype)
        bandwidths = np.zeros((Gp, N), np.int32)
        est_err = np.zeros((Gp, N), np.float64)
        seg_ids = np.zeros((Gp, N), np.int32)
        tlens0 = np.zeros((Gp, S), np.int32)
        tmpl0 = np.zeros((Gp, S, Tmax), np.int8)
        live = np.zeros((Gp, S), bool)

        for g in range(Gp):
            pk = packs[g] if g < len(packs) else packs[0]
            is_live = g < len(packs)
            slot0_pad = clusters[pk.members[0][0]][0]
            gap_pad = slot0_pad
            reads = []
            for s, (ci, off, n) in enumerate(pk.members):
                # align>1 gap lanes carry the PREVIOUS slot's seg id, so
                # they must duplicate THAT slot's read (the edits union
                # has no weight mask; a duplicate is a no-op there,
                # weight 0 silences every other reduction)
                reads += [gap_pad] * (off - len(reads))
                reads.extend(clusters[ci])
                gap_pad = clusters[ci][0]
                if is_live:
                    weights[g, off : off + n] = 1.0
                    live[g, s] = True
                info = infos[ci]
                seed = clusters[ci][info.seed_idx]
                tlens0[g, s] = info.tlen0
                tmpl0[g, s, : len(seed)] = seed.seq
            reads += [slot0_pad] * (N - len(reads))  # tail is seg id 0
            b = batch_reads(reads, max_len=L, dtype=dtype)
            seqs[g], match[g], mismatch[g] = b.seq, b.match, b.mismatch
            ins[g], dels[g], lengths[g] = b.ins, b.dels, b.lengths
            bandwidths[g] = [r.bandwidth for r in reads]
            est_err[g] = [r.est_n_errors for r in reads]
            seg_ids[g] = pk.seg_ids
            # pad segment slots mirror slot 0 so their (frozen) loops
            # trace over real-shaped data
            for s in range(len(pk.members), S):
                tlens0[g, s] = tlens0[g, 0]
                tmpl0[g, s] = tmpl0[g, 0]
        thresholds = np.array([
            [poisson_cquantile(est_err[g, k], self.bandwidth_pvalue)
             for k in range(N)] for g in range(Gp)
        ])
        return {
            "plan": plan, "packs": list(packs), "seqs": seqs,
            "match": match, "mismatch": mismatch, "ins": ins,
            "dels": dels, "lengths": lengths, "weights": weights,
            "bandwidths": bandwidths, "est_err": est_err,
            "thresholds": thresholds, "tlens0": tlens0, "tmpl0": tmpl0,
            "seg_ids": seg_ids, "live": live,
        }

    def run_seg(self, p: dict):
        """Device side of one segment-packed chunk: same protocol as
        ``run`` (adapt rounds block; the stage dispatch is async), with
        per-LANE template lengths (each lane's band frame follows its
        segment's template) and the segment stage program."""
        import jax.numpy as jnp

        from ..engine.device_loop import MAX_DRIFT

        plan, packs = p["plan"], p["packs"]
        _, _, Tmax, _ = plan.key
        S = plan.sp
        shard = self._shard
        lengths, weights = p["lengths"], p["weights"]
        bandwidths, tlens0 = p["bandwidths"], p["tlens0"]
        seg_ids = p["seg_ids"]
        # per-lane template length: each lane follows its own segment
        tlen_lane = np.take_along_axis(tlens0, seg_ids, axis=1)

        sq_d = shard(p["seqs"], None, None)
        mt_d = shard(p["match"], None, None)
        mm_d = shard(p["mismatch"], None, None)
        gi_d = shard(p["ins"], None, None)
        dl_d = shard(p["dels"], None, None)
        ln_d = shard(lengths, None)
        w_d = shard(weights, None)
        sg_d = shard(seg_ids, None)
        t0_d = shard(p["tmpl0"], None, None)
        tl_d = jnp.asarray(tlens0)
        lv_d = jnp.asarray(p["live"])

        entry_bw = bandwidths.copy()
        fixed = np.zeros_like(weights, bool)
        fixed[weights == 0] = True
        adaptive = self.band_growth == "adaptive"
        if adaptive:
            bandwidths = np.where(
                fixed, bandwidths, adaptive_entry(bandwidths)
            )
        old_errors = np.full(lengths.shape, np.iinfo(np.int64).max)
        for _ in range(MAX_BANDWIDTH_DOUBLINGS + 1):
            K = _bucket(
                int((2 * bandwidths + np.abs(lengths - tlen_lane)
                     + 1).max()),
                plan.band,
            )
            out = _seg_adapt_program(Tmax, K, S, adaptive,
                                     self.band_dtype, self.want_guard,
                                     self.input_enc)(
                sq_d, mt_d, mm_d, gi_d, dl_d, ln_d,
                shard(bandwidths, None), w_d, sg_d, t0_d, tl_d,
            )
            n_err = np.asarray(out[0]).astype(np.int64)
            edge = (np.asarray(out[1]).astype(np.int64) if adaptive
                    else None)
            if self.want_guard:
                # map each lane back to its segment's cluster id
                owners = [
                    [pk.members[s][0] if s < len(pk.members)
                     else pk.members[0][0]
                     for s in seg_ids[g]]
                    for g, pk in enumerate(packs)
                ]
                self._check_guard(np.asarray(out[-1]), "adapt", owners)
            bandwidths, fixed, old_errors = grow_bandwidths(
                bandwidths, fixed, old_errors, n_err, p["thresholds"],
                entry_bw, tlen_lane, lengths,
                band_growth=self.band_growth, edge_hits=edge,
            )
            if fixed.all():
                break
        if self.bw_sink is not None:
            self.bw_sink(bandwidths[weights > 0])

        K = _bucket(
            int((2 * bandwidths + np.abs(lengths - tlen_lane)
                 + 1).max()) + MAX_DRIFT,
            plan.band,
        )
        step_state = (
            (sq_d, mt_d, mm_d, gi_d, dl_d), ln_d,
            shard(bandwidths, None), w_d, sg_d,
        )
        packed = _seg_stage_program(
            Tmax, K, self.H, self.min_dist, self.use_edits, self.donate,
            S, self.band_dtype, self.input_enc,
        )(t0_d, tl_d, lv_d, step_state)
        return packed, plan, packs

    def collect_seg(self, handle):
        """Blocking fetch + unpack of a segment-packed chunk: one
        ``(cluster index, SweepResult)`` per live segment."""
        from ..engine.device_loop import unpack_stage_packed

        packed_dev, plan, packs = handle
        packed = np.asarray(packed_dev)
        Tmax = plan.key[2]
        out = []
        for g, pk in enumerate(packs):
            for s, (ci, _, _) in enumerate(pk.members):
                tlen, total, n_rec, completed, _, _, _, tmpl = (
                    unpack_stage_packed(packed[g, s], self.H, Tmax)
                )
                out.append((ci, SweepResult(
                    consensus=tmpl[:tlen], score=total, n_iters=n_rec,
                    converged=completed,
                )))
        if self.want_guard:
            self._check_totals(out, "stage")
        return out


def sweep_clusters_sharded(
    clusters: Sequence[Sequence[ReadScores]],
    mesh=None,
    max_iters: int = 100,
    min_dist: int = 15,
    bandwidth_pvalue: float = 0.1,
    len_bucket: int = 64,
    cluster_chunk: int = 0,
    scheduler: str = "bucketed",
    read_bucket: int = READ_BUCKET,
    band_bucket: int = BAND_BUCKET,
    do_alignment_proposals: bool = False,
    return_stats: bool = False,
    lane_target: int = LANE_TARGET,
    segment_pack: Optional[bool] = None,
    segment_align: int = 1,
    n_workers: int = 1,
    journal_path: str = "",
    resume: bool = False,
    band_dtype: str = "f32",
    band_growth: str = "double",
    guard: bool = False,
    verify_fraction: float = 0.0,
    input_enc: str = "f32",
    speculate_k: int = 0,
):
    """One consensus per cluster, all clusters in one device program.

    ``clusters``: per-cluster ReadScores lists (build with
    make_read_scores). ``mesh``: optional Mesh whose FIRST axis shards
    the cluster dimension; None runs unsharded on the default device.
    ``cluster_chunk`` > 0 processes the cluster axis in sequential
    chunks of (up to) that size (bands for every in-flight cluster live
    in HBM simultaneously — a 1024-cluster batch can exceed one chip);
    the effective chunk size rounds up to the cluster grid so all
    chunks share one shape. ``scheduler``/``read_bucket``/
    ``band_bucket``/``lane_target``: see plan_sweep (``lane_target``
    packs multiple small clusters into full 128-lane tiles per launch).
    ``do_alignment_proposals`` enables
    the in-kernel alignment-edits candidate gate (the driver default),
    matching ``rifraf(..., do_alignment_proposals=True)``.
    ``segment_pack``/``segment_align``: read-granularity packing of
    small clusters into shared lane blocks (see plan_sweep; default
    follows the ``RIFRAF_TPU_SEGMENT_PACK`` env gate). Results are
    bit-identical either way (tests/test_lane_packing.py).
    ``n_workers`` > 1 runs a device-parallel FLEET instead of a mesh:
    one ChunkExecutor pinned per device (round-robin over
    ``jax.devices()``), chunks dealt round-robin across them, each
    worker running its own pack→run→collect pipeline on a thread.
    Because jit follows committed argument placement, the fleet shares
    one trace per bucket signature (the module-level lru-cached program
    factories) and one fingerprinted persistent compilation cache — the
    bucket grid warms once per fleet, not once per worker. Mutually
    exclusive with ``mesh`` (a mesh shards ONE program over devices;
    the fleet runs independent programs per device).

    ``journal_path`` enables the write-ahead results journal: every
    completed chunk's per-cluster results are appended (one fsync'd
    JSONL record each, io.journal format) as soon as its blocking fetch
    lands, so a process death — ``kill -9`` included — forfeits at most
    the chunks in flight. ``resume=True`` then replays the journal
    (after checking its config fingerprint against this call's inputs
    and parameters; a mismatch raises ``io.journal.JournalError``),
    skips the journaled chunks, and returns results bit-identical to an
    uninterrupted run. The checkpoint interval is ONE CHUNK: at most
    one chunk per pipeline slot is recomputed.

    ``guard=True`` turns on the numerical sentinels: every adapt round
    fetches the on-device guard reduction (NaN/+Inf/sentinel-underflow
    per read lane, engine.integrity) and every collected total is
    host-checked — a trip raises ``NumericalIntegrityError`` naming the
    stage and lane instead of journaling a poisoned result.
    ``verify_fraction`` > 0 shadow-verifies that fraction of completed
    clusters (deterministically sampled by content digest, so the same
    clusters re-verify on every run): each sampled result is re-scored
    on the independent oracle path (``RIFRAF_TPU_FUSED_IMPL`` flipped,
    per-cluster device loop) and a disagreement beyond the
    tests/test_precision.py tolerance raises ``ResultDivergenceError``.
    Both default OFF, leaving the default path bit-identical.

    ``input_enc`` records the requested streamed-input encoding
    (params.input_enc). The sweep's device programs run the XLA fused
    step on exact f32 inputs either way, so results are bit-identical
    across encodings HERE — the knob keys the compiled-program caches
    and folds into the journal fingerprint (when not the "f32"
    default) so ``resume=True`` refuses to mix a journal written under
    one encoding into a run configured for the other.

    ``speculate_k`` > 0 turns on speculative edit-set evaluation inside
    the whole-block stage programs (params.speculate_k): each work
    round scores 2+k templates as segments of one
    ``fused_step_segmented`` launch and skips the next round whenever
    the replayed greedy rule lands on a speculative composite. Results
    are ALWAYS bit-identical to the serial path; buckets whose Tmax
    exceeds the segmented step's dense-block threshold, and
    segment-packed buckets (whose lane axis already carries multiple
    clusters), silently run serial. Attempt/hit totals land in
    ``SweepStats``; the extra segment lanes are reported as
    ``spec_overhead_lanes`` and excluded from the lane-occupancy
    metrics, which stay comparable to non-speculative baselines.

    Returns the per-cluster results IN INPUT ORDER; with
    ``return_stats`` also a SweepStats (per-bucket occupancy, padding
    waste, and timing).
    """
    t_start = time.perf_counter()
    G = len(clusters)
    # typed validation before any planning/packing: an empty cluster or
    # zero-length read would otherwise die inside _cluster_infos or as
    # an opaque shape error at pack time
    from ..engine.validate import validate_encoded_cluster

    for gi, c in enumerate(clusters):
        validate_encoded_cluster(c, source=f"sweep cluster {gi}")
    check_band_growth(band_growth)
    from ..ops.encoding import check_input_enc

    check_input_enc(input_enc)
    if speculate_k not in (0, 1, 2):
        raise ValueError(
            f"speculate_k must be 0, 1, or 2, got {speculate_k!r}"
        )
    infos = _cluster_infos(clusters, band_growth)
    n_axis = mesh.devices.size if mesh is not None else 1
    plans = plan_sweep(
        clusters, scheduler=scheduler, read_bucket=read_bucket,
        band_bucket=band_bucket, len_bucket=len_bucket,
        cluster_chunk=cluster_chunk, n_axis=n_axis, infos=infos,
        lane_target=lane_target, segment_pack=segment_pack,
        segment_align=segment_align, band_growth=band_growth,
    )
    if G == 0:
        stats = SweepStats(0, 0, 0, 0, 0, 0.0, 0, 0.0, [])
        return ([], stats) if return_stats else []

    if n_workers > 1 and mesh is not None:
        raise ValueError("n_workers > 1 is the per-device fleet; "
                         "pass mesh OR n_workers, not both")
    # settled per-read bandwidths of every chunk's live lanes, for the
    # SweepStats histogram (lock-shared across fleet worker threads)
    settled_bw: List[np.ndarray] = []
    bw_lock = threading.Lock()

    def bw_sink(bw):
        with bw_lock:
            settled_bw.append(np.asarray(bw).ravel())

    if n_workers > 1:
        import jax

        devs = jax.devices()
        executors = [
            ChunkExecutor(
                device=devs[i % len(devs)], max_iters=max_iters,
                min_dist=min_dist, bandwidth_pvalue=bandwidth_pvalue,
                do_alignment_proposals=do_alignment_proposals,
                band_dtype=band_dtype, band_growth=band_growth,
                bw_sink=bw_sink if return_stats else None,
                want_guard=guard, input_enc=input_enc,
                speculate_k=speculate_k,
            )
            for i in range(n_workers)
        ]
    else:
        executors = [ChunkExecutor(
            mesh=mesh, max_iters=max_iters, min_dist=min_dist,
            bandwidth_pvalue=bandwidth_pvalue,
            do_alignment_proposals=do_alignment_proposals,
            band_dtype=band_dtype, band_growth=band_growth,
            bw_sink=bw_sink if return_stats else None,
            want_guard=guard, input_enc=input_enc,
            speculate_k=speculate_k,
        )]

    tasks = [
        (bi, plan, chunk)
        for bi, plan in enumerate(plans)
        for chunk in plan.chunks
    ]
    bucket_seconds = [0.0] * len(plans)
    seconds_lock = threading.Lock()
    out: List[Optional[SweepResult]] = [None] * G

    # ---- write-ahead journal / resume (the checkpoint interval is one
    # chunk: each completed chunk's results are fsync'd before the next
    # collect, so a kill forfeits only the chunks in flight) ----
    journal = None
    done_tasks: set = set()
    if journal_path:
        from ..io.journal import open_resumable
        from ..utils.constants import encode_seq

        fp = _journal_fingerprint(
            G, infos, clusters, max_iters, min_dist,
            bandwidth_pvalue, len_bucket, cluster_chunk, scheduler,
            read_bucket, band_bucket, do_alignment_proposals,
            lane_target, segment_pack, segment_align,
            band_dtype, band_growth, guard, verify_fraction,
            input_enc, speculate_k,
        )
        journal, prior = open_resumable(
            journal_path,
            {"fingerprint": fp, "n_tasks": len(tasks), "n_clusters": G},
            resume,
        )
        for rec in prior:
            if rec.get("kind") != "chunk":
                continue
            ti = rec.get("task")
            if not isinstance(ti, int) or not 0 <= ti < len(tasks):
                continue
            # replay: decode_seq/encode_seq and JSON float repr both
            # roundtrip exactly, so replayed results are bit-identical
            # to the run that journaled them
            for ci, seq, score, n_iters, converged in rec["results"]:
                out[ci] = SweepResult(
                    consensus=encode_seq(seq), score=float(score),
                    n_iters=int(n_iters), converged=bool(converged),
                )
            done_tasks.add(ti)
    pending = [(ti, t) for ti, t in enumerate(tasks)
               if ti not in done_tasks]

    def make_stages(executor):
        # one pack/run/collect triple per fleet worker; `out` writes are
        # index-addressed and chunk-disjoint so only the per-bucket
        # timing accumulator needs the lock
        def pack(task):
            ti, (bi, plan, idxs) = task
            if isinstance(plan, SegmentBucketPlan):
                return ti, bi, True, executor.pack_seg(
                    plan, idxs, clusters, infos)
            return ti, bi, False, executor.pack(
                plan, idxs, clusters, infos)

        def run(arg):
            ti, bi, seg, packed = arg
            t0 = time.perf_counter()
            handle = (executor.run_seg(packed) if seg
                      else executor.run(packed))
            with seconds_lock:
                bucket_seconds[bi] += time.perf_counter() - t0
            return ti, bi, seg, handle

        def collect(arg):
            ti, bi, seg, handle = arg
            t0 = time.perf_counter()
            if seg:
                pairs = executor.collect_seg(handle)
            else:
                pairs = list(zip(handle[2], executor.collect(handle)))
            for ci, r in pairs:
                out[ci] = r
            if journal is not None:
                from ..utils.constants import decode_seq

                journal.append({
                    "kind": "chunk", "task": ti,
                    "results": [
                        [int(ci), decode_seq(r.consensus),
                         float(r.score), int(r.n_iters),
                         bool(r.converged)]
                        for ci, r in pairs
                    ],
                })
            with seconds_lock:
                bucket_seconds[bi] += time.perf_counter() - t0

        return pack, run, collect

    if len(executors) == 1:
        pack, run, collect = make_stages(executors[0])
        pipeline_map(pack, run, collect, pending)
    else:
        # deal chunks round-robin across the fleet; each worker drives
        # its own double-buffered pipeline on its own thread. The
        # lru-cached program factories hand every worker the SAME jit
        # wrapper per bucket signature, so a signature traces once and
        # per-device executables come out of one (persistent,
        # fingerprinted) compilation cache — the grid warms once per
        # fleet, not once per worker.
        shards = [pending[w::len(executors)]
                  for w in range(len(executors))]

        def drive(w):
            pack, run, collect = make_stages(executors[w])
            pipeline_map(pack, run, collect, shards[w])

        threads = [
            threading.Thread(target=drive, args=(w,), daemon=True)
            for w in range(1, len(executors))
            if shards[w]
        ]
        for th in threads:
            th.start()
        if shards[0]:
            drive(0)
        for th in threads:
            th.join()
    if journal is not None:
        journal.close()

    # ---- shadow verification: re-score a deterministic content-keyed
    # sample of the completed clusters on the independent oracle path.
    # Runs AFTER the journal closes: a diverged result has already been
    # journaled as a chunk, but the raise below means the caller never
    # sees (or re-emits) it as truth, and a resume re-verifies the same
    # sample again.
    if verify_fraction > 0.0:
        from ..engine.integrity import selected_for_verify, verify_result

        for ci in range(G):
            if out[ci] is None:
                continue
            digest = _content_digest([clusters[ci]])
            if not selected_for_verify(digest, verify_fraction):
                continue
            verify_result(
                clusters[ci], out[ci].consensus, out[ci].score,
                what=f"sweep cluster {ci}", band_dtype=band_dtype,
                max_iters=max_iters, min_dist=min_dist,
                bandwidth_pvalue=bandwidth_pvalue,
                do_alignment_proposals=do_alignment_proposals,
                band_growth=band_growth,
            )

    if not return_stats:
        return list(out)

    from ..utils.shapes import pack_lanes

    useful_total = sum(i.useful for i in infos)
    buckets = []
    reads_used = 0
    cluster_lanes = 0
    slots_total = 0
    spec_overhead = 0
    if speculate_k:
        from ..ops.fused import DENSE_BLOCK_THRESHOLD
    for bi, plan in enumerate(plans):
        seg = isinstance(plan, SegmentBucketPlan)
        if seg:
            # chunks hold PackPlans; flatten to member cluster indices
            idx_chunks = [
                [ci for pk_ in ch for ci, _, _ in pk_.members]
                for ch in plan.chunks
            ]
            n_slots_used = sum(len(ch) for ch in plan.chunks)
        else:
            idx_chunks = plan.chunks
        n_in = sum(len(ch) for ch in idx_chunks)
        padded = len(plan.chunks) * plan.gp * plan.key[0] * plan.key[1]
        useful = sum(infos[ci].useful for ch in idx_chunks for ci in ch)
        lane_lens = [
            len(r) for ch in idx_chunks for ci in ch
            for r in clusters[ci]
        ]
        pk = pack_lanes(lane_lens)
        slots = len(plan.chunks) * _lane_slots(plan.gp, plan.key[0])
        reads = sum(
            infos[ci].n_reads for ch in idx_chunks for ci in ch
        )
        reads_used += reads
        # segment-packed buckets reserve lanes at READ granularity — a
        # cluster occupies exactly its reads' lanes, not a whole Npad
        # block, so cluster-lane accounting equals read accounting
        cluster_lanes += reads if seg else n_in * plan.key[0]
        slots_total += slots
        # speculating buckets tile each cluster's read lanes 2+k times
        # inside the stage launch; the 1+k copies are overhead lanes,
        # tracked apart from the demand-side slot accounting (mirrors
        # ChunkExecutor.run's per-chunk eligibility rule)
        if (speculate_k and not seg
                and plan.key[2] + 1 <= DENSE_BLOCK_THRESHOLD):
            spec_overhead += (
                len(plan.chunks)
                * _lane_slots(plan.gp, (2 + speculate_k) * plan.key[0])
                - slots
            )
        buckets.append(BucketStats(
            key=plan.key, n_clusters=n_in, n_chunks=len(plan.chunks),
            gp=plan.gp,
            occupancy=(
                (n_slots_used if seg else n_in)
                / (len(plan.chunks) * plan.gp)
            ),
            useful_cells=useful, padded_cells=padded,
            waste=1.0 - useful / padded,
            seconds=bucket_seconds[bi],
            lane_occupancy=pk.occupancy,
            uniform_lane_occupancy=pk.uniform_occupancy,
            lane_slots=slots,
            lane_slot_occupancy=reads / slots if slots else 1.0,
        ))
    padded_total = plan_cells(plans)
    uniform_plans = plan_sweep(
        clusters, scheduler="uniform", len_bucket=len_bucket,
        cluster_chunk=cluster_chunk, n_axis=n_axis, infos=infos,
    )
    stats = SweepStats(
        n_clusters=G, n_buckets=len(plans), n_chunks=len(tasks),
        useful_cells=useful_total, padded_cells=padded_total,
        waste=1.0 - useful_total / padded_total,
        uniform_padded_cells=plan_cells(uniform_plans),
        seconds=time.perf_counter() - t_start,
        buckets=buckets,
        lane_occupancy=cluster_lanes / slots_total if slots_total else 1.0,
        lane_occupancy_reads=(
            reads_used / slots_total if slots_total else 1.0
        ),
        band_dtype=band_dtype,
        band_growth=band_growth,
        bw_hist=_settled_bw_hist(settled_bw),
        input_enc=input_enc,
        speculate_k=speculate_k,
        spec_overhead_lanes=spec_overhead,
        spec_attempts=sum(e.spec_attempts for e in executors),
        spec_hits=sum(e.spec_hits for e in executors),
    )
    return list(out), stats
