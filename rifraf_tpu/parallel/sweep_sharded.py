"""Cluster-sharded consensus sweep: many clusters, one device program.

The reference fans independent consensus jobs over Julia worker
processes (scripts/rifraf.jl:190-191, `pmap`). parallel.cluster replaces
that with device-pinned host threads — one PYTHON driver per cluster.
This module is the third rung (BASELINE.json config 5, "1024-cluster
sweep ... across a pod"): the WHOLE hill-climb of G clusters runs as one
jitted program, vmapped over the cluster axis and sharded across a
`jax.sharding.Mesh` — XLA partitions the program along clusters (no
collectives needed; the axis is embarrassingly parallel), so a pod
slice processes thousands of clusters with one dispatch per
adaptation round plus one per stage sweep.

Scope: the device-loop configuration (engine.device_loop) — no
reference, full batch per cluster, all-edits candidates
(do_alignment_proposals=False). Per-cluster results are BIT-IDENTICAL
to running `rifraf()` per cluster in that configuration
(tests/test_sweep_sharded.py): the same fused XLA step, the same
candidate selection, the same adaptive-bandwidth protocol, just with a
leading cluster axis everywhere (lax.while_loop under vmap keeps
finished clusters frozen while stragglers iterate).
"""

from __future__ import annotations

from typing import List, NamedTuple, Sequence

import numpy as np

from ..models.sequences import ReadScores, batch_reads
from ..utils.mathops import logsumexp10, poisson_cquantile

MAX_BANDWIDTH_DOUBLINGS = 5  # model.jl:650


class SweepResult(NamedTuple):
    consensus: np.ndarray
    score: float
    n_iters: int
    converged: bool


def _bucket(n: int, b: int) -> int:
    return ((n + b - 1) // b) * b


def sweep_clusters_sharded(
    clusters: Sequence[Sequence[ReadScores]],
    mesh=None,
    max_iters: int = 100,
    min_dist: int = 15,
    bandwidth_pvalue: float = 0.1,
    len_bucket: int = 64,
    cluster_chunk: int = 0,
) -> List[SweepResult]:
    """One consensus per cluster, all clusters in one device program.

    ``clusters``: per-cluster ReadScores lists (build with
    make_read_scores). ``mesh``: optional Mesh whose FIRST axis shards
    the cluster dimension; None runs unsharded on the default device.
    ``cluster_chunk`` > 0 processes the cluster axis in sequential
    chunks of that size (bands for every in-flight cluster live in HBM
    simultaneously — a 1024-cluster batch can exceed one chip).
    """
    if cluster_chunk and len(clusters) > cluster_chunk:
        out: List[SweepResult] = []
        for s in range(0, len(clusters), cluster_chunk):
            out.extend(sweep_clusters_sharded(
                clusters[s : s + cluster_chunk], mesh=mesh,
                max_iters=max_iters, min_dist=min_dist,
                bandwidth_pvalue=bandwidth_pvalue, len_bucket=len_bucket,
            ))
        return out
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec

    from ..engine.device_loop import make_stage_runner
    from ..ops import align_jax
    from ..ops.fused import fused_step_full, pack_layout

    from ..engine.params import resolve_dtype

    dtype = resolve_dtype(None)
    G = len(clusters)
    if G == 0:
        return []
    n_axis = mesh.devices.size if mesh is not None else 1
    Gp = _bucket(G, max(n_axis, 1))
    N = max(len(c) for c in clusters)
    L = _bucket(max(len(r) for c in clusters for r in c), len_bucket)

    # pad every cluster to [N] reads (repeating the first read at weight
    # 0 keeps shapes without changing geometry bounds) and every read to
    # [L]; clusters beyond G repeat cluster 0 at weight 0 everywhere
    seqs = np.zeros((Gp, N, L), np.int8)
    match = np.zeros((Gp, N, L), dtype)
    mismatch = np.zeros((Gp, N, L), dtype)
    ins = np.zeros((Gp, N, L), dtype)
    dels = np.zeros((Gp, N, L + 1), dtype)
    lengths = np.zeros((Gp, N), np.int32)
    weights = np.zeros((Gp, N), dtype)
    bandwidths = np.zeros((Gp, N), np.int32)
    est_err = np.zeros((Gp, N), np.float64)

    for g in range(Gp):
        c = clusters[g] if g < G else clusters[0]
        live = len(c) if g < G else 0
        b = batch_reads(list(c) + [c[0]] * (N - len(c)), max_len=L,
                        dtype=dtype)
        seqs[g], match[g], mismatch[g] = b.seq, b.match, b.mismatch
        ins[g], dels[g], lengths[g] = b.ins, b.dels, b.lengths
        weights[g, :live] = 1.0
        bandwidths[g] = [r.bandwidth for r in c] + [c[0].bandwidth] * (
            N - len(c)
        )
        est_err[g] = [r.est_n_errors for r in c] + [c[0].est_n_errors] * (
            N - len(c)
        )

    # initial consensus per cluster: the read with the best
    # logsumexp10(match_scores) (model.jl:575-579)
    tlens0 = np.zeros(Gp, np.int32)
    Tmax = 0
    best_idx = np.zeros(Gp, np.int64)
    for g in range(Gp):
        c = clusters[g] if g < G else clusters[0]
        k = int(np.argmax([logsumexp10(r.match_scores) for r in c]))
        best_idx[g] = k
        tlens0[g] = len(c[k])
        Tmax = max(Tmax, len(c[k]) + 1)
    Tmax = _bucket(Tmax + 1, len_bucket)
    tmpl0 = np.zeros((Gp, Tmax), np.int8)
    for g in range(Gp):
        c = clusters[g] if g < G else clusters[0]
        r = c[int(best_idx[g])]
        tmpl0[g, : len(r)] = r.seq

    from ..engine.device_loop import MAX_DRIFT

    T1 = Tmax + 1
    shard = (
        (lambda a, *spec: jax.device_put(
            a, NamedSharding(mesh, PartitionSpec(mesh.axis_names[0], *spec))
        ))
        if mesh is not None
        else (lambda a, *spec: jnp.asarray(a))
    )

    def shard_all(bw):
        return (
            shard(seqs, None, None), shard(match, None, None),
            shard(mismatch, None, None), shard(ins, None, None),
            shard(dels, None, None), shard(lengths, None),
            shard(bw, None), shard(weights, None),
        )

    # ---- adaptive bandwidth (smart_forward_moves!, model.jl:643-672),
    # all clusters per round in ONE vmapped dispatch ----
    def adapt_round_fn(K):
        def one(seq_g, match_g, mismatch_g, ins_g, dels_g, lengths_g,
                bw_g, w_g, tmpl_g, tlen_g):
            geom = align_jax.BandGeometry.make(lengths_g, tlen_g, bw_g)
            _, _, _, packed = fused_step_full(
                tmpl_g[: Tmax], seq_g, match_g, mismatch_g, ins_g, dels_g,
                geom, w_g, K, False, True, 0, False,
            )
            lay = pack_layout(N, T1, True, False)
            return packed[slice(*lay["n_errors"])]

        return jax.jit(jax.vmap(one))

    entry_bw = bandwidths.copy()
    fixed = np.zeros((Gp, N), bool)
    fixed[weights == 0] = True
    old_errors = np.full((Gp, N), np.iinfo(np.int64).max)
    thresholds = np.array([
        [poisson_cquantile(est_err[g, k], bandwidth_pvalue)
         for k in range(N)] for g in range(Gp)
    ])
    for _ in range(MAX_BANDWIDTH_DOUBLINGS + 1):
        K = int(
            (2 * bandwidths + np.abs(lengths - tlens0[:, None]) + 1).max()
        )
        K = _bucket(K, 8)
        n_err = np.asarray(adapt_round_fn(K)(
            *shard_all(bandwidths), shard(tmpl0, None),
            jnp.asarray(tlens0),
        )).astype(np.int64)
        max_bw = np.minimum(
            np.minimum(entry_bw << MAX_BANDWIDTH_DOUBLINGS,
                       tlens0[:, None]),
            lengths,
        )
        grow = (~fixed) & (n_err > thresholds) & (n_err < old_errors) & (
            bandwidths < max_bw
        )
        fixed |= ~grow
        if not grow.any():
            break
        old_errors = np.where(grow, n_err, old_errors)
        bandwidths = np.where(grow, np.minimum(bandwidths * 2, max_bw),
                              bandwidths)

    # ---- the whole INIT stage, vmapped over clusters ----
    K = _bucket(
        int((2 * bandwidths + np.abs(lengths - tlens0[:, None]) + 1).max())
        + MAX_DRIFT,
        8,
    )
    lay = pack_layout(N, T1, False)

    def step_fn(tmpl, tlen, s):
        (seq_g, match_g, mismatch_g, ins_g, dels_g), lengths_g, bw_g, w_g = s
        geom = align_jax.BandGeometry.make(lengths_g, tlen, bw_g)
        _, _, _, packed = fused_step_full(
            tmpl[:Tmax], seq_g, match_g, mismatch_g, ins_g, dels_g, geom,
            w_g, K, False, False, 0,
        )
        sub_t = packed[slice(*lay["sub"])].reshape(T1, 4)
        ins_t = packed[slice(*lay["ins"])].reshape(T1, 4)
        del_t = packed[slice(*lay["del"])]
        return packed[0], sub_t, ins_t, del_t

    runner = make_stage_runner(
        step_fn, do_indels=True, min_dist=min_dist, H=max_iters + 1,
        Tmax=Tmax, stop_on_same=True,
    )
    sq_d, mt_d, mm_d, gi_d, dl_d, ln_d, bw_d, w_d = shard_all(bandwidths)
    step_state = ((sq_d, mt_d, mm_d, gi_d, dl_d), ln_d, bw_d, w_d)

    packed = jax.vmap(
        lambda t0, tl, st: runner.run(t0, tl, -jnp.inf, jnp.int32(max_iters),
                                      jnp.int32(0), st),
        in_axes=(0, 0, ((0, 0, 0, 0, 0), 0, 0, 0)),
    )(shard(tmpl0, None), jnp.asarray(tlens0), step_state)
    packed = np.asarray(packed)

    H = max_iters + 1
    out = []
    for g in range(G):
        p = packed[g]
        tlen = int(p[0])
        total = float(p[1])
        n_rec = int(p[2])
        completed = bool(p[3])
        o = 5 + H + H * Tmax
        cons = p[o : o + Tmax].astype(np.int8)[:tlen]
        out.append(SweepResult(
            consensus=cons, score=total, n_iters=n_rec, converged=completed,
        ))
    return out
