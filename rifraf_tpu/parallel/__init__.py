from .cluster import (
    PipelineJobError,
    pipeline_map,
    resolve_jobs_flag,
    sweep_clusters,
)
from .sharding import (
    READS_AXIS,
    make_mesh,
    pad_batch_to,
    shard_batch,
    sharded_consensus_step,
)
from .sweep_sharded import (
    BucketPlan,
    BucketStats,
    ChunkExecutor,
    SweepResult,
    SweepStats,
    bucket_key,
    cluster_info,
    plan_sweep,
    sweep_clusters_sharded,
)
