from .cluster import resolve_jobs_flag, sweep_clusters
from .sharding import (
    READS_AXIS,
    make_mesh,
    pad_batch_to,
    shard_batch,
    sharded_consensus_step,
)
from .sweep_sharded import SweepResult, sweep_clusters_sharded
