"""Device-mesh parallelism: sharded consensus steps and cluster sweeps.

The reference's only parallelism is process-level `pmap` over input files
(scripts/rifraf.jl:190-191, Julia Distributed RPC). The TPU-native design
replaces that with XLA collectives over ICI:

- **Read sharding (TP-like)**: one consensus spans a pod slice by sharding
  the read axis of the batch across the mesh. Per-read DP fills are
  embarrassingly parallel; the only cross-chip communication is the
  `psum` of per-read scores — a single scalar (or [P] vector) reduction
  over ICI per step, inserted automatically by XLA from the sharding
  annotations.
- **Cluster sweep (DP-like)**: independent consensus jobs (one per
  cluster/file) driven concurrently, one worker thread per device — the
  `pmap` equivalent. Implemented in rifraf_tpu.parallel.cluster.

Everything goes through `jax.jit` with `NamedSharding` in/out specs: pick a
mesh, annotate shardings, let XLA insert collectives.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.sequences import ReadBatch
from ..ops import align_jax
from ..ops.align_jax import BandGeometry
from ..ops.proposal_jax import _score_one_read

READS_AXIS = "reads"


def make_mesh(n_devices: Optional[int] = None, axis: str = READS_AXIS) -> Mesh:
    """A 1-D device mesh over the read (or cluster) axis."""
    devices = np.array(jax.devices())
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(devices, (axis,))


def shard_read_axis(arr, mesh: Mesh):
    """Place one [N, ...] array with its leading (read) axis sharded over
    the mesh."""
    return jax.device_put(np.asarray(arr), NamedSharding(mesh, P(READS_AXIS)))


def shard_batch(batch: ReadBatch, mesh: Mesh) -> ReadBatch:
    """Place every [N, ...] batch array with its read axis sharded over the
    mesh. N must be divisible by the mesh size (pad the batch if not)."""
    return ReadBatch(*[shard_read_axis(a, mesh) for a in batch])


def pad_batch_to(batch: ReadBatch, n: int) -> Tuple[ReadBatch, np.ndarray]:
    """Pad the read axis to n by DUPLICATING the last real read (weight 0);
    returns the padded batch and a {0,1} weight vector marking real reads.

    Duplication (rather than zero-length dummies) keeps the static band
    height K unchanged: a length-0 dummy's band spans ``|0 - tlen| + 3``
    data rows, which would inflate every read's band buffer to the full
    template length."""
    cur = batch.n_reads
    if cur >= n:
        w = np.ones(cur, dtype=np.float64)
        return batch, w
    pad = n - cur

    def padded(a):
        reps = np.repeat(a[-1:], pad, axis=0)
        return np.concatenate([a, reps])

    out = ReadBatch(*[padded(np.asarray(a)) for a in batch])
    w = np.concatenate([np.ones(cur), np.zeros(pad)])
    return out, w


def weighted_read_sum(weights, values):
    """Sum weight*value over the leading (read) axis, neutralizing
    zero-weight padding rows by masking on the WEIGHT — not on finiteness
    of the value. A real read's legitimate -inf score must propagate (an
    impossible proposal must rank below every valid one), while padding
    rows contribute exactly 0 even when their values are -inf/nan."""
    w = weights.reshape(weights.shape + (1,) * (values.ndim - 1))
    return jnp.sum(jnp.where(w > 0, w * values, 0.0), axis=0)


def _consensus_step(
    template,
    seq,
    match,
    mismatch,
    ins,
    dels,
    geom: BandGeometry,
    weights,
    ptype,
    ppos,
    pbase,
    K: int,
):
    """One full sharded consensus step: the merged forward+backward fill
    (one column scan carries both chains — align_jax._fwd_bwd_one),
    per-read total scores, and all-proposal scores, reduced over the read
    axis. The reductions are where XLA inserts `psum` over ICI when the
    read axis is sharded."""
    fwd_bwd = jax.vmap(
        align_jax._fwd_bwd_one,
        in_axes=(None, 0, 0, 0, 0, 0, 0, None),
    )
    A, _, scores, B = fwd_bwd(template, seq, match, mismatch, ins, dels, geom, K)
    score_fn = jax.vmap(
        _score_one_read, in_axes=(0, 0, 0, 0, 0, 0, 0, 0, None, None, None)
    )
    pscores = score_fn(A, B, seq, match, mismatch, ins, dels, geom, ptype, ppos, pbase)
    total = weighted_read_sum(weights, scores)
    proposal_totals = weighted_read_sum(weights, pscores)
    return total, proposal_totals


def sharded_consensus_step(
    mesh: Mesh,
    template: np.ndarray,
    batch: ReadBatch,
    geom: BandGeometry,
    proposals_enc: Tuple[np.ndarray, np.ndarray, np.ndarray],
    weights: np.ndarray,
    K: int,
):
    """jit + shard one consensus step over the mesh's read axis.

    Returns (total_score, proposal_total_scores[P]) — both fully
    replicated after the XLA-inserted reductions.
    """
    ptype, ppos, pbase = proposals_enc
    rsh = NamedSharding(mesh, P(READS_AXIS))
    rep = NamedSharding(mesh, P())
    in_shardings = (
        rep,  # template
        rsh,  # seq
        rsh,  # match
        rsh,  # mismatch
        rsh,  # ins
        rsh,  # dels
        BandGeometry(rsh, rsh, rsh, rsh, rsh),  # per-read geometry scalars
        rsh,  # weights
        rep,  # ptype
        rep,  # ppos
        rep,  # pbase
    )
    step = jax.jit(
        _consensus_step,
        static_argnums=(11,),
        in_shardings=in_shardings,
        out_shardings=(rep, rep),
    )
    return step(
        jnp.asarray(template, jnp.int8),
        jnp.asarray(batch.seq),
        jnp.asarray(batch.match),
        jnp.asarray(batch.mismatch),
        jnp.asarray(batch.ins),
        jnp.asarray(batch.dels),
        geom,
        jnp.asarray(weights),
        jnp.asarray(ptype),
        jnp.asarray(ppos),
        jnp.asarray(pbase),
        K,
    )
