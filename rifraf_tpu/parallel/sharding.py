"""Device-mesh parallelism: sharded consensus steps and cluster sweeps.

The reference's only parallelism is process-level `pmap` over input files
(scripts/rifraf.jl:190-191, Julia Distributed RPC). The TPU-native design
replaces that with XLA collectives over ICI:

- **Read sharding (TP-like)**: one consensus spans a pod slice by sharding
  the read axis of the batch across the mesh. Per-read DP fills are
  embarrassingly parallel; the only cross-chip communication is the
  `psum` of per-read scores — a single scalar (or [P] vector) reduction
  over ICI per step, inserted automatically by XLA from the sharding
  annotations.
- **Cluster sweep (DP-like)**: independent consensus jobs (one per
  cluster/file) driven concurrently, one worker thread per device — the
  `pmap` equivalent. Implemented in rifraf_tpu.parallel.cluster.

Everything goes through `jax.jit` with `NamedSharding` in/out specs: pick a
mesh, annotate shardings, let XLA insert collectives.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.sequences import ReadBatch
from ..ops import align_jax
from ..ops.align_jax import BandGeometry
from ..ops.proposal_jax import _score_one_read
from ..utils.meshutil import shard_map_compat as _shard_map

READS_AXIS = "reads"


def make_mesh(n_devices: Optional[int] = None, axis: str = READS_AXIS) -> Mesh:
    """A 1-D device mesh over the read (or cluster) axis."""
    devices = np.array(jax.devices())
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(devices, (axis,))


def shard_read_axis(arr, mesh: Mesh):
    """Place one [N, ...] array with its leading (read) axis sharded over
    the mesh."""
    return jax.device_put(np.asarray(arr), NamedSharding(mesh, P(READS_AXIS)))


def shard_batch(batch: ReadBatch, mesh: Mesh) -> ReadBatch:
    """Place every [N, ...] batch array with its read axis sharded over the
    mesh. N must be divisible by the mesh size (pad the batch if not)."""
    return ReadBatch(*[shard_read_axis(a, mesh) for a in batch])


def pad_batch_to(batch: ReadBatch, n: int) -> Tuple[ReadBatch, np.ndarray]:
    """Pad the read axis to n by DUPLICATING the last real read (weight 0);
    returns the padded batch and a {0,1} weight vector marking real reads.

    Duplication (rather than zero-length dummies) keeps the static band
    height K unchanged: a length-0 dummy's band spans ``|0 - tlen| + 3``
    data rows, which would inflate every read's band buffer to the full
    template length."""
    cur = batch.n_reads
    if cur >= n:
        w = np.ones(cur, dtype=np.float64)
        return batch, w
    pad = n - cur

    def padded(a):
        reps = np.repeat(a[-1:], pad, axis=0)
        return np.concatenate([a, reps])

    out = ReadBatch(*[padded(np.asarray(a)) for a in batch])
    w = np.concatenate([np.ones(cur), np.zeros(pad)])
    return out, w


def weighted_read_sum(weights, values):
    """Sum weight*value over the leading (read) axis, neutralizing
    zero-weight padding rows by masking on the WEIGHT — not on finiteness
    of the value. A real read's legitimate -inf score must propagate (an
    impossible proposal must rank below every valid one), while padding
    rows contribute exactly 0 even when their values are -inf/nan."""
    w = weights.reshape(weights.shape + (1,) * (values.ndim - 1))
    return jnp.sum(jnp.where(w > 0, w * values, 0.0), axis=0)


def _consensus_step(
    template,
    seq,
    match,
    mismatch,
    ins,
    dels,
    geom: BandGeometry,
    weights,
    ptype,
    ppos,
    pbase,
    K: int,
):
    """One full sharded consensus step: the merged forward+backward fill
    (one column scan carries both chains — align_jax._fwd_bwd_one),
    per-read total scores, and all-proposal scores, reduced over the read
    axis. The reductions are where XLA inserts `psum` over ICI when the
    read axis is sharded."""
    fwd_bwd = jax.vmap(
        align_jax._fwd_bwd_one,
        in_axes=(None, 0, 0, 0, 0, 0, 0, None),
    )
    A, _, scores, B = fwd_bwd(template, seq, match, mismatch, ins, dels, geom, K)
    score_fn = jax.vmap(
        _score_one_read, in_axes=(0, 0, 0, 0, 0, 0, 0, 0, None, None, None)
    )
    pscores = score_fn(A, B, seq, match, mismatch, ins, dels, geom, ptype, ppos, pbase)
    total = weighted_read_sum(weights, scores)
    proposal_totals = weighted_read_sum(weights, pscores)
    return total, proposal_totals


# --- mesh-sharded Pallas engine ------------------------------------------
#
# GSPMD cannot partition a pallas_call, so the Pallas fill+dense step runs
# under shard_map: each shard fills its local reads' bands with the
# on-core kernel, and the cross-shard reductions (total score, dense
# all-edit tables, edit-indicator unions) are explicit psum/pmax over the
# read axis — the same collectives XLA inserts for the fused XLA path.
# One subtlety: the uniform band frame must be GLOBAL (one OFF for every
# shard, computed by pmax) so the band layout, the static K, and the
# host-side traceback geometry agree across chips.


def mesh_fill_buffers(mesh: Mesh, batch: ReadBatch, Npad_local: int):
    """Per-shard FillBuffers (ops.fill_pallas) built under shard_map from
    a read-sharded batch; the returned (global-view) buffers keep their
    lane axis sharded with Npad_local lanes per device."""

    from ..ops.fill_pallas import FillBuffers, build_fill_buffers

    def local(seq, match, mismatch, ins, dels, lengths):
        return build_fill_buffers(
            seq, match, mismatch, ins, dels, lengths, Npad_local
        )

    lanes2 = P(None, READS_AXIS)
    out_specs = FillBuffers(
        seq_T=lanes2, match_T=lanes2, mismatch_T=lanes2, ins_T=lanes2,
        dels_T=lanes2, rseq_T=lanes2, rmatch_T=lanes2, rmismatch_T=lanes2,
        rins_T=lanes2, rdels_T=lanes2, lengths=P(READS_AXIS),
    )
    fn = _shard_map(
        local, mesh=mesh,
        in_specs=(P(READS_AXIS, None),) * 5 + (P(READS_AXIS),),
        out_specs=out_specs,
    )
    return fn(
        batch.seq, batch.match, batch.mismatch, batch.ins, batch.dels,
        batch.lengths,
    )


@functools.partial(
    jax.jit,
    static_argnames=("mesh", "K", "T1p", "C", "want_stats",
                     "want_moves", "interpret", "impl"),
)
def mesh_fused_step_pallas(
    mesh: Mesh,
    template,  # int8 [Tmax] (replicated)
    tlen,  # int32
    bufs,  # FillBuffers, lane axis sharded (mesh_fill_buffers)
    lengths,  # [Nglobal] int32, read-sharded (pre-lane-padding)
    bandwidths,  # [Nglobal] int32, read-sharded
    weights,  # [Nglobal] f32, read-sharded ({0,1} padding mask)
    K: int,
    T1p: int,
    C: int,
    want_stats: bool = False,
    want_moves: bool = False,
    interpret: bool = False,
    impl=None,
):
    """The Pallas fused step over a read-sharded mesh: per-shard on-core
    fill + dense tables, cross-shard psum/pmax reductions. Returns
    (packed, moves-or-None); packed follows pack_layout_pallas with
    Npad = n_devices * Npad_local (per-shard lane padding preserved —
    map read r to slot (r // Nlocal) * Npad_local + r % Nlocal).

    ``impl`` is the fused-step routing ("mega"/"split") resolved by the
    CALLER via ops.fused_pallas.select_impl — a static argname here, so
    it must be decided outside this jit (same discipline as the
    single-device dispatchers: the env selector never reads inside a
    trace). Each shard runs the SINGLE-LAUNCH megakernel on its local
    lanes when eligible; only the psum/pmax epilogue crosses chips."""

    from ..ops.fused_pallas import fused_tables_auto

    def local(t, tl, bufs_l, lens_l, bw_l, w_l):
        from ..ops.dense_pallas import pack_parts

        geom = BandGeometry.make(lens_l, tl, bw_l)
        OFF_g = jax.lax.pmax(jnp.max(geom.offset), READS_AXIS)
        sl = bufs_l.lengths
        # the split path's backward-halo rolls need ONE slen_min base
        # across shards (any shared base is self-consistent; a per-shard
        # minimum is not). The megakernel bakes the mirroring at write
        # time and ignores it.
        slen_min_g = jax.lax.pmin(
            jnp.min(jnp.where(sl > 0, sl, jnp.int32(2**30))), READS_AXIS
        )
        out = fused_tables_auto(
            t, tl, bufs_l, geom, w_l, K, T1p, C,
            want_stats=want_stats, want_moves=want_moves,
            off_override=OFF_g, slen_min=slen_min_g, interpret=interpret,
            impl=impl,
        )
        out.pop("impl", None)
        # cross-shard reductions, then the SHARED section order
        out = dict(
            out,
            total=jax.lax.psum(out["total"], READS_AXIS),
            sub=jax.lax.psum(out["sub"], READS_AXIS),
            ins=jax.lax.psum(out["ins"], READS_AXIS),
            **{"del": jax.lax.psum(out["del"], READS_AXIS)},
        )
        if want_stats:
            out["edits"] = jax.lax.pmax(
                out["edits"].astype(jnp.float32), READS_AXIS
            )
        parts = pack_parts(out, want_stats)
        moves = out.get("moves")
        if moves is None:
            moves = jnp.zeros((0, 0, 0), jnp.int8)
        return tuple(parts), moves

    n_parts = 2 + (2 if want_stats else 0) + 3
    # per-shard packed sections: scalars and tables are replicated after
    # the collectives; per-read vectors stay sharded
    rep = P()
    shard = P(READS_AXIS)
    part_specs = [rep, shard]
    if want_stats:
        part_specs += [shard, rep]
    part_specs += [rep, rep, rep]
    assert len(part_specs) == n_parts
    fn = _shard_map(
        local, mesh=mesh,
        in_specs=(
            P(), P(),
            jax.tree_util.tree_map(lambda _: P(None, READS_AXIS), bufs)._replace(
                lengths=P(READS_AXIS)
            ),
            shard, shard, shard,
        ),
        out_specs=(tuple(part_specs), P(READS_AXIS, None, None)),
        # pallas_call has no varying-manual-axes annotations; the
        # collectives above establish the replication invariants instead
        check_vma=False,
    )
    parts, moves = fn(template, tlen, bufs, lengths, bandwidths, weights)
    packed = jnp.concatenate(list(parts))
    return packed, (moves if want_moves else None)


@functools.partial(
    jax.jit,
    static_argnames=("mesh", "K", "T1p", "C", "interpret"),
)
def mesh_fill_stats_pallas(
    mesh: Mesh, template, tlen, bufs, lengths, bandwidths,
    K: int, T1p: int, C: int, interpret: bool = False,
):
    """Sharded adaptation round: per-shard forward-only Pallas fill with
    move recording + traceback stats. Returns packed
    [scores (Npad), n_errors (Npad)] with the per-shard lane layout of
    mesh_fused_step_pallas."""

    from ..ops.dense_pallas import fill_stats_pallas

    def local(t, tl, bufs_l, lens_l, bw_l):
        geom = BandGeometry.make(lens_l, tl, bw_l)
        OFF_g = jax.lax.pmax(jnp.max(geom.offset), READS_AXIS)
        packed = fill_stats_pallas(
            t, tl, bufs_l, geom, K, T1p, C, off_override=OFF_g,
            interpret=interpret,
        )
        Npad_l = bufs_l.seq_T.shape[1]
        return packed[:Npad_l], packed[Npad_l:]

    fn = _shard_map(
        local, mesh=mesh,
        in_specs=(
            P(), P(),
            jax.tree_util.tree_map(lambda _: P(None, READS_AXIS), bufs)._replace(
                lengths=P(READS_AXIS)
            ),
            P(READS_AXIS), P(READS_AXIS),
        ),
        out_specs=(P(READS_AXIS), P(READS_AXIS)),
        check_vma=False,
    )
    scores, nerr = fn(template, tlen, bufs, lengths, bandwidths)
    return jnp.concatenate([scores, nerr])


@functools.partial(
    jax.jit,
    static_argnames=("mesh", "K", "n_seg", "want_stats", "want_tables"),
)
def mesh_fused_step_segmented(
    mesh: Mesh,
    templates,  # [S, Tmax] int8 (replicated; one template per segment)
    tlens,  # [S] int32 (replicated)
    seg_ids,  # [Nglobal] int32, read-sharded (lane -> segment slot)
    seq,  # [Nglobal, L] int8, read-sharded
    match,
    mismatch,
    ins,
    dels,  # [Nglobal, L + 1]
    lengths,  # [Nglobal] int32, read-sharded
    bandwidths,  # [Nglobal] int32, read-sharded
    weights,  # [Nglobal] f32, read-sharded ({0,1} padding mask)
    K: int,
    n_seg: int,
    want_stats: bool = False,
    want_tables: bool = True,
):
    """ops.fused.fused_step_segmented over a read-sharded mesh: each
    device runs the segment-packed fused step on its local lane slice
    (the per-lane fills were already independent; packing changes
    nothing), and every SEGMENT-MASKED reduction finishes with a
    cross-shard collective — ``psum`` for the per-segment totals and
    dense edit tables, ``pmax`` for the edits-indicator union. Per-lane
    outputs (``scores``, ``n_errors``) keep their read sharding.

    The global lane count must divide the mesh size; pad with
    weight-0 lanes that DUPLICATE a real read of their assigned segment
    (the same padding convention as the single-device packer —
    ChunkExecutor.pack_seg). Same dict contract as the unsharded step.
    """

    from ..ops.fused import fused_step_segmented

    def local(tpl, tl, sg_l, sq_l, mt_l, mm_l, in_l, dl_l, ln_l, bw_l,
              w_l):
        out = fused_step_segmented(
            tpl, tl, sg_l, sq_l, mt_l, mm_l, in_l, dl_l, ln_l, bw_l,
            w_l, K, n_seg,
            want_stats=want_stats, want_tables=want_tables,
        )
        out = dict(
            out,
            total=jax.lax.psum(out["total"], READS_AXIS),
            sub=jax.lax.psum(out["sub"], READS_AXIS),
            ins=jax.lax.psum(out["ins"], READS_AXIS),
            **{"del": jax.lax.psum(out["del"], READS_AXIS)},
        )
        if want_stats:
            out["edits"] = jax.lax.pmax(out["edits"], READS_AXIS)
        return out

    rep = P()
    shard = P(READS_AXIS)
    out_specs = {
        "total": rep, "scores": shard,
        "sub": rep, "ins": rep, "del": rep,
    }
    if want_stats:
        out_specs.update({"n_errors": shard, "edits": rep})
    fn = _shard_map(
        local, mesh=mesh,
        in_specs=(rep, rep) + (shard,) * 9,
        out_specs=out_specs,
        # the collectives above establish the replication invariants;
        # see mesh_fused_step_pallas
        check_vma=False,
    )
    return fn(templates, tlens, seg_ids, seq, match, mismatch, ins,
              dels, lengths, bandwidths, weights)


def sharded_consensus_step(
    mesh: Mesh,
    template: np.ndarray,
    batch: ReadBatch,
    geom: BandGeometry,
    proposals_enc: Tuple[np.ndarray, np.ndarray, np.ndarray],
    weights: np.ndarray,
    K: int,
):
    """jit + shard one consensus step over the mesh's read axis.

    Returns (total_score, proposal_total_scores[P]) — both fully
    replicated after the XLA-inserted reductions.
    """
    ptype, ppos, pbase = proposals_enc
    rsh = NamedSharding(mesh, P(READS_AXIS))
    rep = NamedSharding(mesh, P())
    in_shardings = (
        rep,  # template
        rsh,  # seq
        rsh,  # match
        rsh,  # mismatch
        rsh,  # ins
        rsh,  # dels
        BandGeometry(rsh, rsh, rsh, rsh, rsh),  # per-read geometry scalars
        rsh,  # weights
        rep,  # ptype
        rep,  # ppos
        rep,  # pbase
    )
    step = jax.jit(
        _consensus_step,
        static_argnums=(11,),
        in_shardings=in_shardings,
        out_shardings=(rep, rep),
    )
    return step(
        jnp.asarray(template, jnp.int8),
        jnp.asarray(batch.seq),
        jnp.asarray(batch.match),
        jnp.asarray(batch.mismatch),
        jnp.asarray(batch.ins),
        jnp.asarray(batch.dels),
        geom,
        jnp.asarray(weights),
        jnp.asarray(ptype),
        jnp.asarray(ppos),
        jnp.asarray(pbase),
        K,
    )
