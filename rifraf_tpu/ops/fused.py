"""The fused consensus step: fills + all-edits rescoring in ONE dispatch.

One driver iteration's device work (the hill-climbing loop's inner step,
/root/reference/src/model.jl:679-719 realign + 385-456 candidate scoring)
as a single XLA program: batched banded forward and backward fills, then
the dense all-edits scorer over the fresh bands, then the weighted
read-axis reduction — device-resident inputs in, three small score tables
and a scalar out. Fusing eliminates per-call host<->device transfers,
which cost a fixed ~100 ms round trip EACH on the tunneled TPU
(BASELINE.md round 3; earlier sub-ms "fused step" numbers were async
measurement artifacts — the honest dependent-chain time at 1 kb x 256
reads is ~0.4 s, dominated by per-column kernel overheads).

The `optimization_barrier` between the fills and the dense sweep is
load-bearing: without it XLA fuses the dense scorer's band-wide consumers
into the column scans and the schedule collapses (measured ~4.6 s per
step vs the ~0.4 s honest baseline — ~11x slower; the original
"30,000x" figure was computed against the async-artifact sub-ms number).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from . import align_jax
from .proposal_dense import (
    _dense_batch,
    dense_tables_blocked,
    masked_weighted_sum,
)

# templates longer than this use the blocked dense sweep (memory-bound
# above it, see dense_tables_blocked)
DENSE_BLOCK_THRESHOLD = 2048

# Guard-flag bits (engine.integrity decodes these): NaN anywhere in the
# guarded values, +Inf (never legitimate — scores are log10 probabilities,
# padding is -Inf), and finite values below GUARD_UNDERFLOOR ("sentinel
# underflow": a log10 score can never legitimately reach this magnitude,
# so a finite value out here means accumulation drifted into the -Inf
# padding sentinel's range and comparisons/maxes are no longer
# trustworthy).
GUARD_NAN = 1
GUARD_POSINF = 2
GUARD_UNDERFLOW = 4
GUARD_UNDERFLOOR = -1e18


def _guard_flags(*arrays):
    """Per-read int32 guard bitmask over ``arrays`` whose leading axis is
    the read axis: GUARD_NAN | GUARD_POSINF | GUARD_UNDERFLOW reduced over
    every trailing axis. -Inf band padding is legal and flags nothing."""
    flags = None
    for x in arrays:
        axes = tuple(range(1, x.ndim))
        nan = jnp.any(jnp.isnan(x), axis=axes)
        pos = jnp.any(jnp.isposinf(x), axis=axes)
        under = jnp.any(
            jnp.isfinite(x) & (x < GUARD_UNDERFLOOR), axis=axes
        )
        f = (
            nan.astype(jnp.int32) * GUARD_NAN
            | pos.astype(jnp.int32) * GUARD_POSINF
            | under.astype(jnp.int32) * GUARD_UNDERFLOW
        )
        flags = f if flags is None else flags | f
    return flags


@jax.custom_batching.custom_vmap
def _fill_barrier(ab):
    return jax.lax.optimization_barrier(ab)


@_fill_barrier.def_vmap
def _fill_barrier_vmap(axis_size, in_batched, ab):
    # optimization_barrier is identity with no batching rule in this JAX
    # version; the barrier applies unchanged to the batched operands, so
    # a cluster-vmapped step (parallel.sweep_sharded) keeps the same
    # fill/dense scheduling fence as the unbatched one
    return jax.lax.optimization_barrier(ab), in_batched[0]


def _band_narrow(A, B, band_dtype):
    """Round the freshly filled band tables to the band-store dtype.
    ``bf16`` models (and on TPU realizes) a half-width HBM store of the
    forward/backward bands — exactly the Pallas kernels' bf16 band
    buffers; every consumer immediately widens back so all downstream
    accumulation stays in the working dtype. ``f32`` is the identity
    (bit-identical default)."""
    if band_dtype == "bf16":
        return A.astype(jnp.bfloat16), B.astype(jnp.bfloat16)
    return A, B


def _fused_parts(
    template, seq, match, mismatch, ins, dels, geom, weights, K,
    want_moves, want_stats, want_tables=True, want_edge=False,
    band_dtype="f32", want_guard=False,
):
    """The per-read-block device work: fills, dense tables, stats.

    Returns (A, B, moves_or_None, components) where components is a dict
    of read-reduced/per-read pieces combinable across read blocks.
    ``want_tables=False`` skips the dense all-edits sweep — the
    bandwidth-adaptation rounds only consume scores and traceback
    statistics, and the dense sweep is the single most expensive
    component of the step (round-4 profile). ``want_edge`` adds the
    per-read band-edge-hit counts (adaptive growth's frontier signal)
    to the components; requires ``want_stats``. ``want_guard`` adds a
    per-read guard bitmask over the fresh band tables and scores (the
    numerical sentinel reduction — a handful of lane-wise reductions on
    values already in registers, so the guarded step stays one launch)."""
    fwd_bwd = jax.vmap(
        align_jax._fwd_bwd_one,
        in_axes=(None, 0, 0, 0, 0, 0, 0, None, None),
    )
    need_moves = want_moves or want_stats
    A, moves, scores, B = fwd_bwd(
        template, seq, match, mismatch, ins, dels, geom, K, need_moves
    )
    wide = A.dtype
    A, B = _band_narrow(A, B, band_dtype)
    A, B = _fill_barrier((A, B))
    A, B = A.astype(wide), B.astype(wide)

    T1 = template.shape[0] + 1
    if not want_tables:
        sub_t = jnp.zeros((0, 4), A.dtype)
        ins_t = jnp.zeros((0, 4), A.dtype)
        del_t = jnp.zeros((0,), A.dtype)
    elif T1 > DENSE_BLOCK_THRESHOLD:
        # long templates: all-columns-at-once tiles exceed HBM; compute
        # the (already read-reduced) tables in sequential column blocks
        sub_t, ins_t, del_t = dense_tables_blocked(
            A, B, seq, match, mismatch, ins, dels, geom, weights
        )
    else:
        subs, insr, dele = _dense_batch(
            A, B, seq, match, mismatch, ins, dels, geom
        )
        sub_t = masked_weighted_sum(weights, subs)
        ins_t = masked_weighted_sum(weights, insr)
        del_t = masked_weighted_sum(weights, dele)

    comp = {
        "total": jnp.sum(jnp.where(weights > 0, scores, 0.0) * weights),
        "scores": scores,
        "sub": sub_t,
        "ins": ins_t,
        "del": del_t,
    }
    if want_guard:
        comp["guard"] = _guard_flags(A, B, scores[:, None])
    if want_stats:
        if want_edge:
            stats = jax.vmap(
                functools.partial(
                    align_jax._traceback_stats_one, want_edge=True
                ),
                in_axes=(0, 0, None, 0, None),
            )
            nerr, edits, ehits = stats(moves, seq, template, geom, K)
            comp["edge_hits"] = ehits
        else:
            stats = jax.vmap(
                align_jax._traceback_stats_one,
                in_axes=(0, 0, None, 0, None),
            )
            nerr, edits = stats(moves, seq, template, geom, K)
        comp["n_errors"] = nerr
        # union over reads; a zero-weight padding read duplicates a real
        # read so its contribution is a no-op for the union
        comp["edits"] = jnp.max(edits, axis=0)
    if not want_moves:
        moves = None
    return A, B, moves, comp


def _pack(comp, dtype, want_stats):
    parts = [comp["total"][None].astype(dtype), comp["scores"]]
    if want_stats:
        parts.append(comp["n_errors"].astype(dtype))
        parts.append(comp["edits"].reshape(-1).astype(dtype))
        if "edge_hits" in comp:
            parts.append(comp["edge_hits"].astype(dtype))
    parts += [
        comp["sub"].reshape(-1),
        comp["ins"].reshape(-1),
        comp["del"],
    ]
    if "guard" in comp:
        # guard rides LAST so every pre-guard offset stays byte-identical;
        # the extra trailing scalar guards the dense total itself
        total_flag = _guard_flags(comp["total"][None, None])
        parts.append(
            jnp.concatenate([comp["guard"], total_flag]).astype(dtype)
        )
    return jnp.concatenate(parts)


@functools.partial(
    jax.jit,
    static_argnames=("K", "want_moves", "want_stats", "read_chunk",
                     "want_tables", "want_edge", "band_dtype",
                     "want_guard"),
)
def fused_step_full(
    template, seq, match, mismatch, ins, dels, geom, weights, K,
    want_moves=False, want_stats=False, read_chunk=0, want_tables=True,
    want_edge=False, band_dtype="f32", want_guard=False,
):
    """One driver iteration's full device work in one dispatch.

    Returns (A [N, K, T1], B [N, K, T1], moves [N, K, T1] int8 or None,
    packed) where `packed` is ONE flat array carrying everything the host
    needs this iteration (see pack_layout): the weighted total score,
    per-read scores, per-read traceback error counts and the union
    edit-indicator table (want_stats), and the dense all-edit score
    tables. Every device->host transfer pays a fixed ~100 ms round trip
    on the tunneled TPU (BASELINE.md), so one packed fetch instead of
    five saves ~0.4 s per iteration.

    `moves` is only materialized as an output when want_moves (the SCORE
    stage's host traceback walk); bandwidth adaptation and alignment-
    derived proposals use the device statistics instead.

    `read_chunk` > 0 runs the read axis in sequential blocks of that size
    via lax.map (the read axis is padded to a multiple by repeating the
    last read at weight 0), bounding peak memory: the band buffers and
    band-layout tables are O(reads x K x T1) and at 10 kb x 512 reads the
    all-at-once working set exceeds HBM. Chunked calls return A = B = None
    (the dense tables make them unnecessary to the driver); moves is still
    a full [N, K, T1] output when requested.

    The score tables are summed over reads with weight masking (psum over
    a sharded read axis); table positions >= the true template length are
    garbage.
    """
    if not read_chunk or seq.shape[0] <= read_chunk:
        A, B, moves, comp = _fused_parts(
            template, seq, match, mismatch, ins, dels, geom, weights, K,
            want_moves, want_stats, want_tables, want_edge, band_dtype,
            want_guard,
        )
        return A, B, moves, _pack(comp, match.dtype, want_stats)

    N = seq.shape[0]
    # pad the read axis to a chunk multiple by repeating the last read at
    # weight 0 (repetition keeps band geometry identical, so no K change)
    n_chunks = -(-N // read_chunk)
    Np = n_chunks * read_chunk
    pad = Np - N

    def padded(a):
        if not pad:
            return a
        reps = jnp.repeat(a[-1:], pad, axis=0)
        return jnp.concatenate([a, reps])

    def blk(a):  # [N(+pad), ...] -> [n_chunks, chunk, ...]
        a = padded(a)
        return a.reshape((n_chunks, read_chunk) + a.shape[1:])

    w_padded = jnp.concatenate(
        [weights, jnp.zeros((pad,), weights.dtype)]
    ) if pad else weights
    xs = (
        blk(seq), blk(match), blk(mismatch), blk(ins), blk(dels),
        jax.tree_util.tree_map(blk, geom),
        w_padded.reshape((n_chunks, read_chunk)),
    )

    def body(x):
        seq_c, match_c, mismatch_c, ins_c, dels_c, geom_c, w_c = x
        _, _, moves_c, comp = _fused_parts(
            template, seq_c, match_c, mismatch_c, ins_c, dels_c, geom_c,
            w_c, K, want_moves, want_stats, want_tables, want_edge,
            band_dtype, want_guard,
        )
        if moves_c is None:
            moves_c = jnp.zeros((0,), jnp.int8)
        return moves_c, comp

    moves_b, comps = jax.lax.map(body, xs)
    comp = {
        "total": jnp.sum(comps["total"]),
        "scores": comps["scores"].reshape(Np)[:N],
        "sub": jnp.sum(comps["sub"], axis=0),
        "ins": jnp.sum(comps["ins"], axis=0),
        "del": jnp.sum(comps["del"], axis=0),
    }
    if want_stats:
        comp["n_errors"] = comps["n_errors"].reshape(Np)[:N]
        # padding rows duplicate a real read, so the per-chunk unions
        # already exclude nothing and add nothing
        comp["edits"] = jnp.max(comps["edits"], axis=0)
        if want_edge:
            comp["edge_hits"] = comps["edge_hits"].reshape(Np)[:N]
    if want_guard:
        comp["guard"] = comps["guard"].reshape(Np)[:N]
    moves = (
        moves_b.reshape((Np,) + moves_b.shape[2:])[:N] if want_moves else None
    )
    return None, None, moves, _pack(comp, match.dtype, want_stats)


def segment_weights(seg_ids, weights, n_seg: int):
    """Per-segment weight rows [S, N]: ``weights`` where the lane
    belongs to segment ``s``, exact zero elsewhere. The segment-reduce
    primitive every packed reduction builds on: a per-segment masked
    sum walks the SAME lane axis in the same order as the per-problem
    reduction, with exact zeros in foreign lanes — adding 0.0 is exact
    and order-preserving reductions keep the real summands' partial-sum
    structure, so per-segment results are bit-identical to per-problem
    runs (tests/test_lane_packing.py)."""
    return jnp.where(
        seg_ids[None, :] == jnp.arange(n_seg)[:, None],
        weights[None, :],
        jnp.zeros((), weights.dtype),
    )


def segment_masked_sum(seg_w, x):
    """Segment-reduce variant of ``masked_weighted_sum``: one weighted
    read-axis sum per segment row of ``seg_w`` [S, N] -> [S, ...]."""
    return jax.vmap(lambda w: masked_weighted_sum(w, x))(seg_w)


def segment_masked_sum_lanes(seg_w, x):
    """Lane-LAST segment reduce: ``x [..., N]`` summed over its last
    axis per segment row of ``seg_w [S, N]`` -> ``[S, ...]``. The
    Pallas epilogues keep the lane axis last (tile layout), so this is
    their variant of ``segment_masked_sum`` — same mask-before-multiply
    discipline, same in-order lane walk, so the single-segment case is
    bit-identical to the unsegmented ``sum(where(w > 0, x, 0) * w)``."""
    return jax.vmap(
        lambda w: jnp.sum(jnp.where(w > 0, x, jnp.zeros((), x.dtype)) * w,
                          axis=-1)
    )(seg_w)


def segment_union_max_lanes(seg_ids, x, n_seg: int):
    """Per-segment max-union over a lane-last axis: ``x [..., N]`` ->
    ``[S, ...]`` with foreign lanes replaced by exact zeros. The edits
    union has no weight mask — pad lanes must duplicate a read of their
    assigned segment slot (the packing convention), making their
    indicators a no-op in the union."""
    mask = seg_ids[None, :] == jnp.arange(n_seg)[:, None]
    return jax.vmap(
        lambda m: jnp.max(jnp.where(m, x, jnp.zeros((), x.dtype)), axis=-1)
    )(mask)


@functools.partial(
    jax.jit,
    static_argnames=("K", "n_seg", "want_stats", "want_tables",
                     "want_edge", "band_dtype", "want_guard"),
)
def fused_step_segmented(
    templates, tlens, seg_ids, seq, match, mismatch, ins, dels,
    lengths, bandwidths, weights, K, n_seg,
    want_stats=False, want_tables=True, want_edge=False,
    band_dtype="f32", want_guard=False,
):
    """The fused step for a SEGMENT-PACKED lane block: multiple
    independent problems share one ``[N]`` read block, identified by a
    per-lane problem id (``utils.shapes.pack_segments``), and every
    lane-axis reduction is segment-aware.

    ``templates [S, Tmax]`` / ``tlens [S]`` hold one template per
    segment slot; each lane scores against ITS segment's template
    (``templates[seg_ids]`` — the per-lane fills are already
    independent per read, so packing changes nothing there). Per-lane
    band frames come from ``BandGeometry.make`` with the gathered
    per-lane template length. Reductions run per segment with
    zero-masked foreign lanes (see ``segment_weights``): results are
    bit-identical to running each segment in its own block.

    Two callers build S-segment blocks: the lane packer (independent
    CLUSTERS sharing a block, ``utils.shapes.pack_segments``) and the
    speculative refine rounds (the SAME reads tiled against
    ``2 + speculate_k`` candidate templates, ``engine.device_loop``) —
    the segment mask does not care which axis varies, template or
    reads.

    Returns a dict: ``total [S]``, per-lane ``scores [N]``, dense
    tables ``sub/ins [S, T1, 4]``, ``del [S, T1]``; with ``want_stats``
    also per-lane ``n_errors [N]`` and the per-segment edits union
    ``edits [S, T1, 9]``. Pad lanes must carry weight 0 AND duplicate a
    read of their assigned segment slot (the edits union has no weight
    mask — a duplicate's indicators are a no-op, exactly the
    per-problem padding convention).

    Declines (raises) on templates long enough for the blocked dense
    sweep — ``dense_tables_blocked`` reduces internally at full lane
    width, so the packer routes those problems to whole-block
    execution instead.
    """
    from . import align_jax

    Tmax = templates.shape[1]
    T1 = Tmax + 1
    if want_tables and T1 > DENSE_BLOCK_THRESHOLD:
        raise NotImplementedError(
            "segment packing declines blocked-dense templates "
            f"(T1={T1} > {DENSE_BLOCK_THRESHOLD})"
        )
    t_lane = templates[seg_ids]  # [N, Tmax]
    geom = align_jax.BandGeometry.make(
        lengths, tlens[seg_ids], bandwidths
    )
    fwd_bwd = jax.vmap(
        align_jax._fwd_bwd_one,
        in_axes=(0, 0, 0, 0, 0, 0, 0, None, None),
    )
    A, moves, scores, B = fwd_bwd(
        t_lane, seq, match, mismatch, ins, dels, geom, K, want_stats
    )
    wide = A.dtype
    A, B = _band_narrow(A, B, band_dtype)
    A, B = _fill_barrier((A, B))
    A, B = A.astype(wide), B.astype(wide)

    seg_w = segment_weights(seg_ids, weights, n_seg)
    out = {
        "total": jax.vmap(
            lambda w: jnp.sum(jnp.where(w > 0, scores, 0.0) * w)
        )(seg_w),
        "scores": scores,
    }
    if want_guard:
        # per-LANE flags: the executor attributes a trip to a lane, then
        # maps the lane back to its segment/request host-side
        out["guard"] = _guard_flags(A, B, scores[:, None])
    if want_tables:
        subs, insr, dele = _dense_batch(
            A, B, seq, match, mismatch, ins, dels, geom
        )
        out["sub"] = segment_masked_sum(seg_w, subs)
        out["ins"] = segment_masked_sum(seg_w, insr)
        out["del"] = segment_masked_sum(seg_w, dele)
    else:
        out["sub"] = jnp.zeros((n_seg, 0, 4), A.dtype)
        out["ins"] = jnp.zeros((n_seg, 0, 4), A.dtype)
        out["del"] = jnp.zeros((n_seg, 0), A.dtype)
    if want_stats:
        if want_edge:
            stats = jax.vmap(
                functools.partial(
                    align_jax._traceback_stats_one, want_edge=True
                ),
                in_axes=(0, 0, 0, 0, None),
            )
            nerr, edits, ehits = stats(moves, seq, t_lane, geom, K)
            out["edge_hits"] = ehits
        else:
            stats = jax.vmap(
                align_jax._traceback_stats_one, in_axes=(0, 0, 0, 0, None)
            )
            nerr, edits = stats(moves, seq, t_lane, geom, K)
        out["n_errors"] = nerr
        mask = seg_ids[None, :] == jnp.arange(n_seg)[:, None]
        out["edits"] = jax.vmap(
            lambda m: jnp.max(
                jnp.where(m[:, None, None], edits, jnp.zeros((), edits.dtype)),
                axis=0,
            )
        )(mask)
    return out


def pack_layout(n_reads: int, T1: int, want_stats: bool,
                want_tables: bool = True, want_edge: bool = False,
                want_guard: bool = False):
    """Slice map of fused_step_full's packed array: name -> (start, stop).
    ``want_edge`` (valid only with ``want_stats``) inserts the per-read
    ``edge_hits`` section after ``edits`` — absent by default, so every
    existing layout stays byte-identical. ``want_guard`` appends the
    ``guard`` section (n_reads per-read flag words + 1 trailing
    dense-total flag) at the very END, so even a guarded layout leaves
    every pre-guard offset unchanged."""
    out = {}
    o = 0

    def take(name, size):
        nonlocal o
        out[name] = (o, o + size)
        o += size

    take("total", 1)
    take("scores", n_reads)
    if want_stats:
        take("n_errors", n_reads)
        take("edits", T1 * 9)
        if want_edge:
            take("edge_hits", n_reads)
    if want_tables:
        take("sub", T1 * 4)
        take("ins", T1 * 4)
        take("del", T1)
    if want_guard:
        take("guard", n_reads + 1)
    return out


def unpack_tables(packed, n_reads: int, T1: int, want_stats: bool = False):
    """Score-table view of the packed array (host- or trace-side):
    ``(total, sub [T1, 4], ins [T1, 4], del [T1])``, plus the union
    edit-indicator table ``edits [T1, 9]`` when ``want_stats``. The one
    consumer-side copy of the slicing every step engine shares
    (engine.realign's stage runners, parallel.sweep_sharded's per-bucket
    programs)."""
    lay = pack_layout(n_reads, T1, want_stats)
    sub = packed[slice(*lay["sub"])].reshape(T1, 4)
    insr = packed[slice(*lay["ins"])].reshape(T1, 4)
    dele = packed[slice(*lay["del"])]
    out = (packed[0], sub, insr, dele)
    if want_stats:
        out = out + (packed[slice(*lay["edits"])].reshape(T1, 9),)
    return out


def fused_step(template, seq, match, mismatch, ins, dels, geom, weights, K):
    """Score-table view of the fused step: (sub, ins, del, total)."""
    _, _, _, packed = fused_step_full(
        template, seq, match, mismatch, ins, dels, geom, weights, K
    )
    total, sub, insr, dele = unpack_tables(
        packed, seq.shape[0], template.shape[0] + 1
    )
    return sub, insr, dele, total
