"""The fused consensus step: fills + all-edits rescoring in ONE dispatch.

One driver iteration's device work (the hill-climbing loop's inner step,
/root/reference/src/model.jl:679-719 realign + 385-456 candidate scoring)
as a single XLA program: batched banded forward and backward fills, then
the dense all-edits scorer over the fresh bands, then the weighted
read-axis reduction — device-resident inputs in, three small score tables
and a scalar out. Fusing eliminates the per-call host->device transfers
and dispatch round trips that dominate the unfused chain (BASELINE.md:
~11 ms unfused vs ~0.15 ms fused at 1 kb x 256 reads on TPU v5e).

The `optimization_barrier` between the fills and the dense sweep is
load-bearing: without it XLA fuses the dense scorer's band-wide consumers
into the column scans and the schedule collapses (measured ~4.6 s per
step — 30,000x slower).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from . import align_jax
from .proposal_dense import _dense_batch


@functools.partial(
    jax.jit, static_argnames=("K", "want_moves", "want_stats")
)
def fused_step_full(
    template, seq, match, mismatch, ins, dels, geom, weights, K,
    want_moves=False, want_stats=False,
):
    """One driver iteration's full device work in one dispatch.

    Returns (A [N, K, T1], B [N, K, T1], moves [N, K, T1] int8 or None,
    packed) where `packed` is ONE flat array carrying everything the host
    needs this iteration (see pack_layout): the weighted total score,
    per-read scores, per-read traceback error counts and the union
    edit-indicator table (want_stats), and the dense all-edit score
    tables. On hardware where every device->host transfer pays a fixed
    latency (BASELINE.md), fetching one packed array instead of five is
    the difference between a ~100 ms and a ~500 ms iteration.

    `moves` is only materialized as an output when want_moves (the SCORE
    stage's host traceback walk); bandwidth adaptation and alignment-
    derived proposals use the device statistics instead.

    The score tables are summed over reads with weight masking (psum over
    a sharded read axis); table positions >= the true template length are
    garbage.
    """
    fwd = jax.vmap(
        align_jax._forward_one,
        in_axes=(None, 0, 0, 0, 0, 0, 0, None, None),
    )
    bwd = jax.vmap(
        align_jax._backward_one, in_axes=(None, 0, 0, 0, 0, 0, 0, None)
    )
    need_moves = want_moves or want_stats
    A, moves, scores = fwd(
        template, seq, match, mismatch, ins, dels, geom, K, need_moves
    )
    B, _ = bwd(template, seq, match, mismatch, ins, dels, geom, K)
    A, B = jax.lax.optimization_barrier((A, B))
    subs, insr, dele = _dense_batch(A, B, seq, match, mismatch, ins, dels, geom)

    def wsum(x):
        w = weights.reshape((-1,) + (1,) * (x.ndim - 1))
        # mask BEFORE multiplying: 0 * -inf must not poison the total
        return jnp.sum(jnp.where(w > 0, x, 0.0) * w, axis=0)

    total = jnp.sum(jnp.where(weights > 0, scores, 0.0) * weights)
    dtype = scores.dtype
    parts = [total[None], scores]
    if want_stats:
        stats = jax.vmap(
            align_jax._traceback_stats_one, in_axes=(0, 0, None, 0, None)
        )
        nerr, edits = stats(moves, seq, template, geom, K)
        parts.append(nerr.astype(dtype))
        # union over reads; a zero-weight padding read duplicates a real
        # read so its contribution is a no-op for the union
        edits_any = jnp.max(edits, axis=0)
        parts.append(edits_any.reshape(-1).astype(dtype))
    parts += [
        wsum(subs).reshape(-1),
        wsum(insr).reshape(-1),
        wsum(dele),
    ]
    packed = jnp.concatenate(parts)
    if not want_moves:
        moves = None
    return A, B, moves, packed


def pack_layout(n_reads: int, T1: int, want_stats: bool):
    """Slice map of fused_step_full's packed array: name -> (start, stop)."""
    out = {}
    o = 0

    def take(name, size):
        nonlocal o
        out[name] = (o, o + size)
        o += size

    take("total", 1)
    take("scores", n_reads)
    if want_stats:
        take("n_errors", n_reads)
        take("edits", T1 * 9)
    take("sub", T1 * 4)
    take("ins", T1 * 4)
    take("del", T1)
    return out


def fused_step(template, seq, match, mismatch, ins, dels, geom, weights, K):
    """Score-table view of the fused step: (sub, ins, del, total)."""
    _, _, _, packed = fused_step_full(
        template, seq, match, mismatch, ins, dels, geom, weights, K
    )
    N = seq.shape[0]
    T1 = template.shape[0] + 1
    lay = pack_layout(N, T1, False)
    sub = packed[slice(*lay["sub"])].reshape(T1, 4)
    insr = packed[slice(*lay["ins"])].reshape(T1, 4)
    dele = packed[slice(*lay["del"])]
    return sub, insr, dele, packed[0]
