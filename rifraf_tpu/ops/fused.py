"""The fused consensus step: fills + all-edits rescoring in ONE dispatch.

One driver iteration's device work (the hill-climbing loop's inner step,
/root/reference/src/model.jl:679-719 realign + 385-456 candidate scoring)
as a single XLA program: batched banded forward and backward fills, then
the dense all-edits scorer over the fresh bands, then the weighted
read-axis reduction — device-resident inputs in, three small score tables
and a scalar out. Fusing eliminates the per-call host->device transfers
and dispatch round trips that dominate the unfused chain (BASELINE.md:
~11 ms unfused vs ~0.15 ms fused at 1 kb x 256 reads on TPU v5e).

The `optimization_barrier` between the fills and the dense sweep is
load-bearing: without it XLA fuses the dense scorer's band-wide consumers
into the column scans and the schedule collapses (measured ~4.6 s per
step — 30,000x slower).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from . import align_jax
from .proposal_dense import _dense_batch


@functools.partial(jax.jit, static_argnames=("K",))
def fused_step(template, seq, match, mismatch, ins, dels, geom, weights, K):
    """Forward + backward fills and dense all-edit score tables.

    Returns (sub [T1, 4], ins [T1, 4], del [T1], total_score) — tables
    summed over reads with weight masking (psum over a sharded read axis);
    positions >= the true template length are garbage.
    """
    fwd = jax.vmap(
        align_jax._forward_one, in_axes=(None, 0, 0, 0, 0, 0, 0, None)
    )
    bwd = jax.vmap(
        align_jax._backward_one, in_axes=(None, 0, 0, 0, 0, 0, 0, None)
    )
    A, _, scores = fwd(template, seq, match, mismatch, ins, dels, geom, K)
    B, _ = bwd(template, seq, match, mismatch, ins, dels, geom, K)
    A, B = jax.lax.optimization_barrier((A, B))
    subs, insr, dele = _dense_batch(A, B, seq, match, mismatch, ins, dels, geom)

    def wsum(x):
        w = weights.reshape((-1,) + (1,) * (x.ndim - 1))
        # mask BEFORE multiplying: 0 * -inf must not poison the total
        return jnp.sum(jnp.where(w > 0, x, 0.0) * w, axis=0)

    total = jnp.sum(jnp.where(weights > 0, scores, 0.0) * weights)
    return wsum(subs), wsum(insr), wsum(dele), total
