"""On-core Pallas fill: the banded DP column sweep as ONE kernel.

Second-generation Pallas engine for the reference's hot inner loop
(/root/reference/src/align.jl:50-179). The XLA scan path (align_jax)
pays per-column kernel-launch overhead — ~75 ms for a merged
forward+backward fill at 1 kb x 256 reads where the arithmetic is
worth ~1 ms (round-4 profile) — and the first-generation kernel
(exp/align_pallas_gen1.py) iterated ONE column per sequential grid step, losing to
that same overhead ~100x. This kernel keeps the whole column sweep
on-core:

- **Uniform band frame.** The first-generation kernel placed each
  read's band at its own diagonal offset, so score tables had to be
  pre-shifted per read on the host (the gen-1 kernel's _prep_tables) and
  re-uploaded every call. Here every read shares ONE frame: data row d
  of column j holds cell ``i = d + j - OFF`` with a single batch-wide
  ``OFF = max_k(offset_k)``; each read keeps its own band LIMITS as a
  lane mask (``delta_k <= d < delta_k + nd_k``). In-band cells get
  identical values to the per-read frame (the recurrence only relates
  same/adjacent data rows, and out-of-band neighbors are -inf in both
  frames) — pinned by the oracle tests. Table windows become
  read-independent: column j reads buffer rows [j, j+K) for EVERY
  lane, so the buffers are just the batch score tables transposed
  (reads on lanes), built on device with one dynamic_update_slice —
  no host prep, no per-read shifts, no gathers anywhere.
  The frame's band-buffer height ``K = max_k(delta_k + nd_k)`` equals
  the per-read frame's ``max_k(nd_k)`` when reads share a bandwidth
  and their length spread stays within the bandwidth (the common
  case; uniform_band_height computes the exact value either way).

- **Reads on lanes, C columns per grid step.** A [K, 128] tile holds
  one band column for 128 reads; the DP carry lives in a VMEM scratch
  that persists across the sequentially-iterated column-block axis.
  Each grid step processes C columns as straight-line code on tiles
  resident in VMEM: per column, one static [c, c+K) window of each
  pre-blocked table (block rows are buffer rows [jb*C, jb*C + C + K)),
  the match/delete candidate maxes, and the within-column insert chain
  in the same max-plus closed form as the XLA path
  (``F = G + cummax(cand - G)``, computed along sublanes with
  log-step rolls).

- **Forward and backward in one launch.** The backward band is the
  forward DP of the reversed problem with IDENTICAL band geometry
  (align.jl:196-202), so the reversed-read lanes ride as extra lane
  blocks in the same grid; a per-block index map picks the reversed
  template for them. The reversed-problem output is flipped back to
  backward-band layout by the XLA helper `flip_reversed_uniform`.

- **Optional in-kernel move recording** (want_moves): the kernel emits
  the per-cell traceback codes alongside the fill, so bandwidth
  adaptation, alignment-derived proposals (device stats over the move
  band), and SCORE-stage host tracebacks all ride the on-core engine.

- **Panel chaining** (col0/carry_in/carry_out): a launch may cover only
  a panel of template columns, chaining the DP carry and score
  accumulator from the previous panel — the long-template mode
  (ops.dense_pallas.fused_tables_pallas_panels) that keeps 30 kb+
  working sets inside HBM.

Batches whose uniform-frame K would blow up (pathological read-length
spread) stay on the XLA path — see engine.realign._pallas_mode for the
policy.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# pallas renamed TPUCompilerParams -> CompilerParams across jax releases;
# accept either so the kernel builds on both sides of the rename.
_CompilerParams = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams

from .align_jax import BandGeometry
from .align_np import (
    TRACE_DELETE,
    TRACE_INSERT,
    TRACE_MATCH,
    TRACE_NONE,
)
from .encoding import (
    check_input_enc,
    dequant_block,
    pack_codes_blocked,
    quantize_rows,
    unpack_codes,
)
from ..utils.shapes import plan_cols

# finite sentinel: avoids -inf arithmetic on the VPU (inf - inf = nan in
# the chain's cand - G); half of float32 min keeps all sums finite
NEG_INF = float(np.finfo(np.float32).min) / 2
# liveness threshold for move recording: real DP values are bounded by
# ~#cells * min-score (~1e6 magnitude), unreachable cells sit near NEG_INF
NEG_LIVE = NEG_INF / 2

LANES = 128


def neg_inf_for(dtype) -> float:
    """Finite -inf sentinel that is SUM-SAFE in the given band-store
    dtype: half the dtype's most-negative finite value, so adding two
    sentinels (the first accumulation a consumer might do in the narrow
    dtype) lands exactly on the dtype's finite minimum instead of
    silently overflowing to -inf. float32 returns the historical
    NEG_INF constant bit-for-bit (f32.min / 2), keeping the default
    path's values unchanged; bfloat16 — whose exponent range matches
    f32 but whose finite max (~3.39e38) sits BELOW 2 * |NEG_INF| —
    gets bf16.min / 2 (~-1.69e38), which still sits far below the
    NEG_LIVE liveness threshold so move masking is unaffected."""
    dt = jnp.dtype(dtype)
    if dt == jnp.dtype(jnp.float32):
        return NEG_INF
    return float(jnp.finfo(dt).min) / 2


def band_store_dtype(band_dtype: str):
    """Map a Params.band_dtype string to the jnp dtype of the band
    tables' HBM store ("f32" -> float32, "bf16" -> bfloat16). All
    accumulation stays float32 regardless (cast at load, accumulate
    wide); this dtype governs only what is written to / read from the
    band buffers."""
    if band_dtype == "bf16":
        return jnp.bfloat16
    if band_dtype == "f32":
        return jnp.float32
    raise ValueError(
        f"band_dtype must be 'f32' or 'bf16', got {band_dtype!r}"
    )


def uniform_frame(geom: BandGeometry):
    """(OFF, delta, nd) of the shared band frame (dynamic scalars)."""
    OFF = jnp.max(geom.offset)
    delta = OFF - geom.offset
    return OFF, delta, geom.nd


def uniform_geometry(geom: BandGeometry, lengths=None,
                     off_override=None) -> BandGeometry:
    """A BandGeometry whose frame matches the uniform band layout: every
    read gets ``offset = OFF`` (so ``d = i - j + OFF``) and a doctored
    bandwidth such that the derived traceback end row
    ``max(slen - tlen, 0) + bandwidth`` equals the uniform frame's
    ``dend = slen - tlen + OFF``. Consumers of the Pallas move band
    (align_jax._traceback_stats_one / traceback_batch) then work
    unchanged. ``lengths`` overrides geom.slen (lane-padded batches);
    ``off_override`` pins OFF (sharded meshes use the global maximum so
    every shard shares one frame)."""
    slen = geom.slen if lengths is None else jnp.asarray(lengths, jnp.int32)
    OFF = jnp.max(geom.offset) if off_override is None else (
        jnp.asarray(off_override, jnp.int32)
    )
    tlen = jnp.broadcast_to(geom.tlen.reshape(-1)[0], slen.shape)
    offset = jnp.broadcast_to(OFF, slen.shape)
    bw = OFF - jnp.maximum(tlen - slen, 0)
    nd = (OFF - geom.offset) + geom.nd
    nd = jnp.broadcast_to(jnp.max(nd), slen.shape)
    return BandGeometry(slen, tlen, bw, offset, nd)


def uniform_band_height(geom_host_offsets, geom_host_nd, mult: int = 8) -> int:
    """Static band-buffer height of the uniform frame: max(delta + nd),
    rounded up to `mult` (f32 sublane tiling)."""
    off = np.asarray(geom_host_offsets)
    nd = np.asarray(geom_host_nd)
    K = int((off.max() - off + nd).max())
    return ((K + mult - 1) // mult) * mult


def _cumop(x, op, K: int):
    """Inclusive scan along sublanes (axis 0) via log-step doubling."""
    s = 1
    while s < K:
        shifted = pltpu.roll(x, s, axis=0)
        idx = jax.lax.broadcasted_iota(jnp.int32, x.shape, 0)
        x = jnp.where(idx >= s, op(x, shifted), x)
        s *= 2
    return x


def _fill_kernel(
    # SMEM inputs
    tlen_ref,  # [1, 1] true template length
    off_ref,  # [1, 1] uniform frame offset OFF
    col0_ref,  # [1, 1] global column of this launch's first column
    t_ref,  # [n_tpl, n_cols] template codes per stream (LOCAL columns)
    # per-lane metadata, [1, 1, 128] blocks
    slen_ref,
    delta_ref,
    ndv_ref,
    dend_ref,
    # pre-blocked tables, [1, CB, 128] blocks (buffer rows [jb*C, jb*C+CB));
    # packed encoding: int8 planes + [1, CBp, 128] packed code words
    mt_ref,
    mm_ref,
    gi_ref,
    dl_ref,
    sq_ref,
    # with input_enc == "packed": qm_ref [8, 1, 128] dequant rows
    # with has_carry: carry_in [K, 128] and score_in [1, 128] inputs
    # (the previous panel's final column / score accumulator)
    # outputs: out_ref [C * K, 128] band columns, score_ref [1, 128]
    # final scores (last step), then mv_ref [C * K, 128] int32 move codes
    # when want_moves, then carry_out [K, 128] when has_carry; scratch:
    # carry [K, 128] previous column, acc_score [1, 128]
    *refs,
    K: int,
    C: int,
    blocks_per_tpl: int,
    want_moves: bool = False,
    has_carry: bool = False,
    band_neg: float = NEG_INF,
    input_enc: str = "f32",
):
    refs = list(refs)
    qm_ref = refs.pop(0) if input_enc == "packed" else None
    carry_in = score_in = None
    if has_carry:
        carry_in = refs.pop(0)
        score_in = refs.pop(0)
    out_ref = refs.pop(0)
    score_ref = refs.pop(0)
    mv_ref = refs.pop(0) if want_moves else None
    carry_out = refs.pop(0) if has_carry else None
    carry, acc_score = refs
    jb = pl.program_id(1)
    stream = pl.program_id(0) // blocks_per_tpl
    tlen = tlen_ref[0, 0]
    OFF = off_ref[0, 0]
    col0 = col0_ref[0, 0]

    slen = slen_ref[0, 0, :]
    delta = delta_ref[0, 0, :]
    nd = ndv_ref[0, 0, :]
    d = jax.lax.broadcasted_iota(jnp.int32, (K, LANES), 0)
    # band_neg == NEG_INF on the f32 path (bit-identical); a narrower
    # band store uses its own sum-safe sentinel (neg_inf_for) so the
    # stored value survives the downcast without overflowing to -inf
    neg = jnp.full((K, LANES), band_neg, jnp.float32)
    in_lane_band = (d >= delta[None, :]) & (d < (delta + nd)[None, :])

    @pl.when(jb == 0)
    def _():
        if has_carry:
            carry[:] = carry_in[:]
            acc_score[:] = score_in[:]
        else:
            acc_score[:] = jnp.full((1, LANES), NEG_INF, jnp.float32)

    if input_enc == "packed":
        # decode the whole block ONCE per grid step, then window the
        # decoded f32/int32 arrays: 2-bit code unpack (16 shift/mask
        # ops) + per-plane affine int8 dequant against the per-lane
        # qmeta rows. Every max-plus candidate below accumulates in f32
        # exactly like the f32 path — only the HBM->VMEM bytes shrank.
        mt_t = dequant_block(mt_ref[0], qm_ref[0, 0, :], qm_ref[4, 0, :])
        mm_t = dequant_block(mm_ref[0], qm_ref[1, 0, :], qm_ref[5, 0, :])
        gi_t = dequant_block(gi_ref[0], qm_ref[2, 0, :], qm_ref[6, 0, :])
        dl_t = dequant_block(dl_ref[0], qm_ref[3, 0, :], qm_ref[7, 0, :])
        sq_t = unpack_codes(sq_ref[0])

    prev = carry[:]
    for c in range(C):
        j = col0 + jb * C + c
        i = d + (j - OFF)
        valid = (i >= 0) & (i <= slen[None, :]) & in_lane_band & (j <= tlen)

        if input_enc == "packed":
            # static windows of the decoded block; pad rows decode as
            # garbage codes mod 4 but only feed masked-out cells
            mw = mt_t[c : c + K, :]
            mmw = mm_t[c : c + K, :]
            giw = gi_t[c : c + K, :]
            dlw = dl_t[c : c + K, :]
            sqw = sq_t[c : c + K, :]
        else:
            # static windows of the pre-blocked tables: column j = block
            # row c (zero casts: the f32 default stays bit-identical)
            mw = mt_ref[0, c : c + K, :]
            mmw = mm_ref[0, c : c + K, :]
            giw = gi_ref[0, c : c + K, :]
            dlw = dl_ref[0, c : c + K, :]
            sqw = sq_ref[0, c : c + K, :]

        # template base of column j (junk at j == 0); t_ref holds only
        # this launch's columns, so index locally
        tb = t_ref[stream, jb * C + c]

        # j == 0: only cell (0, 0) seeds the recurrence
        first = j == 0
        msc = jnp.where(sqw == tb, mw, mmw)
        mcand = jnp.where((i >= 1) & jnp.logical_not(first), prev + msc, neg)
        prev_up = pltpu.roll(prev, K - 1, axis=0)  # prev_up[d] = prev[d+1]
        prev_up = jnp.where(d == K - 1, neg, prev_up)
        dcand = jnp.where(first, neg, prev_up + dlw)
        cand = jnp.maximum(mcand, dcand)
        cand = jnp.where(first & (i == 0), 0.0, cand)
        cand = jnp.where(valid, cand, neg)

        # within-column insert chain F[d] = max(cand[d], F[d-1] + g[d]):
        # max-plus closed form F = G + cummax(cand - G), G = cumsum(g);
        # valid because a column's in-band rows are contiguous in d
        g = jnp.where((i >= 1) & valid, giw, 0.0)
        G = _cumop(g, lambda a, b: a + b, K)
        F = G + _cumop(cand - G, jnp.maximum, K)
        F = jnp.where(valid, F, neg)

        if want_moves:
            # move codes from the same candidates the fill used, with the
            # reference tie-break priority match > insert > delete
            # (align.jl:78-86; identical to align_jax._scan_fill's argmax
            # over [mcand, icand, dcand]). Finite-sentinel note: when both
            # mcand and dcand derive from out-of-band predecessors their
            # NEG-offset values differ from the XLA path's -inf ties, but
            # every such divergence is confined to cells whose F stays
            # near NEG_INF — masked to TRACE_NONE by the liveness test in
            # both engines (see tests/test_fill_dense_pallas.py moves
            # equality).
            icand = pltpu.roll(F, 1, axis=0)
            icand = jnp.where(d == 0, neg, icand) + g
            mv = jnp.where(
                (mcand >= icand) & (mcand >= dcand),
                TRACE_MATCH,
                jnp.where(icand >= dcand, TRACE_INSERT, TRACE_DELETE),
            )
            live = valid & (F > NEG_LIVE)
            mv = jnp.where(
                first,
                jnp.where((i > 0) & live, TRACE_INSERT, TRACE_NONE),
                jnp.where(live, mv, TRACE_NONE),
            )

            # only the forward stream's moves are ever consumed; skipping
            # the reversed lanes halves the move-band write traffic (the
            # rev half of the output stays uninitialized garbage)
            @pl.when(stream == 0)
            def _():
                mv_ref[c * K : (c + 1) * K, :] = mv.astype(jnp.int32)

        prev = F
        # store-narrow: a bf16 out_ref takes the cast here; the f32 DP
        # carry (prev) and the score accumulator never narrow
        out_ref[c * K : (c + 1) * K, :] = F.astype(out_ref.dtype)

        @pl.when(j == tlen)
        def _():
            dend = dend_ref[0, 0, :]
            sel = jnp.where(d == dend[None, :], F, NEG_INF)
            acc_score[:] = jnp.max(sel, axis=0, keepdims=True)

    carry[:] = prev

    @pl.when(jb == pl.num_programs(1) - 1)
    def _():
        score_ref[:] = acc_score[:]
        if has_carry:
            carry_out[:] = prev


@functools.partial(
    jax.jit,
    static_argnames=("K", "T1p", "NBLK", "C", "want_moves", "interpret",
                     "band_dtype", "input_enc"),
)
def _fill_call(
    tlen_s,  # [1, 1] int32
    off_s,  # [1, 1] int32
    t_cols,  # [n_tpl, T1p] int32; row b//NB_per_tpl... (see index map)
    meta,  # [4, 1, Npad] int32: slen, delta, nd, dend
    mt, mm, gi, dl, sq,  # [NSTEPS, CB, Npad] pre-blocked tables
    K: int,
    T1p: int,
    NBLK: int,
    C: int,
    want_moves: bool = False,
    interpret: bool = False,
    col0=None,  # [1, 1] int32 global first column (panel launches)
    carry_in=None,  # [K, NBLK*128] previous panel's final column
    score_in=None,  # [1, NBLK*128] previous panel's score accumulator
    band_dtype: str = "f32",
    input_enc: str = "f32",
    qmeta=None,  # [8, 1, NBLK*128] f32 dequant rows (packed enc only)
):
    n_steps = T1p // C
    CB = mt.shape[1]
    n_tpl = t_cols.shape[0]
    blocks_per_tpl = NBLK // n_tpl
    has_carry = carry_in is not None
    band_dt = band_store_dtype(band_dtype)
    if col0 is None:
        col0 = jnp.zeros((1, 1), jnp.int32)

    grid = (NBLK, n_steps)

    def tab_spec(rows=CB):
        return pl.BlockSpec(
            (1, rows, LANES), lambda nb, jb: (jb, 0, nb),
            memory_space=pltpu.VMEM,
        )

    def lane_spec():
        return pl.BlockSpec(
            (1, 1, LANES), lambda nb, jb: (0, 0, nb),
            memory_space=pltpu.VMEM,
        )

    kernel = functools.partial(
        _fill_kernel, K=K, C=C, blocks_per_tpl=blocks_per_tpl,
        want_moves=want_moves, has_carry=has_carry,
        band_neg=neg_inf_for(band_dt), input_enc=input_enc,
    )

    out_specs = [
        pl.BlockSpec(
            (C * K, LANES), lambda nb, jb: (jb, nb),
            memory_space=pltpu.VMEM,
        ),
        pl.BlockSpec(
            (1, LANES), lambda nb, jb: (0, nb), memory_space=pltpu.VMEM
        ),
    ]
    out_shape = [
        jax.ShapeDtypeStruct((n_steps * C * K, NBLK * LANES), band_dt),
        jax.ShapeDtypeStruct((1, NBLK * LANES), jnp.float32),
    ]
    if want_moves:
        out_specs.append(
            pl.BlockSpec(
                (C * K, LANES), lambda nb, jb: (jb, nb),
                memory_space=pltpu.VMEM,
            )
        )
        out_shape.append(
            jax.ShapeDtypeStruct((n_steps * C * K, NBLK * LANES), jnp.int32)
        )
    if has_carry:
        out_specs.append(
            pl.BlockSpec(
                (K, LANES), lambda nb, jb: (0, nb), memory_space=pltpu.VMEM
            )
        )
        out_shape.append(
            jax.ShapeDtypeStruct((K, NBLK * LANES), jnp.float32)
        )

    in_specs = [
        pl.BlockSpec((1, 1), lambda nb, jb: (0, 0), memory_space=pltpu.SMEM),
        pl.BlockSpec((1, 1), lambda nb, jb: (0, 0), memory_space=pltpu.SMEM),
        pl.BlockSpec((1, 1), lambda nb, jb: (0, 0), memory_space=pltpu.SMEM),
        # whole template table (TPU SMEM blocks must span the trailing
        # dims); the kernel indexes [stream, column] dynamically
        pl.BlockSpec(
            (n_tpl, t_cols.shape[1]), lambda nb, jb: (0, 0),
            memory_space=pltpu.SMEM,
        ),
        lane_spec(),  # slen
        lane_spec(),  # delta
        lane_spec(),  # nd
        lane_spec(),  # dend
        tab_spec(),  # mt
        tab_spec(),  # mm
        tab_spec(),  # gi
        tab_spec(),  # dl
        tab_spec(rows=sq.shape[1]),  # sq (CBp packed words, CB codes f32)
    ]
    args = [
        tlen_s, off_s, jnp.asarray(col0, jnp.int32).reshape(1, 1), t_cols,
        meta[0][None], meta[1][None], meta[2][None], meta[3][None],
        mt, mm, gi, dl, sq,
    ]
    if input_enc == "packed":
        in_specs.append(
            pl.BlockSpec(
                (8, 1, LANES), lambda nb, jb: (0, 0, nb),
                memory_space=pltpu.VMEM,
            )
        )
        args.append(qmeta)
    if has_carry:
        in_specs.append(
            pl.BlockSpec(
                (K, LANES), lambda nb, jb: (0, nb), memory_space=pltpu.VMEM
            )
        )
        in_specs.append(
            pl.BlockSpec(
                (1, LANES), lambda nb, jb: (0, nb), memory_space=pltpu.VMEM
            )
        )
        args += [carry_in, score_in]

    outs = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((K, LANES), jnp.float32),
            pltpu.VMEM((1, LANES), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(*args)
    outs = list(outs)
    out_band = outs.pop(0)
    scores = outs.pop(0)
    # moves stay RAW int32: the Pallas stats kernel consumes them in
    # this exact layout/dtype (no int8 round trip); exporting callers
    # (fill_uniform) cast at the boundary instead
    moves = outs.pop(0) if want_moves else None
    if has_carry:
        carry_out = outs.pop(0)
        return out_band, scores, moves, carry_out
    return out_band, scores, moves


def _block_tables(buf, n_steps: int, C: int, CB: int):
    """[Lbuf, Npad] -> [n_steps, CB, Npad]: block jb holds buffer rows
    [jb*C, jb*C + CB) (the halo'd window its C columns read)."""
    return jnp.stack(
        [jax.lax.dynamic_slice_in_dim(buf, jb * C, CB, axis=0)
         for jb in range(n_steps)]
    )


def _reverse_rows(a, lengths):
    """Reverse each row's true-length prefix (tail padding stays)."""
    L = a.shape[1]
    k = jnp.arange(L)
    idx = jnp.where(k[None, :] < lengths[:, None],
                    lengths[:, None] - 1 - k[None, :], k[None, :])
    return jnp.take_along_axis(a, idx, axis=1)


def _reverse_rows1(a, lengths):
    """Like _reverse_rows for the length-(L+1) dels tables."""
    L1 = a.shape[1]
    k = jnp.arange(L1)
    idx = jnp.where(k[None, :] <= lengths[:, None],
                    lengths[:, None] - k[None, :], k[None, :])
    return jnp.take_along_axis(a, idx, axis=1)


class FillBuffers(NamedTuple):
    """Device-resident, template-independent fill inputs: the transposed
    (+reversed, for the backward stream) score tables and lane metadata
    minus frame placement. Built once per batch selection
    (engine.realign caches this; only the template changes per call).

    With ``input_enc="packed"`` (build_fill_buffers) the four score
    planes are stored int8 (per-read affine quantization, fwd and rev
    sharing one scale/offset because quantization happens before the
    reversal) and ``qmeta`` carries the [8, Npad] f32 dequantization
    table: rows 0-3 the match/mismatch/ins/dels scales, rows 4-7 the
    offsets. ``seq_T`` stays int32 either way — the 2-bit base packing
    happens after halo blocking (prepare_fill), and the XLA stats
    fallback reads the unpacked codes. The default f32 encoding leaves
    ``qmeta`` None and every dtype exactly as before."""

    seq_T: jnp.ndarray  # [L, Npad] int32, fwd lanes
    match_T: jnp.ndarray
    mismatch_T: jnp.ndarray
    ins_T: jnp.ndarray
    dels_T: jnp.ndarray  # [L + 1, Npad]
    rseq_T: jnp.ndarray  # reversed-read lanes
    rmatch_T: jnp.ndarray
    rmismatch_T: jnp.ndarray
    rins_T: jnp.ndarray
    rdels_T: jnp.ndarray
    lengths: jnp.ndarray  # [Npad] int32 (0 for padding lanes)
    qmeta: Optional[jnp.ndarray] = None  # [8, Npad] f32, packed enc only


def _pad_lanes(a, Npad: int, fill=0.0):
    n = a.shape[0]
    if n == Npad:
        return a
    pad = jnp.full((Npad - n,) + a.shape[1:], fill, a.dtype)
    return jnp.concatenate([a, pad], axis=0)


@functools.partial(jax.jit, static_argnames=("Npad", "input_enc"))
def build_fill_buffers(seq, match, mismatch, ins, dels, lengths,
                       Npad: int, input_enc: str = "f32") -> FillBuffers:
    """Transpose the batch tables to lanes-last and precompute the
    reversed-read variants (template-independent; cache per batch).

    ``input_enc="packed"`` additionally quantizes the four score planes
    to int8 against a per-read scale/offset pair (ops.encoding) BEFORE
    building the reversed variants, so the forward and reversed streams
    of a read dequantize against the same pair; ``qmeta`` carries the
    dequantization table. The base codes are left int32 here — the 2-bit
    packing is applied to the halo-blocked tables in prepare_fill, where
    the block layout the kernels unpack is known."""
    check_input_enc(input_enc)
    f32 = jnp.float32
    sq = _pad_lanes(seq.astype(jnp.int32), Npad, -9)
    mt = _pad_lanes(match.astype(f32), Npad)
    mm = _pad_lanes(mismatch.astype(f32), Npad)
    gi = _pad_lanes(ins.astype(f32), Npad)
    dl = _pad_lanes(dels.astype(f32), Npad)
    ln = _pad_lanes(lengths.astype(jnp.int32), Npad)
    qmeta = None
    if input_enc == "packed":
        pos = jnp.arange(mt.shape[1], dtype=jnp.int32)
        m_mask = pos[None, :] < ln[:, None]
        d_mask = (
            jnp.arange(dl.shape[1], dtype=jnp.int32)[None, :]
            <= ln[:, None]
        )
        mt, s_mt, o_mt = quantize_rows(mt, m_mask)
        mm, s_mm, o_mm = quantize_rows(mm, m_mask)
        gi, s_gi, o_gi = quantize_rows(gi, m_mask)
        dl, s_dl, o_dl = quantize_rows(dl, d_mask)
        qmeta = jnp.stack(
            [s_mt, s_mm, s_gi, s_dl, o_mt, o_mm, o_gi, o_dl]
        )
    return FillBuffers(
        seq_T=sq.T, match_T=mt.T, mismatch_T=mm.T, ins_T=gi.T, dels_T=dl.T,
        rseq_T=_reverse_rows(sq, ln).T,
        rmatch_T=_reverse_rows(mt, ln).T,
        rmismatch_T=_reverse_rows(mm, ln).T,
        rins_T=_reverse_rows(gi, ln).T,
        rdels_T=_reverse_rows1(dl, ln).T,
        lengths=ln,
        qmeta=qmeta,
    )


def prepare_fill(
    template,  # int8 [Tmax] padded template
    tlen,  # int32 true length
    bufs: FillBuffers,
    geom: BandGeometry,
    K: int,
    T1p: int,
    C: int,
    with_backward: bool = True,
    off_override=None,
    input_enc: str = "f32",
):
    """Build every _fill_call input: frame scalars, per-lane metadata,
    template column tables, and the halo-blocked score tables for the
    forward (and optionally reversed) stream. Returns a dict; the
    forward-stream blocked tables ride along for reuse by the dense
    kernel (ops.dense_pallas), which consumes the identical layout.
    ``off_override`` pins the frame offset OFF (sharded meshes pass the
    global maximum so all shards share one frame). ``input_enc="packed"``
    (bufs built with the same flag) 2-bit packs the blocked base-code
    tables (ops.encoding.pack_codes_blocked — the score planes arrive
    already int8 from build_fill_buffers) and adds the [8, 1, lanes]
    ``qmeta`` dequantization rows the kernels consume."""
    check_input_enc(input_enc)
    Npad = bufs.seq_T.shape[1]
    n_steps = T1p // C
    CB = C + K

    tlen = jnp.asarray(tlen, jnp.int32)
    OFF = (
        jnp.max(geom.offset).astype(jnp.int32) if off_override is None
        else jnp.asarray(off_override, jnp.int32)
    )
    delta = _pad_lanes((OFF - geom.offset).astype(jnp.int32), Npad)
    ndv = _pad_lanes(geom.nd.astype(jnp.int32), Npad)
    slen = bufs.lengths
    dend = slen - tlen + OFF

    # the kernel only reads buffer rows [0, T1p + K); build the buffer
    # with enough tail room that the placement below never clips (OFF is
    # bounded by tlen + bandwidth <= T1p - 1 + K), then drop the unread
    # tail before blocking
    L = bufs.seq_T.shape[0]
    Lbuf = T1p + K + 8
    Lbig = Lbuf + L

    def place(tab_T, row0, fill):
        # buffer row r holds table index r - (OFF + 1) (dl: r - OFF):
        # column j's window is rows [j, j + K) for every lane
        buf = jnp.full((Lbig, Npad), fill, tab_T.dtype)
        buf = jax.lax.dynamic_update_slice(
            buf, tab_T, (row0.astype(jnp.int32), jnp.int32(0))
        )
        return buf[:Lbuf]

    row_tab = OFF + 1
    row_dl = OFF

    def stream(sqT, mtT, mmT, giT, dlT):
        # place() follows each table's dtype: int8 planes (packed enc)
        # get an int8 zero fill, and the blocked base codes 2-bit pack
        # (fill rows decode as garbage mod 4 — masked like every other
        # out-of-range cell, see ops.encoding)
        sq_b = _block_tables(place(sqT, row_tab, -9), n_steps, C, CB)
        if input_enc == "packed":
            sq_b = pack_codes_blocked(sq_b)
        return (
            _block_tables(place(mtT, row_tab, 0.0), n_steps, C, CB),
            _block_tables(place(mmT, row_tab, 0.0), n_steps, C, CB),
            _block_tables(place(giT, row_tab, 0.0), n_steps, C, CB),
            _block_tables(place(dlT, row_dl, 0.0), n_steps, C, CB),
            sq_b,
        )

    f_mt, f_mm, f_gi, f_dl, f_sq = stream(
        bufs.seq_T, bufs.match_T, bufs.mismatch_T, bufs.ins_T, bufs.dels_T
    )

    # template columns: row j holds t[j - 1] (row 0 unused); pad to T1p
    def to_cols(t):
        cols = jnp.concatenate([t[:1], t]).astype(jnp.int32)
        return jnp.pad(cols, (0, T1p - cols.shape[0]))

    tpl = to_cols(template)

    meta_rows = [slen, delta, ndv, dend]

    if with_backward:
        # reversed template: reverse the true-length prefix
        k = jnp.arange(template.shape[0])
        ridx = jnp.clip(tlen - 1 - k, 0, template.shape[0] - 1)
        rtemplate = jnp.where(k < tlen, template[ridx], template[k])
        rtpl = to_cols(rtemplate)
        r_mt, r_mm, r_gi, r_dl, r_sq = stream(
            bufs.rseq_T, bufs.rmatch_T, bufs.rmismatch_T, bufs.rins_T,
            bufs.rdels_T,
        )
        mt = jnp.concatenate([f_mt, r_mt], axis=2)
        mm = jnp.concatenate([f_mm, r_mm], axis=2)
        gi = jnp.concatenate([f_gi, r_gi], axis=2)
        dl = jnp.concatenate([f_dl, r_dl], axis=2)
        sq = jnp.concatenate([f_sq, r_sq], axis=2)
        t_cols = jnp.stack([tpl, rtpl])
        meta = jnp.stack(
            [jnp.concatenate([m, m])[None] for m in meta_rows]
        )
    else:
        mt, mm, gi, dl, sq = f_mt, f_mm, f_gi, f_dl, f_sq
        t_cols = tpl[None]
        meta = jnp.stack([m[None] for m in meta_rows])

    qmeta = None
    if input_enc == "packed":
        # fwd and rev lanes of a read share one scale/offset pair
        # (quantization precedes the reversal in build_fill_buffers)
        qmeta = bufs.qmeta[:, None, :]
        if with_backward:
            qmeta = jnp.concatenate([qmeta, qmeta], axis=2)

    return {
        "tlen_s": jnp.reshape(tlen, (1, 1)),
        "off_s": jnp.reshape(OFF, (1, 1)),
        "OFF": OFF,
        "t_cols": t_cols,
        "meta": meta,
        "tabs": (mt, mm, gi, dl, sq),
        "fwd_tabs": (f_mt, f_mm, f_gi, f_dl, f_sq),
        "qmeta": qmeta,
    }


@functools.partial(
    jax.jit, static_argnames=("K", "T1p_pad")
)
def prepare_fill_panels(
    template,  # int8 [Tmax] padded template
    tlen,  # int32 true length
    bufs: FillBuffers,
    geom: BandGeometry,
    K: int,
    T1p_pad: int,  # panelized column count (multiple of the panel size)
    off_override=None,
):
    """Panel-mode fill inputs: the PLACED (padded, un-blocked) forward
    and reversed table buffers plus frame scalars/metadata. Panels slice
    buffer rows [col0, col0 + P + K) per launch instead of materializing
    the fully blocked tables (whose halo'd copy is what breaks the HBM
    budget at very long templates)."""
    Npad = bufs.seq_T.shape[1]
    tlen = jnp.asarray(tlen, jnp.int32)
    OFF = (
        jnp.max(geom.offset).astype(jnp.int32) if off_override is None
        else jnp.asarray(off_override, jnp.int32)
    )
    delta = _pad_lanes((OFF - geom.offset).astype(jnp.int32), Npad)
    ndv = _pad_lanes(geom.nd.astype(jnp.int32), Npad)
    slen = bufs.lengths
    dend = slen - tlen + OFF

    L = bufs.seq_T.shape[0]
    Lbuf = T1p_pad + K + 8
    Lbig = Lbuf + L

    def place(tab_T, row0, fill):
        buf = jnp.full((Lbig, Npad), fill, tab_T.dtype)
        buf = jax.lax.dynamic_update_slice(
            buf, tab_T, (row0.astype(jnp.int32), jnp.int32(0))
        )
        return buf[:Lbuf]

    def placed(sqT, mtT, mmT, giT, dlT):
        return (
            place(mtT, OFF + 1, 0.0),
            place(mmT, OFF + 1, 0.0),
            place(giT, OFF + 1, 0.0),
            place(dlT, OFF, 0.0),
            place(sqT, OFF + 1, -9),
        )

    def to_cols(t):
        cols = jnp.concatenate([t[:1], t]).astype(jnp.int32)
        return jnp.pad(cols, (0, T1p_pad - cols.shape[0]))

    k = jnp.arange(template.shape[0])
    ridx = jnp.clip(tlen - 1 - k, 0, template.shape[0] - 1)
    rtemplate = jnp.where(k < tlen, template[ridx], template[k])

    return {
        "tlen_s": jnp.reshape(tlen, (1, 1)),
        "off_s": jnp.reshape(OFF, (1, 1)),
        "OFF": OFF,
        "tpl_cols": to_cols(template),
        "rtpl_cols": to_cols(rtemplate),
        "meta": jnp.stack([m[None] for m in (slen, delta, ndv, dend)]),
        "fwd_placed": placed(
            bufs.seq_T, bufs.match_T, bufs.mismatch_T, bufs.ins_T,
            bufs.dels_T,
        ),
        "rev_placed": placed(
            bufs.rseq_T, bufs.rmatch_T, bufs.rmismatch_T, bufs.rins_T,
            bufs.rdels_T,
        ),
    }


@functools.partial(
    jax.jit,
    static_argnames=("K", "T1p", "C", "with_backward", "want_moves",
                     "interpret", "band_dtype", "input_enc"),
)
def fill_uniform(
    template,  # int8 [Tmax] padded template
    tlen,  # int32 true length
    bufs: FillBuffers,
    geom: BandGeometry,  # per-read (offset may exceed lanes: padded below)
    K: int,
    T1p: int,
    C: int = 0,
    with_backward: bool = True,
    want_moves: bool = False,
    interpret: bool = False,
    band_dtype: str = "f32",
    input_enc: str = "f32",
):
    """Pallas banded fill in the uniform frame.

    Returns (A [N, K, T1p], Brev or None, scores [N], OFF, moves or None)
    where A is the forward band, Brev the RAW reversed-problem forward
    band (flip to backward layout with flip_reversed_uniform), scores[k]
    = A[dend_k, tlen], and moves the forward-stream move band
    [N, K, T1p] int8 (uniform frame; pair with uniform_geometry for
    consumers). N = lane count (callers slice off padding lanes).
    """
    Npad = bufs.seq_T.shape[1]
    NB = Npad // LANES
    if C <= 0:
        C = plan_cols(T1p, K, kernel="fill", want_moves=want_moves).cols
    p = prepare_fill(template, tlen, bufs, geom, K, T1p, C, with_backward,
                     input_enc=input_enc)
    NBLK = 2 * NB if with_backward else NB
    band_flat, scores, moves_flat = _fill_call(
        p["tlen_s"], p["off_s"], p["t_cols"], p["meta"], *p["tabs"],
        K=K, T1p=T1p, NBLK=NBLK, C=C, want_moves=want_moves,
        interpret=interpret, band_dtype=band_dtype,
        input_enc=input_enc, qmeta=p["qmeta"],
    )
    # [n_steps*C*K, NBLK*128] -> [T1p, K, NBLK*128] -> [lanes, K, T1p]
    band = band_flat.reshape(T1p, K, NBLK * LANES).transpose(2, 1, 0)
    A = band[:Npad]
    moves = None
    if want_moves:
        moves = (
            moves_flat.reshape(T1p, K, NBLK * LANES)
            .transpose(2, 1, 0)[:Npad]
            .astype(jnp.int8)
        )
    if with_backward:
        Brev = band[Npad:]
        return A, Brev, scores[0, :Npad], p["OFF"], moves
    return A, None, scores[0, :Npad], p["OFF"], moves


@functools.partial(jax.jit, static_argnames=("K",))
def flip_reversed_uniform(Brev, tlen, slen, OFF, K: int):
    """Map the reversed-problem forward band into backward-band layout in
    the uniform frame: B[d, j] = Brev[S - d, tlen - j] with
    S = slen - tlen + 2*OFF (derivation: i_rev = slen - i,
    j_rev = tlen - j, d_rev = i_rev - j_rev + OFF)."""
    T1p = Brev.shape[-1]

    def flip_one(b, S):
        f = b[::-1, ::-1]  # rows: K-1-d; cols: T1p-1-j
        # want row S - d = (K-1-d) shifted by S - (K-1)
        f = jnp.roll(f, S - (K - 1), axis=0)
        f = jnp.roll(f, tlen + 1 - T1p, axis=1)
        return f

    S = slen - tlen + 2 * OFF
    return jax.vmap(flip_one)(Brev, S)
