"""Batched banded alignment on device (JAX/XLA), the framework's hot kernel.

TPU-native re-design of the reference banded Viterbi DP
(/root/reference/src/align.jl:50-212) over the packed band layout
(/root/reference/src/bandedarrays.jl:101-114).

Design
------
The reference stores cell ``[i, j]`` at data row ``d = (i - j) + h_offset +
bandwidth``. That layout is *diagonal-aligned*: a match move ``(i-1, j-1)``
lives at the SAME data row ``d`` of the previous column, a delete move
``(i, j-1)`` at ``d + 1`` of the previous column, and an insert move
``(i-1, j)`` at ``d - 1`` of the same column. So a column update is:

  1. ``cand[d] = max(prev[d] + match_score, prev[d+1] + del_score)`` —
     fully vectorized over the band;
  2. the insert chain ``F[d] = max(cand[d], F[d-1] + ins[d])`` — a max-plus
     linear recurrence with the closed form
     ``F = G + cummax(cand - G)`` where ``G = cumsum(ins)``,

which makes the whole column fill a handful of vector ops of band height K.
A ``lax.scan`` walks the columns; ``vmap`` batches over reads. No per-cell
loops, no gathers in the inner loop, static shapes throughout — exactly what
XLA wants. Codon moves (used only for the consensus-vs-reference alignment)
are handled by the numpy oracle engine (align_np) on the host; the device
kernel covers the read hot path, matching the reference where reads never
carry codon scores (model.jl:893-896 requires len(ref) % 3 == 0 only for the
reference, and codon scores come from ref_scores only).

Shapes are bucketed: reads padded to ``L``, template padded to ``T``; the
true lengths are dynamic scalars so consensus edits do NOT trigger
recompilation. Out-of-band and padding cells hold ``-inf``.

Trace codes match align.jl:4-12 / align_np.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models.sequences import ReadBatch, ReadScores
from .align_np import (
    TRACE_DELETE,
    TRACE_INSERT,
    TRACE_MATCH,
    TRACE_NONE,
)
from .banded_array import BandedArray, ndatarows

NEG_INF = -jnp.inf


class BandGeometry(NamedTuple):
    """Per-read band frame (all dynamic scalars; shapes stay static).

    ``d = (i - j) + offset`` maps cell (i, j) to data row d; the band
    occupies data rows [0, nd) (bandedarrays.jl:44-53, 101-114).
    """

    slen: jnp.ndarray  # int32, true read length
    tlen: jnp.ndarray  # int32, true template length
    bandwidth: jnp.ndarray  # int32
    offset: jnp.ndarray  # int32 = h_offset + bandwidth
    nd: jnp.ndarray  # int32 = 2*bw + |slen - tlen| + 1 data rows used

    @classmethod
    def make(cls, slen, tlen, bandwidth):
        slen = jnp.asarray(slen, jnp.int32)
        tlen = jnp.broadcast_to(jnp.asarray(tlen, jnp.int32), slen.shape)
        bandwidth = jnp.broadcast_to(jnp.asarray(bandwidth, jnp.int32), slen.shape)
        h_offset = jnp.maximum(tlen - slen, 0)
        nd = 2 * bandwidth + jnp.abs(slen - tlen) + 1
        return cls(slen, tlen, bandwidth, h_offset + bandwidth, nd)


def _column_cells(geom: BandGeometry, K: int, j):
    """Row index i and validity for each data row d of column j."""
    d = jnp.arange(K, dtype=jnp.int32)
    i = d + j - geom.offset
    valid = (i >= 0) & (i <= geom.slen) & (d < geom.nd) & (j <= geom.tlen)
    return i, valid


def _pick_unroll(T: int, cap: int = 16) -> int:
    """Largest power of two <= cap dividing T (template lengths are
    bucketed to multiples of 64 by the engine, so this is normally 16;
    odd ad-hoc lengths just fall back to 1)."""
    c = 1
    while c < cap and T % (c * 2) == 0:
        c *= 2
    return c


class BandTables(NamedTuple):
    """Per-base score tables pre-shifted into band layout: entry [d, j]
    holds the table value the DP needs at data row d of column j, i.e.
    index ``si = d + j - offset - 1`` (sb/mt/mm/gi) or ``i = d + j -
    offset`` (dl). Built with K contiguous dynamic slices — fancy-index
    gathers measured ~1600x slower than slice builds on the available TPU
    (BASELINE.md), and per-column gathers inside the scan were the
    dominant cost of the whole fill."""

    sb: jnp.ndarray  # int8 [K, T1] read base at si
    mt: jnp.ndarray  # [K, T1] match score at si
    mm: jnp.ndarray  # [K, T1] mismatch score at si
    gi: jnp.ndarray  # [K, T1] insertion score at si
    dl: jnp.ndarray  # [K, T1] deletion score at i


def band_tables(seq, match, mismatch, ins, dels, offset, K: int, T1: int):
    """Pre-shift the per-base tables into band layout (see BandTables).

    ``offset`` may be a traced per-read scalar; out-of-range entries read
    zero, which every consumer masks (the same cells the clipped-gather
    formulation masked)."""
    num = jnp.stack([match, mismatch, ins])  # [3, L]
    num = jnp.pad(num, ((0, 0), (K, K + T1)))
    dlp = jnp.pad(dels, (K - 1, K + T1))
    sqp = jnp.pad(seq, (K, K + T1))
    rows3, rowsd, rowss = [], [], []
    for d in range(K):
        start = jnp.asarray(K + d - offset - 1, jnp.int32)
        rows3.append(jax.lax.dynamic_slice(num, (jnp.int32(0), start), (3, T1)))
        rowsd.append(jax.lax.dynamic_slice(dlp, (start,), (T1,)))
        rowss.append(jax.lax.dynamic_slice(sqp, (start,), (T1,)))
    num_t = jnp.stack(rows3)  # [K, 3, T1]
    return BandTables(
        sb=jnp.stack(rowss),
        mt=num_t[:, 0],
        mm=num_t[:, 1],
        gi=num_t[:, 2],
        dl=jnp.stack(rowsd),
    )


@functools.partial(
    jax.jit, static_argnames=("K", "want_moves", "trim", "skew_matches")
)
def _forward_one(
    t,  # int8 [T] padded template
    seq,  # int8 [L] padded read
    match,  # [L]
    mismatch,  # [L]
    ins,  # [L]
    dels,  # [L + 1]
    geom: BandGeometry,
    K: int,
    want_moves: bool = False,
    trim: bool = False,
    skew_matches: bool = False,
):
    """Banded forward DP for one read. Returns (band [K, T+1], moves, score).

    Mirrors align.jl:114-194 (forward! / forward_moves!); `moves` is all
    TRACE_NONE when want_moves=False.
    """
    T = t.shape[0]
    T1 = T + 1
    Wpad = K + T1
    bands, moves = _scan_fill(
        jnp.pad(seq, (K, Wpad))[None],
        jnp.pad(match, (K, Wpad))[None],
        jnp.pad(mismatch, (K, Wpad))[None],
        jnp.pad(ins, (K, Wpad))[None],
        jnp.pad(dels, (K - 1, Wpad))[None],
        jnp.concatenate([t[:1], t])[None],
        geom, K, T, want_moves, trim,
        0.99 if skew_matches else 1.0,
    )
    band = bands[:, 0].T  # [K, T+1]
    moves = moves.T
    d_end = jnp.maximum(geom.slen - geom.tlen, 0) + geom.bandwidth
    score = band[d_end, geom.tlen]
    return band, moves, score


def _scan_fill(sq_pad, mt_pad, mm_pad, gi_pad, dl_pad, tb_cols, geom, K, T,
               want_moves, trim, skew_val):
    """The shared banded column-scan fill over S stacked streams.

    Every stream shares band geometry (the backward fill is the forward
    DP of the reversed problem with IDENTICAL geometry), so one scan can
    carry all of them as an [S, K] state — each per-column kernel
    (candidate maxes, the insert-chain cumsum/cummax) runs once on the
    stacked state. _forward_one passes S=1; _fwd_bwd_one passes S=2.

    Per-base table reads happen as contiguous [S, window] dynamic slices
    of the padded tables: fancy-index gathers measured ~1600x slower on
    the available TPU (BASELINE.md round 3), and materializing full
    [K, T1] shifted tables blows HBM at 10 kb x 512 reads. ``dl_pad`` is
    padded one element less so the same window start yields index i for
    it and i-1 for the others. The tables stay per-stream-stacked only
    along S (small); stacking the four TABLE KINDS into one array makes
    XLA tile the size-4 axis to its (8, 128) layout unit under vmap — a
    measured 128x memory expansion.

    Returns (bands [T1, S, K], moves [T1, K] int8 for stream 0).
    """
    S = sq_pad.shape[0]
    dtype = mt_pad.dtype
    skew = jnp.asarray(skew_val, dtype)
    negS = jnp.full((S, 1), NEG_INF, dtype)

    def read_windows(j, width):
        start = jnp.asarray(K + j - geom.offset - 1, jnp.int32)
        sl = lambda a: jax.lax.dynamic_slice(
            a, (jnp.int32(0), start), (S, width)
        )
        return sl(sq_pad), sl(mt_pad), sl(mm_pad), sl(gi_pad), sl(dl_pad)

    def make_col(prev, j, sb, mt, mm, gi, dl, tb, first):
        i, valid = _column_cells(geom, K, j)  # [K], shared by all streams
        g = jnp.where((i >= 1) & valid, gi, jnp.zeros_like(gi))
        if trim:
            g = jnp.where((j == 0) | (j == geom.tlen), jnp.zeros_like(g), g)
        if first:
            # column 0: cell (0, 0) = 0; rows below filled by the chain
            cand = jnp.where(i == 0, jnp.zeros((S, K), dtype), NEG_INF)
            mcand = dcand = jnp.full((S, K), NEG_INF, dtype)
        else:
            match_sc = jnp.where(sb == tb[:, None], mt, mm * skew)
            # match from (i-1, j-1): same data row of the previous column
            mcand = jnp.where(i >= 1, prev + match_sc, NEG_INF)
            # delete from (i, j-1): data row d+1 of the previous column
            prev_up = jnp.concatenate([prev[:, 1:], negS], axis=1)
            dcand = prev_up + dl
            cand = jnp.maximum(mcand, dcand)
        # within-column insert chain F[d] = max(cand[d], F[d-1]+g[d]),
        # closed form in the max-plus semiring: with G = cumsum(g),
        # F = G + cummax(cand - G). Valid because the in-band rows of a
        # column are contiguous in d, so no chain crosses a gap.
        G = jnp.cumsum(g, axis=1)
        F = G + jax.lax.cummax(jnp.where(valid, cand, NEG_INF) - G, axis=1)
        col = jnp.where(valid, F, NEG_INF)
        if want_moves and first:
            move = jnp.where(
                (i > 0) & (col[0] > NEG_INF), TRACE_INSERT, TRACE_NONE
            ).astype(jnp.int8)
        elif want_moves:
            # moves only for stream 0 (the true forward band)
            shifted = jnp.concatenate(
                [jnp.full((1,), NEG_INF, dtype), col[0, :-1]]
            )
            icand = shifted + g[0]
            # tie-break priority matches the reference helper call order:
            # match > insert > delete (align.jl:78-86)
            stacked = jnp.stack([mcand[0], icand, dcand[0]])
            move = jnp.array(
                [TRACE_MATCH, TRACE_INSERT, TRACE_DELETE], jnp.int8
            )[jnp.argmax(stacked, axis=0)]
            move = jnp.where(valid & (col[0] > NEG_INF), move, TRACE_NONE)
        else:
            move = jnp.zeros((K,), jnp.int8)
        return col, move

    sb0, mt0, mm0, gi0, dl0 = read_windows(jnp.int32(0), K)
    col0, moves0 = make_col(
        None, jnp.int32(0), sb0, mt0, mm0, gi0, dl0, tb_cols[:, 0], True,
    )

    # unroll C columns of straight-line elementwise code per scan step:
    # a single-column step body is too small to amortize per-step launch
    # overheads
    C = _pick_unroll(T)

    def step(prev, xs):
        j, tb = xs
        # consecutive columns' windows overlap: ONE [S, K + C - 1] slice
        # per table per block, static sub-windows per column
        sqw, mtw, mmw, giw, dlw = read_windows(j[0], K + C - 1)
        cols, mvs = [], []
        for u in range(C):
            col, move = make_col(
                prev, j[u], sqw[:, u : u + K], mtw[:, u : u + K],
                mmw[:, u : u + K], giw[:, u : u + K], dlw[:, u : u + K],
                tb[:, u], False,
            )
            prev = col
            cols.append(col)
            mvs.append(move)
        return prev, (jnp.stack(cols), jnp.stack(mvs))

    xs = (
        jnp.arange(1, T + 1, dtype=jnp.int32).reshape(T // C, C),
        tb_cols[:, 1:].reshape(S, T // C, C).transpose(1, 0, 2),
    )
    _, (cols, mv) = jax.lax.scan(step, col0, xs)
    cols = cols.reshape(T, S, K)
    mv = mv.reshape(T, K)
    bands = jnp.concatenate([col0[None], cols], axis=0)  # [T1, S, K]
    moves = jnp.concatenate([moves0[None], mv], axis=0)  # [T1, K]
    return bands, moves


def _reverse_read(seq, match, mismatch, ins, dels, slen):
    """Reversed per-base tables for the backward pass (align.jl:196-202);
    reverses only the true-length prefix of each padded array."""
    L = seq.shape[0]
    k = jnp.arange(L)
    idx = jnp.clip(slen - 1 - k, 0, L - 1)
    live = k < slen
    rseq = jnp.where(live, seq[idx], seq[k])
    rmatch = jnp.where(live, match[idx], match[k])
    rmismatch = jnp.where(live, mismatch[idx], mismatch[k])
    rins = jnp.where(live, ins[idx], ins[k])
    k1 = jnp.arange(L + 1)
    idx1 = jnp.clip(slen - k1, 0, L)
    rdels = jnp.where(k1 <= slen, dels[idx1], dels[k1])
    return rseq, rmatch, rmismatch, rins, rdels


def _reverse_template(t, tlen):
    T = t.shape[0]
    k = jnp.arange(T)
    idx = jnp.clip(tlen - 1 - k, 0, T - 1)
    return jnp.where(k < tlen, t[idx], t[k])


@functools.partial(jax.jit, static_argnames=("K",))
def _flip_reversed_band(band, geom: BandGeometry, K: int):
    """Map the reversed-problem forward band into backward-band layout:
    180-degree flip, re-center the diagonal frame, re-mask rolled-in
    padding (align.jl:196-202 flip!)."""
    T1 = band.shape[1]
    flipped = band[::-1, ::-1]
    flipped = jnp.roll(flipped, geom.nd - K, axis=0)
    flipped = jnp.roll(flipped, geom.tlen + 1 - T1, axis=1)
    j = jnp.arange(T1, dtype=jnp.int32)
    dd = jnp.arange(K, dtype=jnp.int32)
    i = dd[:, None] + j[None, :] - geom.offset
    valid = (i >= 0) & (i <= geom.slen) & (dd[:, None] < geom.nd) & (
        j[None, :] <= geom.tlen
    )
    return jnp.where(valid, flipped, NEG_INF)


def _backward_one(t, seq, match, mismatch, ins, dels, geom: BandGeometry, K: int):
    """Backward DP: forward on reversed sequences, then flip
    (align.jl:196-202)."""
    rt = _reverse_template(t, geom.tlen)
    rseq, rmatch, rmismatch, rins, rdels = _reverse_read(
        seq, match, mismatch, ins, dels, geom.slen
    )
    band, _, score = _forward_one(
        rt, rseq, rmatch, rmismatch, rins, rdels, geom, K
    )
    return _flip_reversed_band(band, geom, K), score


@functools.partial(jax.jit, static_argnames=("K", "want_moves"))
def _fwd_bwd_one(t, seq, match, mismatch, ins, dels, geom: BandGeometry,
                 K: int, want_moves: bool = False):
    """Forward AND backward bands in ONE column scan (_scan_fill, S=2).

    The backward band is the forward DP of the reversed problem
    (align.jl:196-202) with identical geometry, so both chains advance
    column-by-column in lockstep and every column kernel runs once on
    the stacked pair. On hardware where the fill cost is per-column
    kernel count (BASELINE.md round 3), this roughly halves fill time.
    Returns (A, moves, score, B) with values identical to
    _forward_one + _backward_one (pinned by
    tests/test_fused.py::test_fwd_bwd_merged_matches_separate).
    """
    T = t.shape[0]
    T1 = T + 1
    rt = _reverse_template(t, geom.tlen)
    rseq, rmatch, rmismatch, rins, rdels = _reverse_read(
        seq, match, mismatch, ins, dels, geom.slen
    )
    Wpad = K + T1

    def pad2(a, b, lo):
        return jnp.stack([jnp.pad(a, (lo, Wpad)), jnp.pad(b, (lo, Wpad))])

    bands, moves = _scan_fill(
        pad2(seq, rseq, K),
        pad2(match, rmatch, K),
        pad2(mismatch, rmismatch, K),
        pad2(ins, rins, K),
        pad2(dels, rdels, K - 1),
        jnp.stack([
            jnp.concatenate([t[:1], t]),
            jnp.concatenate([rt[:1], rt]),
        ]),
        geom, K, T, want_moves, False, 1.0,
    )
    A = bands[:, 0].T  # [K, T1]
    moves = moves.T
    d_end = jnp.maximum(geom.slen - geom.tlen, 0) + geom.bandwidth
    score = A[d_end, geom.tlen]

    # backward band: the reversed-stream fill in backward layout
    B = _flip_reversed_band(bands[:, 1].T, geom, K)
    return A, moves, score, B


_forward_batch = jax.jit(
    jax.vmap(_forward_one, in_axes=(None, 0, 0, 0, 0, 0, 0, None, None, None, None)),
    static_argnames=("K", "want_moves", "trim", "skew_matches"),
)
_backward_batch = jax.jit(
    jax.vmap(_backward_one, in_axes=(None, 0, 0, 0, 0, 0, 0, None)),
    static_argnames=("K",),
)


def batch_geometry(batch: ReadBatch, tlen: int) -> BandGeometry:
    return BandGeometry.make(batch.lengths, np.int32(tlen), batch.bandwidth)


def band_height(batch: ReadBatch, tlen: int, margin: int = 0) -> int:
    """Static band-buffer height K covering every read in the batch.

    `margin` leaves headroom for adaptive bandwidth doubling without
    recompilation (model.jl:643-672 doubles up to 2^5).
    """
    bw = np.asarray(batch.bandwidth).astype(np.int64)
    lengths = np.asarray(batch.lengths).astype(np.int64)
    nd = 2 * (bw + margin) + np.abs(lengths - tlen) + 1
    return int(nd.max())


def forward_batch(
    template: np.ndarray,
    batch: ReadBatch,
    tlen: Optional[int] = None,
    K: Optional[int] = None,
    want_moves: bool = False,
    trim: bool = False,
    skew_matches: bool = False,
):
    """Batched banded forward DP over all reads vs one (padded) template.

    Returns (bands [N, K, T+1], moves [N, K, T+1] int8, scores [N],
    geometry). `template` may be longer than `tlen` (bucket padding).
    """
    if tlen is None:
        tlen = len(template)
    if K is None:
        K = band_height(batch, tlen)
    geom = batch_geometry(batch, tlen)
    bands, moves, scores = _forward_batch(
        jnp.asarray(template, jnp.int8),
        jnp.asarray(batch.seq),
        jnp.asarray(batch.match),
        jnp.asarray(batch.mismatch),
        jnp.asarray(batch.ins),
        jnp.asarray(batch.dels),
        geom,
        K,
        want_moves,
        trim,
        skew_matches,
    )
    return bands, moves, scores, geom


def backward_batch(
    template: np.ndarray,
    batch: ReadBatch,
    tlen: Optional[int] = None,
    K: Optional[int] = None,
):
    """Batched banded backward DP. Returns (bands [N, K, T+1], scores [N],
    geometry); scores equal the forward totals (B[0, 0] == A[end, end])."""
    if tlen is None:
        tlen = len(template)
    if K is None:
        K = band_height(batch, tlen)
    geom = batch_geometry(batch, tlen)
    bands, scores = _backward_batch(
        jnp.asarray(template, jnp.int8),
        jnp.asarray(batch.seq),
        jnp.asarray(batch.match),
        jnp.asarray(batch.mismatch),
        jnp.asarray(batch.ins),
        jnp.asarray(batch.dels),
        geom,
        K,
    )
    return bands, scores, geom


def _resolve_insert_chain(seed, ichain):
    """On-path membership closure within one column: a cell at data row d
    whose move is INSERT extends the path to row d-1, so membership
    propagates DOWNWARD in d from every seed through runs of insert moves:
    P[d-1] |= P[d] & ichain[d]. Solved in closed form with the same
    max-plus cumulative trick as the fill's insert chain (_scan_fill),
    on the flipped axis and with finite sentinels (bool semiring embedded
    as 0 / -1e6; path lengths <= K keep everything far from overflow)."""
    s = seed[::-1]
    c = ichain[::-1]
    g = jnp.where(
        jnp.concatenate([jnp.zeros((1,), bool), c[:-1]]), 0.0, -1e6
    ).astype(jnp.float32)
    cand = jnp.where(s, 0.0, -1e12).astype(jnp.float32)
    G = jnp.cumsum(g)
    F = G + jax.lax.cummax(cand - G)
    return (F > -1e5)[::-1]


def _traceback_stats_one(moves, seq, t, geom: BandGeometry, K: int,
                         edge_lo=None, edge_hi=None,
                         want_edge: bool = False):
    """Device traceback statistics for one read: (a) the alignment error
    count of the optimal path (count_errors, align.jl:240-250) and (b) an
    indicator table of the single-base edits the path implies
    (moves_to_proposals, model.jl:458-480): columns 0-3 substitution
    bases, 4-7 insertion bases, 8 deletion; rows = template positions.

    ``want_edge`` appends (c) the count of on-path cells sitting exactly
    on the band-limit rows — the score-frontier signal adaptive band
    growth keys on (a path forced along the band wall means the optimum
    likely lies outside it). ``edge_lo``/``edge_hi`` give the limit rows
    in this move band's frame; they default to 0 and ``geom.nd - 1``
    (the per-read XLA frame), and uniform-frame callers MUST pass the
    read's true limits (the shared frame widens ``nd``, so the frame
    edge is not the band edge).

    The move band assigns every cell exactly one predecessor, so the
    traceback path equals the predecessor-closure of the end cell — which
    a reverse scan over columns computes with dense [K] vector ops (seed
    from the next column's match/delete moves, then the within-column
    insert-chain closure), no sequential pointer chase. This keeps the
    statistics on device: at the driver's scales fetching the [N, K, T+1]
    move band to the host costs latency + bytes/bandwidth EVERY iteration
    (BASELINE.md: the D2H link is the scarcest resource on the available
    hardware), and a per-read while_loop walk measured ~100x slower than
    this scan at 10 kb templates.
    """
    T1 = moves.shape[1]
    d = jnp.arange(K, dtype=jnp.int32)
    off = geom.offset
    d_end = jnp.maximum(geom.slen - geom.tlen, 0) + geom.bandwidth
    e_lo = jnp.int32(0) if edge_lo is None else jnp.asarray(edge_lo, jnp.int32)
    e_hi = (geom.nd - 1 if edge_hi is None
            else jnp.asarray(edge_hi, jnp.int32))
    # padded read bases + per-column template bases: the scan body reads
    # its [K]-windows with contiguous slices, no gathers (see _forward_one)
    sqp = jnp.pad(seq, (K, K + T1))
    tb_cols = jnp.concatenate([t[:1], t])[:T1]

    def step(P, x):
        jc, Mj, sb, tb = x
        sb = sb.astype(jnp.int32)
        # inject the end-cell seed at the last true column; carried seeds
        # for padded columns (jc > tlen) are all-False so they emit nothing
        seed = P | ((jc == geom.tlen) & (d == d_end))
        on = _resolve_insert_chain(seed, Mj == TRACE_INSERT)
        i = d + jc - off
        is_m = on & (Mj == TRACE_MATCH)
        is_i = on & (Mj == TRACE_INSERT)
        is_d = on & (Mj == TRACE_DELETE)
        mism = is_m & (sb != tb)
        nerr_c = jnp.sum((mism | is_i | is_d).astype(jnp.int32))
        sub_any = jnp.stack([jnp.any(mism & (sb == b)) for b in range(4)])
        ins_any = jnp.stack([jnp.any(is_i & (sb == b)) for b in range(4)])
        del_any = jnp.any(is_d)
        hits_c = jnp.sum(
            (on & ((d == e_lo) | (d == e_hi))).astype(jnp.int32)
        )
        # a complete path reaches cell (0, 0) = data row `offset` of col 0
        reached0 = jnp.any(on & (d == off) & (jc == 0))
        # seeds for column jc-1: match pred at the same data row, delete
        # pred one data row down
        Pnext = is_m | jnp.concatenate([jnp.zeros((1,), bool), is_d[:-1]])
        return Pnext, (nerr_c, sub_any, ins_any, del_any, reached0, hits_c)

    # unroll C columns per scan step (see _forward_one: per-step [K]
    # work cannot amortize the TPU scan-step overhead). The scan covers
    # columns T1-1 .. 1 (T of them, divisible by the unroll); column 0 is
    # the tail call below.
    C = _pick_unroll(T1 - 1)

    def block(P, xs):
        jc, tb = xs
        # columns descend: j[u] = j[0] - u; one [K + C - 1] slice covers
        # the whole block's read-base windows, one [K, C] slice the
        # block's move columns (a transposed xs feed of the move band
        # would materialize a second copy of it)
        start = jnp.asarray(K + jc[0] - off - 1 - (C - 1), jnp.int32)
        sqw = jax.lax.dynamic_slice(sqp, (start,), (K + C - 1,))
        mv_blk = jax.lax.dynamic_slice(
            moves, (jnp.int32(0), jnp.asarray(jc[0] - (C - 1), jnp.int32)),
            (K, C),
        )
        outs = []
        for u in range(C):
            sb = sqw[C - 1 - u : C - 1 - u + K]
            P, out = step(P, (jc[u], mv_blk[:, C - 1 - u], sb, tb[u]))
            outs.append(out)
        return P, tuple(jnp.stack(o) for o in zip(*outs))

    js = jnp.arange(T1 - 1, 0, -1, dtype=jnp.int32).reshape((T1 - 1) // C, C)
    xs = (
        js,
        tb_cols[:0:-1].reshape((T1 - 1) // C, C),
    )
    P0 = jnp.zeros((K,), bool)
    Pend, (nerr_c, sub_any, ins_any, del_any, reached0, hits_c) = (
        jax.lax.scan(block, P0, xs)
    )
    sb_col0 = jax.lax.dynamic_slice(sqp, (jnp.asarray(K - off - 1, jnp.int32),), (K,))
    _, (nerr0, sub0, ins0, del0, reached0_0, hits0) = step(
        Pend, (jnp.int32(0), moves[:, 0], sb_col0, tb_cols[0])
    )
    flat = lambda x: x.reshape((T1 - 1,) + x.shape[2:])
    nerr_c = jnp.concatenate([flat(nerr_c), nerr0[None]])
    sub_any = jnp.concatenate([flat(sub_any), sub0[None]])
    ins_any = jnp.concatenate([flat(ins_any), ins0[None]])
    del_any = jnp.concatenate([flat(del_any), del0[None]])
    reached0 = jnp.concatenate([flat(reached0), reached0_0[None]])
    hits_c = jnp.concatenate([flat(hits_c), hits0[None]])
    # scan ran j descending; flip to ascending-j order
    sub_any, ins_any, del_any = sub_any[::-1], ins_any[::-1], del_any[::-1]
    nerr = jnp.sum(nerr_c)
    nerr = jnp.where(jnp.any(reached0), nerr, -1)
    # column jc emits substitutions/deletions at pos jc-1, insertions at
    # pos jc: shift the sub/del rows down by one
    zrow = jnp.zeros((1, 4), bool)
    sub_t = jnp.concatenate([sub_any[1:], zrow])
    del_t = jnp.concatenate([del_any[1:], jnp.zeros((1,), bool)])
    edits = jnp.concatenate(
        [sub_t, ins_any, del_t[:, None]], axis=1
    ).astype(jnp.int8)
    if want_edge:
        return nerr, edits, jnp.sum(hits_c)
    return nerr, edits


def traceback_batch(
    moves: np.ndarray,
    geom: BandGeometry,
    max_steps: Optional[int] = None,
    seqs: Optional[np.ndarray] = None,
    template: Optional[np.ndarray] = None,
):
    """Host-side traceback for every read, vectorized over the batch.

    The move band is O(N*K*T) int8 — cheap to ship to host; the pointer
    chase (align.jl:229-238) is inherently sequential per read, so all reads
    step in lockstep here instead of running a device while_loop.
    Returns a list of per-read move-code lists (reference order). When
    `seqs` [N, L] and `template` are given, also returns per-read alignment
    error counts (mismatches + indel columns, align.jl:240-250) computed
    during the same walk.
    """
    moves = np.asarray(moves)
    slen = np.asarray(geom.slen)
    tlen = np.asarray(geom.tlen)
    offset = np.asarray(geom.offset)
    if seqs is not None:
        seqs = np.asarray(seqs)  # gather once; the walk below is host numpy
    if template is not None:
        template = np.asarray(template)
    N, K, _ = moves.shape
    i = slen.copy().astype(np.int64)
    if tlen.ndim == 0:
        tl = np.full(N, int(tlen), dtype=np.int64)
    else:
        tl = tlen.astype(np.int64)
    j = tl.copy()
    count = seqs is not None and template is not None
    n_errors = np.zeros(N, dtype=np.int64)
    if max_steps is None:
        max_steps = int((slen + tl).max()) + 1
    rows = np.arange(N)
    taken = np.zeros((N, max_steps), dtype=np.int8)
    lengths = np.zeros(N, dtype=np.int64)
    for step in range(max_steps):
        active = (i > 0) | (j > 0)
        if not active.any():
            break
        d = np.clip(i - j + offset, 0, K - 1)
        m = moves[rows, d, np.clip(j, 0, moves.shape[2] - 1)]
        m = np.where(active, m, TRACE_NONE)
        bad = active & (m == TRACE_NONE)
        if bad.any():
            raise RuntimeError(f"traceback hit TRACE_NONE for reads {np.nonzero(bad)[0]}")
        taken[:, step] = m
        lengths += active
        if count:
            sb = seqs[rows, np.clip(i - 1, 0, seqs.shape[1] - 1)]
            tb = template[np.clip(j - 1, 0, len(template) - 1)]
            mism = (m == TRACE_MATCH) & (sb != tb)
            n_errors += active & (mism | (m == TRACE_INSERT) | (m == TRACE_DELETE))
        di = (m == TRACE_MATCH) + (m == TRACE_INSERT)
        dj = (m == TRACE_MATCH) + (m == TRACE_DELETE)
        i = i - di * active
        j = j - dj * active
    paths = [taken[n, : lengths[n]][::-1].tolist() for n in range(N)]
    if count:
        return paths, n_errors
    return paths


def band_to_banded_array(
    band: np.ndarray,
    slen: int,
    tlen: int,
    bandwidth: int,
    default=-np.inf,
    dtype=np.float64,
) -> BandedArray:
    """Convert one device band [K, T+1] back to a host BandedArray (tests /
    host fallback interop)."""
    band = np.asarray(band)
    shape = (slen + 1, tlen + 1)
    out = BandedArray(shape, bandwidth, default=default, dtype=dtype)
    nd = ndatarows(shape[0], shape[1], bandwidth)
    out.data[:nd, : tlen + 1] = band[:nd, : tlen + 1]
    return out
