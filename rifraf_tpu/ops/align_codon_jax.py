"""Codon-capable single-pair alignment on device (JAX/XLA).

The consensus-vs-reference alignment is the one place the reference
enables codon moves (3-base indels at codon-tolerant penalties,
/root/reference/src/align.jl:87-104): FRAME realigns the consensus to
the reference every iteration and rescoring candidates joins recomputed
columns with the backward band (model.jl:302-383). The host engine
(ops.align_np / engine.scoring_np) is exact but python-loop-bound —
measured ~11 s per realign and ~0.26 s per proposal at a 9 kb
reference. This module runs the same math as ONE jitted column scan
(and a vmapped proposal scorer), exact-equal to the host engine
(tests/test_align_codon_jax.py), ~20-100x faster on CPU and usable on
TPU.

Design: a single sequence pair needs no band packing tricks — each
column is a DENSE length-(L+1) row vector with -inf outside the band
(the direct transcription of align_np.forward_moves_vec's column body,
which is the tested production host path), and only the STORAGE is
banded ([T1p, K] slices at the band's start row). The codon-insert
chain (distance-3 edges within a column) uses the same
relax-to-fixpoint loop as the host engine, as a lax.while_loop whose
trip count is data-dependent (usually 1-2 passes).

Trace codes match align_np; moves bands ship to the host for the
traceback walks of FRAME's seeding logic (the bands are [T1p, K] — tiny
for one pair).
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.constants import CODON_LENGTH
from ..utils.shapes import bucket as _bucket
from .align_np import (
    TRACE_CODON_DELETE,
    TRACE_CODON_INSERT,
    TRACE_DELETE,
    TRACE_INSERT,
    TRACE_MATCH,
    TRACE_NONE,
)

NEG = -jnp.inf


class RefTables(NamedTuple):
    """Device-resident score tables of one ReadScores (the reference)."""

    seq: jnp.ndarray  # int8 [L]
    match: jnp.ndarray  # [L]
    mismatch: jnp.ndarray  # [L]
    ins: jnp.ndarray  # [L]
    dels: jnp.ndarray  # [L + 1]
    cins: jnp.ndarray  # [max(L - 2, 0)] codon-insert scores (index i - 3)
    cdel: jnp.ndarray  # [L + 1] codon-delete scores (index i)
    slen: jnp.ndarray  # int32
    bandwidth: jnp.ndarray  # int32
    do_cins: bool
    do_cdel: bool


def make_ref_tables(rs, pad_to: int = 0, bandwidth: Optional[int] = None,
                    skew: bool = False) -> RefTables:
    """Build RefTables from a models.sequences.ReadScores.

    ``pad_to`` pads every per-base vector to a shape bucket (true length
    rides in ``slen``) so refs of similar sizes share one compiled
    engine. Padding entries are never read in-band (row bounds cap at
    slen). ``skew`` bakes the 0.99 mismatch skew into the table (the
    engine itself is skew-agnostic)."""
    do_cins = bool(rs.do_codon_moves and rs.codon_ins_scores is not None
                   and len(rs.codon_ins_scores) > 0)
    do_cdel = bool(rs.do_codon_moves and rs.codon_del_scores is not None)
    L = len(rs.seq)
    Lp = max(pad_to, L)

    def pad(a, n, fill=0.0):
        a = np.asarray(a)
        out = np.full(n, fill, a.dtype)
        out[: len(a)] = a
        return out

    mm = np.asarray(rs.mismatch_scores)
    if skew:
        mm = mm * 0.99
    return RefTables(
        seq=jnp.asarray(pad(rs.seq, Lp, 0)).astype(jnp.int8),
        match=jnp.asarray(pad(rs.match_scores, Lp)),
        mismatch=jnp.asarray(pad(mm, Lp)),
        ins=jnp.asarray(pad(rs.ins_scores, Lp)),
        dels=jnp.asarray(pad(rs.del_scores, Lp + 1)),
        cins=jnp.asarray(pad(
            rs.codon_ins_scores if do_cins else np.zeros(max(L - 2, 0)),
            max(Lp - 2, 1),
        )),
        cdel=jnp.asarray(pad(
            rs.codon_del_scores if do_cdel else np.zeros(L + 1), Lp + 1
        )),
        slen=jnp.int32(L),
        bandwidth=jnp.int32(rs.bandwidth if bandwidth is None else bandwidth),
        do_cins=do_cins,
        do_cdel=do_cdel,
    )


def _reverse_tables(rt: RefTables) -> RefTables:
    """ReadScores.reversed() on device: reverse the TRUE-length prefix
    of every per-base vector (tail padding stays in place)."""
    L = rt.slen

    def rev(a, true_len):
        n = a.shape[0]
        k = jnp.arange(n)
        idx = jnp.where(k < true_len, true_len - 1 - k, k)
        return a[jnp.clip(idx, 0, n - 1)]

    return rt._replace(
        seq=rev(rt.seq, L),
        match=rev(rt.match, L),
        mismatch=rev(rt.mismatch, L),
        ins=rev(rt.ins, L),
        dels=rev(rt.dels, L + 1),
        cins=rev(rt.cins, jnp.maximum(L - 2, 0)),
        cdel=rev(rt.cdel, L + 1),
    )


def _row_bounds(j, tlen, slen, bw):
    """Inclusive row range of column j (bandedarrays.jl:44-53): the band
    covers rows within bw of the main diagonal of the (slen+1, tlen+1)
    rectangle."""
    h_off = jnp.maximum(tlen - slen, 0)
    v_off = jnp.maximum(slen - tlen, 0)
    start = jnp.maximum(0, j - h_off - bw)
    stop = jnp.minimum(j + v_off + bw, slen)
    return start, stop


def _chain1(cand, g1):
    """Within-column insert chain F[d] = max(cand[d], F[d-1] + g1[d]) in
    max-plus closed form (align_np._chain1)."""
    G = jnp.cumsum(g1)
    return G + jax.lax.cummax(cand - G)


def _shift_down(v, k: int):
    pad = jnp.full((k,), NEG, v.dtype)
    return jnp.concatenate([pad, v[:-k]])


def _column(prev1, prev2, prev3, j, tb, rt: RefTables, tlen, trim: bool,
            skew: bool, nrows: int, want_moves: bool, T1p: int,
            bounds_j=None):
    """One dense column of the codon-capable banded DP (the column body
    of align_np.forward_moves_vec, vectorized over rows).

    ``bounds_j``: column used for the ROW RANGE only — the proposal
    scorer recomputes columns of an EDITED (possibly longer) alignment
    and clamps their range to the original matrix's last column
    (scoring_np._new_column's A.row_range(min(logical, ncols - 1)))."""
    i = jnp.arange(nrows)
    jb = j if bounds_j is None else bounds_j
    start, stop = _row_bounds(jb, tlen, rt.slen, rt.bandwidth)
    inband = (i >= start) & (i <= stop) & (jb <= tlen)

    si = jnp.clip(i - 1, 0, rt.seq.shape[0] - 1)
    sb = rt.seq[si]
    mm = rt.mismatch[si] * (0.99 if skew else 1.0)
    msc = jnp.where(sb == tb, rt.match[si], mm)
    first = j == 0
    mcand = jnp.where(
        (i >= 1) & jnp.logical_not(first), _shift_down(prev1, 1) + msc, NEG
    )
    dcand = jnp.where(
        jnp.logical_not(first),
        prev1 + rt.dels[jnp.clip(i, 0, rt.dels.shape[0] - 1)],
        NEG,
    )
    cand = jnp.maximum(mcand, dcand)
    if rt.do_cdel:
        cdel_cand = jnp.where(
            j >= CODON_LENGTH,
            prev3 + rt.cdel[jnp.clip(i, 0, rt.cdel.shape[0] - 1)],
            NEG,
        )
        cand = jnp.maximum(cand, cdel_cand)
    else:
        cdel_cand = jnp.full((nrows,), NEG, cand.dtype)
    cand = jnp.where(first, jnp.where(i == 0, 0.0, NEG), cand)
    cand = jnp.where(inband, cand, NEG)

    g1 = jnp.where((i >= 1) & inband,
                   rt.ins[jnp.clip(i - 1, 0, rt.ins.shape[0] - 1)], 0.0)
    if trim:
        # terminal insertions are free (align.jl:73-76); the last true
        # column is tlen, not T1p - 1
        g1 = jnp.where((i >= 1) & ((j == 0) | (j == tlen)),
                       jnp.zeros_like(g1), g1)
    F = _chain1(cand, g1)
    if rt.do_cins:
        ci = rt.cins
        g3 = jnp.where(
            (i >= CODON_LENGTH) & inband,
            ci[jnp.clip(i - CODON_LENGTH, 0, max(ci.shape[0] - 1, 0))],
            NEG,
        )

        def relax_cond(state):
            F, improved = state
            return improved

        def relax_body(state):
            F, _ = state
            relaxed = jnp.maximum(cand, _shift_down(F, CODON_LENGTH) + g3)
            F2 = _chain1(relaxed, g1)
            improved = jnp.any(F2 > F)
            return jnp.maximum(F, F2), improved

        F, _ = jax.lax.while_loop(
            relax_cond, relax_body, (F, jnp.asarray(True))
        )
    else:
        g3 = None
    F = jnp.where(inband, F, NEG)

    if want_moves:
        ins_real = _shift_down(F, 1) + g1
        stacked = [mcand, ins_real, dcand]
        codes = [TRACE_MATCH, TRACE_INSERT, TRACE_DELETE]
        if rt.do_cins:
            stacked.append(_shift_down(F, CODON_LENGTH) + g3)
            codes.append(TRACE_CODON_INSERT)
        stacked.append(cdel_cand)
        codes.append(TRACE_CODON_DELETE)
        best = jnp.argmax(jnp.stack(stacked), axis=0)
        mv = jnp.array(codes, jnp.int8)[best]
        mv = jnp.where(jnp.isfinite(F), mv, TRACE_NONE)
        mv = jnp.where(first & (i == 0), TRACE_NONE, mv)
    else:
        mv = jnp.zeros((nrows,), jnp.int8)
    return F, mv, start


@functools.partial(
    jax.jit,
    static_argnames=("K", "T1p", "nrows", "want_moves", "trim", "skew",
                     "do_cins", "do_cdel"),
)
def _forward_scan(t_cols, tlen, rt_arrays, K: int, T1p: int, nrows: int,
                  want_moves: bool, trim: bool, skew: bool,
                  do_cins: bool, do_cdel: bool):
    """Band-space column scan: O(K) work per column (the dense-row
    formulation cost O(L) per column and LOST to the numpy engine at
    long refs). Internally diagonal-aligned (data row d = i - j + off,
    so the match/delete/codon-delete predecessors sit at constant row
    offsets of previous columns); each column converts to the
    start-row packing of CodonBands on output."""
    rt = RefTables(*rt_arrays, do_cins=do_cins, do_cdel=do_cdel)
    dtype = rt.match.dtype
    slen = rt.slen
    bw = rt.bandwidth
    h_off = jnp.maximum(tlen - slen, 0)
    off = h_off + bw
    d = jnp.arange(K)
    skew_f = 0.99 if skew else 1.0

    # padded tables for uniform [K]-windows: window of column j starts at
    # si = j - off - 1 (+K pad) for base-indexed tables, i = j - off for
    # the i-indexed ones
    pad_k = lambda a, lead: jnp.concatenate([
        jnp.full((lead,), 0, a.dtype), a,
        jnp.full((K + T1p,), 0, a.dtype),
    ])
    sq_p = pad_k(rt.seq, K)
    mt_p = pad_k(rt.match, K)
    mm_p = pad_k(rt.mismatch * skew_f, K)
    gi_p = pad_k(rt.ins, K)
    dl_p = pad_k(rt.dels, K - 1)  # dl window start j-off (+K-1 pad)
    cd_p = pad_k(rt.cdel, K - 1)
    # cins indexed by i - 3: entry for row i at window slot d needs
    # cins[i - 3] -> pad 3 more leading slots
    ci_p = pad_k(rt.cins, K + 2)

    neg = jnp.full((K,), NEG, dtype)

    def step(carry, x):
        prev1, prev2, prev3 = carry
        j, tb = x
        i = d + (j - off)
        start, stop = _row_bounds(j, tlen, slen, bw)
        inband = (i >= start) & (i <= stop) & (j <= tlen)

        w0 = jnp.asarray(K + j - off - 1, jnp.int32)
        sl = lambda a: jax.lax.dynamic_slice(a, (w0,), (K,))
        sb = sl(sq_p)
        msc = jnp.where(sb == tb, sl(mt_p), sl(mm_p))
        first = j == 0
        # match: (i-1, j-1) = same data row of the previous column;
        # delete: (i, j-1) = row d+1; codon delete: (i, j-3) = row d+3
        mcand = jnp.where((i >= 1) & jnp.logical_not(first),
                          prev1 + msc, NEG)
        prev1_up = jnp.concatenate([prev1[1:], neg[:1]])
        dcand = jnp.where(jnp.logical_not(first), prev1_up + sl(dl_p), NEG)
        cand = jnp.maximum(mcand, dcand)
        if do_cdel:
            prev3_up3 = jnp.concatenate([prev3[3:], neg[:3]])
            cdel_cand = jnp.where(j >= CODON_LENGTH,
                                  prev3_up3 + sl(cd_p), NEG)
            cand = jnp.maximum(cand, cdel_cand)
        else:
            cdel_cand = neg
        cand = jnp.where(first, jnp.where(i == 0, 0.0, NEG), cand)
        cand = jnp.where(inband, cand, NEG)

        g1 = jnp.where((i >= 1) & inband, sl(gi_p), 0.0)
        if trim:
            g1 = jnp.where((i >= 1) & ((j == 0) | (j == tlen)),
                           jnp.zeros_like(g1), g1)
        F = _chain1(cand, g1)
        if do_cins:
            g3 = jnp.where((i >= CODON_LENGTH) & inband, sl(ci_p), NEG)

            def relax_body(state):
                F, _ = state
                relaxed = jnp.maximum(cand, _shift_down(F, CODON_LENGTH) + g3)
                F2 = _chain1(relaxed, g1)
                return jnp.maximum(F, F2), jnp.any(F2 > F)

            F, _ = jax.lax.while_loop(
                lambda s: s[1], relax_body, (F, jnp.asarray(True))
            )
        else:
            g3 = None
        F = jnp.where(inband, F, NEG)

        if want_moves:
            ins_real = _shift_down(F, 1) + g1
            stacked = [mcand, ins_real, dcand]
            codes = [TRACE_MATCH, TRACE_INSERT, TRACE_DELETE]
            if do_cins:
                stacked.append(_shift_down(F, CODON_LENGTH) + g3)
                codes.append(TRACE_CODON_INSERT)
            stacked.append(cdel_cand)
            codes.append(TRACE_CODON_DELETE)
            best = jnp.argmax(jnp.stack(stacked), axis=0)
            mv = jnp.array(codes, jnp.int8)[best]
            mv = jnp.where(jnp.isfinite(F), mv, TRACE_NONE)
            mv = jnp.where(first & (i == 0), TRACE_NONE, mv)
        else:
            mv = jnp.zeros((K,), jnp.int8)

        # convert diagonal packing (row i at d = i - j + off) to the
        # start-row packing of CodonBands (row i at i - start): slot d'
        # holds row start + d', i.e. diag index start + d' - j + off
        shift = start - (j - off)  # 0 once j >= off, off - j before
        Fp = jnp.concatenate([F, jnp.full((K,), NEG, dtype)])
        mvp = jnp.concatenate([mv, jnp.zeros((K,), jnp.int8)])
        band = jax.lax.dynamic_slice(Fp, (shift.astype(jnp.int32),), (K,))
        mvb = jax.lax.dynamic_slice(mvp, (shift.astype(jnp.int32),), (K,))
        return (F, prev1, prev2), (band, mvb, start.astype(jnp.int32))

    js = jnp.arange(T1p, dtype=jnp.int32)
    carry0 = (neg, neg, neg)
    _, (bands, moves, starts) = jax.lax.scan(step, carry0, (js, t_cols))
    score = bands[tlen, slen - starts[tlen]]
    return bands, moves, starts, score


class CodonBands(NamedTuple):
    """Banded store of one fill: band[j, d] = column j row (starts[j]+d)."""

    bands: jnp.ndarray  # [T1p, K]
    moves: jnp.ndarray  # [T1p, K] int8 (zeros when not requested)
    starts: jnp.ndarray  # [T1p] int32
    score: jnp.ndarray  # scalar
    tlen: int
    K: int


def forward_codon(template, tlen, rt: RefTables, K: int, T1p: int,
                  want_moves=False, trim=False, skew=False) -> CodonBands:
    """Codon-capable banded forward fill of template-vs-reference.

    `template` is a padded int8 [>= T1p - 1] array; `tlen` its true
    length. K must cover the band height (band_height_codon)."""
    nrows = int(rt.seq.shape[0]) + 1
    t_cols = jnp.pad(
        jnp.concatenate([template[:1], template]).astype(jnp.int8),
        (0, max(0, T1p - int(template.shape[0]) - 1)),
    )[:T1p]
    bands, moves, starts, score = _forward_scan(
        t_cols, jnp.asarray(tlen, jnp.int32), tuple(rt[:9]), K, T1p,
        nrows, want_moves, trim, skew, rt.do_cins, rt.do_cdel,
    )
    # tlen may be a tracer (the device FRAME loop fills under jit with a
    # drifting consensus length); keep it as-is in the pytree then
    tlen_out = int(tlen) if isinstance(tlen, (int, np.integer)) else tlen
    return CodonBands(bands, moves, starts, score, tlen_out, K)


def backward_codon(template, tlen, rt: RefTables, K: int, T1p: int):
    """Backward band: forward fill of the reversed problem, flipped back
    (align.jl:196-202). Returns a CodonBands whose column j holds the
    backward values B[i, j] at rows [starts[j], starts[j]+K)."""
    tlen_i = jnp.asarray(tlen, jnp.int32)
    rrt = _reverse_tables(rt)
    Tpad = int(template.shape[0])
    k = jnp.arange(Tpad)
    ridx = jnp.clip(tlen_i - 1 - k, 0, Tpad - 1)
    rtemplate = jnp.where(k < tlen_i, template[ridx], template[k])
    fb = forward_codon(rtemplate, tlen, rrt, K, T1p)
    return _flip_codon(fb, tlen_i, rt.slen, rt.bandwidth, K, T1p)


@functools.partial(jax.jit, static_argnames=("K", "T1p"))
def _flip_codon(fb: CodonBands, tlen, slen, bw, K: int, T1p: int):
    """B[i, j] = Arev[slen - i, tlen - j]: per column j, fetch reversed
    column tlen - j, flip its rows, and re-slice at column j's own band
    start."""
    nrows_pad = K  # working in band space directly

    def one(j):
        jr = tlen - j
        jr_ok = (jr >= 0) & (jr <= tlen)
        jr_c = jnp.clip(jr, 0, T1p - 1)
        col = fb.bands[jr_c]  # [K] rows ir in [starts[jr], ...)
        st_r = fb.starts[jr_c]
        # forward row i = slen - ir; reversed col rows ir descending ->
        # flip gives ascending i with i0 = slen - (st_r + K - 1)
        colf = col[::-1]
        i0 = slen - (st_r + K - 1)
        # this column's band start in forward space
        st_f, _ = _row_bounds(j, tlen, slen, bw)
        # shift so entry d holds row st_f + d  (out-of-range -> NEG)
        shift = st_f - i0
        d = jnp.arange(K)
        src = d + shift
        valid = (src >= 0) & (src < K) & jr_ok
        out = jnp.where(
            valid,
            colf[jnp.clip(src, 0, K - 1)],
            NEG,
        )
        return out, st_f.astype(jnp.int32)

    js = jnp.arange(T1p, dtype=jnp.int32)
    bands, starts = jax.vmap(one)(js)
    score = bands[0, 0 - starts[0]]  # B[0, 0] == total
    return CodonBands(bands, jnp.zeros_like(fb.moves), starts, score,
                      fb.tlen, K)


def band_height_codon(slen: int, tlen: int, bw: int) -> int:
    """Static K covering every column's row range (stop - start + 1 is
    at most 2*bw + |slen - tlen| + 1)."""
    return 2 * bw + abs(slen - tlen) + 1


def dense_col(cb: CodonBands, j, nrows: int):
    """Unpack band column j to a dense [nrows] vector (-inf outside)."""
    buf = jnp.full((nrows + cb.K,), NEG, cb.bands.dtype)
    buf = jax.lax.dynamic_update_slice(buf, cb.bands[j], (cb.starts[j],))
    return buf[:nrows]


def backtrace_codon(moves: np.ndarray, starts: np.ndarray, slen: int,
                    tlen: int) -> list:
    """Host traceback walk over a CodonBands move band (align.jl:229-238
    / align_np.backtrace): returns the move list from (0, 0) to
    (slen, tlen)."""
    from .align_np import OFFSETS

    i, j = int(slen), int(tlen)
    out = []
    while i > 0 or j > 0:
        m = int(moves[j, i - starts[j]])
        if m == TRACE_NONE:
            raise RuntimeError(f"traceback hit TRACE_NONE at ({i}, {j})")
        out.append(m)
        di, dj = OFFSETS[m]
        i -= di
        j -= dj
    out.reverse()
    return out


def count_errors_codon(moves: np.ndarray, starts: np.ndarray, slen: int,
                       tlen: int, ref_seq: np.ndarray,
                       template: np.ndarray) -> int:
    """Alignment error count of the optimal path (count_errors,
    align.jl:240-250): mismatching matches plus indel columns."""
    from .align_np import OFFSETS

    i, j = int(slen), int(tlen)
    n = 0
    while i > 0 or j > 0:
        m = int(moves[j, i - starts[j]])
        if m == TRACE_NONE:
            raise RuntimeError(f"traceback hit TRACE_NONE at ({i}, {j})")
        if m == TRACE_MATCH:
            n += int(ref_seq[i - 1] != template[j - 1])
        else:
            n += 1
        di, dj = OFFSETS[m]
        i -= di
        j -= dj
    return n


@functools.partial(jax.jit, static_argnames=("K", "R", "do_cins"))
def path_indel_columns(moves, starts, slen, tlen, K: int, R: int,
                       do_cins: bool):
    """Which columns of the optimal path contain a single-indel move —
    the device-side equivalent of backtrace_codon +
    generate.single_indel_proposals' emission columns (model.jl:538-562):
    an INSERT move in column j emits Insertion(j, .) (anchor j), a DELETE
    move in column j emits Deletion(j - 1) (anchor j), codon moves emit
    nothing. Returns (ins_col, del_col): [T1p] booleans over columns.

    Works by backward reachability over the move band: each cell's
    recorded move points at exactly ONE predecessor, so the set reachable
    from (slen, tlen) is precisely the traceback path — no host fetch of
    the move band needed. The scan walks columns high-to-low carrying
    pending row-sets for the next three columns (MATCH/DELETE feed
    column j-1, CODON_DELETE feeds column j-3); within a column, INSERT
    chains (pred = previous row, same column) are closed in one shot by
    an exact integer segment trick, and CODON_INSERT edges (pred = three
    rows down, same column) by a tiny fixpoint loop.

    ``R`` must be >= max row + K (callers pass nrows + K) so pending
    row-sets can hold any band window."""
    d = jnp.arange(K)

    def close_column(pend, mv):
        # reversed slot space e = K-1-d: row-decreasing edges point in
        # +e direction, so closure is a forward scan
        ins_e = (mv == TRACE_INSERT)[::-1]
        # e and e+1 connect iff slot e (rev) holds an INSERT move; a
        # segment id that increments at every broken edge makes
        # "reachable from some pending slot in my segment" an exact
        # integer cummax test (float cumsums would lose precision here)
        brk = jnp.concatenate([
            jnp.ones((1,), jnp.int32),
            jnp.logical_not(ins_e[:-1]).astype(jnp.int32),
        ])
        seg = jnp.cumsum(brk)

        def close1(p):
            return p | (jax.lax.cummax(jnp.where(p, seg, -1)) == seg)

        on = close1(pend[::-1])
        if do_cins:
            cins_e = (mv == TRACE_CODON_INSERT)[::-1]

            def relax(state):
                cur, _ = state
                add = jnp.concatenate([
                    jnp.zeros((CODON_LENGTH,), bool),
                    (cur & cins_e)[:-CODON_LENGTH],
                ])
                nxt = close1(cur | add)
                return nxt, jnp.any(nxt & jnp.logical_not(cur))

            on, _ = jax.lax.while_loop(
                lambda s: s[1], relax, (on, jnp.asarray(True))
            )
        return on[::-1]

    def step(carry, x):
        p1, p2, p3 = carry
        mv, st, j = x
        pend = jax.lax.dynamic_slice(p1, (st,), (K,))
        pend = pend | ((j == tlen) & (d == slen - st))
        on = close_column(pend, mv)
        del_on = on & (mv == TRACE_DELETE)
        ins_any = jnp.any(on & (mv == TRACE_INSERT))
        del_any = jnp.any(del_on)
        zero = jnp.zeros((R,), bool)
        m_rows = jax.lax.dynamic_update_slice(
            zero, on & (mv == TRACE_MATCH), (st,)
        )
        # MATCH pred is (i-1, j-1): shift the row-set down one
        m_rows = jnp.concatenate([m_rows[1:], zero[:1]])
        d_rows = jax.lax.dynamic_update_slice(zero, del_on, (st,))
        c_rows = jax.lax.dynamic_update_slice(
            zero, on & (mv == TRACE_CODON_DELETE), (st,)
        )
        return (p2 | m_rows | d_rows, p3, c_rows), (ins_any, del_any)

    zero = jnp.zeros((R,), bool)
    js = jnp.arange(moves.shape[0], dtype=jnp.int32)
    _, (ins_col, del_col) = jax.lax.scan(
        step, (zero, zero, zero), (moves, starts, js), reverse=True
    )
    return ins_col, del_col


# --- proposal scoring (model.jl:302-383 / engine.scoring_np) -------------

KIND_SUB, KIND_DEL, KIND_INS = 0, 1, 2
_BOFF = {KIND_SUB: 2, KIND_INS: 1, KIND_DEL: 2}


@functools.partial(
    jax.jit,
    static_argnames=("K", "T1p", "nrows", "do_cins", "do_cdel"),
)
def _score_proposals_codon(
    kinds, poss, bases,  # int32 [P]
    t_cols,  # int8 [T1p] (row j holds consensus[j - 1])
    tlen,
    A_bands, A_starts,  # [T1p, K], [T1p]
    B_bands, B_starts,
    rt_arrays,
    K: int, T1p: int, nrows: int, do_cins: bool, do_cdel: bool,
):
    rt = RefTables(*rt_arrays, do_cins=do_cins, do_cdel=do_cdel)
    NCOL = CODON_LENGTH + 1
    i = jnp.arange(nrows)

    def dense(bands, starts, j):
        buf = jnp.full((nrows + K,), NEG, bands.dtype)
        buf = jax.lax.dynamic_update_slice(
            buf, bands[jnp.clip(j, 0, T1p - 1)],
            (starts[jnp.clip(j, 0, T1p - 1)],),
        )
        return buf[:nrows]

    def summax(a, b):
        s = a + b
        return jnp.max(jnp.where(jnp.isfinite(s), s, NEG))

    def one(kind, pos, base):
        acol = pos
        ncols = tlen + 1
        is_del = kind == KIND_DEL
        is_ins = kind == KIND_INS
        # deletion: pure join of A[:, pos] and B[:, pos + 1] (model.jl:
        # 227-236); with codon moves the generic path below also covers
        # it (n_new_bases = 0), matching score_proposal's structure
        first_bcol = acol + jnp.where(is_ins, 1, 2)

        # consensus bases of the recomputed columns
        n_after_full = CODON_LENGTH
        last_bcol = first_bcol + CODON_LENGTH - 1
        just_a = last_bcol >= ncols - 1
        n_after = jnp.where(
            just_a,
            tlen - pos - jnp.where(is_ins, 0, 1),
            n_after_full,
        )
        n_new = jnp.where(is_del, 0, 1) + n_after

        next_pos = pos + jnp.where(is_ins, 0, 1)
        sub_bases = jnp.where(
            is_del,
            # suffix only
            t_cols[jnp.clip(next_pos + 1 + jnp.arange(NCOL), 0, T1p - 1)],
            jnp.concatenate([
                base[None].astype(jnp.int8),
                t_cols[jnp.clip(next_pos + 1 + jnp.arange(NCOL - 1), 0,
                                T1p - 1)],
            ]),
        )

        # suffix deletion needs no recomputation (model.jl:316-319)
        del_tail = is_del & (acol == ncols - 2)

        # recompute up to NCOL columns sequentially; columns beyond n_new
        # are computed but ignored
        prevs0 = (
            dense(A_bands, A_starts, acol),
            dense(A_bands, A_starts, acol - 1),
            dense(A_bands, A_starts, acol - 2),
        )

        def newcol(carry, kk):
            prev1, prev2, prev3 = carry
            logical = acol + kk + 1
            F, _, _ = _column(
                prev1, prev2, prev3, logical, sub_bases[kk], rt, tlen,
                False, False, nrows, False, T1p,
                bounds_j=jnp.minimum(logical, ncols - 1),
            )
            return (F, prev1, prev2), F

        _, newcols = jax.lax.scan(
            newcol, prevs0, jnp.arange(NCOL, dtype=jnp.int32)
        )

        # join: best over the CODON_LENGTH B columns (model.jl:357-377)
        def join(jj):
            new_j = n_new - CODON_LENGTH + jj
            ok = (new_j >= 0) & (new_j < NCOL)
            col = newcols[jnp.clip(new_j, 0, NCOL - 1)]
            bj = first_bcol + jj
            bcol = dense(B_bands, B_starts, bj)
            return jnp.where(ok & (bj <= tlen), summax(col, bcol), NEG)

        joins = jax.vmap(join)(jnp.arange(CODON_LENGTH))
        best = jnp.max(joins)
        # just_a: the final recomputed column's last row IS the score
        tail_score = newcols[jnp.clip(n_new - 1, 0, NCOL - 1)][rt.slen]
        del_tail_score = dense(A_bands, A_starts, ncols - 2)[rt.slen]
        return jnp.where(
            del_tail, del_tail_score, jnp.where(just_a, tail_score, best)
        )

    return jax.vmap(one)(kinds, poss, bases)


# --- host-facing engine ---------------------------------------------------

# refs shorter than this keep the numpy host engine (compile cost and
# per-column dispatch overheads beat it only at scale)
DEVICE_THRESHOLD = 512
_LEN_BUCKET = 256


class CodonDeviceAligner:
    """Jitted consensus-vs-reference alignment state: the device
    counterpart of engine.realign.RefAligner's host engine for LONG
    references (the host column loop measured ~11 s per realign at 9 kb;
    this engine is one compiled scan). Shapes are bucketed so FRAME's
    changing consensus lengths and adapting bandwidths reuse compiled
    engines. Fills are cached per (skew, consensus, bandwidth) VARIANT —
    FRAME interleaves unskewed realigns with skewed seed alignments
    (single_indel_proposals), and the unskewed bands must survive for
    proposal scoring."""

    def __init__(self, ref_scores_obj):
        self.rs = ref_scores_obj
        self.Lpad = _bucket(len(ref_scores_obj.seq), _LEN_BUCKET)
        self._rt = {}
        self._fills = {}  # skew -> fill state dict

    def _tables(self, bandwidth: int, skew: bool) -> RefTables:
        key = (bandwidth, skew)
        if key not in self._rt:
            self._rt[key] = make_ref_tables(
                self.rs, pad_to=self.Lpad, bandwidth=bandwidth, skew=skew
            )
        return self._rt[key]

    def _shapes(self, tlen: int, bandwidth: int):
        K = _bucket(
            band_height_codon(len(self.rs.seq), tlen, bandwidth) + 1, 16
        )
        Tmax = _bucket(tlen + 1, 64)
        T1p = Tmax + 64
        return K, Tmax, T1p

    def fill(self, consensus: np.ndarray, bandwidth: int,
             want_moves: bool = True, skew: bool = False,
             want_backward: bool = True) -> dict:
        """Forward (+moves) and backward fills; caches per skew variant
        on (consensus, bandwidth, want flags). Returns the fill state."""
        tlen = len(consensus)
        key = (consensus.tobytes(), tlen, bandwidth, want_moves,
               want_backward)
        st = self._fills.get(skew)
        if st is not None and st["key"] == key:
            return st
        rt = self._tables(bandwidth, skew)
        K, Tmax, T1p = self._shapes(tlen, bandwidth)
        tpl = np.zeros(Tmax, np.int8)
        tpl[:tlen] = consensus
        tpl_dev = jnp.asarray(tpl)
        # the skew variant is baked into rt already — passing skew here
        # too would double-apply the 0.99 mismatch factor and diverge
        # from the numpy engine's single application
        fwd = forward_codon(tpl_dev, tlen, rt, K, T1p,
                            want_moves=want_moves, skew=False)
        bwd = (backward_codon(tpl_dev, tlen, rt, K, T1p)
               if want_backward else None)
        tpl_cols = np.zeros(T1p, np.int8)
        tpl_cols[1 : tlen + 1] = consensus
        st = {
            "key": key,
            "fwd": fwd,
            "bwd": bwd,
            "moves_host": np.asarray(fwd.moves) if want_moves else None,
            "starts_host": np.asarray(fwd.starts),
            "tpl_cols": tpl_cols,
            "tlen": tlen,
            "K": K,
            "T1p": T1p,
            "bandwidth": bandwidth,
            "skew": skew,
        }
        self._fills[skew] = st
        return st

    def score(self) -> float:
        return float(np.asarray(self._fills[False]["fwd"].score))

    def moves_list(self, skew: bool = False) -> list:
        st = self._fills[skew]
        return backtrace_codon(
            st["moves_host"], st["starts_host"], len(self.rs.seq),
            st["tlen"],
        )

    def n_errors(self, consensus: np.ndarray, skew: bool = False) -> int:
        st = self._fills[skew]
        return count_errors_codon(
            st["moves_host"], st["starts_host"], len(self.rs.seq),
            st["tlen"], np.asarray(self.rs.seq), consensus,
        )

    def score_proposals(self, proposals) -> np.ndarray:
        """Codon-capable O(band) rescoring of a proposal list
        (model.jl:302-383), one vmapped dispatch (unskewed bands)."""
        from ..engine.proposals import Deletion, Insertion, Substitution

        if len(proposals) == 0:
            return np.empty(0)
        st = self._fills[False]
        kinds = np.array([
            {Substitution: KIND_SUB, Deletion: KIND_DEL,
             Insertion: KIND_INS}[type(p)] for p in proposals
        ], np.int32)
        poss = np.array([p.pos for p in proposals], np.int32)
        bases = np.array([getattr(p, "base", 0) for p in proposals],
                         np.int32)
        rt = self._tables(st["bandwidth"], False)
        out = _score_proposals_codon(
            jnp.asarray(kinds), jnp.asarray(poss), jnp.asarray(bases),
            jnp.asarray(st["tpl_cols"]), jnp.int32(st["tlen"]),
            st["fwd"].bands, st["fwd"].starts,
            st["bwd"].bands, st["bwd"].starts,
            tuple(rt[:9]), st["K"], st["T1p"], self.Lpad + 1,
            rt.do_cins, rt.do_cdel,
        )
        return np.asarray(out)


# small identity-keyed engine cache: FRAME calls has_single_indels /
# single_indel_proposals repeatedly with the SAME reference object, and
# rebuilding the engine re-uploads all score tables per call. Entries
# hold (rs, engine) so an id() reuse after GC can never serve a stale
# engine (hit requires `entry_rs is rs`).
_ENGINE_CACHE: dict = {}
_ENGINE_CACHE_MAX = 4


def get_engine(rs) -> "CodonDeviceAligner":
    key = id(rs)
    hit = _ENGINE_CACHE.get(key)
    if hit is not None and hit[0] is rs:
        return hit[1]
    eng = CodonDeviceAligner(rs)
    if len(_ENGINE_CACHE) >= _ENGINE_CACHE_MAX:
        _ENGINE_CACHE.pop(next(iter(_ENGINE_CACHE)))
    _ENGINE_CACHE[key] = (rs, eng)
    return eng


def align_moves_device(consensus: np.ndarray, rs,
                       skew_matches: bool = False) -> list:
    """Device-backed align_moves (align.jl:337-344) for long pairs:
    codon-capable forward fill + host traceback walk."""
    eng = get_engine(rs)
    eng.fill(np.asarray(consensus, np.int8), int(rs.bandwidth),
             want_moves=True, skew=skew_matches, want_backward=False)
    return eng.moves_list(skew=skew_matches)
