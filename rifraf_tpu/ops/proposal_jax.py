"""Batched proposal scoring on device: every proposal x every read, one launch.

TPU-native version of the O(bandwidth) rescoring trick
(/root/reference/src/model.jl:227-285). Where the reference loops proposals
and reads on the host, here the whole candidate set is scored as one
[K x P] x N vectorized program:

- Deletion(pos): max-plus join of A[:, pos] with B[:, pos+1]; in the
  diagonal-aligned band frame the B column is shifted one data row down.
- Substitution/Insertion: one new band column computed from A[:, pos]
  (match = same/previous data row, delete = next data row, insert chain =
  the same closed-form max-plus scan as the forward kernel), joined with
  B[:, pos+1] / B[:, pos].

vmapped over reads; proposals dimension is vectorized directly. Codon moves
(consensus-vs-reference only) stay on the host oracle
(rifraf_tpu.engine.scoring_np).

Proposal encoding: ptype 0=substitution, 1=insertion, 2=deletion
(engine.proposals' 0-based coordinates).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..engine.proposals import Deletion, Insertion, Proposal, Substitution
from ..models.sequences import ReadBatch
from .align_jax import BandGeometry

NEG_INF = -jnp.inf

PTYPE_SUB = 0
PTYPE_INS = 1
PTYPE_DEL = 2


def encode_proposals(
    proposals, pad_to: Optional[int] = None
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Pack a proposal list into (ptype, pos, base) int arrays, padded to
    `pad_to` with harmless dummies (so the proposal count does not force an
    XLA recompile every iteration)."""
    P = len(proposals) if pad_to is None else pad_to
    ptype = np.full(P, PTYPE_DEL, dtype=np.int32)
    pos = np.zeros(P, dtype=np.int32)
    base = np.zeros(P, dtype=np.int8)
    for k, p in enumerate(proposals):
        pos[k] = p.pos
        if isinstance(p, Substitution):
            ptype[k] = PTYPE_SUB
            base[k] = p.base
        elif isinstance(p, Insertion):
            ptype[k] = PTYPE_INS
            base[k] = p.base
        else:
            ptype[k] = PTYPE_DEL
    return ptype, pos, base


def _score_one_read(
    A,  # [K, T+1]
    B,  # [K, T+1]
    seq,  # [L]
    match,  # [L]
    mismatch,  # [L]
    ins,  # [L]
    dels,  # [L+1]
    geom: BandGeometry,  # scalars for this read
    ptype,  # [P]
    ppos,  # [P]
    pbase,  # [P]
):
    K, _ = A.shape
    L = seq.shape[0]
    dtype = A.dtype
    slen, tlen = geom.slen, geom.tlen
    off = geom.offset
    v_off = jnp.maximum(slen - tlen, 0)

    d = jnp.arange(K, dtype=jnp.int32)[:, None]  # [K, 1]
    pos = ppos[None, :]  # [1, P]
    is_sub = (ptype == PTYPE_SUB)[None, :]
    is_del = ptype == PTYPE_DEL

    # --- deletion: join A[:, pos] with B[:, pos+1] one data row down ---
    a_del = jnp.take(A, ppos, axis=1)  # [K, P]
    b_del = jnp.take(B, jnp.minimum(ppos + 1, tlen), axis=1)
    b_shift = jnp.concatenate([jnp.full((1, b_del.shape[1]), NEG_INF, dtype), b_del[:-1]])
    del_score = jnp.max(a_del + b_shift, axis=0)

    # --- substitution / insertion: one new band column ---
    f = pos + jnp.where(is_sub, 1, 0)  # frame column of the new column
    i = d + f - off  # true row index per data row [K, P]
    jc = jnp.minimum(pos + 1, tlen)  # row-range column (model.jl:263)
    rmin = jnp.maximum(0, jc - off)
    rmax = jnp.minimum(jc + v_off + geom.bandwidth, slen)
    valid = (i >= rmin) & (i <= rmax)

    acol = a_del  # A[:, pos], reused
    acol_up = jnp.concatenate([acol[1:], jnp.full((1, acol.shape[1]), NEG_INF, dtype)])
    acol_dn = jnp.concatenate([jnp.full((1, acol.shape[1]), NEG_INF, dtype), acol[:-1]])
    m_src = jnp.where(is_sub, acol, acol_dn)
    d_src = jnp.where(is_sub, acol_up, acol)

    si = jnp.clip(i - 1, 0, L - 1)
    sb = seq[si]
    msc = jnp.where(sb == pbase[None, :], match[si], mismatch[si])
    mcand = jnp.where(i >= 1, m_src + msc, NEG_INF)
    dcand = d_src + dels[jnp.clip(i, 0, L)]
    cand = jnp.where(valid, jnp.maximum(mcand, dcand), NEG_INF)
    g = jnp.where((i >= 1) & valid, ins[si], jnp.zeros_like(msc))
    G = jnp.cumsum(g, axis=0)
    NC = G + jax.lax.cummax(cand - G, axis=0)
    NC = jnp.where(valid, NC, NEG_INF)

    bj = jnp.where(ptype == PTYPE_SUB, ppos + 1, ppos)
    bcol = jnp.take(B, jnp.minimum(bj, tlen), axis=1)
    subins_score = jnp.max(NC + bcol, axis=0)

    return jnp.where(is_del, del_score, subins_score)


_score_batch = jax.jit(
    jax.vmap(
        _score_one_read,
        in_axes=(0, 0, 0, 0, 0, 0, 0, 0, None, None, None),
    )
)


def score_proposals_batch(
    A_bands,
    B_bands,
    batch: ReadBatch,
    geom: BandGeometry,
    proposals,
    pad_bucket: int = 128,
):
    """Score every proposal against every read. Returns [N, P] scores.

    The driver sums over reads (and adds the host-scored reference term) to
    rank candidates; keeping the read axis separate lets a sharded batch
    `psum` partial sums across chips. The proposal axis is padded up to a
    `pad_bucket` multiple so iteration-varying candidate counts hit the jit
    cache.
    """
    P = len(proposals)
    padded = ((P + pad_bucket - 1) // pad_bucket) * pad_bucket
    ptype, pos, base = encode_proposals(proposals, pad_to=padded)
    out = _score_batch(
        A_bands,
        B_bands,
        jnp.asarray(batch.seq),
        jnp.asarray(batch.match),
        jnp.asarray(batch.mismatch),
        jnp.asarray(batch.ins),
        jnp.asarray(batch.dels),
        geom,
        jnp.asarray(ptype),
        jnp.asarray(pos),
        jnp.asarray(base),
    )
    return out[:, :P]
