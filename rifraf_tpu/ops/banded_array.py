"""Banded 2-D array: dense storage only within a diagonal band.

Host-side (numpy) mirror of /root/reference/src/bandedarrays.jl:5-231, with
0-based indexing. Element [i, j] of the logical (nrows x ncols) array lives at
``data[(i - j) + h_offset + bandwidth, j]``; out-of-band reads return
`default`, out-of-band writes raise.

This class is the exactness oracle for the device kernels (which use the same
memory layout, transposed to (col, diag) order) and part of the public API for
parity with the reference.
"""

from __future__ import annotations

import numpy as np


def ndatarows(nrows: int, ncols: int, bandwidth: int) -> int:
    """Number of used data rows (bandedarrays.jl:101-104)."""
    return 2 * bandwidth + abs(nrows - ncols) + 1


def bandlimits(nrows: int, ncols: int, bandwidth: int):
    """Limits on (i - j) for in-band cells (bandedarrays.jl:44-53)."""
    if ncols > nrows:
        return nrows - ncols - bandwidth, bandwidth
    return -bandwidth, nrows - ncols + bandwidth


def equal_ranges(a_range, b_range):
    """Overlap of two sub-columns given their true row ranges
    (bandedarrays.jl:220-231). Ranges are inclusive (start, stop), 0-based;
    returns 0-based half-open index ranges into each sub-column."""
    a_start, a_stop = a_range
    b_start, b_stop = b_range
    alen = a_stop - a_start + 1
    blen = b_stop - b_start + 1
    amin = max(b_start - a_start, 0)
    amax = alen - max(a_stop - b_stop, 0)
    bmin = max(a_start - b_start, 0)
    bmax = blen - max(b_stop - a_stop, 0)
    return (amin, amax), (bmin, bmax)


class BandedArray:
    """Banded array with out-of-band default (bandedarrays.jl:5-42)."""

    def __init__(self, shape, bandwidth: int, default=0.0, dtype=np.float64):
        if bandwidth < 1:
            raise ValueError("bandwidth must be positive")
        self.dtype = np.dtype(dtype)
        self.default = self.dtype.type(default)
        self.bandwidth = bandwidth
        self._set_shape(shape)
        self.data = np.zeros((ndatarows(*shape, bandwidth), shape[1]), dtype=dtype)

    def _set_shape(self, shape):
        nrows, ncols = shape
        self.nrows = nrows
        self.ncols = ncols
        self.h_offset = max(ncols - nrows, 0)
        self.v_offset = max(nrows - ncols, 0)
        self.lower, self.upper = bandlimits(nrows, ncols, self.bandwidth)

    @property
    def shape(self):
        return (self.nrows, self.ncols)

    def resize(self, shape) -> None:
        """Change logical shape, reallocating only if needed
        (bandedarrays.jl:80-93)."""
        self._set_shape(shape)
        drows, dcols = self.data.shape
        need_rows = ndatarows(self.nrows, self.ncols, self.bandwidth)
        if need_rows > drows or self.ncols > dcols:
            self.data = np.zeros((need_rows, self.ncols), dtype=self.dtype)

    def newbandwidth(self, bandwidth: int) -> None:
        """Change bandwidth, reallocating (bandedarrays.jl:95-98)."""
        self.bandwidth = bandwidth
        self._set_shape((self.nrows, self.ncols))
        self.data = np.zeros(
            (ndatarows(self.nrows, self.ncols, bandwidth), self.ncols),
            dtype=self.dtype,
        )

    def inband(self, i: int, j: int) -> bool:
        """Is [i, j] in the banded region? (bandedarrays.jl:152-157)"""
        if i < 0 or j < 0 or i >= self.nrows or j >= self.ncols:
            return False
        return self.lower <= i - j <= self.upper

    def data_row(self, i: int, j: int) -> int:
        """The data row holding element [i, j] (bandedarrays.jl:109-114)."""
        if not self.inband(i, j):
            raise IndexError(f"[{i}, {j}] is not in band")
        return (i - j) + self.h_offset + self.bandwidth

    def row_range(self, j: int):
        """Inclusive (start, stop) rows of column j that are dense
        (bandedarrays.jl:133-137)."""
        start = max(0, j - self.h_offset - self.bandwidth)
        stop = min(j + self.v_offset + self.bandwidth, self.nrows - 1)
        return start, stop

    def data_row_range(self, j: int):
        a, b = self.row_range(j)
        return self.data_row(a, j), self.data_row(b, j)

    def sparsecol(self, j: int) -> np.ndarray:
        """View of the in-band elements of column j (bandedarrays.jl:146-149)."""
        start, stop = self.data_row_range(j)
        return self.data[start : stop + 1, j]

    def __getitem__(self, idx):
        i, j = idx
        if self.inband(i, j):
            return self.data[self.data_row(i, j), j]
        return self.default

    def __setitem__(self, idx, value):
        i, j = idx
        if not self.inband(i, j):
            raise IndexError(f"Cannot set out-of-band element [{i}, {j}].")
        self.data[self.data_row(i, j), j] = value

    def full(self) -> np.ndarray:
        """Dense representation; out-of-band cells are zero, matching the
        reference's `full` (bandedarrays.jl:160-168)."""
        result = np.zeros(self.shape, dtype=self.dtype)
        for j in range(self.ncols):
            start, stop = self.row_range(j)
            dstart, dstop = self.data_row_range(j)
            result[start : stop + 1, j] = self.data[dstart : dstop + 1, j]
        return result

    def dense(self, default=None) -> np.ndarray:
        """Dense representation with out-of-band cells set to `default`."""
        if default is None:
            default = self.default
        result = np.full(self.shape, default, dtype=self.dtype)
        for j in range(self.ncols):
            start, stop = self.row_range(j)
            dstart, dstop = self.data_row_range(j)
            result[start : stop + 1, j] = self.data[dstart : dstop + 1, j]
        return result

    def flip(self) -> None:
        """Reverse rows and columns in place: [i, j] -> [m-1-i, n-1-j]
        (bandedarrays.jl:176-198)."""
        n = ndatarows(self.nrows, self.ncols, self.bandwidth)
        self.data[:n, : self.ncols] = self.data[:n, : self.ncols][::-1, ::-1]

    def copy(self) -> "BandedArray":
        out = BandedArray.__new__(BandedArray)
        out.dtype = self.dtype
        out.default = self.default
        out.bandwidth = self.bandwidth
        out._set_shape(self.shape)
        out.data = self.data.copy()
        return out
