"""Reference (numpy, cell-by-cell) banded alignment engine.

A faithful 0-based re-statement of /root/reference/src/align.jl. This is the
exactness oracle for the vectorized JAX/Pallas kernels and the host fallback
for tiny problems (e.g. consensus-vs-reference alignment during frame
correction). The hot path for real workloads is rifraf_tpu.ops.align_jax.

Trace codes and move offsets follow align.jl:4-18.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from ..models.sequences import ReadScores
from ..utils.constants import CODON_LENGTH, GAP_INT
from .banded_array import BandedArray, equal_ranges

# Trace codes (align.jl:7-12)
TRACE_NONE = 0
TRACE_MATCH = 1
TRACE_INSERT = 2
TRACE_DELETE = 3
TRACE_CODON_INSERT = 4
TRACE_CODON_DELETE = 5

# (di, dj) move offsets (align.jl:14-18), indexed by trace code
OFFSETS = {
    TRACE_MATCH: (1, 1),
    TRACE_INSERT: (1, 0),
    TRACE_DELETE: (0, 1),
    TRACE_CODON_INSERT: (3, 0),
    TRACE_CODON_DELETE: (0, 3),
}


def offset_forward(move: int, i: int, j: int) -> Tuple[int, int]:
    a, b = OFFSETS[move]
    return i + a, j + b


def offset_backward(move: int, i: int, j: int) -> Tuple[int, int]:
    a, b = OFFSETS[move]
    return i - a, j - b


def update(
    A: BandedArray,
    i: int,
    j: int,
    s_base: int,
    t_base: int,
    pseq: ReadScores,
    newcols: Optional[np.ndarray] = None,
    acol: int = -1,
    trim: bool = False,
    skew_matches: bool = False,
) -> Tuple[float, int]:
    """Score one DP cell: max over moves into (i, j) (align.jl:50-112).

    (i, j) are 0-based cell indices in the (slen+1, tlen+1) DP matrix; cell
    (i, j) scores aligning s[:i] to t[:j]. When `acol >= 0`, columns > acol
    are read from `newcols[:, col - acol - 1]` instead of A (the proposal
    rescoring trick, model.jl:242-285).
    """
    nrows, ncols = A.shape
    seqlen = len(pseq)
    # clamped per-base score index (align.jl:64): i chars consumed -> scores
    # of s[i-1]
    seq_i = max(i - 1, 0)
    match_score = (
        pseq.match_scores[seq_i] if s_base == t_base else pseq.mismatch_scores[seq_i]
    )
    ins_score = pseq.ins_scores[seq_i]
    del_score = pseq.del_scores[i]

    if skew_matches and s_base != t_base:
        match_score *= 0.99
    # allow terminal insertions for free (align.jl:73-76)
    if trim and (j == 0 or j == ncols - 1):
        ins_score = 0.0

    final_score = -np.inf
    final_move = TRACE_NONE

    def helper(final_score, final_move, move_score, move):
        prev_i, prev_j = offset_backward(move, i, j)
        rangecol = min(prev_j, ncols - 1)
        if A.inband(prev_i, rangecol):
            if acol < 0 or prev_j <= acol:
                score = A[prev_i, prev_j] + move_score
            else:
                score = newcols[prev_i, prev_j - acol - 1] + move_score
            if score > final_score:
                return score, move
        return final_score, final_move

    final_score, final_move = helper(final_score, final_move, match_score, TRACE_MATCH)
    final_score, final_move = helper(final_score, final_move, ins_score, TRACE_INSERT)
    final_score, final_move = helper(final_score, final_move, del_score, TRACE_DELETE)

    if pseq.do_codon_moves:
        if pseq.do_codon_ins and i >= CODON_LENGTH:
            codon_ins_score = pseq.codon_ins_scores[i - CODON_LENGTH]
            final_score, final_move = helper(
                final_score, final_move, codon_ins_score, TRACE_CODON_INSERT
            )
        if pseq.do_codon_del and j >= CODON_LENGTH:
            codon_del_score = pseq.codon_del_scores[i]
            final_score, final_move = helper(
                final_score, final_move, codon_del_score, TRACE_CODON_DELETE
            )
    if final_score == -np.inf:
        raise RuntimeError("new score is invalid")
    if final_move == TRACE_NONE:
        raise RuntimeError("failed to find a move")
    return final_score, final_move


def forward_moves_inplace(
    t: np.ndarray,
    s: ReadScores,
    result: BandedArray,
    moves: BandedArray,
    trim: bool = False,
    skew_matches: bool = False,
) -> None:
    """Banded forward DP recording traceback moves (align.jl:114-141)."""
    new_shape = (len(s) + 1, len(t) + 1)
    result.newbandwidth(s.bandwidth)
    moves.newbandwidth(s.bandwidth)
    result.resize(new_shape)
    moves.resize(new_shape)
    result.data.fill(-np.inf)
    moves.data.fill(TRACE_NONE)
    result[0, 0] = 0.0
    nrows, ncols = new_shape
    for j in range(ncols):
        start, stop = result.row_range(j)
        for i in range(start, stop + 1):
            if i == 0 and j == 0:
                continue
            sbase = s.seq[i - 1] if i > 0 else GAP_INT
            tbase = t[j - 1] if j > 0 else GAP_INT
            score, move = update(
                result, i, j, sbase, tbase, s, trim=trim, skew_matches=skew_matches
            )
            result[i, j] = score
            moves[i, j] = move


def forward_moves(
    t: np.ndarray, s: ReadScores, trim: bool = False, skew_matches: bool = False
) -> Tuple[BandedArray, BandedArray]:
    """Banded forward DP + traceback matrix (align.jl:144-153)."""
    shape = (len(s) + 1, len(t) + 1)
    result = BandedArray(shape, s.bandwidth, default=-np.inf)
    moves = BandedArray(shape, s.bandwidth, default=TRACE_NONE, dtype=np.int8)
    forward_moves_inplace(t, s, result, moves, trim=trim, skew_matches=skew_matches)
    return result, moves


def _chain1(cand: np.ndarray, g1: np.ndarray) -> np.ndarray:
    """Resolve F[r] = max(cand[r], F[r-1] + g1[r]) in closed form:
    F = G + cummax(cand - G) with G = cumsum(g1) (max-plus semiring)."""
    G = np.cumsum(g1)
    with np.errstate(invalid="ignore"):
        return G + np.maximum.accumulate(cand - G)


def _shift_down(x: np.ndarray, k: int, fill=-np.inf) -> np.ndarray:
    """out[r] = x[r-k]."""
    out = np.full_like(x, fill)
    if k < len(x):
        out[k:] = x[:-k] if k > 0 else x
    return out


def forward_moves_vec(
    t: np.ndarray,
    s: ReadScores,
    trim: bool = False,
    skew_matches: bool = False,
    want_moves: bool = True,
    doreverse: bool = False,
) -> Tuple[BandedArray, Optional[BandedArray]]:
    """Column-vectorized banded forward DP, codon-capable.

    Semantically equal to the cell loop (forward_moves_inplace /
    align.jl:114-179) up to fp reassociation. Within-column insert chains
    use the max-plus closed form; codon-insert chains (distance-3 edges)
    are resolved by iterating chain1 with the distance-3 relaxation to a
    fixpoint — each pass extends optimal paths by at least one codon-insert
    edge, so convergence is exact.

    This is the production host path for consensus-vs-reference alignments
    (each column is one numpy vector op instead of a Python cell loop).
    """
    rs = s.reversed() if doreverse else s
    t_eff = np.asarray(t)[::-1] if doreverse else np.asarray(t)
    shape = (len(rs) + 1, len(t_eff) + 1)
    nrows, ncols = shape
    A = BandedArray(shape, rs.bandwidth, default=-np.inf)
    A.data.fill(-np.inf)
    moves = None
    if want_moves:
        moves = BandedArray(shape, rs.bandwidth, default=TRACE_NONE, dtype=np.int8)
        moves.data.fill(TRACE_NONE)

    seq = rs.seq
    match_sc = rs.match_scores
    mismatch_sc = rs.mismatch_scores * 0.99 if skew_matches else rs.mismatch_scores
    ins_sc = rs.ins_scores
    del_sc = rs.del_scores
    do_cins = rs.do_codon_ins and len(rs.codon_ins_scores) > 0
    do_cdel = rs.do_codon_del
    cins_sc = rs.codon_ins_scores
    cdel_sc = rs.codon_del_scores

    # per-column band state: values + row offsets of up to 3 previous cols
    prev: List[Tuple[int, np.ndarray]] = []
    neg = -np.inf
    for j in range(ncols):
        start, stop = A.row_range(j)
        i = np.arange(start, stop + 1)
        n = len(i)

        def from_col(col_idx: int, row_shift: int) -> np.ndarray:
            """Values of column col_idx at rows i - row_shift, -inf outside."""
            if col_idx < 0 or j - col_idx > len(prev):
                return np.full(n, neg)
            pstart, pvals = prev[col_idx - j]  # prev[-1] is column j-1
            out = np.full(n, neg)
            rows = i - row_shift
            lo = max(rows[0], pstart)
            hi = min(rows[-1], pstart + len(pvals) - 1)
            if lo > hi:
                return out
            out[lo - rows[0] : hi - rows[0] + 1] = pvals[lo - pstart : hi - pstart + 1]
            return out

        if j == 0:
            cand = np.where(i == 0, 0.0, neg)
            mcand = dcand = cdel_cand = np.full(n, neg)
        else:
            tb = t_eff[j - 1]
            si = np.clip(i - 1, 0, len(seq) - 1)
            sb = seq[si]
            msc = np.where(sb == tb, match_sc[si], mismatch_sc[si])
            mcand = np.where(i >= 1, from_col(j - 1, 1) + msc, neg)
            dcand = from_col(j - 1, 0) + del_sc[np.clip(i, 0, len(del_sc) - 1)]
            cand = np.maximum(mcand, dcand)
            if do_cdel and j >= CODON_LENGTH:
                cdel_cand = from_col(j - CODON_LENGTH, 0) + cdel_sc[
                    np.clip(i, 0, len(cdel_sc) - 1)
                ]
                cand = np.maximum(cand, cdel_cand)
            else:
                cdel_cand = np.full(n, neg)

        g1 = np.where(i >= 1, ins_sc[np.clip(i - 1, 0, len(ins_sc) - 1)], 0.0)
        if trim and (j == 0 or j == ncols - 1):
            g1 = np.where(i >= 1, 0.0, g1)
        if do_cins:
            g3 = np.where(
                i >= CODON_LENGTH,
                cins_sc[np.clip(i - CODON_LENGTH, 0, len(cins_sc) - 1)],
                neg,
            )
        F = _chain1(cand, g1)
        if do_cins:
            # fixpoint over distance-3 codon-insert edges; each pass extends
            # optimal paths by >= 1 such edge, so this terminates exactly
            for _ in range(n // CODON_LENGTH + 1):
                relaxed = np.maximum(cand, _shift_down(F, CODON_LENGTH) + g3)
                F2 = _chain1(relaxed, g1)
                with np.errstate(invalid="ignore"):
                    improved = bool(np.any(F2 > F))
                F = np.maximum(F, F2)
                if not improved:
                    break

        A.data[A.data_row(start, j) : A.data_row(stop, j) + 1, j] = F
        if want_moves:
            ins_real = _shift_down(F, 1) + g1
            stacked = [mcand, ins_real, dcand]
            codes = [TRACE_MATCH, TRACE_INSERT, TRACE_DELETE]
            if do_cins:
                stacked.append(_shift_down(F, CODON_LENGTH) + g3)
                codes.append(TRACE_CODON_INSERT)
            stacked.append(cdel_cand)
            codes.append(TRACE_CODON_DELETE)
            # cell (0, 0) and out-of-band stay TRACE_NONE
            best = np.argmax(np.stack(stacked), axis=0)
            mv = np.array(codes, dtype=np.int8)[best]
            finite = np.isfinite(F)
            mv = np.where(finite, mv, TRACE_NONE)
            if j == 0:
                mv = np.where(i == 0, TRACE_NONE, mv)
            moves.data[
                moves.data_row(start, j) : moves.data_row(stop, j) + 1, j
            ] = mv

        prev.append((start, F))
        if len(prev) > CODON_LENGTH:
            prev.pop(0)
    return A, moves


def forward_vec(
    t: np.ndarray,
    s: ReadScores,
    doreverse: bool = False,
    trim: bool = False,
    skew_matches: bool = False,
) -> BandedArray:
    """Vectorized forward fill without moves."""
    A, _ = forward_moves_vec(
        t, s, trim=trim, skew_matches=skew_matches, want_moves=False,
        doreverse=doreverse,
    )
    return A


def backward_vec(t: np.ndarray, s: ReadScores) -> BandedArray:
    """Vectorized backward DP (forward on reversed + flip, align.jl:196-202)."""
    A = forward_vec(t, s, doreverse=True)
    A.flip()
    return A


def forward_inplace(
    t: np.ndarray,
    s: ReadScores,
    result: BandedArray,
    doreverse: bool = False,
    trim: bool = False,
    skew_matches: bool = False,
) -> None:
    """Banded forward fill without moves (align.jl:155-179).

    With `doreverse`, aligns the reversed sequences (used by backward)
    without materializing them, exactly like align.jl:171-172.
    """
    new_shape = (len(s) + 1, len(t) + 1)
    result.newbandwidth(s.bandwidth)
    result.resize(new_shape)
    result.data.fill(-np.inf)
    result[0, 0] = 0.0
    nrows, ncols = new_shape
    rs = s.reversed() if doreverse else s
    t_eff = t[::-1] if doreverse else t
    for j in range(ncols):
        start, stop = result.row_range(j)
        for i in range(start, stop + 1):
            if i == 0 and j == 0:
                continue
            sbase = rs.seq[i - 1] if i > 0 else GAP_INT
            tbase = t_eff[j - 1] if j > 0 else GAP_INT
            score, _ = update(
                result, i, j, sbase, tbase, rs, trim=trim, skew_matches=skew_matches
            )
            result[i, j] = score


def forward(
    t: np.ndarray,
    s: ReadScores,
    doreverse: bool = False,
    trim: bool = False,
    skew_matches: bool = False,
) -> BandedArray:
    """F[i, j] = best log10 prob of aligning s[:i] to t[:j] (align.jl:185-194)."""
    result = BandedArray((len(s) + 1, len(t) + 1), s.bandwidth, default=-np.inf)
    forward_inplace(t, s, result, doreverse=doreverse, trim=trim, skew_matches=skew_matches)
    return result


def backward_inplace(t: np.ndarray, s: ReadScores, result: BandedArray) -> None:
    """Backward DP = forward on reversed sequences, flipped (align.jl:196-202)."""
    forward_inplace(t, s, result, doreverse=True)
    result.flip()


def backward(t: np.ndarray, s: ReadScores) -> BandedArray:
    """B[i, j] = best log10 prob of aligning s[i:] to t[j:] (align.jl:208-212)."""
    result = forward(t, s, doreverse=True)
    result.flip()
    return result


def backtrace(moves: BandedArray) -> List[int]:
    """Walk the move matrix from the bottom-right corner (align.jl:229-238)."""
    taken = []
    i, j = moves.nrows - 1, moves.ncols - 1
    while i > 0 or j > 0:
        m = int(moves[i, j])
        taken.append(m)
        i, j = offset_backward(m, i, j)
    return taken[::-1]


def backtrace_indices(moves: BandedArray, start=None) -> List[Tuple[int, int]]:
    """Cell indices visited by the backtrace (align.jl:214-227)."""
    result = []
    if start is None:
        i, j = moves.nrows - 1, moves.ncols - 1
    else:
        i, j = start
    while i > 0 or j > 0:
        m = int(moves[i, j])
        i, j = offset_backward(m, i, j)
        result.append((i, j))
    return result[::-1]


def moves_to_aligned_seqs(
    moves: List[int], t: np.ndarray, s: np.ndarray
) -> Tuple[np.ndarray, np.ndarray]:
    """Reconstruct gapped alignment strings as int8 arrays with GAP_INT gaps
    (align.jl:286-311)."""
    aligned_t: List[int] = []
    aligned_s: List[int] = []
    i, j = -1, -1
    for move in moves:
        di, dj = OFFSETS[move]
        i += di
        j += dj
        if move == TRACE_MATCH:
            aligned_t.append(t[j])
            aligned_s.append(s[i])
        elif move == TRACE_INSERT:
            aligned_t.append(GAP_INT)
            aligned_s.append(s[i])
        elif move == TRACE_DELETE:
            aligned_t.append(t[j])
            aligned_s.append(GAP_INT)
        elif move == TRACE_CODON_INSERT:
            aligned_t.extend([GAP_INT] * 3)
            aligned_s.extend([s[i - 2], s[i - 1], s[i]])
        elif move == TRACE_CODON_DELETE:
            aligned_t.extend([t[j - 2], t[j - 1], t[j]])
            aligned_s.extend([GAP_INT] * 3)
    return np.array(aligned_t, dtype=np.int8), np.array(aligned_s, dtype=np.int8)


def moves_to_indices(moves: List[int], tlen: int, slen: int) -> np.ndarray:
    """Index vector mapping positions in t to positions in s (align.jl:322-335).

    One entry per move that advances the template position (codon deletes
    contribute a single entry, matching align.jl:327-333).
    """
    result = []
    i, j = 0, 0
    last_j = 0
    for move in moves:
        di, dj = OFFSETS[move]
        i += di
        j += dj
        if j > last_j:
            result.append(i)
            last_j = j
    return np.array(result, dtype=np.int64)


def count_errors_in_moves(moves_arr: BandedArray, t: np.ndarray, s: np.ndarray) -> int:
    """Number of aligned-column mismatches along the traceback
    (align.jl:240-245)."""
    moves = backtrace(moves_arr)
    a, b = moves_to_aligned_seqs(moves, t, s)
    return int(np.sum(a != b))


def count_errors(t: np.ndarray, s: ReadScores) -> int:
    """align.jl:247-250."""
    _, amoves = forward_moves_vec(t, s, skew_matches=True)
    return count_errors_in_moves(amoves, t, s.seq)


def edit_distance(t: np.ndarray, s: np.ndarray) -> int:
    """Approximate Levenshtein distance via skewed alignment (align.jl:253-260)."""
    from ..models.errormodel import ErrorModel, Scores
    from ..models.sequences import make_read_scores

    log_ps = np.full(len(s), -1.0)
    bandwidth = int(np.ceil(min(len(t), len(s)) * 0.5))
    scores = Scores.from_error_model(ErrorModel(1.0, 1.0, 1.0))
    seq = make_read_scores(s, log_ps, max(bandwidth, 1), scores)
    _, amoves = forward_moves_vec(t, seq, skew_matches=True)
    return count_errors_in_moves(amoves, t, s)


def band_tolerance(amoves: BandedArray) -> int:
    """Minimum distance of the traceback path from the band edge
    (align.jl:262-284)."""
    nrows, ncols = amoves.shape
    dist = nrows
    i, j = nrows - 1, ncols - 1
    while i > 0 or j > 0:
        start, stop = amoves.row_range(j)
        if start > 0:
            dist = min(dist, abs(i - start))
        if stop < nrows - 1:
            dist = min(dist, abs(i - stop))
        i, j = offset_backward(int(amoves[i, j]), i, j)
    start, stop = amoves.row_range(j)
    if start > 0:
        dist = min(dist, abs(i - start))
    if stop < nrows - 1:
        dist = min(dist, abs(i - stop))
    return dist


def align_moves(
    t: np.ndarray, s: ReadScores, trim: bool = False, skew_matches: bool = False
) -> List[int]:
    """align.jl:337-344."""
    _, amoves = forward_moves_vec(t, s, trim=trim, skew_matches=skew_matches)
    return backtrace(amoves)


def align(
    t: np.ndarray, s: ReadScores, trim: bool = False, skew_matches: bool = False
) -> Tuple[np.ndarray, np.ndarray]:
    """Align and return gapped sequences (align.jl:346-353)."""
    moves = align_moves(t, s, trim=trim, skew_matches=skew_matches)
    return moves_to_aligned_seqs(moves, t, s.seq)
