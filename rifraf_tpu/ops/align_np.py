"""Reference (numpy, cell-by-cell) banded alignment engine.

A faithful 0-based re-statement of /root/reference/src/align.jl. This is the
exactness oracle for the vectorized JAX/Pallas kernels and the host fallback
for tiny problems (e.g. consensus-vs-reference alignment during frame
correction). The hot path for real workloads is rifraf_tpu.ops.align_jax.

Trace codes and move offsets follow align.jl:4-18.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from ..models.sequences import ReadScores
from ..utils.constants import CODON_LENGTH, GAP_INT
from .banded_array import BandedArray, equal_ranges

# Trace codes (align.jl:7-12)
TRACE_NONE = 0
TRACE_MATCH = 1
TRACE_INSERT = 2
TRACE_DELETE = 3
TRACE_CODON_INSERT = 4
TRACE_CODON_DELETE = 5

# (di, dj) move offsets (align.jl:14-18), indexed by trace code
OFFSETS = {
    TRACE_MATCH: (1, 1),
    TRACE_INSERT: (1, 0),
    TRACE_DELETE: (0, 1),
    TRACE_CODON_INSERT: (3, 0),
    TRACE_CODON_DELETE: (0, 3),
}


def offset_forward(move: int, i: int, j: int) -> Tuple[int, int]:
    a, b = OFFSETS[move]
    return i + a, j + b


def offset_backward(move: int, i: int, j: int) -> Tuple[int, int]:
    a, b = OFFSETS[move]
    return i - a, j - b


def update(
    A: BandedArray,
    i: int,
    j: int,
    s_base: int,
    t_base: int,
    pseq: ReadScores,
    newcols: Optional[np.ndarray] = None,
    acol: int = -1,
    trim: bool = False,
    skew_matches: bool = False,
) -> Tuple[float, int]:
    """Score one DP cell: max over moves into (i, j) (align.jl:50-112).

    (i, j) are 0-based cell indices in the (slen+1, tlen+1) DP matrix; cell
    (i, j) scores aligning s[:i] to t[:j]. When `acol >= 0`, columns > acol
    are read from `newcols[:, col - acol - 1]` instead of A (the proposal
    rescoring trick, model.jl:242-285).
    """
    nrows, ncols = A.shape
    seqlen = len(pseq)
    # clamped per-base score index (align.jl:64): i chars consumed -> scores
    # of s[i-1]
    seq_i = max(i - 1, 0)
    match_score = (
        pseq.match_scores[seq_i] if s_base == t_base else pseq.mismatch_scores[seq_i]
    )
    ins_score = pseq.ins_scores[seq_i]
    del_score = pseq.del_scores[i]

    if skew_matches and s_base != t_base:
        match_score *= 0.99
    # allow terminal insertions for free (align.jl:73-76)
    if trim and (j == 0 or j == ncols - 1):
        ins_score = 0.0

    final_score = -np.inf
    final_move = TRACE_NONE

    def helper(final_score, final_move, move_score, move):
        prev_i, prev_j = offset_backward(move, i, j)
        rangecol = min(prev_j, ncols - 1)
        if A.inband(prev_i, rangecol):
            if acol < 0 or prev_j <= acol:
                score = A[prev_i, prev_j] + move_score
            else:
                score = newcols[prev_i, prev_j - acol - 1] + move_score
            if score > final_score:
                return score, move
        return final_score, final_move

    final_score, final_move = helper(final_score, final_move, match_score, TRACE_MATCH)
    final_score, final_move = helper(final_score, final_move, ins_score, TRACE_INSERT)
    final_score, final_move = helper(final_score, final_move, del_score, TRACE_DELETE)

    if pseq.do_codon_moves:
        if pseq.do_codon_ins and i >= CODON_LENGTH:
            codon_ins_score = pseq.codon_ins_scores[i - CODON_LENGTH]
            final_score, final_move = helper(
                final_score, final_move, codon_ins_score, TRACE_CODON_INSERT
            )
        if pseq.do_codon_del and j >= CODON_LENGTH:
            codon_del_score = pseq.codon_del_scores[i]
            final_score, final_move = helper(
                final_score, final_move, codon_del_score, TRACE_CODON_DELETE
            )
    if final_score == -np.inf:
        raise RuntimeError("new score is invalid")
    if final_move == TRACE_NONE:
        raise RuntimeError("failed to find a move")
    return final_score, final_move


def forward_moves_inplace(
    t: np.ndarray,
    s: ReadScores,
    result: BandedArray,
    moves: BandedArray,
    trim: bool = False,
    skew_matches: bool = False,
) -> None:
    """Banded forward DP recording traceback moves (align.jl:114-141)."""
    new_shape = (len(s) + 1, len(t) + 1)
    result.newbandwidth(s.bandwidth)
    moves.newbandwidth(s.bandwidth)
    result.resize(new_shape)
    moves.resize(new_shape)
    result.data.fill(-np.inf)
    moves.data.fill(TRACE_NONE)
    result[0, 0] = 0.0
    nrows, ncols = new_shape
    for j in range(ncols):
        start, stop = result.row_range(j)
        for i in range(start, stop + 1):
            if i == 0 and j == 0:
                continue
            sbase = s.seq[i - 1] if i > 0 else GAP_INT
            tbase = t[j - 1] if j > 0 else GAP_INT
            score, move = update(
                result, i, j, sbase, tbase, s, trim=trim, skew_matches=skew_matches
            )
            result[i, j] = score
            moves[i, j] = move


def forward_moves(
    t: np.ndarray, s: ReadScores, trim: bool = False, skew_matches: bool = False
) -> Tuple[BandedArray, BandedArray]:
    """Banded forward DP + traceback matrix (align.jl:144-153)."""
    shape = (len(s) + 1, len(t) + 1)
    result = BandedArray(shape, s.bandwidth, default=-np.inf)
    moves = BandedArray(shape, s.bandwidth, default=TRACE_NONE, dtype=np.int8)
    forward_moves_inplace(t, s, result, moves, trim=trim, skew_matches=skew_matches)
    return result, moves


def forward_inplace(
    t: np.ndarray,
    s: ReadScores,
    result: BandedArray,
    doreverse: bool = False,
    trim: bool = False,
    skew_matches: bool = False,
) -> None:
    """Banded forward fill without moves (align.jl:155-179).

    With `doreverse`, aligns the reversed sequences (used by backward)
    without materializing them, exactly like align.jl:171-172.
    """
    new_shape = (len(s) + 1, len(t) + 1)
    result.newbandwidth(s.bandwidth)
    result.resize(new_shape)
    result.data.fill(-np.inf)
    result[0, 0] = 0.0
    nrows, ncols = new_shape
    rs = s.reversed() if doreverse else s
    t_eff = t[::-1] if doreverse else t
    for j in range(ncols):
        start, stop = result.row_range(j)
        for i in range(start, stop + 1):
            if i == 0 and j == 0:
                continue
            sbase = rs.seq[i - 1] if i > 0 else GAP_INT
            tbase = t_eff[j - 1] if j > 0 else GAP_INT
            score, _ = update(
                result, i, j, sbase, tbase, rs, trim=trim, skew_matches=skew_matches
            )
            result[i, j] = score


def forward(
    t: np.ndarray,
    s: ReadScores,
    doreverse: bool = False,
    trim: bool = False,
    skew_matches: bool = False,
) -> BandedArray:
    """F[i, j] = best log10 prob of aligning s[:i] to t[:j] (align.jl:185-194)."""
    result = BandedArray((len(s) + 1, len(t) + 1), s.bandwidth, default=-np.inf)
    forward_inplace(t, s, result, doreverse=doreverse, trim=trim, skew_matches=skew_matches)
    return result


def backward_inplace(t: np.ndarray, s: ReadScores, result: BandedArray) -> None:
    """Backward DP = forward on reversed sequences, flipped (align.jl:196-202)."""
    forward_inplace(t, s, result, doreverse=True)
    result.flip()


def backward(t: np.ndarray, s: ReadScores) -> BandedArray:
    """B[i, j] = best log10 prob of aligning s[i:] to t[j:] (align.jl:208-212)."""
    result = forward(t, s, doreverse=True)
    result.flip()
    return result


def backtrace(moves: BandedArray) -> List[int]:
    """Walk the move matrix from the bottom-right corner (align.jl:229-238)."""
    taken = []
    i, j = moves.nrows - 1, moves.ncols - 1
    while i > 0 or j > 0:
        m = int(moves[i, j])
        taken.append(m)
        i, j = offset_backward(m, i, j)
    return taken[::-1]


def backtrace_indices(moves: BandedArray, start=None) -> List[Tuple[int, int]]:
    """Cell indices visited by the backtrace (align.jl:214-227)."""
    result = []
    if start is None:
        i, j = moves.nrows - 1, moves.ncols - 1
    else:
        i, j = start
    while i > 0 or j > 0:
        m = int(moves[i, j])
        i, j = offset_backward(m, i, j)
        result.append((i, j))
    return result[::-1]


def moves_to_aligned_seqs(
    moves: List[int], t: np.ndarray, s: np.ndarray
) -> Tuple[np.ndarray, np.ndarray]:
    """Reconstruct gapped alignment strings as int8 arrays with GAP_INT gaps
    (align.jl:286-311)."""
    aligned_t: List[int] = []
    aligned_s: List[int] = []
    i, j = -1, -1
    for move in moves:
        di, dj = OFFSETS[move]
        i += di
        j += dj
        if move == TRACE_MATCH:
            aligned_t.append(t[j])
            aligned_s.append(s[i])
        elif move == TRACE_INSERT:
            aligned_t.append(GAP_INT)
            aligned_s.append(s[i])
        elif move == TRACE_DELETE:
            aligned_t.append(t[j])
            aligned_s.append(GAP_INT)
        elif move == TRACE_CODON_INSERT:
            aligned_t.extend([GAP_INT] * 3)
            aligned_s.extend([s[i - 2], s[i - 1], s[i]])
        elif move == TRACE_CODON_DELETE:
            aligned_t.extend([t[j - 2], t[j - 1], t[j]])
            aligned_s.extend([GAP_INT] * 3)
    return np.array(aligned_t, dtype=np.int8), np.array(aligned_s, dtype=np.int8)


def moves_to_indices(moves: List[int], tlen: int, slen: int) -> np.ndarray:
    """Index vector mapping positions in t to positions in s (align.jl:322-335).

    One entry per move that advances the template position (codon deletes
    contribute a single entry, matching align.jl:327-333).
    """
    result = []
    i, j = 0, 0
    last_j = 0
    for move in moves:
        di, dj = OFFSETS[move]
        i += di
        j += dj
        if j > last_j:
            result.append(i)
            last_j = j
    return np.array(result, dtype=np.int64)


def count_errors_in_moves(moves_arr: BandedArray, t: np.ndarray, s: np.ndarray) -> int:
    """Number of aligned-column mismatches along the traceback
    (align.jl:240-245)."""
    moves = backtrace(moves_arr)
    a, b = moves_to_aligned_seqs(moves, t, s)
    return int(np.sum(a != b))


def count_errors(t: np.ndarray, s: ReadScores) -> int:
    """align.jl:247-250."""
    _, amoves = forward_moves(t, s, skew_matches=True)
    return count_errors_in_moves(amoves, t, s.seq)


def edit_distance(t: np.ndarray, s: np.ndarray) -> int:
    """Approximate Levenshtein distance via skewed alignment (align.jl:253-260)."""
    from ..models.errormodel import ErrorModel, Scores
    from ..models.sequences import make_read_scores

    log_ps = np.full(len(s), -1.0)
    bandwidth = int(np.ceil(min(len(t), len(s)) * 0.5))
    scores = Scores.from_error_model(ErrorModel(1.0, 1.0, 1.0))
    seq = make_read_scores(s, log_ps, max(bandwidth, 1), scores)
    _, amoves = forward_moves(t, seq, skew_matches=True)
    return count_errors_in_moves(amoves, t, s)


def band_tolerance(amoves: BandedArray) -> int:
    """Minimum distance of the traceback path from the band edge
    (align.jl:262-284)."""
    nrows, ncols = amoves.shape
    dist = nrows
    i, j = nrows - 1, ncols - 1
    while i > 0 or j > 0:
        start, stop = amoves.row_range(j)
        if start > 0:
            dist = min(dist, abs(i - start))
        if stop < nrows - 1:
            dist = min(dist, abs(i - stop))
        i, j = offset_backward(int(amoves[i, j]), i, j)
    start, stop = amoves.row_range(j)
    if start > 0:
        dist = min(dist, abs(i - start))
    if stop < nrows - 1:
        dist = min(dist, abs(i - stop))
    return dist


def align_moves(
    t: np.ndarray, s: ReadScores, trim: bool = False, skew_matches: bool = False
) -> List[int]:
    """align.jl:337-344."""
    _, amoves = forward_moves(t, s, trim=trim, skew_matches=skew_matches)
    return backtrace(amoves)


def align(
    t: np.ndarray, s: ReadScores, trim: bool = False, skew_matches: bool = False
) -> Tuple[np.ndarray, np.ndarray]:
    """Align and return gapped sequences (align.jl:346-353)."""
    moves = align_moves(t, s, trim=trim, skew_matches=skew_matches)
    return moves_to_aligned_seqs(moves, t, s.seq)
