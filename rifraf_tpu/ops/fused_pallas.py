"""Single-launch fused megakernel: fill -> dense -> stats in ONE grid.

The split Pallas path (ops.dense_pallas.fused_tables_pallas) runs one
fused step as three launches — dual-stream fill, dense all-edits
rescoring, reverse-sweep stats — and round-trips the band tables and
move codes through HBM between them: the fill WRITES both bands, the
backward-alignment halo program READS the reversed band and WRITES a
halo-blocked copy, and the dense kernel READS both again (roofline
round 5: the band traffic is ~60% of the stats-on step's bytes).

This module chains all three stages under ONE pallas_call so the bands
and move codes are written once and read once, with no halo copy:

- grid (NB, 2 * n_steps), lane blocks OUTERMOST and both axes
  "arbitrary": each 128-lane block runs its full phase-1 + phase-2
  sweep before the next block reuses the shared scratch carry.
- phase 1 (jb < n_steps): the forward fill (verbatim
  fill_pallas._fill_kernel math) AND the reversed-problem fill in
  MIRRORED band coordinates (m = K - 1 - d), both DMA'd per block into
  per-lane-block ANY scratch ([T1p * K, 128] per band; the compiler
  places these in HBM, but they are private to the launch — written
  once, read once, never re-blocked). The forward move codes land in a
  third int32 scratch when the stats chain is on.
- phase 2 (jb2 = 2 * n_steps - 1 - jb, i.e. column blocks in REVERSE
  order, the traceback direction): DMA the forward block back, DMA a
  (C + 2)-column window of the mirrored reversed band, align it with
  ONE per-lane binary-decomposed roll (the flip-native layout turns the
  whole backward-band alignment of dense_pallas.backward_halo_blocks
  into a cyclic roll), then run the dense kernel math (verbatim
  _dense_kernel) and, fused behind it, the reverse-sweep stats
  recurrence (verbatim stats_pallas._stats_kernel) with its P/acc
  carry in VMEM scratch.

Mirrored reversed fill
----------------------
The backward band is B[d, j] = Brev[S_l - d, tlen - j] with
S_l = slen_l - tlen + 2 * OFF (dense_pallas module docstring). Row
extraction d -> S_l - d is a FLIP plus per-lane shift — and a flip is
not a rotation, so it cannot be done on-core with pltpu.roll. Instead
the reversed fill here runs in mirrored coordinates: scratch row m of
column jr holds Brev[K - 1 - m, jr], so the flip is baked in at write
time and phase 2's extraction is the pure per-lane cyclic roll
rolled[(C + 1 - c) * K + d] = B[d, jb2 * C + c]. Bit-identity with the
oracle's reversed stream holds because every elementwise op keeps its
operand order and the suffix doubling scan (stats_pallas._cumop_rev) on
mirrored data combines EXACTLY the same operand pairs in the same
order as the prefix scan (fill_pallas._cumop) on unmirrored data:
step s of either scan computes op(x_here, x_from_s_away) over the same
association tree. The mirrored table windows come from pre-flipped
placed buffers (prepare_fused), one (C + K)-row block per grid step —
the same bytes per step as the split fill's blocked tables.

Selection and the split oracle
------------------------------
RIFRAF_TPU_FUSED_IMPL=split pins the 3-launch path (the oracle the CI
kernels job diffs against; default "mega"). The megakernel DECLINES to
split automatically when:

- ``want_moves`` (the SCORE-stage host traceback needs the exported
  move band; the megakernel keeps moves in launch-private scratch);
- ``plan_cols(T1p, K, "fused", want_moves=want_stats)`` reports the
  chained per-step working set does not fit the VMEM budget
  (BlockPlan.fits — the planner always returns cols >= 1, so `fits` is
  the decline signal);
- panel mode / mesh sharding (the callers in engine.realign route
  those to the split/panel paths before reaching the dispatcher).

The int8-panel grids therefore exercise the decline path by
construction, and fused_tables_auto's outputs are bit-identical to the
oracle either way (tests/test_fused_pallas.py pins the equality across
stats-on/off in interpret mode).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# pallas renamed TPUCompilerParams -> CompilerParams across jax releases;
# accept either so the kernel builds on both sides of the rename.
_CompilerParams = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams

from ..utils.shapes import BlockPlan, plan_cols
from .align_np import TRACE_DELETE, TRACE_INSERT, TRACE_MATCH, TRACE_NONE
from .fill_pallas import (
    LANES,
    NEG_INF,
    NEG_LIVE,
    FillBuffers,
    _block_tables,
    _cumop,
    _pad_lanes,
    band_store_dtype,
    neg_inf_for,
)
from .align_jax import BandGeometry
from .dense_pallas import ROWS, fused_tables_pallas, pack_parts
from .encoding import dequant_block, pack_codes_blocked, unpack_codes
from .stats_pallas import CARRY_ROWS, _cumop_rev, _edits_from_union, _finish_nerr


def fused_impl() -> str:
    """Env selector: RIFRAF_TPU_FUSED_IMPL=split pins the 3-launch
    oracle; default "mega" (single launch where eligible). Read by the
    NON-jit dispatchers below, so the choice is resolved per call site,
    not frozen into a trace cache."""
    return os.environ.get("RIFRAF_TPU_FUSED_IMPL", "mega")


def mega_plan(T1p: int, K: int, want_stats: bool = False,
              vmem_budget=None) -> BlockPlan:
    """The megakernel's block plan: kernel="fused", whose per-step set
    is the max of the phase-1 (dual fill) and phase-2 (dense + stats)
    working sets; ``want_moves`` position carries want_stats because the
    move tile only exists when the stats chain is fused in."""
    kw = {} if vmem_budget is None else {"vmem_budget": int(vmem_budget)}
    return plan_cols(T1p, K, kernel="fused", want_moves=want_stats, **kw)


def mega_eligible(T1p: int, K: int, want_stats: bool = False,
                  want_moves: bool = False, vmem_budget=None,
                  impl=None):
    """(ok, reason) for routing one fused step to the megakernel."""
    impl = fused_impl() if impl is None else impl
    if impl == "split":
        return False, "RIFRAF_TPU_FUSED_IMPL=split"
    if want_moves:
        return False, ("want_moves: the host traceback consumes the "
                       "exported move band; the megakernel keeps moves "
                       "in launch-private scratch")
    plan = mega_plan(T1p, K, want_stats=want_stats, vmem_budget=vmem_budget)
    if not plan.fits:
        return False, (f"plan_cols(fused): 1-column working set "
                       f"{plan.vmem_bytes}B exceeds VMEM budget "
                       f"{plan.vmem_budget}B")
    return True, "mega"


def mega_segment_eligible(n_seg: int):
    """(ok, reason) for a SEGMENT-PACKED launch on the megakernel: the
    kernel streams ONE template's columns through its fill/dense/stats
    phases, so a multi-template packed block (one template per segment,
    ops.fused.fused_step_segmented) has no single-launch program here —
    the planner routes those to the XLA segmented step. This covers
    both multi-CLUSTER packs (utils.shapes.pack_segments) and the
    speculative multi-TEMPLATE rounds (RifrafParams.speculate_k tiles
    the same reads against 2 + k candidate templates): a speculating
    stage is routed to the XLA runner up front
    (engine.realign.stage_runner). The trivial single-segment case is
    just the normal launch (its epilogue already runs through the
    shared segment-reduce helpers)."""
    if n_seg > 1:
        return False, (
            f"segment-packed launch (n_seg={n_seg}): the megakernel "
            "fills one template per launch; multi-template packed "
            "blocks (cluster packs and speculative rounds alike) run "
            "the XLA segmented step"
        )
    return True, "mega"


def select_impl(T1p: int, K: int, want_stats: bool = False,
                want_moves: bool = False, vmem_budget=None, impl=None):
    """("mega"|"split", reason) — the single routing decision shared by
    the dispatchers here and engine.realign's roofline recording."""
    ok, why = mega_eligible(T1p, K, want_stats, want_moves,
                            vmem_budget=vmem_budget, impl=impl)
    return ("mega" if ok else "split"), why


def mega_cols(T1p: int, K: int, want_stats: bool = False,
              interpret: bool = False, vmem_budget=None) -> int:
    """Columns per grid step for the megakernel (interpret mode pins
    C <= 8 like engine.realign._dense_cols, keeping the traced kernel
    body bounded for the CPU suite)."""
    plan = mega_plan(T1p, K, want_stats=want_stats, vmem_budget=vmem_budget)
    return min(plan.cols, 8) if interpret else plan.cols


def prepare_fused(
    template,  # int8 [Tmax] padded template
    tlen,  # int32 true length
    bufs: FillBuffers,
    geom: BandGeometry,
    K: int,
    T1p: int,
    C: int,
    off_override=None,
    input_enc: str = "f32",
):
    """Megakernel inputs: frame scalars, per-lane metadata (the fill
    AND dense rows in one stack), the forward blocked tables (same
    placement + blocking as fill_pallas.prepare_fill, so the values the
    kernel reads are bit-identical to the oracle's), and the MIRRORED
    reversed-stream tables: the placed reversed buffers row-flipped
    (f[r] = buf[Lbuf - 1 - r], one zero pad row so every block slice is
    in bounds) and blocked so that block jb's window for column
    c = C - 1 - (local offset) yields tileM[m] = buf[j + K - 1 - m] —
    the value the mirrored fill needs at row m, which is exactly what
    the oracle's reversed stream reads at row d = K - 1 - m.

    ``input_enc="packed"`` ships the score planes int8 (already
    quantized in build_fill_buffers — the forward and reversed streams
    share one qmeta because quantization happens before reversal) and
    packs both code tables 2-bit AFTER blocking/mirroring
    (ops.encoding.pack_codes_blocked), so the kernel's per-step decode
    sees exactly the rows the f32 path would read."""
    Npad = bufs.seq_T.shape[1]
    n_steps = T1p // C
    CB = C + K

    tlen = jnp.asarray(tlen, jnp.int32)
    OFF = (
        jnp.max(geom.offset).astype(jnp.int32) if off_override is None
        else jnp.asarray(off_override, jnp.int32)
    )
    delta = _pad_lanes((OFF - geom.offset).astype(jnp.int32), Npad)
    ndv = _pad_lanes(geom.nd.astype(jnp.int32), Npad)
    slen = bufs.lengths
    dend = slen - tlen + OFF
    roff = _pad_lanes(geom.offset.astype(jnp.int32), Npad)
    bw = _pad_lanes(geom.bandwidth.astype(jnp.int32), Npad)

    L = bufs.seq_T.shape[0]
    Lbuf = T1p + K + 8
    Lbig = Lbuf + L

    def place(tab_T, row0, fill):
        buf = jnp.full((Lbig, Npad), fill, tab_T.dtype)
        buf = jax.lax.dynamic_update_slice(
            buf, tab_T, (row0.astype(jnp.int32), jnp.int32(0))
        )
        return buf[:Lbuf]

    row_tab = OFF + 1
    row_dl = OFF

    def pack_sq(sq_b):
        # 2-bit pack after blocking: -9 pad packs as garbage 3, masked
        # at every consumption site (ops.encoding module docstring)
        return pack_codes_blocked(sq_b) if input_enc == "packed" else sq_b

    def fwd(sqT, mtT, mmT, giT, dlT):
        return (
            _block_tables(place(mtT, row_tab, 0.0), n_steps, C, CB),
            _block_tables(place(mmT, row_tab, 0.0), n_steps, C, CB),
            _block_tables(place(giT, row_tab, 0.0), n_steps, C, CB),
            _block_tables(place(dlT, row_dl, 0.0), n_steps, C, CB),
            pack_sq(_block_tables(place(sqT, row_tab, -9), n_steps, C, CB)),
        )

    def _mirror_blocks(buf):
        # one pad row: the deepest block slice ends at row Lbuf + 1 (its
        # last row is never read — max in-kernel window row is C + K - 2)
        f = jnp.concatenate(
            [buf[::-1], jnp.zeros((1, Npad), buf.dtype)], axis=0
        )
        b0 = Lbuf - K - C + 1  # block jb starts at b0 - jb * C (>= 9)
        return jnp.stack(
            [f[b0 - jb * C : b0 - jb * C + CB] for jb in range(n_steps)]
        )

    def rev(sqT, mtT, mmT, giT, dlT):
        return (
            _mirror_blocks(place(mtT, row_tab, 0.0)),
            _mirror_blocks(place(mmT, row_tab, 0.0)),
            _mirror_blocks(place(giT, row_tab, 0.0)),
            _mirror_blocks(place(dlT, row_dl, 0.0)),
            pack_sq(_mirror_blocks(place(sqT, row_tab, -9))),
        )

    fwd_tabs = fwd(bufs.seq_T, bufs.match_T, bufs.mismatch_T, bufs.ins_T,
                   bufs.dels_T)
    rev_tabs = rev(bufs.rseq_T, bufs.rmatch_T, bufs.rmismatch_T,
                   bufs.rins_T, bufs.rdels_T)

    def to_cols(t):
        cols = jnp.concatenate([t[:1], t]).astype(jnp.int32)
        return jnp.pad(cols, (0, T1p - cols.shape[0]))

    k = jnp.arange(template.shape[0])
    ridx = jnp.clip(tlen - 1 - k, 0, template.shape[0] - 1)
    rtemplate = jnp.where(k < tlen, template[ridx], template[k])

    return {
        "tlen_s": jnp.reshape(tlen, (1, 1)),
        "off_s": jnp.reshape(OFF, (1, 1)),
        "OFF": OFF,
        "t_cols": jnp.stack([to_cols(template), to_cols(rtemplate)]),
        "meta6": jnp.stack(
            [m[None] for m in (slen, delta, ndv, dend, roff, bw)]
        ),
        "fwd_tabs": fwd_tabs,
        "rev_tabs": rev_tabs,
        "qmeta": (
            bufs.qmeta[:, None, :] if input_enc == "packed" else None
        ),
    }


def _mega_kernel(
    # SMEM inputs
    tlen_ref,  # [1, 1]
    off_ref,  # [1, 1]
    t_ref,  # [2, T1p] template codes (row 0 forward, row 1 reversed)
    # per-lane metadata, [1, 1, 128] blocks
    slen_ref,
    delta_ref,
    ndv_ref,
    dend_ref,
    roff_ref,
    bw_ref,
    # forward blocked tables [1, CB, 128]: phase-1 block jb, phase-2
    # block jb2 (the dense re-read)
    fmt_ref,
    fmm_ref,
    fgi_ref,
    fdl_ref,
    fsq_ref,
    # mirrored reversed tables [1, CB, 128], phase-1 blocks only
    rmt_ref,
    rmm_ref,
    rgi_ref,
    rdl_ref,
    rsq_ref,
    # outputs: dense [1, 1, C*ROWS, 128], score [1, 128], then with
    # want_stats tiles [C*ROWS, 128] and acc [CARRY_ROWS, 128]; scratch
    # per the scratch_shapes list in _mega_call
    *refs,
    K: int,
    C: int,
    n_steps: int,
    want_stats: bool,
    band_neg: float = NEG_INF,
    input_enc: str = "f32",
):
    refs = list(refs)
    # packed enc appends the [8, 1, 128] qmeta block after the tables —
    # it arrives FIRST in *refs, before any output ref
    qm_ref = refs.pop(0) if input_enc == "packed" else None
    dense_ref = refs.pop(0)
    score_ref = refs.pop(0)
    tiles_ref = refs.pop(0) if want_stats else None
    acc_ref = refs.pop(0) if want_stats else None
    band_f = refs.pop(0)  # ANY [T1p*K, 128] f32, forward band
    band_r = refs.pop(0)  # ANY [T1p*K, 128] f32, mirrored reversed band
    stage_f = refs.pop(0)  # VMEM [C*K, 128] f32 (fwd tile / A tile)
    stage_r = refs.pop(0)  # VMEM [C*K, 128] f32 (rev tile)
    stage_b = refs.pop(0)  # VMEM [(C+2)*K, 128] f32 (phase-2 B window)
    sem = refs.pop(0)
    fcarry = refs.pop(0)  # VMEM [K, 128] f32
    rcarry = refs.pop(0)  # VMEM [K, 128] f32
    acc_score = refs.pop(0)  # VMEM [1, 128] f32
    if want_stats:
        moves_any = refs.pop(0)  # ANY [T1p*K, 128] int32
        stage_mv = refs.pop(0)  # VMEM [C*K, 128] int32
        P_scr = refs.pop(0)  # VMEM [K, 128] int32
        acc_scr = refs.pop(0)  # VMEM [CARRY_ROWS, 128] int32

    jb = pl.program_id(1)
    phase1 = jb < n_steps
    tlen = tlen_ref[0, 0]
    OFF = off_ref[0, 0]
    slen = slen_ref[0, 0, :]
    delta = delta_ref[0, 0, :]
    nd = ndv_ref[0, 0, :]
    dend = dend_ref[0, 0, :]
    d = jax.lax.broadcasted_iota(jnp.int32, (K, LANES), 0)
    # band_neg == NEG_INF on the f32 path (bit-identical); a bf16 band
    # store swaps in its own sum-safe finite sentinel (neg_inf_for) so
    # stored sentinels round-trip the narrow band exactly
    neg = jnp.full((K, LANES), band_neg, jnp.float32)

    @pl.when(jb == 0)
    def _():
        acc_score[:] = jnp.full((1, LANES), NEG_INF, jnp.float32)
        if want_stats:
            P_scr[:] = jnp.zeros((K, LANES), jnp.int32)
            acc_scr[:] = jnp.zeros((CARRY_ROWS, LANES), jnp.int32)

    if input_enc == "packed":
        # per-grid-step decode of the loaded table blocks: int8 planes
        # dequantize against the per-lane qmeta rows (accumulate-wide —
        # every max-plus candidate below stays f32), packed code words
        # unpack to one code row per band row (pad garbage is masked at
        # every consumption site)
        def _decode(mt_r, mm_r, gi_r, dl_r, sq_r):
            return (
                dequant_block(mt_r[0], qm_ref[0, 0, :], qm_ref[4, 0, :]),
                dequant_block(mm_r[0], qm_ref[1, 0, :], qm_ref[5, 0, :]),
                dequant_block(gi_r[0], qm_ref[2, 0, :], qm_ref[6, 0, :]),
                dequant_block(dl_r[0], qm_ref[3, 0, :], qm_ref[7, 0, :]),
                unpack_codes(sq_r[0]),
            )

    @pl.when(phase1)
    def _():
        in_band_f = (d >= delta[None, :]) & (d < (delta + nd)[None, :])
        # mirrored data row of the reversed stream: scratch row m holds
        # the reversed problem's band row K - 1 - m
        md = (K - 1) - d
        in_band_r = (md >= delta[None, :]) & (md < (delta + nd)[None, :])

        if input_enc == "packed":
            fmt_t, fmm_t, fgi_t, fdl_t, fsq_t = _decode(
                fmt_ref, fmm_ref, fgi_ref, fdl_ref, fsq_ref
            )
            rmt_t, rmm_t, rgi_t, rdl_t, rsq_t = _decode(
                rmt_ref, rmm_ref, rgi_ref, rdl_ref, rsq_ref
            )

        prev_f = fcarry[:]
        prev_r = rcarry[:]
        for c in range(C):
            j = jb * C + c
            first = j == 0

            # ---- forward fill column (fill_pallas._fill_kernel) ------
            i = d + (j - OFF)
            valid = (i >= 0) & (i <= slen[None, :]) & in_band_f & (j <= tlen)
            if input_enc == "packed":
                mw = fmt_t[c : c + K, :]
                mmw = fmm_t[c : c + K, :]
                giw = fgi_t[c : c + K, :]
                dlw = fdl_t[c : c + K, :]
                sqw = fsq_t[c : c + K, :]
            else:
                mw = fmt_ref[0, c : c + K, :]
                mmw = fmm_ref[0, c : c + K, :]
                giw = fgi_ref[0, c : c + K, :]
                dlw = fdl_ref[0, c : c + K, :]
                sqw = fsq_ref[0, c : c + K, :]
            tb = t_ref[0, j]
            msc = jnp.where(sqw == tb, mw, mmw)
            mcand = jnp.where(
                (i >= 1) & jnp.logical_not(first), prev_f + msc, neg
            )
            prev_up = pltpu.roll(prev_f, K - 1, axis=0)
            prev_up = jnp.where(d == K - 1, neg, prev_up)
            dcand = jnp.where(first, neg, prev_up + dlw)
            cand = jnp.maximum(mcand, dcand)
            cand = jnp.where(first & (i == 0), 0.0, cand)
            cand = jnp.where(valid, cand, neg)
            g = jnp.where((i >= 1) & valid, giw, 0.0)
            G = _cumop(g, lambda a, b: a + b, K)
            F = G + _cumop(cand - G, jnp.maximum, K)
            F = jnp.where(valid, F, neg)

            if want_stats:
                icand = pltpu.roll(F, 1, axis=0)
                icand = jnp.where(d == 0, neg, icand) + g
                mv = jnp.where(
                    (mcand >= icand) & (mcand >= dcand),
                    TRACE_MATCH,
                    jnp.where(icand >= dcand, TRACE_INSERT, TRACE_DELETE),
                )
                live = valid & (F > NEG_LIVE)
                mv = jnp.where(
                    first,
                    jnp.where((i > 0) & live, TRACE_INSERT, TRACE_NONE),
                    jnp.where(live, mv, TRACE_NONE),
                )
                stage_mv[c * K : (c + 1) * K, :] = mv.astype(jnp.int32)

            prev_f = F
            # store-narrow: a bf16 stage takes the cast here; the f32 DP
            # carry (prev_f) and the score accumulator never narrow
            stage_f[c * K : (c + 1) * K, :] = F.astype(stage_f.dtype)

            @pl.when(j == tlen)
            def _():
                sel = jnp.where(d == dend[None, :], F, NEG_INF)
                acc_score[:] = jnp.max(sel, axis=0, keepdims=True)

            # ---- mirrored reversed fill column -----------------------
            # identical math at data row K - 1 - m; the delete
            # predecessor (data row + 1) sits at scratch row m - 1, and
            # the within-column insert chain runs as the SUFFIX scan —
            # same operand pairs, same association tree, so the values
            # are bit-identical to the oracle's reversed stream
            ir = md + (j - OFF)
            validr = (
                (ir >= 0) & (ir <= slen[None, :]) & in_band_r & (j <= tlen)
            )
            o = C - 1 - c  # mirrored window offset within the block
            if input_enc == "packed":
                rmw = rmt_t[o : o + K, :]
                rmmw = rmm_t[o : o + K, :]
                rgiw = rgi_t[o : o + K, :]
                rdlw = rdl_t[o : o + K, :]
                rsqw = rsq_t[o : o + K, :]
            else:
                rmw = rmt_ref[0, o : o + K, :]
                rmmw = rmm_ref[0, o : o + K, :]
                rgiw = rgi_ref[0, o : o + K, :]
                rdlw = rdl_ref[0, o : o + K, :]
                rsqw = rsq_ref[0, o : o + K, :]
            tbr = t_ref[1, j]
            mscr = jnp.where(rsqw == tbr, rmw, rmmw)
            mcandr = jnp.where(
                (ir >= 1) & jnp.logical_not(first), prev_r + mscr, neg
            )
            prev_dn = pltpu.roll(prev_r, 1, axis=0)
            prev_dn = jnp.where(d == 0, neg, prev_dn)
            dcandr = jnp.where(first, neg, prev_dn + rdlw)
            candr = jnp.maximum(mcandr, dcandr)
            candr = jnp.where(first & (ir == 0), 0.0, candr)
            candr = jnp.where(validr, candr, neg)
            gr = jnp.where((ir >= 1) & validr, rgiw, 0.0)
            Gr = _cumop_rev(gr, lambda a, b: a + b, K)
            Fr = Gr + _cumop_rev(candr - Gr, jnp.maximum, K)
            Fr = jnp.where(validr, Fr, neg)
            prev_r = Fr
            stage_r[c * K : (c + 1) * K, :] = Fr.astype(stage_r.dtype)

        fcarry[:] = prev_f
        rcarry[:] = prev_r

        dma = pltpu.make_async_copy(
            stage_f, band_f.at[pl.ds(jb * C * K, C * K), :], sem
        )
        dma.start()
        dma.wait()
        dma = pltpu.make_async_copy(
            stage_r, band_r.at[pl.ds(jb * C * K, C * K), :], sem
        )
        dma.start()
        dma.wait()
        if want_stats:
            dma = pltpu.make_async_copy(
                stage_mv, moves_any.at[pl.ds(jb * C * K, C * K), :], sem
            )
            dma.start()
            dma.wait()

    @pl.when(jnp.logical_not(phase1))
    def _():
        jb2 = (2 * n_steps - 1) - jb
        Wk = (C + 2) * K

        dma = pltpu.make_async_copy(
            band_f.at[pl.ds(jb2 * C * K, C * K), :], stage_f, sem
        )
        dma.start()
        dma.wait()
        # backward window: columns [jb2*C, jb2*C + C] of B live at
        # mirrored flat rows (tlen - j) * K + (K - 1 - S_l) + d; fetch
        # (C + 2) column blocks from the clamped base and realign with
        # one per-lane cyclic roll
        base_raw = (tlen - jb2 * C - C - 1) * K
        base = jnp.clip(base_raw, 0, n_steps * C * K - Wk)
        dma = pltpu.make_async_copy(
            band_r.at[pl.ds(base, Wk), :], stage_b, sem
        )
        dma.start()
        dma.wait()
        if want_stats:
            dma = pltpu.make_async_copy(
                moves_any.at[pl.ds(jb2 * C * K, C * K), :], stage_mv, sem
            )
            dma.start()
            dma.wait()

        S_l = dend + OFF  # slen - tlen + 2*OFF, per lane
        s_l = (K - 1) - S_l - (base - base_raw)
        t_l = jnp.mod(-s_l, Wk)[None, :]  # rolled[r] = win[(r + s_l) % Wk]
        rolled = stage_b[:]
        bit = 1
        while bit < Wk:
            rcand = pltpu.roll(rolled, bit, axis=0)
            rolled = jnp.where((t_l & bit) != 0, rcand, rolled)
            bit *= 2

        roff = roff_ref[0, 0, :]
        bw = bw_ref[0, 0, :]
        zero16 = jnp.full((ROWS - 9, LANES), 0.0, jnp.float32)
        v_off = jnp.maximum(slen - tlen, 0)
        zero_i = jnp.zeros((1, LANES), jnp.int32)

        if input_enc == "packed":
            # phase-2 re-read: the index maps park the forward table
            # refs on block jb2 here — decode once for the dense windows
            # and the fused stats read-base rows
            fmt_t, fmm_t, fgi_t, fdl_t, fsq_t = _decode(
                fmt_ref, fmm_ref, fgi_ref, fdl_ref, fsq_ref
            )

        def tab_win(lo, hi):
            """(sq, mt, mm, gi, dl) windows [lo, hi) of the decoded
            (packed) or raw (f32, zero-cast) forward block."""
            if input_enc == "packed":
                return (fsq_t[lo:hi, :], fmt_t[lo:hi, :], fmm_t[lo:hi, :],
                        fgi_t[lo:hi, :], fdl_t[lo:hi, :])
            return (fsq_ref[0, lo:hi, :], fmt_ref[0, lo:hi, :],
                    fmm_ref[0, lo:hi, :], fgi_ref[0, lo:hi, :],
                    fdl_ref[0, lo:hi, :])

        if want_stats:
            P = P_scr[:] > 0
            nerr = acc_scr[0:1, :]
            reached = acc_scr[1:2, :]

        # columns DESCEND: the fused stats sweep chains P toward j - 1
        # (the dense math is column-independent, so it rides along)
        for c in range(C - 1, -1, -1):
            j = jb2 * C + c

            # ---- dense all-edits column (dense_pallas._dense_kernel) -
            # load-wide: the band stage may be narrower (bf16); every
            # max-plus candidate and join below accumulates in f32
            A_j = stage_f[c * K : (c + 1) * K, :].astype(jnp.float32)
            B_j = rolled[(C + 1 - c) * K : (C + 2 - c) * K, :].astype(
                jnp.float32
            )
            B_n = rolled[(C - c) * K : (C + 1 - c) * K, :].astype(
                jnp.float32
            )

            A_up = pltpu.roll(A_j, K - 1, axis=0)
            A_up = jnp.where(d == K - 1, neg, A_up)
            A_dn = pltpu.roll(A_j, 1, axis=0)
            A_dn = jnp.where(d == 0, neg, A_dn)
            B_n_dn = pltpu.roll(B_n, 1, axis=0)
            B_n_dn = jnp.where(d == 0, neg, B_n_dn)

            jc = jnp.minimum(j + 1, tlen)
            rmin = jnp.maximum(0, jc - roff)
            rmax = jnp.minimum(jc + v_off + bw, slen)

            dele = jnp.max(A_j + B_n_dn, axis=0, keepdims=True)

            def edit_scores(i, sq, mt, mm, gi, dl, m_src, d_src, B_join):
                valid = (i >= rmin[None, :]) & (i <= rmax[None, :])
                dcand = d_src + dl
                g = jnp.where((i >= 1) & valid, gi, 0.0)
                G = _cumop(g, lambda a, b: a + b, K)
                outs = []
                for b in range(4):
                    msc = jnp.where(sq == b, mt, mm)
                    mcand = jnp.where(i >= 1, m_src + msc, neg)
                    cand = jnp.where(valid, jnp.maximum(mcand, dcand), neg)
                    NC = G + _cumop(cand - G, jnp.maximum, K)
                    NC = jnp.where(valid, NC, neg)
                    outs.append(jnp.max(NC + B_join, axis=0, keepdims=True))
                return outs

            subs = edit_scores(
                d + (j + 1 - OFF), *tab_win(c + 1, c + 1 + K),
                A_j, A_up, B_n,
            )
            insr = edit_scores(
                d + (j - OFF), *tab_win(c, c + K),
                A_dn, A_j, B_j,
            )
            dense_ref[0, 0, c * ROWS : (c + 1) * ROWS, :] = jnp.concatenate(
                [dele] + subs + insr + [zero16], axis=0
            )

            # ---- fused reverse stats column (stats_pallas) -----------
            if want_stats:
                mv = stage_mv[c * K : (c + 1) * K, :].astype(jnp.int32)
                if input_enc == "packed":
                    sb = fsq_t[c : c + K, :]
                else:
                    sb = fsq_ref[0, c : c + K, :]
                tb = t_ref[0, j]

                seed = P | ((j == tlen) & (d == dend[None, :]))
                ichain = mv == TRACE_INSERT

                ich_up = pltpu.roll(ichain.astype(jnp.float32), K - 1,
                                    axis=0)
                ich_up = jnp.where(d == K - 1, 0.0, ich_up)
                gs = jnp.where(ich_up > 0, 0.0, -1e6)
                cands = jnp.where(seed, 0.0, -1e12)
                Gs = _cumop_rev(gs, lambda a, b: a + b, K)
                Fs = Gs + _cumop_rev(cands - Gs, jnp.maximum, K)
                on = Fs > -1e5

                is_m = on & (mv == TRACE_MATCH)
                is_i = on & ichain
                is_d = on & (mv == TRACE_DELETE)
                mism = is_m & (sb != tb)
                err = mism | is_i | is_d
                nerr = nerr + jnp.sum(err.astype(jnp.int32), axis=0,
                                      keepdims=True, dtype=jnp.int32)
                r0 = jnp.sum(
                    (on & (d == OFF)).astype(jnp.int32), axis=0,
                    keepdims=True, dtype=jnp.int32,
                )
                reached = reached | jnp.where(j == 0, r0, zero_i)

                def any_row(m):
                    return jnp.max(m.astype(jnp.float32), axis=0,
                                   keepdims=True)

                rows = (
                    [any_row(mism & (sb == b)) for b in range(4)]
                    + [any_row(is_i & (sb == b)) for b in range(4)]
                    + [any_row(is_d),
                       jnp.zeros((ROWS - 9, LANES), jnp.float32)]
                )
                tiles_ref[c * ROWS : (c + 1) * ROWS, :] = jnp.concatenate(
                    rows, axis=0
                )

                is_d_dn = pltpu.roll(is_d.astype(jnp.float32), 1, axis=0)
                is_d_dn = jnp.where(d == 0, 0.0, is_d_dn)
                P = is_m | (is_d_dn > 0)

        if want_stats:
            P_scr[:] = P.astype(jnp.int32)
            acc_new = jnp.concatenate(
                [nerr, reached,
                 jnp.zeros((CARRY_ROWS - 2, LANES), jnp.int32)],
                axis=0,
            )
            acc_scr[:] = acc_new

            @pl.when(jb == 2 * n_steps - 1)
            def _():
                acc_ref[:] = acc_new

    @pl.when(jb == 2 * n_steps - 1)
    def _():
        score_ref[:] = acc_score[:]


@functools.partial(
    jax.jit,
    static_argnames=("K", "T1p", "C", "want_stats", "interpret",
                     "band_dtype", "input_enc"),
)
def _mega_call(
    tlen_s,  # [1, 1] int32
    off_s,  # [1, 1] int32
    t_cols,  # [2, T1p] int32
    meta6,  # [6, 1, Npad] int32: slen, delta, nd, dend, roff, bw
    fwd_tabs,  # 5 x [n_steps, CB, Npad]
    rev_tabs,  # 5 x [n_steps, CB, Npad] mirrored
    K: int,
    T1p: int,
    C: int,
    want_stats: bool = False,
    interpret: bool = False,
    band_dtype: str = "f32",
    input_enc: str = "f32",
    qmeta=None,  # [8, 1, Npad] f32 dequant rows (packed enc only)
):
    n_steps = T1p // C
    Npad = meta6.shape[2]
    NB = Npad // LANES
    CB = C + K
    band_dt = band_store_dtype(band_dtype)
    grid = (NB, 2 * n_steps)

    def smem_spec():
        return pl.BlockSpec(
            (1, 1), lambda nb, jb: (0, 0), memory_space=pltpu.SMEM
        )

    def lane_spec():
        return pl.BlockSpec(
            (1, 1, LANES), lambda nb, jb: (0, 0, nb),
            memory_space=pltpu.VMEM,
        )

    def fwd_tab_spec(rows=CB):
        # phase 1 streams block jb (the fill), phase 2 re-reads block
        # jb2 (the dense windows + the stats read-base table); the
        # packed code table carries CBp word rows instead of CB
        return pl.BlockSpec(
            (1, rows, LANES),
            lambda nb, jb, n=n_steps: (
                jnp.where(jb < n, jb, 2 * n - 1 - jb), 0, nb
            ),
            memory_space=pltpu.VMEM,
        )

    def rev_tab_spec(rows=CB):
        # phase-1 only; parked on the last fill block through phase 2
        return pl.BlockSpec(
            (1, rows, LANES),
            lambda nb, jb, n=n_steps: (
                jnp.where(jb < n, jb, n - 1), 0, nb
            ),
            memory_space=pltpu.VMEM,
        )

    in_specs = (
        [smem_spec(), smem_spec(),
         pl.BlockSpec((2, T1p), lambda nb, jb: (0, 0),
                      memory_space=pltpu.SMEM)]
        + [lane_spec() for _ in range(6)]
        + [fwd_tab_spec() for _ in range(4)]
        + [fwd_tab_spec(rows=fwd_tabs[4].shape[1])]
        + [rev_tab_spec() for _ in range(4)]
        + [rev_tab_spec(rows=rev_tabs[4].shape[1])]
    )

    # phase-1 steps park the write-once outputs on the block phase 2
    # touches first (jb2 = n_steps - 1): the parked garbage is
    # overwritten in place before any block switch flushes it
    out_specs = [
        pl.BlockSpec(
            (1, 1, C * ROWS, LANES),
            lambda nb, jb, n=n_steps: (
                nb, jnp.where(jb < n, n - 1, 2 * n - 1 - jb), 0, 0
            ),
            memory_space=pltpu.VMEM,
        ),
        pl.BlockSpec(
            (1, LANES), lambda nb, jb: (0, nb), memory_space=pltpu.VMEM
        ),
    ]
    out_shape = [
        jax.ShapeDtypeStruct((NB, n_steps, C * ROWS, LANES), jnp.float32),
        jax.ShapeDtypeStruct((1, NB * LANES), jnp.float32),
    ]
    if want_stats:
        out_specs.append(
            pl.BlockSpec(
                (C * ROWS, LANES),
                lambda nb, jb, n=n_steps: (
                    jnp.where(jb < n, n - 1, 2 * n - 1 - jb), nb
                ),
                memory_space=pltpu.VMEM,
            )
        )
        out_shape.append(
            jax.ShapeDtypeStruct((n_steps * C * ROWS, NB * LANES),
                                 jnp.float32)
        )
        out_specs.append(
            pl.BlockSpec(
                (CARRY_ROWS, LANES), lambda nb, jb: (0, nb),
                memory_space=pltpu.VMEM,
            )
        )
        out_shape.append(
            jax.ShapeDtypeStruct((CARRY_ROWS, NB * LANES), jnp.int32)
        )

    scratch = [
        # the launch-private band round trip — the megakernel's dominant
        # byte term — carries the band-store dtype end to end
        pltpu.ANY((T1p * K, LANES), band_dt),  # band_f
        pltpu.ANY((T1p * K, LANES), band_dt),  # band_r (mirrored)
        pltpu.VMEM((C * K, LANES), band_dt),  # stage_f
        pltpu.VMEM((C * K, LANES), band_dt),  # stage_r
        pltpu.VMEM(((C + 2) * K, LANES), band_dt),  # stage_b
        pltpu.SemaphoreType.DMA,
        pltpu.VMEM((K, LANES), jnp.float32),  # fcarry
        pltpu.VMEM((K, LANES), jnp.float32),  # rcarry
        pltpu.VMEM((1, LANES), jnp.float32),  # acc_score
    ]
    if want_stats:
        scratch += [
            pltpu.ANY((T1p * K, LANES), jnp.int32),  # moves
            pltpu.VMEM((C * K, LANES), jnp.int32),  # stage_mv
            pltpu.VMEM((K, LANES), jnp.int32),  # P_scr
            pltpu.VMEM((CARRY_ROWS, LANES), jnp.int32),  # acc_scr
        ]

    mt, mm, gi, dl, sq = fwd_tabs
    rmt, rmm, rgi, rdl, rsq = rev_tabs
    args = [
        tlen_s, off_s, t_cols,
        meta6[0][None], meta6[1][None], meta6[2][None],
        meta6[3][None], meta6[4][None], meta6[5][None],
        mt, mm, gi, dl, sq, rmt, rmm, rgi, rdl, rsq,
    ]
    if input_enc == "packed":
        in_specs.append(
            pl.BlockSpec(
                (8, 1, LANES), lambda nb, jb: (0, 0, nb),
                memory_space=pltpu.VMEM,
            )
        )
        args.append(qmeta)
    return pl.pallas_call(
        functools.partial(
            _mega_kernel, K=K, C=C, n_steps=n_steps,
            want_stats=want_stats, band_neg=neg_inf_for(band_dt),
            input_enc=input_enc,
        ),
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=scratch,
        compiler_params=_CompilerParams(
            # lane blocks share the scratch carry: both axes sequential
            dimension_semantics=("arbitrary", "arbitrary"),
        ),
        interpret=interpret,
    )(*args)


def fused_tables_mega(
    template,  # int8 [Tmax]
    tlen,  # int32
    bufs: FillBuffers,
    geom: BandGeometry,
    weights,  # [N] f32
    K: int,
    T1p: int,
    C: int,
    want_stats: bool = False,
    off_override=None,
    interpret: bool = False,
    band_dtype: str = "f32",
    input_enc: str = "f32",
):
    """One fused consensus step in a SINGLE Pallas launch — same dict
    contract as dense_pallas.fused_tables_pallas (minus want_moves,
    which declines to the split path in fused_tables_auto).

    The kernel body emits PER-LANE values; every lane-axis reduction
    lives in this epilogue and runs through the shared segment-reduce
    helpers (ops.fused.segment_masked_sum_lanes / _union_max_lanes) in
    their trivial single-segment form — one segment spanning all lanes
    reduces with the exact formula and lane order of the unsegmented
    sum, so routing through the helpers is bit-identical. Multi-segment
    launches decline here (mega_segment_eligible): the kernel streams
    one template's columns, so packed multi-template blocks run the XLA
    segmented step instead."""
    from .fused import (
        segment_masked_sum_lanes,
        segment_union_max_lanes,
        segment_weights,
    )

    Npad = bufs.seq_T.shape[1]
    NB = Npad // LANES
    n_steps = T1p // C
    prep = prepare_fused(template, tlen, bufs, geom, K, T1p, C,
                         off_override=off_override, input_enc=input_enc)
    outs = _mega_call(
        prep["tlen_s"], prep["off_s"], prep["t_cols"], prep["meta6"],
        prep["fwd_tabs"], prep["rev_tabs"],
        K=K, T1p=T1p, C=C, want_stats=want_stats, interpret=interpret,
        band_dtype=band_dtype, input_enc=input_enc, qmeta=prep["qmeta"],
    )
    outs = list(outs)
    dense_out = outs.pop(0)
    scores2 = outs.pop(0)

    # identical epilogue to dense_call + dense_tables_pallas /
    # fused_tables_pallas: same reshape, same masked weighted reduction
    per_lane = dense_out.reshape(NB, n_steps, C, ROWS, LANES)
    per_lane = per_lane.transpose(1, 2, 3, 0, 4).reshape(
        T1p, ROWS, NB * LANES
    )
    w = _pad_lanes(weights.astype(jnp.float32), Npad)
    seg0 = jnp.zeros((Npad,), jnp.int32)  # one segment = all lanes
    seg_w = segment_weights(seg0, w, 1)
    tables = segment_masked_sum_lanes(seg_w, per_lane)[0]
    scores = scores2[0, :Npad]
    total = segment_masked_sum_lanes(seg_w, scores)[0]
    out = {
        "total": total, "scores": scores,
        "sub": tables[:, 1:5], "ins": tables[:, 5:9], "del": tables[:, 0],
    }
    if want_stats:
        tiles = outs.pop(0)
        acc = outs.pop(0)
        T1 = template.shape[0] + 1
        out["n_errors"] = _finish_nerr(acc, Npad)
        um = segment_union_max_lanes(
            seg0, tiles.reshape(T1p, ROWS, NB * LANES), 1
        )[0][:T1]
        out["edits"] = _edits_from_union(um > 0.0)
    return out


def fused_tables_auto(
    template,
    tlen,
    bufs: FillBuffers,
    geom: BandGeometry,
    weights,
    K: int,
    T1p: int,
    C: int,
    want_stats: bool = False,
    want_moves: bool = False,
    off_override=None,
    slen_min=None,
    interpret: bool = False,
    impl=None,
    vmem_budget=None,
    band_dtype: str = "f32",
    input_enc: str = "f32",
):
    """Route one fused step to the megakernel or the 3-launch split
    oracle (same dict contract either way, plus out["impl"] naming the
    path taken). ``impl`` overrides the env selector (pass the value
    resolved at dispatch-planning time so a jit trace cache keyed on it
    stays honest); ``vmem_budget`` overrides the planner budget (the
    decline guard test shrinks it)."""
    sel, _ = select_impl(T1p, K, want_stats=want_stats,
                         want_moves=want_moves, vmem_budget=vmem_budget,
                         impl=impl)
    if sel == "mega":
        Cm = mega_cols(T1p, K, want_stats=want_stats, interpret=interpret,
                       vmem_budget=vmem_budget)
        out = fused_tables_mega(
            template, tlen, bufs, geom, weights, K, T1p, Cm,
            want_stats=want_stats, off_override=off_override,
            interpret=interpret, band_dtype=band_dtype,
            input_enc=input_enc,
        )
    else:
        out = fused_tables_pallas(
            template, tlen, bufs, geom, weights, K, T1p, C,
            want_stats=want_stats, want_moves=want_moves,
            off_override=off_override, slen_min=slen_min,
            interpret=interpret, band_dtype=band_dtype,
            input_enc=input_enc,
        )
    out["impl"] = sel
    return out


@functools.partial(
    jax.jit,
    static_argnames=("K", "T1p", "C", "want_stats", "interpret",
                     "band_dtype", "input_enc"),
)
def _fused_step_mega(
    template, tlen, bufs: FillBuffers, geom: BandGeometry, weights,
    K: int, T1p: int, C: int,
    want_stats: bool = False, interpret: bool = False,
    band_dtype: str = "f32", input_enc: str = "f32",
):
    out = fused_tables_mega(
        template, tlen, bufs, geom, weights, K, T1p, C,
        want_stats=want_stats, interpret=interpret, band_dtype=band_dtype,
        input_enc=input_enc,
    )
    return jnp.concatenate(pack_parts(out, want_stats))


def fused_step_auto(
    template, tlen, bufs: FillBuffers, geom: BandGeometry, weights,
    K: int, T1p: int, C: int,
    want_stats: bool = False, want_moves: bool = False,
    interpret: bool = False, impl=None, band_dtype: str = "f32",
    input_enc: str = "f32",
):
    """Packed-single-fetch dispatcher (dense_pallas.fused_step_pallas's
    contract: (packed, moves-or-None)) routing to the megakernel when
    eligible. The impl decision happens OUTSIDE the jitted bodies, so
    flipping RIFRAF_TPU_FUSED_IMPL between calls takes effect without
    poisoning a trace cache."""
    from .dense_pallas import fused_step_pallas

    sel, _ = select_impl(T1p, K, want_stats=want_stats,
                         want_moves=want_moves, impl=impl)
    if sel == "mega":
        Cm = mega_cols(T1p, K, want_stats=want_stats, interpret=interpret)
        packed = _fused_step_mega(
            template, tlen, bufs, geom, weights, K, T1p, Cm,
            want_stats=want_stats, interpret=interpret,
            band_dtype=band_dtype, input_enc=input_enc,
        )
        return packed, None
    return fused_step_pallas(
        template, tlen, bufs, geom, weights, K, T1p, C,
        want_stats=want_stats, want_moves=want_moves, interpret=interpret,
        band_dtype=band_dtype, input_enc=input_enc,
    )
