"""On-core Pallas reverse-sweep traceback statistics.

Third Pallas kernel of the engine: consumes the fill kernel's in-kernel
move codes DIRECTLY in the uniform-frame band layout (flat
[T1p * K, lanes], reads on lanes — the exact buffer `_fill_call`
emits) and computes, in one sequential sweep over column blocks from
the last template column down to column 0:

- per-lane alignment error counts of the optimal path
  (count_errors, align.jl:240-250) and the path-completeness flag;
- per-column single-base-edit indicators (moves_to_proposals,
  model.jl:458-480) emitted as small [16, 128] tiles per column — the
  same output shape as the dense kernel's join maxima, reduced over
  lanes in XLA.

This replaces the XLA moves scan (align_jax._traceback_stats_one via
dense_pallas.stats_from_moves) on the Pallas path: that scan re-reads
the move band through an unrolled lax.scan at ~3x the fill kernel's
wall clock (round-5 roofline: 30 ms stats vs 10 ms fill at
1 kb x 2048) because each unrolled column pays XLA op overhead on [K]
vectors. Here the sweep is straight-line code on [K, 128] tiles with
the same grid/blocking as the fill — the move band streams through
VMEM once, so the stats step is bounded by its bytes, not its columns.

Recurrence (one column j, all lanes):

  seed[d]   = P[d] | (j == tlen & d == dend)        # end-cell seed
  on        = insert-chain closure of seed           # see below
  is_m/i/d  = on & (move == MATCH / INSERT / DELETE)
  nerr     += sum_d(mismatch | is_i | is_d)
  P'[d]     = is_m[d] | is_d[d-1]                    # col j-1 seeds

The insert-chain closure (on-path membership propagates DOWNWARD in d
through runs of INSERT moves: on[d] = seed[d] | (on[d+1] & ins[d+1]))
uses the same max-plus closed form as the XLA oracle
(align_jax._resolve_insert_chain) but WITHOUT the axis flips: with
g[d] = 0 if ins[d+1] else -1e6 and cand[d] = 0 if seed[d] else -1e12,

  F = Gs + suffix_cummax(cand - Gs),   Gs = suffix_cumsum(g)

and on = F > -1e5. Suffix scans run along sublanes via log-step rolls
(`_cumop_rev`, the mirror of fill_pallas._cumop). Bit-identity with the
oracle holds because every partial sum is an exact small multiple of
1e6 in f32 (path lengths <= K <= 1024), so the scan association order
cannot perturb any value, and the downstream outputs are pure booleans
/ int32 counts of those booleans (tests/test_stats_pallas.py pins the
equality across geometries in interpret mode).

The kernel accepts the move band as int32 (the fill kernel's raw
output — the fused path feeds it straight through, no int8 round trip)
or int8 (the panel path's accumulated band; widened on load). Panel
launches chain (P, nerr, reached) through a [K + 8, lanes] carry, run
in REVERSE panel order.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# pallas renamed TPUCompilerParams -> CompilerParams across jax releases;
# accept either so the kernel builds on both sides of the rename.
_CompilerParams = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams

from .align_np import TRACE_DELETE, TRACE_INSERT, TRACE_MATCH
from .encoding import unpack_codes
from .fill_pallas import LANES

ROWS = 16  # per-column indicator tile rows (9 used; dense_pallas.ROWS)
CARRY_ROWS = 8  # accumulator rows chained between panels (2 used)


def use_pallas_stats() -> bool:
    """Env opt-out: RIFRAF_TPU_STATS_IMPL=xla routes the Pallas paths
    back through the XLA moves scan (stats_from_moves). Read at trace
    time by the jitted wrappers."""
    return os.environ.get("RIFRAF_TPU_STATS_IMPL", "pallas") != "xla"


def _cumop_rev(x, op, K: int):
    """Inclusive SUFFIX scan along sublanes (axis 0) via log-step
    doubling — the mirror of fill_pallas._cumop: after the pass,
    x[d] = op(x[d], x[d+1], ..., x[K-1])."""
    s = 1
    while s < K:
        # roll(x, K - s)[d] = x[(d + s) mod K]
        shifted = pltpu.roll(x, K - s, axis=0)
        idx = jax.lax.broadcasted_iota(jnp.int32, x.shape, 0)
        x = jnp.where(idx < K - s, op(x, shifted), x)
        s *= 2
    return x


def _stats_kernel(
    # SMEM inputs
    tlen_ref,  # [1, 1] true template length
    off_ref,  # [1, 1] uniform frame offset OFF
    col0_ref,  # [1, 1] global column of this launch's first column
    t_ref,  # [1, n_cols] template codes (LOCAL columns)
    # per-lane metadata, [1, 1, 128] block
    dend_ref,  # traceback end row dend = slen - tlen + OFF
    # band-layout blocks
    mv_ref,  # [C * K, 128] move codes, block jb_rev (int32 or int8)
    sq_ref,  # [1, CB, 128] blocked read-base table (fill layout;
    #          packed enc: [1, CBp, 128] int32 words, ops.encoding)
    *refs,
    K: int,
    C: int,
    want_tiles: bool = True,
    has_carry: bool = False,
    want_edge: bool = False,
    input_enc: str = "f32",
):
    refs = list(refs)
    # want_edge appends the per-lane TRUE band limits (delta, nd) after
    # the read-base table: the uniform frame widens every lane to the
    # shared K, so the frame rows 0 / K-1 are NOT the band edges
    delta_ref = refs.pop(0) if want_edge else None
    nd_ref = refs.pop(0) if want_edge else None
    carry_in = refs.pop(0) if has_carry else None
    tiles_ref = refs.pop(0)
    acc_ref = refs.pop(0)
    carry_out = refs.pop(0) if has_carry else None
    P_scr, acc_scr = refs

    jb = pl.program_id(1)
    n_steps = pl.num_programs(1)
    tlen = tlen_ref[0, 0]
    OFF = off_ref[0, 0]
    col0 = col0_ref[0, 0]
    # the grid's sequential axis runs FORWARD while the index maps feed
    # blocks in reverse; block jb holds columns of block jb_rev
    jb_rev = n_steps - 1 - jb

    d = jax.lax.broadcasted_iota(jnp.int32, (K, LANES), 0)
    dend = dend_ref[0, 0, :]
    zero_i = jnp.zeros((1, LANES), jnp.int32)

    @pl.when(jb == 0)
    def _():
        if has_carry:
            P_scr[:] = carry_in[0:K, :]
            acc_scr[:] = carry_in[K : K + CARRY_ROWS, :]
        else:
            P_scr[:] = jnp.zeros((K, LANES), jnp.int32)
            acc_scr[:] = jnp.zeros((CARRY_ROWS, LANES), jnp.int32)

    P = P_scr[:] > 0
    nerr = acc_scr[0:1, :]
    reached = acc_scr[1:2, :]
    ehits = acc_scr[2:3, :]
    if want_edge:
        edge_lo = delta_ref[0, 0, :][None, :]
        edge_hi = (delta_ref[0, 0, :] + nd_ref[0, 0, :] - 1)[None, :]

    if input_enc == "packed":
        # decode the whole code block once per grid step; the sweep only
        # compares codes under the on-path masks, so pad garbage (codes
        # taken mod 4) never reaches an output
        sq_t = unpack_codes(sq_ref[0])

    # columns DESCEND within the block (the sweep chains P toward j-1)
    for c in range(C - 1, -1, -1):
        j = col0 + jb_rev * C + c
        mv = mv_ref[c * K : (c + 1) * K, :].astype(jnp.int32)
        if input_enc == "packed":
            sb = sq_t[c : c + K, :]
        else:
            sb = sq_ref[0, c : c + K, :]  # = seq[i-1], i = d + j - OFF
        tb = t_ref[0, jb_rev * C + c]

        seed = P | ((j == tlen) & (d == dend[None, :]))
        ichain = mv == TRACE_INSERT

        # on-path closure: on[d] = seed[d] | (on[d+1] & ichain[d+1]),
        # max-plus closed form on the un-flipped axis (module docstring)
        ich_up = pltpu.roll(ichain.astype(jnp.float32), K - 1, axis=0)
        ich_up = jnp.where(d == K - 1, 0.0, ich_up)
        g = jnp.where(ich_up > 0, 0.0, -1e6)
        cand = jnp.where(seed, 0.0, -1e12)
        Gs = _cumop_rev(g, lambda a, b: a + b, K)
        F = Gs + _cumop_rev(cand - Gs, jnp.maximum, K)
        on = F > -1e5

        is_m = on & (mv == TRACE_MATCH)
        is_i = on & ichain
        is_d = on & (mv == TRACE_DELETE)
        mism = is_m & (sb != tb)
        err = mism | is_i | is_d
        # dtype pinned: under x64, jnp.sum would promote int32 to int64
        # and poison the int32 accumulator scratch
        nerr = nerr + jnp.sum(err.astype(jnp.int32), axis=0,
                              keepdims=True, dtype=jnp.int32)
        # a complete path reaches cell (0, 0) = data row OFF of column 0
        r0 = jnp.sum(
            (on & (d == OFF)).astype(jnp.int32), axis=0, keepdims=True,
            dtype=jnp.int32,
        )
        reached = reached | jnp.where(j == 0, r0, zero_i)

        if want_edge:
            # on-path cells pinned to a band-limit row: the adaptive
            # growth frontier signal (one count per column crossed)
            hit = on & ((d == edge_lo) | (d == edge_hi))
            ehits = ehits + jnp.sum(hit.astype(jnp.int32), axis=0,
                                    keepdims=True, dtype=jnp.int32)

        if want_tiles:
            def any_row(m):
                return jnp.max(m.astype(jnp.float32), axis=0, keepdims=True)

            rows = (
                [any_row(mism & (sb == b)) for b in range(4)]
                + [any_row(is_i & (sb == b)) for b in range(4)]
                + [any_row(is_d),
                   jnp.zeros((ROWS - 9, LANES), jnp.float32)]
            )
            tiles_ref[c * ROWS : (c + 1) * ROWS, :] = jnp.concatenate(
                rows, axis=0
            )

        # seeds for column j - 1: match pred at the same data row,
        # delete pred one data row down
        is_d_dn = pltpu.roll(is_d.astype(jnp.float32), 1, axis=0)
        is_d_dn = jnp.where(d == 0, 0.0, is_d_dn)
        P = is_m | (is_d_dn > 0)

    P_scr[:] = P.astype(jnp.int32)
    # row 2 carries the edge-hit count; it stays all-zero (bit-identical
    # to the historical layout) unless want_edge accumulated into it
    acc_new = jnp.concatenate(
        [nerr, reached, ehits,
         jnp.zeros((CARRY_ROWS - 3, LANES), jnp.int32)],
        axis=0,
    )
    acc_scr[:] = acc_new

    @pl.when(jb == n_steps - 1)
    def _():
        acc_ref[:] = acc_new
        if has_carry:
            carry_out[0:K, :] = P.astype(jnp.int32)
            carry_out[K : K + CARRY_ROWS, :] = acc_new


@functools.partial(
    jax.jit,
    static_argnames=("K", "T1p", "NB", "C", "want_tiles", "interpret",
                     "want_edge", "input_enc"),
)
def _stats_call(
    tlen_s,  # [1, 1] int32
    off_s,  # [1, 1] int32
    t_cols,  # [1, T1p] int32 template columns (to_cols layout)
    dend,  # [1, nlanes] int32 (>= NB * 128 lanes; extras ignored)
    mv_flat,  # [T1p * K, nlanes] int32 or int8 move band (fill layout)
    sq,  # [n_steps, CB, nlanes] blocked read-base table (fill layout)
    K: int,
    T1p: int,
    NB: int,
    C: int,
    want_tiles: bool = True,
    interpret: bool = False,
    col0=None,  # [1, 1] int32 global first column (panel launches)
    carry_in=None,  # [K + 8, NB*128] int32 previous panel's state
    want_edge: bool = False,
    delta=None,  # [1, nlanes] int32 per-lane frame shift (want_edge)
    ndv=None,  # [1, nlanes] int32 per-lane TRUE band height (want_edge)
    input_enc: str = "f32",
):
    """One reverse stats sweep over ``T1p`` columns and ``NB`` forward
    lane blocks (``mv_flat``/``sq``/``dend`` may carry extra reversed
    lanes — the lane-block index never touches them). Returns
    (tiles [T1p * 16, NB*128] f32 — or a [8, NB*128] dummy when
    ``want_tiles`` is False —, acc [8, NB*128] int32 with rows
    0 = n_errors and 1 = reached-origin, carry_out when chaining)."""
    n_steps = T1p // C
    CB = sq.shape[1]
    has_carry = carry_in is not None
    if col0 is None:
        col0 = jnp.zeros((1, 1), jnp.int32)

    grid = (NB, n_steps)

    def smem_spec():
        return pl.BlockSpec(
            (1, 1), lambda nb, jb: (0, 0), memory_space=pltpu.SMEM
        )

    in_specs = [
        smem_spec(),  # tlen
        smem_spec(),  # off
        smem_spec(),  # col0
        pl.BlockSpec(
            (1, t_cols.shape[1]), lambda nb, jb: (0, 0),
            memory_space=pltpu.SMEM,
        ),
        pl.BlockSpec(
            (1, 1, LANES), lambda nb, jb: (0, 0, nb),
            memory_space=pltpu.VMEM,
        ),  # dend
        # REVERSE feed: sequential step jb reads column block
        # n_steps - 1 - jb
        pl.BlockSpec(
            (C * K, LANES),
            lambda nb, jb, n=n_steps: (n - 1 - jb, nb),
            memory_space=pltpu.VMEM,
        ),  # moves
        pl.BlockSpec(
            (1, CB, LANES),
            lambda nb, jb, n=n_steps: (n - 1 - jb, 0, nb),
            memory_space=pltpu.VMEM,
        ),  # sq
    ]
    args = [
        tlen_s, off_s, jnp.asarray(col0, jnp.int32).reshape(1, 1),
        t_cols, dend[None], mv_flat, sq,
    ]
    if want_edge:
        lane_spec = pl.BlockSpec(
            (1, 1, LANES), lambda nb, jb: (0, 0, nb),
            memory_space=pltpu.VMEM,
        )
        in_specs += [lane_spec, lane_spec]
        args += [delta[None], ndv[None]]
    if has_carry:
        in_specs.append(
            pl.BlockSpec(
                (K + CARRY_ROWS, LANES), lambda nb, jb: (0, nb),
                memory_space=pltpu.VMEM,
            )
        )
        args.append(carry_in)

    if want_tiles:
        tiles_spec = pl.BlockSpec(
            (C * ROWS, LANES),
            lambda nb, jb, n=n_steps: (n - 1 - jb, nb),
            memory_space=pltpu.VMEM,
        )
        tiles_shape = jax.ShapeDtypeStruct(
            (n_steps * C * ROWS, NB * LANES), jnp.float32
        )
    else:
        # dummy block every step revisits; never read back
        tiles_spec = pl.BlockSpec(
            (8, LANES), lambda nb, jb: (0, nb), memory_space=pltpu.VMEM
        )
        tiles_shape = jax.ShapeDtypeStruct((8, NB * LANES), jnp.float32)

    out_specs = [
        tiles_spec,
        pl.BlockSpec(
            (CARRY_ROWS, LANES), lambda nb, jb: (0, nb),
            memory_space=pltpu.VMEM,
        ),
    ]
    out_shape = [
        tiles_shape,
        jax.ShapeDtypeStruct((CARRY_ROWS, NB * LANES), jnp.int32),
    ]
    if has_carry:
        out_specs.append(
            pl.BlockSpec(
                (K + CARRY_ROWS, LANES), lambda nb, jb: (0, nb),
                memory_space=pltpu.VMEM,
            )
        )
        out_shape.append(
            jax.ShapeDtypeStruct((K + CARRY_ROWS, NB * LANES), jnp.int32)
        )

    outs = pl.pallas_call(
        functools.partial(
            _stats_kernel, K=K, C=C, want_tiles=want_tiles,
            has_carry=has_carry, want_edge=want_edge,
            input_enc=input_enc,
        ),
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((K, LANES), jnp.int32),
            pltpu.VMEM((CARRY_ROWS, LANES), jnp.int32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(*args)
    outs = list(outs)
    tiles = outs.pop(0)
    acc = outs.pop(0)
    if has_carry:
        return tiles, acc, outs.pop(0)
    return tiles, acc


def _edits_from_union(um_bool):
    """[T1, 16] lane-union indicators -> the [T1, 9] edits table in
    stats_from_moves's row convention: column jc emits substitutions /
    deletions at template position jc - 1, insertions at jc."""
    sub_any = um_bool[:, 0:4]
    ins_any = um_bool[:, 4:8]
    del_any = um_bool[:, 8]
    zrow = jnp.zeros((1, 4), bool)
    sub_t = jnp.concatenate([sub_any[1:], zrow])
    del_t = jnp.concatenate([del_any[1:], jnp.zeros((1,), bool)])
    return jnp.concatenate(
        [sub_t, ins_any, del_t[:, None]], axis=1
    ).astype(jnp.int8)


def _finish_nerr(acc, Npad: int):
    """Per-lane error counts; incomplete paths (never reached the
    origin cell) report -1, matching count_errors on the XLA path."""
    return jnp.where(acc[1, :Npad] > 0, acc[0, :Npad], -1).astype(
        jnp.int32
    )


def _finish_edge(acc, Npad: int):
    """Per-lane band-edge hit counts (acc row 2); incomplete paths
    report 0 — they never trigger growth anyway (n_errors = -1 sits
    below every threshold), matching the XLA want_edge path's contract
    that the signal only matters on complete, flagged reads."""
    return jnp.where(acc[1, :Npad] > 0, acc[2, :Npad], 0).astype(
        jnp.int32
    )


def traceback_stats_pallas(
    prep: dict,  # prepare_fill output (tlen_s/off_s/t_cols/meta/fwd_tabs)
    mv_flat,  # [T1p * K, nlanes] int32 move band straight from _fill_call
    K: int,
    T1p: int,
    C: int,
    Npad: int,
    T1: int,  # template length + 1 (sizes the edits table)
    want_edits: bool = True,
    interpret: bool = False,
    want_edge: bool = False,
    input_enc: str = "f32",
):
    """Stats for a single-launch fill: reuses the fill's prepared
    inputs verbatim (same C, same blocked read-base table, dend from the
    same meta — so the sweep sees exactly the frame the moves were
    recorded in; packed enc reuses the fill's packed code words, no
    qmeta — stats only reads codes). Returns (n_errors [Npad] int32,
    edits [T1, 9] int8 or None), plus a trailing (edge_hits [Npad]
    int32) when ``want_edge`` (per-lane true band limits ride in from
    the same meta rows the fill masked with)."""
    NB = Npad // LANES
    kw = {}
    if want_edge:
        kw = dict(
            want_edge=True, delta=prep["meta"][1], ndv=prep["meta"][2],
        )
    tiles, acc = _stats_call(
        prep["tlen_s"], prep["off_s"], prep["t_cols"][:1], prep["meta"][3],
        mv_flat, prep["fwd_tabs"][4],
        K=K, T1p=T1p, NB=NB, C=C, want_tiles=want_edits,
        interpret=interpret, input_enc=input_enc, **kw,
    )
    nerr = _finish_nerr(acc, Npad)
    edits = None
    if want_edits:
        um = jnp.max(tiles.reshape(T1p, ROWS, NB * LANES), axis=2)[:T1]
        edits = _edits_from_union(um > 0.0)
    if want_edge:
        return nerr, edits, _finish_edge(acc, Npad)
    return nerr, edits


@functools.partial(
    jax.jit, static_argnames=("K", "P", "C", "NB", "interpret")
)
def _panel_stats(
    tlen_s, off_s, dend, placed_sq, tpl_cols, mv_buf, col0, carry,
    K: int, P: int, C: int, NB: int, interpret: bool = False,
):
    """One panel's reverse stats launch: slice the move buffer and the
    placed read-base buffer at col0 (the fill's panel windows), block
    the table, run the sweep with the chained carry. Returns
    (um [P, 16] lane-union indicators, acc, carry')."""
    from .fill_pallas import _block_tables

    CB = C + K
    n_steps = P // C
    c0 = jnp.asarray(col0, jnp.int32)
    mv_panel = jax.lax.dynamic_slice_in_dim(mv_buf, c0 * K, P * K, axis=0)
    sq_win = jax.lax.dynamic_slice_in_dim(placed_sq, c0, P + K, axis=0)
    sq = _block_tables(sq_win, n_steps, C, CB)
    t_cols = jax.lax.dynamic_slice_in_dim(tpl_cols, c0, P)[None]
    tiles, acc, carry2 = _stats_call(
        tlen_s, off_s, t_cols, dend, mv_panel, sq,
        K=K, T1p=P, NB=NB, C=C, want_tiles=True, interpret=interpret,
        col0=jnp.reshape(c0, (1, 1)), carry_in=carry,
    )
    # reduce over lanes per panel: keeps the transient per-column tile
    # store O(panel), same scaling as the dense kernel's panel slices
    um = jnp.max(tiles.reshape(P, ROWS, NB * LANES), axis=2)
    return um, acc, carry2


def traceback_stats_pallas_panels(
    prep: dict,  # prepare_fill_panels output
    mv_buf,  # [T1p_pad * K, Npad] int8 accumulated move band
    K: int,
    T1p_pad: int,
    P: int,
    C: int,
    Npad: int,
    T1: int,
    interpret: bool = False,
):
    """Stats for the panel-blocked path: panels sweep RIGHT-TO-LEFT
    (the traceback direction), chaining (P, n_errors, reached) through
    the [K + 8, Npad] carry; each panel's indicator tiles are reduced
    over lanes before the next panel runs. Returns
    (n_errors [Npad] int32, edits [T1, 9] int8)."""
    NB = Npad // LANES
    n_panels = T1p_pad // P
    carry = jnp.zeros((K + CARRY_ROWS, Npad), jnp.int32)
    ums = [None] * n_panels
    acc = None
    for p in range(n_panels - 1, -1, -1):
        um, acc, carry = _panel_stats(
            prep["tlen_s"], prep["off_s"], prep["meta"][3],
            prep["fwd_placed"][4], prep["tpl_cols"], mv_buf,
            jnp.int32(p * P), carry,
            K=K, P=P, C=C, NB=NB, interpret=interpret,
        )
        ums[p] = um
    nerr = _finish_nerr(acc, Npad)
    um_all = jnp.concatenate(ums, axis=0)[:T1]
    return nerr, _edits_from_union(um_all > 0.0)


def int8_moves_ok(K: int, C: int) -> bool:
    """int8 move-band blocks need (C * K) % 32 == 0 (the int8 sublane
    tile is 32 rows). The panel path checks this before routing its
    int8 buffer through the kernel; failures fall back to the XLA
    scan."""
    return (C * K) % 32 == 0
