"""On-core Pallas dense all-edits scorer over uniform-frame bands.

Companion to ops.fill_pallas: scores EVERY single-base edit (the
reference's O(band) rescoring trick, /root/reference/src/model.jl:242-285
+ util.jl:40-48, densified over all positions as in ops.proposal_dense)
directly from the fill kernel's native band layout — flat [T1p * K,
lanes] with reads on lanes — so the bands never get transposed,
flipped, or fetched. The XLA dense sweep costs ~135 ms at 1 kb x 256 on
the available TPU and the band-layout fix-ups another ~45 ms (round-4
profile); this kernel plus the in-jit backward alignment replaces both.

Backward-band alignment
-----------------------
The backward band is computed as the forward DP of the reversed problem
(fill_pallas). Its raw output ``Brev`` relates to the backward band by
``B[d, j] = Brev[S_k - d, tlen - j]`` with ``S_k = slen_k - tlen +
2*OFF``. The column remap is read-independent (flip + uniform roll);
the row remap splits into a uniform roll and per-lane residuals
``r_k = slen_k - min(slen)`` that are STATIC per batch — so
``backward_halo_blocks`` does the whole remap with in-block flips/rolls
plus one masked roll per DISTINCT residual (a handful at realistic
read-length spreads), one halo block at a time.

The dense kernel
----------------
Grid (read_blocks, column_blocks); per column j of a block, in VMEM:

- deletions: ``max_d(A[d, j] + B[d-1, j+1])`` (summax join, util.jl:40-48);
- substitutions at j: one recomputed column in frame j+1 from
  (A[:, j], A[d+1, j]) per base, joined with B[:, j+1];
- insertions after j: one recomputed column in frame j from
  (A[d-1, j], A[:, j]) per base, joined with B[:, j];

emitting PER-LANE join maxima as a [16, 128] tile per column (rows:
0 deletion, 1-4 substitution bases, 5-8 insertion bases, 9-15 zero
padding); the read-weighted reduction over lanes happens in XLA on
these small outputs. Row-range masks use each read's OWN band limits
(model.jl:263's row_range), not the uniform frame's — exactness vs the
reference is pinned by the oracle tests against ops.proposal_dense.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# pallas renamed TPUCompilerParams -> CompilerParams across jax releases;
# accept either so the kernel builds on both sides of the rename.
_CompilerParams = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams

from . import stats_pallas
from .align_jax import BandGeometry
from .encoding import check_input_enc, dequant_block, unpack_codes
from .fill_pallas import (
    LANES,
    NEG_INF,
    FillBuffers,
    _cumop,
    _pad_lanes,
)

ROWS = 16  # padded per-column output rows (9 used)


def backward_halo_blocks(Brev_flat, tlen, OFF, slen, K: int,
                         T1p: int, C: int, lane0: int = 0,
                         slen_min=None, jb0=0, n_blocks=None):
    """Backward-band alignment + halo blocking in ONE memory-lean pass.

    Produces the halo-blocked backward band [n_steps, (C+1)*K, Npad]
    directly from the raw reversed-problem band, one output block at a
    time (lax.map) so peak HBM stays O(block) instead of the full-band
    copy per flip/roll that the naive chain materializes (measured OOM
    at 2048 reads x 1 kb: ~17 roll intermediates of ~1 GB each).

    ``Brev_flat`` may carry extra lane blocks (e.g. the fill kernel's
    combined [.., 2*Npad] output); ``lane0`` selects where the reversed
    stream's lanes start. Output block jb holds B columns
    [jb*C, jb*C + C] with B[d, j] = Brev[S_k - d, tlen - j]; cells with
    j > tlen or rolled-in rows are garbage by contract (consumers mask
    by row range / join against A's NEG sentinel). ``slen_min``
    overrides the local minimum read length (any base works — the
    binary-decomposed per-lane rolls are self-consistent with whichever
    S_min base is used). ``jb0``/``n_blocks``
    restrict the output to block rows [jb0, jb0 + n_blocks) — the
    panel-mode fill processes one template panel at a time."""
    Npad = slen.shape[0]
    n_steps = T1p // C
    B3 = Brev_flat.reshape(T1p, K, -1)
    tlen = jnp.asarray(tlen, jnp.int32)
    if slen_min is None:
        slen_min = jnp.min(jnp.where(slen > 0, slen, jnp.int32(2**30)))
    else:
        slen_min = jnp.asarray(slen_min, jnp.int32)
    S_min = slen_min - tlen + 2 * OFF
    r_lane = (slen - slen_min)[None, None, :]

    jb0 = jnp.asarray(jb0, jnp.int32)
    if n_blocks is None:
        n_blocks = n_steps
    # per-lane residual roll via binary decomposition: log2(K)
    # conditional power-of-two rolls compose to a roll by r_lane for
    # ARBITRARY per-lane residuals (the old per-distinct-residual chain
    # capped how many read lengths a batch could have). Residuals are
    # < K whenever the uniform frame is sane (engine policy checks the
    # length spread), so K bits always suffice.
    n_bits = max(1, int(np.ceil(np.log2(max(K, 2)))))

    def one_block(jb_local):
        jb = jb0 + jb_local
        # B columns [jb*C, jb*C + C] = Brev columns [tlen-jb*C-C, tlen-jb*C]
        start_raw = tlen - jb * C - C
        start = jnp.maximum(start_raw, 0)
        shift = start - start_raw  # >0 when clamped (j near/past tlen)
        blk = jax.lax.dynamic_slice(
            B3, (start, jnp.int32(0), jnp.int32(lane0)), (C + 1, K, Npad)
        )
        blk = blk[::-1]  # ascending B-column order
        # clamped windows are shifted; realign (garbage rotates among
        # garbage columns only)
        blk = jnp.roll(blk, -shift, axis=0)
        # rows: want row d = Brev row S_k - d
        blk = blk[:, ::-1]  # row d holds Brev row K-1-d
        blk = jnp.roll(blk, S_min - (K - 1), axis=1)
        for b in range(n_bits):
            step = 1 << b
            blk = jnp.where(
                (r_lane >> b) & 1 == 1, jnp.roll(blk, step, axis=1), blk
            )
        return blk.reshape((C + 1) * K, Npad)

    return jax.lax.map(one_block, jnp.arange(n_blocks, dtype=jnp.int32))


def _dense_kernel(
    tlen_ref,  # SMEM [1, 1]
    off_ref,  # SMEM [1, 1] uniform OFF
    col0_ref,  # SMEM [1, 1] global column of this launch's first column
    slen_ref,  # [1, 1, 128] int32
    roff_ref,  # [1, 1, 128] int32 per-read band offset (geom.offset)
    bw_ref,  # [1, 1, 128] int32 per-read bandwidth
    a_ref,  # [1, C * K, 128] forward band columns of this block
    bh_ref,  # [1, (C + 1) * K, 128] backward band columns j .. j+C
    mt_ref,  # [1, CB, 128] blocked tables (fill_pallas layout)
    mm_ref,
    gi_ref,
    dl_ref,
    sq_ref,  # packed enc: [1, CBp, 128] packed code words
    # packed enc: qm_ref [8, 1, 128] dequant rows rides after sq
    *refs,
    K: int,
    C: int,
    input_enc: str = "f32",
):
    refs = list(refs)
    qm_ref = refs.pop(0) if input_enc == "packed" else None
    out_ref = refs.pop(0)  # VMEM [1, 1, C * ROWS, 128] per-lane maxima
    tlen = tlen_ref[0, 0]
    OFF = off_ref[0, 0]
    col0 = col0_ref[0, 0]
    jb = pl.program_id(1)

    if input_enc == "packed":
        # decode the block once per grid step (ops.encoding), then take
        # the same static windows the f32 path reads from the refs; all
        # max-plus math below stays f32 (accumulate-wide)
        mt_t = dequant_block(mt_ref[0], qm_ref[0, 0, :], qm_ref[4, 0, :])
        mm_t = dequant_block(mm_ref[0], qm_ref[1, 0, :], qm_ref[5, 0, :])
        gi_t = dequant_block(gi_ref[0], qm_ref[2, 0, :], qm_ref[6, 0, :])
        dl_t = dequant_block(dl_ref[0], qm_ref[3, 0, :], qm_ref[7, 0, :])
        sq_t = unpack_codes(sq_ref[0])

    def tab_win(lo, hi):
        """(sq, mt, mm, gi, dl) windows [lo, hi) of the decoded (packed)
        or raw (f32, zero-cast) block."""
        if input_enc == "packed":
            return (sq_t[lo:hi, :], mt_t[lo:hi, :], mm_t[lo:hi, :],
                    gi_t[lo:hi, :], dl_t[lo:hi, :])
        return (sq_ref[0, lo:hi, :], mt_ref[0, lo:hi, :],
                mm_ref[0, lo:hi, :], gi_ref[0, lo:hi, :],
                dl_ref[0, lo:hi, :])

    slen = slen_ref[0, 0, :]
    roff = roff_ref[0, 0, :]
    bw = bw_ref[0, 0, :]
    d = jax.lax.broadcasted_iota(jnp.int32, (K, LANES), 0)
    neg = jnp.full((K, LANES), NEG_INF, jnp.float32)
    zero16 = jnp.full((ROWS - 9, LANES), 0.0, jnp.float32)
    v_off = jnp.maximum(slen - tlen, 0)

    for c in range(C):
        j = col0 + jb * C + c
        # load-wide: the band store may be narrower (bf16); every max-plus
        # candidate and join below accumulates in f32. No-op for f32 bands.
        A_j = a_ref[0, c * K : (c + 1) * K, :].astype(jnp.float32)
        B_j = bh_ref[0, c * K : (c + 1) * K, :].astype(jnp.float32)
        B_n = bh_ref[0, (c + 1) * K : (c + 2) * K, :].astype(jnp.float32)

        # A[d+1, j], A[d-1, j], B[d-1, j+1]
        A_up = pltpu.roll(A_j, K - 1, axis=0)
        A_up = jnp.where(d == K - 1, neg, A_up)
        A_dn = pltpu.roll(A_j, 1, axis=0)
        A_dn = jnp.where(d == 0, neg, A_dn)
        B_n_dn = pltpu.roll(B_n, 1, axis=0)
        B_n_dn = jnp.where(d == 0, neg, B_n_dn)

        # row-range of the recomputed column (model.jl:263): the read's
        # own band limits at column jc = min(j+1, tlen)
        jc = jnp.minimum(j + 1, tlen)
        rmin = jnp.maximum(0, jc - roff)
        rmax = jnp.minimum(jc + v_off + bw, slen)

        dele = jnp.max(A_j + B_n_dn, axis=0, keepdims=True)  # [1, LANES]

        def edit_scores(i, sq, mt, mm, gi, dl, m_src, d_src, B_join):
            valid = (i >= rmin[None, :]) & (i <= rmax[None, :])
            dcand = d_src + dl
            g = jnp.where((i >= 1) & valid, gi, 0.0)
            G = _cumop(g, lambda a, b: a + b, K)
            outs = []
            for b in range(4):
                msc = jnp.where(sq == b, mt, mm)
                mcand = jnp.where(i >= 1, m_src + msc, neg)
                cand = jnp.where(valid, jnp.maximum(mcand, dcand), neg)
                NC = G + _cumop(cand - G, jnp.maximum, K)
                NC = jnp.where(valid, NC, neg)
                outs.append(jnp.max(NC + B_join, axis=0, keepdims=True))
            return outs  # 4 x [1, LANES]

        # substitutions at j: frame j+1 -> table window = block rows
        # [c+1, c+1+K); insertions after j: frame j -> rows [c, c+K)
        subs = edit_scores(
            d + (j + 1 - OFF),
            *tab_win(c + 1, c + 1 + K),
            A_j, A_up, B_n,
        )
        insr = edit_scores(
            d + (j - OFF),
            *tab_win(c, c + K),
            A_dn, A_j, B_j,
        )
        out_ref[0, 0, c * ROWS : (c + 1) * ROWS, :] = jnp.concatenate(
            [dele] + subs + insr + [zero16], axis=0
        )


@functools.partial(jax.jit, static_argnames=("K", "T1p", "C", "interpret",
                                              "input_enc"))
def dense_call(
    tlen_s,  # [1, 1] int32
    off_s,  # [1, 1] int32
    meta,  # [3, Npad] int32: slen, roff, bw
    A_flat,  # [T1p * K, Npad] forward band (uniform frame, flat)
    Bh,  # [n_steps, (C + 1) * K, Npad] halo-blocked backward band
    mt, mm, gi, dl, sq,  # [NSTEPS, CB, Npad] blocked tables
    K: int,
    T1p: int,
    C: int,
    interpret: bool = False,
    col0=None,  # [1, 1] int32 global first column (panel launches)
    input_enc: str = "f32",
    qmeta=None,  # [8, 1, >=Npad] f32 dequant rows (packed enc only)
):
    if col0 is None:
        col0 = jnp.zeros((1, 1), jnp.int32)
    # lane count from the metadata, NOT the band: A_flat may carry extra
    # lane blocks (the fill kernel's combined fwd+rev output) that the
    # lane-block index simply never touches — avoiding a ~1 GB copy
    Npad = meta.shape[1]
    NB = Npad // LANES
    n_steps = T1p // C
    CB = mt.shape[1]

    grid = (NB, n_steps)

    def tab_spec(rows=CB):
        return pl.BlockSpec(
            (1, rows, LANES), lambda nb, jb: (jb, 0, nb),
            memory_space=pltpu.VMEM,
        )

    def lane_spec():
        return pl.BlockSpec(
            (1, 1, LANES), lambda nb, jb: (0, 0, nb),
            memory_space=pltpu.VMEM,
        )

    in_specs = [
        pl.BlockSpec((1, 1), lambda nb, jb: (0, 0), memory_space=pltpu.SMEM),
        pl.BlockSpec((1, 1), lambda nb, jb: (0, 0), memory_space=pltpu.SMEM),
        pl.BlockSpec((1, 1), lambda nb, jb: (0, 0), memory_space=pltpu.SMEM),
        lane_spec(),  # slen
        lane_spec(),  # roff
        lane_spec(),  # bw
        pl.BlockSpec(
            (1, C * K, LANES), lambda nb, jb: (0, jb, nb),
            memory_space=pltpu.VMEM,
        ),  # A block
        pl.BlockSpec(
            (1, (C + 1) * K, LANES), lambda nb, jb: (jb, 0, nb),
            memory_space=pltpu.VMEM,
        ),  # halo-blocked B
        tab_spec(),
        tab_spec(),
        tab_spec(),
        tab_spec(),
        tab_spec(rows=sq.shape[1]),  # sq (CBp packed words / CB codes)
    ]
    args = [
        tlen_s, off_s, jnp.asarray(col0, jnp.int32).reshape(1, 1),
        meta[0][None, None], meta[1][None, None], meta[2][None, None],
        A_flat[None],
        Bh,
        mt, mm, gi, dl, sq,
    ]
    if input_enc == "packed":
        in_specs.append(
            pl.BlockSpec(
                (8, 1, LANES), lambda nb, jb: (0, 0, nb),
                memory_space=pltpu.VMEM,
            )
        )
        # qmeta may carry extra reversed lanes (prepare_fill's combined
        # layout) — the forward lane-block index never touches them
        args.append(qmeta)

    out = pl.pallas_call(
        functools.partial(_dense_kernel, K=K, C=C, input_enc=input_enc),
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, 1, C * ROWS, LANES), lambda nb, jb: (nb, jb, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct(
            (NB, n_steps, C * ROWS, LANES), jnp.float32
        ),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(*args)
    # [NB, n_steps, C*ROWS, 128] -> per-lane tables [T1p, ROWS, Npad]
    out = out.reshape(NB, n_steps, C, ROWS, LANES)
    out = out.transpose(1, 2, 3, 0, 4).reshape(T1p, ROWS, NB * LANES)
    return out


def dense_tables_pallas(
    tlen_s, off_s, meta, A_flat, Bh, tabs, weights, K, T1p, C,
    interpret=False, input_enc="f32", qmeta=None,
):
    """Weighted batch-total score tables from the dense kernel.

    Returns (sub [T1p, 4], ins [T1p, 4], del [T1p]) — matching
    ops.proposal_dense.score_all_edits's contract (positions >= tlen
    are garbage)."""
    mt, mm, gi, dl, sq = tabs
    per_lane = dense_call(
        tlen_s, off_s, meta, A_flat, Bh, mt, mm, gi, dl, sq,
        K=K, T1p=T1p, C=C, interpret=interpret, input_enc=input_enc,
        qmeta=qmeta,
    )
    w = weights[None, None, :]
    tables = jnp.sum(jnp.where(w > 0, per_lane, 0.0) * w, axis=2)
    return tables[:, 1:5], tables[:, 5:9], tables[:, 0]


def _moves_band(moves_flat, K: int, T1p: int, Npad: int):
    """[n_steps*C*K, NBLK*128] int8 -> forward-stream [Npad, K, T1p]."""
    nlanes = moves_flat.shape[1]
    return moves_flat.reshape(T1p, K, nlanes).transpose(2, 1, 0)[:Npad]


def stats_from_moves(moves, seq_lanes, template, geom: BandGeometry,
                     lengths, K: int, off_override=None,
                     want_edge: bool = False):
    """Device traceback statistics over the Pallas move band: per-lane
    alignment error counts and the union single-base-edit indicator table
    (the Pallas counterpart of ops.fused's want_stats components).

    ``moves`` is the uniform-frame forward move band [Npad, K, T1]
    (T1 = template length + 1 — callers slice the fill's T1p columns so
    the stats scan unrolls on the bucketed length); the scan itself is
    align_jax._traceback_stats_one, which works unchanged because
    uniform_geometry re-expresses the uniform frame in its per-read
    terms. Padding lanes have all-NONE moves (their n_errors slot is -1;
    callers slice to real reads) and contribute nothing to the union.
    ``want_edge`` appends per-lane band-edge-hit counts (the on-path
    cells sitting exactly on a band limit — the adaptive-growth
    frontier signal); the 2-tuple return is unchanged without it."""
    from .align_jax import _traceback_stats_one
    from .fill_pallas import uniform_geometry

    ugeom = uniform_geometry(geom, lengths=lengths,
                             off_override=off_override)
    if want_edge:
        # the uniform frame widens every lane's nd to the shared K, so
        # the read's TRUE band limits must ride along explicitly:
        # uniform row d maps to per-read row d - delta_k, whose edges
        # sit at d == delta_k and d == delta_k + nd_k - 1
        Npad = moves.shape[0]
        OFF = jnp.max(geom.offset) if off_override is None else (
            jnp.asarray(off_override, jnp.int32)
        )
        delta = _pad_lanes((OFF - geom.offset).astype(jnp.int32), Npad)
        ndv = _pad_lanes(geom.nd.astype(jnp.int32), Npad)
        nerr, edits, ehits = jax.vmap(
            functools.partial(_traceback_stats_one, want_edge=True),
            in_axes=(0, 0, None, 0, None, 0, 0),
        )(moves, seq_lanes, template, ugeom, K, delta, delta + ndv - 1)
        return nerr, jnp.max(edits, axis=0), ehits
    nerr, edits = jax.vmap(
        _traceback_stats_one, in_axes=(0, 0, None, 0, None)
    )(moves, seq_lanes, template, ugeom, K)
    return nerr, jnp.max(edits, axis=0)


def fused_tables_pallas(
    template,  # int8 [Tmax] padded template
    tlen,  # int32 true length
    bufs: FillBuffers,
    geom: BandGeometry,
    weights,  # [N] f32 (padding lanes 0)
    K: int,
    T1p: int,
    C: int,
    want_stats: bool = False,
    want_moves: bool = False,
    off_override=None,
    slen_min=None,
    interpret: bool = False,
    band_dtype: str = "f32",
    input_enc: str = "f32",
):
    """One hill-climb iteration's device work, all-Pallas: forward +
    backward fills (one launch), backward alignment, dense all-edits
    tables, and (want_stats) traceback statistics from the in-kernel
    move band — the Pallas counterpart of ops.fused.fused_step_full.
    Returns a dict with total, scores [Npad], sub [T1p, 4], ins [T1p, 4],
    del [T1p], plus n_errors [Npad] / edits [T1, 9] (want_stats) and the
    forward move band [Npad, K, T1p] int8 (want_moves). ``band_dtype``
    ("f32"/"bf16") selects the HBM store dtype of both band buffers;
    scores, tables, and every reduction stay f32 either way.
    ``input_enc`` ("f32"/"packed") selects the streamed input wire
    format (ops.encoding); the kernels decode at VMEM load and all
    max-plus math stays f32."""
    from . import fill_pallas

    Npad = bufs.seq_T.shape[1]
    NB = Npad // LANES
    need_moves = want_stats or want_moves
    p = fill_pallas.prepare_fill(
        template, tlen, bufs, geom, K, T1p, C, with_backward=True,
        off_override=off_override, input_enc=input_enc,
    )
    band_flat, scores2, moves_flat = fill_pallas._fill_call(
        p["tlen_s"], p["off_s"], p["t_cols"], p["meta"], *p["tabs"],
        K=K, T1p=T1p, NBLK=2 * NB, C=C, want_moves=need_moves,
        interpret=interpret, band_dtype=band_dtype,
        input_enc=input_enc, qmeta=p["qmeta"],
    )
    scores = scores2[0, :Npad]

    # the backward stream occupies lane blocks [NB, 2NB) of band_flat;
    # the dense kernel reads the forward lanes of band_flat in place
    Bh = backward_halo_blocks(
        band_flat, jnp.asarray(tlen, jnp.int32), p["OFF"], bufs.lengths,
        K, T1p, C, lane0=Npad, slen_min=slen_min,
    )
    A_flat = band_flat

    w = _pad_lanes(weights.astype(jnp.float32), Npad)
    meta3 = jnp.stack([
        bufs.lengths,
        _pad_lanes(geom.offset.astype(jnp.int32), Npad),
        _pad_lanes(geom.bandwidth.astype(jnp.int32), Npad),
    ])
    sub_t, ins_t, del_t = dense_tables_pallas(
        p["tlen_s"], p["off_s"], meta3, A_flat, Bh, p["fwd_tabs"], w,
        K, T1p, C, interpret=interpret, input_enc=input_enc,
        qmeta=p["qmeta"],
    )
    # the one epilogue lane reduction of the split path (tables reduce
    # in-kernel), routed through the shared segment-reduce helper in
    # its trivial single-segment form — bit-identical to the plain
    # masked weighted sum, and the same code path a segment-packed
    # epilogue would take
    from .fused import segment_masked_sum_lanes, segment_weights

    total = segment_masked_sum_lanes(
        segment_weights(jnp.zeros((Npad,), jnp.int32), w, 1), scores
    )[0]
    out = {
        "total": total, "scores": scores,
        "sub": sub_t, "ins": ins_t, "del": del_t,
    }
    if need_moves:
        if want_stats:
            T1 = template.shape[0] + 1
            if stats_pallas.use_pallas_stats():
                # on-core reverse sweep straight over the fill kernel's
                # raw int32 move band (no int8 round trip, no XLA scan)
                nerr, edits = stats_pallas.traceback_stats_pallas(
                    p, moves_flat, K, T1p, C, Npad, T1,
                    interpret=interpret, input_enc=input_enc,
                )
            else:
                moves = _moves_band(moves_flat, K, T1p, Npad)
                nerr, edits = stats_from_moves(
                    moves[:, :, :T1], bufs.seq_T.T, template, geom,
                    bufs.lengths, K, off_override=off_override,
                )
            out["n_errors"] = nerr
            out["edits"] = edits
        if want_moves:
            out["moves"] = _moves_band(
                moves_flat, K, T1p, Npad
            ).astype(jnp.int8)
    return out


@functools.partial(
    jax.jit,
    static_argnames=("K", "T1p", "C", "want_stats", "want_moves",
                     "interpret", "band_dtype", "input_enc"),
)
def fused_step_pallas(
    template, tlen, bufs: FillBuffers, geom: BandGeometry, weights,
    K: int, T1p: int, C: int,
    want_stats: bool = False, want_moves: bool = False,
    interpret: bool = False, band_dtype: str = "f32",
    input_enc: str = "f32",
):
    """Packed-single-fetch wrapper of fused_tables_pallas (layout:
    pack_layout_pallas). Returns (packed, moves-or-None)."""
    out = fused_tables_pallas(
        template, tlen, bufs, geom, weights, K, T1p, C,
        want_stats=want_stats, want_moves=want_moves, interpret=interpret,
        band_dtype=band_dtype, input_enc=input_enc,
    )
    return jnp.concatenate(pack_parts(out, want_stats)), out.get("moves")


def pack_parts(out: dict, want_stats: bool):
    """The packed-fetch section list, in pack_layout_pallas order — the
    ONE place the producer-side order lives (fused_step_pallas, the
    panel path, and the mesh wrapper all build from it; 'sub' and 'ins'
    have identical sizes, so a divergent hand-built order would misread
    silently, not shape-error)."""
    parts = [out["total"][None], out["scores"]]
    if want_stats:
        parts.append(out["n_errors"].astype(jnp.float32))
        parts.append(out["edits"].reshape(-1).astype(jnp.float32))
    parts += [out["sub"].reshape(-1), out["ins"].reshape(-1), out["del"]]
    return parts


def pack_layout_pallas(Npad: int, T1p: int, want_stats: bool = False,
                       T1: int = 0):
    """Slice map of fused_step_pallas's packed array. ``T1`` (the
    unpadded template length + 1) sizes the stats edit table."""
    out = {}
    o = 0

    def take(name, size):
        nonlocal o
        out[name] = (o, o + size)
        o += size

    take("total", 1)
    take("scores", Npad)
    if want_stats:
        take("n_errors", Npad)
        take("edits", T1 * 9)
    take("sub", T1p * 4)
    take("ins", T1p * 4)
    take("del", T1p)
    return out


@functools.partial(
    jax.jit, static_argnames=("K", "T1p", "C", "interpret", "want_edge",
                              "band_dtype", "input_enc")
)
def fill_stats_pallas(
    template, tlen, bufs: FillBuffers, geom: BandGeometry,
    K: int, T1p: int, C: int, off_override=None,
    interpret: bool = False, want_edge: bool = False,
    band_dtype: str = "f32", input_enc: str = "f32",
):
    """Bandwidth-adaptation round on the Pallas engine: forward-only fill
    with in-kernel move recording, then the device traceback statistics —
    no backward stream, no dense sweep (their outputs would be discarded
    every round the bandwidths grow; the XLA path skips them via
    want_tables=False for the same reason). Returns packed
    [scores (Npad), n_errors (Npad)], plus a trailing edge-hit block
    [edge_hits (Npad)] when ``want_edge`` (on-path traceback cells that
    sit exactly on the read's band-limit rows — the adaptive-growth
    frontier signal)."""
    from . import fill_pallas

    Npad = bufs.seq_T.shape[1]
    NB = Npad // LANES
    p = fill_pallas.prepare_fill(
        template, tlen, bufs, geom, K, T1p, C, with_backward=False,
        off_override=off_override, input_enc=input_enc,
    )
    _, scores2, moves_flat = fill_pallas._fill_call(
        p["tlen_s"], p["off_s"], p["t_cols"], p["meta"], *p["tabs"],
        K=K, T1p=T1p, NBLK=NB, C=C, want_moves=True, interpret=interpret,
        band_dtype=band_dtype, input_enc=input_enc, qmeta=p["qmeta"],
    )
    T1 = template.shape[0] + 1
    ehits = None
    if stats_pallas.use_pallas_stats():
        # adaptation only needs n_errors: skip the indicator tiles
        if want_edge:
            nerr, _, ehits = stats_pallas.traceback_stats_pallas(
                p, moves_flat, K, T1p, C, Npad, T1, want_edits=False,
                interpret=interpret, want_edge=True, input_enc=input_enc,
            )
        else:
            nerr, _ = stats_pallas.traceback_stats_pallas(
                p, moves_flat, K, T1p, C, Npad, T1, want_edits=False,
                interpret=interpret, input_enc=input_enc,
            )
    else:
        moves = _moves_band(moves_flat, K, T1p, Npad)
        if want_edge:
            nerr, _, ehits = stats_from_moves(
                moves[:, :, :T1], bufs.seq_T.T, template, geom,
                bufs.lengths, K, off_override=off_override,
                want_edge=True,
            )
        else:
            nerr, _ = stats_from_moves(
                moves[:, :, :T1], bufs.seq_T.T, template, geom,
                bufs.lengths, K, off_override=off_override,
            )
    parts = [scores2[0, :Npad], nerr.astype(jnp.float32)]
    if want_edge:
        parts.append(ehits.astype(jnp.float32))
    return jnp.concatenate(parts)


# --- panel-blocked long-template path --------------------------------------


@functools.partial(
    jax.jit, static_argnames=("K", "P", "C", "NB", "want_moves",
                              "interpret")
)
def _panel_fill(
    tlen_s, off_s, meta, placed, tpl_cols, col0, carry, score,
    K: int, P: int, C: int, NB: int,
    want_moves: bool = False, interpret: bool = False,
):
    """One panel's fill launch for one stream: slice the placed table
    buffers at col0, halo-block the window, and run _fill_call with the
    carry chained from the previous panel. Returns (band_flat [P*K, Npad],
    score', moves-or-None, carry')."""
    from . import fill_pallas

    mt, mm, gi, dl, sq = placed
    CB = C + K
    n_steps = P // C
    c0 = jnp.asarray(col0, jnp.int32)

    def blk(buf):
        win = jax.lax.dynamic_slice_in_dim(buf, c0, P + K, axis=0)
        return fill_pallas._block_tables(win, n_steps, C, CB)

    t_cols = jax.lax.dynamic_slice_in_dim(tpl_cols, c0, P)[None]
    band, score2, moves, carry2 = fill_pallas._fill_call(
        tlen_s, off_s, t_cols, meta,
        blk(mt), blk(mm), blk(gi), blk(dl), blk(sq),
        K=K, T1p=P, NBLK=NB, C=C, want_moves=want_moves,
        col0=jnp.reshape(c0, (1, 1)), carry_in=carry, score_in=score,
        interpret=interpret,
    )
    return band, score2, moves, carry2


@functools.partial(
    jax.jit,
    static_argnames=("K", "P", "C", "NB", "T1p_pad", "interpret"),
)
def _panel_dense(
    tlen_s, off_s, meta3, placed_fwd, band_fwd, Brev_flat, weights,
    col0, jb0,
    K: int, P: int, C: int, NB: int, T1p_pad: int,
    interpret: bool = False,
):
    """One panel's dense step: halo-block the panel's backward columns
    from the full Brev band, then run the dense kernel on the panel's
    forward band. Returns (sub [P, 4], ins [P, 4], del [P])."""
    from . import fill_pallas

    mt, mm, gi, dl, sq = placed_fwd
    CB = C + K
    n_steps = P // C
    c0 = jnp.asarray(col0, jnp.int32)

    def blk(buf):
        win = jax.lax.dynamic_slice_in_dim(buf, c0, P + K, axis=0)
        return fill_pallas._block_tables(win, n_steps, C, CB)

    Npad = meta3.shape[1]
    Bh = backward_halo_blocks(
        Brev_flat, tlen_s[0, 0], off_s[0, 0], meta3[0],
        K, T1p_pad, C, jb0=jb0, n_blocks=n_steps,
    )
    per_lane = dense_call(
        tlen_s, off_s, meta3, band_fwd, Bh,
        blk(mt), blk(mm), blk(gi), blk(dl), blk(sq),
        K=K, T1p=P, C=C, col0=jnp.reshape(c0, (1, 1)),
        interpret=interpret,
    )
    w = weights[None, None, :]
    tables = jnp.sum(jnp.where(w > 0, per_lane, 0.0) * w, axis=2)
    return tables[:, 1:5], tables[:, 5:9], tables[:, 0]


@functools.partial(jax.jit, donate_argnums=(0,))
def _write_panel(buf, panel, row0):
    """Write one panel's flat rows into the full-band buffer in place
    (donation makes the update alias the input across the dispatch)."""
    return jax.lax.dynamic_update_slice(
        buf, panel.astype(buf.dtype), (row0, jnp.int32(0))
    )


def fused_tables_pallas_panels(
    template,  # int8 [Tmax]
    tlen,  # int32
    bufs: FillBuffers,
    geom: BandGeometry,
    weights,
    K: int,
    T1p: int,
    C: int,
    panel_cols: int,
    want_stats: bool = False,
    want_moves: bool = False,
    interpret: bool = False,
):
    """The fused step for templates whose single-launch working set
    exceeds HBM: the reversed stream fills the FULL band first (it must
    be complete before any forward panel's dense join), then forward
    panels of ``panel_cols`` columns stream left-to-right — each panel
    launch chains the DP carry from the previous one, computes its dense
    all-edit table slice against the halo-blocked backward columns, and
    is then discarded. Peak HBM is the full Brev band plus O(panel)
    temporaries instead of two full bands plus their halo'd copies.
    Same contract as fused_tables_pallas (dict)."""
    from . import fill_pallas

    Npad = bufs.seq_T.shape[1]
    NB = Npad // LANES
    P = panel_cols
    T1p_pad = ((T1p + P - 1) // P) * P
    n_panels = T1p_pad // P
    pp = fill_pallas.prepare_fill_panels(
        template, tlen, bufs, geom, K, T1p_pad
    )
    tlen_s, off_s, meta = pp["tlen_s"], pp["off_s"], pp["meta"]
    need_moves = want_stats or want_moves

    # phase 1: full reversed-problem band. Panels are written into a
    # PREALLOCATED buffer with donation — collecting panels and
    # concatenating would double the peak (full band + its copy), which
    # is exactly the headroom long templates do not have.
    carry = jnp.zeros((K, Npad), jnp.float32)
    score = jnp.full((1, Npad), NEG_INF, jnp.float32)
    Brev_flat = jnp.zeros((T1p_pad * K, Npad), jnp.float32)
    for p in range(n_panels):
        band, score, _, carry = _panel_fill(
            tlen_s, off_s, meta, pp["rev_placed"], pp["rtpl_cols"],
            jnp.int32(p * P), carry, score,
            K=K, P=P, C=C, NB=NB, want_moves=False, interpret=interpret,
        )
        Brev_flat = _write_panel(Brev_flat, band, jnp.int32(p * P * K))

    # phase 2: forward panels + dense slices
    meta3 = jnp.stack([
        bufs.lengths,
        _pad_lanes(geom.offset.astype(jnp.int32), Npad),
        _pad_lanes(geom.bandwidth.astype(jnp.int32), Npad),
    ])
    w = _pad_lanes(weights.astype(jnp.float32), Npad)
    carry = jnp.zeros((K, Npad), jnp.float32)
    score = jnp.full((1, Npad), NEG_INF, jnp.float32)
    subs, inss, dels_t = [], [], []
    moves_flat = (
        jnp.zeros((T1p_pad * K, Npad), jnp.int8) if need_moves else None
    )
    for p in range(n_panels):
        band, score, mv, carry = _panel_fill(
            tlen_s, off_s, meta, pp["fwd_placed"], pp["tpl_cols"],
            jnp.int32(p * P), carry, score,
            K=K, P=P, C=C, NB=NB, want_moves=need_moves,
            interpret=interpret,
        )
        sub_p, ins_p, del_p = _panel_dense(
            tlen_s, off_s, meta3, pp["fwd_placed"], band, Brev_flat, w,
            jnp.int32(p * P), jnp.int32(p * (P // C)),
            K=K, P=P, C=C, NB=NB, T1p_pad=T1p_pad,
            interpret=interpret,
        )
        subs.append(sub_p)
        inss.append(ins_p)
        dels_t.append(del_p)
        if need_moves:
            moves_flat = _write_panel(
                moves_flat, mv, jnp.int32(p * P * K)
            )
    scores = score[0]
    total = jnp.sum(jnp.where(w > 0, scores, 0.0) * w)
    out = {
        "total": total,
        "scores": scores,
        "sub": jnp.concatenate(subs)[:T1p],
        "ins": jnp.concatenate(inss)[:T1p],
        "del": jnp.concatenate(dels_t)[:T1p],
    }
    if need_moves:
        if want_stats:
            T1 = template.shape[0] + 1
            if (stats_pallas.use_pallas_stats()
                    and stats_pallas.int8_moves_ok(K, C)):
                # reverse panel sweep over the accumulated int8 move
                # band, carry-chained right-to-left; per-panel tiles are
                # lane-reduced immediately so the transient stays
                # O(panel), like the dense slices above
                nerr, edits = stats_pallas.traceback_stats_pallas_panels(
                    pp, moves_flat, K, T1p_pad, P, C, Npad, T1,
                    interpret=interpret,
                )
            else:
                moves = _moves_band(moves_flat, K, T1p_pad, Npad)
                nerr, edits = stats_from_moves(
                    moves[:, :, :T1], bufs.seq_T.T, template, geom,
                    bufs.lengths, K,
                )
            out["n_errors"] = nerr
            out["edits"] = edits
        if want_moves:
            out["moves"] = _moves_band(moves_flat, K, T1p_pad, Npad)
    return out
