"""Dense all-edits proposal scoring: one launch for every possible edit.

TPU-native second-generation scorer for the O(bandwidth) rescoring trick
(/root/reference/src/model.jl:242-285 + util.jl:40-48). The first-generation
kernel (proposal_jax) vectorizes over an arbitrary proposal LIST, gathering
the A/B band columns each proposal touches — fine for sparse candidate
sets, but the hill-climbing stages score ~9*len edits (every substitution
and insertion at every position, every deletion: model.jl:401-456), and at
that density per-proposal gathers re-read the bands hundreds of times.

This module scores ALL single-base edits at once with band-shaped tensor
ops, no proposal axis at all:

- deletions: ``max_d(A[d, j] + B[d-1, j+1])`` for every j simultaneously —
  one shifted add over the band and a max along the band axis;
- substitutions/insertions: the "one new column" recomputation
  (model.jl:242-285) for every position as a single [K, T+1] sweep — the
  skewed score-table gathers (one per table, reused by all 4 bases), the
  candidate max, and the within-column insert chain as a batched
  ``cummax`` along the band axis, joined with the B band;
- the read axis is vmapped, and the weighted read-reduction happens on
  device, so a sharded batch psums partial sums over ICI.

Cost: ~30 band-sized tensor ops per read for all 9*len+4 edits, vs
O(len) per-proposal column gathers — the arithmetic intensity that the
VPU wants. Returns score TABLES (sub [T+1, 4], ins [T+1, 4], del [T+1])
matching estimate_probs' layout (model.jl:737-791); entries at positions
beyond the true template length are meaningless and must be sliced off by
the caller.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..models.sequences import ReadBatch
from . import align_jax
from .align_jax import BandGeometry

NEG_INF = -jnp.inf


def masked_weighted_sum(weights, x):
    """Sum weight*x over the leading (read) axis. Mask BEFORE multiplying:
    a zero-weight padding row may hold -inf/nan and 0 * -inf would poison
    the total, while a real read's legitimate -inf must propagate."""
    w = weights.reshape((-1,) + (1,) * (x.ndim - 1))
    return jnp.sum(jnp.where(w > 0, x, 0.0) * w, axis=0)


def _edit_scores_core(i, sq, mt, mm, gi, dl, m_src, d_src, B_join,
                      rmin, rmax):
    """Shared sub/ins scoring core: new column from (m_src, d_src) at true
    row index i, joined with B_join — for all positions in the tile and
    all 4 bases. Used identically by the all-at-once sweep ([K, T1]
    operands) and the blocked sweep ([K, CB] tiles); any change to the
    recurrence must flow through here so both paths stay in lockstep."""
    valid = (i >= rmin) & (i <= rmax)
    dcand = d_src + dl
    g = jnp.where((i >= 1) & valid, gi, jnp.zeros_like(gi))
    G = jnp.cumsum(g, axis=0)
    outs = []
    for b in range(4):
        msc = jnp.where(sq == b, mt, mm)
        mcand = jnp.where(i >= 1, m_src + msc, NEG_INF)
        cand = jnp.where(valid, jnp.maximum(mcand, dcand), NEG_INF)
        NC = G + jax.lax.cummax(cand - G, axis=0)
        NC = jnp.where(valid, NC, NEG_INF)
        outs.append(jnp.max(NC + B_join, axis=0))
    return jnp.stack(outs, axis=-1)


def _dense_one_read(
    A,  # [K, T1] cached forward band
    B,  # [K, T1] cached backward band
    seq,  # int8 [L]
    match,  # [L]
    mismatch,  # [L]
    ins,  # [L]
    dels,  # [L + 1]
    geom: BandGeometry,  # per-read scalars
):
    """All-edit score tables for one read (vmapped over the batch).

    Mirrors proposal_jax._score_one_read cell-for-cell, with the proposal
    axis replaced by the template-position axis of the bands themselves.
    """
    K, T1 = A.shape
    dtype = A.dtype
    slen, tlen, off = geom.slen, geom.tlen, geom.offset
    v_off = jnp.maximum(slen - tlen, 0)

    d = jnp.arange(K, dtype=jnp.int32)[:, None]  # [K, 1]
    j = jnp.arange(T1, dtype=jnp.int32)[None, :]  # [1, T1] = proposal pos

    # row-range bounds of the recomputed column (model.jl:263)
    jc = jnp.minimum(j + 1, tlen)
    rmin = jnp.maximum(0, jc - off)
    rmax = jnp.minimum(jc + v_off + geom.bandwidth, slen)

    def shift_left(a):
        """Column j -> column j+1's values. For B this equals the former
        clamped take(B, min(j+1, tlen)) everywhere pos < tlen; columns at
        or beyond tlen are garbage by contract (sliced off by callers)."""
        return jnp.concatenate([a[:, 1:], a[:, -1:]], axis=1)

    B_next = shift_left(B)  # [K, T1] = B[:, pos+1]
    neg_row = jnp.full((1, T1), NEG_INF, dtype)
    A_up = jnp.concatenate([A[1:], neg_row], axis=0)  # A[d+1, j]
    A_dn = jnp.concatenate([neg_row, A[:-1]], axis=0)  # A[d-1, j]

    # --- deletions: join A[:, pos] with B[:, pos+1] one data row down ---
    B_next_sh = jnp.concatenate([neg_row, B_next[:-1]], axis=0)
    dele = jnp.max(A + B_next_sh, axis=0)  # [T1]; valid for pos < tlen

    # band-layout table slices, shared with the fill kernel's layout:
    # column j holds table index d + j - off - 1 (sb/mt/mm/gi) and
    # d + j - off (dl). The insertion pass reads them directly; the
    # substitution pass (one frame right) reads them shifted one column.
    # Replaces full-band fancy-index gathers, measured ~1600x slower than
    # the slice build on the available TPU (BASELINE.md round 3).
    tabs = align_jax.band_tables(seq, match, mismatch, ins, dels, off, K, T1)

    def edit_scores(i, sq, mt, mm, gi, dl, m_src, d_src, B_join):
        return _edit_scores_core(
            i, sq, mt, mm, gi, dl, m_src, d_src, B_join, rmin, rmax
        )

    # substitution at pos: new column in frame pos+1, joined with B[:, pos+1]
    subs = edit_scores(
        d + j + 1 - off, shift_left(tabs.sb), shift_left(tabs.mt),
        shift_left(tabs.mm), shift_left(tabs.gi), shift_left(tabs.dl),
        A, A_up, B_next,
    )
    # insertion after pos: new column in frame pos, joined with B[:, pos]
    insr = edit_scores(
        d + j - off, tabs.sb, tabs.mt, tabs.mm, tabs.gi, tabs.dl,
        A_dn, A, B,
    )
    return subs, insr, dele


_dense_batch = jax.vmap(_dense_one_read, in_axes=(0, 0, 0, 0, 0, 0, 0, 0))


def _hankel_rows(W, K: int, k_len: int):
    """[K, k_len] tile from a 1-D window: tile[d, jj] = W[d + jj]."""
    return jnp.stack([W[d : d + k_len] for d in range(K)])


def _dense_block_one(Ab, Bb, mt_pad, mm_pad, gi_pad, dl_pad, sq_pad, geom,
                     j0, CB: int):
    """Score tables for CB consecutive positions of one read.

    ``Ab`` is [K, CB] (columns j0..j0+CB-1), ``Bb`` is [K, CB+1] (columns
    j0..j0+CB). Same math as _dense_one_read, restricted to the block, with
    the per-base tables read as Hankel tiles of contiguous windows."""
    K = Ab.shape[0]
    dtype = Ab.dtype
    slen, tlen, off = geom.slen, geom.tlen, geom.offset
    v_off = jnp.maximum(slen - tlen, 0)

    d = jnp.arange(K, dtype=jnp.int32)[:, None]
    j = j0 + jnp.arange(CB, dtype=jnp.int32)[None, :]
    jc = jnp.minimum(j + 1, tlen)
    rmin = jnp.maximum(0, jc - off)
    rmax = jnp.minimum(jc + v_off + geom.bandwidth, slen)

    # forward-layout tiles covering table columns j0 .. j0+CB: entry
    # [d, jj] = table[d + (j0 + jj) - off - 1] (dl: index + 1).
    # INVARIANT (clamp-is-masked): when the template is much longer than
    # a read, `start` can exceed the padded table length and XLA clamps
    # the slice start, silently shifting the window. That is safe only
    # because every cell the shifted window feeds has true row index
    # i > slen there, i.e. lies outside [rmin, rmax], and
    # _edit_scores_core masks it to -inf. Any change to the valid mask
    # must preserve this.
    start = jnp.asarray(K + j0 - off - 1, jnp.int32)
    k_len = CB + 1
    W = K + k_len - 1
    win = lambda a: jax.lax.dynamic_slice(a, (start,), (W,))
    mt_t = _hankel_rows(win(mt_pad), K, k_len)
    mm_t = _hankel_rows(win(mm_pad), K, k_len)
    gi_t = _hankel_rows(win(gi_pad), K, k_len)
    dl_t = _hankel_rows(win(dl_pad), K, k_len)
    sb_t = _hankel_rows(win(sq_pad), K, k_len)

    neg_row = jnp.full((1, CB), NEG_INF, dtype)
    B_next = Bb[:, 1:]  # [K, CB] = B[:, j+1]
    B_cur = Bb[:, :CB]
    A_up = jnp.concatenate([Ab[1:], neg_row], axis=0)
    A_dn = jnp.concatenate([neg_row, Ab[:-1]], axis=0)
    B_next_sh = jnp.concatenate([neg_row, B_next[:-1]], axis=0)
    dele = jnp.max(Ab + B_next_sh, axis=0)  # [CB]

    def edit_scores(i, sq, mt, mm, gi, dl, m_src, d_src, B_join):
        return _edit_scores_core(
            i, sq, mt, mm, gi, dl, m_src, d_src, B_join, rmin, rmax
        )

    # substitution at pos: table columns j+1 (tile columns 1..CB)
    subs = edit_scores(
        d + j + 1 - off, sb_t[:, 1:], mt_t[:, 1:], mm_t[:, 1:],
        gi_t[:, 1:], dl_t[:, 1:], Ab, A_up, B_next,
    )
    # insertion after pos: table columns j (tile columns 0..CB-1)
    insr = edit_scores(
        d + j - off, sb_t[:, :CB], mt_t[:, :CB], mm_t[:, :CB],
        gi_t[:, :CB], dl_t[:, :CB], A_dn, Ab, B_cur,
    )
    return subs, insr, dele


def dense_tables_blocked(
    A, B, seq, match, mismatch, ins, dels, geom, weights, block: int = 256
):
    """Weighted batch-total score tables, computed in sequential column
    blocks (lax.map) so peak memory stays O(reads x K x block) — the
    all-columns-at-once sweep materializes O(reads x K x T1) tiles, which
    exceeds HBM at 10 kb x 512 reads. Returns (sub [T1, 4], ins [T1, 4],
    del [T1]), read-reduced with zero-weight masking."""
    N, K, T1 = A.shape
    dtype = A.dtype
    nblk = -(-T1 // block)
    pad_cols = nblk * block + 1 - T1
    negpad = jnp.full((N, K, pad_cols), NEG_INF, dtype)
    Ap = jnp.concatenate([A, negpad], axis=-1)
    Bp = jnp.concatenate([B, negpad], axis=-1)

    # separate padded tables: stacking them [N, 4, Lp] triggers a 128x
    # tiling expansion of the size-4 axis (see align_jax._forward_one)
    Wpad = K + block + 1
    mt_pad = jnp.pad(match, ((0, 0), (K, Wpad)))
    mm_pad = jnp.pad(mismatch, ((0, 0), (K, Wpad)))
    gi_pad = jnp.pad(ins, ((0, 0), (K, Wpad)))
    dl_pad = jnp.pad(dels, ((0, 0), (K - 1, Wpad)))
    sq_pad = jnp.pad(seq, ((0, 0), (K, Wpad)))

    def body(j0):
        Ab = jax.lax.dynamic_slice(
            Ap, (jnp.int32(0), jnp.int32(0), jnp.asarray(j0, jnp.int32)),
            (N, K, block),
        )
        Bb = jax.lax.dynamic_slice(
            Bp, (jnp.int32(0), jnp.int32(0), jnp.asarray(j0, jnp.int32)),
            (N, K, block + 1),
        )
        one = jax.vmap(
            _dense_block_one, in_axes=(0, 0, 0, 0, 0, 0, 0, 0, None, None)
        )
        subs, insr, dele = one(
            Ab, Bb, mt_pad, mm_pad, gi_pad, dl_pad, sq_pad, geom, j0, block
        )
        return (masked_weighted_sum(weights, subs),
                masked_weighted_sum(weights, insr),
                masked_weighted_sum(weights, dele))

    j0s = jnp.arange(nblk, dtype=jnp.int32) * block
    sub_b, ins_b, del_b = jax.lax.map(body, j0s)
    return (
        sub_b.reshape(nblk * block, 4)[:T1],
        ins_b.reshape(nblk * block, 4)[:T1],
        del_b.reshape(nblk * block)[:T1],
    )


@jax.jit
def _dense_total(A, B, seq, match, mismatch, ins, dels, geom, weights):
    subs, insr, dele = _dense_batch(A, B, seq, match, mismatch, ins, dels, geom)
    return (masked_weighted_sum(weights, subs),
            masked_weighted_sum(weights, insr),
            masked_weighted_sum(weights, dele))


def score_all_edits(
    A_bands,
    B_bands,
    batch: ReadBatch,
    geom: BandGeometry,
    weights=None,
):
    """Batch-total score tables for every single-base edit.

    Returns (sub [T1, 4], ins [T1, 4], del [T1]) — already summed over
    reads on device (psum over a sharded read axis). Positions >= the true
    template length are garbage; slice before use.
    """
    if weights is None:
        weights = jnp.ones(batch.n_reads, dtype=A_bands.dtype)
    return _dense_total(
        A_bands,
        B_bands,
        jnp.asarray(batch.seq),
        jnp.asarray(batch.match),
        jnp.asarray(batch.mismatch),
        jnp.asarray(batch.ins),
        jnp.asarray(batch.dels),
        geom,
        jnp.asarray(weights),
    )
