"""Dense all-edits proposal scoring: one launch for every possible edit.

TPU-native second-generation scorer for the O(bandwidth) rescoring trick
(/root/reference/src/model.jl:242-285 + util.jl:40-48). The first-generation
kernel (proposal_jax) vectorizes over an arbitrary proposal LIST, gathering
the A/B band columns each proposal touches — fine for sparse candidate
sets, but the hill-climbing stages score ~9*len edits (every substitution
and insertion at every position, every deletion: model.jl:401-456), and at
that density per-proposal gathers re-read the bands hundreds of times.

This module scores ALL single-base edits at once with band-shaped tensor
ops, no proposal axis at all:

- deletions: ``max_d(A[d, j] + B[d-1, j+1])`` for every j simultaneously —
  one shifted add over the band and a max along the band axis;
- substitutions/insertions: the "one new column" recomputation
  (model.jl:242-285) for every position as a single [K, T+1] sweep — the
  skewed score-table gathers (one per table, reused by all 4 bases), the
  candidate max, and the within-column insert chain as a batched
  ``cummax`` along the band axis, joined with the B band;
- the read axis is vmapped, and the weighted read-reduction happens on
  device, so a sharded batch psums partial sums over ICI.

Cost: ~30 band-sized tensor ops per read for all 9*len+4 edits, vs
O(len) per-proposal column gathers — the arithmetic intensity that the
VPU wants. Returns score TABLES (sub [T+1, 4], ins [T+1, 4], del [T+1])
matching estimate_probs' layout (model.jl:737-791); entries at positions
beyond the true template length are meaningless and must be sliced off by
the caller.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..models.sequences import ReadBatch
from .align_jax import BandGeometry

NEG_INF = -jnp.inf


def _dense_one_read(
    A,  # [K, T1] cached forward band
    B,  # [K, T1] cached backward band
    seq,  # int8 [L]
    match,  # [L]
    mismatch,  # [L]
    ins,  # [L]
    dels,  # [L + 1]
    geom: BandGeometry,  # per-read scalars
):
    """All-edit score tables for one read (vmapped over the batch).

    Mirrors proposal_jax._score_one_read cell-for-cell, with the proposal
    axis replaced by the template-position axis of the bands themselves.
    """
    K, T1 = A.shape
    L = seq.shape[0]
    dtype = A.dtype
    slen, tlen, off = geom.slen, geom.tlen, geom.offset
    v_off = jnp.maximum(slen - tlen, 0)

    d = jnp.arange(K, dtype=jnp.int32)[:, None]  # [K, 1]
    j = jnp.arange(T1, dtype=jnp.int32)[None, :]  # [1, T1] = proposal pos

    # row-range bounds of the recomputed column (model.jl:263)
    jc = jnp.minimum(j + 1, tlen)
    rmin = jnp.maximum(0, jc - off)
    rmax = jnp.minimum(jc + v_off + geom.bandwidth, slen)

    # B[:, pos+1] for every pos at once
    jnext = jnp.minimum(jnp.arange(T1, dtype=jnp.int32) + 1, tlen)
    B_next = jnp.take(B, jnext, axis=1)  # [K, T1]
    neg_row = jnp.full((1, T1), NEG_INF, dtype)
    A_up = jnp.concatenate([A[1:], neg_row], axis=0)  # A[d+1, j]
    A_dn = jnp.concatenate([neg_row, A[:-1]], axis=0)  # A[d-1, j]

    # --- deletions: join A[:, pos] with B[:, pos+1] one data row down ---
    B_next_sh = jnp.concatenate([neg_row, B_next[:-1]], axis=0)
    dele = jnp.max(A + B_next_sh, axis=0)  # [T1]; valid for pos < tlen

    def edit_scores(i, m_src, d_src, B_join):
        """Sub/ins share this: new column from (m_src, d_src) at true row
        index i[d, j], joined with B_join — for all positions and all 4
        bases. The score-table gathers are per-table, shared by bases."""
        si = jnp.clip(i - 1, 0, L - 1)
        sq = seq[si]
        mt = match[si]
        mm = mismatch[si]
        gi = ins[si]
        dl = dels[jnp.clip(i, 0, L)]
        valid = (i >= rmin) & (i <= rmax)
        dcand = d_src + dl
        g = jnp.where((i >= 1) & valid, gi, jnp.zeros_like(gi))
        G = jnp.cumsum(g, axis=0)
        outs = []
        for b in range(4):
            msc = jnp.where(sq == b, mt, mm)
            mcand = jnp.where(i >= 1, m_src + msc, NEG_INF)
            cand = jnp.where(valid, jnp.maximum(mcand, dcand), NEG_INF)
            NC = G + jax.lax.cummax(cand - G, axis=0)
            NC = jnp.where(valid, NC, NEG_INF)
            outs.append(jnp.max(NC + B_join, axis=0))
        return jnp.stack(outs, axis=-1)  # [T1, 4]

    # substitution at pos: new column in frame pos+1, joined with B[:, pos+1]
    subs = edit_scores(d + j + 1 - off, A, A_up, B_next)
    # insertion after pos: new column in frame pos, joined with B[:, pos]
    insr = edit_scores(d + j - off, A_dn, A, B)
    return subs, insr, dele


_dense_batch = jax.vmap(_dense_one_read, in_axes=(0, 0, 0, 0, 0, 0, 0, 0))


@jax.jit
def _dense_total(A, B, seq, match, mismatch, ins, dels, geom, weights):
    subs, insr, dele = _dense_batch(A, B, seq, match, mismatch, ins, dels, geom)

    def wsum(x):
        w = weights.reshape((-1,) + (1,) * (x.ndim - 1))
        # mask BEFORE multiplying: 0 * -inf must not poison the total
        return jnp.sum(jnp.where(w > 0, x, 0.0) * w, axis=0)

    return wsum(subs), wsum(insr), wsum(dele)


def score_all_edits(
    A_bands,
    B_bands,
    batch: ReadBatch,
    geom: BandGeometry,
    weights=None,
):
    """Batch-total score tables for every single-base edit.

    Returns (sub [T1, 4], ins [T1, 4], del [T1]) — already summed over
    reads on device (psum over a sharded read axis). Positions >= the true
    template length are garbage; slice before use.
    """
    if weights is None:
        weights = jnp.ones(batch.n_reads, dtype=A_bands.dtype)
    return _dense_total(
        A_bands,
        B_bands,
        jnp.asarray(batch.seq),
        jnp.asarray(batch.match),
        jnp.asarray(batch.mismatch),
        jnp.asarray(batch.ins),
        jnp.asarray(batch.dels),
        geom,
        jnp.asarray(weights),
    )
