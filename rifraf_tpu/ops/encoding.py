"""Packed input encoding: 2-bit base codes + int8-quantized score planes.

The fused step is data-movement-bound even after the PR-10 band-store
narrowing — the next biggest resident inputs are the read-code table and
the four per-base score planes the fill/dense/stats kernels stream every
grid step (roofline: 5 halo'd [CB, Npad] f32 blocks per step per
stream). This module is the single definition of the opt-in
``input_enc="packed"`` wire format those kernels decode at VMEM load:

- **Bases pack 2-bit.** Codes are in {0, 1, 2, 3}; padding/fill rows
  carry garbage after ``& 3`` but every kernel consumes the code table
  under its validity mask (``0 <= i <= slen`` and the per-lane band
  limits), so decoded garbage never reaches an output. Packing happens
  AFTER halo blocking: each ``[S, CB, Npad]`` int32 block stacks 16
  code rows per int32 word along the sublane axis (CB padded up to a
  multiple of 16), giving a ``[S, ceil16(CB)//16, Npad]`` word table —
  16x fewer sublanes than the int8-widened-to-int32 plane it replaces,
  and the in-kernel unpack is 16 shift-and-mask ops per grid step.

- **Score planes quantize to int8 per read.** Every plane is affine in
  the read's ``error_log_p`` plus a shared penalty, so one
  (scale, offset) pair per read per plane bounds the quantization error:
  ``scale = max(hi - lo, eps) / 254`` over the read's true-length
  positions, ``q = clip(round((v - lo) / scale) - 127, -127, 127)``,
  ``dequant = q * scale + offset`` with ``offset = lo + 127 * scale``.
  The absolute dequantization error is ``<= scale / 2`` at every
  in-range position (quantize_error_bound). Kernels dequantize the
  whole [CB, lanes] block to f32 once per grid step and run every
  max-plus candidate wide — accumulate-wide exactly like the PR-10
  bf16 band store.

The default ``input_enc="f32"`` path never touches this module's wire
format: the f32 kernels read the same refs with the same zero-cast
windows as before, bit-identical end to end.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

CODES_PER_WORD = 16  # 2-bit codes per int32 word
QLEVELS = 254  # int8 payload levels: q in [-127, 127]
QEPS = 1e-6  # scale floor for constant planes (error <= QEPS / 508)


def ceil16(n: int) -> int:
    """Round up to a multiple of CODES_PER_WORD."""
    return ((n + CODES_PER_WORD - 1) // CODES_PER_WORD) * CODES_PER_WORD


def packed_rows(CB: int) -> int:
    """Sublane rows of the packed code table for a CB-row block."""
    return ceil16(CB) // CODES_PER_WORD


def pack_codes_blocked(blocked):
    """Pack a halo-blocked code table ``[S, CB, lanes]`` (any int dtype;
    values are taken mod 4, so the -9 pad sentinel packs as garbage) to
    ``[S, ceil16(CB)//16, lanes]`` int32: word row q of block s holds
    code rows ``{w * CBp + q : w in 0..15}`` in bit field ``2w``, the
    layout ``unpack_codes`` inverts with a sublane concatenation."""
    S, CB, lanes = blocked.shape
    CB16 = ceil16(CB)
    CBp = CB16 // CODES_PER_WORD
    codes = blocked.astype(jnp.int32) & 3
    codes = jnp.pad(codes, ((0, 0), (0, CB16 - CB), (0, 0)))
    codes = codes.reshape(S, CODES_PER_WORD, CBp, lanes)
    shifts = (2 * jnp.arange(CODES_PER_WORD, dtype=jnp.int32)).reshape(
        1, CODES_PER_WORD, 1, 1
    )
    # slot 15 sets bits 30-31: the sum wraps the int32 sign bit, which
    # is fine — unpack masks every extracted field with & 3
    return jnp.sum(codes << shifts, axis=1).astype(jnp.int32)


def unpack_codes(pk):
    """Unpack one packed word block ``[CBp, lanes]`` int32 back to
    ``[CBp * 16, lanes]`` int32 codes (the first CB rows match the
    packed input's codes mod 4; the tail is pad). Pure shift/mask jnp —
    safe inside a Pallas kernel body, where it runs once per grid step.
    The arithmetic shift's sign extension at slot 15 is masked by
    ``& 3``."""
    return jnp.concatenate(
        [(pk >> (2 * s)) & 3 for s in range(CODES_PER_WORD)], axis=0
    )


def quantize_rows(vals, mask, eps: float = QEPS):
    """Per-row affine int8 quantization of a score plane.

    ``vals`` is ``[N, L]`` float, ``mask`` the same-shape validity mask
    (True-length positions). Returns ``(q, scale, offset)`` with ``q``
    int8 ``[N, L]``, ``scale``/``offset`` f32 ``[N]`` such that
    ``q * scale + offset`` reconstructs every masked value to within
    ``scale / 2`` (quantize_error_bound). Rows with an empty mask get
    scale = eps / QLEVELS and offset 0 (their values are never read)."""
    vals = vals.astype(jnp.float32)
    big = jnp.float32(jnp.finfo(jnp.float32).max)
    any_valid = jnp.any(mask, axis=1)
    lo = jnp.min(jnp.where(mask, vals, big), axis=1)
    hi = jnp.max(jnp.where(mask, vals, -big), axis=1)
    lo = jnp.where(any_valid, lo, 0.0)
    hi = jnp.where(any_valid, hi, 0.0)
    scale = jnp.maximum(hi - lo, eps) / QLEVELS
    offset = lo + 127.0 * scale
    q = jnp.round((vals - lo[:, None]) / scale[:, None]) - 127.0
    q = jnp.clip(q, -127.0, 127.0).astype(jnp.int8)
    return q, scale.astype(jnp.float32), offset.astype(jnp.float32)


def dequantize_rows(q, scale, offset):
    """Inverse of quantize_rows: ``q * scale + offset`` in f32."""
    return (
        q.astype(jnp.float32) * scale[:, None].astype(jnp.float32)
        + offset[:, None].astype(jnp.float32)
    )


def quantize_error_bound(scale):
    """Per-read absolute error bound of the int8 round trip: half a
    quantization step. Property-tested in tests/test_input_encoding.py."""
    return 0.5 * scale


def dequant_block(block_ref0, scale_row, offset_row):
    """In-kernel dequantization of one loaded int8 table block
    ``[CB, lanes]`` against per-lane ``[lanes]`` scale/offset rows —
    the accumulate-wide load every packed kernel shares."""
    return (
        block_ref0.astype(jnp.float32) * scale_row[None, :]
        + offset_row[None, :]
    )


VALID_INPUT_ENCS = ("f32", "packed")


def check_input_enc(input_enc: str) -> str:
    """Validate and return the encoding knob (shared by params/engine/
    sweep/serve plumbing)."""
    if input_enc not in VALID_INPUT_ENCS:
        raise ValueError(
            f"input_enc must be one of {VALID_INPUT_ENCS}, got "
            f"{input_enc!r}"
        )
    return input_enc


@functools.partial(jax.jit, static_argnames=())
def _roundtrip_codes(blocked):
    """Test helper: pack then unpack, cropped to the input rows."""
    S, CB, lanes = blocked.shape
    pk = pack_codes_blocked(blocked)
    un = jax.vmap(unpack_codes)(pk)
    return un[:, :CB, :]
