from .sample import (
    hmm_sample,
    sample_from_template,
    sample_mixture,
    sample_reference,
    sample_sequences,
)
