"""Generative read simulator: the framework's test-data factory.

Mirrors /root/reference/src/sample.jl. An HMM walks a template emitting
substitution/insertion/deletion errors proportional to per-base error
probability (codon-indel mode for references); per-read quality tracks
follow an Exponential phred offset plus Gaussian jitter in the phred
domain, so "actual" and "reported" error probabilities differ like real
sequencer quality strings do.

All randomness flows through a numpy Generator for reproducibility (the
reference uses Julia's global RNG).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from ..models.errormodel import ErrorModel
from ..utils.phred import p_to_phred

MIN_PROB = 1e-10
MAX_PROB = 0.5


def random_seq(rng: np.random.Generator, n: int) -> np.ndarray:
    return rng.integers(0, 4, size=n).astype(np.int8)


def mutate_base(rng: np.random.Generator, base: int) -> int:
    """sample.jl:5-11."""
    return int((base + rng.integers(1, 4)) % 4)


def mutate_seq(rng: np.random.Generator, seq: np.ndarray, n_diffs: int) -> np.ndarray:
    """Mutate `n_diffs` random positions (sample.jl:13-20; positions drawn
    with replacement, as in the reference)."""
    seq = seq.copy()
    positions = rng.integers(0, len(seq), size=n_diffs)
    for i in positions:
        seq[i] = mutate_base(rng, seq[i])
    return seq


def jitter_phred_domain(
    rng: np.random.Generator, x: np.ndarray, phred_std: float
) -> np.ndarray:
    """Independent Gaussian noise in the phred domain (sample.jl:35-42)."""
    error = rng.standard_normal(len(x)) * phred_std / 10.0
    result = np.power(10.0, np.log10(x) + error)
    return np.clip(result, MIN_PROB, MAX_PROB)


def hmm_sample(
    rng: np.random.Generator,
    sequence: np.ndarray,
    error_p: np.ndarray,
    errors: ErrorModel,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """The generative error walk (sample.jl:44-123).

    Returns (read, per-base error probs, seqbools, tbools): seqbools[j]
    marks read base j as correctly sequenced; tbools[j] marks template
    base j as correctly represented.
    """
    errors = errors.normalize()
    codon = errors.codon_insertion > 0.0 or errors.codon_deletion > 0.0
    if codon and (errors.insertion > 0.0 or errors.deletion > 0.0):
        raise ValueError("codon and non-codon indels are not both allowed")
    sub_ratio = errors.mismatch
    ins_ratio = errors.codon_insertion if codon else errors.insertion
    del_ratio = errors.codon_deletion if codon else errors.deletion

    final_seq: List[int] = []
    final_error_p: List[float] = []
    seqbools: List[bool] = []
    tbools: List[bool] = []
    skip = 0
    n = len(sequence)
    for i in range(n + 1):
        p = error_p[i - 1] if i >= n else error_p[i]
        prev_p = error_p[0] if i == 0 else error_p[i - 1]
        # insertion before position i
        max_p = max(p, prev_p)
        ins_p = max_p * ins_ratio
        if codon:
            ins_p /= 3.0
        if rng.random() < ins_p:
            if codon:
                final_seq.extend(int(b) for b in random_seq(rng, 3))
                final_error_p.extend([max_p] * 3)
                seqbools.extend([False] * 3)
            else:
                final_seq.append(int(random_seq(rng, 1)[0]))
                final_error_p.append(max_p)
                seqbools.append(False)
        if i >= n:
            break
        # only skip after insertions, to ensure equal probability of
        # insertions and deletions (sample.jl:92-95)
        if skip > 0:
            skip -= 1
            continue
        # deletion of position i
        if codon:
            if i > n - 3:
                del_p = 0.0
            else:
                del_p = float(np.max(error_p[i : i + 3])) * del_ratio / 3.0
        else:
            del_p = p * del_ratio
        if rng.random() < del_p:
            skip = 2 if codon else 0
            tbools.extend([False] * (skip + 1))
        else:
            if rng.random() < p * sub_ratio:
                final_seq.append(mutate_base(rng, sequence[i]))
                seqbools.append(False)
                tbools.append(False)
            else:
                final_seq.append(int(sequence[i]))
                seqbools.append(True)
                tbools.append(True)
            final_error_p.append(p)
    return (
        np.array(final_seq, dtype=np.int8),
        np.array(final_error_p),
        np.array(seqbools, dtype=bool),
        np.array(tbools, dtype=bool),
    )


def sample_reference(
    rng: np.random.Generator,
    template: np.ndarray,
    error_rate: float,
    errors: ErrorModel,
) -> np.ndarray:
    """Codon-only errors; length forced to a multiple of 3
    (sample.jl:125-144)."""
    norm = errors.normalize()
    if norm.insertion > 0.0 or norm.deletion > 0.0:
        raise ValueError("non-codon indels are not allowed in reference")
    error_p = error_rate * np.ones(len(template))
    reference, _, _, _ = hmm_sample(rng, template, error_p, errors)
    if len(reference) % 3 == 1:
        idx = int(rng.integers(0, len(reference)))
        reference = np.delete(reference, idx)
    elif len(reference) % 3 == 2:
        idx = int(rng.integers(0, len(reference) + 1))
        reference = np.insert(reference, idx, random_seq(rng, 1)[0])
    return reference


def sample_from_template(
    rng: np.random.Generator,
    template: np.ndarray,
    template_error_p: np.ndarray,
    errors: ErrorModel,
    phred_scale: float,
    actual_std: float,
    reported_std: float,
):
    """One read: exponential phred offset + Gaussian jitter
    (sample.jl:146-171)."""
    errors = errors.normalize()
    if errors.codon_insertion > 0.0 or errors.codon_deletion > 0.0:
        raise ValueError("codon indels are not allowed in sequences")
    offset = rng.exponential(phred_scale)
    base_vector = np.power(
        10.0, (-10.0 * np.log10(template_error_p) + offset) / (-10.0)
    )
    jittered_error_p = jitter_phred_domain(rng, base_vector, actual_std)
    seq, actual_error_p, sbools, tbools = hmm_sample(
        rng, template, jittered_error_p, errors
    )
    reported_error_p = jitter_phred_domain(rng, actual_error_p, reported_std)
    phreds = p_to_phred(reported_error_p)
    return seq, actual_error_p, phreds, sbools, tbools


def sample_mixture(
    nseqs: Tuple[int, int],
    length: int,
    n_diffs: int,
    ref_error_rate: float = 0.1,
    ref_errors: ErrorModel = ErrorModel(10, 0, 0, 1, 0),
    error_rate: float = 0.01,
    alpha: float = 0.1,
    phred_scale: float = 1.5,
    actual_std: float = 3.0,
    reported_std: float = 1.0,
    seq_errors: ErrorModel = ErrorModel(1, 5, 5),
    rng: Optional[np.random.Generator] = None,
):
    """Two templates differing at n_diffs positions; reads from both
    (sample.jl:173-220)."""
    if rng is None:
        rng = np.random.default_rng()
    template1 = random_seq(rng, length)
    template2 = mutate_seq(rng, template1, n_diffs)
    templates = [template1, template2]

    reference = sample_reference(rng, template1, ref_error_rate, ref_errors)

    # four-parameter Beta distribution of per-base template error rates
    beta = alpha * (error_rate - MAX_PROB) / (MIN_PROB - error_rate)
    template_error_p = (
        rng.beta(alpha, beta, size=length) * (MAX_PROB - MIN_PROB) + MIN_PROB
    )

    seqs, actual_error_ps, phreds, seqbools, tbools = [], [], [], [], []
    for t, n in zip(templates, nseqs):
        for _ in range(n):
            seq, actual_error_p, phred, cb, db = sample_from_template(
                rng, t, template_error_p, seq_errors, phred_scale,
                actual_std, reported_std,
            )
            seqs.append(seq)
            actual_error_ps.append(actual_error_p)
            phreds.append(phred)
            seqbools.append(cb)
            tbools.append(db)
    return (
        reference,
        templates,
        template_error_p,
        seqs,
        actual_error_ps,
        phreds,
        seqbools,
        tbools,
    )


def sample_sequences(
    nseqs: int = 3,
    length: int = 90,
    rng: Optional[np.random.Generator] = None,
    **kwargs,
):
    """Single-template convenience wrapper (sample.jl:277-298)."""
    (ref, templates, t_p, seqs, actual, phreds, cb, db) = sample_mixture(
        (nseqs, 0), length, 0, rng=rng, **kwargs
    )
    return ref, templates[0], t_p, seqs, actual, phreds, cb, db
