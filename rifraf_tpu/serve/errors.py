"""Typed request-level errors of the online consensus service.

Every rejection the server can issue is a distinct exception type with a
stable machine-readable ``code`` (the JSONL ``error`` field of the
``rifraf-serve`` CLI). A rejected request NEVER stalls the micro-batch it
would have joined: oversize and past-deadline requests are peeled off at
admission or at pack time, and queue overflow is reported to the caller
synchronously (backpressure) instead of blocking the submit.
"""

from __future__ import annotations


class ServeError(Exception):
    """Base class for request-level serving errors."""

    code = "serve_error"


class QueueFullError(ServeError):
    """The bounded admission queue is at capacity — the caller should
    back off and retry (the backpressure signal)."""

    code = "queue_full"


class SheddedError(ServeError):
    """Deadline-aware load shedding (``ServeConfig.shed``): at
    admission the server estimated that queued work ahead of this
    request would consume its whole deadline budget, so it was shed
    immediately with a ``retry_after_s`` hint instead of being queued
    to time out. Unlike ``QueueFullError`` (a hard capacity cliff),
    shedding is proportional: requests with generous deadlines are
    still admitted while doomed ones are refused the moment they
    arrive — under sustained overload the server degrades to a
    predictable admitted-availability instead of timing everything
    out."""

    code = "shedded"

    def __init__(self, message: str = "", retry_after_s: float = 0.0):
        super().__init__(message)
        self.retry_after_s = float(retry_after_s)


class DeadlineExceededError(ServeError):
    """The request's deadline passed before it could be dispatched."""

    code = "deadline_exceeded"


class OversizeError(ServeError):
    """The request exceeds the server's hard shape limits (``max_len`` /
    ``max_reads``) and cannot be served at all. Requests that merely
    exceed the BATCHED grid (``batch_max_len`` / ``batch_max_reads`` /
    ``batch_max_band``) are not rejected — they fall back to the
    per-cluster device loop as singletons."""

    code = "oversize"


class EmptyClusterError(ServeError):
    """The request carries no reads."""

    code = "empty_cluster"


class InvalidRequestError(ServeError):
    """The request failed the typed validation pass at admission
    (``engine.validate``): zero-length reads, malformed cluster shape —
    input that would otherwise surface as an opaque shape error deep
    inside jit. The underlying ``InvalidInputError.code`` (e.g.
    ``zero_length_read``) is preserved in the message."""

    code = "invalid_input"


class ServerClosedError(ServeError):
    """submit() after close(), or a request abandoned by close(): the
    drain deadline expired with the request still unresolved, so the
    server resolved its future with this error instead of leaving it
    hanging (the no-hung-futures-ever invariant)."""

    code = "server_closed"


class WorkerCrashError(ServeError):
    """The worker thread died (crashed) while this request was in
    flight, and the request could not be recovered: its retry budget
    was already spent, or the supervisor's restart cap was reached
    (the server is unhealthy). Requests WITH budget are re-run on the
    restarted worker instead of receiving this error."""

    code = "worker_crash"


class ServerUnhealthyError(ServeError):
    """submit() while the server is unhealthy: the supervisor exhausted
    its worker-restart cap (crash loop) and stopped taking traffic."""

    code = "server_unhealthy"


class WaitTimeoutError(ServeError):
    """A synchronous wait on a request's result exceeded its timeout
    (``ServeConfig.result_timeout_s`` or the deadline-derived bound).
    The convenience waiters (``submit_many``, the CLI drain) convert
    this into an ``ok=False`` response instead of blocking forever on
    a dead worker."""

    code = "wait_timeout"
