"""Deterministic fault injection for the serving substrate.

Production hardening needs failures on demand: a transient device error,
a worker thread dying mid-flush, a slow fetch — none of which occur
naturally on a healthy dev box. A ``FaultPlan`` injects typed exceptions
or delays at named sites, deterministically (by invocation count and/or
a seeded Bernoulli draw), so the chaos test grid and the bench chaos
config can provoke every failure path reproducibly.

Sites (fired by the server/worker at the matching point):

- ``ingest``   — ``io.stream``'s record loop, once per accepted record
  (an injected ``error`` quarantines the record; ``crash`` kills the
  ingesting process like a real truncation-at-the-worst-moment);
- ``admit``    — ``ConsensusServer.submit``, after validation, before
  the request enters the admission queue (raises to the CALLER);
- ``pack``     — ``Worker._pack``, the host-side batch build;
- ``compile``  — ``Worker.plan_for``/``seg_plan_for``, where the
  lru-cached program factories are keyed;
- ``dispatch`` — ``Worker._run``, before the device dispatch;
- ``fetch``    — ``Worker._collect``, before the blocking fetch;
- ``fallback`` — ``Worker._run_fallback``, the per-cluster device loop.

Kinds:

- ``error`` raises ``InjectedFaultError`` — a plain ``RuntimeError``
  subclass, deliberately NOT a ``ServeError``, so it travels the
  unexpected-exception paths (pipeline isolation, the retry ladder);
- ``crash`` raises ``InjectedCrashError`` — a ``BaseException``
  subclass that escapes every ``except Exception`` handler and kills
  the worker thread outright (the SIGKILL-style death the supervisor
  watchdog exists for);
- ``delay`` sleeps ``ms`` milliseconds (stall/watchdog testing);
- ``corrupt`` raises NOTHING: the consumer polls ``plan.corrupt(site)``
  at the fetch site and, when it answers, deterministically perturbs
  the fetched value (``corrupt_value`` — a float64 bit flip, ``bit=``
  selects which). The silent-wrong-answer injection the
  result-integrity layer (shadow verification, device quarantine)
  exists to catch: no exception, no crash, just a plausible wrong
  number.

Spec grammar (``RIFRAF_TPU_FAULTS`` env var or ``ServeConfig.faults``)::

    specs   := spec (";" spec)*
    spec    := site ":" kind [":" opts]
    opts    := opt ("," opt)*
    opt     := "n=" int      max fires (default 1; 0 = unlimited)
             | "after=" int  skip the first N invocations of the site
             | "p=" float    fire probability (seeded Bernoulli)
             | "seed=" int   RNG seed for p (default 0)
             | "ms=" float   delay milliseconds (kind=delay)
             | "bit=" int    float64 bit to flip (kind=corrupt,
                             default 51 — the top mantissa bit)

e.g. ``"dispatch:error:n=2;fetch:delay:ms=50;fetch:corrupt:n=3"``.
All counting is thread-safe; ``snapshot()`` reports per-site invocation
and per-spec fire counts for ``ConsensusServer.health()``.
"""

from __future__ import annotations

import os
import random
import struct
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

ENV_VAR = "RIFRAF_TPU_FAULTS"

SITES = ("ingest", "admit", "pack", "compile", "dispatch", "fetch",
         "fallback")
KINDS = ("error", "crash", "delay", "corrupt")

# default corrupt bit: the float64 top mantissa bit — a large, finite,
# sign-preserving relative error (the classic silent bit-flip)
CORRUPT_BIT = 51


def corrupt_value(x: float, bit: int = CORRUPT_BIT) -> float:
    """Deterministically flip one bit of ``x``'s float64 representation.
    The injected silent corruption: finite in, (usually) finite out,
    numerically wrong."""
    b = struct.unpack("<Q", struct.pack("<d", float(x)))[0]
    b ^= 1 << (int(bit) % 64)
    return struct.unpack("<d", struct.pack("<Q", b))[0]


class InjectedFaultError(RuntimeError):
    """An injected recoverable fault (kind="error"). Not a ServeError:
    it must look like an unexpected internal failure to every handler."""


class InjectedCrashError(BaseException):
    """An injected thread-killing fault (kind="crash"). Derives from
    BaseException so ``except Exception`` isolation (pipeline_map, the
    worker loop wrap) does NOT contain it — the hosting thread dies,
    which is the scenario the supervisor watchdog recovers from."""


@dataclass
class FaultSpec:
    """One injection rule at one site."""

    site: str
    kind: str  # "error" | "crash" | "delay" | "corrupt"
    n: int = 1  # max fires; 0 = unlimited
    after: int = 0  # skip the first `after` invocations of the site
    p: float = 1.0  # fire probability per eligible invocation
    seed: int = 0  # Bernoulli RNG seed (deterministic across runs)
    ms: float = 0.0  # delay milliseconds (kind="delay")
    bit: int = CORRUPT_BIT  # float64 bit to flip (kind="corrupt")
    fired: int = 0  # mutable: how many times this spec has fired
    _rng: random.Random = field(default=None, repr=False)  # type: ignore

    def __post_init__(self):
        if self.site not in SITES:
            raise ValueError(
                f"unknown fault site {self.site!r} (sites: {SITES})"
            )
        if self.kind not in KINDS:
            raise ValueError(
                f"unknown fault kind {self.kind!r} (kinds: {KINDS})"
            )
        self._rng = random.Random(self.seed)


class FaultPlan:
    """A thread-safe set of ``FaultSpec`` rules plus fire accounting."""

    def __init__(self, specs: Sequence[FaultSpec] = ()):
        self.specs: List[FaultSpec] = list(specs)
        self._lock = threading.Lock()
        self._site_calls: Dict[str, int] = {}

    def __bool__(self) -> bool:
        return bool(self.specs)

    # ---- construction ----

    @classmethod
    def parse(cls, text: Optional[str]) -> "FaultPlan":
        """Parse the spec grammar (see module docstring). Empty/None
        yields an inert plan."""
        specs: List[FaultSpec] = []
        for raw in (text or "").split(";"):
            raw = raw.strip()
            if not raw:
                continue
            parts = raw.split(":", 2)
            if len(parts) < 2:
                raise ValueError(
                    f"fault spec {raw!r} needs at least site:kind"
                )
            site, kind = parts[0].strip(), parts[1].strip()
            kw: dict = {}
            if len(parts) == 3 and parts[2].strip():
                for opt in parts[2].split(","):
                    k, _, v = opt.partition("=")
                    k = k.strip()
                    if not _:
                        raise ValueError(
                            f"fault option {opt!r} is not key=value"
                        )
                    if k in ("n", "after", "seed", "bit"):
                        kw[k] = int(v)
                    elif k in ("p", "ms"):
                        kw[k] = float(v)
                    else:
                        raise ValueError(f"unknown fault option {k!r}")
            specs.append(FaultSpec(site=site, kind=kind, **kw))
        return cls(specs)

    @classmethod
    def from_env(cls) -> "FaultPlan":
        return cls.parse(os.environ.get(ENV_VAR, ""))

    # ---- the injection point ----

    def fire(self, site: str) -> None:
        """One named-site invocation: count it, then let each matching
        live spec act — delays sleep, errors/crashes raise. Thread-safe;
        the sleep happens outside the lock."""
        if not self.specs:
            return
        delay_s = 0.0
        to_raise: Optional[BaseException] = None
        with self._lock:
            idx = self._site_calls.get(site, 0)
            self._site_calls[site] = idx + 1
            for s in self.specs:
                if s.site != site or s.kind == "corrupt":
                    continue
                if s.n and s.fired >= s.n:
                    continue
                if idx < s.after:
                    continue
                if s.p < 1.0 and s._rng.random() >= s.p:
                    continue
                s.fired += 1
                if s.kind == "delay":
                    delay_s += s.ms / 1e3
                elif s.kind == "error":
                    to_raise = InjectedFaultError(
                        f"injected fault at site {site!r} "
                        f"(invocation {idx})"
                    )
                    break
                else:  # crash
                    to_raise = InjectedCrashError(
                        f"injected crash at site {site!r} "
                        f"(invocation {idx})"
                    )
                    break
        if delay_s:
            time.sleep(delay_s)
        if to_raise is not None:
            raise to_raise

    def corrupt(self, site: str) -> Optional[int]:
        """The silent sibling of :meth:`fire` for ``kind="corrupt"``
        specs: returns the bit to flip when a matching spec fires, else
        None. Counted on a separate per-site key (``site~corrupt``) so
        corrupt ``after=`` gating does not interact with the raising
        kinds' invocation counts. Never raises — the whole point is
        that the caller hands on a plausibly wrong value."""
        if not self.specs:
            return None
        with self._lock:
            key = site + "~corrupt"
            idx = self._site_calls.get(key, 0)
            self._site_calls[key] = idx + 1
            for s in self.specs:
                if s.site != site or s.kind != "corrupt":
                    continue
                if s.n and s.fired >= s.n:
                    continue
                if idx < s.after:
                    continue
                if s.p < 1.0 and s._rng.random() >= s.p:
                    continue
                s.fired += 1
                return s.bit
        return None

    # ---- observability ----

    def snapshot(self) -> dict:
        """JSON-serializable fire accounting for health()."""
        with self._lock:
            return {
                "site_calls": dict(self._site_calls),
                "specs": [
                    {"site": s.site, "kind": s.kind, "n": s.n,
                     "after": s.after, "p": s.p, "fired": s.fired}
                    for s in self.specs
                ],
            }


def resolve_faults(spec) -> FaultPlan:
    """ServeConfig.faults -> FaultPlan: pass a FaultPlan through, parse
    a spec string, and fall back to the ``RIFRAF_TPU_FAULTS`` env var
    for None (so a chaos run can be configured without code changes)."""
    if isinstance(spec, FaultPlan):
        return spec
    if isinstance(spec, str):
        return FaultPlan.parse(spec)
    if spec is None:
        return FaultPlan.from_env()
    raise TypeError(f"faults must be FaultPlan | str | None, got {spec!r}")
