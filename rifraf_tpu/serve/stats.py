"""Serving metrics: counters, latency percentiles, batch occupancy.

Built on utils.timers.Timers for the wall-clock sections (pack /
dispatch / fetch / fallback / warmup) and a bounded latency reservoir
for the percentiles; ``snapshot()`` is the JSON-serializable export the
CLI prints and bench.py emits (Timers.to_dict does the timer half).
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Dict, Optional

import numpy as np

from ..utils.timers import Timers

# newest-N latency reservoir: enough for stable p99 at bench scale
# without unbounded growth in a long-lived server
LATENCY_WINDOW = 65536


class ServerStats:
    """Thread-safe rollup of everything the server observes."""

    def __init__(self):
        self._lock = threading.Lock()
        self.timers = Timers()
        self._counters: Dict[str, int] = {}
        self._latencies = deque(maxlen=LATENCY_WINDOW)
        # micro-batch shape accounting (the SweepStats analogue)
        self._batches = 0
        self._batched_requests = 0
        self._padded_slots = 0
        self._useful_cells = 0
        self._padded_cells = 0
        self._useful_lanes = 0
        self._cluster_lanes = 0
        self._lane_slots = 0
        self._spec_overhead_lanes = 0
        self._model_bytes = 0.0
        self._declines: Dict[str, int] = {}
        # load estimators (elastic scaling + admission control):
        # exponentially weighted means of per-request service seconds
        # (dispatch -> resolve) and queue-wait seconds (submit ->
        # dispatch). None until the first observation
        self._service_ewma: Optional[float] = None
        self._queue_wait_ewma: Optional[float] = None

    # EWMA smoothing for the load estimators: heavy enough to ride out
    # micro-batch size jitter, light enough to track a load shift
    # within a few dozen requests
    EWMA_ALPHA = 0.2

    def count(self, name: str, k: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + k

    def get(self, name: str) -> int:
        with self._lock:
            return self._counters.get(name, 0)

    def note_service(self, seconds: float) -> None:
        """One request's service time (first pickup by a worker to
        resolution) into the shed estimator's EWMA."""
        with self._lock:
            prev = self._service_ewma
            self._service_ewma = (
                seconds if prev is None
                else prev + self.EWMA_ALPHA * (seconds - prev)
            )

    def note_queue_wait(self, seconds: float) -> None:
        """One request's time-in-queue (submit to first worker pickup)
        into the elastic scale-up signal's EWMA."""
        with self._lock:
            prev = self._queue_wait_ewma
            self._queue_wait_ewma = (
                seconds if prev is None
                else prev + self.EWMA_ALPHA * (seconds - prev)
            )

    def service_estimate(self) -> Optional[float]:
        with self._lock:
            return self._service_ewma

    def queue_wait_estimate(self) -> Optional[float]:
        with self._lock:
            return self._queue_wait_ewma

    def observe_latency(self, seconds: float) -> None:
        with self._lock:
            self._latencies.append(seconds)

    def note_batch(self, n_real: int, gp: int, useful_cells: int,
                   padded_cells: int, useful_lanes: int = 0,
                   lane_slots: int = 0, cluster_lanes: int = 0,
                   spec_overhead_lanes: int = 0) -> None:
        """One dispatched micro-batch: ``n_real`` live requests padded
        to a ``gp``-cluster chunk of ``padded_cells`` read-lane cells
        occupying ``lane_slots`` hardware 128-lane slots, of which
        ``cluster_lanes`` belong to a real request's Npad block and
        ``useful_lanes`` carried a real read. ``spec_overhead_lanes``
        counts the extra segment copies a speculative stage launch
        (ServeConfig.speculate_k) tiles alongside the demand lanes —
        overhead, not demand, so it is tracked apart from ``lane_slots``
        and the lane-occupancy ratios stay comparable across
        speculation settings."""
        with self._lock:
            self._batches += 1
            self._batched_requests += n_real
            self._padded_slots += gp
            self._useful_cells += useful_cells
            self._padded_cells += padded_cells
            self._useful_lanes += useful_lanes
            self._lane_slots += lane_slots
            self._cluster_lanes += cluster_lanes
            self._spec_overhead_lanes += spec_overhead_lanes

    def note_model_bytes(self, nbytes: float) -> None:
        """Fold one micro-batch's modelled HBM traffic (utils.roofline
        fused-step byte model x stage steps) into the running total the
        bench's pct_hbm_roof is computed from."""
        with self._lock:
            self._model_bytes += nbytes

    def note_declines(self, declines) -> None:
        """Fold a fallback run's RifrafResult.metadata["declines"] into
        per-reason counters (the server's reject/fallback observability
        without log parsing)."""
        with self._lock:
            for d in declines or ():
                key = f"{d['stage']}: {d['reason']}"
                self._declines[key] = self._declines.get(key, 0) + 1

    def _percentiles(self):
        lat = np.asarray(self._latencies, float)
        if lat.size == 0:
            return {}
        p50, p95, p99 = np.percentile(lat, [50, 95, 99])
        return {
            "p50": round(float(p50) * 1e3, 3),
            "p95": round(float(p95) * 1e3, 3),
            "p99": round(float(p99) * 1e3, 3),
            "mean": round(float(lat.mean()) * 1e3, 3),
            "max": round(float(lat.max()) * 1e3, 3),
            "n": int(lat.size),
        }

    def ladder(self) -> Dict[str, int]:
        """The degradation-ladder counters (``ladder_*``), un-prefixed:
        retries per rung, recoveries, budget exhaustions."""
        with self._lock:
            return {
                k[len("ladder_"):]: v
                for k, v in self._counters.items()
                if k.startswith("ladder_")
            }

    # counters the result-integrity layer emits (worker._note_trip,
    # _maybe_verify, golden_probe): collected for health()["integrity"]
    # and the bench chaos JSON line
    INTEGRITY_COUNTERS = (
        "guard_trips", "divergence_trips", "verify_sampled", "verify_ok",
        "verify_divergence", "verify_recovered", "verify_errors",
        "injected_corrupt", "device_quarantined", "device_reinstated",
        "probe_pass", "probe_fail", "quarantine_requeued",
    )

    def integrity(self) -> Dict[str, int]:
        """The result-integrity counters that are non-zero (sentinel
        trips, shadow-verification outcomes, quarantine lifecycle)."""
        with self._lock:
            return {
                k: self._counters[k]
                for k in self.INTEGRITY_COUNTERS
                if self._counters.get(k)
            }

    def snapshot(self, queue_depth: Optional[int] = None) -> dict:
        """JSON-serializable state: counters, occupancy, padding waste,
        latency percentiles (ms), decline reasons, timer sections."""
        with self._lock:
            out = {
                "counters": dict(self._counters),
                "retry_ladder": {
                    k[len("ladder_"):]: v
                    for k, v in self._counters.items()
                    if k.startswith("ladder_")
                },
                "batches": self._batches,
                "batch_occupancy": round(
                    self._batched_requests / self._padded_slots, 4
                ) if self._padded_slots else None,
                "padding_waste": round(
                    1.0 - self._useful_cells / self._padded_cells, 4
                ) if self._padded_cells else None,
                # slot fill (real requests' Npad blocks over hardware
                # 128-lane slots — what the lane-capacity flush
                # controls) and the read-level fill that further
                # discounts within-request padding to Npad
                "lane_occupancy": round(
                    self._cluster_lanes / self._lane_slots, 4
                ) if self._lane_slots else None,
                "lane_occupancy_reads": round(
                    self._useful_lanes / self._lane_slots, 4
                ) if self._lane_slots else None,
                # speculative segment copies (overhead, not demand —
                # excluded from the occupancy ratios above)
                "spec_overhead_lanes": self._spec_overhead_lanes,
                "model_gb": round(self._model_bytes / 1e9, 3),
                "service_ewma_ms": round(self._service_ewma * 1e3, 3)
                if self._service_ewma is not None else None,
                "queue_wait_ewma_ms": round(
                    self._queue_wait_ewma * 1e3, 3
                ) if self._queue_wait_ewma is not None else None,
                "latency_ms": self._percentiles(),
                "declines": dict(self._declines),
                "timers": self.timers.to_dict(),
            }
        if queue_depth is not None:
            out["queue_depth"] = queue_depth
        return out
