"""The dispatch worker: flushed micro-batches -> device -> responses.

Flushes stream through ``parallel.cluster.pipeline_map`` in bursts, so
the worker inherits the offline sweep's double-buffering: host packing
of batch k+1 overlaps device execution of batch k, and batch k's
blocking fetch happens only after k+1 has been dispatched. Per-job
error isolation (``on_error="return"``) means one failing micro-batch
fails ONLY its own requests — the batches behind it keep flowing.

Two flush kinds:

- ``"batch"``: a same-bucket micro-batch, packed/dispatched through the
  shared ChunkExecutor (the sweep's lru-cached program factories, so a
  serving signature and an offline sweep bucket share one executable);
- ``"fallback"``: an oversize singleton, run through the per-cluster
  device loop (``rifraf()`` in the sweep-equivalent configuration) so a
  3 kb outlier degrades gracefully instead of dragging a whole bucket's
  padded shape up with it.
"""

from __future__ import annotations

import time
from queue import Empty, Queue
from typing import List, NamedTuple

from ..parallel.cluster import PipelineJobError, pipeline_map
from ..parallel.sweep_sharded import (
    BucketPlan,
    ChunkExecutor,
    PackPlan,
    SegmentBucketPlan,
    SweepResult,
    _lane_slots,
)
from ..utils.shapes import bucket as _bucket
from ..utils.shapes import pack_segments, pow2_bucket
from .batcher import resolve_segment_pack, segment_eligible
from .errors import DeadlineExceededError, ServeError
from .request import Request, Response, ServeConfig
from .stats import ServerStats

STOP = object()  # flush-queue shutdown sentinel


def _batch_model_bytes(plan: BucketPlan, results: List[SweepResult]):
    """Modelled HBM traffic of one fetched micro-batch: the fused-step
    byte model at the batch's padded shape (lane-slot Npad — the
    [gp, N] read axes on 128-lane tiles) times its stage-step count
    (max member iterations; the vmapped while_loop runs until the last
    cluster converges). Adaptation rounds excluded — a floor."""
    from ..utils import roofline
    from ..utils.shapes import plan_cols

    N, _, Tmax, K0 = plan.key
    C = plan_cols(Tmax, K0, kernel="dense").cols
    steps = max((r.n_iters for r in results), default=0)
    return roofline.fused_model(
        Tmax, K0, _lane_slots(plan.gp, N), C
    )["bytes"] * steps


class Flush(NamedTuple):
    kind: str  # "batch" | "fallback"
    requests: List[Request]


class InternalError(ServeError):
    """A micro-batch failed in pack/dispatch/fetch; carries the cause."""

    code = "internal"


def respond_error(req: Request, err: ServeError, stats: ServerStats,
                  counter: str) -> None:
    if req.future.done():
        return
    lat = time.perf_counter() - req.t_submit
    stats.count(counter)
    req.future.set_result(Response(
        id=req.id, ok=False, error=err, latency_s=lat, path="rejected",
    ))


class Worker:
    """Owns the ChunkExecutor and the flush-queue consumer loop."""

    def __init__(self, config: ServeConfig, stats: ServerStats):
        self.config = config
        self.stats = stats
        self.segment_pack = resolve_segment_pack(config)
        self.executor = ChunkExecutor(
            mesh=config.mesh,
            max_iters=config.max_iters,
            min_dist=config.min_dist,
            bandwidth_pvalue=config.bandwidth_pvalue,
            do_alignment_proposals=config.do_alignment_proposals,
        )

    # ---- pipeline stages (pack on the background thread, run/collect
    # on the worker thread) ----

    def plan_for(self, key, n: int) -> BucketPlan:
        """One-chunk plan for a micro-batch of n clusters: the cluster
        axis rounds to the next power of two (and the mesh axis) so the
        number of distinct compiled batch shapes stays logarithmic."""
        mesh = self.config.mesh
        n_axis = mesh.devices.size if mesh is not None else 1
        gp = _bucket(pow2_bucket(n), max(n_axis, 1))
        return BucketPlan(key=key, band=self.config.band_bucket, gp=gp,
                          chunks=[list(range(n))])

    def _seg_batch(self, live: List[Request]) -> bool:
        """Whether a flushed micro-batch runs segment-packed: the
        server packs cross-request, every member carries its cluster
        info (the packer needs read counts and seed slots), and every
        member individually qualifies (the batcher's grouping
        guarantees this for its own flushes; drains can mix)."""
        return self.segment_pack and all(
            r.info is not None
            and segment_eligible(r.key, self.config.lane_target)
            for r in live
        )

    def seg_plan_for(self, requests: List[Request]):
        """Segmented one-chunk plan for a micro-batch: first-fit the
        requests' read counts into shared lane blocks
        (utils.shapes.pack_segments); member indices index into the
        flush's request list. The pack-count axis rounds to the next
        power of two (and the mesh axis) like plan_for."""
        cfg = self.config
        pk = pack_segments(
            [r.info.n_reads for r in requests], lanes=cfg.lane_target
        )
        npad = _bucket(pk.npad, cfg.read_bucket)
        packs = [
            PackPlan(
                members=list(blk),
                seg_ids=pk.seg_ids[b] + [0] * (npad - len(pk.seg_ids[b])),
            )
            for b, blk in enumerate(pk.blocks)
        ]
        mesh = cfg.mesh
        n_axis = mesh.devices.size if mesh is not None else 1
        gp = _bucket(pow2_bucket(len(packs)), max(n_axis, 1))
        # segment-grouped requests share the shape axes exactly; maxima
        # keep a mixed drain flush safe
        shape = tuple(
            max(r.key[i] for r in requests) for i in (1, 2, 3)
        )
        plan = SegmentBucketPlan(
            key=(npad,) + shape, band=cfg.band_bucket, sp=pk.n_seg,
            gp=gp, chunks=[packs],
        )
        return plan, packs

    def _pack(self, flush: Flush):
        if flush.kind != "batch":
            return flush, None
        now = time.perf_counter()
        live = []
        for r in flush.requests:
            if r.expired(now):
                respond_error(r, DeadlineExceededError(
                    f"request {r.id}: deadline passed before dispatch"
                ), self.stats, "rejected_deadline")
            else:
                live.append(r)
        if not live:
            return Flush("batch", []), None
        with self.stats.timers.time("serve_pack"):
            seg = self._seg_batch(live)
            key = live[0].key
            if seg:
                plan, packs = self.seg_plan_for(live)
                mesh = self.config.mesh
                n_axis = mesh.devices.size if mesh is not None else 1
                if (n_axis > 1 and len(packs) < n_axis
                        and len(live) > len(packs)):
                    # mesh decline (same rule as plan_sweep): the mesh
                    # shards the pack axis, and packing this flush into
                    # fewer packs than devices would serialize it while
                    # one-request-per-slot shards evenly. A seg group
                    # only shares the SHAPE axes, so the whole-block
                    # fallback pads to the flush's per-axis maxima.
                    seg = False
                    key = tuple(
                        max(r.key[i] for r in live) for i in range(4)
                    )
            if seg:
                packed = self.executor.pack_seg(
                    plan, packs, [r.cluster for r in live],
                    [r.info for r in live],
                )
            else:
                plan = self.plan_for(key, len(live))
                packed = self.executor.pack(
                    plan, range(len(live)), [r.cluster for r in live],
                    [r.info for r in live],
                )
        return Flush("batch", live), (plan, packed)

    def _run(self, arg):
        flush, staged = arg
        if flush.kind == "fallback":
            return flush, self._run_fallback(flush.requests[0])
        if staged is None:
            return flush, None
        plan, packed = staged
        seg = isinstance(plan, SegmentBucketPlan)
        with self.stats.timers.time("serve_dispatch"):
            handle = (self.executor.run_seg(packed) if seg
                      else self.executor.run(packed))
        N, L = plan.key[0], plan.key[1]
        n_reads = sum(r.info.n_reads for r in flush.requests)
        self.stats.note_batch(
            n_real=len(flush.requests), gp=plan.gp,
            useful_cells=sum(r.info.useful for r in flush.requests),
            padded_cells=plan.gp * N * L,
            useful_lanes=n_reads,
            lane_slots=_lane_slots(plan.gp, N),
            # segment-packed requests reserve lanes at read granularity
            # — a request's footprint is its reads, not a whole Npad
            # block, so the corrected occupancy counts reads
            cluster_lanes=(n_reads if seg
                           else len(flush.requests) * N),
        )
        return flush, handle

    def _collect(self, arg) -> int:
        flush, handle = arg
        if handle is None:
            return 0
        if flush.kind == "fallback":
            self._respond_ok(flush.requests[0], handle, "fallback")
            return 1
        if isinstance(handle[1], SegmentBucketPlan):
            with self.stats.timers.time("serve_fetch"):
                pairs = self.executor.collect_seg(handle)
            self.stats.note_model_bytes(_batch_model_bytes(
                handle[1], [res for _, res in pairs]
            ))
            for ci, res in pairs:
                self._respond_ok(flush.requests[ci], res, "batched")
            return len(pairs)
        with self.stats.timers.time("serve_fetch"):
            results = self.executor.collect(handle)
        self.stats.note_model_bytes(_batch_model_bytes(handle[1], results))
        for req, res in zip(flush.requests, results):
            self._respond_ok(req, res, "batched")
        return len(flush.requests)

    # ---- per-request terminals ----

    def _respond_ok(self, req: Request, res: SweepResult,
                    path: str) -> None:
        if req.future.done():
            return
        lat = time.perf_counter() - req.t_submit
        self.stats.observe_latency(lat)
        self.stats.count("completed")
        req.future.set_result(Response(
            id=req.id, ok=True, consensus=res.consensus, score=res.score,
            n_iters=res.n_iters, converged=res.converged, latency_s=lat,
            path=path,
        ))

    def _run_fallback(self, req: Request) -> SweepResult:
        """PR 1 per-cluster device loop, in the batched path's exact
        algorithmic configuration (full batch, all-edits candidates or
        the edits gate) so oversize singletons stay bit-identical to
        what a bigger bucket grid would have produced."""
        from ..engine.driver import rifraf
        from ..engine.params import RifrafParams

        cfg = self.config
        with self.stats.timers.time("serve_fallback"):
            result = rifraf(
                [r.seq for r in req.cluster],
                error_log_ps=[r.error_log_p for r in req.cluster],
                params=RifrafParams(
                    batch_size=0, batch_fixed=False,
                    do_alignment_proposals=cfg.do_alignment_proposals,
                    max_iters=cfg.max_iters, min_dist=cfg.min_dist,
                    bandwidth_pvalue=cfg.bandwidth_pvalue,
                    bandwidth=cfg.bandwidth, scores=cfg.scores,
                ),
            )
        self.stats.count("fallback")
        if result.metadata:
            self.stats.note_declines(result.metadata.get("declines"))
        return SweepResult(
            consensus=result.consensus,
            score=float(result.state.score),
            n_iters=int(result.state.stage_iterations.sum()),
            converged=bool(result.state.converged),
        )

    def _fail_flush(self, flush: Flush, err: PipelineJobError) -> None:
        wrapped = InternalError(str(err))
        wrapped.__cause__ = err.__cause__
        for r in flush.requests:
            respond_error(r, wrapped, self.stats, "failed_internal")

    # ---- the consumer loop (one thread) ----

    def run_loop(self, flush_q: Queue) -> None:
        stop = False
        while not stop:
            item = flush_q.get()
            if item is STOP:
                break
            burst: List[Flush] = [item]
            while True:
                try:
                    nxt = flush_q.get_nowait()
                except Empty:
                    break
                if nxt is STOP:
                    stop = True
                    break
                burst.append(nxt)
            results = pipeline_map(
                self._pack, self._run, self._collect, burst,
                on_error="return",
            )
            for r in results:
                if isinstance(r, PipelineJobError):
                    self._fail_flush(burst[r.job_index], r)
