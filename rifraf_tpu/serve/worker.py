"""The dispatch worker: flushed micro-batches -> device -> responses.

Flushes stream through ``parallel.cluster.pipeline_map`` in bursts, so
the worker inherits the offline sweep's double-buffering: host packing
of batch k+1 overlaps device execution of batch k, and batch k's
blocking fetch happens only after k+1 has been dispatched. Per-job
error isolation (``on_error="return"``) means one failing micro-batch
fails ONLY its own requests — the batches behind it keep flowing.

Two flush kinds:

- ``"batch"``: a same-bucket micro-batch, packed/dispatched through the
  shared ChunkExecutor (the sweep's lru-cached program factories, so a
  serving signature and an offline sweep bucket share one executable);
- ``"fallback"``: an oversize singleton, run through the per-cluster
  device loop (``rifraf()`` in the sweep-equivalent configuration) so a
  3 kb outlier degrades gracefully instead of dragging a whole bucket's
  padded shape up with it.

Failure handling is a DEGRADATION LADDER, not all-or-nothing: a failed
micro-batch retries once at the next-simpler execution rung —
segment-packed (rung 0) -> whole-block batch (rung 1) -> per-request
device-loop fallback (rung 2) — under a bounded per-request retry
budget (``ServeConfig.max_retries``). Every rung is bit-identical to
the others for a given request (tests/test_lane_packing.py,
tests/test_serve.py), so a ladder-recovered response equals the
unfaulted one. ``Flush.rung`` pins a flush to a rung; the supervisor
uses it to re-run a crashed worker's in-flight requests one rung down.

The loop itself is hardened: a ``STOP`` discovered mid-burst still runs
the already-collected flushes before exiting, an unexpected exception
in the burst machinery fails that burst's requests (typed
``InternalError``) instead of killing the thread silently, and every
terminal resolution tolerates a concurrent resolver
(``InvalidStateError`` -> counted no-op) so two racing terminals can
never take the worker down. Injected faults (serve.faults) fire at the
``pack``/``compile``/``dispatch``/``fetch``/``fallback`` sites, on the
ladder's inline retries as well as the pipelined first attempt.
"""

from __future__ import annotations

import time
from concurrent.futures import InvalidStateError
from queue import Empty, Queue
from typing import List, NamedTuple, Optional

import numpy as np

from ..engine.integrity import IntegrityError
from ..parallel.cluster import PipelineJobError, pipeline_map
from ..parallel.sweep_sharded import (
    BucketPlan,
    ChunkExecutor,
    PackPlan,
    SegmentBucketPlan,
    SweepResult,
    _lane_slots,
)
from ..utils.meshutil import mesh_axis_size, mesh_round
from ..utils.shapes import bucket as _bucket
from ..utils.shapes import pack_segments
from .batcher import resolve_segment_pack, segment_eligible
from .errors import DeadlineExceededError, ServeError
from .faults import FaultPlan, corrupt_value, resolve_faults
from .quarantine import DeviceScoreboard, device_key, golden_problem
from .request import Request, Response, ServeConfig
from .stats import ServerStats

STOP = object()  # flush-queue shutdown sentinel

# ladder rungs: 0 = auto (segment-packed when eligible), 1 = whole-block
# micro-batch (segment packing disabled), 2 = per-request device-loop
# fallback. Counters are keyed by the rung a RETRY lands on.
RUNG_NAMES = {0: "auto", 1: "block", 2: "fallback"}


def _batch_model_bytes(plan: BucketPlan, results: List[SweepResult]):
    """Modelled HBM traffic of one fetched micro-batch: the fused-step
    byte model at the batch's padded shape (lane-slot Npad — the
    [gp, N] read axes on 128-lane tiles) times its stage-step count
    (max member iterations; the vmapped while_loop runs until the last
    cluster converges). Adaptation rounds excluded — a floor."""
    from ..utils import roofline
    from ..utils.shapes import plan_cols

    N, _, Tmax, K0 = plan.key
    C = plan_cols(Tmax, K0, kernel="dense").cols
    steps = max((r.n_iters for r in results), default=0)
    return roofline.fused_model(
        Tmax, K0, _lane_slots(plan.gp, N), C
    )["bytes"] * steps


class Flush(NamedTuple):
    kind: str  # "batch" | "fallback"
    requests: List[Request]
    rung: int = 0  # degradation-ladder rung this flush executes at


class InternalError(ServeError):
    """A micro-batch failed in pack/dispatch/fetch; carries the cause."""

    code = "internal"


def resolve_future(req: Request, response: Response,
                   stats: ServerStats) -> bool:
    """Resolve a request's future, tolerating a concurrent resolver:
    two terminals can interleave (worker vs supervisor vs close()), and
    the done() pre-check alone is racy — the second set_result raises
    InvalidStateError, which must be a counted no-op, never a
    worker-killing exception. Returns whether THIS call resolved it."""
    if req.future.done():
        stats.count("double_resolve")
        return False
    try:
        req.future.set_result(response)
        return True
    except InvalidStateError:
        stats.count("double_resolve")
        return False


def respond_error(req: Request, err: ServeError, stats: ServerStats,
                  counter: str) -> None:
    lat = time.perf_counter() - req.t_submit
    if resolve_future(req, Response(
        id=req.id, ok=False, error=err, latency_s=lat, path="rejected",
    ), stats):
        stats.count(counter)


class Worker:
    """Owns the ChunkExecutor and the flush-queue consumer loop."""

    def __init__(self, config: ServeConfig, stats: ServerStats,
                 faults: Optional[FaultPlan] = None, device=None,
                 burst_limit: Optional[int] = None,
                 scoreboard: Optional[DeviceScoreboard] = None):
        self.config = config
        self.stats = stats
        self.faults = faults if faults is not None else resolve_faults(
            config.faults
        )
        self.segment_pack = resolve_segment_pack(config)
        # fleet mode: each worker's executor pins its arrays to ONE
        # device (jit then runs there), and bursts are capped so one
        # worker cannot drain the shared flush queue while its fleet
        # mates idle
        self.device = device
        self.burst_limit = burst_limit
        self.executor = ChunkExecutor(
            mesh=config.mesh,
            max_iters=config.max_iters,
            min_dist=config.min_dist,
            bandwidth_pvalue=config.bandwidth_pvalue,
            do_alignment_proposals=config.do_alignment_proposals,
            device=device,
            band_dtype=config.band_dtype,
            band_growth=config.band_growth,
            want_guard=config.guard,
            input_enc=config.input_enc,
            speculate_k=config.speculate_k,
        )
        # result-integrity surface: the per-device scoreboard (shared
        # across the fleet) attributes guard trips / divergences to
        # this worker's device and drives quarantine/probing
        self.scoreboard = scoreboard
        self.dev_key = device_key(device)
        self._last_probe = -float("inf")
        # supervision surface: the supervisor reads these to detect a
        # crashed/stalled worker and to recover its in-flight requests
        self.last_beat = time.perf_counter()
        self.busy = False
        self.inflight: List[Flush] = []
        # graceful drain (elastic scale-down): with ``draining`` set the
        # loop finishes its current burst, takes nothing further off the
        # shared flush queue, and exits; ``drained`` marks the CLEAN
        # exit — a dead draining thread without it crashed mid-burst and
        # the supervisor recovers its in-flight flushes like any crash
        self.draining = False
        self.drained = False

    def _heartbeat(self, *_ignored) -> None:
        self.last_beat = time.perf_counter()

    # ---- pipeline stages (pack on the background thread, run/collect
    # on the worker thread) ----

    def plan_for(self, key, n: int) -> BucketPlan:
        """One-chunk plan for a micro-batch of n clusters: the cluster
        axis rounds to the next power of two (and the mesh axis) so the
        number of distinct compiled batch shapes stays logarithmic."""
        self.faults.fire("compile")
        gp = mesh_round(n, self.config.mesh, pow2=True)
        return BucketPlan(key=key, band=self.config.band_bucket, gp=gp,
                          chunks=[list(range(n))])

    def _seg_batch(self, live: List[Request]) -> bool:
        """Whether a flushed micro-batch runs segment-packed: the
        server packs cross-request, every member carries its cluster
        info (the packer needs read counts and seed slots), and every
        member individually qualifies (the batcher's grouping
        guarantees this for its own flushes; drains can mix)."""
        return self.segment_pack and all(
            r.info is not None
            and segment_eligible(r.key, self.config.lane_target)
            for r in live
        )

    def seg_plan_for(self, requests: List[Request]):
        """Segmented one-chunk plan for a micro-batch: first-fit the
        requests' read counts into shared lane blocks
        (utils.shapes.pack_segments); member indices index into the
        flush's request list. The pack-count axis rounds to the next
        power of two (and the mesh axis) like plan_for."""
        self.faults.fire("compile")
        cfg = self.config
        pk = pack_segments(
            [r.info.n_reads for r in requests], lanes=cfg.lane_target
        )
        npad = _bucket(pk.npad, cfg.read_bucket)
        packs = [
            PackPlan(
                members=list(blk),
                seg_ids=pk.seg_ids[b] + [0] * (npad - len(pk.seg_ids[b])),
            )
            for b, blk in enumerate(pk.blocks)
        ]
        gp = mesh_round(len(packs), cfg.mesh, pow2=True)
        # segment-grouped requests share the shape axes exactly; maxima
        # keep a mixed drain flush safe
        shape = tuple(
            max(r.key[i] for r in requests) for i in (1, 2, 3)
        )
        plan = SegmentBucketPlan(
            key=(npad,) + shape, band=cfg.band_bucket, sp=pk.n_seg,
            gp=gp, chunks=[packs],
        )
        return plan, packs

    def _pack(self, flush: Flush):
        # first worker pickup: stamp dispatch time and feed the load
        # estimators (queue-wait drives elastic scale-up; the dispatch
        # stamp anchors the service-time EWMA the shed door uses)
        t_pick = time.perf_counter()
        for r in flush.requests:
            if r.t_dispatch is None:
                r.t_dispatch = t_pick
                self.stats.note_queue_wait(t_pick - r.t_submit)
        if flush.kind != "batch":
            return flush, None
        self.faults.fire("pack")
        now = time.perf_counter()
        live = []
        for r in flush.requests:
            if r.expired(now):
                respond_error(r, DeadlineExceededError(
                    f"request {r.id}: deadline passed before dispatch"
                ), self.stats, "rejected_deadline")
            else:
                live.append(r)
        if not live:
            return Flush("batch", [], flush.rung), None
        with self.stats.timers.time("serve_pack"):
            # rung >= 1 pins the whole-block path: the ladder's
            # "next-simpler" retry must not re-enter segment packing
            seg = flush.rung < 1 and self._seg_batch(live)
            key = live[0].key
            if seg:
                plan, packs = self.seg_plan_for(live)
                n_axis = mesh_axis_size(self.config.mesh)
                if (n_axis > 1 and len(packs) < n_axis
                        and len(live) > len(packs)):
                    # mesh decline (same rule as plan_sweep): the mesh
                    # shards the pack axis, and packing this flush into
                    # fewer packs than devices would serialize it while
                    # one-request-per-slot shards evenly. A seg group
                    # only shares the SHAPE axes, so the whole-block
                    # fallback pads to the flush's per-axis maxima.
                    seg = False
            if not seg and (flush.rung >= 1 or flush.kind == "batch"):
                # a mixed/laddered flush only shares the SHAPE axes;
                # per-axis maxima cover every member
                key = tuple(
                    max(r.key[i] for r in live) for i in range(4)
                )
            if seg:
                packed = self.executor.pack_seg(
                    plan, packs, [r.cluster for r in live],
                    [r.info for r in live],
                )
            else:
                plan = self.plan_for(key, len(live))
                packed = self.executor.pack(
                    plan, range(len(live)), [r.cluster for r in live],
                    [r.info for r in live],
                )
        return Flush("batch", live, flush.rung), (plan, packed)

    def _run(self, arg):
        flush, staged = arg
        if flush.kind == "fallback":
            return flush, self._run_fallback(flush.requests[0])
        if staged is None:
            return flush, None
        self.faults.fire("dispatch")
        plan, packed = staged
        seg = isinstance(plan, SegmentBucketPlan)
        with self.stats.timers.time("serve_dispatch"):
            handle = (self.executor.run_seg(packed) if seg
                      else self.executor.run(packed))
        N, L = plan.key[0], plan.key[1]
        n_reads = sum(r.info.n_reads for r in flush.requests)
        # whole-block batches speculate when the executor's per-chunk
        # eligibility holds (ChunkExecutor.run): the 1+k extra segment
        # copies of the chunk's lanes are overhead, not demand
        spec_over = 0
        if not seg and self.executor.speculate_k:
            from ..ops.fused import DENSE_BLOCK_THRESHOLD

            if plan.key[2] + 1 <= DENSE_BLOCK_THRESHOLD:
                k = self.executor.speculate_k
                spec_over = (_lane_slots(plan.gp, (2 + k) * N)
                             - _lane_slots(plan.gp, N))
        self.stats.note_batch(
            n_real=len(flush.requests), gp=plan.gp,
            useful_cells=sum(r.info.useful for r in flush.requests),
            padded_cells=plan.gp * N * L,
            useful_lanes=n_reads,
            lane_slots=_lane_slots(plan.gp, N),
            # segment-packed requests reserve lanes at read granularity
            # — a request's footprint is its reads, not a whole Npad
            # block, so the corrected occupancy counts reads
            cluster_lanes=(n_reads if seg
                           else len(flush.requests) * N),
            spec_overhead_lanes=spec_over,
        )
        return flush, handle

    def _collect(self, arg) -> int:
        flush, handle = arg
        if handle is None:
            return 0
        if flush.kind == "fallback":
            self._respond_ok(flush.requests[0], handle, "fallback")
            return 1
        self.faults.fire("fetch")
        if isinstance(handle[1], SegmentBucketPlan):
            with self.stats.timers.time("serve_fetch"):
                pairs = self.executor.collect_seg(handle)
            self.stats.note_model_bytes(_batch_model_bytes(
                handle[1], [res for _, res in pairs]
            ))
            for ci, res in pairs:
                self._respond_ok(flush.requests[ci],
                                 self._maybe_corrupt(res), "batched")
            return len(pairs)
        with self.stats.timers.time("serve_fetch"):
            results = self.executor.collect(handle)
        self.stats.note_model_bytes(_batch_model_bytes(handle[1], results))
        for req, res in zip(flush.requests, results):
            self._respond_ok(req, self._maybe_corrupt(res), "batched")
        return len(flush.requests)

    def _maybe_corrupt(self, res: SweepResult) -> SweepResult:
        """The ``corrupt`` fault kind at the fetch site: a silent,
        deterministic float64 bit flip on a fetched score — the
        wrong-but-plausible answer the shadow-verification layer exists
        to catch. One corrupt-plan poll per fetched result."""
        bit = self.faults.corrupt("fetch")
        if bit is None:
            return res
        self.stats.count("injected_corrupt")
        return res._replace(score=corrupt_value(res.score, bit))

    # ---- per-request terminals ----

    def _note_trip(self, kind: str) -> None:
        """One integrity trip ("guard" | "divergence") attributed to
        this worker's device: count it, and evict the device from the
        round-robin when it crosses the scoreboard threshold."""
        self.stats.count(f"{kind}_trips")
        if (self.scoreboard is not None
                and self.scoreboard.record_trip(self.device, kind)):
            self.stats.count("device_quarantined")

    def _maybe_verify(self, req: Request,
                      res: SweepResult) -> Optional[SweepResult]:
        """Shadow verification: deterministically sample completed
        results by content digest (``verify_fraction``) and re-score on
        the independent oracle path (engine.integrity.oracle_rescore —
        the alternate fused-impl routing, i.e. the degradation ladder's
        rung-2 shape on the OTHER kernel). A divergence beyond the
        precision-harness tolerance is counted, attributed to this
        worker's device on the quarantine scoreboard, and the ORACLE
        result replaces the bad answer (never emitted). Returns the
        replacement, or None when verification passed / didn't sample /
        itself failed (the primary answer stands — a broken verifier
        must not take down serving)."""
        cfg = self.config
        if cfg.verify_fraction <= 0.0:
            return None
        from ..engine.integrity import (
            oracle_rescore,
            scores_diverge,
            selected_for_verify,
        )
        from ..parallel.sweep_sharded import _content_digest

        if not selected_for_verify(_content_digest([req.cluster]),
                                   cfg.verify_fraction):
            return None
        self.stats.count("verify_sampled")
        try:
            with self.stats.timers.time("serve_verify"):
                oracle = oracle_rescore(
                    req.cluster, max_iters=cfg.max_iters,
                    min_dist=cfg.min_dist,
                    bandwidth_pvalue=cfg.bandwidth_pvalue,
                    do_alignment_proposals=cfg.do_alignment_proposals,
                    band_dtype=cfg.band_dtype,
                    band_growth=cfg.band_growth,
                    scores=cfg.scores, bandwidth=cfg.bandwidth,
                    input_enc=cfg.input_enc,
                )
        except Exception:  # noqa: BLE001 — verifier failure != result
            self.stats.count("verify_errors")
            return None
        want = float(oracle.state.score)
        diverged, _tol = scores_diverge(res.score, want, cfg.band_dtype)
        same = np.array_equal(np.asarray(res.consensus),
                              np.asarray(oracle.consensus))
        if same and not diverged:
            self.stats.count("verify_ok")
            return None
        self.stats.count("verify_divergence")
        self._note_trip("divergence")
        self.stats.count("verify_recovered")
        return SweepResult(
            consensus=oracle.consensus, score=want,
            n_iters=int(oracle.state.stage_iterations.sum()),
            converged=bool(oracle.state.converged),
        )

    def _respond_ok(self, req: Request, res: SweepResult,
                    path: str) -> None:
        replacement = self._maybe_verify(req, res)
        if replacement is not None:
            res, path = replacement, "verified"
        lat = time.perf_counter() - req.t_submit
        response = Response(
            id=req.id, ok=True, consensus=res.consensus, score=res.score,
            n_iters=res.n_iters, converged=res.converged, latency_s=lat,
            path=path,
        )
        if resolve_future(req, response, self.stats):
            self.stats.observe_latency(lat)
            self.stats.note_service(
                time.perf_counter()
                - (req.t_dispatch if req.t_dispatch is not None
                   else req.t_submit))
            self.stats.count("completed")
            if self.config.journal is not None:
                # write-ahead completion record; a broken journal must
                # never take down serving, so failures are counted, not
                # raised
                try:
                    self.config.journal(response)
                except Exception:
                    self.stats.count("journal_errors")

    def _run_fallback(self, req: Request) -> SweepResult:
        """PR 1 per-cluster device loop, in the batched path's exact
        algorithmic configuration (full batch, all-edits candidates or
        the edits gate) so oversize singletons stay bit-identical to
        what a bigger bucket grid would have produced."""
        from ..engine.driver import rifraf
        from ..engine.params import RifrafParams

        self.faults.fire("fallback")
        cfg = self.config
        with self.stats.timers.time("serve_fallback"):
            result = rifraf(
                [r.seq for r in req.cluster],
                error_log_ps=[r.error_log_p for r in req.cluster],
                params=RifrafParams(
                    batch_size=0, batch_fixed=False,
                    do_alignment_proposals=cfg.do_alignment_proposals,
                    max_iters=cfg.max_iters, min_dist=cfg.min_dist,
                    bandwidth_pvalue=cfg.bandwidth_pvalue,
                    bandwidth=cfg.bandwidth, scores=cfg.scores,
                    band_dtype=cfg.band_dtype,
                    band_growth=cfg.band_growth,
                    input_enc=cfg.input_enc,
                    speculate_k=cfg.speculate_k,
                ),
            )
        self.stats.count("fallback")
        if result.metadata:
            self.stats.note_declines(result.metadata.get("declines"))
        return SweepResult(
            consensus=result.consensus,
            score=float(result.state.score),
            n_iters=int(result.state.stage_iterations.sum()),
            converged=bool(result.state.converged),
        )

    # ---- the degradation ladder ----

    def _wrap(self, err: BaseException) -> InternalError:
        if isinstance(err, PipelineJobError):
            wrapped = InternalError(str(err))
            wrapped.__cause__ = err.__cause__
        else:
            wrapped = InternalError(f"micro-batch failed: {err!r}")
            wrapped.__cause__ = err
        return wrapped

    def _fail_flush(self, flush: Flush, err: BaseException) -> None:
        wrapped = self._wrap(err)
        for r in flush.requests:
            respond_error(r, wrapped, self.stats, "failed_internal")

    def _retry_or_fail(self, flush: Flush, err: BaseException) -> None:
        """One failed flush: descend the ladder for members with retry
        budget, fail the rest (typed InternalError). A rung-0 batch
        retries whole-block; everything deeper — including fallback
        flushes, which have no simpler rung — retries per-request
        fallback, so a transient fault there still clears. The
        per-request budget bounds the recursion."""
        cfg = self.config
        # a tripped numerical sentinel is a ladder entry like any other
        # failure, but it ALSO scores against this worker's device:
        # repeated trips quarantine the chip while the ladder re-runs
        # the requests elsewhere/simpler
        cause = (err.__cause__ if isinstance(err, PipelineJobError)
                 else err)
        if isinstance(cause, IntegrityError):
            self._note_trip("guard")
        wrapped = self._wrap(err)
        retryable: List[Request] = []
        for r in flush.requests:
            if r.future.done():
                continue
            if r.retries < cfg.max_retries:
                r.retries += 1
                retryable.append(r)
            else:
                self.stats.count("ladder_exhausted")
                respond_error(r, wrapped, self.stats, "failed_internal")
        if not retryable:
            return
        next_rung = (1 if flush.kind == "batch" and flush.rung == 0
                     else 2)
        self.stats.count(f"ladder_retry_{RUNG_NAMES[next_rung]}",
                         len(retryable))
        if next_rung == 1:
            self._run_inline(Flush("batch", retryable, 1))
        else:
            for r in retryable:
                self._run_request_fallback(r)

    def _run_inline(self, flush: Flush) -> None:
        """Execute one flush synchronously (the ladder's retry path —
        no pipeline, the burst already drained); a failure descends the
        ladder again."""
        try:
            n = self._collect(self._run(self._pack(flush)))
            if n:
                self.stats.count("ladder_recovered", n)
        except Exception as e:  # noqa: BLE001 — ladder descends
            self._retry_or_fail(flush, e)

    def _run_request_fallback(self, req: Request) -> None:
        """Rung 2: one request through the per-cluster device loop; the
        last rung, so a failure re-enters the ladder at rung 2 (another
        fallback attempt) until the budget runs out."""
        try:
            res = self._run_fallback(req)
        except Exception as e:  # noqa: BLE001 — budget bounds this
            self._retry_or_fail(Flush("fallback", [req], 2), e)
            return
        self._respond_ok(req, res, "fallback")
        self.stats.count("ladder_recovered")

    # ---- the golden probe ----

    def golden_probe(self) -> bool:
        """Run the known-answer golden problem through this worker's
        OWN executor (own device, own compiled path): pass iff the
        consensus equals the planted template and the score is finite.
        The outcome lands on the scoreboard — a pass REINSTATES a
        quarantined device, a fail (or any exception) quarantines it.
        Deliberately does NOT fire fault sites: the probe measures the
        hardware, not the chaos plan."""
        from ..parallel.sweep_sharded import bucket_key, cluster_info

        cfg = self.config
        self._last_probe = time.perf_counter()
        try:
            cluster, template = golden_problem(cfg)
            info = cluster_info(cluster, cfg.band_growth)
            key = bucket_key(info, cfg.read_bucket, cfg.band_bucket,
                             cfg.len_bucket)
            gp = mesh_round(1, cfg.mesh, pow2=True)
            plan = BucketPlan(key=key, band=cfg.band_bucket, gp=gp,
                              chunks=[list(range(gp))])
            packed = self.executor.pack(plan, range(gp), [cluster] * gp,
                                        [info] * gp)
            res = self.executor.collect(self.executor.run(packed))[0]
            ok = (np.array_equal(np.asarray(res.consensus), template)
                  and np.isfinite(res.score))
        except Exception:  # noqa: BLE001 — a failing probe IS the signal
            ok = False
        self.stats.count("probe_pass" if ok else "probe_fail")
        if self.scoreboard is not None:
            was = self.scoreboard.is_quarantined(self.device)
            self.scoreboard.note_probe(self.device, ok)
            if ok and was:
                self.stats.count("device_reinstated")
        return ok

    # ---- the consumer loop (one thread) ----

    def take_inflight(self) -> List[Flush]:
        """Supervisor-side recovery: the flushes the (dead) worker was
        executing when it crashed. Clears the slot so a double-recovery
        cannot re-run them."""
        flushes, self.inflight = self.inflight, []
        return flushes

    def _execute_burst(self, burst: List[Flush]) -> None:
        self.inflight = burst
        results = pipeline_map(
            self._pack, self._run, self._collect, burst,
            on_error="return", stage_hook=self._heartbeat,
        )
        for r in results:
            if isinstance(r, PipelineJobError):
                self._retry_or_fail(burst[r.job_index], r)
        # cleared only on completion: after a mid-burst crash
        # (BaseException) the supervisor reads it via take_inflight()
        self.inflight = []

    # how long the consumer blocks per queue poll: short enough that a
    # drain request is noticed promptly, long enough to stay cheap for
    # an idle single-worker server
    POLL_S = 0.05

    def run_loop(self, flush_q: Queue) -> None:
        stop = False
        while not stop:
            if self.draining:
                # graceful drain: the in-flight burst (if any) finished
                # on the previous iteration and nothing further is
                # taken — queued flushes stay for the rest of the fleet
                self.drained = True
                return
            try:
                item = flush_q.get(timeout=self.POLL_S)
            except Empty:
                continue
            self._heartbeat()
            if item is STOP:
                break
            if (self.scoreboard is not None
                    and self.scoreboard.is_quarantined(self.device)):
                # evicted from the round-robin: hand the flush back for
                # fleet mates and re-probe (rate-limited); this worker
                # takes traffic again only after a clean probe
                flush_q.put(item)
                self.stats.count("quarantine_requeued")
                now = time.perf_counter()
                if (now - self._last_probe
                        >= self.config.probe_interval_s):
                    self.golden_probe()
                else:
                    time.sleep(min(self.config.probe_interval_s, 0.01))
                continue
            self.busy = True
            burst: List[Flush] = [item]
            while (self.burst_limit is None
                   or len(burst) < self.burst_limit):
                try:
                    nxt = flush_q.get_nowait()
                except Empty:
                    break
                if nxt is STOP:
                    # run the already-collected flushes before exiting:
                    # a shutdown must not orphan work that was queued
                    # ahead of it
                    stop = True
                    break
                burst.append(nxt)
            try:
                self._execute_burst(burst)
            except Exception as e:  # noqa: BLE001 — the loop must live
                # unexpected failure OUTSIDE per-job isolation (ladder
                # bookkeeping, stats, ...): fail the burst's unresolved
                # requests instead of dying silently with their futures
                # hanging. BaseException (injected crash / interpreter
                # teardown) still propagates — that is the supervisor's
                # department.
                self.stats.count("worker_loop_errors")
                wrapped = self._wrap(e)
                for f in self.take_inflight():
                    for r in f.requests:
                        respond_error(r, wrapped, self.stats,
                                      "failed_internal")
            self.busy = False
            self._heartbeat()
